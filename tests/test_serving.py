"""Serving tier (ISSUE 8): arrival plans, the paged KV cache, the
decode/prefill split, the continuous-batching engine, fault
composition, and the record round-trip against committed fixtures."""
from __future__ import annotations

import json
import math
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.models import transformer as tfm
from dlnetbench_tpu.serving.arrivals import ArrivalPlan, splitmix64
from dlnetbench_tpu.serving.kv_cache import (CacheConfig, CacheOOM,
                                             PagedKVCache,
                                             device_buffers,
                                             paged_attention_decode,
                                             sharded_paged_attention)

DATA = Path(__file__).parent / "data"

pytestmark = pytest.mark.serving


def tiny_model(**over) -> tfm.TransformerConfig:
    kw = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
              ff_dim=64, num_layers=2, seq_len=32, gated=True,
              max_positions=0, dtype="float32")
    kw.update(over)
    return tfm.TransformerConfig(**kw)


def tiny_serving(**over):
    from dlnetbench_tpu.serving.scheduler import ServingConfig
    kw = dict(slots=4, page_size=4, num_pages=32, max_seq_len=32,
              slo_ttft_ms=200.0, slo_tpot_ms=100.0)
    kw.update(over)
    return ServingConfig(**kw)


# ---------------------------------------------------------------------
# arrival plans


def test_arrival_plan_validation_errors():
    with pytest.raises(ValueError, match="unknown kind"):
        ArrivalPlan(kind="lunar").validate()
    with pytest.raises(ValueError, match="phases"):
        ArrivalPlan(kind="diurnal", rate_rps=10.0,
                    num_requests=5, phases=[]).validate()
    with pytest.raises(ValueError, match="phases"):
        ArrivalPlan(kind="diurnal", rate_rps=10.0, num_requests=5,
                    phases=[[0.5, 2.0], [0.2, 1.0]]).validate()
    with pytest.raises(ValueError, match="multiplier"):
        ArrivalPlan(kind="diurnal", rate_rps=10.0, num_requests=5,
                    phases=[[0.0, -1.0]]).validate()
    with pytest.raises(ValueError, match="rate_rps > 0"):
        ArrivalPlan(kind="poisson", rate_rps=-3.0,
                    num_requests=5).validate()
    with pytest.raises(ValueError, match="rate_rps > 0"):
        ArrivalPlan(kind="poisson", rate_rps=0.0,
                    num_requests=5).validate()
    with pytest.raises(ValueError, match="num_requests"):
        ArrivalPlan(kind="poisson", rate_rps=10.0,
                    num_requests=0).validate()
    with pytest.raises(ValueError, match="non-empty 'trace'"):
        ArrivalPlan(kind="replay", trace=[]).validate()
    with pytest.raises(ValueError, match="non-decreasing"):
        ArrivalPlan(kind="replay",
                    trace=[{"t": 1.0}, {"t": 0.5}]).validate()
    with pytest.raises(ValueError, match="prompt_len"):
        ArrivalPlan(kind="poisson", rate_rps=1.0, num_requests=1,
                    prompt_len=0).validate()
    with pytest.raises(ValueError, match="duty"):
        ArrivalPlan(kind="bursty", rate_rps=1.0, num_requests=1,
                    duty=1.5).validate()


def test_arrival_plan_roundtrip_and_determinism():
    plan = ArrivalPlan(kind="bursty", rate_rps=20.0, num_requests=30,
                       seed=5, prompt_len=[4, 9], output_len=3,
                       period_s=0.5, duty=0.25, factor=3.0)
    again = ArrivalPlan.from_dict(json.loads(plan.dumps()))
    assert again.to_dict() == plan.to_dict()
    a, b = plan.sample(), again.sample()
    assert [(r.arrival_s, r.prompt_len, r.output_len) for r in a] \
        == [(r.arrival_s, r.prompt_len, r.output_len) for r in b]
    assert all(r.output_len == 3 for r in a)
    assert all(4 <= r.prompt_len <= 9 for r in a)
    assert all(a[i].arrival_s <= a[i + 1].arrival_s
               for i in range(len(a) - 1))


def test_arrival_plan_fixture_loads():
    """The committed plan fixture parses via the @path convention and
    round-trips through its own wire format."""
    plan = ArrivalPlan.loads(f"@{DATA / 'arrival_poisson.json'}")
    assert plan.kind == "poisson" and plan.num_requests == 24
    assert plan.to_dict() == json.loads(
        (DATA / "arrival_poisson.json").read_text())
    assert len(plan.sample()) == 24


def test_splitmix64_matches_native_constants():
    """First draws of the shared splitmix64 (fault_plan.hpp:147) —
    golden values computed from the reference constants so a silent
    constant drift breaks loudly."""
    v1, s = splitmix64(0)
    v2, _ = splitmix64(s)
    assert v1 == 0xE220A8397B1DCDAF
    assert v2 == 0x6E789E6AA1B965F4


def test_replay_plan_samples_trace_verbatim():
    plan = ArrivalPlan(kind="replay", trace=[
        {"t": 0.0, "prompt_len": 5, "output_len": 2},
        {"t": 0.25, "prompt_len": 7, "output_len": 3}])
    reqs = plan.sample()
    assert [(r.arrival_s, r.prompt_len, r.output_len) for r in reqs] \
        == [(0.0, 5, 2), (0.25, 7, 3)]


# ---------------------------------------------------------------------
# paged KV cache


def test_cache_allocate_append_free_and_stats():
    cc = CacheConfig(num_layers=1, num_kv_heads=2, head_dim=8,
                     num_pages=8, page_size=4, max_seqs=2,
                     max_pages_per_seq=4)
    cache = PagedKVCache(cc)
    cache.allocate(0, 6)           # 2 pages
    assert cache.pages_in_use == 2
    pages0 = list(cache.block_tables[0, :2])
    assert len(set(pages0)) == 2
    cache.append(0, 5)
    st = cache.stats()
    assert st["pages_in_use"] == 2 and st["peak_pages_in_use"] == 2
    # 5 tokens in 8 allocated slots: 3 wasted
    assert st["fragmentation"] == round(3 / 8, 4)
    cache.allocate(1, 16)          # 4 pages
    assert cache.pages_in_use == 6
    with pytest.raises(ValueError, match="max_pages_per_seq"):
        PagedKVCache(cc).allocate(0, 100)
    tight = PagedKVCache(CacheConfig(
        num_layers=1, num_kv_heads=2, head_dim=8, num_pages=6,
        page_size=4, max_seqs=2, max_pages_per_seq=4))
    tight.allocate(0, 16)          # 4 of 6 pages
    with pytest.raises(CacheOOM, match="free"):
        tight.allocate(1, 16)      # needs 4, only 2 free
    cache.free(0)
    assert cache.pages_in_use == 4 and cache.lengths[0] == 0
    # freed pages are reusable
    cache.allocate(0, 16)
    assert cache.pages_in_use == 8
    assert not cache.can_fit(1)


def test_cache_append_past_reservation_refused():
    cc = CacheConfig(num_layers=1, num_kv_heads=2, head_dim=8,
                     num_pages=8, page_size=4, max_seqs=1,
                     max_pages_per_seq=4)
    cache = PagedKVCache(cc)
    cache.allocate(0, 4)
    cache.append(0, 4)
    with pytest.raises(CacheOOM, match="reservation"):
        cache.append(0)


def test_gather_attention_matches_dense_reference():
    """The fallback path against plain masked attention on a
    contiguous copy of the same cache."""
    key = jax.random.key(0)
    b, hq, hkv, dh, pages, psize, pmax = 3, 4, 2, 8, 16, 4, 6
    q = jax.random.normal(key, (b, hq, dh))
    kp = jax.random.normal(jax.random.key(1), (hkv, pages, psize, dh))
    vp = jax.random.normal(jax.random.key(2), (hkv, pages, psize, dh))
    lengths = jnp.asarray([5, 9, 1], jnp.int32)
    pidx = jnp.asarray(
        np.arange(b * pmax).reshape(b, pmax) % pages, jnp.int32)
    got = paged_attention_decode(q, kp, vp, lengths, pidx,
                                 impl="gather")
    # dense reference per batch element
    for i in range(b):
        k = kp[:, pidx[i]].reshape(hkv, pmax * psize, dh)
        v = vp[:, pidx[i]].reshape(hkv, pmax * psize, dh)
        t = int(lengths[i])
        g = hq // hkv
        qi = q[i].reshape(hkv, g, dh)
        scores = jnp.einsum("hgd,htd->hgt", qi, k[:, :t])
        p = jax.nn.softmax(scores, axis=-1)
        ref = jnp.einsum("hgt,htd->hgd", p, v[:, :t]).reshape(hq, dh)
        np.testing.assert_allclose(np.asarray(got[i]), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_sharded_paged_attention_matches_unsharded(eight_devices):
    """The shard_map KV-head sharding (SNIPPETS [3] recipe) on the CPU
    mesh: numerics identical to the unsharded fallback."""
    from dlnetbench_tpu.parallel.mesh import make_flat_mesh
    mesh = make_flat_mesh(devices=eight_devices[:2], axis="kv")
    q = jax.random.normal(jax.random.key(7), (3, 4, 8))
    kp = jax.random.normal(jax.random.key(8), (2, 16, 4, 8))
    vp = jax.random.normal(jax.random.key(9), (2, 16, 4, 8))
    lengths = jnp.asarray([5, 9, 2], jnp.int32)
    pidx = jnp.asarray(np.arange(18).reshape(3, 6) % 16, jnp.int32)
    ref = paged_attention_decode(q, kp, vp, lengths, pidx,
                                 impl="gather")
    got = sharded_paged_attention(mesh, impl="gather")(
        q, kp, vp, lengths, pidx)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_paged_attention_block_parity_and_validation(eight_devices):
    """``pages_per_compute_block`` is a real parameter now (ISSUE 9
    satellite — the old inline ``min(pages, 8)`` hard-code): results
    are identical across 3 explicit block values (the knob sizes the
    kernel grid, never the math — the gather impl computes full
    attention regardless, and the tpu_only case below locks the Pallas
    kernel to the same contract), it flows through
    ``sharded_paged_attention``, and a non-divisor is refused loudly on
    every impl."""
    from dlnetbench_tpu.parallel.mesh import make_flat_mesh

    q = jax.random.normal(jax.random.key(7), (3, 4, 8))
    kp = jax.random.normal(jax.random.key(8), (2, 16, 4, 8))
    vp = jax.random.normal(jax.random.key(9), (2, 16, 4, 8))
    lengths = jnp.asarray([5, 9, 2], jnp.int32)
    pidx = jnp.asarray(np.arange(18).reshape(3, 6) % 16, jnp.int32)
    ref = paged_attention_decode(q, kp, vp, lengths, pidx,
                                 impl="gather")
    for blk in (1, 2, 6):          # 3 divisors of pages_per_seq=6
        got = paged_attention_decode(q, kp, vp, lengths, pidx,
                                     impl="gather",
                                     pages_per_compute_block=blk)
        assert jnp.array_equal(got, ref), blk
    # flows through the sharded wrapper unchanged
    mesh = make_flat_mesh(devices=eight_devices[:2], axis="kv")
    got = sharded_paged_attention(mesh, impl="gather",
                                  pages_per_compute_block=2)(
        q, kp, vp, lengths, pidx)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # a non-divisor fails LOUD (experiment-knob convention), every impl
    with pytest.raises(ValueError, match="does not divide"):
        paged_attention_decode(q, kp, vp, lengths, pidx, impl="gather",
                               pages_per_compute_block=4)


@pytest.mark.tpu_only
def test_pallas_paged_attention_block_parity():
    """On-chip: the Pallas kernel itself across 3 block values — the
    knob moves the grid, never the numbers."""
    q = jax.random.normal(jax.random.key(7), (4, 8, 128), jnp.float32)
    kp = jax.random.normal(jax.random.key(8), (2, 32, 16, 128),
                           jnp.float32)
    vp = jax.random.normal(jax.random.key(9), (2, 32, 16, 128),
                           jnp.float32)
    lengths = jnp.asarray([40, 128, 16, 70], jnp.int32)
    pidx = jnp.asarray(np.arange(4 * 8).reshape(4, 8) % 32, jnp.int32)
    ref = paged_attention_decode(q, kp, vp, lengths, pidx,
                                 impl="pallas",
                                 pages_per_compute_block=8)
    for blk in (1, 2, 4):
        got = paged_attention_decode(q, kp, vp, lengths, pidx,
                                     impl="pallas",
                                     pages_per_compute_block=blk)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.tpu_only
def test_pallas_paged_attention_matches_gather():
    """On-chip: the Pallas paged_attention kernel against the gather
    fallback (collectable everywhere, runs on TPU only — the
    conftest.py tpu_only skip hook)."""
    q = jax.random.normal(jax.random.key(7), (4, 8, 128),
                          jnp.float32)
    kp = jax.random.normal(jax.random.key(8), (2, 32, 16, 128),
                           jnp.float32)
    vp = jax.random.normal(jax.random.key(9), (2, 32, 16, 128),
                           jnp.float32)
    lengths = jnp.asarray([40, 128, 16, 70], jnp.int32)
    pidx = jnp.asarray(np.arange(4 * 8).reshape(4, 8) % 32, jnp.int32)
    ref = paged_attention_decode(q, kp, vp, lengths, pidx,
                                 impl="gather")
    got = paged_attention_decode(q, kp, vp, lengths, pidx,
                                 impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------
# decode path parity


def test_decode_path_matches_full_forward():
    """Prefill (uneven chunks) + single-token decode over the paged
    cache must greedy-decode the SAME tokens as iterated full forwards
    — the whole serving tier's correctness anchor."""
    from dlnetbench_tpu.serving import decode as D
    cfg = tiny_model()
    params = tfm.init_params(jax.random.key(0), cfg)
    cc = CacheConfig(num_layers=2, num_kv_heads=2, head_dim=8,
                     num_pages=16, page_size=4, max_seqs=2,
                     max_pages_per_seq=6)
    cache = PagedKVCache(cc)
    k, v = device_buffers(cc)
    prompt = np.array([5, 9, 3, 11, 7], np.int32)
    out_len = 6
    cache.allocate(0, len(prompt) + out_len)

    toks = list(prompt)
    for _ in range(out_len):
        logits = tfm.forward(params, jnp.asarray([toks]), cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
    ref = toks[len(prompt):]

    prefill = D.make_prefill_chunk(cfg, cc, chunk=3)
    decode = D.make_decode_step(cfg, cc)
    row = jnp.asarray(cache.block_tables[0])
    pos = 0
    nxt = None
    while pos < len(prompt):
        n = min(3, len(prompt) - pos)
        ch = np.zeros(3, np.int32)
        ch[:n] = prompt[pos:pos + n]
        k, v, nxt = prefill(params, k, v, jnp.asarray(ch),
                            jnp.int32(pos), jnp.int32(n), row)
        pos += n
        cache.append(0, n)
    got = [int(nxt)]
    last = int(nxt)
    bt = jnp.asarray(cache.block_tables)
    for _ in range(out_len - 1):
        k, v, nxt = decode(
            params, k, v,
            jnp.asarray(np.array([last, 0], np.int32)),
            jnp.asarray(np.array([int(cache.lengths[0]), 0], np.int32)),
            bt, jnp.asarray(np.array([True, False])))
        cache.append(0)
        last = int(np.asarray(nxt)[0])
        got.append(last)
    assert got == ref


def test_decode_rejects_unsupported_configs():
    from dlnetbench_tpu.serving.decode import check_config
    with pytest.raises(ValueError, match="gated"):
        check_config(tiny_model(gated=False, max_positions=32))
    # MoE models are SUPPORTED since ISSUE 15 (the per-expert batched
    # decode path); only non-gated MoE keeps refusing
    check_config(tiny_model(num_experts=4, top_k=2))


# ---------------------------------------------------------------------
# the continuous-batching engine


@pytest.fixture(scope="module")
def tiny_engine():
    """One compiled engine shared by the engine tests (compile is the
    expensive part; ``run`` resets all run state)."""
    from dlnetbench_tpu.serving.scheduler import Engine
    return Engine(tiny_model(), tiny_serving())


def test_engine_completes_all_requests(tiny_engine):
    plan = ArrivalPlan(kind="poisson", rate_rps=80.0, num_requests=12,
                       seed=3, prompt_len=[4, 8], output_len=[2, 5])
    reqs = plan.sample()
    completed, wall = tiny_engine.run(reqs)
    assert len(completed) == 12
    assert {c.rid for c in completed} == {r.rid for r in reqs}
    for c in completed:
        assert c.first_token_s >= c.arrival_s
        assert c.finish_s >= c.first_token_s
        assert c.ttft_ms >= 0 and c.e2e_ms >= c.ttft_ms
    assert wall > 0
    # every page returned to the free list
    assert tiny_engine.cache.pages_in_use == 0


def test_engine_inline_prefill_generates_same_tokens():
    """Inline-chunked prefill and separate-phase prefill are
    scheduling policies over the SAME math — the generated token
    streams must match request for request."""
    from dlnetbench_tpu.serving.scheduler import Engine
    plan = ArrivalPlan(kind="poisson", rate_rps=100.0, num_requests=6,
                       seed=11, prompt_len=[4, 9], output_len=3)
    cfg = tiny_model()
    params = tfm.init_params(jax.random.key(0), cfg)
    outs = {}
    for mode in ("separate", "inline"):
        eng = Engine(cfg, tiny_serving(prefill=mode, prefill_chunk=4),
                     params=params)
        tokens = {}
        orig = eng._maybe_finish

        def spy(slot, st, _tokens=tokens, _orig=orig):
            if st.generated >= st.req.output_len:
                _tokens.setdefault(st.req.rid, st.last_token)
            _orig(slot, st)

        eng._maybe_finish = spy
        completed, _ = eng.run(plan.sample())
        assert len(completed) == 6
        outs[mode] = tokens
    assert outs["separate"] == outs["inline"]


def test_engine_kv_sharded_matches_unsharded(eight_devices):
    """A kv_shard=2 ENGINE (not just the attention op): the AOT decode
    step is lowered against NamedSharding page pools and its outputs
    keep that sharding call after call — the op-level parity test
    missed exactly this (an AOT program never auto-reshards), so the
    engine-level run is the regression guard.  Token streams must match
    the unsharded engine's."""
    from dlnetbench_tpu.serving.scheduler import Engine
    plan = ArrivalPlan(kind="poisson", rate_rps=100.0, num_requests=5,
                       seed=4, prompt_len=[4, 8], output_len=3)
    cfg = tiny_model()
    params = tfm.init_params(jax.random.key(0), cfg)
    outs = {}
    for shard in (1, 2):
        eng = Engine(cfg, tiny_serving(kv_shard=shard), params=params)
        tokens = {}
        orig = eng._maybe_finish

        def spy(slot, st, _tokens=tokens, _orig=orig):
            if st.generated >= st.req.output_len:
                _tokens.setdefault(st.req.rid, st.last_token)
            _orig(slot, st)

        eng._maybe_finish = spy
        completed, _ = eng.run(plan.sample())
        assert len(completed) == 5
        # a second run through the same compiled engine exercises the
        # post-output sharding round trip
        completed2, _ = eng.run(plan.sample())
        assert len(completed2) == 5
        outs[shard] = tokens
    assert outs[1] == outs[2]


def test_engine_rejects_oversized_request(tiny_engine):
    plan = ArrivalPlan(kind="replay", trace=[
        {"t": 0.0, "prompt_len": 30, "output_len": 10}])
    with pytest.raises(ValueError, match="max_seq_len"):
        tiny_engine.run(plan.sample())


def test_serving_config_validation():
    with pytest.raises(ValueError, match="prefill"):
        tiny_serving(prefill="speculative").validate()
    with pytest.raises(ValueError, match="multiple"):
        tiny_serving(max_seq_len=30).validate()
    with pytest.raises(ValueError, match="divide"):
        tiny_serving(slots=3, world=2).validate()
    # a pool too small for even ONE max-length request would starve the
    # queue head forever (the admission gate can never pass) — refused
    # at config time, not discovered as a busy-spin
    with pytest.raises(ValueError, match="cannot hold"):
        tiny_serving(num_pages=4, max_seq_len=32,
                     page_size=4).validate()


# ---------------------------------------------------------------------
# fault composition (the satellite the record schema pays for)


def _fault_plan(events, policy="fail_fast"):
    from dlnetbench_tpu.faults.plan import FaultEvent, FaultPlan
    return FaultPlan(events=[FaultEvent(**e) for e in events],
                     policy=policy)


def test_delay_fault_inflates_p99_over_clean_baseline():
    """A straggler delay plan on the decode loop must show up as a
    measured p99/p50 amplification over the clean baseline — the same
    plan JSON that drives the training tier."""
    from dlnetbench_tpu.serving.scheduler import run_serving
    cfg = tiny_model()
    sc = tiny_serving(slo_ttft_ms=100.0, slo_tpot_ms=50.0)
    plan = ArrivalPlan(kind="poisson", rate_rps=100.0, num_requests=12,
                       seed=3, prompt_len=[4, 8], output_len=[3, 5])
    params = tfm.init_params(jax.random.key(0), cfg)
    clean = run_serving(cfg, sc, plan,
                        params=params).global_meta["serving"]
    fp = _fault_plan([{"kind": "delay", "magnitude_us": 20000,
                       "iteration": 0}])
    faulted_res = run_serving(cfg, sc, plan, fault_plan=fp,
                              params=params)
    faulted = faulted_res.global_meta["serving"]
    assert faulted["e2e_ms"]["p99"] > clean["e2e_ms"]["p99"]
    assert faulted["e2e_ms"]["p50"] > clean["e2e_ms"]["p50"]
    # amplification, not noise: the delay rides every engine step
    assert faulted["e2e_ms"]["p99"] > clean["e2e_ms"]["p99"] + 15.0
    g = faulted_res.global_meta
    assert g["fault_plan"]["events"][0]["kind"] == "delay"
    assert g["fault_injected_delay_us"] > 0


def test_crash_shrink_dips_and_recovers_goodput():
    """crash+shrink: capacity halves, in-flight work is redone on the
    rebuilt engine (recompile priced into recovery_ms), and the
    record's SLO-goodput timeline shows the dip AND the recovery —
    post-disruption arrivals meet the SLO again."""
    from dlnetbench_tpu.serving.scheduler import run_serving
    cfg = tiny_model()
    sc = tiny_serving(world=2, slots=4, slo_ttft_ms=300.0,
                      slo_tpot_ms=100.0)
    # two waves: the first saturates into the crash, the second lands
    # AFTER recovery so its requests meet the SLO again
    trace = [{"t": 0.01 * i, "prompt_len": 6, "output_len": 4}
             for i in range(10)]
    trace += [{"t": 4.0 + 0.05 * i, "prompt_len": 6, "output_len": 4}
              for i in range(6)]
    plan = ArrivalPlan(kind="replay", trace=trace)
    fp = _fault_plan([{"kind": "crash", "ranks": [1], "iteration": 4}],
                     policy="shrink")
    res = run_serving(cfg, sc, plan, fault_plan=fp)
    g = res.global_meta
    assert g["degraded_world"] == [0]
    assert g["degraded_slots"] == 2
    assert g["detection_ms"] >= 0
    assert g["recovery_ms"] > 0        # the rebuild+recompile is priced
    assert res.num_runs == len(trace)  # every request still completes
    tl = g["serving"]["goodput_timeline"]
    fracs = [w["goodput_frac"] for w in tl if w["completed"]]
    assert min(fracs) < 1.0            # the dip (SLO missed mid-crash)
    assert fracs[-1] == 1.0            # the recovery arc closes
    # the record flows through emit/parser like any training record
    from dlnetbench_tpu.metrics.emit import result_to_record
    from dlnetbench_tpu.metrics.parser import validate_record
    rec = result_to_record(res)
    validate_record(rec)
    assert rec["global"]["degraded_world"] == [0]
    assert len(rec["ranks"]) == 1      # survivor mesh rows only


def test_fail_fast_crash_propagates():
    from dlnetbench_tpu.faults.inject import RankFailure
    from dlnetbench_tpu.serving.scheduler import run_serving
    plan = ArrivalPlan(kind="poisson", rate_rps=200.0, num_requests=8,
                       seed=0, prompt_len=4, output_len=4)
    fp = _fault_plan([{"kind": "crash", "ranks": [0], "iteration": 2}])
    with pytest.raises(RankFailure):
        run_serving(tiny_model(), tiny_serving(), plan, fault_plan=fp)


# ---------------------------------------------------------------------
# the record pathway (fixtures committed; schema v2 unchanged)


def test_serving_record_fixture_roundtrip():
    """The committed serving record flows through parser -> merge ->
    serving_summary without special-casing, and its arrival plan
    re-validates through the plan schema."""
    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe,
                                               validate_record)
    records = load_records(DATA / "record_serving.jsonl")
    assert len(records) == 1
    rec = records[0]
    assert rec["version"] == 2
    validate_record(rec)
    srv = rec["global"]["serving"]
    ArrivalPlan.from_dict(rec["global"]["arrival_plan"])  # re-validates
    # per-request timers ride like any timer: num_runs long, with v2
    # band summaries that describe them
    row = rec["ranks"][0]
    assert len(row["ttft"]) == rec["num_runs"] == srv["completed"]
    assert row["summary"]["ttft"]["n"] == rec["num_runs"]

    df = records_to_dataframe(records)
    for col in ("serving_offered_rps", "serving_ttft_p99_ms",
                "serving_goodput_frac", "ttft", "tpot", "e2e"):
        assert col in df.columns, col
    assert len(df) == rec["num_runs"]

    merged = merge_records(records)   # single-process merge: identity
    validate_record(merged)
    ss = serving_summary([merged])
    assert len(ss) == 1
    got = ss.iloc[0]
    assert got["offered_rps"] == srv["offered_rps"]
    assert got["ttft_p99_ms"] == srv["ttft_ms"]["p99"]
    assert got["goodput_frac"] == srv["goodput_frac"]
    assert got["fault"] == "-" and math.isnan(got["detection_ms"])


def test_v1_and_no_serving_records_still_parse():
    """Pre-serving records keep parsing and contribute nothing to the
    serving summary; a mixed-version merge is still refused."""
    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe,
                                               validate_record)
    v1 = load_records(DATA / "record_v1.jsonl")
    for rec in v1:
        validate_record(rec)
    df = records_to_dataframe(v1)
    assert "serving_offered_rps" not in df.columns
    assert serving_summary(v1).empty
    serving = load_records(DATA / "record_serving.jsonl")
    with pytest.raises(ValueError):
        merge_records([serving[0], dict(v1[0], process=1)])


def test_mixed_plan_merge_refused():
    """Two serving records with DIFFERENT arrival plans are different
    runs — the merge must refuse them like mismatched fault plans."""
    import copy

    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import load_records
    rec = load_records(DATA / "record_serving.jsonl")[0]
    other = copy.deepcopy(rec)
    other["process"] = 1
    other["global"]["num_processes"] = 2
    rec = copy.deepcopy(rec)
    rec["global"]["num_processes"] = 2
    other["global"]["arrival_plan"]["rate_rps"] = 999.0
    with pytest.raises(ValueError, match="arrival_plan"):
        merge_records([rec, other])


@pytest.mark.slow
@pytest.mark.decode
def test_bench_serving_decode_runs_end_to_end():
    """The real aux line: three compiled engines (1-step, fused
    N-step, N-step + speculative), 3 interleaved rounds — heavier than
    a schema lock, so it rides the slow lane.  The ISSUE 11 acceptance
    pieces must be present and true: exact token parity across
    variants, and the dispatch decomposition in the A/B blocks."""
    import bench
    line = bench._bench_serving_decode()
    assert line is not None and line["unit"] == "ms"
    assert line["n"] == 3 and line["value"] > 0
    assert line["p99_ms"]["band"][0] <= line["value"] \
        <= line["p99_ms"]["band"][1]
    assert line["token_parity"] is True
    assert line["multi_step"]["steps_per_dispatch"]["value"] > 1.0
    assert line["speculative"]["spec"]["acceptance_rate"]["n"] == 3
    flip = line["attribution_flip"]
    assert flip["one_step_host_frac"]["n"] == 3
    assert flip["multi_step_host_frac"]["value"] \
        < flip["one_step_host_frac"]["value"]


# ---------------------------------------------------------------------
# serving metrics units


def test_percentiles_and_slo_goodput():
    from dlnetbench_tpu.serving import metrics as M
    vals = [float(v) for v in range(1, 101)]
    assert M.percentile(vals, 50) == 50.0
    assert M.percentile(vals, 99) == 99.0
    assert math.isnan(M.percentile([], 50))
    c_ok = M.Completed(rid=0, arrival_s=0.0, admitted_s=0.0,
                       first_token_s=0.05, finish_s=0.2,
                       prompt_len=4, output_len=4)
    c_late = M.Completed(rid=1, arrival_s=0.0, admitted_s=0.0,
                         first_token_s=0.5, finish_s=0.9,
                         prompt_len=4, output_len=4)
    assert M.meets_slo(c_ok, slo_ttft_ms=100, slo_tpot_ms=100)
    assert not M.meets_slo(c_late, slo_ttft_ms=100, slo_tpot_ms=100)
    # single-token outputs are judged on TTFT alone (no TPOT sample)
    c_one = M.Completed(rid=2, arrival_s=0.0, admitted_s=0.0,
                        first_token_s=0.05, finish_s=0.05,
                        prompt_len=4, output_len=1)
    assert math.isnan(c_one.tpot_ms)
    assert M.meets_slo(c_one, slo_ttft_ms=100, slo_tpot_ms=0.001)
    # an outage window with zero completions reports null, never a
    # fabricated 1.0 (the crash-dip channel must show the outage)
    tl = M.goodput_timeline([c_ok, M.Completed(
        rid=3, arrival_s=0.0, admitted_s=0.0, first_token_s=1.6,
        finish_s=1.7, prompt_len=4, output_len=4)],
        slo_ttft_ms=100, slo_tpot_ms=100, window_s=0.5)
    assert tl[0]["completed"] == 1 and tl[0]["goodput_frac"] == 1.0
    assert tl[1]["completed"] == 0 and tl[1]["goodput_frac"] is None
    assert tl[-1]["completed"] == 1 and tl[-1]["goodput_frac"] == 0.0


# ------------------------------- windowed sparse prefill (ISSUE 10)

longcontext = pytest.mark.longcontext


def _run_prefill(cfg, cc, prompt, chunk=3):
    from dlnetbench_tpu.serving import decode as D
    params = tfm.init_params(jax.random.key(0), cfg)
    cache = PagedKVCache(cc)
    k, v = device_buffers(cc)
    cache.allocate(0, len(prompt) + 1)
    prefill = jax.jit(D.make_prefill_chunk(cfg, cc, chunk))
    row = jnp.asarray(cache.block_tables[0])
    pos, nxt = 0, None
    while pos < len(prompt):
        n = min(chunk, len(prompt) - pos)
        ch = np.zeros(chunk, np.int32)
        ch[:n] = prompt[pos:pos + n]
        k, v, nxt = prefill(params, k, v, jnp.asarray(ch),
                            jnp.int32(pos), jnp.int32(n), row)
        pos += n
    return int(nxt)


@longcontext
def test_windowed_prefill_token_parity_with_dense():
    """ISSUE 10 satellite: the sliding-window prefill gathers only the
    window's pages, yet (a) with a window covering the whole prompt it
    reproduces the dense path's token exactly, and (b) with a NARROW
    window it reproduces the windowed full forward (the dense-masked
    reference) — same mask builders, same semantics."""
    import dataclasses
    cc = CacheConfig(num_layers=2, num_kv_heads=2, head_dim=8,
                     num_pages=16, page_size=4, max_seqs=2,
                     max_pages_per_seq=6)
    prompt = np.asarray([5, 9, 3, 11, 7, 2, 13, 1, 8, 4, 10, 6,
                         12, 14], np.int32)
    cfg = tiny_model()
    dense_tok = _run_prefill(cfg, cc, prompt)
    big = dataclasses.replace(cfg, attention_window=cc.max_seq_len)
    assert _run_prefill(big, cc, prompt) == dense_tok

    win = dataclasses.replace(cfg, attention_window=6)
    got = _run_prefill(win, cc, prompt)
    params = tfm.init_params(jax.random.key(0), cfg)
    ref_cfg = dataclasses.replace(win, seq_len=len(prompt),
                                  attention_impl="xla")
    logits = tfm.forward(params, jnp.asarray(prompt)[None], ref_cfg)
    assert got == int(jnp.argmax(logits[0, -1]))


@longcontext
def test_windowed_prefill_single_chunk_and_page_aligned_window():
    """Window edge shapes: a window equal to one page and a chunk
    larger than the remaining prompt (padding tail) still match the
    dense-masked reference."""
    import dataclasses
    cc = CacheConfig(num_layers=2, num_kv_heads=2, head_dim=8,
                     num_pages=16, page_size=4, max_seqs=2,
                     max_pages_per_seq=6)
    prompt = np.asarray([3, 1, 4, 1, 5, 9, 2, 6, 5, 3], np.int32)
    win = tiny_model(attention_window=4)
    got = _run_prefill(win, cc, prompt, chunk=8)
    params = tfm.init_params(jax.random.key(0), win)
    ref_cfg = dataclasses.replace(win, seq_len=len(prompt),
                                  attention_impl="xla")
    logits = tfm.forward(params, jnp.asarray(prompt)[None], ref_cfg)
    assert got == int(jnp.argmax(logits[0, -1]))


@longcontext
def test_serving_rejects_segment_masks():
    from dlnetbench_tpu.serving.decode import check_config
    with pytest.raises(ValueError, match="segment"):
        check_config(tiny_model(attention_seg_avg=16))


@longcontext
def test_decode_step_refuses_window_configs():
    """The decode step attends the FULL cache (the paged kernel has no
    lower-bound mask): a window config must fail loud at construction
    instead of silently generating under different attention semantics
    than the windowed prefill/training path."""
    from dlnetbench_tpu.serving import decode as D
    cc = CacheConfig(num_layers=2, num_kv_heads=2, head_dim=8,
                     num_pages=16, page_size=4, max_seqs=2,
                     max_pages_per_seq=6)
    cfg = tiny_model(attention_window=6)
    with pytest.raises(ValueError, match="window"):
        D.make_decode_step(cfg, cc)
    # the prefill side stays windowed (the ISSUE 10 satellite)
    D.make_prefill_chunk(cfg, cc, chunk=4)
