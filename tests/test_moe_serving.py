"""MoE decode in the serving tier (ISSUE 15 tentpole leg d):
per-expert token batching with overflow rounds, greedy token parity
against the training forward, the fused multi-step loop's accumulated
imbalance stats, the seeded skew injection, flight-ring telemetry,
and the record/parser/summary pathway."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.models import transformer as tfm
from dlnetbench_tpu.serving import moe_decode as MD
from dlnetbench_tpu.serving.arrivals import ArrivalPlan
from dlnetbench_tpu.serving.scheduler import Engine, ServingConfig

pytestmark = [pytest.mark.moe, pytest.mark.serving]

_F32 = jnp.float32


def moe_model(**over) -> tfm.TransformerConfig:
    kw = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
              ff_dim=64, num_layers=2, seq_len=32, gated=True,
              max_positions=0, dtype="float32", num_experts=4,
              top_k=2, moe_capacity_factor=1.0)
    kw.update(over)
    return tfm.TransformerConfig(**kw)


def moe_serving(**over) -> ServingConfig:
    kw = dict(slots=4, page_size=4, num_pages=64, max_seq_len=32,
              warmup_requests=0)
    kw.update(over)
    return ServingConfig(**kw)


def tiny_plan(n=6, seed=0) -> ArrivalPlan:
    return ArrivalPlan(kind="poisson", rate_rps=200.0, num_requests=n,
                       seed=seed, prompt_len=(4, 8), output_len=(4, 6))


# -------------------------------------------------- the MLP itself
def test_moe_mlp_rounds_lossless_vs_dense_math():
    """Whatever the round count, the result is the top-k gated sum —
    compare against the direct (unbatched) per-token computation at a
    capacity that FORCES multiple rounds."""
    b, d, e, h, k = 8, 16, 4, 24, 2
    x = jax.random.normal(jax.random.key(0), (b, d), _F32)
    wr = jax.random.normal(jax.random.key(1), (d, e), _F32)
    wg = jax.random.normal(jax.random.key(2), (e, d, h), _F32) * 0.1
    wu = jax.random.normal(jax.random.key(3), (e, d, h), _F32) * 0.1
    wd = jax.random.normal(jax.random.key(4), (e, h, d), _F32) * 0.1
    y, load, rounds = MD.moe_mlp_rounds(x, wr, wg, wu, wd, top_k=k,
                                        capacity=1)
    assert int(rounds) >= 2            # capacity 1 forces overflow
    assert int(load.sum()) == b * k
    # unbatched reference
    from dlnetbench_tpu.models import layers as L
    logits = L.router_logits(x, wr)
    tv, idx = jax.lax.top_k(logits, k)
    w = jax.nn.softmax(tv, axis=-1)
    ref = np.zeros((b, d), np.float32)
    for t in range(b):
        for j in range(k):
            ei = int(idx[t, j])
            xe = x[t][None]
            hh = (jax.nn.silu(xe @ wg[ei]) * (xe @ wu[ei]))
            ref[t] += float(w[t, j]) * np.asarray(hh @ wd[ei])[0]
    assert np.abs(np.asarray(y) - ref).max() < 1e-4


def test_moe_mlp_rounds_inactive_masked():
    b, d, e = 4, 16, 4
    x = jax.random.normal(jax.random.key(0), (b, d), _F32)
    wr = jax.random.normal(jax.random.key(1), (d, e), _F32)
    wg = jax.random.normal(jax.random.key(2), (e, d, 8), _F32)
    wu = jax.random.normal(jax.random.key(3), (e, d, 8), _F32)
    wd = jax.random.normal(jax.random.key(4), (e, 8, d), _F32)
    active = jnp.array([True, False, True, False])
    y, load, rounds = MD.moe_mlp_rounds(x, wr, wg, wu, wd, top_k=1,
                                        capacity=4, active=active)
    assert int(load.sum()) == 2        # inactive rows occupy nothing
    assert float(jnp.abs(y[1]).max()) == 0.0
    assert float(jnp.abs(y[3]).max()) == 0.0
    # no active rows: zero rounds, the loop never trips
    _, load0, rounds0 = MD.moe_mlp_rounds(
        x, wr, wg, wu, wd, top_k=1, capacity=4,
        active=jnp.zeros((b,), bool))
    assert int(rounds0) == 0 and int(load0.sum()) == 0


def test_skew_bias_seeded_and_off():
    assert MD.skew_bias(4, 0.0, 3) is None
    b1 = MD.skew_bias(4, 10.0, 3)
    b2 = MD.skew_bias(4, 10.0, 3)
    b3 = MD.skew_bias(4, 10.0, 4)
    assert jnp.all(b1 == b2)
    assert not jnp.all(b1 == b3)


# ------------------------------------------------------- the engine
def test_moe_decode_token_parity_vs_forward():
    """The serving acceptance anchor, MoE form: prefill+decode over
    the paged cache greedy-decodes the SAME tokens as iterated full
    forwards of the identical MoE model."""
    mcfg = moe_model()
    eng = Engine(mcfg, moe_serving())
    plan = tiny_plan()
    reqs = plan.sample()
    eng.run(reqs)
    from dlnetbench_tpu.serving.decode import prompt_tokens_for
    for r in reqs[:3]:
        toks = list(prompt_tokens_for(r, mcfg.vocab_size))
        ref = []
        for _ in range(r.output_len):
            logits = tfm.forward(eng.params, jnp.asarray([toks]), mcfg)
            nxt = int(jnp.argmax(logits[0, -1]))
            ref.append(nxt)
            toks.append(nxt)
        assert eng.token_streams[r.rid] == ref, r.rid


def test_moe_fused_loop_token_parity_and_stats():
    """N-step fused MoE decode == 1-step MoE decode token for token,
    and the loop's ACCUMULATED imbalance stats arrive at the host."""
    mcfg = moe_model()
    plan = tiny_plan()
    eng1 = Engine(mcfg, moe_serving())
    eng1.run(plan.sample())
    engN = Engine(mcfg, moe_serving(multi_step_n=4))
    engN.run(plan.sample())
    for rid, stream in eng1.token_streams.items():
        assert engN.token_streams[rid] == stream, rid
    blk = engN.moe_block()
    assert blk["dispatches"] > 0
    assert blk["rounds_mean"] > 0
    assert len(blk["expert_load"]) == 4


def test_moe_skew_increases_rounds_and_imbalance():
    """The study's mechanism: the seeded router skew concentrates load
    (imbalance up) and overflows the per-round capacity (rounds up) on
    the SAME arrival plan."""
    mcfg = moe_model(num_experts=8, top_k=1, seq_len=64)
    plan = ArrivalPlan(kind="poisson", rate_rps=400.0, num_requests=10,
                       seed=0, prompt_len=(4, 8), output_len=(6, 10))
    balanced = Engine(mcfg, moe_serving(slots=8, num_pages=160))
    balanced.run(plan.sample())
    skewed = Engine(mcfg, moe_serving(slots=8, num_pages=160,
                                      moe_skew=50.0, moe_skew_seed=1))
    skewed.run(plan.sample())
    b, s = balanced.moe_block(), skewed.moe_block()
    assert s["load_imbalance"] > b["load_imbalance"]
    assert s["rounds_mean"] > b["rounds_mean"]
    # k=1 full concentration: every token on one expert
    assert s["load_imbalance"] == pytest.approx(8.0)


def test_moe_quantized_cache_composes():
    """MoE decode over an int8 paged cache: the MLP path and the
    cache quantization are orthogonal; tokens still complete."""
    mcfg = moe_model()
    eng = Engine(mcfg, moe_serving(cache_dtype="int8"))
    done, _ = eng.run(tiny_plan(n=4).sample())
    assert len(done) == 4


def test_moe_speculative_refused():
    with pytest.raises(ValueError, match="[Mm]o[eE]"):
        Engine(moe_model(),
               moe_serving(speculative=True, multi_step_n=2))
    from dlnetbench_tpu.serving.speculative import check_spec_config
    with pytest.raises(ValueError, match="MoE"):
        check_spec_config(moe_model(), spec_k=2, drafter="ngram",
                          drafter_layers=1)


def test_moe_skew_validation():
    with pytest.raises(ValueError, match="moe_skew"):
        moe_serving(moe_skew=-1.0).validate()


def test_moe_telemetry_fields():
    """With the flight recorder armed, engine-step samples carry the
    expert-imbalance telemetry (moe_rounds / moe_imbalance)."""
    from dlnetbench_tpu.metrics import telemetry
    rec = telemetry.enable(capacity=256)
    try:
        eng = Engine(moe_model(), moe_serving())
        eng.run(tiny_plan(n=3).sample())
    finally:
        telemetry.disable()
    samples = [s for s in rec.samples() if "moe_rounds" in s]
    assert samples, "no engine-step sample carried moe telemetry"
    assert all(s["moe_imbalance"] >= 1.0 for s in samples)


def test_moe_record_parser_summary_pathway():
    """run_serving -> record: the measured moe block + comparable
    knobs ride the globals, the parser hoists moe_* columns, and
    serving_summary carries skew/imbalance/rounds."""
    pytest.importorskip("pandas")
    import io

    from dlnetbench_tpu.analysis.bandwidth import serving_summary
    from dlnetbench_tpu.metrics.emit import emit_result
    from dlnetbench_tpu.metrics.parser import records_to_dataframe
    from dlnetbench_tpu.serving.scheduler import run_serving
    mcfg = moe_model()
    res = run_serving(mcfg, moe_serving(moe_skew=10.0, moe_skew_seed=2,
                                        warmup_requests=0),
                      tiny_plan(n=4))
    rec = emit_result(res, stream=io.StringIO())
    g = rec["global"]
    assert g["moe"]["dispatches"] > 0
    assert g["serving_config"]["moe_skew"] == 10.0
    df = records_to_dataframe([rec])
    assert float(df["moe_load_imbalance"].iloc[0]) >= 1.0
    assert float(df["moe_rounds_mean"].iloc[0]) > 0
    assert "moe_expert_load_max" in df.columns
    summ = serving_summary([rec])
    assert float(summ["moe_skew"].iloc[0]) == 10.0
    assert float(summ["expert_imbalance"].iloc[0]) >= 1.0
    assert float(summ["moe_rounds_mean"].iloc[0]) > 0


def test_moe_crash_shrink_composes():
    """A crash+shrink fault plan on a MoE engine: the rebuilt engine
    keeps serving MoE (the moe block survives segmentation) and every
    request completes."""
    from dlnetbench_tpu.faults.plan import FaultPlan
    mcfg = moe_model()
    scfg = moe_serving(slots=4, world=2, warmup_requests=0)
    plan = tiny_plan(n=6)
    fplan = FaultPlan.from_dict({
        "policy": "shrink",
        "events": [{"kind": "crash", "ranks": [1], "iteration": 3}]})
    from dlnetbench_tpu.serving.scheduler import run_serving
    res = run_serving(mcfg, scfg, plan, fault_plan=fplan)
    g = res.global_meta
    assert g["serving"]["completed"] == 6
    assert g.get("degraded_world") == [0]
    assert g["moe"]["dispatches"] > 0
