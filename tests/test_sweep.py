"""Sweep driver (sweep.py): grid expansion, command/tag construction, and
one real two-point subprocess sweep on the virtual CPU mesh whose records
land in a DataFrame with the swept axis as a column."""
from __future__ import annotations

import os
import sys
from pathlib import Path

import pytest

from dlnetbench_tpu import sweep
from dlnetbench_tpu.metrics.parser import get_metrics_dataframe


def test_expand_grid():
    assert sweep.expand_grid({}) == [{}]
    pts = sweep.expand_grid({"a": ["1", "2"], "b": ["x"]})
    assert pts == [{"a": "1", "b": "x"}, {"a": "2", "b": "x"}]


def test_point_command_splits_env_and_flags():
    argv, env = sweep.point_command(
        "dp", {"num_buckets": "4", "env:XLA_FLAGS": "--foo"}, ["--extra"])
    assert argv[:4] == [sys.executable, "-m", "dlnetbench_tpu.cli", "dp"]
    # passthrough first, swept flags AFTER it (last occurrence wins in
    # argparse, so a colliding fixed flag can never shadow the axis)
    assert argv[4] == "--extra"
    nb = argv.index("--num_buckets")
    assert argv[nb + 1] == "4" and nb > 4
    assert env == {"XLA_FLAGS": "--foo"}
    # both axes become --tag entries, env: prefix stripped
    tags = [argv[i + 1] for i, a in enumerate(argv) if a == "--tag"]
    assert set(tags) == {"num_buckets=4", "XLA_FLAGS=--foo"}


def test_duplicate_axis_rejected(capsys):
    with pytest.raises(SystemExit):
        sweep.main(["dp", "--model", "m", "--out", "/dev/null",
                    "--axis", "num_buckets=2", "--axis", "num_buckets=4"])
    assert "given twice" in capsys.readouterr().err


def test_axis_parsing_errors():
    with pytest.raises(ValueError):
        sweep._parse_axis("novalue")
    key, vals = sweep._parse_axis("env:LIBTPU_INIT_ARGS=--a=1,2|--b")
    assert key == "env:LIBTPU_INIT_ARGS" and vals == ["--a=1,2", "--b"]


def test_bound_tally_skips_records_from_earlier_sweeps(tmp_path, capsys):
    """emit_result appends, so a reused --out file carries records from
    earlier sweeps — the per-grid tally must only count records past the
    pre-sweep byte offset or grid B inherits grid A's verdicts."""
    import io
    import json as _json
    out = tmp_path / "runs.jsonl"

    def rec(bound):
        return _json.dumps({"global": {"attribution": {"bound": bound}}})

    out.write_text(rec("host") + "\n" + rec("host") + "\n")
    offset = out.stat().st_size
    with out.open("a") as f:
        f.write(rec("mxu") + "\n" + rec("mxu") + "\n" + rec("ici") + "\n")

    stream = io.StringIO()
    tally = sweep.bound_tally(str(out), stream, start_offset=offset)
    assert tally == {"mxu": 2, "ici": 1}
    assert "host" not in stream.getvalue()

    # offset 0 (fresh file) still tallies everything
    assert sweep.bound_tally(str(out), io.StringIO()) == \
        {"host": 2, "mxu": 2, "ici": 1}
    # unreadable file: {} and silence
    assert sweep.bound_tally(str(tmp_path / "missing.jsonl"),
                             io.StringIO()) == {}


def test_in_process_mode_calls_cli_directly(monkeypatch):
    """Flag-only grids default to in-process execution: cli.main is
    invoked in THIS process (sharing burn calibration, meshes and the
    jax backend across points) with the proxy argv, no subprocess."""
    from dlnetbench_tpu import cli
    calls = []
    monkeypatch.setattr(cli, "main", lambda argv: calls.append(argv) or 0)

    def boom(*a, **k):  # the subprocess path must never fire
        raise AssertionError("subprocess.run called in in-process mode")
    monkeypatch.setattr(sweep.subprocess, "run", boom)

    failed = sweep.run_sweep("dp", {"num_buckets": ["2", "4"]},
                             ["--model", "m"])
    assert failed == 0
    assert len(calls) == 2
    assert calls[0][0] == "dp" and "--num_buckets" in calls[0]
    assert calls[0][calls[0].index("--num_buckets") + 1] == "2"
    assert calls[1][calls[1].index("--num_buckets") + 1] == "4"


def test_sweep_emits_one_span_per_point(monkeypatch):
    """With tracing enabled (the --trace_out path), every grid point is
    wrapped in a 'sweep-point' span tagged with its axis values, so a
    traced sweep attributes wall-clock per configuration."""
    from dlnetbench_tpu import cli
    from dlnetbench_tpu.metrics import spans

    monkeypatch.setattr(cli, "main", lambda argv: 0)
    tracer = spans.enable()
    try:
        failed = sweep.run_sweep("dp", {"num_buckets": ["2", "4"]},
                                 ["--model", "m"])
    finally:
        spans.disable()
    assert failed == 0
    points = [s for s in tracer.spans if s["name"] == "sweep-point"]
    assert [s["attrs"]["point"] for s in points] == \
        ["num_buckets=2", "num_buckets=4"]
    assert all(s["attrs"]["mode"] == "in-process" for s in points)


def test_env_axis_forces_subprocess(monkeypatch):
    """env: axes need backend-init-time isolation: auto mode must take
    the subprocess path, and forcing in-process is an error."""
    ran = []

    class _Proc:
        returncode = 0

    monkeypatch.setattr(sweep.subprocess, "run",
                        lambda argv, env=None: ran.append((argv, env))
                        or _Proc())
    axes = {"env:XLA_FLAGS": ["--a", "--b"]}
    assert sweep.run_sweep("dp", axes, ["--model", "m"]) == 0
    assert len(ran) == 2 and ran[0][1]["XLA_FLAGS"] == "--a"
    with pytest.raises(ValueError, match="fresh subprocess"):
        sweep.run_sweep("dp", axes, ["--model", "m"], in_process=True)


def test_in_process_point_failure_counted(monkeypatch):
    from dlnetbench_tpu import cli
    monkeypatch.setattr(cli, "main",
                        lambda argv: (_ for _ in ()).throw(SystemExit(2)))
    failed = sweep.run_sweep("dp", {"num_buckets": ["2", "4"]},
                             ["--model", "m"], keep_going=True)
    assert failed == 2


def test_dry_run_prints_commands(capsys):
    rc = sweep.main(["dp", "--model", "gpt2_l_16_bfloat16",
                     "--out", "/dev/null", "--axis", "num_buckets=2,4",
                     "--dry_run", "--", "--platform", "cpu"])
    assert rc == 0
    err = capsys.readouterr().err
    assert "[sweep 1/2]" in err and "[sweep 2/2]" in err
    assert "--num_buckets 2" in err and "--num_buckets 4" in err


@pytest.mark.slow
def test_real_two_point_sweep(tmp_path):
    out = tmp_path / "sweep.jsonl"
    env = {**os.environ,
           "XLA_FLAGS": "--xla_force_host_platform_device_count=4"}
    # run through main() but patch env via the env: axis mechanism is
    # subprocess-side; here we set the parent env for the children
    old = os.environ.copy()
    os.environ.update(env)
    try:
        rc = sweep.main([
            "dp", "--model", "gpt2_l_16_bfloat16", "--out", str(out),
            "--axis", "num_buckets=2,4", "--",
            "--platform", "cpu", "-r", "2", "-w", "1",
            "--size_scale", "1e-5", "--time_scale", "1e-4",
            "--no_topology"])
    finally:
        os.environ.clear()
        os.environ.update(old)
    assert rc == 0
    df = get_metrics_dataframe(out, "dp")
    # swept axis surfaced as a column with both values present, keeping
    # the proxy's int typing (globals win over the string tag)
    assert sorted(df["num_buckets"].unique()) == [2, 4]
    assert (df.groupby("num_buckets")["run"].count() > 0).all()


@pytest.mark.slow
def test_pod_study_end_to_end(tmp_path):
    """examples/pod_study.py (the north-star study) must run every proxy
    on the virtual mesh and produce the bandwidth table + the PNGs."""
    import subprocess
    proc = subprocess.run(
        [sys.executable, "examples/pod_study.py",
         "--out_dir", str(tmp_path), "--devices", "4", "--runs", "1",
         "--models", "mixtral_8x7b_16_bfloat16"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "XLA_FLAGS": ""},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "effective bandwidth per collective" in proc.stdout
    # every proxy family must have reported at least one bandwidth row
    for proxy in ("dp", "fsdp", "hybrid_2d", "hybrid_3d", "hybrid_3d_moe",
                  "ring_attention", "ulysses"):
        assert proxy in proc.stdout, f"{proxy} missing from study output"
    assert (tmp_path / "bandwidth_summary.csv").stat().st_size > 0
    for png in ("dp_runtime_scaling", "dp_barrier_by_bucket",
                "pareto_proxies"):
        assert (tmp_path / f"{png}.png").stat().st_size > 0


@pytest.mark.slow
def test_pod_study_native_tier(tmp_path):
    """The same north-star study driven through the C++ binaries
    (--tier native): every proxy runs on the threaded shm fabric and the
    analysis layer ingests the native records identically."""
    import shutil
    import subprocess
    if shutil.which("cmake") is None or shutil.which("ninja") is None:
        pytest.skip("cmake/ninja not available")
    repo = Path(__file__).resolve().parent.parent
    from dlnetbench_tpu.utils.native_build import native_bin
    native_bin(repo)
    proc = subprocess.run(
        [sys.executable, "examples/pod_study.py", "--tier", "native",
         "--out_dir", str(tmp_path), "--devices", "8", "--runs", "1",
         "--models", "mixtral_8x7b_16_bfloat16"],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "XLA_FLAGS": ""},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "effective bandwidth per collective" in proc.stdout
    for proxy in ("fsdp", "hybrid_2d", "hybrid_3d_moe", "ring_attention",
                  "ulysses"):
        assert proxy in proc.stdout, f"{proxy} missing from native study"
    assert (tmp_path / "bandwidth_summary.csv").stat().st_size > 0


@pytest.mark.slow
def test_example_study_end_to_end(tmp_path):
    """examples/dp_bucket_study.py must run the whole sweep->parse->plot
    loop and write the three PNGs."""
    import subprocess
    proc = subprocess.run(
        [sys.executable, "examples/dp_bucket_study.py",
         "--out_dir", str(tmp_path), "--buckets", "2,4", "--devices", "4"],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "XLA_FLAGS": ""},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for png in ("runtime_by_bucket", "barrier_by_bucket", "pareto"):
        assert (tmp_path / f"{png}.png").stat().st_size > 0
    assert "mean per bucket count" in proc.stdout


@pytest.mark.slow
def test_pod_study_native_hier_backend(tmp_path):
    """The north-star study over the multi-host device path: every point
    runs as 2 OS processes (per-process executor + TCP DCN combine) and
    the per-process records merge into the study stream."""
    import subprocess
    proc = subprocess.run(
        [sys.executable, "examples/pod_study.py",
         "--out_dir", str(tmp_path), "--tier", "native",
         "--backend", "pjrt-hier", "--devices", "4", "--runs", "1",
         "--models", "mixtral_8x7b_16_bfloat16"],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "DLNB_PJRT_EXECUTOR": "host"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "effective bandwidth per collective" in proc.stdout
    assert (tmp_path / "bandwidth_summary.csv").stat().st_size > 0
    # merged records carry the hierarchy identity
    from dlnetbench_tpu.metrics.parser import load_records
    recs = load_records(tmp_path / "records.jsonl")
    assert recs, "no merged records written"
    for rec in recs:
        assert rec["global"]["dcn_transport"] == "tcp"
        assert rec["global"]["num_processes"] == 2
