"""HF-config -> architecture-card mapping (hf_import.py).

Config dicts below mirror the public HF configs of the registry models;
mapping them must reproduce the committed cards field-for-field (reference
python/download_models.py caches exactly these configs).
"""
from __future__ import annotations

import json

import pytest

from dlnetbench_tpu.core.model_card import load_model_card
from dlnetbench_tpu import hf_import


GPT2_L = {"model_type": "gpt2", "n_embd": 1280, "n_head": 20, "n_layer": 36,
          "n_positions": 1024, "n_inner": None, "vocab_size": 50257}

LLAMA3_8B = {"model_type": "llama", "hidden_size": 4096,
             "num_attention_heads": 32, "num_key_value_heads": 8,
             "intermediate_size": 14336, "max_position_embeddings": 8192,
             "num_hidden_layers": 32, "vocab_size": 128256}

MIXTRAL = {"model_type": "mixtral", "hidden_size": 4096,
           "num_attention_heads": 32, "num_key_value_heads": 8,
           "intermediate_size": 14336, "max_position_embeddings": 32768,
           "num_hidden_layers": 32, "vocab_size": 32000,
           "num_local_experts": 8, "num_experts_per_tok": 2}

VIT_B = {"model_type": "vit", "hidden_size": 768, "num_attention_heads": 12,
         "intermediate_size": 3072, "num_hidden_layers": 12,
         "image_size": 224, "patch_size": 16, "num_labels": 1000}


@pytest.mark.parametrize("name,cfg", [
    ("gpt2_l", GPT2_L), ("llama3_8b", LLAMA3_8B),
    ("mixtral_8x7b", MIXTRAL), ("vit_b", VIT_B),
])
def test_mapping_reproduces_committed_card(name, cfg):
    got = hf_import.card_from_hf_config(name, cfg)
    want = load_model_card(name)
    assert got == want


def test_gpt2_default_inner_is_4x():
    card = hf_import.card_from_hf_config("gpt2_l", GPT2_L)
    assert card.ff_dim == 4 * 1280 and card.tied_embeddings


def test_unknown_model_type_raises():
    with pytest.raises(ValueError, match="model_type"):
        hf_import.card_from_hf_config("x", {"model_type": "mamba"})
    with pytest.raises(KeyError):
        hf_import.fetch_card("not_a_model")


def test_card_json_roundtrip(tmp_path):
    """import_model (offline fallback) writes a card that load_model_card
    parses back to the identical dataclass, for every registry model."""
    for name in hf_import.REGISTRY:
        hf_import.import_model(name, tmp_path)
        assert load_model_card(name, tmp_path) == load_model_card(name)


def test_cli_list_and_all(tmp_path, capsys):
    assert hf_import.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "meta-llama/Meta-Llama-3-8B" in out and "gpt2-large" in out
    assert hf_import.main(["--all", "--out_dir", str(tmp_path)]) == 0
    written = sorted(p.stem for p in tmp_path.glob("*.json"))
    assert written == sorted(hf_import.REGISTRY)
    # moe block survives the roundtrip as nested JSON
    raw = json.loads((tmp_path / "mixtral_8x7b.json").read_text())
    assert raw["moe_params"]["num_experts_per_tok"] == 2
