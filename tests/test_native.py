"""Integration tests for the native (C++) tier.

Builds ``native/`` with CMake+Ninja once per session, runs its ctest unit
suites and every proxy binary on the in-process threaded fabric, and
verifies:
  * the emitted JSON record parses through the SAME analysis pipeline as
    the Python tier (``metrics.parser``) with full rank coverage,
  * the native schedule algebra agrees with the Python tier's
    (cross-implementation check — the Python module is the executable
    spec for ``native/include/dlnb/schedule.hpp``),
  * congestor (`_loop`) binaries exist for every proxy (reference
    PROXY_LOOP builds, Makefile.common:96-109).
"""
from __future__ import annotations

import json
import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"

pytestmark = pytest.mark.skipif(
    shutil.which("cmake") is None or shutil.which("ninja") is None,
    reason="cmake/ninja not available")

# The session-scoped shared build-tree fixture `native_bin` lives in
# conftest.py, so the default lane and the opt-in heavy lane
# (-m native_slow; see pyproject [tool.pytest.ini_options]) share one
# incremental CMake/Ninja tree.  Heavy tests — wide multi-process
# configs, mid-run kill tests built on multi-second sleeps, sleep-driven
# schedule-wall proofs — carry @pytest.mark.native_slow; at least one
# representative of each family (shm, pjrt-host, tcp, hier, merge,
# energy, death-detection) stays in the default lane.


def run_proxy(native_bin, name, *extra, model="gpt2_l_16_bfloat16", world=4,
              env=None):
    cmd = [str(native_bin / name), "--model", model, "--world", str(world),
           "--time_scale", "0.0001", "--size_scale", "0.00001",
           "--runs", "2", "--warmup", "1", "--no_topology",
           "--base_path", str(REPO), *map(str, extra)]
    full_env = None
    if env:
        import os
        full_env = {**os.environ, **env}
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=180,
                         env=full_env)
    assert out.returncode == 0, f"{name} failed: {out.stderr}"
    return json.loads(out.stdout)


def test_native_unit_suites(native_bin):
    for t in ("test_core", "test_comm", "test_pjrt"):
        out = subprocess.run([str(native_bin.parent / t)],
                             capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, f"{t} failures:\n{out.stdout}"


@pytest.mark.parametrize("name,extra,model,world", [
    ("dp", ("--num_buckets", 4), "gpt2_l_16_bfloat16", 4),
    ("fsdp", ("--num_units", 4, "--sharding_factor", 4),
     "llama3_8b_16_bfloat16", 8),
    ("hybrid_2d", ("--num_stages", 4, "--num_microbatches", 4),
     "llama3_8b_16_bfloat16", 8),
    ("hybrid_3d", ("--num_stages", 2, "--num_microbatches", 4, "--tp", 2),
     "llama3_8b_16_bfloat16", 8),
    ("hybrid_3d_moe",
     ("--num_stages", 4, "--num_microbatches", 4, "--num_expert_shards", 2),
     "mixtral_8x7b_16_bfloat16", 8),
    ("ring_attention", ("--sp", 4, "--max_layers", 2),
     "llama3_8b_16_bfloat16", 4),
    ("ulysses", ("--sp", 4, "--max_layers", 2), "llama3_8b_16_bfloat16", 4),
])
def test_native_proxy_record(native_bin, name, extra, model, world):
    from dlnetbench_tpu.metrics.parser import records_to_dataframe, \
        validate_record

    rec = run_proxy(native_bin, name, *extra, model=model, world=world)
    assert rec["section"] == name
    assert rec["global"]["world_size"] == world
    assert rec["global"]["backend"] == "shm"
    # transport provenance: in-process thread bytes, stamped so the
    # bandwidth table can never read these rows as fabric physics
    assert rec["global"]["transport"] == "shm"
    # schema v2 parity with the Python tier: band summaries ride the
    # record (validate_record cross-checks each n against its samples)
    assert rec["version"] == 2
    s = rec["ranks"][0]["summary"]["runtimes"]
    assert s["n"] == rec["num_runs"]
    assert s["band"][0] <= s["value"] <= s["band"][1]
    assert s["best"] == s["band"][0] > 0
    validate_record(rec)  # full rank set, per-run timer lengths
    df = records_to_dataframe([rec])
    assert len(df) == world * rec["num_runs"]
    assert (df["runtime"] > 0).all()


def test_native_timers_expected(native_bin):
    rec = run_proxy(native_bin, "fsdp", "--num_units", 4,
                    "--sharding_factor", 2, model="llama3_8b_16_bfloat16",
                    world=4)
    row = rec["ranks"][0]
    for timer in ("runtimes", "allgather", "allgather_wait_fwd",
                  "allgather_wait_bwd", "reduce_scatter", "barrier_time"):
        assert timer in row, f"missing fsdp timer {timer}"
        assert len(row[timer]) == rec["num_runs"]
    # replica grid recorded per rank
    assert {r["replica_id"] for r in rec["ranks"]} == {0, 1}


def test_native_schedule_matches_python(native_bin):
    """The dp bucket split and message sizes must agree across tiers."""
    from dlnetbench_tpu.core.model_stats import load_model_stats
    from dlnetbench_tpu.core.schedule import dp_schedule

    rec = run_proxy(native_bin, "dp", "--num_buckets", 7,
                    model="llama3_70b_16_bfloat16", world=2)
    stats = load_model_stats("llama3_70b_16_bfloat16")
    sched = dp_schedule(stats, 7)
    assert rec["global"]["schedule_bucket_bytes"] == sched.bucket_bytes


@pytest.mark.moe
def test_native_moe_a2a_matches_jax_twin(native_bin):
    """Native-vs-SPMD MoE schedule parity (ISSUE 15 satellite): the
    a2a bytes/step the native hybrid_3d_moe RECORD declares equal the
    JAX twin's arithmetic (models/moe.a2a_elems_per_rank — the same
    formula the twin's actual [E, C, d] dispatch buffer realizes at
    cf=1, pinned buffer-vs-formula by tests/test_moe.py)."""
    from dlnetbench_tpu.core.model_card import load_model_card
    from dlnetbench_tpu.core.model_stats import load_model_stats
    from dlnetbench_tpu.models import moe as moe_mod

    ep, mbs = 2, 4
    rec = run_proxy(native_bin, "hybrid_3d_moe", "--num_stages", 4,
                    "--num_microbatches", mbs, "--num_expert_shards",
                    ep, model="mixtral_8x7b_16_bfloat16", world=8)
    stats = load_model_stats("mixtral_8x7b_16_bfloat16")
    card = load_model_card("mixtral_8x7b")
    tokens_per_mb = (stats.batch_size // mbs) * stats.seq_len
    twin = moe_mod.a2a_elems_per_rank(tokens_per_mb, card.top_k,
                                      stats.embed_dim, ep)
    # the native record scales sizes (harness.hpp scale_count: floor,
    # min 1) — undo the dev-box scaling to compare the declared
    # full-size message against the twin formula
    scale = rec["global"]["size_scale"]
    elems = rec["global"]["a2a_bytes"] // 2  # bf16 itemsize
    assert elems == max(1, int(twin * scale))


def test_native_reads_reference_stats_files(native_bin, tmp_path):
    """Keyed parsing survives the reference's drifted committed files
    (lowercase ``non_expert_size``, SURVEY.md §7.4) — point the binary at a
    base-path layout holding the REFERENCE's file, not our clean copy."""
    ref = Path("/root/reference/model_stats/llama3_70b_16_bfloat16.txt")
    if not ref.exists():
        pytest.skip("reference tree not mounted")
    assert "non_expert_size" in ref.read_text(), \
        "expected the reference file to carry the lowercase-key drift"
    stats_dir = tmp_path / "dlnetbench_tpu" / "data" / "model_stats"
    stats_dir.mkdir(parents=True)
    shutil.copy(ref, stats_dir / ref.name)
    models_dir = tmp_path / "dlnetbench_tpu" / "data" / "models"
    models_dir.mkdir(parents=True)
    out = subprocess.run(
        [str(native_bin / "dp"), "--model", "llama3_70b_16_bfloat16",
         "--world", "2", "--num_buckets", "2", "--runs", "1", "--warmup", "1",
         "--time_scale", "0.00001", "--size_scale", "0.00001",
         "--no_topology", "--base_path", str(tmp_path)],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    rec = json.loads(out.stdout)
    # drifted lowercase key parsed correctly (llama3_70b non_expert_size
    # equals model_size in the reference's committed data)
    total = sum(rec["global"]["schedule_bucket_bytes"])
    assert total > 0


# ---------------------------------------------------------------------
# --backend pjrt: the PJRT fabric (VERDICT r1 #1).  The host executor
# stands in for the plugin in CI — identical CollectiveProgram semantics,
# same rendezvous/slot/cache machinery (pjrt_fabric.hpp); the plugin
# path itself is exercised by test_native_pjrt_real_plugin when a TPU
# is reachable.

PJRT_HOST = {"DLNB_PJRT_EXECUTOR": "host"}


@pytest.mark.parametrize("name,extra,model,world", [
    ("dp", ("--num_buckets", 4), "gpt2_l_16_bfloat16", 4),
    ("fsdp", ("--num_units", 3, "--sharding_factor", 2),
     "gpt2_l_16_bfloat16", 4),
    ("hybrid_2d", ("--num_stages", 2, "--num_microbatches", 4),
     "gpt2_l_16_bfloat16", 4),
    ("hybrid_3d", ("--num_stages", 2, "--num_microbatches", 2, "--tp", 2),
     "gpt2_l_16_bfloat16", 8),
    ("hybrid_3d_moe",
     ("--num_stages", 2, "--num_microbatches", 2, "--num_expert_shards", 2),
     "mixtral_8x7b_16_bfloat16", 8),
    ("ring_attention", ("--sp", 4, "--max_layers", 2),
     "llama3_8b_16_bfloat16", 4),
    ("ulysses", ("--sp", 2, "--max_layers", 2), "llama3_8b_16_bfloat16", 4),
])
def test_native_pjrt_backend_record(native_bin, name, extra, model, world):
    from dlnetbench_tpu.metrics.parser import records_to_dataframe, \
        validate_record

    rec = run_proxy(native_bin, name, "--backend", "pjrt", *extra,
                    model=model, world=world, env=PJRT_HOST)
    g = rec["global"]
    assert g["backend"] == "pjrt"
    assert g["pjrt_executor"] == "host"
    assert g["p2p_transport"] == "host"
    # executor/transport provenance: the CI stand-in is host memory
    # traffic and must say so (analysis/bandwidth.py transport column)
    assert g["executor"] == "HostExecutor"
    assert g["transport"] == "host"
    # the executable cache was exercised: at least one compile, and reuse
    # across warmup+measured iterations produces hits
    assert g["cache_misses"] >= 1
    assert g["cache_hits"] > g["cache_misses"]
    validate_record(rec)
    df = records_to_dataframe([rec])
    assert len(df) == world * rec["num_runs"]
    assert (df["runtime"] > 0).all()


def test_native_pjrt_executor_forced_plugin_fails_cleanly(native_bin):
    """--backend pjrt with DLNB_PJRT_EXECUTOR=plugin and a bogus plugin
    path must error out, not silently fall back."""
    import os
    cmd = [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
           "--world", "2", "--num_buckets", "2", "--backend", "pjrt",
           "--pjrt_plugin", "/nonexistent/libtpu.so",
           "--runs", "1", "--warmup", "1", "--time_scale", "0.0001",
           "--size_scale", "0.00001", "--no_topology",
           "--base_path", str(REPO)]
    out = subprocess.run(
        cmd, capture_output=True, text=True, timeout=60,
        env={**os.environ, "DLNB_PJRT_EXECUTOR": "plugin"})
    assert out.returncode != 0
    assert "plugin" in out.stderr


def test_native_pjrt_devices_validation(native_bin):
    """--devices shorter than world is a startup error (reference -d
    semantics, utils.hpp:62-71)."""
    cmd = [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
           "--world", "4", "--num_buckets", "2", "--backend", "pjrt",
           "--devices", "0,1", "--no_topology", "--base_path", str(REPO)]
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    assert out.returncode != 0
    assert "devices" in out.stderr


def test_native_pjrt_real_plugin(native_bin):
    """End-to-end on the real PJRT plugin (libtpu) when a device is
    reachable: world=1 degenerate collectives still compile, cache, and
    execute on the TPU runtime (VERDICT r1 #1 done-criterion)."""
    import os
    probe = subprocess.run([str(native_bin / "pjrt_probe")],
                           capture_output=True, text=True, timeout=120)
    report = json.loads(probe.stdout)
    if not report.get("available"):
        pytest.skip(f"no usable PJRT plugin: {report.get('reason', '?')}")
    rec = run_proxy(native_bin, "dp", "--backend", "pjrt",
                    "--num_buckets", "2", world=1,
                    env={"DLNB_PJRT_EXECUTOR": "plugin"})
    g = rec["global"]
    assert g["backend"] == "pjrt"
    assert g["pjrt_executor"] != "host"
    assert g["executor"] == "PluginExecutor"
    assert g["transport"] == "ici"
    assert g["cache_misses"] >= 1


def test_loop_binaries_exist(native_bin):
    for name in ("dp", "fsdp", "hybrid_2d", "hybrid_3d", "hybrid_3d_moe",
                 "ring_attention", "ulysses"):
        assert (native_bin / f"{name}_loop").exists(), f"{name}_loop missing"


def test_loop_mode_runs_forever(native_bin):
    """The _loop congestor must not terminate on its own (reference
    PROXY_LOOP infinite run loop, dp.cpp:251-256)."""
    cmd = [str(native_bin / "dp_loop"), "--model", "gpt2_l_16_bfloat16",
           "--world", "2", "--num_buckets", "2", "--time_scale", "0.0001",
           "--size_scale", "0.00001", "--no_topology",
           "--base_path", str(REPO)]
    with pytest.raises(subprocess.TimeoutExpired):
        subprocess.run(cmd, capture_output=True, timeout=3)


@pytest.mark.parametrize("schedule", [
    "gpipe",  # the default-lane bubble representative
    pytest.param("1f1b", marks=[pytest.mark.slow, pytest.mark.native_slow]),
])
def test_native_pipeline_bubble(native_bin, schedule):
    """The native engine realizes the GPipe fill/drain bubble through its
    blocking rendezvous send/recv chain (reference hybrid_2d.cpp:106-133):
    at fixed S*M, runtime scales with (M+S-1)/(S*M), not M/(S*M).
    S=2,M=8 -> 9/16 model-time units; S=4,M=4 -> 7/16; expected ratio
    ~7/9 = 0.78, vs ~0.5 if stages never waited for upstream compute."""
    times = {}
    for S, M in ((2, 8), (4, 4)):
        rec = run_proxy(native_bin, "hybrid_2d", "--num_stages", S,
                        "--num_microbatches", M, "--dp", 1,
                        "--schedule", schedule, "--time_scale", "0.05",
                        "--runs", 3, world=S)
        assert rec["global"]["ticks_per_direction"] == M + S - 1
        times[S] = min(rec["ranks"][0]["runtimes"])
    ratio = times[4] / times[2]
    assert 0.62 < ratio < 0.95, (
        f"{schedule}: t(S=4)/t(S=2) = {ratio:.3f}; expected ~0.78 "
        f"(bubble present) — ~0.5 means the fill serialization regressed")


def test_native_1f1b_schedule(native_bin):
    """1F1B (slot-indexed Isend, per-stage warmup) emits a valid record
    with the schedule tagged and the same pp entry totals as GPipe."""
    from dlnetbench_tpu.metrics.parser import validate_record

    recs = {}
    for sch in ("gpipe", "1f1b", "zb"):
        rec = run_proxy(native_bin, "hybrid_2d", "--num_stages", 4,
                        "--num_microbatches", 8, "--schedule", sch,
                        model="llama3_8b_16_bfloat16", world=8)
        validate_record(rec)
        assert rec["global"]["schedule"] == sch
        recs[sch] = rec
    for other in ("1f1b", "zb"):
        for a, b in zip(recs["gpipe"]["ranks"], recs[other]["ranks"]):
            assert len(a["pp_comm"]) == len(b["pp_comm"])  # same hop totals


@pytest.mark.slow
@pytest.mark.native_slow
def test_native_zb_beats_two_phase_wall(native_bin):
    """ZB-H1's weight-grad ticks fill the drain bubble: with burns
    dominating (time_scale high enough that sleeps dwarf comm), the zb
    iteration must run measurably under the 1f1b/gpipe wall.  S=4, M=4:
    zb clock = 3M + S - 1 = 15 units vs 3(M + S - 1) = 21 — ratio 0.71."""
    times = {}
    for sch in ("1f1b", "zb"):
        rec = run_proxy(native_bin, "hybrid_2d", "--num_stages", 4,
                        "--num_microbatches", 4, "--dp", 1,
                        "--schedule", sch, "--time_scale", "0.05",
                        "--runs", 5, world=4)
        # min over ALL ranks x runs: the best observation is the one
        # closest to the schedule's clock; per-run jitter on a loaded CI
        # host only ever inflates sleep-driven runtimes
        times[sch] = min(t for row in rec["ranks"]
                         for t in row["runtimes"])
    ratio = times["zb"] / times["1f1b"]
    assert ratio < 0.9, (
        f"zb/1f1b runtime ratio {ratio:.3f}; expected ~0.71 — the "
        f"weight-grad ticks are not filling the bubble")


# ---------------------------------------------------------------------
# --backend tcp: the cross-process fabric (VERDICT r1 #7) — two real OS
# processes bootstrap over a loopback coordinator (the ncclUniqueId
# role, reference dp.cpp:166-189), run the proxy jointly, and their
# per-process records merge into one via dlnetbench_tpu.metrics.merge.

def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_ranks_with_port_retry(make_cmd, n, *, timeout=90):
    """Launch one process per rank on a freshly-probed port; the port
    can be stolen before rank 0 binds it (TOCTOU), so retry on a new
    port ONLY for that distinguishable signature — rank 0's bind
    failure, or a hang (the thief may itself be listening, wedging a
    rank against a foreign coordinator).  Any other non-zero exit is a
    real fabric regression and is returned for the caller to assert on,
    never retried into an occasional flake.  ``make_cmd(rank, port)``
    returns (argv, env-or-None); every process of an attempt is reaped
    before the next attempt or return.  Returns (procs, outs)."""
    for attempt in range(3):
        port = _free_port()
        procs = []
        for r in range(n):
            argv, env = make_cmd(r, port)
            procs.append(subprocess.Popen(
                argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env))
        outs, timed_out = [], False
        for p in procs:
            try:
                outs.append(p.communicate(timeout=timeout)[0])
            except subprocess.TimeoutExpired:
                timed_out = True
                p.kill()
                outs.append(p.communicate()[0])
        if all(p.returncode == 0 for p in procs):
            break
        port_stolen = (timed_out
                       or any("tcp: bind failed (port" in o for o in outs))
        if not port_stolen or attempt == 2:
            break
    return procs, outs


def test_native_tcp_selftest(native_bin):
    """Every collective + p2p + split verified across 2 OS processes
    ('correct sums' done-criterion)."""
    procs, outs = _spawn_ranks_with_port_retry(
        lambda r, port: ([str(native_bin / "tcp_selftest"), "--world", "2",
                          "--rank", str(r),
                          "--coordinator", f"127.0.0.1:{port}"], None),
        2)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r} OK" in out


@pytest.mark.slow
@pytest.mark.native_slow
def test_native_tcp_ring_zero_tail_blocks(native_bin):
    """DLNB_TCP_RING_THRESHOLD=1 forces every allreduce through the ring
    at world 5, where the selftest's small counts (2, 8 elements) leave
    ceil-partitioned blocks of length ZERO — the configuration whose
    tail-block pointer arithmetic was UB before the r4 fix (ADVICE r3).
    Sums must still come out exact."""
    import os
    procs, outs = _spawn_ranks_with_port_retry(
        lambda r, port: ([str(native_bin / "tcp_selftest"), "--world", "5",
                          "--rank", str(r),
                          "--coordinator", f"127.0.0.1:{port}"],
                         {**os.environ, "DLNB_TCP_RING_THRESHOLD": "1"}),
        5, timeout=120)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r} OK" in out


@pytest.mark.slow
@pytest.mark.native_slow
def test_native_tcp_ring_survives_clean_early_exit(native_bin):
    """Clean EARLY EXIT is not death (r4 fix): --final_ring makes fast
    ranks leave the fabric the instant their ring completes, while rank
    0's final receive is test-delayed 1 s.  Pre-fix, the departed peers'
    EOFs tripped the ring's transitive-death check (false positive) and
    the concurrent error paths double-joined shared slot workers (a
    deadlock seen ~40% of runs at procs 3).  Post-fix, the Bye frame
    marks the departure clean, rank 0's delayed take matches the
    already-queued frames, and every rank exits 0."""
    import os

    def make_cmd(r, port):
        env = {**os.environ}
        if r == 0:
            env["DLNB_TEST_RING_FINAL_RECV_DELAY_MS"] = "1000"
        return ([str(native_bin / "tcp_selftest"), "--world", "3",
                 "--rank", str(r), "--coordinator", f"127.0.0.1:{port}",
                 "--final_ring"], env)

    procs, outs = _spawn_ranks_with_port_retry(make_cmd, 3, timeout=60)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r} OK" in out


def test_native_tcp_peer_death_detected(native_bin, tmp_path):
    """Failure detection (SURVEY.md §5.3: the reference has none — a dead
    rank hangs the job at the vendor's mercy): when a TCP-fabric peer
    dies mid-run, the survivor must FAIL with a diagnostic, not hang."""
    import time

    port = _free_port()

    def spawn(r):
        return subprocess.Popen(
            [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
             "--world", "2", "--backend", "tcp", "--rank", str(r),
             "--coordinator", f"127.0.0.1:{port}", "--num_buckets", "2",
             "--time_scale", "0.2", "--size_scale", "0.00001",
             "--runs", "500", "--warmup", "1", "--no_topology",
             "--base_path", str(REPO)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    # ~38 ms/iteration x 500 runs ≈ 19 s of measured runs: the kill at
    # t=2 s lands deep inside them, far from startup and teardown
    survivor, victim = spawn(0), spawn(1)
    try:
        time.sleep(2.0)
        victim.kill()
        victim.communicate()
        out = survivor.communicate(timeout=60)[0]
    finally:
        survivor.kill()
    assert survivor.returncode != 0, \
        f"survivor exited 0 after peer death:\n{out}"
    # either detection path is fine: the reader thread failing blocked
    # collectives ("disconnected mid-run") or a send hitting the dead
    # peer's closed socket first ("peer gone")
    assert "disconnected mid-run" in out or "peer gone" in out, out


@pytest.mark.slow
def test_congestion_study_end_to_end(native_bin, tmp_path):
    """examples/congestion_study.py (the `_loop` congestors' purpose,
    SURVEY.md §5.3) must run the solo + under-load measurement pair and
    write a finite report.  No inflation threshold is asserted — the
    contention magnitude is host-dependent; the study's job is to
    measure it, the test's job is that the machinery works."""
    import sys
    proc = subprocess.run(
        [sys.executable, "examples/congestion_study.py",
         "--out_dir", str(tmp_path), "--runs", "3"],
        capture_output=True, text=True, timeout=300, cwd=str(REPO))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads((tmp_path / "report.json").read_text())
    for key in ("solo", "congested"):
        assert report[key]["runtime_us"] > 0
        assert report[key]["barrier_us"] > 0
    assert report["runtime_inflation"] > 0
    assert "inflation" in proc.stdout


def test_native_dp_over_tcp_and_merge(native_bin, tmp_path):
    """dp across 2 processes: each emits its own record (own timers,
    process identity), metrics.merge reassembles the full rank set."""
    from dlnetbench_tpu.metrics.merge import merge_files
    from dlnetbench_tpu.metrics.parser import records_to_dataframe, \
        validate_record

    port = _free_port()
    outs = [tmp_path / f"p{r}.jsonl" for r in range(2)]
    procs = [subprocess.Popen(
        [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
         "--world", "2", "--backend", "tcp", "--rank", str(r),
         "--coordinator", f"127.0.0.1:{port}", "--num_buckets", "2",
         "--time_scale", "0.0001", "--size_scale", "0.00001",
         "--runs", "2", "--warmup", "1", "--no_topology",
         "--base_path", str(REPO), "--out", str(outs[r])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(2)]
    texts = [p.communicate(timeout=120)[0] for p in procs]
    for r, (p, txt) in enumerate(zip(procs, texts)):
        assert p.returncode == 0, f"rank {r} failed:\n{txt}"

    for r, path in enumerate(outs):
        rec = json.loads(path.read_text().strip())
        assert rec["process"] == r
        assert rec["global"]["backend"] == "tcp"
        assert rec["global"]["num_processes"] == 2
        # 127.0.0.1 coordinator: the record says its sockets are
        # loopback, so the bandwidth table labels these rows' transport
        assert rec["global"]["transport"] == "tcp:loopback"
        assert [row["rank"] for row in rec["ranks"]] == [r]

    merged = merge_files(tmp_path / "merged.jsonl", outs)
    validate_record(merged)
    assert [row["rank"] for row in merged["ranks"]] == [0, 1]
    df = records_to_dataframe([merged])
    assert len(df) == 2 * merged["num_runs"]
    assert (df["runtime"] > 0).all()


# ---------------------------------------------------------------------
# --backend pjrt --procs N: the hierarchical ICI×DCN fabric (VERDICT r2
# #1) — each OS process drives its own CollectiveExecutor over its local
# "devices" (HostExecutor in CI, libtpu on a TPU host), the processes
# compose over the TCP mesh, and the per-process records merge into one
# run.  The reference's multi-node NCCL operating mode (dp.cpp:166-189).

_HOST_EXEC = {"DLNB_PJRT_EXECUTOR": "host"}


def _spawn_hier(native_bin, name, port, rank, *extra, world=4, procs=2,
                out=None, model="gpt2_l_16_bfloat16", env=None):
    import os
    cmd = [str(native_bin / name), "--model", model,
           "--world", str(world), "--backend", "pjrt",
           "--procs", str(procs), "--rank", str(rank),
           "--coordinator", f"127.0.0.1:{port}",
           "--time_scale", "0.0001", "--size_scale", "0.00001",
           "--runs", "2", "--warmup", "1", "--no_topology",
           "--base_path", str(REPO), *map(str, extra)]
    if out is not None:
        cmd += ["--out", str(out)]
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            env={**os.environ, **_HOST_EXEC,
                                 **(env or {})})


@pytest.mark.parametrize("world,nprocs", [
    (4, 2),   # default-lane representative; wider configs are opt-in
    # 3 processes, world 12: the uneven split in hier_selftest spans
    # strict subsets of the processes ({0,1}, the NON-adjacent {0,2})
    # with uneven per-process membership — this repo's own bug history
    # says fabric bugs hide just past the smallest config (VERDICT r3
    # weak #3)
    pytest.param(12, 3, marks=[pytest.mark.slow, pytest.mark.native_slow]),
    # UNEVEN LOCALS (VERDICT r4 #5): world does not divide procs — the
    # balanced layout gives locals 3,2 and 3,3,3,3,2,2 — so spanning
    # splits by local index produce groups missing members on the
    # smaller processes, and every collective's DCN routing must handle
    # the ragged layout.  The 6-process case is also the deepest DCN
    # mesh the suite runs.
    pytest.param(5, 2, marks=[pytest.mark.slow, pytest.mark.native_slow]),
    pytest.param(16, 6, marks=[pytest.mark.slow, pytest.mark.native_slow]),
    # VERDICT r5 item #5: 8 processes / world >= 24 with a RAGGED layout
    # (26 = 8*3+2 -> balanced locals 4,4,3,3,3,3,3,3) — the widest DCN
    # mesh the suite runs, with uneven per-process membership on every
    # subset-spanning split
    pytest.param(26, 8, marks=[pytest.mark.slow, pytest.mark.native_slow]),
])
def test_native_hier_selftest(native_bin, world, nprocs):
    """Every collective, all split orientations (groups inside one
    process, spanning all processes, and uneven groups spanning process
    subsets), and cross-process p2p verified by all global ranks
    ('correct sums' done-criterion for the multi-host device path)."""
    import os
    procs, outs = _spawn_ranks_with_port_retry(
        lambda r, port: ([str(native_bin / "hier_selftest"),
                          "--world", str(world), "--procs", str(nprocs),
                          "--rank", str(r),
                          "--coordinator", f"127.0.0.1:{port}"],
                         {**os.environ, **_HOST_EXEC}),
        nprocs)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {r} failed:\n{out}"
        assert f"hier_selftest process {r} OK" in out


def test_native_hier_dcn_wire_bytes(native_bin):
    """Bandwidth-trueness of every block-routed DCN algorithm, pinned to
    the EXACT byte count (no timing): hier_wire_probe runs a known
    collective sequence at world 8 over 4 processes and reports the
    socket bytes TcpFabric counted.  The expectation is the canonical
    direct algorithm's wire cost (hier_fabric.hpp header); the legacy
    gather-based alltoall leg alone would have moved 4x more
    ((P-1)*m*G*C vs m*(G-m)*C).  This is what makes busbw over hier
    records admissible (VERDICT r3 #2)."""
    import os
    world, nprocs, count, iters = 8, 4, 1024, 3
    m, esz, hdr = world // nprocs, 4, 40  # f32; sizeof(FrameHeader)
    G, P = world, nprocs
    per_iter = (
        # alltoall: blocks destined to each peer's members only
        (P - 1) * hdr + m * (G - m) * count * esz
        # reduce-scatter: each peer gets its members' partial blocks
        + (P - 1) * hdr + (G - m) * count * esz
        # allgather: packed local blocks to every peer, no padding
        + (P - 1) * hdr + (P - 1) * m * count * esz
        # ring shift: ONE boundary block crosses per process
        + (P - 1) * hdr + 1 * count * esz
        # allreduce DCN leg: count elems over the P-process TCP mesh
        # (below the ring threshold -> pairwise full mesh of P)
        + (P - 1) * (hdr + count * esz))
    expected = 2 * (P - 1) * hdr + iters * per_iter  # + 2 barriers

    procs, outs = _spawn_ranks_with_port_retry(
        lambda r, port: ([str(native_bin / "hier_wire_probe"),
                          "--world", str(world), "--procs", str(nprocs),
                          "--rank", str(r),
                          "--coordinator", f"127.0.0.1:{port}",
                          "--count", str(count), "--iters", str(iters)],
                         {**os.environ, **_HOST_EXEC}),
        nprocs)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {r} failed:\n{out}"
        rec = json.loads(out.strip().splitlines()[-1])
        assert rec["dcn_algo"] == "blocked"
        assert rec["tcp_bytes_sent"] == expected, \
            (r, rec["tcp_bytes_sent"], expected)


@pytest.mark.parametrize("name,extra,world,model,nprocs", [
    # default-lane representative: dp over the smallest hier config
    # (cross-process DCN combine + merge); the rest of the matrix —
    # wider meshes, pipelines, MoE ZB — is the opt-in heavy lane
    ("dp", ("--num_buckets", 2), 4, "gpt2_l_16_bfloat16", 2),
    # 4 OS processes x 2 local ranks: the DCN mesh at its widest test
    # configuration.  The test env forces the ring threshold to 1 byte
    # (scaled test buckets are ~4 KB, far under the 64 KiB default), so
    # the DCN allreduce leg genuinely rides ring_allreduce at P=4
    pytest.param("dp", ("--num_buckets", 4), 8, "gpt2_l_16_bfloat16", 4,
                 marks=[pytest.mark.slow, pytest.mark.native_slow]),
    pytest.param("fsdp", ("--num_units", 3, "--sharding_factor", 2), 4,
                 "gpt2_l_16_bfloat16", 2,
                 marks=[pytest.mark.slow, pytest.mark.native_slow]),
    # pipeline: the stage-1 -> stage-2 hop crosses the process boundary,
    # exercising Hier's cross-process p2p (TCP frames with encoded
    # endpoint tags)
    pytest.param("hybrid_2d", ("--num_stages", 4, "--num_microbatches", 4),
                 4, "gpt2_l_16_bfloat16", 2,
                 marks=[pytest.mark.slow, pytest.mark.native_slow]),
    # MoE ZB: spanning splits + Alltoall's block-routed DCN leg + the
    # zero-bubble schedule's p2p pattern, 2 procs x 4 local ranks
    pytest.param("hybrid_3d_moe",
                 ("--num_stages", 2, "--num_microbatches", 2,
                  "--num_expert_shards", 2, "--schedule", "zb"), 8,
                 "mixtral_8x7b_16_bfloat16", 2,
                 marks=[pytest.mark.slow, pytest.mark.native_slow]),
    # ring attention: RingShift's KV rotation crosses the process
    # boundary via the boundary-block-routed DCN leg
    pytest.param("ring_attention", ("--sp", 4, "--max_layers", 2), 4,
                 "llama3_8b_16_bfloat16", 2,
                 marks=[pytest.mark.slow, pytest.mark.native_slow]),
])
def test_native_proxy_over_hier_and_merge(native_bin, tmp_path, name, extra,
                                          world, model, nprocs):
    """Proxies across OS processes on the hier fabric: local
    collectives on each process's executor, DCN combine over TCP,
    records merged by metrics.merge with the hierarchy described.
    fsdp's allreduce_comm groups stride the process boundary, so the
    spanning-split slotted path is exercised too."""
    from dlnetbench_tpu.metrics.merge import merge_files
    from dlnetbench_tpu.metrics.parser import records_to_dataframe, \
        validate_record

    port = _free_port()
    local = world // nprocs
    outs = [tmp_path / f"p{r}.jsonl" for r in range(nprocs)]
    # the threshold must be IDENTICAL on every process (it is part of
    # the collective's wire protocol); 1 byte forces the ring at the
    # suite's tiny scaled buckets for the wide-mesh case
    env = ({"DLNB_TCP_RING_THRESHOLD": "1"} if nprocs > 2 else None)
    procs = [_spawn_hier(native_bin, name, port, r, *extra, world=world,
                         procs=nprocs, out=outs[r], model=model, env=env)
             for r in range(nprocs)]
    texts = [p.communicate(timeout=180)[0] for p in procs]
    for r, (p, txt) in enumerate(zip(procs, texts)):
        assert p.returncode == 0, f"process {r} failed:\n{txt}"

    for r, path in enumerate(outs):
        rec = json.loads(path.read_text().strip())
        assert rec["process"] == r
        g = rec["global"]
        assert g["backend"] == "pjrt"
        assert g["num_processes"] == nprocs
        assert g["local_world"] == local
        assert g["dcn_transport"] == "tcp"
        assert g["p2p_transport"] == "host+tcp"
        assert g["pjrt_executor"] == "host"
        # composed provenance: host-executor local leg + loopback DCN
        assert g["transport"] == "host+tcp:loopback"
        assert g["executor"] == "HostExecutor"
        # each process emits only its own local ranks
        assert [row["rank"] for row in rec["ranks"]] == \
            list(range(r * local, (r + 1) * local))
        # allreduce components carry their split's real spanning
        # process count (advisor r4): moe at world 8 / 2 procs has a
        # dp split {r, r+4} crossing the process boundary (span 2)
        # while the contiguous ep pairs stay inside one process
        # (span 1) — bandwidth.py keys the full-mesh refusal on these
        if name == "hybrid_3d_moe":
            cm = g["comm_model"]
            assert cm["dp_comm"][0]["span"] == 2
            assert cm["dp_ep_comm"][0]["span"] == 1

    merged = merge_files(tmp_path / "merged.jsonl", outs)
    validate_record(merged)
    assert [row["rank"] for row in merged["ranks"]] == list(range(world))
    assert [row["process_index"] for row in merged["ranks"]] == \
        [r // local for r in range(world)]
    df = records_to_dataframe([merged])
    assert len(df) == world * merged["num_runs"]
    assert (df["runtime"] > 0).all()


# ---------------------------------------------------------------------
# Native energy channel (VERDICT r2 #2): the C++ RAPL/hwmon chain
# (energy.hpp, the reference's -lpower_profiler role,
# Makefile.flags.mk:119-124) brackets each measured run and emits
# per-run energy_consumed on the process's first rank.  Tested against a
# fake sysfs tree (DLNB_RAPL_ROOT/DLNB_HWMON_ROOT), like the Python
# tier's tests — this rig has no real counters.

def test_native_energy_channel_and_pareto(native_bin, tmp_path):
    import os
    hw = tmp_path / "hwmon" / "hwmon0"
    hw.mkdir(parents=True)
    (hw / "power1_input").write_text("10000000\n")   # 10 W in uW
    (hw / "name").write_text("cpu_fake\n")
    cmd = [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
           "--world", "2", "--num_buckets", "2",
           "--time_scale", "0.1", "--size_scale", "0.00001",
           "--runs", "3", "--warmup", "1", "--no_topology",
           "--base_path", str(REPO)]
    env = {**os.environ, "DLNB_RAPL_ROOT": str(tmp_path / "absent"),
           "DLNB_HWMON_ROOT": str(tmp_path / "hwmon")}
    # an ambient device selector would disable the fake sensor
    env.pop("DLNB_HWMON_DEVICE", None)
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=180,
                         env=env)
    assert out.returncode == 0, out.stderr
    rec = json.loads(out.stdout)

    assert rec["global"]["energy_source"] == "hwmon:cpu_fake"
    assert rec["global"]["energy_scope"] == "process"
    rows = {row["rank"]: row for row in rec["ranks"]}
    # host counter: exactly the process's first rank carries the channel
    ej = rows[0]["energy_consumed"]
    assert len(ej) == rec["num_runs"]
    assert all(j >= 0 for j in ej)
    # 10 W for ~3 x tens-of-ms runs must integrate to something positive
    assert sum(ej) > 0, ej
    assert "energy_consumed" not in rows[1]

    # the Pareto analysis must accept native records and auto-pick the
    # energy axis (reference plots_pareto_energy role)
    import matplotlib
    matplotlib.use("Agg")
    from dlnetbench_tpu.metrics.parser import records_to_dataframe
    from dlnetbench_tpu.analysis.plots import plot_pareto
    df = records_to_dataframe([rec])
    assert "energy_consumed" in df.columns
    ax = plot_pareto(df.dropna(subset=["energy_consumed"]))
    assert ax.get_ylabel().startswith("energy_consumed")


def test_native_energy_absent_without_counters(native_bin, tmp_path):
    """No counter -> no channel, like the reference built without the
    profiler: records stay clean of zero-filled energy arrays."""
    import os
    rec_env = {**os.environ, "DLNB_RAPL_ROOT": str(tmp_path / "no_rapl"),
               "DLNB_HWMON_ROOT": str(tmp_path / "no_hwmon")}
    out = subprocess.run(
        [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
         "--world", "2", "--num_buckets", "2", "--time_scale", "0.0001",
         "--size_scale", "0.00001", "--runs", "2", "--warmup", "1",
         "--no_topology", "--base_path", str(REPO)],
        capture_output=True, text=True, timeout=180, env=rec_env)
    assert out.returncode == 0, out.stderr
    rec = json.loads(out.stdout)
    assert "energy_source" not in rec["global"]
    assert all("energy_consumed" not in row for row in rec["ranks"])


# ---------------------------------------------------------------------
# TCP ring allreduce (VERDICT r2 #6): large allreduces ride a
# bandwidth-optimal ring instead of the O(n^2) contribution mesh.

def test_native_tcp_ring_correct_sums(native_bin):
    """tcp_selftest at world=4 crosses the 64 KiB ring threshold with an
    odd count (tail block shorter), so the rotation math is verified by
    every rank across 4 real OS processes."""
    port = _free_port()
    procs = [subprocess.Popen(
        [str(native_bin / "tcp_selftest"), "--world", "4",
         "--rank", str(r), "--coordinator", f"127.0.0.1:{port}"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(4)]
    outs = [p.communicate(timeout=120)[0] for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"rank {r} OK" in out


def test_native_tcp_ring_wire_bytes_scale(native_bin, tmp_path):
    """The deterministic busbw-flatness proof: each record reports the
    process's actual socket bytes (tcp_bytes_sent).  With ring engaged,
    an allreduce moves ~2(n-1)/n x count per rank — far under the full
    mesh's (n-1) x count — so the world-4 dp run must sit near the ring
    estimate and well under the mesh estimate (no timing involved)."""
    port = _free_port()
    world, runs, warmup = 4, 2, 1
    outs = [tmp_path / f"p{r}.jsonl" for r in range(world)]
    procs = [subprocess.Popen(
        [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
         "--world", str(world), "--backend", "tcp", "--rank", str(r),
         "--coordinator", f"127.0.0.1:{port}", "--num_buckets", "2",
         "--time_scale", "0.0001", "--size_scale", "0.0002",
         "--runs", str(runs), "--warmup", str(warmup), "--no_topology",
         "--base_path", str(REPO), "--out", str(outs[r])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(world)]
    texts = [p.communicate(timeout=180)[0] for p in procs]
    for r, (p, txt) in enumerate(zip(procs, texts)):
        assert p.returncode == 0, f"rank {r} failed:\n{txt}"

    rec = json.loads(outs[0].read_text().strip())
    g = rec["global"]
    bucket_bytes = g["bucket_bytes"]
    assert all(b >= g["tcp_ring_threshold_bytes"] for b in bucket_bytes), \
        "test premise broken: buckets must engage the ring"
    iters = runs + warmup
    ring_est = iters * sum(2 * (world - 1) / world * b
                           for b in bucket_bytes)
    mesh_est = iters * sum((world - 1) * b for b in bucket_bytes)
    sent = g["tcp_bytes_sent"]
    # ring plus bootstrap/barrier/estimate overhead, but nowhere near
    # the full mesh (at world=4 the mesh moves 2x the ring's bytes)
    assert sent < 0.75 * mesh_est, (sent, ring_est, mesh_est)
    assert sent > 0.9 * ring_est, (sent, ring_est, mesh_est)


@pytest.mark.slow
@pytest.mark.native_slow
def test_native_tcp_ring_peer_death_detected(native_bin, tmp_path):
    """A mid-ring death must fail ALL survivors promptly — including
    non-neighbors, whose next awaited block transitively depends on the
    dead rank — not just the dead rank's successor."""
    import time

    port = _free_port()
    world = 3

    def spawn(r):
        return subprocess.Popen(
            [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
             "--world", str(world), "--backend", "tcp", "--rank", str(r),
             "--coordinator", f"127.0.0.1:{port}", "--num_buckets", "2",
             "--time_scale", "0.2", "--size_scale", "0.0002",
             "--runs", "500", "--warmup", "1", "--no_topology",
             "--base_path", str(REPO)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)

    procs = [spawn(r) for r in range(world)]
    try:
        time.sleep(2.0)
        procs[1].kill()
        procs[1].communicate()
        outs = []
        for r in (0, 2):
            outs.append(procs[r].communicate(timeout=60)[0])
    finally:
        for p in procs:
            p.kill()
    for r, out in zip((0, 2), outs):
        assert procs[r].returncode != 0, \
            f"rank {r} exited 0 after mid-ring peer death:\n{out}"
        assert "disconnected mid-run" in out or "peer gone" in out, out


def test_native_scheduler_variables_in_record(native_bin):
    """The native tier stamps the same launcher variables as the Python
    tier (metrics.emit.scheduler_variables parity)."""
    rec = run_proxy(native_bin, "dp", "--num_buckets", 2, world=2,
                    env={"DLNB_TAG_protocol": "ring",
                         "SLURM_JOB_ID": "1234"})
    v = rec["global"]["variables"]
    assert v["protocol"] == "ring"
    assert v["slurm_job_id"] == "1234"
    # parser hoists them to DataFrame columns
    from dlnetbench_tpu.metrics.parser import records_to_dataframe
    df = records_to_dataframe([rec])
    assert (df["protocol"] == "ring").all()


@pytest.mark.slow
@pytest.mark.native_slow
def test_native_hier_peer_death_detected(native_bin):
    """Failure detection on the hierarchical fabric: when one OS process
    of a --procs run dies mid-run, the survivor must fail fast with a
    diagnostic (the TCP layer's per-peer death tracking propagating
    through the DCN combine), not hang."""
    import os
    import time

    port = _free_port()

    def spawn(r):
        return subprocess.Popen(
            [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
             "--world", "4", "--backend", "pjrt", "--procs", "2",
             "--rank", str(r), "--coordinator", f"127.0.0.1:{port}",
             "--num_buckets", "2", "--time_scale", "0.2",
             "--size_scale", "0.0001", "--runs", "500", "--warmup", "1",
             "--no_topology", "--base_path", str(REPO)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, **_HOST_EXEC})

    survivor, victim = spawn(0), spawn(1)
    try:
        time.sleep(3.0)
        victim.kill()
        victim.communicate()
        out = survivor.communicate(timeout=60)[0]
    finally:
        survivor.kill()
    assert survivor.returncode != 0, \
        f"survivor exited 0 after peer death:\n{out}"
    assert "disconnected mid-run" in out or "peer gone" in out, out


@pytest.mark.slow
@pytest.mark.native_slow
def test_native_hier_noncoordinator_death_at_three_procs(native_bin):
    """At procs=3, killing a NON-coordinator process (rank 1) mid-run
    must fail BOTH survivors fast — including rank 2, whose death signal
    arrives only via the TCP mesh, not the bootstrap socket (VERDICT r3
    weak #3: mid-run death beyond the 2-process config)."""
    import os
    import time

    port = _free_port()

    def spawn(r):
        return subprocess.Popen(
            [str(native_bin / "dp"), "--model", "gpt2_l_16_bfloat16",
             "--world", "6", "--backend", "pjrt", "--procs", "3",
             "--rank", str(r), "--coordinator", f"127.0.0.1:{port}",
             "--num_buckets", "2", "--time_scale", "0.2",
             "--size_scale", "0.0001", "--runs", "500", "--warmup", "1",
             "--no_topology", "--base_path", str(REPO)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env={**os.environ, **_HOST_EXEC})

    procs = [spawn(r) for r in range(3)]
    victim = procs[1]
    survivors = [procs[0], procs[2]]
    outs = []
    try:
        time.sleep(3.0)
        victim.kill()
        victim.communicate()
        for s in survivors:
            outs.append(s.communicate(timeout=60)[0])
    finally:
        for s in survivors:
            s.kill()
    for i, (s, out) in enumerate(zip(survivors, outs)):
        assert s.returncode != 0, \
            f"survivor {i} exited 0 after peer death:\n{out}"
        assert "disconnected mid-run" in out or "peer gone" in out, out


# ---------------------------------------------------------------------
# Race detection (SURVEY.md §5.2: the reference ships no sanitizer
# configs at all).  The rank fabrics are thread-heavy — slot workers,
# reader threads, rendezvous — so the repo carries a dedicated TSan
# preset alongside the ASan/UBSan debug preset, and this (slow) test
# builds it and runs the unit suites plus the cross-process selftest
# under it.

def test_build_dir_claim_permission_discipline(tmp_path):
    """_claim (advisor r4): a pre-existing same-uid build dir with
    group/world WRITE bits may already contain planted build.ninja —
    must be wiped, not merely chmodded; read-only-permissive dirs are
    tightened in place; a foreign-uid dir is rejected (not testable
    unprivileged)."""
    from dlnetbench_tpu.utils.native_build import _claim

    d = tmp_path / "bld"
    d.mkdir(mode=0o755)  # world-readable, NOT writable
    (d / "build.ninja").write_text("ok")
    _claim(d)
    assert (d.stat().st_mode & 0o777) == 0o700
    assert (d / "build.ninja").exists()  # tightened in place, kept

    d.chmod(0o775)  # group-WRITABLE: contents are untrusted
    (d / "build.ninja").write_text("planted")
    _claim(d)
    assert (d.stat().st_mode & 0o777) == 0o700
    assert not (d / "build.ninja").exists()  # wiped and recreated


@pytest.mark.slow
@pytest.mark.native_slow
def test_native_tsan_fabrics(tmp_path):
    from dlnetbench_tpu.utils.native_build import build_root
    build = build_root(REPO, "tsan")
    # --preset keeps the committed TSan flags authoritative; -B only
    # relocates the tree out of the repo (CMake: CLI overrides preset).
    subprocess.run(["cmake", "--preset", "tsan", "-S", str(NATIVE),
                    "-B", str(build)],
                   check=True, capture_output=True)
    subprocess.run(["ninja", "-C", str(build), "test_comm", "test_pjrt",
                    "tcp_selftest", "hier_selftest", "fault_selftest"],
                   check=True, capture_output=True)
    for t in ("test_comm", "test_pjrt"):
        out = subprocess.run([str(build / t)], capture_output=True,
                             text=True, timeout=600)
        assert out.returncode == 0, f"{t} under tsan:\n{out.stdout[-2000:]}"
        assert "ThreadSanitizer" not in out.stdout + out.stderr
    procs, outs = _spawn_ranks_with_port_retry(
        lambda r, port: ([str(build / "bin" / "tcp_selftest"),
                          "--world", "4", "--rank", str(r),
                          "--coordinator", f"127.0.0.1:{port}"], None),
        4, timeout=300)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} under tsan:\n{out}"
        assert "ThreadSanitizer" not in out, out

    # the r4 hier additions are the thread-heaviest new code (per-slot
    # DCN exchanges from concurrent rendezvous execs, Bye-frame
    # teardown, concurrent quiesce): run the full hier selftest —
    # including the uneven subset-spanning splits — under TSan at
    # procs 3 x 4 local ranks
    import os
    # (12, 3): the r4 subset-spanning config; (16, 6): the r5
    # uneven-locals config (balanced layout 3,3,3,3,2,2); (26, 8): the
    # r7 scale-up (VERDICT r5 item #5) — 8 processes, world 26, ragged
    # locals 4,4,3,3,3,3,3,3, the widest DCN mesh in the suite — the
    # spanning-split rendezvous and block routing must stay race-free
    # on every ragged layout
    for world, nprocs in ((12, 3), (16, 6), (26, 8)):
        procs, outs = _spawn_ranks_with_port_retry(
            lambda r, port: ([str(build / "bin" / "hier_selftest"),
                              "--world", str(world),
                              "--procs", str(nprocs),
                              "--rank", str(r),
                              "--coordinator", f"127.0.0.1:{port}"],
                             {**os.environ, **_HOST_EXEC}),
            nprocs, timeout=300)
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, \
                f"hier proc {r}/{nprocs} w={world} under tsan:\n{out}"
            assert "ThreadSanitizer" not in out, out

    # fault-injection crash paths (ISSUE 5 satellite: injected delays
    # and scripted deaths are exactly where data races hide).  shm
    # crash + shrink: the group-abort poisoning races rank threads
    # blocked in rendezvous/mailboxes against the dying thread's
    # mark_rank_dead; the survivor regroup then reuses slot workers.
    crash = '{"events":[{"kind":"crash","ranks":[2],"iteration":3}]}'
    out = subprocess.run(
        [str(build / "bin" / "fault_selftest"), "--world", "4",
         "--iters", "6", "--fault", crash, "--fault_policy", "shrink"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, f"shm shrink under tsan:\n{out.stdout}"
    assert "ThreadSanitizer" not in out.stdout + out.stderr, out.stdout
    # shm crash fail-fast: every survivor thread must abort cleanly
    # (nonzero exit, no race) while the dying thread unwinds
    out = subprocess.run(
        [str(build / "bin" / "fault_selftest"), "--world", "4",
         "--iters", "6", "--fault", crash],
        capture_output=True, text=True, timeout=300)
    assert out.returncode != 0
    assert "ThreadSanitizer" not in out.stdout + out.stderr, out.stdout
    # tcp crash + shrink: reader threads observe the victim's EOF while
    # rank threads are mid-collective; survivors switch comms live
    tcp_crash = '{"events":[{"kind":"crash","ranks":[1],"iteration":3}]}'
    procs, outs = _spawn_ranks_with_port_retry(
        lambda r, port: ([str(build / "bin" / "fault_selftest"),
                          "--backend", "tcp", "--world", "3",
                          "--rank", str(r),
                          "--coordinator", f"127.0.0.1:{port}",
                          "--iters", "6", "--fault", tcp_crash,
                          "--fault_policy", "shrink"], None),
        3, timeout=300)
    assert procs[1].returncode != 0  # the scripted victim
    for r in (0, 2):
        assert procs[r].returncode == 0, \
            f"tcp shrink survivor {r} under tsan:\n{outs[r]}"
    for out_text in outs:
        assert "ThreadSanitizer" not in out_text, out_text

    # preempt + rejoin (ISSUE 7 grow path): the evictee's drained
    # singleton replay runs CONCURRENTLY with the survivors' degraded
    # window, then everyone live-switches onto the pre-built full-world
    # comm at the rejoin trigger — the thread-heaviest elastic
    # transition (three communicators active across one run).  shm
    # races rank threads in one process; tcp adds reader threads and
    # the returning rank's cross-process rendezvous.
    rejoin = ('{"policy":"shrink","events":['
              '{"kind":"preempt","ranks":[1],"iteration":3,'
              '"magnitude_us":5000},'
              '{"kind":"rejoin","ranks":[1],"iteration":7}]}')
    out = subprocess.run(
        [str(build / "bin" / "fault_selftest"), "--world", "4",
         "--iters", "10", "--fault", rejoin, "--fault_policy", "shrink"],
        capture_output=True, text=True, timeout=300)
    assert out.returncode == 0, f"shm rejoin under tsan:\n{out.stdout}"
    assert "ThreadSanitizer" not in out.stdout + out.stderr, out.stdout
    procs, outs = _spawn_ranks_with_port_retry(
        lambda r, port: ([str(build / "bin" / "fault_selftest"),
                          "--backend", "tcp", "--world", "3",
                          "--rank", str(r),
                          "--coordinator", f"127.0.0.1:{port}",
                          "--iters", "10", "--fault", rejoin,
                          "--fault_policy", "shrink"], None),
        3, timeout=300)
    for r in range(3):  # nobody dies on the elastic arc
        assert procs[r].returncode == 0, \
            f"tcp rejoin rank {r} under tsan:\n{outs[r]}"
    for out_text in outs:
        assert "ThreadSanitizer" not in out_text, out_text
