"""Decomposed collective matmuls (ops/collective_matmul.py) vs the fused
references, forward and backward, on the virtual CPU mesh.

The references need no shard_map at all: with the layouts used here the
global semantics of gather-then-dot AND dot-then-psum_scatter are both
exactly ``jnp.dot(global_x, global_w)`` (the gather only reassembles the
global array; the scatter only distributes the full product), so every
comparison is against the plain dot — and each decomposed program
compiles ONCE via ``jax.vjp`` (fwd + bwd share the trace), keeping the
suite inside the tier-1 wall budget.

The all-gather-matmul forward is per-row identical math (exact); ring
reduce-scatter and the dw rings accumulate in ring order, so those carry
the documented f32 reduction tolerance."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from dlnetbench_tpu.ops import collective_matmul as CM
from dlnetbench_tpu.utils.jax_compat import shard_map

MB, S_LOC, D, K = 2, 4, 16, 12   # K even: exercises both ring directions


def _mesh(devs, n):
    return Mesh(np.array(devs[:n]).reshape(n), ("r",))


def _ref_value_and_grads(x, w):
    """Fused-path semantics of BOTH ops at these layouts: the plain dot."""
    def f(a, b):
        return jnp.dot(a, b)
    out, vjp = jax.vjp(f, x, w)
    return out, vjp(jnp.sin(out))


def _run_value_and_grads(fn, mesh, in_specs, out_specs, x, w):
    """One trace for forward + backward of a shard_map'd decomposed op."""
    sm = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=False)
    out, vjp = jax.vjp(sm, x, w)
    return out, vjp(jnp.sin(out))


@pytest.mark.parametrize("n,chunks", [(2, 1), (4, 2)])
def test_all_gather_matmul_matches_fused(eight_devices, n, chunks):
    mesh = _mesh(eight_devices, n)
    x = jax.random.normal(jax.random.key(0), (MB, n * S_LOC, D),
                          jnp.float32)
    w = jax.random.normal(jax.random.key(1), (D, K), jnp.float32) * 0.1

    o_ref, g_ref = _ref_value_and_grads(x, w)
    o_dec, g_dec = _run_value_and_grads(
        lambda a, b: CM.all_gather_matmul(a, b, "r", gather_axis=1,
                                          chunks=chunks),
        mesh, (P(None, "r", None), P()), P(), x, w)
    # forward: per-row identical math -> exact
    np.testing.assert_array_equal(np.asarray(o_ref), np.asarray(o_dec))
    # dx (decomposed reduce-scatter) and dw (ring accumulation): f32 tol
    for a, b in zip(g_ref, g_dec):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("n,chunks", [(2, 1), (4, 2)])
def test_matmul_reduce_scatter_matches_fused(eight_devices, n, chunks):
    mesh = _mesh(eight_devices, n)
    a = jax.random.normal(jax.random.key(2), (MB, n * S_LOC, D),
                          jnp.float32)
    w = jax.random.normal(jax.random.key(3), (D, K), jnp.float32) * 0.1

    o_ref, g_ref = _ref_value_and_grads(a, w)
    # row-parallel layout: contraction dim of a and rows of w sharded;
    # psum_scatter of the partial products == the full dot, distributed
    o_dec, g_dec = _run_value_and_grads(
        lambda x_, y_: CM.matmul_reduce_scatter(x_, y_, "r",
                                                scatter_axis=1,
                                                chunks=chunks),
        mesh, (P(None, None, "r"), P("r", None)), P(None, "r", None),
        a, w)
    np.testing.assert_allclose(np.asarray(o_ref), np.asarray(o_dec),
                               rtol=1e-5, atol=1e-5)
    for x_, y_ in zip(g_ref, g_dec):
        np.testing.assert_allclose(np.asarray(x_), np.asarray(y_),
                                   rtol=1e-5, atol=1e-5)


def test_odd_output_width_unidirectional_fallback(eight_devices):
    """K=1 cannot split across the bidirectional rings — the
    reduce-scatter must fall back to one ring, still correct."""
    mesh = _mesh(eight_devices, 4)
    a = jax.random.normal(jax.random.key(4), (MB, 4 * S_LOC, D),
                          jnp.float32)
    w = jax.random.normal(jax.random.key(5), (D, 1), jnp.float32)
    out = shard_map(
        lambda x_, y_: CM.matmul_reduce_scatter(x_, y_, "r",
                                                scatter_axis=1),
        mesh=mesh, in_specs=(P(None, None, "r"), P("r", None)),
        out_specs=P(None, "r", None), check_vma=False)(a, w)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(jnp.dot(a, w)),
                               rtol=1e-5, atol=1e-6)


def test_ab_legs_keep_shapes(eight_devices):
    """The A/B decomposition legs: fake_comm (compute leg — full FLOPs,
    permutes stripped) and fake_compute (comm leg — full wire schedule,
    matmuls stubbed) must both preserve the output contract."""
    mesh = _mesh(eight_devices, 4)
    x = jax.random.normal(jax.random.key(6), (MB, 4 * S_LOC, D),
                          jnp.float32)
    w = jax.random.normal(jax.random.key(7), (D, K), jnp.float32)
    for leg in ("fake_comm", "fake_compute"):
        out = shard_map(
            lambda a, b: CM.all_gather_matmul(a, b, "r", gather_axis=1,
                                              **{leg: True}),
            mesh=mesh, in_specs=(P(None, "r", None), P()),
            out_specs=P(), check_vma=False)(x, w)
        assert out.shape == (MB, 4 * S_LOC, K), leg
        assert np.all(np.isfinite(np.asarray(out))), leg
    # 1-rank axis degenerates to the plain dot exactly
    mesh1 = _mesh(eight_devices, 1)
    x1 = x[:, :S_LOC]
    o1 = shard_map(lambda a, b: CM.all_gather_matmul(a, b, "r"),
                   mesh=mesh1, in_specs=(P(), P()), out_specs=P(),
                   check_vma=False)(x1, w)
    np.testing.assert_array_equal(np.asarray(o1),
                                  np.asarray(jnp.dot(x1, w)))
