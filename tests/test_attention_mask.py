"""Mask-builder unit tests (ops/attention_mask.py, ISSUE 10): verdict
tables vs brute force, sparsity goldens, seeded segment-plan
determinism, ring-hop verdicts, and the record-globals round trip
(parser hoist + merge mismatch-refusal)."""
from __future__ import annotations

import numpy as np
import pytest

from dlnetbench_tpu.ops import attention_mask as am

pytestmark = pytest.mark.longcontext

SPECS = [
    am.MaskSpec(causal=True),
    am.MaskSpec(causal=True, window=24),
    am.MaskSpec(causal=True, seg_avg=20, seg_seed=3),
    am.MaskSpec(causal=False, seg_avg=16, seg_seed=1),
    am.MaskSpec(causal=True, window=16, seg_avg=24, seg_seed=7),
]


def _brute_verdicts(spec, s, bq, bk):
    d = am.dense_mask(spec, s)
    out = np.zeros((s // bq, s // bk), np.uint8)
    for i in range(s // bq):
        for j in range(s // bk):
            blk = d[i * bq:(i + 1) * bq, j * bk:(j + 1) * bk]
            out[i, j] = (am.FULL if blk.all()
                         else am.PARTIAL if blk.any() else am.SKIP)
    return out


@pytest.mark.parametrize("spec", SPECS)
@pytest.mark.parametrize("s,bq,bk", [(128, 16, 16), (128, 32, 16),
                                     (96, 16, 32)])
def test_verdicts_match_brute_force(spec, s, bq, bk):
    """The interval math (never an S x S materialization) must agree
    with the O(S^2) dense mask block by block — verdicts AND both
    visit-range tables (fwd/dq per-q-block, dkv per-kv-block)."""
    bm = am.block_mask(spec, s, bq, bk)
    want = _brute_verdicts(spec, s, bq, bk)
    assert (bm.verdicts() == want).all()
    for i in range(bm.nq):
        nz = np.nonzero(want[i] != am.SKIP)[0]
        assert bm.q_first_k[i] == nz.min() and bm.q_last_k[i] == nz.max()
    for j in range(bm.nk):
        nz = np.nonzero(want[:, j] != am.SKIP)[0]
        assert (bm.kv_first_q[j] == nz.min()
                and bm.kv_last_q[j] == nz.max())


@pytest.mark.parametrize("spec", SPECS)
def test_allowed_predicate_matches_dense(spec):
    """The traceable predicate (ring hops, serving prefill) is the same
    semantics as the dense builder."""
    import jax.numpy as jnp
    s = 96
    seg = (am.segment_ids(spec.seg_seed, spec.seg_avg, s)
           if spec.seg_avg else None)
    q = jnp.arange(s)
    got = np.asarray(am.allowed(spec, q[:, None], q[None, :],
                                seg_ids=seg))
    assert (got == am.dense_mask(spec, s)).all()


def test_sparsity_fraction_goldens():
    # causal S=64: 1 - (64*65/2) / 64^2
    assert am.sparsity_fraction(am.MaskSpec(causal=True), 64) \
        == pytest.approx(1 - (64 * 65 / 2) / 64 ** 2)
    # causal & window W=4, S=8: allowed per row = min(q+1, 4)
    allowed = sum(min(q + 1, 4) for q in range(8))
    assert am.sparsity_fraction(
        am.MaskSpec(causal=True, window=4), 8) \
        == pytest.approx(1 - allowed / 64)
    # windows tighter than causal are strictly sparser
    assert (am.sparsity_fraction(am.MaskSpec(causal=True, window=8), 64)
            > am.sparsity_fraction(am.MaskSpec(causal=True), 64))


def test_segment_plan_seeded_determinism():
    a = am.segment_ids(5, 16, 256)
    b = am.segment_ids(5, 16, 256)
    c = am.segment_ids(6, 16, 256)
    assert (a == b).all()
    assert not (a == c).all()
    # ids are monotone from 0, lengths within the drawn range
    assert a[0] == 0 and (np.diff(a) >= 0).all() and (np.diff(a) <= 1).all()
    lengths = np.diff(np.flatnonzero(np.diff(a)))  # interior doc lengths
    if lengths.size:
        assert lengths.min() >= max(1, 16 // 2)
        assert lengths.max() <= 16 + 16 // 2


def test_spec_validation_and_round_trip():
    with pytest.raises(ValueError):
        am.MaskSpec(causal=False, window=8)       # non-causal window
    with pytest.raises(ValueError):
        am.MaskSpec(causal=False)                 # trivial all-allowed
    with pytest.raises(ValueError):
        am.MaskSpec(causal=True, window=-1)
    spec = am.MaskSpec(causal=True, window=128, seg_avg=64, seg_seed=9)
    assert am.MaskSpec.from_dict(spec.to_dict()) == spec
    assert spec.label() == "causal&window(128)&seg(avg=64,seed=9)"
    assert am.MaskSpec(causal=True).is_plain_causal
    assert not spec.is_plain_causal
    with pytest.raises(ValueError):
        am.block_mask(spec, 100, 16, 16)          # blocks don't divide


def test_block_stats_account_for_all_blocks():
    bm = am.block_mask(am.MaskSpec(causal=True, window=16), 128, 16, 16)
    st = bm.stats()
    assert (st["blocks_skipped"] + st["blocks_full"]
            + st["blocks_partial"] == st["blocks_total"] == 64)
    assert 0 < st["block_skip_fraction"] < 1
    assert st["sparsity_fraction"] == pytest.approx(
        am.sparsity_fraction(bm.spec, 128), abs=1e-6)


@pytest.mark.parametrize("spec", [None] + SPECS)
def test_ring_hop_work_matches_dense_tiles(spec):
    s, n = 128, 8
    work = am.ring_hop_work(spec, s, n)
    dspec = spec if spec is not None else am.MaskSpec(causal=True)
    d = am.dense_mask(dspec, s)
    sl = s // n
    for me in range(n):
        for src in range(n):
            assert work[me, src] == d[me * sl:(me + 1) * sl,
                                      src * sl:(src + 1) * sl].any()
    frac = am.ring_skipped_hop_fraction(spec, s, n)
    assert frac == pytest.approx(1 - work.mean())
    if spec is None:
        # plain causal: the strictly-future half of the hop grid
        assert frac == pytest.approx((n * (n - 1) / 2) / n ** 2)


def test_long_context_block_coverage_64k_128k():
    """The mask layer itself is O(S + blocks) host work — the 64k/128k
    plans the bench shapes use must build instantly and account for
    every block (ISSUE 10 satellite's coverage check at scale)."""
    for s in (64 * 1024, 128 * 1024):
        spec = am.MaskSpec(causal=True, window=s // 16)
        bm = am.block_mask(spec, s, 2048, 2048)
        st = bm.stats()
        assert st["blocks_total"] == (s // 2048) ** 2
        assert st["block_skip_fraction"] > 0.8   # the window is narrow
        bm_c = am.block_mask(am.MaskSpec(causal=True), s, 2048, 2048)
        assert bm_c.stats()["block_skip_fraction"] == pytest.approx(
            (s // 2048 - 1) / (2 * (s // 2048)), abs=1e-6)


def test_record_globals_round_trip_and_merge_refusal():
    """Mask spec + sparsity are COMPARABLE globals: the parser hoists
    them to columns, and records measured under different masks refuse
    to merge — a different mask IS a different run, exactly like
    mismatched fault or arrival plans."""
    import copy

    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import records_to_dataframe

    spec = am.MaskSpec(causal=True, window=32)
    g = am.record_globals(spec, 128, n_shards=4)
    assert g["attention_mask"] == "causal&window(32)"
    assert 0 < g["mask_sparsity"] < 1
    assert g["ring_skipped_hop_fraction"] > 0

    def rec(proc, globals_extra):
        return {"section": "spmd", "version": 2, "process": proc,
                "global": {"world_size": 2, "num_processes": 2,
                           **globals_extra},
                "mesh": {"platform": "cpu"}, "num_runs": 1,
                "warmup_times": [],
                "ranks": [{"rank": proc, "device_id": proc,
                           "process_index": proc, "hostname": f"h{proc}",
                           "runtimes": [1.0],
                           "summary": {"runtimes": {
                               "value": 1.0, "best": 1.0,
                               "band": [1.0, 1.0], "n": 1}}}]}

    r0, r1 = rec(0, g), rec(1, g)
    merged = merge_records([copy.deepcopy(r0), copy.deepcopy(r1)])
    assert merged["global"]["attention_mask"] == g["attention_mask"]
    df = records_to_dataframe([merged], validate=False)
    assert set(df["attention_mask"]) == {g["attention_mask"]}
    assert set(df["mask_sparsity"]) == {g["mask_sparsity"]}
    assert set(df["ring_skipped_hop_fraction"]) \
        == {g["ring_skipped_hop_fraction"]}

    # a different mask must refuse the merge, naming the key
    g2 = am.record_globals(am.MaskSpec(causal=True, window=64), 128,
                           n_shards=4)
    with pytest.raises(ValueError, match="attention_mask"):
        merge_records([rec(0, g), rec(1, g2)])
