"""Fused-quantization Pallas matmuls (ops/quantized_matmul.py, ISSUE 3
tentpole) — interpret-mode unit tests against the composed XLA
reference: int8 EXACT (shared scale definition + associative int32
accumulation), fp8 within e4m3 quantization tolerance, delayed-scaling
state threading, and the transformer config plumbing.

The on-chip paired A/B harness test at the bottom is ``tpu_only``:
collectable on the CPU mesh, skipped there (conftest), measured on the
real chip."""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from dlnetbench_tpu.ops import quantized_matmul as qmm
from dlnetbench_tpu.ops.fp8 import fp8_dot, swiglu_fp8_fused
from dlnetbench_tpu.ops.int8 import (
    int8_dot,
    swiglu_int8,
    swiglu_int8_fused,
    swiglu_int8_fused_delayed,
)

_F32 = jnp.float32


def _rand(key, shape, scale=1.0):
    return jax.random.normal(jax.random.key(key), shape,
                             jnp.bfloat16) * scale


# shapes that exercise multi-block grids in all three axes at the
# default block sizes AND odd small blocks via fit_block halving
_SHAPES = [(128, 256, 64), (48, 32, 40), (8, 16, 8)]


@pytest.mark.parametrize("t,k,n", _SHAPES)
def test_int8_fused_exact_vs_composed(t, k, n):
    """int8 fused must equal the composed XLA path EXACTLY: the scale
    formula is shared (quantized_matmul.scale_from_amax), int32
    accumulation is associative across the contraction tiling, and the
    f32 sa*sb epilogue is the same arithmetic."""
    x = _rand(0, (t, k))
    w = _rand(1, (k, n), 0.05)
    got = qmm.int8_dot_fused(x, w)
    want = int8_dot(x, w)
    assert got.dtype == want.dtype
    assert jnp.array_equal(got, want), "fused int8 != composed int8"


def test_int8_fused_exact_with_small_blocks():
    """Force a multi-block grid on every axis (block 32/64 over 128-256
    dims) so the k-loop accumulation and block epilogue are actually
    exercised, not degenerate single-block grids."""
    x = _rand(2, (128, 256))
    w = _rand(3, (256, 128), 0.05)
    sx = qmm.scale_from_amax(jnp.max(jnp.abs(x.astype(_F32))), "int8")
    wq, sw = qmm.quantize_tensor(w, "int8")
    got = qmm.fused_matmul(x, wq, sw, sx, fmt="int8",
                           block_m=32, block_n=64, block_k=64)
    want = int8_dot(x, w)
    assert jnp.array_equal(got, want)


def test_fp8_fused_close_to_composed():
    """fp8 accumulates in f32, so the tiled accumulation order differs
    from the composed single dot — equal within e4m3 quantization
    tolerance, and far tighter than the quantization error itself."""
    x = _rand(4, (128, 256))
    w = _rand(5, (256, 64), 0.05)
    got = qmm.fp8_dot_fused(x, w).astype(_F32)
    want = fp8_dot(x, w).astype(_F32)
    rel = jnp.linalg.norm(got - want) / jnp.maximum(
        jnp.linalg.norm(want), 1e-9)
    assert rel < 1e-2, f"fused fp8 vs composed relative error {rel}"
    # and both near the full-precision reference
    full = jnp.dot(x.astype(_F32), w.astype(_F32))
    rel_full = jnp.linalg.norm(got - full) / jnp.linalg.norm(full)
    assert rel_full < 0.05


def test_fused_dots_leading_batch_dims():
    x = _rand(6, (4, 8, 32))
    w = _rand(7, (32, 16), 0.1)
    assert qmm.int8_dot_fused(x, w).shape == (4, 8, 16)
    assert jnp.array_equal(qmm.int8_dot_fused(x, w), int8_dot(x, w))
    assert qmm.fp8_dot_fused(x, w).shape == (4, 8, 16)


def test_fused_dot_straight_through_grads_match_composed():
    x = _rand(8, (32, 16))
    w = _rand(9, (16, 24), 0.1)
    cot = _rand(10, (32, 24))

    def loss(fn):
        return lambda x, w: jnp.sum(fn(x, w).astype(_F32)
                                    * cot.astype(_F32))

    for fused, composed in ((qmm.int8_dot_fused, int8_dot),
                            (qmm.fp8_dot_fused, fp8_dot)):
        gf = jax.grad(loss(fused), argnums=(0, 1))(x, w)
        gc = jax.grad(loss(composed), argnums=(0, 1))(x, w)
        for a, b in zip(gf, gc):
            # both backwards are the identical master-dtype dots
            assert jnp.array_equal(a, b)


def test_delayed_dot_state_threading():
    """The delayed-scaling contract: (1) with amax_in = the TRUE amax,
    the result equals fresh scaling exactly (int8); (2) amax_out is
    the true amax of the CURRENT activation (the next step's state);
    (3) a stale, too-small amax saturates instead of overflowing; (4)
    the carried state gets a zero gradient."""
    x = _rand(11, (64, 32))
    w = _rand(12, (32, 48), 0.1)
    true_amax = jnp.max(jnp.abs(x.astype(_F32)))

    y, amax_out = qmm.int8_dot_fused_delayed(x, w, true_amax)
    assert jnp.array_equal(y, int8_dot(x, w))
    assert jnp.array_equal(amax_out, true_amax)

    y_stale, amax_out2 = qmm.int8_dot_fused_delayed(x, w, true_amax * 0.1)
    assert bool(jnp.all(jnp.isfinite(y_stale.astype(_F32))))
    # the emitted state is the fresh amax regardless of the stale scale
    assert jnp.array_equal(amax_out2, true_amax)

    def loss(x, w, amax):
        y, _ = qmm.int8_dot_fused_delayed(x, w, amax)
        return jnp.sum(y.astype(_F32))

    gx, gw, gamax = jax.grad(loss, argnums=(0, 1, 2))(x, w, true_amax)
    assert float(jnp.sum(jnp.abs(gamax))) == 0.0
    gx_ref, gw_ref = jax.grad(
        lambda x, w: jnp.sum(int8_dot(x, w).astype(_F32)),
        argnums=(0, 1))(x, w)
    assert jnp.array_equal(gx, gx_ref) and jnp.array_equal(gw, gw_ref)

    # fp8 delayed: same contract, quantization-tolerance equality
    yf, am = qmm.fp8_dot_fused_delayed(x, w, true_amax)
    assert jnp.array_equal(am, true_amax)
    ref = fp8_dot(x, w).astype(_F32)
    rel = (jnp.linalg.norm(yf.astype(_F32) - ref)
           / jnp.maximum(jnp.linalg.norm(ref), 1e-9))
    assert rel < 1e-2


def test_swiglu_fused_matches_composed():
    x = _rand(13, (48, 32))
    wg = _rand(14, (32, 40), 0.1)
    wu = _rand(15, (32, 40), 0.1)
    wd = _rand(16, (40, 32), 0.1)
    # int8: exact, forward and (shared master-dtype) backward
    assert jnp.array_equal(swiglu_int8_fused(x, wg, wu, wd),
                           swiglu_int8(x, wg, wu, wd))
    cot = _rand(17, (48, 32))

    def loss(fn):
        return lambda *a: jnp.sum(fn(*a).astype(_F32) * cot.astype(_F32))

    gf = jax.grad(loss(swiglu_int8_fused), argnums=(0, 1, 2, 3))(
        x, wg, wu, wd)
    gc = jax.grad(loss(swiglu_int8), argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    for a, b, name in zip(gf, gc, ("dx", "dwg", "dwu", "dwd")):
        assert jnp.array_equal(a, b), name
    # fp8: within quantization tolerance of the composed swiglu
    from dlnetbench_tpu.ops.fp8 import swiglu_fp8
    got = swiglu_fp8_fused(x, wg, wu, wd).astype(_F32)
    want = swiglu_fp8(x, wg, wu, wd).astype(_F32)
    rel = jnp.linalg.norm(got - want) / jnp.maximum(
        jnp.linalg.norm(want), 1e-9)
    assert rel < 2e-2


def test_swiglu_fused_residual_contract():
    """The fused-kernel swiglu keeps the r5 residual contract: exactly
    the two [T, F] pre-activations (g, u) cross the fwd/bwd boundary —
    ``h`` is recomputed, never saved (the no-remat OOM fix)."""
    x = _rand(18, (48, 32))
    wg = _rand(19, (32, 40), 0.1)
    wu = _rand(20, (32, 40), 0.1)
    wd = _rand(21, (40, 32), 0.1)
    for fn in (swiglu_int8_fused, swiglu_fp8_fused):
        _, vjp = jax.vjp(fn, x, wg, wu, wd)
        n_tf = sum(1 for l in jax.tree.leaves(vjp)
                   if getattr(l, "shape", None) == (48, 40))
        assert n_tf == 2, (fn.__name__, n_tf)


def test_swiglu_fused_delayed_state_and_grads():
    """Layer-level delayed scaling: with the TRUE amaxes as incoming
    state the output is exactly the fresh-scaling fused result, the
    emitted state is [amax_x, amax_h] of THIS step, and gradients match
    the master backward; the state slot gets zero gradient."""
    x = _rand(22, (48, 32))
    wg = _rand(23, (32, 40), 0.1)
    wu = _rand(24, (32, 40), 0.1)
    wd = _rand(25, (40, 32), 0.1)

    # true amaxes of this step's activations
    amax_x = jnp.max(jnp.abs(x.astype(_F32)))
    g = int8_dot(x, wg)
    u = int8_dot(x, wu)
    h = (jax.nn.silu(g.astype(_F32)) * u.astype(_F32)).astype(g.dtype)
    amax_h = jnp.max(jnp.abs(h.astype(_F32)))
    qs = jnp.stack([amax_x, amax_h])

    y, new_qs = swiglu_int8_fused_delayed(x, wg, wu, wd, qs)
    assert jnp.array_equal(y, swiglu_int8_fused(x, wg, wu, wd))
    assert jnp.allclose(new_qs, qs)

    cot = _rand(26, (48, 32))

    def loss_delayed(x, wg, wu, wd, qs):
        y, _ = swiglu_int8_fused_delayed(x, wg, wu, wd, qs)
        return jnp.sum(y.astype(_F32) * cot.astype(_F32))

    def loss_master(*a):
        return jnp.sum(swiglu_int8(*a).astype(_F32) * cot.astype(_F32))

    gd = jax.grad(loss_delayed, argnums=(0, 1, 2, 3, 4))(x, wg, wu, wd, qs)
    gm = jax.grad(loss_master, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    for a, b, name in zip(gd[:4], gm, ("dx", "dwg", "dwu", "dwd")):
        assert jnp.array_equal(a, b), name
    assert float(jnp.sum(jnp.abs(gd[4]))) == 0.0

    # a cold (ones) state still produces finite output and the emitted
    # state converges to the truth in one step — the warm-in contract
    y2, qs2 = swiglu_int8_fused_delayed(x, wg, wu, wd, jnp.ones(2, _F32))
    assert bool(jnp.all(jnp.isfinite(y2.astype(_F32))))
    assert jnp.array_equal(qs2[0], amax_x)


def test_quantize_tensor_shared_with_composed_paths():
    """ops/int8.py and ops/fp8.py _quantize must BE the shared
    definition — this is what makes the fused-vs-composed int8 A/B an
    apples-to-apples recipe comparison."""
    from dlnetbench_tpu.ops.fp8 import _quantize as qf
    from dlnetbench_tpu.ops.int8 import _quantize as qi
    x = _rand(27, (64, 32), 3.0)
    for fn, fmt in ((qi, "int8"), (qf, "float8")):
        xq, s = fn(x)
        xq2, s2 = qmm.quantize_tensor(x, fmt)
        assert jnp.array_equal(xq, xq2) and jnp.array_equal(s, s2)


def test_fused_matmul_validation():
    x = _rand(28, (16, 32))
    wq, sw = qmm.quantize_tensor(_rand(29, (32, 16)), "int8")
    with pytest.raises(ValueError, match="unknown quantization format"):
        qmm.fused_matmul(x, wq, sw, 1.0, fmt="int4")
    with pytest.raises(ValueError, match="contraction mismatch"):
        qmm.fused_matmul(_rand(30, (16, 8)), wq, sw, 1.0, fmt="int8")


_TINY = dict(vocab_size=128, embed_dim=32, num_heads=4, num_kv_heads=2,
             ff_dim=64, num_layers=2, seq_len=16, gated=True,
             max_positions=0)


def test_quantized_swiglu_dispatcher_guards_delayed_state():
    """The layers-level dispatcher must mirror the config validation
    for direct callers: handing delayed state to a composed-configured
    call is an error, not a silent reroute to the fused kernel."""
    from dlnetbench_tpu.models import layers as L
    x = _rand(40, (8, 16))
    w = _rand(41, (16, 24), 0.1)
    wd = _rand(42, (24, 16), 0.1)
    with pytest.raises(ValueError, match="requires quant_fusion='fused'"):
        L.quantized_swiglu(x, w, w, wd, mlp_dtype="int8",
                           quant_fusion="composed",
                           amax_state=jnp.ones(2, _F32))


def test_transformer_quant_config_validation():
    from dlnetbench_tpu.models import transformer as tfm
    with pytest.raises(ValueError, match="quant_fusion"):
        tfm.TransformerConfig(**_TINY, mlp_dtype="int8",
                              quant_fusion="pallas")
    with pytest.raises(ValueError, match="quant_scaling"):
        tfm.TransformerConfig(**_TINY, mlp_dtype="int8",
                              quant_fusion="fused", quant_scaling="stale")
    with pytest.raises(ValueError, match="nothing to quantize"):
        tfm.TransformerConfig(**_TINY, quant_fusion="fused")
    with pytest.raises(ValueError, match="requires quant_fusion='fused'"):
        tfm.TransformerConfig(**_TINY, mlp_dtype="int8",
                              quant_scaling="delayed")
    with pytest.raises(ValueError, match="master-dtype"):
        tfm.TransformerConfig(**_TINY, mlp_dtype="int8",
                              quant_fusion="fused",
                              int8_backward="switchback")
    # legal combos
    cfg = tfm.TransformerConfig(**_TINY, mlp_dtype="float8",
                                quant_fusion="fused",
                                quant_scaling="delayed")
    assert tfm.needs_qstate(cfg)
    with pytest.raises(ValueError, match="delayed"):
        tfm.init_qstate(tfm.TransformerConfig(**_TINY))


@pytest.mark.parametrize("mlp_dtype", ["int8", "float8"])
@pytest.mark.parametrize("scan_layers", [True, False])
def test_transformer_fused_delayed_trains(mlp_dtype, scan_layers):
    """The full vertical: delayed-scaling fused MLPs inside a train
    step, state threaded through both layer-stack codepaths (scan and
    unrolled), loss finite, grads flowing, state moving off init."""
    from dlnetbench_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(**_TINY, mlp_dtype=mlp_dtype,
                                quant_fusion="fused",
                                quant_scaling="delayed",
                                scan_layers=scan_layers)
    params = tfm.init_params(jax.random.key(0), cfg)
    qs = tfm.init_qstate(cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.seq_len + 1),
                                0, cfg.vocab_size)
    step = jax.jit(lambda p, t, q: jax.value_and_grad(
        tfm.loss_fn, has_aux=True)(p, t, cfg, q))
    (loss, new_qs), g = step(params, tokens, qs)
    assert jnp.isfinite(loss)
    assert new_qs.shape == (cfg.num_layers, 2)
    assert bool(jnp.any(new_qs != qs)), "delayed state never updated"
    gmax = jnp.max(jnp.abs(g["layers"]["w_gate"].astype(_F32)))
    assert gmax > 0
    # second step with the threaded state: still finite, state stable
    # (same batch -> same amaxes up to the one-step param update)
    (loss2, qs3), _ = step(jax.tree.map(
        lambda a, b: a - 1e-3 * b.astype(a.dtype), params, g),
        tokens, new_qs)
    assert jnp.isfinite(loss2)
    assert bool(jnp.all(jnp.isfinite(qs3)))


def test_transformer_fused_dynamic_matches_composed_int8():
    """quant_fusion is an IMPLEMENTATION switch, not a recipe switch:
    with fresh scaling the int8 fused step must produce bitwise the
    same loss as the composed step."""
    from dlnetbench_tpu.models import transformer as tfm
    cfg_f = tfm.TransformerConfig(**_TINY, mlp_dtype="int8",
                                  quant_fusion="fused")
    cfg_c = tfm.TransformerConfig(**_TINY, mlp_dtype="int8")
    params = tfm.init_params(jax.random.key(0), cfg_f)
    tokens = jax.random.randint(jax.random.key(1), (2, cfg_f.seq_len + 1),
                                0, cfg_f.vocab_size)
    loss_f = jax.jit(lambda p, t: tfm.loss_fn(p, t, cfg_f))(params, tokens)
    loss_c = jax.jit(lambda p, t: tfm.loss_fn(p, t, cfg_c))(params, tokens)
    assert float(loss_f) == float(loss_c)


def test_forward_requires_qstate_when_delayed():
    from dlnetbench_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(**_TINY, mlp_dtype="int8",
                                quant_fusion="fused",
                                quant_scaling="delayed")
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (1, cfg.seq_len), 0,
                                cfg.vocab_size)
    with pytest.raises(ValueError, match="qstate"):
        tfm.forward(params, tokens, cfg)


@pytest.mark.tpu_only
def test_fused_ab_harness_on_chip():
    """The paired fused-vs-composed A/B at the REAL bench shape — the
    on-chip measurement harness behind bench.py's int8_fused_ab /
    fp8_fused_ab lines.  Collectable everywhere; the CPU mesh skips it
    (conftest) — interpret-mode kernels at 12288x4096x14336 would take
    hours there and measure nothing."""
    import bench
    from dlnetbench_tpu.models.bench_step import bench_card

    card = bench_card()
    dev = jax.devices()[0]
    for fmt in ("int8", "float8"):
        line = bench._bench_quant_fused_ab(card, "tpu_v5e", dev, fmt)
        assert line is not None
        for key in ("value", "best", "band", "n", "composed", "fused",
                    "fused_delayed", "ratio_fused_vs_composed"):
            assert key in line, key
