"""Energy sampling chain (metrics/energy.py) and its harness wiring."""
from __future__ import annotations

import pandas as pd

from dlnetbench_tpu.metrics import energy as E
from dlnetbench_tpu.metrics.emit import result_to_record
from dlnetbench_tpu.metrics.parser import records_to_dataframe
from dlnetbench_tpu.proxies.base import ProxyConfig, StepBundle, run_proxy


class FakeSampler:
    """Deterministic cumulative counter: 2 J per read."""

    def __init__(self):
        self.calls = 0

    def read_joules(self):
        self.calls += 1
        return 2.0 * self.calls


def test_rapl_sampler_counts_and_wraps(tmp_path):
    pkg = tmp_path / "intel-rapl:0"
    pkg.mkdir()
    (pkg / "energy_uj").write_text("1000000")
    (pkg / "max_energy_range_uj").write_text("2000000")
    sub = tmp_path / "intel-rapl:0:0"   # subzone must be ignored
    sub.mkdir()
    (sub / "energy_uj").write_text("999999999")

    s = E.RaplSampler(root=str(tmp_path))
    assert s.available
    assert s.read_joules() == 0.0
    (pkg / "energy_uj").write_text("1500000")
    assert abs(s.read_joules() - 0.5) < 1e-9
    # wraparound: counter drops, range is added back
    (pkg / "energy_uj").write_text("500000")
    assert abs(s.read_joules() - 1.5) < 1e-9


def test_rapl_psys_preempts_packages(tmp_path):
    """psys already contains the package domains: when present, only psys
    is summed (double-count guard)."""
    for zone, name, energy in [("intel-rapl:0", "package-0", "100"),
                               ("intel-rapl:1", "psys", "200")]:
        d = tmp_path / zone
        d.mkdir()
        (d / "name").write_text(name)
        (d / "energy_uj").write_text(energy)
        (d / "max_energy_range_uj").write_text("1000000000")
    s = E.RaplSampler(root=str(tmp_path))
    s.read_joules()
    (tmp_path / "intel-rapl:0" / "energy_uj").write_text("1000100")
    (tmp_path / "intel-rapl:1" / "energy_uj").write_text("1000200")
    assert abs(s.read_joules() - 1.0) < 1e-9  # psys delta only, not both


def test_rapl_unknown_range_drops_wrapped_sample(tmp_path):
    d = tmp_path / "intel-rapl:0"
    d.mkdir()
    (d / "energy_uj").write_text("500000")   # no max_energy_range_uj file
    s = E.RaplSampler(root=str(tmp_path))
    (d / "energy_uj").write_text("100")      # counter wrapped
    assert s.read_joules() == 0.0            # dropped, not +inf
    (d / "energy_uj").write_text("1000100")
    assert abs(s.read_joules() - 1.0) < 1e-9


def test_rapl_unavailable_when_no_domains(tmp_path):
    assert not E.RaplSampler(root=str(tmp_path)).available
    assert not E.HwmonSampler(root=str(tmp_path)).available


def test_run_proxy_emits_energy_consumed():
    bundle = StepBundle(full=lambda: None, compute=None, comm=None,
                        global_meta={"proxy": "t", "world_size": 1})
    cfg = ProxyConfig(warmup=1, runs=3)
    res = run_proxy("t", bundle, cfg, energy_sampler=FakeSampler())
    # per-run open/close brackets of a 2 J/read counter: 2 J each run
    # (genuinely per-run samples, not one amortized bracket)
    want = [2.0, 2.0, 2.0]
    assert res.timers_us["energy_consumed"] == want
    assert len(res.timers_us["runtimes"]) == 3

    rec = result_to_record(res)
    assert rec["ranks"][0]["energy_consumed"] == want
    df = records_to_dataframe([rec])
    assert list(df["energy_consumed"]) == want


def test_no_sampler_no_energy_column():
    bundle = StepBundle(full=lambda: None, compute=None, comm=None,
                        global_meta={"proxy": "t", "world_size": 1})
    cfg = ProxyConfig(warmup=1, runs=2, measure_energy=False)
    res = run_proxy("t", bundle, cfg)
    assert "energy_consumed" not in res.timers_us


def test_pareto_uses_energy_consumed_column():
    from dlnetbench_tpu.analysis.plots import plot_pareto
    import matplotlib
    matplotlib.use("Agg")
    df = pd.DataFrame({
        "runtime": [10.0, 20.0, 30.0, 40.0],
        "energy_consumed": [4.0, 3.0, 2.0, 5.0],
        "model": ["m"] * 4,
        "run": [0, 1, 2, 3],
    })
    ax = plot_pareto(df)
    assert "energy_consumed" in ax.get_ylabel()


def _mk_hwmon(root, dev, name, uw="1000000"):
    d = root / dev
    d.mkdir()
    (d / "name").write_text(name)
    (d / "power1_input").write_text(uw)


def test_hwmon_prefers_cpu_package_sensor(tmp_path):
    """Unconfigured selection must prefer CPU-package-like sensors over the
    alphabetically-first device (battery/NVMe/wifi misattribution guard),
    and surface the chosen device in .source."""
    _mk_hwmon(tmp_path, "hwmon0", "BAT0")          # alphabetically first
    _mk_hwmon(tmp_path, "hwmon1", "coretemp")      # the CPU-like one
    s = E.HwmonSampler(root=str(tmp_path))
    try:
        assert s.available
        assert s.source == "hwmon:coretemp"
    finally:
        s.close()


def test_hwmon_thread_lifecycle(tmp_path):
    """The 5 ms poller starts lazily on first read and stops on close —
    no busy thread for the remaining process lifetime (advisor finding)."""
    _mk_hwmon(tmp_path, "hwmon0", "cpu")
    s = E.HwmonSampler(root=str(tmp_path))
    assert s._thread is None            # nothing spinning before use
    s.read_joules()
    assert s._thread is not None and s._thread.is_alive()
    s.close()
    s._thread.join(timeout=2)
    assert not s._thread.is_alive()
    s.read_joules()                     # restartable for the next phase
    assert s._thread.is_alive()
    s.close()


def test_run_proxy_reports_energy_source():
    bundle = StepBundle(full=lambda: None, compute=None, comm=None,
                        global_meta={"proxy": "t", "world_size": 1})
    sampler = FakeSampler()
    sampler.source = "fake"
    res = run_proxy("t", bundle, ProxyConfig(warmup=1, runs=1),
                    energy_sampler=sampler)
    assert res.global_meta["energy_source"] == "fake"


# ---------------------------------------------------------------------
# TPU chip energy probe (VERDICT r5 #7): attempted channels — PJRT
# device attributes, tpu-named hwmon energy counters, the accel class —
# with the dead end DOCUMENTED (docs/PERF.md) when all miss.  Tested
# against fake sysfs trees; a real counter would make energy_source
# "tpu" automatically through detect_sampler's chip-first ordering.

import pytest  # noqa: E402


def test_tpu_probe_finds_hwmon_energy_counter(tmp_path):
    from dlnetbench_tpu.metrics.energy import TpuChipSampler

    hw = tmp_path / "hwmon" / "hwmon0"
    hw.mkdir(parents=True)
    (hw / "name").write_text("tpu_v5e\n")
    (hw / "energy1_input").write_text("1000000\n")  # 1 J in uJ
    s = TpuChipSampler(hwmon_root=str(tmp_path / "hwmon"),
                       accel_root=str(tmp_path / "no_accel"))
    assert s.available
    assert s.source == "tpu"
    assert s.read_joules() == 0.0
    (hw / "energy1_input").write_text("3500000\n")
    assert s.read_joules() == pytest.approx(2.5)
    assert any("tpu_v5e" in n for n in s.probe_notes)


def test_tpu_probe_accel_class_counter(tmp_path):
    from dlnetbench_tpu.metrics.energy import TpuChipSampler

    acc = tmp_path / "accel" / "accel0" / "device"
    acc.mkdir(parents=True)
    (acc / "energy_uj").write_text("500000\n")
    s = TpuChipSampler(hwmon_root=str(tmp_path / "no_hwmon"),
                       accel_root=str(tmp_path / "accel"))
    assert s.available
    (acc / "energy_uj").write_text("1500000\n")
    assert s.read_joules() == pytest.approx(1.0)


def test_tpu_probe_dead_end_is_reported_not_silent(tmp_path):
    """On images without a chip counter (the current state — the
    docs/PERF.md dead end) the probe must say what it tried and report
    unavailable, so the host samplers take over with host-side
    labeling."""
    from dlnetbench_tpu.metrics.energy import TpuChipSampler

    # a non-tpu hwmon must NOT be claimed as a chip counter
    hw = tmp_path / "hwmon" / "hwmon0"
    hw.mkdir(parents=True)
    (hw / "name").write_text("acpitz\n")
    (hw / "energy1_input").write_text("1000\n")
    s = TpuChipSampler(hwmon_root=str(tmp_path / "hwmon"),
                       accel_root=str(tmp_path / "no_accel"))
    assert not s.available
    assert any("no TPU chip energy counter" in n for n in s.probe_notes)
    # a tpu-named hwmon with only instantaneous power (no cumulative
    # energy channel) is also noted, not claimed
    (hw / "name").write_text("tpu_v5e\n")
    (hw / "energy1_input").unlink()
    (hw / "power1_input").write_text("1000000\n")
    s2 = TpuChipSampler(hwmon_root=str(tmp_path / "hwmon"),
                        accel_root=str(tmp_path / "no_accel"))
    assert not s2.available
    assert any("no energy*_input" in n for n in s2.probe_notes)
