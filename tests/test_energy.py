"""Energy sampling chain (metrics/energy.py) and its harness wiring."""
from __future__ import annotations

import pandas as pd

from dlnetbench_tpu.metrics import energy as E
from dlnetbench_tpu.metrics.emit import result_to_record
from dlnetbench_tpu.metrics.parser import records_to_dataframe
from dlnetbench_tpu.proxies.base import ProxyConfig, StepBundle, run_proxy


class FakeSampler:
    """Deterministic cumulative counter: 2 J per read."""

    def __init__(self):
        self.calls = 0

    def read_joules(self):
        self.calls += 1
        return 2.0 * self.calls


def test_rapl_sampler_counts_and_wraps(tmp_path):
    pkg = tmp_path / "intel-rapl:0"
    pkg.mkdir()
    (pkg / "energy_uj").write_text("1000000")
    (pkg / "max_energy_range_uj").write_text("2000000")
    sub = tmp_path / "intel-rapl:0:0"   # subzone must be ignored
    sub.mkdir()
    (sub / "energy_uj").write_text("999999999")

    s = E.RaplSampler(root=str(tmp_path))
    assert s.available
    assert s.read_joules() == 0.0
    (pkg / "energy_uj").write_text("1500000")
    assert abs(s.read_joules() - 0.5) < 1e-9
    # wraparound: counter drops, range is added back
    (pkg / "energy_uj").write_text("500000")
    assert abs(s.read_joules() - 1.5) < 1e-9


def test_rapl_psys_preempts_packages(tmp_path):
    """psys already contains the package domains: when present, only psys
    is summed (double-count guard)."""
    for zone, name, energy in [("intel-rapl:0", "package-0", "100"),
                               ("intel-rapl:1", "psys", "200")]:
        d = tmp_path / zone
        d.mkdir()
        (d / "name").write_text(name)
        (d / "energy_uj").write_text(energy)
        (d / "max_energy_range_uj").write_text("1000000000")
    s = E.RaplSampler(root=str(tmp_path))
    s.read_joules()
    (tmp_path / "intel-rapl:0" / "energy_uj").write_text("1000100")
    (tmp_path / "intel-rapl:1" / "energy_uj").write_text("1000200")
    assert abs(s.read_joules() - 1.0) < 1e-9  # psys delta only, not both


def test_rapl_unknown_range_drops_wrapped_sample(tmp_path):
    d = tmp_path / "intel-rapl:0"
    d.mkdir()
    (d / "energy_uj").write_text("500000")   # no max_energy_range_uj file
    s = E.RaplSampler(root=str(tmp_path))
    (d / "energy_uj").write_text("100")      # counter wrapped
    assert s.read_joules() == 0.0            # dropped, not +inf
    (d / "energy_uj").write_text("1000100")
    assert abs(s.read_joules() - 1.0) < 1e-9


def test_rapl_unavailable_when_no_domains(tmp_path):
    assert not E.RaplSampler(root=str(tmp_path)).available
    assert not E.HwmonSampler(root=str(tmp_path)).available


def test_run_proxy_emits_energy_consumed():
    bundle = StepBundle(full=lambda: None, compute=None, comm=None,
                        global_meta={"proxy": "t", "world_size": 1})
    cfg = ProxyConfig(warmup=1, runs=3)
    res = run_proxy("t", bundle, cfg, energy_sampler=FakeSampler())
    # one bracket over 3 runs of a 2 J/read counter: 2 J total / 3 runs
    want = [2.0 / 3] * 3
    assert res.timers_us["energy_consumed"] == want
    assert len(res.timers_us["runtimes"]) == 3

    rec = result_to_record(res)
    assert rec["ranks"][0]["energy_consumed"] == want
    df = records_to_dataframe([rec])
    assert list(df["energy_consumed"]) == want


def test_no_sampler_no_energy_column():
    bundle = StepBundle(full=lambda: None, compute=None, comm=None,
                        global_meta={"proxy": "t", "world_size": 1})
    cfg = ProxyConfig(warmup=1, runs=2, measure_energy=False)
    res = run_proxy("t", bundle, cfg)
    assert "energy_consumed" not in res.timers_us


def test_pareto_uses_energy_consumed_column():
    from dlnetbench_tpu.analysis.plots import plot_pareto
    import matplotlib
    matplotlib.use("Agg")
    df = pd.DataFrame({
        "runtime": [10.0, 20.0, 30.0, 40.0],
        "energy_consumed": [4.0, 3.0, 2.0, 5.0],
        "model": ["m"] * 4,
        "run": [0, 1, 2, 3],
    })
    ax = plot_pareto(df)
    assert "energy_consumed" in ax.get_ylabel()
