"""Topology visualizer: tree building and ASCII rendering, including the
multi-slice / multi-host shapes a dev box can't produce natively."""
from __future__ import annotations

import types

import jax

from dlnetbench_tpu.utils.topology import build_topology, format_topology


def _fake_dev(id, process=0, slice_index=0, coords=None, core=None,
              kind="TPU v5p"):
    return types.SimpleNamespace(id=id, process_index=process,
                                 slice_index=slice_index, coords=coords,
                                 core_on_chip=core, device_kind=kind)


def test_build_topology_real_devices():
    tree = build_topology(jax.devices())
    chips = [d for hosts in tree.values() for devs in hosts.values()
             for d in devs]
    assert len(chips) == len(jax.devices())
    assert sorted(c["id"] for c in chips) == sorted(d.id for d in jax.devices())


def test_format_topology_cpu_fallback():
    out = format_topology(jax.devices())
    assert "fabric:" in out
    assert "slice 0" in out
    assert "host 0" in out
    assert out.count("chip id=") == len(jax.devices())


def test_format_topology_multislice_multihost():
    devs = [
        _fake_dev(0, process=0, slice_index=0, coords=(0, 0, 0), core=0),
        _fake_dev(1, process=0, slice_index=0, coords=(1, 0, 0), core=0),
        _fake_dev(2, process=1, slice_index=0, coords=(0, 1, 0), core=0),
        _fake_dev(3, process=2, slice_index=1, coords=(0, 0, 0), core=0),
    ]
    out = format_topology(devs)
    assert "2 slices" in out and "DCN-linked" in out
    assert "3 host" in out
    assert "coords=(1, 0, 0)" in out
    # slice 1 holds exactly one chip, drawn under host 2
    assert "slice 1" in out and "host 2" in out


def test_tree_sorted_and_grouped():
    devs = [_fake_dev(3, process=1), _fake_dev(0, process=0),
            _fake_dev(2, process=1), _fake_dev(1, process=0)]
    tree = build_topology(devs)
    assert list(tree[0].keys()) == [0, 1]
    assert [d["id"] for d in tree[0][0]] == [0, 1]
    assert [d["id"] for d in tree[0][1]] == [2, 3]
