"""Flash-attention kernel vs. the einsum reference (models/layers.py).

Runs the Pallas kernels in interpret mode on the CPU mesh (conftest forces
JAX_PLATFORMS=cpu), checking forward values and all three input gradients.
The einsum implementation is the ground truth; tolerances are fp32-tight.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from dlnetbench_tpu.models import layers as L
from dlnetbench_tpu import ops
from dlnetbench_tpu.ops import flash_attention, flash_supported


def _make_qkv(key, b, s, hq, hkv, dh, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, hq, dh), dtype)
    k = jax.random.normal(kk, (b, s, hkv, dh), dtype)
    v = jax.random.normal(kv, (b, s, hkv, dh), dtype)
    return q, k, v


CASES = [
    # b, s, hq, hkv, dh, causal
    (1, 256, 2, 2, 128, True),    # MHA, aligned head dim
    (2, 256, 4, 2, 128, True),    # GQA group 2
    (1, 256, 4, 1, 64, True),     # MQA + head-dim padding (gpt2-style 64)
    (1, 256, 2, 2, 128, False),   # non-causal (ViT-style)
    (1, 384, 2, 2, 128, True),    # seq that only 128 divides
]


@pytest.mark.parametrize("b,s,hq,hkv,dh,causal", CASES)
def test_forward_matches_reference(b, s, hq, hkv, dh, causal):
    q, k, v = _make_qkv(jax.random.key(0), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, 128, 128)
    assert got.shape == want.shape and got.dtype == want.dtype
    assert jnp.max(jnp.abs(got - want)) < 2e-5


@pytest.mark.parametrize("b,s,hq,hkv,dh,causal", CASES)
def test_gradients_match_reference(b, s, hq, hkv, dh, causal):
    q, k, v = _make_qkv(jax.random.key(1), b, s, hq, hkv, dh)
    cot = jax.random.normal(jax.random.key(2), q.shape, q.dtype)

    def loss_ref(q, k, v):
        return jnp.sum(L.attention(q, k, v, causal=causal) * cot)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 128, 128) * cot)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_fl):
        assert jnp.max(jnp.abs(a - b_)) < 5e-4


def test_dispatcher_and_support_gate():
    q, k, v = _make_qkv(jax.random.key(3), 1, 256, 2, 2, 128)
    assert flash_supported(q, k, v)
    out = ops.attention(q, k, v, causal=True, impl="flash")
    ref = ops.attention(q, k, v, causal=True, impl="xla")
    assert jnp.max(jnp.abs(out - ref)) < 2e-5
    # auto on CPU -> xla path, still correct
    auto = ops.attention(q, k, v, causal=True, impl="auto")
    assert jnp.max(jnp.abs(auto - ref)) < 1e-6
    with pytest.raises(ValueError):
        ops.attention(q, k, v, causal=True, impl="nope")


def test_unsupported_seq_falls_back():
    q, k, v = _make_qkv(jax.random.key(4), 1, 100, 2, 2, 64)
    assert not flash_supported(q, k, v)
    out = ops.attention(q, k, v, causal=True, impl="auto")
    assert out.shape == q.shape
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, None, None)


def test_bf16_forward_close():
    q, k, v = _make_qkv(jax.random.key(5), 1, 256, 2, 2, 128,
                        dtype=jnp.bfloat16)
    want = L.attention(q, k, v, causal=True).astype(jnp.float32)
    got = flash_attention(q, k, v, True, 128, 128).astype(jnp.float32)
    assert jnp.max(jnp.abs(got - want)) < 3e-2
