"""Flash-attention kernel vs. the einsum reference (models/layers.py).

Runs the Pallas kernels in interpret mode on the CPU mesh (conftest forces
JAX_PLATFORMS=cpu), checking forward values and all three input gradients.
The einsum implementation is the ground truth; tolerances are fp32-tight.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from dlnetbench_tpu.models import layers as L
from dlnetbench_tpu import ops
from dlnetbench_tpu.ops import flash_attention, flash_supported


def _make_qkv(key, b, s, hq, hkv, dh, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, s, hq, dh), dtype)
    k = jax.random.normal(kk, (b, s, hkv, dh), dtype)
    v = jax.random.normal(kv, (b, s, hkv, dh), dtype)
    return q, k, v


CASES = [
    # b, s, hq, hkv, dh, causal
    (1, 256, 2, 2, 128, True),    # MHA, aligned head dim
    (2, 256, 4, 2, 128, True),    # GQA group 2
    (1, 256, 4, 1, 64, True),     # MQA + head-dim padding (gpt2-style 64)
    (1, 256, 2, 2, 128, False),   # non-causal (ViT-style)
    (1, 384, 2, 2, 128, True),    # seq that only 128 divides
]


@pytest.mark.parametrize("b,s,hq,hkv,dh,causal", CASES)
def test_forward_matches_reference(b, s, hq, hkv, dh, causal):
    q, k, v = _make_qkv(jax.random.key(0), b, s, hq, hkv, dh)
    want = L.attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, 128, 128)
    assert got.shape == want.shape and got.dtype == want.dtype
    assert jnp.max(jnp.abs(got - want)) < 2e-5


@pytest.mark.parametrize("b,s,hq,hkv,dh,causal", CASES)
def test_gradients_match_reference(b, s, hq, hkv, dh, causal):
    q, k, v = _make_qkv(jax.random.key(1), b, s, hq, hkv, dh)
    cot = jax.random.normal(jax.random.key(2), q.shape, q.dtype)

    def loss_ref(q, k, v):
        return jnp.sum(L.attention(q, k, v, causal=causal) * cot)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, 128, 128) * cot)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_fl):
        assert jnp.max(jnp.abs(a - b_)) < 5e-4


def test_dispatcher_and_support_gate():
    q, k, v = _make_qkv(jax.random.key(3), 1, 256, 2, 2, 128)
    assert flash_supported(q, k, v)
    out = ops.attention(q, k, v, causal=True, impl="flash")
    ref = ops.attention(q, k, v, causal=True, impl="xla")
    assert jnp.max(jnp.abs(out - ref)) < 2e-5
    # auto on CPU -> xla path, still correct
    auto = ops.attention(q, k, v, causal=True, impl="auto")
    assert jnp.max(jnp.abs(auto - ref)) < 1e-6
    with pytest.raises(ValueError):
        ops.attention(q, k, v, causal=True, impl="nope")


def test_unsupported_seq_falls_back():
    q, k, v = _make_qkv(jax.random.key(4), 1, 100, 2, 2, 64)
    assert not flash_supported(q, k, v)
    out = ops.attention(q, k, v, causal=True, impl="auto")
    assert out.shape == q.shape
    with pytest.raises(ValueError):
        flash_attention(q, k, v, True, None, None)


def test_bf16_forward_close():
    q, k, v = _make_qkv(jax.random.key(5), 1, 256, 2, 2, 128,
                        dtype=jnp.bfloat16)
    want = L.attention(q, k, v, causal=True).astype(jnp.float32)
    got = flash_attention(q, k, v, True, 128, 128).astype(jnp.float32)
    assert jnp.max(jnp.abs(got - want)) < 3e-2


# ------------------------------------------ splash (block-sparse) masks

from dlnetbench_tpu.ops import attention_mask as am  # noqa: E402
from dlnetbench_tpu.ops.flash_attention import splash_attention  # noqa: E402

longcontext = pytest.mark.longcontext

MASK_SPECS = [
    am.MaskSpec(causal=True, window=40),
    am.MaskSpec(causal=True, seg_avg=50, seg_seed=3),
    am.MaskSpec(causal=False, seg_avg=64, seg_seed=1),
    am.MaskSpec(causal=True, window=32, seg_avg=80, seg_seed=5),
]


def _masked_ref(q, k, v, spec):
    return L.attention(q, k, v, causal=spec.causal,
                       dense_mask=jnp.asarray(
                           am.dense_mask(spec, q.shape[1])))


@longcontext
def test_splash_causal_bit_identical_to_flash():
    """The acceptance bar: splash with the plain-causal BlockMask is
    BIT-identical to the dense causal flash path — forward AND all
    three gradients (same visit set, same mask booleans, same
    arithmetic; full blocks skipping the mask apply changes nothing
    because an all-true where() is the identity)."""
    q, k, v = _make_qkv(jax.random.key(6), 2, 256, 4, 2, 128)
    spec = am.MaskSpec(causal=True)
    a = flash_attention(q, k, v, True, 128, 128)
    b = splash_attention(q, k, v, spec, 128, 128)
    assert jnp.all(a == b)
    cot = jax.random.normal(jax.random.key(7), q.shape, q.dtype)
    gf = jax.grad(lambda *xs: jnp.sum(
        flash_attention(*xs, True, 128, 128) * cot),
        argnums=(0, 1, 2))(q, k, v)
    gs = jax.grad(lambda *xs: jnp.sum(
        splash_attention(*xs, spec, 128, 128) * cot),
        argnums=(0, 1, 2))(q, k, v)
    for a_, b_ in zip(gf, gs):
        assert jnp.all(a_ == b_)


@longcontext
@pytest.mark.parametrize("spec", MASK_SPECS)
def test_splash_masked_matches_dense_reference(spec):
    """Window / segment / intersection specs vs the dense reference
    applying the SAME mask (fwd <= 1e-5; grads via jax.vjp)."""
    q, k, v = _make_qkv(jax.random.key(8), 2, 256, 4, 2, 128)
    want = _masked_ref(q, k, v, spec)
    got = splash_attention(q, k, v, spec, 64, 64)
    assert jnp.max(jnp.abs(got - want)) < 1e-5
    cot = jax.random.normal(jax.random.key(9), q.shape, q.dtype)
    _, vjp_ref = jax.vjp(lambda *xs: _masked_ref(*xs, spec), q, k, v)
    _, vjp_spl = jax.vjp(lambda *xs: splash_attention(*xs, spec, 64, 64),
                         q, k, v)
    for a_, b_ in zip(vjp_ref(cot), vjp_spl(cot)):
        assert jnp.max(jnp.abs(a_ - b_)) < 1e-4


@longcontext
def test_splash_gqa_and_padded_head_dim():
    """GQA group summing and the head-dim zero-padding path under a
    masked spec (the gpt2-style Dh=64)."""
    spec = am.MaskSpec(causal=True, window=48)
    q, k, v = _make_qkv(jax.random.key(10), 1, 256, 4, 1, 64)
    want = _masked_ref(q, k, v, spec)
    got = splash_attention(q, k, v, spec, 64, 64)
    assert jnp.max(jnp.abs(got - want)) < 1e-5


@longcontext
def test_ops_attention_mask_dispatch():
    """ops.attention routes mask specs: flash -> splash kernels, xla ->
    the dense-masked reference; both agree, and a causal-flag mismatch
    fails loud."""
    from dlnetbench_tpu import ops
    spec = am.MaskSpec(causal=True, window=32)
    q, k, v = _make_qkv(jax.random.key(11), 1, 256, 2, 2, 128)
    a = ops.attention(q, k, v, causal=True, impl="flash", mask=spec)
    b = ops.attention(q, k, v, causal=True, impl="xla", mask=spec)
    assert jnp.max(jnp.abs(a - b)) < 1e-5
    # the plain-causal spec collapses onto the dense-causal default
    c = ops.attention(q, k, v, causal=True, impl="flash",
                      mask=am.MaskSpec(causal=True))
    assert jnp.all(c == ops.attention(q, k, v, causal=True,
                                      impl="flash"))
    with pytest.raises(ValueError, match="causal"):
        ops.attention(q, k, v, causal=False, impl="xla", mask=spec)


@longcontext
def test_block_candidates_cover_64k_128k():
    """ISSUE 10 satellite: every candidate list must resolve a block at
    the long-context bench lengths, and an unresolvable S >= 64k must
    raise NAMING the sequence length instead of silently handing the
    dense path a 4-billion-entry score matrix."""
    from dlnetbench_tpu.ops import flash_attention as _m
    import importlib
    fa = importlib.import_module("dlnetbench_tpu.ops.flash_attention")
    for s in (64 * 1024, 128 * 1024):
        for cands in (fa._BLOCK_CANDIDATES_FWD, fa._BLOCK_CANDIDATES_BWD):
            b = fa._pick_block(s, cands)
            assert b is not None and s % b == 0
    with pytest.raises(ValueError, match="65537"):
        fa._pick_block(64 * 1024 + 1)
    # below the long-context threshold the gate still degrades softly
    assert fa._pick_block(100) is None


@longcontext
def test_auto_dispatch_refuses_silent_dense_at_64k():
    from dlnetbench_tpu import ops
    q = jnp.zeros((1, 64 * 1024, 1, 8), jnp.float32)
    with pytest.raises(ValueError, match="65536"):
        ops.attention(q, q, q, causal=True, impl="auto")
    q_bad = jnp.zeros((1, 64 * 1024 + 1, 1, 8), jnp.float32)
    with pytest.raises(ValueError, match="65537"):
        ops.attention(q_bad, q_bad, q_bad, causal=True, impl="auto")


@longcontext
def test_fit_block_refuses_sub_lane_grid_on_long_dim():
    from dlnetbench_tpu.ops import pallas_common
    assert pallas_common.fit_block(64 * 1024, 2048) == 2048
    with pytest.raises(ValueError, match=str(64 * 1024 + 1)):
        pallas_common.fit_block(64 * 1024 + 1, 2048)
    # short dims keep the soft degradation
    assert pallas_common.fit_block(100, 64) == 4
