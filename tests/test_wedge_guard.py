"""Wedge-guard tests (VERDICT r4 #1/#8): the round-4 postmortem showed a
wedged axon tunnel hangs even ``jax.devices()``, which killed BOTH driver
artifacts.  These tests pin the two repaired properties:

* ``__graft_entry__._force_virtual_cpu`` never calls into a backend that
  is not provably pinned cpu (the CPU dryrun needs zero TPU);
* ``bench.py`` degrades to a parseable skip marker when the backend
  probe never comes up, instead of stack-tracing the artifact away.
"""
from __future__ import annotations

import json

import jax
import pytest


def test_platform_pinned_cpu_true_under_test_harness():
    from dlnetbench_tpu.utils import tpu_probe
    assert tpu_probe.platform_pinned_cpu()  # conftest pins cpu both ways


def test_probe_backend_subprocess_reports_devices(monkeypatch):
    from dlnetbench_tpu.utils import tpu_probe
    # Pin the probe subprocess to cpu through the CONFIG (on the tunnel
    # image sitecustomize overrides the inherited JAX_PLATFORMS=cpu, so
    # an unpinned probe would initialize the real — wedgeable — tunnel
    # backend and make this wedge-guard test itself wedge-sensitive;
    # the subprocess/JSON plumbing under test is platform-agnostic)
    monkeypatch.setattr(
        tpu_probe, "_PROBE_SRC",
        "import jax; jax.config.update('jax_platforms', 'cpu'); "
        + tpu_probe._PROBE_SRC)
    out = tpu_probe.probe_backend(timeout_s=120)
    assert out is not None and out["n"] >= 1
    assert out["platform"] == "cpu"


def test_probe_backend_kills_hung_probe(monkeypatch):
    from dlnetbench_tpu.utils import tpu_probe
    monkeypatch.setattr(tpu_probe, "_PROBE_SRC", "import time; time.sleep(30)")
    assert tpu_probe.probe_backend(timeout_s=0.5) is None


def test_wait_for_backend_bounded_window(monkeypatch):
    from dlnetbench_tpu.utils import tpu_probe
    monkeypatch.setattr(tpu_probe, "probe_backend", lambda timeout_s: None)
    lines = []
    out = tpu_probe.wait_for_backend(window_s=0.1, probe_timeout_s=0.1,
                                     log=lines.append)
    assert out is None and lines  # failed attempts are narrated


def test_force_virtual_cpu_never_probes_unpinned_backend(monkeypatch):
    """Regression pin for MULTICHIP_r04 rc=124: with the platform NOT
    provably cpu (the tunnel case), ``_force_virtual_cpu`` must pin cpu
    BEFORE any ``jax.devices()`` call.  The stub raises if a devices()
    probe happens while a non-cpu platform could still be selected —
    exactly the call that wedged r4."""
    import __graft_entry__ as ge
    from dlnetbench_tpu.utils import tpu_probe

    real_devices = jax.devices

    def wedgeable_devices(*a, **kw):
        if jax.config.jax_platforms != "cpu":
            raise AssertionError(
                "jax.devices() touched while a non-cpu backend could be "
                "selected — this is the r4 wedge")
        return real_devices(*a, **kw)

    monkeypatch.setattr(jax, "devices", wedgeable_devices)
    monkeypatch.setattr(tpu_probe, "platform_pinned_cpu", lambda: False)
    # simulate the tunnel image: config prefers a non-cpu platform
    prev = jax.config.jax_platforms
    jax.config.update("jax_platforms", "tpu,cpu")
    try:
        restore = ge._force_virtual_cpu(8)
        try:
            assert len(jax.devices()) >= 8
            assert jax.config.jax_platforms == "cpu"
        finally:
            restore()  # puts back "tpu,cpu"
    finally:
        from jax.extend import backend as _jeb
        _jeb.clear_backends()
        jax.config.update("jax_platforms", prev)
        assert len(real_devices()) >= 8  # harness backend healthy again


def test_force_virtual_cpu_uses_pinned_backend_without_repin(monkeypatch):
    import __graft_entry__ as ge

    cleared = []
    from jax.extend import backend as _jeb
    monkeypatch.setattr(_jeb, "clear_backends",
                        lambda: cleared.append(1))
    restore = ge._force_virtual_cpu(8)  # harness already pinned cpu w/ 8
    restore()
    assert not cleared  # fast path: no backend teardown needed


def test_bench_skip_marker_when_tpu_never_comes_up(monkeypatch, capsys):
    import bench
    from dlnetbench_tpu.utils import tpu_probe

    monkeypatch.setattr(tpu_probe, "platform_pinned_cpu", lambda: False)
    monkeypatch.setattr(tpu_probe, "wait_for_backend",
                        lambda **kw: None)
    rc = bench.main()
    assert rc == 0  # the skip marker IS the artifact
    out_lines = capsys.readouterr().out.strip().splitlines()
    line = json.loads(out_lines[-1])
    assert "train step" in line["metric"]
    assert "tpu unavailable" in line["skipped"]


def test_bench_proceeds_on_pinned_cpu(monkeypatch):
    import bench
    from dlnetbench_tpu.utils import tpu_probe

    called = []
    monkeypatch.setattr(tpu_probe, "wait_for_backend",
                        lambda **kw: called.append(1) or None)
    assert bench._tpu_up_or_skip()  # pinned cpu: no probe, no skip
    assert not called
