"""Expert-parallel MoE subsystem tests (ISSUE 15): seeded grouped
routing (determinism, shard invariance, the capacity-factor drop
closed form), the grouped Pallas expert-FFN kernels (einsum parity,
count skipping, int8 exactness, empty-DB bit-identity), the decomposed
a2a dispatch/combine loop (monolithic parity forward and backward, the
A/B fake legs), the SPMD training-step wiring, and the
native-vs-SPMD a2a schedule parity."""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.models import layers as L
from dlnetbench_tpu.models import moe
from dlnetbench_tpu.ops import grouped_matmul as gm

pytestmark = pytest.mark.moe

_F32 = jnp.float32


def _routing_case(t=64, d=16, e=4, seed=0):
    x = jax.random.normal(jax.random.key(seed), (t, d), _F32)
    wr = jax.random.normal(jax.random.key(seed + 100), (d, e),
                           _F32) * 0.3
    return x, wr


# ------------------------------------------------------------ routing
def test_legacy_dispatch_bit_identical():
    """drop_seed=None + one group delegates to layers.moe_dispatch —
    the pre-ISSUE-15 harness bit for bit."""
    x, wr = _routing_case()
    xe0, d0, g0 = L.moe_dispatch(x, wr, 4, 2, 1.25)
    xe1, d1, g1 = moe.dispatch(x, wr, 4, 2, 1.25)
    assert jnp.all(xe0 == xe1) and jnp.all(d0 == d1)
    assert jnp.all(g0 == g1)


def test_seeded_routing_deterministic_and_seed_sensitive():
    x, wr = _routing_case()
    a = moe.dispatch(x, wr, 4, 2, 0.5, drop_seed=7, group_tokens=16)
    b = moe.dispatch(x, wr, 4, 2, 0.5, drop_seed=7, group_tokens=16)
    c = moe.dispatch(x, wr, 4, 2, 0.5, drop_seed=8, group_tokens=16)
    assert jnp.all(a[1] == b[1])            # same seed: identical
    assert not jnp.all(a[1] == c[1])        # the seed is load-bearing


@pytest.mark.parametrize("shards", [2, 4])
def test_seeded_routing_shard_invariant(shards):
    """The acceptance bar: the kept/dropped set computed per shard is
    IDENTICAL to the single-device computation over the same global
    tokens (exact one-hot equality — groups nest inside shards and
    the priority is a pure function of (seed, global token id))."""
    t, g = 64, 16
    x, wr = _routing_case(t=t)
    full = moe.dispatch(x, wr, 4, 2, 1.0, drop_seed=11, group_tokens=g,
                        gids=jnp.arange(t))
    h = t // shards
    ch = full[1].shape[2] // shards
    for s in range(shards):
        part = moe.dispatch(x[s * h:(s + 1) * h], wr, 4, 2, 1.0,
                            drop_seed=11, group_tokens=g,
                            gids=jnp.arange(s * h, (s + 1) * h))
        assert jnp.all(
            full[1][s * h:(s + 1) * h, :, s * ch:(s + 1) * ch]
            == part[1]), f"shard {s} routing differs"
        assert jnp.all(full[2][s * h:(s + 1) * h] == part[2])


@pytest.mark.parametrize("cf", [0.25, 0.5, 1.0, 4.0])
@pytest.mark.parametrize("seed", [0, 3])
def test_drop_counts_match_capacity_closed_form(cf, seed):
    """Measured drops == sum_{g,e} max(0, n_ge - cap_g) — the
    capacity-factor closed form, at every capacity and seed."""
    x, wr = _routing_case(seed=seed)
    out = moe.dispatch(x, wr, 4, 2, cf, drop_seed=seed,
                       group_tokens=16, with_stats=True)
    stats = out[3]
    assert float(stats["dropped"]) == float(stats["expected_dropped"])
    # and the closed form recomputed independently agrees
    _, idx = L.moe_router(x, wr, 2)
    counts = np.zeros((4, 4))
    for tok in range(64):
        for kk in range(2):
            counts[tok // 16, int(idx[tok, kk])] += 1
    cap = moe.group_capacity(16, 2, 4, cf)
    assert float(stats["dropped"]) == np.maximum(
        counts - cap, 0).sum()


def test_dispatch_group_divisibility_refused():
    x, wr = _routing_case(t=60)
    with pytest.raises(ValueError, match="group_tokens"):
        moe.dispatch(x, wr, 4, 2, 1.0, group_tokens=16)


def test_stats_globals_shape():
    x, wr = _routing_case()
    stats = moe.dispatch(x, wr, 4, 2, 1.0, drop_seed=1,
                         group_tokens=16, with_stats=True)[3]
    g = moe.stats_globals(jax.device_get(stats), num_experts=4,
                          top_k=2, capacity_factor=1.0, drop_seed=1,
                          group_tokens=16)
    assert g["moe_experts"] == 4 and g["moe_drop_seed"] == 1
    blk = g["moe"]
    assert len(blk["expert_load"]) == 4
    assert abs(sum(blk["expert_load"]) - 1.0) < 1e-3
    assert 0.0 <= blk["drop_rate"] <= 1.0
    assert 0.0 <= blk["router_entropy"] <= 1.0 + 1e-6
    assert blk["load_imbalance"] >= 1.0


# ----------------------------------------------------- grouped kernel
def _gm_case(e=4, c=16, d=32, h=48, dtype=_F32):
    x = jax.random.normal(jax.random.key(0), (e, c, d), dtype)
    wg = jax.random.normal(jax.random.key(1), (e, d, h), dtype) * 0.05
    wu = jax.random.normal(jax.random.key(2), (e, d, h), dtype) * 0.05
    wd = jax.random.normal(jax.random.key(3), (e, h, d), dtype) * 0.05
    return x, wg, wu, wd


def test_grouped_matmul_matches_einsum():
    x, wg, _, _ = _gm_case()
    ref = jnp.einsum("ecd,edh->ech", x, wg)
    out = gm.grouped_matmul(x, wg, block_c=8, block_n=16, block_k=16)
    assert float(jnp.max(jnp.abs(out - ref))) < 1e-5


def test_grouped_matmul_counts_skip():
    """Blocks past an expert's count emit zeros; live rows match the
    dense reference."""
    x, wg, _, _ = _gm_case()
    ref = jnp.einsum("ecd,edh->ech", x, wg)
    counts = jnp.array([16, 5, 0, 9], jnp.int32)
    out = gm.grouped_matmul(x, wg, counts=counts, block_c=4,
                            block_n=16, block_k=16)
    for e in range(4):
        n = int(counts[e])
        nb = min(-(-n // 4) * 4 if n else 0, 16)
        if n:
            assert float(jnp.max(jnp.abs(out[e, :n] - ref[e, :n]))) \
                < 1e-5
        if nb < 16:
            assert float(jnp.max(jnp.abs(out[e, nb:]))) == 0.0


def test_grouped_matmul_int8_exact_vs_composed():
    """Same scales + associative int32 accumulation: the fused grouped
    int8 matmul EQUALS the composed XLA reference exactly (the PR-3
    exactness discipline on the expert axis)."""
    from dlnetbench_tpu.ops.quantized_matmul import (_cast_q,
                                                     scale_from_amax)
    x, wg, _, _ = _gm_case(dtype=jnp.bfloat16)
    wq, sw = gm.quantize_experts(wg, "int8")
    sx = scale_from_amax(gm.expert_amax(x), "int8")
    out = gm.grouped_matmul(x, wq, sx=sx, sw=sw, fmt="int8",
                            block_c=8, block_n=16, block_k=16)
    xq = _cast_q(x.astype(_F32) / sx[:, None, None], "int8")
    comp = (jnp.einsum("ecd,edh->ech", xq.astype(jnp.int32),
                       wq.astype(jnp.int32)).astype(_F32)
            * (sx * sw)[:, None, None]).astype(jnp.bfloat16)
    assert jnp.all(out == comp)


def test_grouped_ffn_grads_match_reference():
    x, wg, wu, wd = _gm_case()

    def loss(x_, a, b, c):
        return jnp.sum(gm.grouped_ffn(x_, a, b, c, block_c=8,
                                      block_n=16, block_k=16) ** 2)

    def ref(x_, a, b, c):
        h = (jax.nn.silu(jnp.einsum("ecd,edh->ech", x_, a))
             * jnp.einsum("ecd,edh->ech", x_, b))
        return jnp.sum(jnp.einsum("ech,ehd->ecd", h, c) ** 2)

    g1 = jax.grad(loss, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    g2 = jax.grad(ref, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    for a, b in zip(g1, g2):
        assert float(jnp.max(jnp.abs(a - b))) < 1e-5


def test_grouped_ffn_fp8_runs_finite():
    x, wg, wu, wd = _gm_case(dtype=jnp.bfloat16)
    y = gm.grouped_ffn(x, wg, wu, wd, fmt="float8", block_c=8,
                       block_n=16, block_k=16)
    assert jnp.all(jnp.isfinite(y.astype(_F32)))


def test_grouped_blocks_validated():
    x, wg, _, _ = _gm_case()
    with pytest.raises(ValueError, match="block_c"):
        gm.grouped_matmul(x, wg, block_c=-4, block_n=16, block_k=16)
    with pytest.raises(ValueError, match="fmt"):
        gm.grouped_matmul(x, wg, fmt="int4")
    with pytest.raises(ValueError, match="sx/sw"):
        gm.grouped_matmul(x, wg, fmt="int8")


@pytest.mark.tuning
def test_grouped_ffn_empty_db_bit_identity(tmp_path, monkeypatch):
    """The ISSUE-9 consult contract on the new site: with no DB the
    consult path is BIT-identical to explicit DEFAULT_BLOCKS, and a
    committed record is consulted (frozen after first consult)."""
    from dlnetbench_tpu import tuning
    x, wg, wu, wd = _gm_case(e=2, c=8, d=16, h=16)
    tuning.reset(clear_env=True)
    try:
        y_off = gm.grouped_ffn(x, wg, wu, wd)
        y_exp = gm.grouped_ffn(x, wg, wu, wd, **gm.DEFAULT_BLOCKS)
        assert jnp.all(y_off == y_exp)
        assert tuning.provenance() is None  # disabled: logs nothing
        # now a DB with a record for THIS key must hit
        from dlnetbench_tpu.tuning.db import TuningDB
        monkeypatch.setenv(tuning.params.ENV_DB_DIR, str(tmp_path))
        db = TuningDB(str(tmp_path))
        key = tuning.params.grouped_ffn_key(2, 8, 16, 16, "none",
                                            x.dtype)
        db.put("grouped_ffn", key, tuning.params.hw_key(),
               {"block_c": 4, "block_n": 8, "block_k": 8})
        tuning.reset()
        y_tuned = gm.grouped_ffn(x, wg, wu, wd)
        prov = tuning.provenance()
        hit = [v for k, v in prov["sites"].items()
               if k == f"grouped_ffn|{key}"]
        assert hit and hit[0]["hit"]
        assert hit[0]["config"]["block_c"] == 4
        # tuned divisor blocks produce the same values (pure tiling)
        assert float(jnp.max(jnp.abs(y_tuned - y_off))) < 1e-5
    finally:
        tuning.reset(clear_env=True)


def test_moe_grouped_matches_sparse_lossless():
    x, wr = _routing_case(t=32, d=16)
    _, wg, wu, wd = _gm_case(e=4, c=32, d=16, h=24)
    ys = L.moe_sparse(x, wr, wg, wu, wd, 2, capacity_factor=2.0)
    yg = moe.moe_grouped(x, wr, wg, wu, wd, 2, capacity_factor=2.0)
    assert float(jnp.max(jnp.abs(ys - yg))) < 1e-5


def test_transformer_moe_grouped_impl():
    """moe_impl='grouped' runs the transformer forward/loss and stays
    near the sparse impl (same routing, grouped kernels)."""
    from dlnetbench_tpu.models import transformer as tfm
    kw = dict(vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2,
              ff_dim=32, num_layers=2, seq_len=16, gated=True,
              max_positions=0, dtype="float32", num_experts=4,
              top_k=2, moe_capacity_factor=2.0)
    cfg_s = tfm.TransformerConfig(moe_impl="sparse", **kw)
    cfg_g = tfm.TransformerConfig(moe_impl="grouped", **kw)
    params = tfm.init_params(jax.random.key(0), cfg_s)
    toks = jax.random.randint(jax.random.key(1), (2, 17), 0, 64)
    l_s = float(tfm.loss_fn(params, toks, cfg_s))
    l_g = float(tfm.loss_fn(params, toks, cfg_g))
    assert abs(l_s - l_g) < 1e-4 * max(1.0, abs(l_s))


# --------------------------------------------------- decomposed a2a
def _shardmap_ffn(fn, mesh):
    from jax.sharding import PartitionSpec as P

    from dlnetbench_tpu.utils.jax_compat import shard_map
    specs = (P("tp"), P("tp"), P("tp"), P("tp"))
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=specs,
                             out_specs=P("tp"), check_vma=False))


def _a2a_case(n=4, e=8, c=6, d=16, h=24):
    """Per-rank [E, C, d] dispatch buffers stacked on the shard axis
    (shard_map P("tp") hands each rank its own buffer) + GLOBAL expert
    weights sharded to [E/n, ...] per rank."""
    from jax.sharding import Mesh
    mesh = Mesh(np.array(jax.devices()[:n]), ("tp",))
    ein = jax.random.normal(jax.random.key(0), (n * e, c, d), _F32)
    wg = jax.random.normal(jax.random.key(1), (e, d, h), _F32) * 0.1
    wu = jax.random.normal(jax.random.key(2), (e, d, h), _F32) * 0.1
    wd = jax.random.normal(jax.random.key(3), (e, h, d), _F32) * 0.1
    return mesh, ein, wg, wu, wd


def test_a2a_expert_ffn_matches_monolithic(eight_devices):
    from jax import lax

    from dlnetbench_tpu.ops.moe_dispatch import a2a_expert_ffn
    mesh, ein, wg, wu, wd = _a2a_case()

    def mono(e_, a, b, c):
        x = lax.all_to_all(e_, "tp", split_axis=0, concat_axis=1,
                           tiled=True)
        y = moe.expert_ffn(x, a, b, c)
        return lax.all_to_all(y.astype(e_.dtype), "tp", split_axis=1,
                              concat_axis=0, tiled=True)

    def deco(e_, a, b, c):
        return a2a_expert_ffn(e_, a, b, c, "tp",
                              chunks=2).astype(e_.dtype)

    out_m = np.asarray(_shardmap_ffn(mono, mesh)(ein, wg, wu, wd))
    out_d = np.asarray(_shardmap_ffn(deco, mesh)(ein, wg, wu, wd))
    assert np.abs(out_m - out_d).max() < 1e-6


def test_a2a_expert_ffn_backward_matches(eight_devices):
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dlnetbench_tpu.ops.moe_dispatch import a2a_expert_ffn
    from dlnetbench_tpu.utils.jax_compat import shard_map
    mesh, ein, wg, wu, wd = _a2a_case()

    def grads_of(fn):
        def local(e_, a, b, c):
            def l(e2, a2, b2, c2):
                return jnp.sum(fn(e2, a2, b2, c2) ** 2)
            return jax.grad(l, argnums=(0, 1, 2, 3))(e_, a, b, c)
        specs = (P("tp"),) * 4
        f = jax.jit(shard_map(local, mesh=mesh, in_specs=specs,
                              out_specs=(P("tp"),) * 4,
                              check_vma=False))
        return [np.asarray(g) for g in f(ein, wg, wu, wd)]

    def mono(e_, a, b, c):
        x = lax.all_to_all(e_, "tp", split_axis=0, concat_axis=1,
                           tiled=True)
        y = moe.expert_ffn(x, a, b, c)
        return lax.all_to_all(y.astype(e_.dtype), "tp", split_axis=1,
                              concat_axis=0, tiled=True)

    def deco(e_, a, b, c):
        return a2a_expert_ffn(e_, a, b, c, "tp").astype(e_.dtype)

    for a, b in zip(grads_of(mono), grads_of(deco)):
        assert np.abs(a - b).max() < 1e-5


def test_a2a_expert_ffn_fake_legs(eight_devices):
    """The A/B decomposition legs keep shapes (comm leg) / values that
    differ from the full program (both legs are stubs, not the real
    math) while executing — the overlap metric's Tc/Tm inputs."""
    from dlnetbench_tpu.ops.moe_dispatch import a2a_expert_ffn
    mesh, ein, wg, wu, wd = _a2a_case()
    full = _shardmap_ffn(
        lambda e_, a, b, c: a2a_expert_ffn(e_, a, b, c, "tp")
        .astype(e_.dtype), mesh)(ein, wg, wu, wd)
    for kw in ({"fake_compute": True}, {"fake_comm": True}):
        out = _shardmap_ffn(
            lambda e_, a, b, c, _kw=kw: a2a_expert_ffn(
                e_, a, b, c, "tp", **_kw).astype(e_.dtype),
            mesh)(ein, wg, wu, wd)
        assert out.shape == full.shape
        assert np.all(np.isfinite(np.asarray(out)))


def test_a2a_expert_ffn_rejects_flat_weights():
    from dlnetbench_tpu.ops.moe_dispatch import a2a_expert_ffn
    with pytest.raises(ValueError, match="E_local"):
        a2a_expert_ffn(jnp.zeros((4, 2, 8)), jnp.zeros((8, 16)),
                       jnp.zeros((8, 16)), jnp.zeros((16, 8)), "tp")


# --------------------------------------------------------- SPMD step
def test_spmd_moe_knob_validation():
    from dlnetbench_tpu.models import spmd
    with pytest.raises(ValueError, match="moe_a2a"):
        spmd.SpmdConfig(moe_a2a="ring").validate(1, 1, 2)
    with pytest.raises(ValueError, match="group_tokens"):
        spmd.SpmdConfig(moe_group_tokens=12).validate(1, 1, 2)
    with pytest.raises(ValueError, match="grouped"):
        spmd.SpmdConfig(moe_ffn_quant="int8").validate(1, 1, 2)
    with pytest.raises(ValueError, match="quant"):
        spmd.SpmdConfig(mlp_int8=True,
                        moe_ffn_impl="grouped").validate(1, 1, 2)


def test_spmd_moe_decomposed_parity(eight_devices):
    """The dryrun bar as a test: decomposed a2a (and the grouped FFN)
    produce the SAME training step as the monolithic einsum baseline
    under seeded grouped routing at finite capacity."""
    import dataclasses

    from dlnetbench_tpu.models import spmd
    cfg0 = spmd.SpmdConfig(batch=8, num_microbatches=2,
                           capacity_factor=1.0, moe_drop_seed=11,
                           moe_group_tokens=8)
    mesh, cfg0, step0, params, tokens = spmd.build(8, cfg0)
    p0, l0 = step0(params, tokens)
    for kw in (dict(moe_a2a="decomposed", moe_chunks=2),
               dict(moe_ffn_impl="grouped")):
        cfg_x = dataclasses.replace(cfg0, **kw)
        step_x = spmd.make_train_step(mesh, cfg_x)
        px, lx = step_x(params, tokens)
        assert abs(float(lx) - float(l0)) <= 1e-4 * max(
            1.0, abs(float(l0))), kw
        dmax = max(float(jnp.max(jnp.abs(
            a.astype(_F32) - b.astype(_F32))))
            for a, b in zip(jax.tree.leaves(px), jax.tree.leaves(p0)))
        assert dmax <= 1e-4, (kw, dmax)


def test_spmd_moe_decomposed_variants_run(eight_devices):
    """The A/B decomposition legs of the decomposed-MoE step compile
    and execute (the overlap-fraction metric's inputs)."""
    from dlnetbench_tpu.models import spmd
    cfg = spmd.SpmdConfig(batch=8, num_microbatches=2,
                          moe_a2a="decomposed")
    mesh, cfg, _, params, tokens = spmd.build(8, cfg)
    for variant in ("compute", "comm"):
        step = spmd.make_train_step(mesh, cfg, variant=variant)
        out = step(params, tokens)
        jax.block_until_ready(out)


# ------------------------------------------------- schedule parity
def test_a2a_elems_matches_native_schedule():
    """Native-vs-SPMD MoE schedule parity (the satellite): the twin
    helper restates core/schedule.moe_schedule's a2a arithmetic — the
    formula the native hybrid_3d_moe proxy declares and moves — and
    the JAX tier's ACTUAL dispatch buffer equals it at dp=1, cf=1."""
    from dlnetbench_tpu.core.model_card import load_model_card
    from dlnetbench_tpu.core.model_stats import load_model_stats
    from dlnetbench_tpu.core.schedule import moe_schedule
    stats = load_model_stats("mixtral_8x7b_16_bfloat16")
    card = load_model_card("mixtral_8x7b")
    for ep in (2, 4):
        sched = moe_schedule(stats, card, num_stages=4,
                             num_microbatches=2, num_expert_shards=ep)
        tokens_per_mb = (stats.batch_size // 2) * stats.seq_len
        assert sched.a2a_elems == moe.a2a_elems_per_rank(
            tokens_per_mb, card.top_k, stats.embed_dim, ep)
        # 2 a2as (dispatch+combine) per MoE layer per direction
        assert sched.a2a_per_direction == 2 * (card.num_layers // 4)


def test_spmd_dispatch_buffer_matches_twin():
    """The twin arithmetic against the REAL dispatch buffer: at dp=1
    and capacity_factor=1 the [E, C, d] buffer _moe_block hands the EP
    all-to-all holds exactly the native message's elements."""
    from dlnetbench_tpu.models import spmd
    cfg = spmd.SpmdConfig(batch=4, num_microbatches=2, seq_len=32,
                          num_experts=4, top_k=2, capacity_factor=1.0,
                          embed_dim=64)
    tp = 2
    t_loc = (cfg.batch // (1 * cfg.num_microbatches)) * \
        (cfg.seq_len // tp)
    x, wr = _routing_case(t=t_loc, d=cfg.embed_dim)
    xe, _, _ = moe.dispatch(x, wr, cfg.num_experts, cfg.top_k,
                            cfg.capacity_factor)
    assert xe.size == moe.spmd_a2a_elems(cfg, dp=1, tp=tp)
    # the native formula over this rank's token share (ep == tp, the
    # per-rank tokens are the global microbatch over dp*tp)
    assert xe.size == moe.a2a_elems_per_rank(
        t_loc * tp, cfg.top_k, cfg.embed_dim, tp)


def test_bandwidth_moe_columns():
    """A record carrying the moe global surfaces expert_imbalance /
    moe_drop_rate on its bandwidth rows; dense records get NaN."""
    pd = pytest.importorskip("pandas")  # noqa: F841
    from dlnetbench_tpu.analysis.bandwidth import (bandwidth_summary,
                                                   effective_bandwidth)
    rec = {
        "section": "t", "num_runs": 1,
        "global": {"model": "m", "comm_model": {
            "ep_comm_time": [{"kind": "alltoall", "group": 2,
                              "bytes": 1024}]},
            "moe": {"load_imbalance": 2.5, "drop_rate": 0.1}},
        "mesh": {"platform": "cpu"},
        "ranks": [{"rank": 0, "ep_comm_time": [100.0]}],
    }
    bw = effective_bandwidth([rec])
    assert float(bw["expert_imbalance"].iloc[0]) == 2.5
    assert float(bw["moe_drop_rate"].iloc[0]) == 0.1
    summ = bandwidth_summary([rec])
    assert "expert_imbalance" in summ.columns
    clean = dict(rec, **{"global": {"model": "m",
                                    "comm_model": rec["global"]
                                    ["comm_model"]}})
    bw2 = effective_bandwidth([clean])
    assert np.isnan(float(bw2["expert_imbalance"].iloc[0]))


def test_merge_moe_volatile():
    """The measured moe block is per-process state, never run
    identity: _comparable_global drops it, so differently-imbalanced
    hosts merge."""
    from dlnetbench_tpu.metrics.merge import _comparable_global
    g = {"model": "m", "moe": {"load_imbalance": 2.0},
         "moe_experts": 8}
    out = _comparable_global(g)
    assert "moe" not in out
    assert out["moe_experts"] == 8   # the KNOB stays comparable


@pytest.mark.tuning
def test_tune_cli_grouped_ffn_e2e(tmp_path, monkeypatch):
    """search -> commit -> consult -> hit on a tiny CPU shape, keys
    built by the same builders the site consults."""
    from dlnetbench_tpu import tuning
    from dlnetbench_tpu.tuning.__main__ import main
    tuning.reset(clear_env=True)
    try:
        rc = main(["tune", "--op", "grouped_ffn", "--db",
                   str(tmp_path), "--experts", "2", "--capacity", "8",
                   "--d", "16", "--n", "16", "--fmt", "none",
                   "--candidates", "4,8,8;8,16,16", "--k", "2",
                   "--rounds", "2"])
        assert rc == 0
        monkeypatch.setenv(tuning.params.ENV_DB_DIR, str(tmp_path))
        tuning.reset()
        x, wg, wu, wd = _gm_case(e=2, c=8, d=16, h=16)
        gm.grouped_ffn(x, wg, wu, wd)
        prov = tuning.provenance()
        key = tuning.params.grouped_ffn_key(2, 8, 16, 16, "none",
                                            x.dtype)
        assert prov["sites"][f"grouped_ffn|{key}"]["hit"]
    finally:
        tuning.reset(clear_env=True)
