"""Checkpoint/resume (utils/checkpoint.py): both backends (orbax when
installed, the pure-numpy npz fallback always), the crash-resume loop,
and the in-loop SnapshotCheckpointer the fault harness wires into
faulted runs.

No blanket orbax importorskip (ISSUE 7 satellite): the npz backend has
no dependency beyond jax/numpy, so the crash-resume contract is
exercised in tier-1 on machines without orbax; orbax-specific cases
skip individually."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.utils import checkpoint as ckpt


def _has_orbax() -> bool:
    try:
        import orbax.checkpoint  # noqa: F401
        return True
    except ImportError:
        return False


BACKENDS = ["npz"] + (["orbax"] if _has_orbax() else [])


@pytest.fixture(params=BACKENDS)
def backend(request):
    return request.param


def test_save_restore_roundtrip(tmp_path, backend):
    params = {"w": jnp.arange(8.0).reshape(2, 4), "b": jnp.ones((3,))}
    ckpt.save_checkpoint(tmp_path / "c", 5, params, backend=backend)
    assert ckpt.latest_step(tmp_path / "c") == 5
    template = jax.tree.map(jnp.zeros_like, params)
    restored, step = ckpt.restore_checkpoint(tmp_path / "c", template)
    assert step == 5
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_npz_roundtrips_bfloat16_bit_exact(tmp_path):
    """dtypes numpy cannot natively serialize (bfloat16 registers as a
    void kind) round-trip through the bit-pattern path."""
    params = {"w": jnp.linspace(-3, 3, 16, dtype=jnp.bfloat16)}
    ckpt.save_checkpoint(tmp_path / "c", 0, params, backend="npz")
    restored, _ = ckpt.restore_checkpoint(tmp_path / "c", params)
    assert restored["w"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(params["w"]).view(np.uint16),
        np.asarray(restored["w"]).view(np.uint16))


def test_dual_layout_dir_prefers_newest_across_backends(tmp_path):
    """A backend="auto" directory written under changing environments
    can hold BOTH layouts; latest/default-restore must take the max
    across them — preferring the npz files outright would silently
    resume from a stale step and supersede the real newest save."""
    pytest.importorskip("orbax.checkpoint")
    params = {"w": jnp.arange(4.0)}
    d = tmp_path / "c"
    ckpt.save_checkpoint(d, 2, params, backend="npz")
    newer = {"w": jnp.arange(4.0) + 10.0}
    ckpt.save_checkpoint(d, 4, newer, backend="orbax")
    assert ckpt.latest_step(d) == 4
    template = jax.tree.map(jnp.zeros_like, params)
    restored, step = ckpt.restore_checkpoint(d, template)
    assert step == 4
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(newer["w"]))
    # an explicit step still routes to the layout that holds it
    restored2, step2 = ckpt.restore_checkpoint(d, template, step=2)
    assert step2 == 2
    np.testing.assert_array_equal(np.asarray(restored2["w"]),
                                  np.asarray(params["w"]))


def test_latest_step_empty(tmp_path):
    assert ckpt.latest_step(tmp_path / "nope") is None
    with pytest.raises(FileNotFoundError):
        ckpt.restore_checkpoint(tmp_path / "nope2", {})


def test_unknown_backend_rejected(tmp_path):
    with pytest.raises(ValueError, match="unknown checkpoint backend"):
        ckpt.save_checkpoint(tmp_path / "c", 0, {"w": jnp.ones(2)},
                             backend="pickle")


def test_keep_limit_prunes_old_steps(tmp_path, backend):
    params = {"w": jnp.ones((2,))}
    for s in range(5):
        ckpt.save_checkpoint(tmp_path / "c", s, params, keep=2,
                             backend=backend)
    assert ckpt.latest_step(tmp_path / "c") == 4
    # steps 0..2 were pruned by keep=2 — only 3 and 4 remain on disk
    steps_on_disk = sorted(int(p.name.removesuffix(".npz"))
                           for p in (tmp_path / "c").iterdir()
                           if p.name.removesuffix(".npz").isdigit())
    assert steps_on_disk == [3, 4]
    with pytest.raises(FileNotFoundError, match="no checkpoint for step 0"):
        ckpt.restore_checkpoint(tmp_path / "c", params, step=0)


def test_crash_resume_loop_npz(tmp_path):
    """The crash-resume contract WITHOUT orbax: 4 steps straight vs
    2 -> 'crash' -> resume -> 2 more must agree exactly (the npz
    backend gathers to host and rebuilds, so equality is bit-exact
    on the same machine)."""
    def step(params, batch):
        p = params["w"] - 0.1 * batch
        return {"w": p}, float(jnp.sum(p))

    batch = jnp.ones((4,))
    p0 = {"w": jnp.zeros((4,))}
    p_ref, ref_losses = p0, []
    for _ in range(4):
        p_ref, loss = step(p_ref, batch)
        ref_losses.append(loss)

    d = tmp_path / "run"
    p1, losses1, start1 = ckpt.train_with_checkpointing(
        step, p0, batch, num_steps=2, ckpt_dir=d, save_every=1,
        backend="npz")
    assert start1 == 0 and len(losses1) == 2
    p2, losses2, start2 = ckpt.train_with_checkpointing(
        step, p0, batch, num_steps=4, ckpt_dir=d, save_every=1,
        backend="npz")
    assert start2 == 2 and len(losses2) == 2
    assert losses1 + losses2 == pytest.approx(ref_losses)
    np.testing.assert_array_equal(np.asarray(p_ref["w"]),
                                  np.asarray(p2["w"]))


@pytest.mark.slow
def test_spmd_crash_resume_matches_uninterrupted(eight_devices, tmp_path):
    """Run 4 steps straight vs. 2 steps -> 'crash' -> resume -> 2 more:
    the final sharded params must match (orbax: sharding-aware
    restore)."""
    pytest.importorskip("orbax.checkpoint")
    from dlnetbench_tpu.models import spmd

    cfg = spmd.SpmdConfig(capacity_factor=8.0)
    mesh, _, step, params0, tokens = spmd.build(8, cfg)
    shardings = spmd.param_shardings(mesh, cfg.sp_mode)

    # uninterrupted
    p_ref = params0
    ref_losses = []
    for _ in range(4):
        p_ref, loss = step(p_ref, tokens)
        ref_losses.append(float(loss))

    # interrupted: first process runs 2 steps with saves ...
    d = tmp_path / "run"
    p1, losses1, start1 = ckpt.train_with_checkpointing(
        step, params0, tokens, num_steps=2, ckpt_dir=d, save_every=1,
        shardings=shardings)
    assert start1 == 0 and len(losses1) == 2
    # ... "crash"; a fresh process resumes from the latest step
    p2, losses2, start2 = ckpt.train_with_checkpointing(
        step, params0, tokens, num_steps=4, ckpt_dir=d, save_every=1,
        shardings=shardings)
    assert start2 == 2 and len(losses2) == 2

    assert losses1 + losses2 == pytest.approx(ref_losses, rel=1e-5)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-5, atol=1e-6)
    # restored arrays keep their mesh sharding (no host-gather restore)
    leaf = p2["layers"]["wq"]
    assert len(leaf.sharding.device_set) > 1


# ---------------------------------------------- SnapshotCheckpointer
def _state():
    return {"w": jnp.arange(64.0).reshape(8, 8),
            "b": jnp.ones((16,), jnp.float32)}


def test_snapshot_periodic_saves_and_costs(tmp_path):
    sc = ckpt.SnapshotCheckpointer(tmp_path / "c", _state(), every=2,
                                   mode="stall", backend="npz")
    for step in range(6):
        sc.on_step(step)
    assert sc.saves == 3  # steps 1, 3, 5
    assert sc.last_saved_step == 5
    assert len(sc.checkpoint_ms) == 3
    assert sc.state_bytes == 64 * 4 + 16 * 4  # f32 leaves
    stats = sc.stats()
    assert stats["checkpoint_saves"] == 3
    assert stats["checkpoint_backend"] == "npz"
    assert stats["checkpoint_ms"] > 0
    # stall mode: the in-window cost IS the whole save
    assert stats["checkpoint_stall_ms"] >= stats["checkpoint_ms"] * 0.5


def test_snapshot_async_completion_gates_lost_work(tmp_path):
    """last_saved_step advances only when the durable write COMPLETES —
    lost_steps computed before the drain must not credit an in-flight
    save."""
    sc = ckpt.SnapshotCheckpointer(tmp_path / "c", _state(), every=1,
                                   mode="async", backend="npz")
    for step in range(4):
        sc.on_step(step)
    sc.wait()
    assert sc.last_saved_step == 3
    # steps 0..5 completed when step 6 failed; last save covered step 3
    assert sc.lost_steps(6) == 2
    # a failure right after the covered step loses nothing
    assert sc.lost_steps(4) == 0
    # restore-from-latest round-trips
    restored, step = ckpt.restore_checkpoint(tmp_path / "c", _state())
    assert step == 3


def test_snapshot_lost_steps_without_any_save(tmp_path):
    sc = ckpt.SnapshotCheckpointer(tmp_path / "c", _state(), every=8,
                                   mode="stall", backend="npz")
    assert sc.last_saved_step is None
    assert sc.lost_steps(5) == 5  # everything since the start is redone


def test_snapshot_drain_save_respects_grace_budget(tmp_path):
    """save_now refuses when the measured median save cost does not fit
    the grace window (a torn final save is worse than the last good
    periodic one), and saves when it does."""
    sc = ckpt.SnapshotCheckpointer(tmp_path / "c", _state(), every=1,
                                   mode="stall", backend="npz")
    sc.on_step(0)  # calibrate: one measured save
    assert not sc.save_now(3, budget_us=0.001)  # 1 ns: nothing fits
    assert sc.last_saved_step == 0
    assert sc.save_now(3, budget_us=60_000_000.0)  # 60 s: plenty
    assert sc.last_saved_step == 3


def test_latest_step_refuses_unreadable_orbax_layout(tmp_path,
                                                     monkeypatch):
    """An orbax-layout directory read on a box without orbax must NOT
    masquerade as checkpoint-free — a resume would silently restart
    from step 0 over real saves.  An empty directory stays an honest
    None."""
    d = tmp_path / "c"
    (d / "3").mkdir(parents=True)

    def no_orbax(*a, **k):
        raise ImportError("no orbax")

    monkeypatch.setattr(ckpt, "_manager", no_orbax)
    with pytest.raises(RuntimeError, match="orbax-layout"):
        ckpt.latest_step(d)
    e = tmp_path / "empty"
    e.mkdir()
    assert ckpt.latest_step(e) is None


def test_snapshot_drain_save_attempts_when_uncalibrated(tmp_path):
    """With no completed save to price from, the drain attempts anyway
    — refusing would waste the grace window exactly when everything
    since start is at stake — and lands when the realized cost fits."""
    sc = ckpt.SnapshotCheckpointer(tmp_path / "c", _state(), every=8,
                                   mode="stall", backend="npz")
    assert sc.last_saved_step is None
    assert sc.save_now(3, budget_us=60_000_000.0)
    assert sc.last_saved_step == 3


def test_snapshot_drain_save_cut_off_rolls_back(tmp_path):
    """A drain whose REALIZED cost overran the grace window was cut off
    by the eviction: atomic publication means the torn write never
    became a checkpoint, so it is unpublished and the last-saved
    pointer (and restore-from-latest) fall back to the previous save."""
    sc = ckpt.SnapshotCheckpointer(tmp_path / "c", _state(), every=1,
                                   mode="stall", backend="npz")
    # uncalibrated, 1 ns window: attempted, overran, rolled back to none
    assert not sc.save_now(3, budget_us=0.001)
    assert sc.last_saved_step is None
    assert ckpt.latest_step(tmp_path / "c") is None
    # with a prior periodic save: the cut-off drain falls back to it
    sc.on_step(0)
    assert sc.last_saved_step == 0
    sc.checkpoint_ms.clear()  # force the attempt past the up-front gate
    assert not sc.save_now(5, budget_us=0.001)
    assert sc.last_saved_step == 0
    assert ckpt.latest_step(tmp_path / "c") == 0


def test_snapshot_rejects_bad_config(tmp_path):
    with pytest.raises(ValueError, match="interval"):
        ckpt.SnapshotCheckpointer(tmp_path, _state(), every=0)
    with pytest.raises(ValueError, match="mode"):
        ckpt.SnapshotCheckpointer(tmp_path, _state(), every=1,
                                  mode="lazy")
