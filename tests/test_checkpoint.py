"""Checkpoint/resume (utils/checkpoint.py) incl. a simulated crash-resume
of the sharded SPMD training step on the 8-device mesh."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("orbax.checkpoint")

from dlnetbench_tpu.models import spmd
from dlnetbench_tpu.utils import checkpoint as ckpt


def test_save_restore_roundtrip(tmp_path):
    params = {"w": jnp.arange(8.0).reshape(2, 4), "b": jnp.ones((3,))}
    ckpt.save_checkpoint(tmp_path / "c", 5, params)
    assert ckpt.latest_step(tmp_path / "c") == 5
    template = jax.tree.map(jnp.zeros_like, params)
    restored, step = ckpt.restore_checkpoint(tmp_path / "c", template)
    assert step == 5
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_latest_step_empty(tmp_path):
    assert ckpt.latest_step(tmp_path / "nope") is None
    with pytest.raises(FileNotFoundError):
        ckpt.restore_checkpoint(tmp_path / "nope2", {})


def test_keep_limit_prunes_old_steps(tmp_path):
    params = {"w": jnp.ones((2,))}
    for s in range(5):
        ckpt.save_checkpoint(tmp_path / "c", s, params, keep=2)
    assert ckpt.latest_step(tmp_path / "c") == 4
    # steps 0..2 were pruned by keep=2 — only 3 and 4 remain on disk
    steps_on_disk = sorted(int(p.name) for p in (tmp_path / "c").iterdir()
                           if p.name.isdigit())
    assert steps_on_disk == [3, 4]
    with pytest.raises(FileNotFoundError, match="no checkpoint for step 0"):
        ckpt.restore_checkpoint(tmp_path / "c", params, step=0)


@pytest.mark.slow
def test_spmd_crash_resume_matches_uninterrupted(eight_devices, tmp_path):
    """Run 4 steps straight vs. 2 steps -> 'crash' -> resume -> 2 more:
    the final sharded params must match."""
    cfg = spmd.SpmdConfig(capacity_factor=8.0)
    mesh, _, step, params0, tokens = spmd.build(8, cfg)
    shardings = spmd.param_shardings(mesh, cfg.sp_mode)

    # uninterrupted
    p_ref = params0
    ref_losses = []
    for _ in range(4):
        p_ref, loss = step(p_ref, tokens)
        ref_losses.append(float(loss))

    # interrupted: first process runs 2 steps with saves ...
    d = tmp_path / "run"
    p1, losses1, start1 = ckpt.train_with_checkpointing(
        step, params0, tokens, num_steps=2, ckpt_dir=d, save_every=1,
        shardings=shardings)
    assert start1 == 0 and len(losses1) == 2
    # ... "crash"; a fresh process resumes from the latest step
    p2, losses2, start2 = ckpt.train_with_checkpointing(
        step, params0, tokens, num_steps=4, ckpt_dir=d, save_every=1,
        shardings=shardings)
    assert start2 == 2 and len(losses2) == 2

    assert losses1 + losses2 == pytest.approx(ref_losses, rel=1e-5)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-5, atol=1e-6)
    # restored arrays keep their mesh sharding (no host-gather restore)
    leaf = p2["layers"]["wq"]
    assert len(leaf.sharding.device_set) > 1
