"""Unit tests for the schedule algebra (buckets, shards, grids, message
sizes) — the reference enforces these only as runtime asserts
(SURVEY.md §4.1); here they are a tested pure library."""
import pytest

from dlnetbench_tpu.core import schedule
from dlnetbench_tpu.core.model_card import load_model_card
from dlnetbench_tpu.core.model_stats import ModelStats
from dlnetbench_tpu.stats_gen import generate_stats


def _stats(name="llama3_8b", batch=16):
    return generate_stats(load_model_card(name), batch, "bfloat16", "tpu_v5p")


def test_split_buckets_conserves_and_spreads():
    assert schedule.split_buckets(10, 3) == [4, 3, 3]
    assert sum(schedule.split_buckets(1234567, 7)) == 1234567
    assert schedule.split_buckets(5, 8) == [1, 1, 1, 1, 1, 0, 0, 0]
    with pytest.raises(ValueError):
        schedule.split_buckets(10, 0)


def test_dp_schedule():
    s = _stats()
    dp = schedule.dp_schedule(s, 4)
    assert sum(dp.bucket_sizes) == s.model_size
    assert dp.bwd_us_per_bucket == pytest.approx(s.bwd_us / 4)
    assert dp.bucket_bytes[0] == int(dp.bucket_sizes[0] * 2.0)


def test_fsdp_schedule_padding():
    s = _stats()
    f = schedule.fsdp_schedule(s, num_units=6, world_size=8)
    assert f.sharding_factor == 8 and f.num_replicas == 1
    # padded: every rank's shard covers the largest unit
    assert f.shard_size * f.sharding_factor >= max(f.unit_sizes)
    f2 = schedule.fsdp_schedule(s, num_units=6, world_size=8, sharding_factor=4)
    assert f2.num_replicas == 2
    with pytest.raises(ValueError):
        schedule.fsdp_schedule(s, num_units=6, world_size=6, sharding_factor=4)


def test_grid3d_coords_roundtrip_and_colors():
    g = schedule.Grid3D(dp=2, pp=4, tp=2)
    assert g.world_size == 16
    for rank in range(g.world_size):
        assert g.rank(*g.coords(rank)) == rank
    # ranks sharing a tp color must differ only in tp coordinate
    for r1 in range(16):
        for r2 in range(16):
            if r1 != r2 and g.tp_color(r1) == g.tp_color(r2):
                d1, p1, _ = g.coords(r1)
                d2, p2, _ = g.coords(r2)
                assert (d1, p1) == (d2, p2)
    # tp is fastest-varying (reference hybrid_3d.cpp:283-285)
    assert g.coords(1) == (0, 0, 1)
    assert g.coords(2) == (0, 1, 0)


def test_pipeline_schedule():
    s = _stats()
    card = load_model_card("llama3_8b")
    p = schedule.pipeline_schedule(s, card, num_stages=4, num_microbatches=8,
                                   dp=2)
    assert p.layers_per_stage == 8
    assert p.pipe_msg_elems == s.seq_len * s.embed_dim * (16 // 8)
    assert p.dp_sync_elems == s.model_size // 4
    assert p.tp_msg_elems == 0
    p3 = schedule.pipeline_schedule(s, card, num_stages=4, num_microbatches=8,
                                    dp=2, tp=2)
    # pipe message NOT divided by tp (reference hybrid_3d.cpp:319); only the
    # TP allreduce is (hybrid_3d.cpp:322)
    assert p3.pipe_msg_elems == p.pipe_msg_elems
    assert p3.tp_msg_elems == p.pipe_msg_elems // 2
    assert p3.dp_sync_elems == s.model_size // 8
    assert p3.fwd_us_per_stage_mb == pytest.approx(p.fwd_us_per_stage_mb / 2)


def test_pipeline_divisibility_errors():
    s = _stats()
    card = load_model_card("llama3_8b")  # 32 layers
    with pytest.raises(ValueError, match="layers"):
        schedule.pipeline_schedule(s, card, num_stages=5, num_microbatches=8)
    with pytest.raises(ValueError, match="microbatches"):
        schedule.pipeline_schedule(s, card, num_stages=4, num_microbatches=5)


def test_moe_schedule():
    s = _stats("mixtral_8x7b")
    card = load_model_card("mixtral_8x7b")
    m = schedule.moe_schedule(s, card, num_stages=4, num_microbatches=4,
                              num_expert_shards=4, dp=2)
    tokens_per_mb = (16 // 4) * s.seq_len
    assert m.a2a_elems == tokens_per_mb * 2 * s.embed_dim // 4
    assert m.a2a_per_direction == 2 * (32 // 4)
    assert m.nonexpert_sync_elems == s.non_expert_size // 4
    # level-2 sync covers EXPERT params only (reference hybrid_3d_moe.cpp:278,362)
    assert m.expert_sync_elems == (s.model_size - s.non_expert_size) // (4 * 4)
    # EP does not divide compute or pipe message (hybrid_3d_moe.cpp:339-347)
    assert m.pipe.fwd_us_per_stage_mb == pytest.approx(s.fwd_us / (4 * 4))
    assert m.pipe.pipe_msg_elems == s.seq_len * s.embed_dim * (16 // 4)
    assert m.grid.tp == 4  # EP takes the fastest-varying axis
    with pytest.raises(ValueError, match="experts"):
        schedule.moe_schedule(s, card, num_stages=4, num_microbatches=4,
                              num_expert_shards=3)


@pytest.mark.parametrize("S,M", [(1, 4), (2, 4), (4, 4), (4, 8), (8, 8)])
def test_zb_tables_valid_and_zero_bubble(S, M):
    """ZB-H1 greedy tables: every dependency lands strictly after the
    tick that produced it, every stage runs exactly M of each op, and the
    makespan hits the analytic 3M + (S-1) unit ticks — versus
    3(M + S - 1) for 1F1B with a 2-unit backward (the zero-bubble win)."""
    tb = schedule.zb_tables(S, M)
    f_t = {s: [] for s in range(S)}
    b_t = {s: [] for s in range(S)}
    w_count = {s: 0 for s in range(S)}
    for t in range(tb.ticks):
        for s in tb.f_stages[t]:
            f_t[s].append(t)
        for s in tb.b_stages[t]:
            b_t[s].append(t)
        for s in tb.w_stages[t]:
            w_count[s] += 1
        # a stage runs at most one unit op per tick
        ops = tb.f_stages[t] + tb.b_stages[t] + tb.w_stages[t]
        assert len(ops) == len(set(ops))
    for s in range(S):
        assert len(f_t[s]) == M and len(b_t[s]) == M and w_count[s] == M
    for s in range(1, S):      # F(k)@s strictly after F(k)@(s-1)
        for k in range(M):
            assert f_t[s][k] > f_t[s - 1][k]
    for s in range(S - 1):     # B(k)@s strictly after B(k)@(s+1)
        for k in range(M):
            assert b_t[s][k] > b_t[s + 1][k]
    assert tb.ticks == 3 * M + (S - 1)
    # hop tables exclude the edge stages that have no neighbor
    assert all(S - 1 not in tick for tick in tb.f_senders(S))
    assert all(0 not in tick for tick in tb.b_senders())


def test_sequence_schedule():
    s = _stats()
    card = load_model_card("llama3_8b")
    q = schedule.sequence_schedule(s, card, sp=8)
    assert q.seq_per_rank == card.seq_len // 8
    assert q.kv_block_elems == 2 * 16 * (card.seq_len // 8) * card.kv_dim
    assert q.num_ring_hops == 7
    with pytest.raises(ValueError):
        schedule.sequence_schedule(s, card, sp=3)
