"""fp8 (e4m3) MLP compute path (ops/fp8.py, VERDICT r2 #7)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from dlnetbench_tpu.ops.fp8 import _quantize, fp8_dot, swiglu_fp8


def test_quantize_roundtrip_scale():
    x = jax.random.normal(jax.random.key(0), (64, 32), jnp.bfloat16) * 3.0
    xq, scale = _quantize(x)
    assert xq.dtype == jnp.float8_e4m3fn
    back = xq.astype(jnp.float32) * scale
    # e4m3 carries ~2 decimal digits; per-tensor scaling keeps the max
    # at the format's ceiling so relative error stays small
    err = jnp.max(jnp.abs(back - x.astype(jnp.float32)))
    assert err <= 0.05 * jnp.max(jnp.abs(x.astype(jnp.float32)))


def test_fp8_dot_close_to_bf16():
    kx, kw = jax.random.split(jax.random.key(1))
    x = jax.random.normal(kx, (128, 256), jnp.bfloat16)
    w = jax.random.normal(kw, (256, 64), jnp.bfloat16) * 0.05
    got = fp8_dot(x, w)
    want = jnp.dot(x.astype(jnp.float32), w.astype(jnp.float32))
    rel = (jnp.linalg.norm(got.astype(jnp.float32) - want)
           / jnp.linalg.norm(want))
    assert rel < 0.05, f"fp8 dot relative error {rel}"
    assert got.dtype == x.dtype


def test_fp8_dot_straight_through_grads():
    """Backward is the master-dtype identity-quantization gradient: same
    values as the bf16 dot's gradients (exactly — bwd never quantizes)."""
    kx, kw, kg = jax.random.split(jax.random.key(2), 3)
    x = jax.random.normal(kx, (4, 8, 16), jnp.bfloat16)
    w = jax.random.normal(kw, (16, 12), jnp.bfloat16) * 0.1
    cot = jax.random.normal(kg, (4, 8, 12), jnp.bfloat16)

    def f_fp8(x, w):
        return jnp.sum(fp8_dot(x, w).astype(jnp.float32) *
                       cot.astype(jnp.float32))

    def f_bf16(x, w):
        return jnp.sum(jnp.dot(x, w, preferred_element_type=jnp.float32) *
                       cot.astype(jnp.float32))

    gx8, gw8 = jax.grad(f_fp8, argnums=(0, 1))(x, w)
    gx, gw = jax.grad(f_bf16, argnums=(0, 1))(x, w)
    assert gx8.shape == x.shape and gw8.shape == w.shape
    assert jnp.allclose(gx8.astype(jnp.float32), gx.astype(jnp.float32),
                        atol=1e-2, rtol=1e-2)
    assert jnp.allclose(gw8.astype(jnp.float32), gw.astype(jnp.float32),
                        atol=1e-2, rtol=1e-2)


@pytest.mark.slow  # ~70s e2e train step; dot/VJP parity rides the fast lane
def test_transformer_fp8_mlp_trains():
    """mlp_dtype='float8' plumbs through the dense SwiGLU stack: a tiny
    train step runs, loss is finite, grads flow into the MLP weights."""
    from dlnetbench_tpu.core.model_card import load_model_card
    from dlnetbench_tpu.models import transformer as tfm

    card = load_model_card("llama3_8b")
    cfg = tfm.TransformerConfig.from_card(card, seq_len=64, num_layers=2,
                                          vocab_size=512)
    import dataclasses
    cfg = dataclasses.replace(cfg, mlp_dtype="float8")
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, cfg.seq_len + 1),
                                0, cfg.vocab_size)
    step = jax.jit(lambda p, t: jax.value_and_grad(tfm.loss_fn)(p, t, cfg))
    loss, g = step(params, tokens)
    assert jnp.isfinite(loss)
    gmax = jnp.max(jnp.abs(g["layers"]["w_gate"].astype(jnp.float32)))
    assert gmax > 0, "no gradient reached the fp8 MLP weights"


def test_fp8_config_validation():
    from dlnetbench_tpu.core.model_card import load_model_card
    from dlnetbench_tpu.models import transformer as tfm
    import dataclasses

    card = load_model_card("mixtral_8x7b")
    cfg = tfm.TransformerConfig.from_card(card, seq_len=64, num_layers=2)
    with pytest.raises(ValueError, match="dense SwiGLU"):
        dataclasses.replace(cfg, mlp_dtype="float8")
    with pytest.raises(ValueError, match="mlp_dtype"):
        dataclasses.replace(cfg, mlp_dtype="fp8")
