"""Semantic validation of the native PJRT backend's generated programs.

The native tier compiles one StableHLO module per (collective, dtype,
shape, groups) (native/include/dlnb/stablehlo_gen.hpp).  These tests have
`pjrt_probe --emit` produce each program and then compile AND EXECUTE it
on a multi-device CPU PJRT client — the same replica-mode execution model
a TPU plugin uses — checking the collective math end to end.  This is the
device-free proof that the native backend's programs are correct XLA.

Also cross-checks the hand-encoded CompileOptionsProto wire bytes by
feeding them to the real compile path.
"""
from __future__ import annotations

import os
import shutil
import subprocess
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.skipif(
    shutil.which("cmake") is None, reason="cmake not available")


@pytest.fixture(scope="session")
def probe(native_devices):
    from dlnetbench_tpu.utils.native_build import native_bin
    return native_bin(REPO) / "pjrt_probe"


@pytest.fixture(scope="session")
def native_devices():
    """8 CPU devices (conftest sets the XLA flags before jax import)."""
    import jax
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    return devs


def emit(probe, op, **kw):
    cmd = [str(probe), "--emit", op]
    for k, v in kw.items():
        cmd += [f"--{k}", str(v)]
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    return out.stdout


def run_module(mlir, num_replicas, per_device_inputs):
    import jax
    from jax._src import xla_bridge
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from jaxlib import _jax

    client = xla_bridge.get_backend("cpu")
    devs = client.local_devices()[:num_replicas]
    opts = _jax.CompileOptions()
    opts.num_replicas = num_replicas
    exe = client.compile_and_load(mlir, devs, opts)

    jdevs = jax.devices()[:num_replicas]
    mesh = Mesh(np.array(jdevs), ("x",))
    sh = NamedSharding(mesh, P("x"))
    shards = [jax.device_put(v, d) for v, d in zip(per_device_inputs, jdevs)]
    n = per_device_inputs[0].shape[0]
    arr = jax.make_array_from_single_device_arrays(
        (n * num_replicas,), sh, shards)
    res = exe.execute_sharded([arr])
    out = res.consume_with_handlers([lambda bufs: [np.asarray(b)
                                                   for b in bufs]])
    return out[0]


def test_allreduce_world(probe):
    mlir = emit(probe, "all_reduce", count=8, replicas=4)
    outs = run_module(mlir, 4,
                      [np.full(8, i + 1, np.float32) for i in range(4)])
    for o in outs:
        np.testing.assert_allclose(o, 10.0)


def test_allreduce_split_groups(probe):
    """One module, two replica groups — the comm-split idiom."""
    mlir = emit(probe, "all_reduce", count=4, replicas=4, groups="0,1;2,3")
    outs = run_module(mlir, 4,
                      [np.full(4, i + 1, np.float32) for i in range(4)])
    np.testing.assert_allclose(outs[0], 3.0)   # 1+2
    np.testing.assert_allclose(outs[2], 7.0)   # 3+4


def test_allgather(probe):
    mlir = emit(probe, "all_gather", count=4, replicas=4)
    outs = run_module(mlir, 4,
                      [np.full(4, float(i), np.float32) for i in range(4)])
    np.testing.assert_allclose(outs[1][::4], [0.0, 1.0, 2.0, 3.0])


def test_reduce_scatter(probe):
    mlir = emit(probe, "reduce_scatter", count=16, replicas=4)
    outs = run_module(mlir, 4,
                      [np.arange(16, dtype=np.float32) for _ in range(4)])
    # device d gets sum over replicas of block d: 4 * arange-block
    np.testing.assert_allclose(outs[2], 4 * np.arange(16)[8:12])


def test_all_to_all(probe):
    mlir = emit(probe, "all_to_all", count=16, replicas=4)
    outs = run_module(
        mlir, 4,
        [np.arange(16, dtype=np.float32) + 100 * i for i in range(4)])
    np.testing.assert_allclose(outs[1][::4], [4.0, 104.0, 204.0, 304.0])


def test_ring_permute(probe):
    mlir = emit(probe, "collective_permute", count=4, replicas=4,
                pairs="0>1;1>2;2>3;3>0")
    outs = run_module(mlir, 4,
                      [np.full(4, float(i), np.float32) for i in range(4)])
    assert [o[0] for o in outs] == [3.0, 0.0, 1.0, 2.0]


def test_bf16_allreduce(probe):
    import jax.numpy as jnp
    mlir = emit(probe, "all_reduce", count=8, replicas=4, dtype="bfloat16")
    outs = run_module(mlir, 4,
                      [jnp.full(8, i + 1, jnp.bfloat16) for i in range(4)])
    assert float(outs[0][0]) == 10.0


def test_burn_module_semantics(probe):
    """The device-burn module (fabric.burn's compiled kernel) must compute
    the documented chain state <- tanh(state @ state / W) for exactly the
    runtime trip count — validated by XLA's real execution of the emitted
    program, like every collective module above."""
    import jax

    W = 8
    mlir = emit(probe, "burn", count=W)
    dev = jax.devices("cpu")[0]
    from jax._src import xla_bridge
    from jaxlib import _jax

    client = xla_bridge.get_backend("cpu")
    opts = _jax.CompileOptions()
    opts.num_replicas = 1
    exe = client.compile_and_load(mlir, client.local_devices()[:1], opts)

    x0 = np.linspace(-0.5, 0.5, W * W).astype(np.float32).reshape(W, W)
    for iters in (0, 3):
        res = exe.execute_sharded([
            jax.device_put(np.int32(iters), dev),
            jax.device_put(x0, dev),
        ])
        out = res.consume_with_handlers(
            [lambda bufs: [np.asarray(b) for b in bufs]])[0][0]
        ref = x0
        for _ in range(iters):
            ref = np.tanh(ref @ ref / W)
        np.testing.assert_allclose(out, ref, atol=1e-5)


def test_dp_pjrt_records_compute_mode(probe):
    """dp --backend pjrt must record which compute simulation ran
    (device_burn on a real plugin, host_sleep on the host executor)."""
    import json

    dp = probe.parent / "dp"
    out = subprocess.run(
        [str(dp), "--model", "gpt2_l_16_bfloat16", "--world", "2",
         "--backend", "pjrt", "--runs", "1", "--warmup", "1",
         "--time_scale", "1e-4", "--size_scale", "1e-5",
         "--num_buckets", "2", "--no_topology",
         "--base_path", str(REPO)],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "DLNB_PJRT_EXECUTOR": "host"},
    )
    assert out.returncode == 0, out.stderr
    rec = json.loads(out.stdout)
    assert rec["global"]["compute_mode"] == "host_sleep"


def test_options_proto_matches_real_parser(probe):
    """Feed the C++-emitted CompileOptionsProto bytes to XLA's REAL proto
    parser and confirm the fields land where the hand-encoder intended —
    this catches any drift between our field numbers and
    xla/pjrt/proto/compile_options.proto."""
    from jaxlib import _jax

    out = subprocess.run([str(probe), "--options_proto", "3"],
                         capture_output=True, text=True, timeout=60)
    proto = bytes.fromhex(out.stdout.strip())
    opts = _jax.CompileOptions.ParseFromString(proto)
    assert opts.num_replicas == 3
    assert opts.num_partitions == 1


def test_probe_reports_cleanly(probe):
    """Probe mode must exit 0 and emit valid JSON whether or not a TPU
    plugin is usable in this environment."""
    import json
    out = subprocess.run([str(probe)], capture_output=True, text=True,
                         timeout=300)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert "available" in rep
    if rep["available"]:
        assert rep.get("allreduce_ok") is True
        assert rep.get("cache_hits", 0) >= 1  # second run hit the cache
