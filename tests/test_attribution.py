"""Bottleneck attribution engine (analysis/attribution.py): the join of
compiled cost analysis, roofline peaks, measured decomposition timers and
device-trace occupancy into one {fractions, bound} verdict per bench
line / run record — plus the ``explain`` CLI that turns the committed
fp8 artifact's 0.40-of-peak reading into a named binding resource
(ROADMAP item 4's evidence gap, measured).
"""
from __future__ import annotations

import io
import json
from pathlib import Path

import pytest

from dlnetbench_tpu.analysis import attribution as attr_mod
from dlnetbench_tpu.core.hardware import HARDWARE, hw_key_for_device_kind

DATA = Path(__file__).parent / "data"
REPO = Path(__file__).parent.parent

V5E = HARDWARE["tpu_v5e"]


def _assert_fractions(block):
    """The acceptance contract: fractions sum to 1 +/- 0.05, every share
    in [0, 1], and the verdict is one of the published vocabulary."""
    fr = block["fractions"]
    assert set(fr) == set(attr_mod.RESOURCES)
    assert sum(fr.values()) == pytest.approx(1.0, abs=0.05)
    for v in fr.values():
        assert 0.0 <= v <= 1.0
    assert block["bound"] in attr_mod.BOUNDS


# ---------------------------------------------------------------------
# attribute_kernel: the bench-line FLOP/byte-model pathway


def test_kernel_near_roofline_is_mxu_bound():
    flops = 1e12
    t = flops / V5E.peak("bfloat16") / 0.9   # 0.9 of peak
    block = attr_mod.attribute_kernel(t, flops, 1e6, V5E, "bfloat16")
    _assert_fractions(block)
    assert block["bound"] == "mxu"
    assert block["fractions"]["compute"] == pytest.approx(0.9, abs=0.01)
    assert block["achieved"]["mxu"]["frac"] == pytest.approx(0.9, abs=0.01)
    assert block["inputs"]["compute_basis"] == "roofline"


def test_kernel_far_from_roofline_is_host_bound():
    flops = 1e12
    t = flops / V5E.peak("bfloat16") / 0.3   # 0.3 of peak, no HBM model
    block = attr_mod.attribute_kernel(t, flops, 1e6, V5E, "bfloat16")
    _assert_fractions(block)
    assert block["bound"] == "host"
    assert block["fractions"]["host"] == pytest.approx(0.7, abs=0.01)


def test_kernel_memory_bound_is_hbm():
    # byte-heavy, FLOP-light: HBM busy time dominates the MXU time
    nbytes = 1e9
    t = nbytes / V5E.hbm_bandwidth / 0.9     # 0.9 of HBM peak
    block = attr_mod.attribute_kernel(t, 1e6, nbytes, V5E, "bfloat16")
    _assert_fractions(block)
    assert block["bound"] == "hbm"
    assert block["achieved"]["hbm"]["frac"] == pytest.approx(0.9, abs=0.01)


def test_kernel_overexplained_model_rescales_not_oversums():
    # an above-peak short-chain reading: modeled busy time exceeds the
    # measurement — shares rescale to sum 1, host goes to 0
    flops = 1e12
    t = flops / V5E.peak("bfloat16") / 1.3   # "1.3 of peak"
    block = attr_mod.attribute_kernel(t, flops, 1e6, V5E, "bfloat16")
    _assert_fractions(block)
    assert block["fractions"]["host"] == 0.0


def test_kernel_faulted_verdict_and_unpriceable_dtype():
    flops = 1e12
    t = flops / V5E.peak("bfloat16")
    block = attr_mod.attribute_kernel(t, flops, 1e6, V5E, "bfloat16",
                                      faulted=True)
    assert block["bound"] == "faulted"
    # no peak for the dtype on this chip -> no block, never a guess
    assert attr_mod.attribute_kernel(t, flops, 1e6, V5E, "nvfp4") is None


# ---------------------------------------------------------------------
# transport semantics


def test_comm_resource_names_the_wire():
    assert attr_mod.comm_resource("ici") == "ici"
    assert attr_mod.comm_resource("ici+dcn") == "dcn"  # DCN leg binds
    assert attr_mod.comm_resource("tcp:ethernet") == "dcn"
    assert attr_mod.comm_resource("tcp:loopback") == "dcn"
    assert attr_mod.comm_resource("shm") == "host"
    assert attr_mod.comm_resource("virtual-host") == "host"
    assert attr_mod.comm_resource(None) == "host"


def test_transport_peak_bytes():
    assert attr_mod.transport_peak_bytes_s("ici", V5E) == V5E.ici_bandwidth
    assert attr_mod.transport_peak_bytes_s("tcp:ethernet", V5E) \
        == attr_mod.DCN_PEAK_BYTES_S
    # no physical wire -> no peak to compare against
    assert attr_mod.transport_peak_bytes_s("shm", V5E) is None
    assert attr_mod.transport_peak_bytes_s("ici", None) is None


def test_hw_key_for_device_kind():
    assert hw_key_for_device_kind("TPU v5 lite") == "tpu_v5e"
    assert hw_key_for_device_kind("TPU v5p") == "tpu_v5p"
    assert hw_key_for_device_kind("TPU v4") == "tpu_v4"
    assert hw_key_for_device_kind("TPU v6 lite") == "tpu_v6e"
    # a cpu/host mesh has no roofline preset
    assert hw_key_for_device_kind("cpu") is None
    assert hw_key_for_device_kind(None) is None


# ---------------------------------------------------------------------
# attribute_decomposition: measured A/B legs, no FLOP model


def test_decomposition_compute_is_host_on_virtual_mesh():
    # loopback compute time must never read as silicon
    block = attr_mod.attribute_decomposition(
        [1.0, 1.0, 1.0], [0.9, 0.9, 0.9], [0.2, 0.2, 0.2])
    _assert_fractions(block)
    assert block["bound"] == "host"
    assert block["inputs"]["compute_basis"] == "measured"
    # exposed comm = median(full - compute), not the wire-only leg
    assert block["fractions"]["comm_exposed"] == pytest.approx(0.1,
                                                               abs=0.01)


def test_decomposition_on_accelerator_is_mxu():
    block = attr_mod.attribute_decomposition(
        [1.0, 1.0], [0.9, 0.9], [0.2, 0.2], transport="ici",
        on_accelerator=True)
    assert block["bound"] == "mxu"


def test_decomposition_comm_exposed_names_transport():
    block = attr_mod.attribute_decomposition(
        [1.0, 1.0], [0.2, 0.2], [0.9, 0.9], transport="ici",
        on_accelerator=True)
    _assert_fractions(block)
    assert block["bound"] == "ici"


def test_straggler_block_is_faulted():
    block = attr_mod.straggler_block(10.0, 13.0, 3.0)
    _assert_fractions(block)
    assert block["bound"] == "faulted"
    assert block["inputs"]["injected_us"] == pytest.approx(3000.0)


# ---------------------------------------------------------------------
# attribute_record: the run-record pathway over committed fixtures


def _load_record(name: str) -> dict:
    return json.loads((DATA / name).read_text().strip().splitlines()[0])


def test_committed_attrib_fixture_roundtrip():
    """The committed real-run fixture: its stamped block satisfies the
    acceptance contract AND recomputation from its raw timers agrees on
    the verdict (the block is derived data, not hand-written)."""
    rec = _load_record("record_attrib.jsonl")
    stamped = rec["global"]["attribution"]
    _assert_fractions(stamped)
    recomputed = attr_mod.attribute_record(rec)
    _assert_fractions(recomputed)
    assert recomputed["bound"] == stamped["bound"]
    # a virtual CPU mesh: loopback bytes are host memory, never fabric
    assert stamped["bound"] == "host"
    assert stamped["inputs"]["compute_basis"] == "measured"


def test_faulted_record_gets_faulted_verdict():
    rec = _load_record("record_faulted.jsonl")
    block = attr_mod.attribute_record(rec)
    if block is not None:
        assert block["bound"] == "faulted"
        _assert_fractions(block)
    else:  # a fixture without runtime samples can't be attributed
        assert not any(r.get("runtimes") for r in rec.get("ranks", []))


def test_record_without_runtimes_returns_none():
    assert attr_mod.attribute_record({"global": {}, "ranks": []}) is None
    assert attr_mod.attribute_record(
        {"global": {}, "ranks": [{"rank": 0}]}) is None


def test_overlap_fixture_record_attributes():
    rec = _load_record("record_overlap.jsonl")
    block = attr_mod.attribute_record(rec)
    assert block is not None
    _assert_fractions(block)


# ---------------------------------------------------------------------
# attribute_line: the legacy bench-line pathway (pre-stamping artifacts)


def test_stamped_block_wins_over_derivation():
    sentinel_block = {"fractions": {}, "bound": "mxu"}
    assert attr_mod.attribute_line(
        {"metric": "m", "unit": "ms", "value": 1.0,
         "attribution": sentinel_block}) is sentinel_block
    # a stamped NON-ms line (the straggler amplification ratio) has no
    # wall-clock for the explain report — never rendered
    assert attr_mod.attribute_line(
        {"metric": "m", "unit": "x (ratio)", "value": 1.03,
         "attribution": sentinel_block}) is None


def test_legacy_fp8_line_derives_host_verdict():
    # the BENCH_r05 shape: 0.40-of-peak with vs_baseline ~= mxu frac
    # (no HBM exposure priced) -> 60% unexplained -> host
    line = {"metric": "fp8(e4m3) mlp-projection matmul, 12288 tok "
                      "D=4096, TPU v5 lite (tpu_v5e, fp8 peak 394 TF/s)",
            "value": 2.6, "unit": "ms",
            "tflops_achieved": 159.0, "vs_baseline": 0.4037}
    block = attr_mod.attribute_line(line)
    _assert_fractions(block)
    assert block["bound"] == "host"
    assert block["fractions"]["host"] > 0.5


def test_unparseable_line_returns_none():
    assert attr_mod.attribute_line({"metric": "no hw key here",
                                    "value": 1.0, "unit": "ms"}) is None
    assert attr_mod.attribute_line({"metric": "x (tpu_v5e)",
                                    "unit": "GB/s", "value": 1.0}) is None


# ---------------------------------------------------------------------
# the explain CLI on the COMMITTED fp8 artifact: ROADMAP item 4's
# evidence gap as a measured verdict


def test_explain_bench_r05_names_the_fp8_binding_resource():
    out = io.StringIO()
    rc = attr_mod.explain(REPO / "BENCH_r05.json", out=out)
    assert rc == 0
    text = out.getvalue()
    blocks = text.split("\n- ")
    chain = [b for b in blocks if b.startswith("fp8(e4m3) swiglu chain")]
    assert len(chain) == 1, text
    # the committed 0.40-of-peak diagnosis, with the binding resource
    # NAMED: host/dispatch overhead, not fp8 silicon
    assert "bound: HOST" in chain[0]
    assert "0.38 of roofline" in chain[0]
    assert "host/dispatch/residency overhead binds this run" in chain[0]
    # the headline train step is the control: compute-bound
    headline = [b for b in blocks if b.startswith("llama3_8b-shaped")]
    assert any("bound: MXU" in b for b in headline)


def test_explain_jsonl_and_cli_main(tmp_path):
    p = tmp_path / "records.jsonl"
    p.write_text(json.dumps(_load_record("record_attrib.jsonl")) + "\n")
    out = io.StringIO()
    assert attr_mod.explain(p, out=out) == 0
    assert "bound: HOST" in out.getvalue()
    assert attr_mod.main(["explain", str(p)]) == 0


def test_explain_empty_artifact_fails(tmp_path):
    p = tmp_path / "empty.jsonl"
    p.write_text(json.dumps({"metric": "x", "unit": "GB/s",
                             "value": 1.0}) + "\n")
    assert attr_mod.explain(p, out=io.StringIO()) == 1


# ---------------------------------------------------------------------
# fixture round-trip: parser -> merge -> bandwidth columns


def test_attrib_fixture_parser_merge_bandwidth_roundtrip():
    from dlnetbench_tpu.analysis.bandwidth import (bandwidth_summary,
                                                   effective_bandwidth)
    from dlnetbench_tpu.metrics.merge import merge_records
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe,
                                               validate_record)

    records = load_records(DATA / "record_attrib.jsonl")
    assert len(records) == 1
    rec = records[0]
    validate_record(rec)
    bound = rec["global"]["attribution"]["bound"]

    # parser: the verdict is a groupby-grade column
    df = records_to_dataframe(records)
    assert (df["attr_bound"] == bound).all()

    # merge (single-process: identity modulo recomputed attribution)
    merged = merge_records(records)
    validate_record(merged)
    _assert_fractions(merged["global"]["attribution"])
    assert merged["global"]["attribution"]["bound"] == bound

    # bandwidth: verdict + fractions ride every row and the summary
    bw = effective_bandwidth([merged])
    for col in ("attr_bound", "attr_compute", "attr_hbm", "attr_comm",
                "attr_host"):
        assert col in bw.columns
    assert (bw["attr_bound"] == bound).all()
    fr = merged["global"]["attribution"]["fractions"]
    assert bw["attr_compute"].iloc[0] == pytest.approx(fr["compute"])
    summary = bandwidth_summary([merged])
    assert (summary["attr_bound"] == bound).all()


def test_records_without_attribution_still_flow():
    """v1 and pre-attribution v2 records keep parsing and the bandwidth
    columns degrade to NaN/'n/a', never a KeyError."""
    import math

    from dlnetbench_tpu.analysis.bandwidth import effective_bandwidth
    from dlnetbench_tpu.metrics.parser import (load_records,
                                               records_to_dataframe)

    v1 = load_records(DATA / "record_v1.jsonl")
    df = records_to_dataframe(v1)
    assert "attr_bound" not in df.columns  # no column invented
    bw = effective_bandwidth(v1)
    assert (bw["attr_bound"] == "n/a").all()
    assert all(math.isnan(v) for v in bw["attr_compute"])


def test_mixed_version_merge_still_refused():
    from dlnetbench_tpu.metrics.merge import merge_records

    v2 = _load_record("record_attrib.jsonl")
    v2["global"]["num_processes"] = 2
    for i, row in enumerate(v2["ranks"]):
        row["process_index"] = i
        row["hostname"] = f"host{i}"
    v1 = json.loads(json.dumps(v2))
    v1["version"] = 1
    v1["process"] = 1
    with pytest.raises(ValueError, match="schema version"):
        merge_records([v2, v1])


def test_merge_recomputes_attribution_over_pooled_rows():
    """Two processes whose records each attributed only their own
    clocks: the merged record's block is recomputed over the pooled
    rows (and differing per-process blocks must not abort the merge as
    a global conflict)."""
    from dlnetbench_tpu.metrics.merge import merge_records

    def proc_rec(p):
        rec = _load_record("record_attrib.jsonl")
        rec["process"] = p
        rec["global"]["num_processes"] = 2
        rec["global"]["attribution"] = dict(
            rec["global"]["attribution"],
            inputs={"time_us": 1.0 + p})  # per-process: differs
        for i, row in enumerate(rec["ranks"]):
            row["process_index"] = i
            row["hostname"] = f"host{i}"
        return rec

    merged = merge_records([proc_rec(0), proc_rec(1)])
    block = merged["global"]["attribution"]
    _assert_fractions(block)
    # recomputed over the pooled rows, not inherited from process 0
    assert block["inputs"]["time_us"] != 1.0


def test_native_style_record_gets_attribution_at_merge():
    """A record whose emitter stamped NO attribution (the native tier's
    C++ emitter) gets one mirrored from its timer summaries at merge
    time."""
    from dlnetbench_tpu.metrics.merge import merge_records

    rec = _load_record("record_attrib.jsonl")
    del rec["global"]["attribution"]
    merged = merge_records([rec])
    _assert_fractions(merged["global"]["attribution"])


# ---------------------------------------------------------------------
# profiling satellites: the conservative 'other' bucket + top ops


def test_collective_stats_buckets_unclassified_as_other():
    """Regression (satellite 1): ops classify_op can't name — a
    synthetic unclassified fusion — used to be silently dropped, making
    every collective look like a LARGER share of device time than it
    was.  They now bucket under 'other' with a count, so occupancy
    fractions are conservative."""
    from dlnetbench_tpu.metrics.profiling import collective_stats
    events = [
        {"name": "fusion.12", "dur": 300.0},        # unclassifiable
        {"name": "all-reduce.1", "dur": 100.0},
        {"name": "end: all-reduce.1", "dur": 100.0},  # completion marker
        # host python spans share the raw trace's event stream (on CPU
        # even device ops ride the /host:CPU lane) — they are NOT
        # device occupancy and must not land in 'other'
        {"name": "$profiler.py:226 trace", "dur": 9e9},
        {"name": "PjitFunction(<lambda>)", "dur": 5e6},
    ]
    stats = collective_stats(events)
    assert set(stats) == {"other", "allreduce"}
    assert stats["other"] == {"count": 1, "total_us": 300.0,
                              "mean_us": 300.0, "max_us": 300.0}
    assert stats["allreduce"]["total_us"] == 100.0
    # the conservative occupancy: allreduce is 25% of device time, not
    # the 100% the silent drop used to imply
    total = sum(s["total_us"] for s in stats.values())
    assert stats["allreduce"]["total_us"] / total == pytest.approx(0.25)


def test_top_device_ops_ranked_and_marker_free():
    from dlnetbench_tpu.metrics.profiling import top_device_ops
    events = [
        {"name": "fusion.1", "dur": 10.0},
        {"name": "fusion.1", "dur": 20.0},
        {"name": "all-reduce.2", "dur": 25.0},
        {"name": "end: all-reduce.2", "dur": 25.0},
        {"name": "", "dur": 99.0},
    ]
    top = top_device_ops(events, k=2)
    assert top == [{"op": "fusion.1", "total_us": 30.0, "count": 2},
                   {"op": "all-reduce.2", "total_us": 25.0, "count": 1}]
    assert top_device_ops(events, k=0) == []
    # host spans excluded like in collective_stats
    assert top_device_ops([{"name": "$x.py:1 f", "dur": 9.0}]) == []


def test_host_lane_events_excluded_from_device_occupancy():
    """Bare-identifier HOST events — compiler passes when a compile
    lands inside the profiled window ('dce', 'algsimp'), argument
    bookkeeping ('ParseArguments') — pass the op-name shape test, but
    they run on the python dispatch thread; the ``_thread`` annotation
    from load_trace_events keeps them out of 'other' and top_device_ops.
    The CPU thunk executor's 'call' wrapper (whose duration encloses
    its children on the same lane) is excluded too."""
    from dlnetbench_tpu.metrics.profiling import (collective_stats,
                                                  top_device_ops)
    events = [
        {"name": "dot.4", "dur": 50.0,
         "_thread": "tf_XLATfrtCpuClient/-123"},
        {"name": "all-reduce.1", "dur": 10.0,
         "_thread": "tf_XLAEigen/-456"},
        # host-lane bare identifiers: NOT device occupancy
        {"name": "dce", "dur": 9e4, "_thread": "python"},
        {"name": "algsimp", "dur": 8e4, "_thread": "python"},
        {"name": "ParseArguments", "dur": 7e4, "_thread": "python"},
        # thunk wrapper enclosing dot.4 — counting it double-counts
        {"name": "call", "dur": 55.0,
         "_thread": "tf_XLATfrtCpuClient/-123"},
    ]
    stats = collective_stats(events)
    assert set(stats) == {"other", "allreduce"}
    assert stats["other"] == {"count": 1, "total_us": 50.0,
                              "mean_us": 50.0, "max_us": 50.0}
    assert top_device_ops(events) == [
        {"op": "dot.4", "total_us": 50.0, "count": 1},
        {"op": "all-reduce.1", "total_us": 10.0, "count": 1}]


def test_load_trace_events_annotates_thread_names(tmp_path):
    """load_trace_events resolves thread_name metadata onto each event;
    traces without metadata (merged artifacts) stay unannotated."""
    import gzip
    from dlnetbench_tpu.metrics.profiling import load_trace_events
    trace = {"traceEvents": [
        {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
         "args": {"name": "python"}},
        {"ph": "X", "pid": 1, "tid": 2, "name": "backend_compile",
         "ts": 0.0, "dur": 5.0},
        {"ph": "X", "pid": 1, "tid": 3, "name": "fusion.1",
         "ts": 1.0, "dur": 2.0},
    ]}
    p = tmp_path / "t.trace.json.gz"
    with gzip.open(p, "wt") as f:
        json.dump(trace, f)
    events = load_trace_events(p)
    by_name = {e["name"]: e for e in events}
    assert by_name["backend_compile"]["_thread"] == "python"
    assert "_thread" not in by_name["fusion.1"]  # no metadata for tid 3


def test_attribute_record_prefers_stamped_device_top_ops():
    rec = json.loads((DATA / "record_attrib.jsonl").read_text())
    rec["global"]["device_top_ops"] = [
        {"op": "fusion.3", "total_us": 12.0, "count": 4}]
    block = attr_mod.attribute_record(rec)
    assert block["top_ops"] == [{"op": "fusion.3", "total_us": 12.0,
                                 "count": 4}]
