"""Span tracer + merged Chrome-trace exporter (metrics/spans.py).

Locks the tentpole properties: spans nest and close correctly (including
across threads and through exceptions), the disabled path hands out one
shared no-op object (nothing allocated or recorded per span), and the
merged host+device trace.json round-trips through the SAME loader the
device-trace channel uses (``profiling.load_trace_events``), with
collective device ops colored/kind-tagged via ``classify_op``.
"""
from __future__ import annotations

import json
import threading

import pytest

from dlnetbench_tpu.metrics import spans
from dlnetbench_tpu.metrics.profiling import collective_stats, load_trace_events


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Never leak an enabled tracer into (or out of) a test."""
    spans.disable()
    yield
    spans.disable()


def test_disabled_span_is_shared_noop():
    assert not spans.is_enabled()
    a = spans.span("anything", key="value")
    b = spans.span("else")
    # ONE module-level singleton: the disabled path allocates no span
    # object, and entering it records nothing anywhere
    assert a is b is spans.NULL_SPAN
    with a:
        pass
    assert spans.current() is None


def test_enable_disable_lifecycle():
    tr = spans.enable()
    assert spans.is_enabled() and spans.current() is tr
    with spans.span("x"):
        pass
    got = spans.disable()
    assert got is tr and not spans.is_enabled()
    assert [s["name"] for s in tr.spans] == ["x"]
    # disabled again: back to the singleton
    assert spans.span("y") is spans.NULL_SPAN


def test_spans_nest_and_close_correctly():
    tr = spans.enable()
    with spans.span("outer", phase="run"):
        with spans.span("inner"):
            pass
        with spans.span("inner2"):
            pass
    spans.disable()
    by_name = {s["name"]: s for s in tr.spans}
    assert set(by_name) == {"outer", "inner", "inner2"}
    outer, inner, inner2 = (by_name[n] for n in ("outer", "inner", "inner2"))
    # children close before the parent (append order) and nest inside it
    assert [s["name"] for s in tr.spans] == ["inner", "inner2", "outer"]
    assert outer["depth"] == 0 and inner["depth"] == inner2["depth"] == 1
    for child in (inner, inner2):
        assert child["ts_us"] >= outer["ts_us"]
        assert (child["ts_us"] + child["dur_us"]
                <= outer["ts_us"] + outer["dur_us"] + 1e-6)
    assert outer["attrs"] == {"phase": "run"}


def test_span_survives_exception_and_marks_it():
    tr = spans.enable()
    with pytest.raises(RuntimeError):
        with spans.span("doomed", what="x"):
            raise RuntimeError("boom")
    # the failed phase stays on the timeline, marked — and the depth
    # stack unwound, so the next span is top-level again
    with spans.span("after"):
        pass
    spans.disable()
    doomed, after = tr.spans
    assert doomed["name"] == "doomed"
    assert doomed["attrs"]["error"] == "RuntimeError"
    assert after["depth"] == 0


def test_threads_keep_independent_depth():
    tr = spans.enable()
    seen = {}

    def worker():
        with spans.span("in-thread"):
            pass

    with spans.span("main-outer"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    spans.disable()
    for s in tr.spans:
        seen[s["name"]] = s
    # the worker's span is NOT nested under the main thread's open span
    assert seen["in-thread"]["depth"] == 0
    assert seen["in-thread"]["tid"] != seen["main-outer"]["tid"]


def _synthetic_device_events():
    """What load_trace_events returns from a jax profiler dir: complete
    events on the profiler's own epoch (big ts), some collectives."""
    return [
        {"ph": "X", "pid": 7, "tid": 1, "name": "fusion.3",
         "ts": 5_000_000.0, "dur": 40.0},
        {"ph": "X", "pid": 7, "tid": 1, "name": "all-reduce.1",
         "ts": 5_000_050.0, "dur": 25.0},
        {"ph": "X", "pid": 8, "tid": 1, "name": "collective-permute.2",
         "ts": 5_000_060.0, "dur": 10.0},
    ]


def test_merged_trace_roundtrips_through_load_trace_events(tmp_path):
    tr = spans.enable()
    with spans.span("build"):
        pass
    with spans.span("profile"):
        pass
    spans.disable()

    out = tmp_path / "trace.json"
    trace = spans.write_chrome_trace(out, tr, _synthetic_device_events())

    # one artifact, loadable by the same loader as the raw device traces
    events = load_trace_events(out)
    names = [e["name"] for e in events]
    assert "build" in names and "profile" in names
    assert "all-reduce.1" in names and "fusion.3" in names
    # the device half still feeds the per-collective stats channel
    stats = collective_stats(events)
    assert stats["allreduce"]["count"] == 1
    assert stats["permute"]["count"] == 1

    by_name = {e["name"]: e for e in trace["traceEvents"]
               if e.get("ph") == "X"}
    # host track on pid 0; device pids shifted past it
    assert by_name["build"]["pid"] == spans.HOST_PID
    assert by_name["all-reduce.1"]["pid"] > spans.HOST_PID
    # collectives colored + kind-tagged via classify_op; compute ops not
    assert by_name["all-reduce.1"]["cname"]
    assert by_name["all-reduce.1"]["args"]["kind"] == "allreduce"
    assert by_name["collective-permute.2"]["args"]["kind"] == "permute"
    assert "cname" not in by_name["fusion.3"]
    # device timeline aligned: earliest device event starts where the
    # host "profile" span (the profiled iteration) starts
    profile_ts = next(s["ts_us"] for s in tr.spans if s["name"] == "profile")
    assert by_name["fusion.3"]["ts"] == pytest.approx(profile_ts)


def test_host_only_trace_and_file_loader(tmp_path):
    tr = spans.enable()
    with spans.span("only-host"):
        pass
    spans.disable()
    out = tmp_path / "host.json"
    spans.write_chrome_trace(out, tr, None)
    events = load_trace_events(out)
    assert [e["name"] for e in events] == ["only-host"]
    # a directory without profiler output still raises (old contract)
    with pytest.raises(FileNotFoundError):
        load_trace_events(tmp_path / "empty_dir_nope")


@pytest.mark.slow
def test_cli_trace_out_end_to_end(eight_devices, tmp_path):
    """Acceptance lock: ONE cli command produces a merged host+device
    trace with build/compile/warmup/timed phases AND device collective
    ops visible, loadable through load_trace_events."""
    from dlnetbench_tpu.cli import main

    out = tmp_path / "rec.jsonl"
    trace = tmp_path / "t.json"
    rc = main(["dp", "--model", "gpt2_l_16_bfloat16", "--num_buckets", "2",
               "--platform", "cpu", "-r", "2", "-w", "1",
               "--size_scale", "1e-5", "--time_scale", "1e-4",
               "--no_topology", "--trace-out", str(trace),
               "--out", str(out)])
    assert rc == 0
    events = load_trace_events(trace)
    host_names = {e["name"] for e in events
                  if e.get("pid") == spans.HOST_PID}
    # the harness phases the tentpole demands, all on one timeline
    for phase in ("build", "compile", "warmup", "timed", "fence",
                  "profile"):
        assert phase in host_names, f"missing host span {phase!r}"
    # device collectives present and kind-tagged
    stats = collective_stats(events)
    assert stats.get("allreduce", {}).get("count", 0) >= 1
    assert any(e.get("args", {}).get("kind") == "allreduce"
               for e in events)


# ---------------------------------------------------------------------
# record-derived tracks: native-tier --trace-out (satellite)


def _attrib_record():
    from pathlib import Path
    return json.loads((Path(__file__).parent / "data"
                       / "record_attrib.jsonl").read_text())


def test_active_stacks_snapshot():
    spans.enable()
    try:
        assert spans.active_stacks() == {}
        with spans.span("outer"):
            with spans.span("inner"):
                stacks = spans.active_stacks()
                assert list(stacks.values()) == [["outer", "inner"]]
            assert list(spans.active_stacks().values()) == [["outer"]]
        assert spans.active_stacks() == {}
    finally:
        spans.disable()
    assert spans.active_stacks() == {}  # tracing off -> {}


def test_attribution_counter_events():
    attr = {"fractions": {"compute": 0.6, "hbm": 0.1,
                          "comm_exposed": 0.2, "host": 0.1},
            "bound": "mxu"}
    events = spans.attribution_counter_events(attr, dur_us=500.0)
    names = [e["name"] for e in events]
    assert "process_name" in names
    counters = [e for e in events if e["ph"] == "C"]
    # one sample at each end of the run window, all four series in args
    assert [e["ts"] for e in counters] == [0.0, 500.0]
    assert counters[0]["args"]["compute"] == 0.6
    meta = [e for e in events if e["name"] == "process_name"][0]
    assert "mxu" in meta["args"]["name"]
    assert spans.attribution_counter_events({}) == []
    assert spans.attribution_counter_events({"bound": "mxu"}) == []


def test_record_track_events_lay_out_runs():
    """A run record (either tier) becomes per-rank Perfetto tracks:
    runtimes as end-to-end duration events, sibling timers as counter
    series, band summaries as annotations, the attribution block as a
    counter track over the laid-out window."""
    rec = _attrib_record()
    events = spans.record_track_events(rec)
    by_ph = {}
    for e in events:
        by_ph.setdefault(e["ph"], []).append(e)
    runs = [e for e in by_ph["X"] if e["name"].startswith("run ")]
    n_ranks = len(rec["ranks"])
    n_runs = len(rec["ranks"][0]["runtimes"])
    assert len(runs) == n_ranks * n_runs
    # rank 0's runs are wall-adjacent: run j starts where j-1 ended
    r0 = [e for e in runs if e["pid"] == spans._RECORD_PID_BASE]
    assert r0[1]["ts"] == pytest.approx(r0[0]["ts"] + r0[0]["dur"])
    # band summaries annotate the track
    bands = [e for e in events if e["ph"] == "i"]
    assert any(e["name"] == "runtimes band" for e in bands)
    assert bands[0]["args"]["n"] == n_runs
    # the record's attribution block rides as a counter track
    counters = [e for e in by_ph["C"]
                if e["pid"] == spans.ATTRIBUTION_PID]
    assert counters and "compute" in counters[0]["args"]


def test_merge_trace_out_writes_native_style_trace(tmp_path):
    """metrics.merge --trace-out: a record-only trace (no in-process
    tracer, the native tier's situation) that round-trips through the
    shared loader."""
    from dlnetbench_tpu.metrics import merge as merge_mod

    rec = _attrib_record()
    src = tmp_path / "in.jsonl"
    src.write_text(json.dumps(rec) + "\n")
    out = tmp_path / "merged.jsonl"
    trace = tmp_path / "trace.json"
    rc = merge_mod.main(["--trace-out", str(trace), str(out), str(src)])
    assert rc == 0
    written = json.loads(trace.read_text())
    phs = {e["ph"] for e in written["traceEvents"]}
    assert {"X", "C", "M"} <= phs
    # the shared loader reads the complete events back (device-timeline
    # consumers only ever see X events)
    loaded = load_trace_events(trace)
    assert loaded and all(e["ph"] == "X" for e in loaded)
