"""Cross-rank critical-path blame (analysis/critical_path.py).

The ISSUE 14 blame acceptance, end to end through real clocks: four
"ranks" run the same measured step loop as four rank-scoped views of
one FaultPlan (``FaultInjector(plan, world, rank=r)`` — the
multi-controller emulation), each measuring its own wall clock; the
merged per-rank timelines must attribute >= 80% of the fault window's
excess step time to the injected rank, and a clean run must attribute
no rank above the noise band.
"""
from __future__ import annotations

import json
import time

import pytest

from dlnetbench_tpu.analysis.critical_path import (blame_columns,
                                                   blame_from_matrix,
                                                   blame_report,
                                                   matrix_from_flights,
                                                   step_matrix)
from dlnetbench_tpu.faults.inject import FaultInjector
from dlnetbench_tpu.faults.plan import FaultEvent, FaultPlan

pytestmark = pytest.mark.telemetry

WARM, RUNS, WORLD = 2, 10, 4
DELAY_US = 4000.0
WIN = (WARM + 3, WARM + 7)  # plan-step units (warmup included)


def _measured_rank_rows(plan: FaultPlan) -> list[dict]:
    """Genuinely measured per-rank step timelines: each rank runs the
    same busy-work step loop under ITS OWN rank-scoped injector and its
    own clock — exactly what one process per rank would measure."""
    rows = []
    for r in range(WORLD):
        inj = FaultInjector(plan, world=WORLD, rank=r)
        walls = []
        for _ in range(WARM + RUNS):
            t0 = time.perf_counter()
            inj.before_step()
            acc = sum(i * i for i in range(4000))  # ~0.3 ms busy step
            assert acc > 0
            walls.append(round((time.perf_counter() - t0) * 1e6, 1))
        rows.append({"rank": r, "device_id": r, "process_index": r,
                     "hostname": f"host{r}", "runtimes": walls[WARM:]})
    return rows


def _record(rows: list[dict], plan: FaultPlan | None) -> dict:
    g: dict = {"model": "busywork", "world_size": WORLD}
    if plan is not None and plan.events:
        g["fault_plan"] = plan.to_dict()
    return {"section": "dp", "version": 2, "process": 0, "global": g,
            "mesh": {}, "num_runs": RUNS,
            "warmup_times": [0.0] * WARM, "ranks": rows}


def test_straggler_blame_lands_on_injected_rank():
    """ISSUE 14 acceptance: >= 80% of the fault window's excess lands
    on the injected rank, which is also the only suspect."""
    plan = FaultPlan(events=[FaultEvent(
        kind="delay", ranks=[2], iteration=WIN[0], until=WIN[1],
        magnitude_us=DELAY_US)]).validate()
    rec = _record(_measured_rank_rows(plan), plan)
    rep = blame_report(rec)
    assert rep["clock_alignment"] == "collective-fence"
    win = rep["window"]
    # sample units: plan window rebased by the warmup length
    assert win["sample_range"] == [WIN[0] - WARM, WIN[1] - WARM]
    assert win["top_rank"] == 2
    assert win["top_frac"] >= 0.8
    # the injected sleep dominates the window's excess
    assert win["excess_us"] >= 0.5 * DELAY_US * (WIN[1] - WIN[0])
    assert rep["suspects"] == [2]
    cols = blame_columns(rec)
    assert cols["blame_rank"] == "2" and cols["blame_frac"] >= 0.8


def test_clean_run_blames_no_rank_above_noise():
    rec = _record(_measured_rank_rows(FaultPlan()), None)
    rep = blame_report(rec)
    assert rep["suspects"] == []
    assert "window" not in rep
    cols = blame_columns(rec)
    assert cols["blame_rank"] == "-"


def test_single_controller_record_degrades_to_no_signal():
    """Rank rows sharing ONE clock (the python single-controller
    duplication) have zero per-rank signal — blame must say so, never
    fabricate a verdict.  The gate holds on the WINDOW path too: a
    faulted single-controller record (fault_plan present, identical
    rows) must not crown rank 0 with a 0%-blame verdict."""
    import math

    shared = [300.0, 305.0, 310.0, 303.0]
    rows = [{"rank": r, "runtimes": list(shared)} for r in range(4)]
    rec = {"section": "dp", "global": {"model": "m"}, "num_runs": 4,
           "warmup_times": [], "ranks": rows}
    rep = blame_report(rec)
    assert rep["suspects"] == []
    cols = blame_columns(rec)
    assert cols["blame_rank"] == "-" and math.isnan(cols["blame_frac"])
    faulted = json.loads(json.dumps(rec))
    faulted["global"]["fault_plan"] = FaultPlan(events=[FaultEvent(
        kind="delay", ranks=[1], iteration=1, until=3,
        magnitude_us=1000.0)]).validate().to_dict()
    cols = blame_columns(faulted)
    assert cols["blame_rank"] == "-" and math.isnan(cols["blame_frac"])


def test_phase_blame_names_the_grown_timer():
    """Per-phase decomposition: the straggler's excess shows up in the
    phase timer that actually grew (here a synthetic comm leg)."""
    base = [100.0] * 6
    mat = [list(base) for _ in range(3)]
    comm = {r: [20.0] * 6 for r in range(3)}
    for i in (2, 3):
        mat[1][i] += 500.0
        comm[1][i] += 500.0
    phases = {r: {"comm_time": comm[r], "compute_time": [80.0] * 6}
              for r in range(3)}
    rep = blame_from_matrix([0, 1, 2], mat, window=(2, 4),
                            phases=phases)
    assert rep["window"]["top_rank"] == 1
    assert rep["phases"]["comm_time"] == pytest.approx(1000.0)
    assert rep["phases"]["compute_time"] == pytest.approx(0.0)


def test_energy_axis_rides_the_report():
    rows = [{"rank": r, "runtimes": [100.0, 101.0],
             "energy_consumed": [0.5 + r, 0.5 + r]} for r in range(2)]
    rec = {"section": "dp", "global": {"model": "m"}, "num_runs": 2,
           "warmup_times": [], "ranks": rows}
    rep = blame_report(rec)
    assert rep["energy_j"] == {"0": 1.0, "1": 3.0}


def test_matrix_from_flights_merges_rank_rings():
    """Per-rank flight dumps (python FlightRecorder or the native
    TelemetryRing's record block) merge on step keys; only the common
    step window survives (rings may roll past each other)."""
    dumps = []
    for r in range(2):
        samples = [{"rank": r, "step": s, "t_s": 0.01 * s,
                    "step_wall_us": 100.0 + r * 10 + s}
                   for s in range(2 + r, 8)]  # rank 1 lost steps 2
        dumps.append({"trigger": "stall", "samples": samples})
    ranks, mat = matrix_from_flights(dumps)
    assert ranks == [0, 1]
    assert len(mat[0]) == len(mat[1]) == 5  # steps 3..7
    assert mat[0][0] == pytest.approx(103.0)
    assert mat[1][0] == pytest.approx(113.0)


def test_step_matrix_truncates_to_common_length():
    rows = [{"rank": 0, "runtimes": [1.0, 2.0, 3.0]},
            {"rank": 1, "runtimes": [1.0, 2.0]}]
    ranks, mat = step_matrix({"ranks": rows, "global": {}})
    assert ranks == [0, 1] and all(len(m) == 2 for m in mat)
    with pytest.raises(ValueError, match="no per-rank"):
        step_matrix({"ranks": [], "global": {}, "section": "x"})


def test_report_cli_end_to_end(tmp_path, capsys):
    """python -m dlnetbench_tpu.analysis.critical_path report — the
    committed telemetry fixture through load -> merge-shape -> report,
    both human and --json forms."""
    from pathlib import Path

    from dlnetbench_tpu.analysis import critical_path as cp

    plan = FaultPlan(events=[FaultEvent(
        kind="delay", ranks=[2], iteration=WIN[0], until=WIN[1],
        magnitude_us=DELAY_US)]).validate()
    rec = _record(_measured_rank_rows(plan), plan)
    path = tmp_path / "runs.jsonl"
    path.write_text(json.dumps(rec) + "\n")
    assert cp.main(["report", str(path)]) == 0
    out = capsys.readouterr().out
    assert "critical path: dp/busywork" in out
    assert "top rank 2" in out
    assert cp.main(["report", "--json", "--section", "dp",
                    str(path)]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["window"]["top_rank"] == 2
    # usage errors are tidy, not tracebacks
    assert cp.main([]) == 2
    assert cp.main(["report"]) == 2
    empty = tmp_path / "none.jsonl"
    empty.write_text(json.dumps({"section": "serving", "global": {},
                                 "ranks": []}) + "\n")
    assert cp.main(["report", str(empty)]) == 1
    assert Path(path).exists()
