"""Real-model tests: shapes, finiteness, gradient flow, and learning on
tiny configs (CPU)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.core.model_card import load_model_card
from dlnetbench_tpu.models import transformer as tfm
from dlnetbench_tpu.models import vit as vitm


def _tiny_cfg(card_name="llama3_8b", **kw):
    card = load_model_card(card_name)
    cfg = tfm.TransformerConfig.from_card(card, seq_len=32, num_layers=2,
                                          vocab_size=64)
    return tfm.TransformerConfig(**{**cfg.__dict__, "embed_dim": 64,
                                    "num_heads": 4, "num_kv_heads": 2,
                                    "ff_dim": 128, "dtype": "float32", **kw})


def test_llama_forward_shapes():
    cfg = _tiny_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits = tfm.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_gpt2_forward():
    cfg = _tiny_cfg("gpt2_l", max_positions=32)
    assert not cfg.gated
    params = tfm.init_params(jax.random.key(0), cfg)
    assert "pos_embed" in params and "head" not in params  # tied embeddings
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits = tfm.forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)


def test_moe_forward():
    cfg = _tiny_cfg("mixtral_8x7b")
    assert cfg.num_experts == 8 and cfg.top_k == 2
    params = tfm.init_params(jax.random.key(0), cfg)
    assert params["layers"]["w_gate"].shape == (2, 8, 64, 128)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)
    logits = tfm.forward(params, tokens, cfg)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_moe_sparse_matches_dense_at_full_capacity():
    """At capacity_factor >= E/top_k no token drops, so the capacity-based
    dispatch must reproduce the dense-dispatch result exactly (modulo
    accumulation order)."""
    import dataclasses
    cfg = _tiny_cfg("mixtral_8x7b")
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                cfg.vocab_size)
    dense = tfm.forward(params, tokens, cfg)
    sparse_cfg = dataclasses.replace(
        cfg, moe_impl="sparse",
        moe_capacity_factor=cfg.num_experts / cfg.top_k)
    sparse = tfm.forward(params, tokens, sparse_cfg)
    np.testing.assert_allclose(np.asarray(dense, np.float32),
                               np.asarray(sparse, np.float32),
                               atol=5e-2, rtol=5e-2)


def test_moe_sparse_trains_and_drops_gracefully():
    """At the production capacity factor (1.25) some tokens drop; the
    forward stays finite and the loss still falls under SGD (dropped
    tokens ride the residual)."""
    import dataclasses
    cfg = dataclasses.replace(_tiny_cfg("mixtral_8x7b"), moe_impl="sparse")
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0,
                                cfg.vocab_size)

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(tfm.loss_fn)(p, tokens, cfg)
        return jax.tree.map(lambda a, b: a - 0.05 * b.astype(a.dtype),
                            p, g), loss

    losses = []
    for _ in range(8):
        params, loss = step(params)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_moe_unknown_impl_rejected():
    import dataclasses
    with pytest.raises(ValueError, match="moe_impl"):
        dataclasses.replace(_tiny_cfg("mixtral_8x7b"), moe_impl="topk")


def test_causality():
    """Changing a future token must not affect earlier logits."""
    cfg = _tiny_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    t1 = jnp.zeros((1, 16), jnp.int32)
    t2 = t1.at[0, 10].set(5)
    l1 = tfm.forward(params, t1, cfg)
    l2 = tfm.forward(params, t2, cfg)
    np.testing.assert_allclose(np.asarray(l1[0, :10]), np.asarray(l2[0, :10]),
                               rtol=1e-5)
    assert not np.allclose(np.asarray(l1[0, 10:]), np.asarray(l2[0, 10:]))


def test_loss_decreases_with_sgd():
    cfg = _tiny_cfg()
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (4, 17), 0, cfg.vocab_size)

    @jax.jit
    def step(p):
        loss, g = jax.value_and_grad(tfm.loss_fn)(p, tokens, cfg)
        return loss, jax.tree.map(lambda a, b: a - 0.5 * b, p, g)

    losses = []
    for _ in range(8):
        loss, params = step(params)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_vit_forward_and_grad():
    card = load_model_card("vit_b")
    cfg = vitm.ViTConfig.from_card(card, num_layers=2, image_size=32)
    cfg = vitm.ViTConfig(**{**cfg.__dict__, "embed_dim": 64, "num_heads": 4,
                            "ff_dim": 128, "num_classes": 10,
                            "dtype": "float32"})
    params = vitm.init_params(jax.random.key(0), cfg)
    images = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    logits = vitm.forward(params, images, cfg)
    assert logits.shape == (2, 10)
    labels = jnp.array([1, 3])
    g = jax.grad(vitm.loss_fn)(params, images, labels, cfg)
    leaves = jax.tree.leaves(g)
    assert all(np.all(np.isfinite(np.asarray(x, dtype=np.float32)))
               for x in leaves)
    assert any(float(jnp.max(jnp.abs(x))) > 0 for x in leaves)


def test_vit_card_guard():
    card = load_model_card("llama3_8b")
    with pytest.raises(ValueError, match="not a ViT"):
        vitm.ViTConfig.from_card(card)
    with pytest.raises(ValueError, match="ViT card"):
        tfm.TransformerConfig.from_card(load_model_card("vit_b"))


def test_remat_policies_agree():
    """remat off / full / dots must give the same loss and gradients; an
    unknown policy string is rejected at construction."""
    from dlnetbench_tpu.models import transformer as tfm
    cfg0 = tfm.TransformerConfig(
        vocab_size=64, embed_dim=32, num_heads=4, num_kv_heads=2, ff_dim=64,
        num_layers=2, seq_len=16, gated=True, max_positions=0,
        dtype="float32")
    params = tfm.init_params(jax.random.key(0), cfg0)
    tokens = jax.random.randint(jax.random.key(1), (2, 17), 0, 64)

    def lg(cfg):
        return jax.value_and_grad(tfm.loss_fn)(params, tokens, cfg)

    l0, g0 = lg(cfg0)
    for policy in ("full", "dots"):
        cfg = tfm.TransformerConfig(
            **{**cfg0.__dict__, "remat": True, "remat_policy": policy})
        l1, g1 = lg(cfg)
        assert jnp.allclose(l0, l1, rtol=1e-6)
        for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
            assert jnp.allclose(a, b, rtol=1e-5, atol=1e-6)

    with pytest.raises(ValueError, match="remat_policy"):
        tfm.TransformerConfig(**{**cfg0.__dict__, "remat_policy": "dot"})

    # remat_scope="mlp" (checkpoint only the SwiGLU — the r5 int8
    # memory knob) must also be gradient-identical; bad scope rejected
    cfg = tfm.TransformerConfig(
        **{**cfg0.__dict__, "remat": True, "remat_scope": "mlp"})
    l1, g1 = lg(cfg)
    assert jnp.allclose(l0, l1, rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        assert jnp.allclose(a, b, rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError, match="remat_scope"):
        tfm.TransformerConfig(**{**cfg0.__dict__, "remat_scope": "layer"})
