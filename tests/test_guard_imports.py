"""Tier-1-adjacent guards.

1. No direct jax shard_map imports outside the compat shim: ``from jax
   import shard_map`` only exists in jax >= 0.6, and 9 test files failed
   COLLECTION on this toolchain (jax 0.4.x) before
   ``utils/jax_compat.py`` — a grep guard keeps the regression from
   coming back one import at a time.
2. ``pytest --collect-only`` must report zero errors: a collection error
   silently removes an entire file's tests from the tier-1 count.
"""
from __future__ import annotations

import re
import subprocess
import sys
from pathlib import Path


REPO = Path(__file__).resolve().parent.parent
SHIM = "dlnetbench_tpu/utils/jax_compat.py"

_DIRECT_IMPORT = re.compile(
    r"^\s*(from\s+jax\s+import\s+.*\bshard_map\b"
    r"|from\s+jax\.experimental\.shard_map\s+import"
    r"|from\s+jax\.experimental\s+import\s+.*\bshard_map\b)",
    re.MULTILINE)


def _repo_py_files():
    for sub in ("dlnetbench_tpu", "tests", "examples"):
        yield from (REPO / sub).rglob("*.py")


def test_no_direct_shard_map_imports():
    offenders = []
    for path in _repo_py_files():
        rel = path.relative_to(REPO).as_posix()
        if rel == SHIM:
            continue
        if _DIRECT_IMPORT.search(path.read_text()):
            offenders.append(rel)
    assert not offenders, (
        f"direct jax shard_map imports outside {SHIM}: {offenders} — "
        f"import it from dlnetbench_tpu.utils.jax_compat instead "
        f"(version-portable, translates check_vma<->check_rep)")


def test_collection_is_clean():
    """Zero collection errors — the seed shipped with 9, which silently
    removed ~a third of the suite from every tier-1 run."""
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "--collect-only",
         "-p", "no:cacheprovider"],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={"JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
             "PYTHONPATH": str(REPO),
             "HOME": str(Path.home())},
    )
    tail = "\n".join(proc.stdout.splitlines()[-10:])
    assert proc.returncode == 0, f"collect-only failed:\n{tail}\n{proc.stderr[-2000:]}"
    assert "error" not in tail.lower(), f"collection errors:\n{tail}"
