"""Fused Pallas SwiGLU-backward kernels (ops/mlp_backward.py) and the
split-dot custom-VJP variant (models/layers.py) against autodiff.

Runs in Pallas interpret mode on the CPU mesh — same kernels, same
index maps, no TPU required (the flash-attention test strategy)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlnetbench_tpu.models.layers import swiglu, swiglu_split_bwd
from dlnetbench_tpu.ops.mlp_backward import dgdu, dwd, swiglu_pallas_bwd


@pytest.fixture(scope="module")
def shapes():
    x = jax.random.normal(jax.random.key(0), (256, 128), jnp.float32)
    wg = jax.random.normal(jax.random.key(1), (128, 256), jnp.float32) * 0.2
    wu = jax.random.normal(jax.random.key(2), (128, 256), jnp.float32) * 0.2
    wd = jax.random.normal(jax.random.key(3), (256, 128), jnp.float32) * 0.2
    return x, wg, wu, wd


@pytest.mark.parametrize("impl", [swiglu_split_bwd, swiglu_pallas_bwd])
def test_swiglu_backward_variants_match_autodiff(shapes, impl):
    x, wg, wu, wd = shapes
    f_ref = lambda *a: (swiglu(*a) ** 2).sum()        # noqa: E731
    f_new = lambda *a: (impl(*a) ** 2).sum()          # noqa: E731
    np.testing.assert_allclose(f_ref(x, wg, wu, wd), f_new(x, wg, wu, wd),
                               rtol=1e-5)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    g_new = jax.grad(f_new, argnums=(0, 1, 2, 3))(x, wg, wu, wd)
    for name, a, b in zip(("dx", "dwg", "dwu", "dwd"), g_ref, g_new):
        scale = float(jnp.max(jnp.abs(a))) + 1e-9
        np.testing.assert_allclose(np.asarray(b) / scale,
                                   np.asarray(a) / scale,
                                   atol=1e-5, err_msg=name)


def test_dgdu_kernel_unit(shapes):
    x, wg, wu, wd = shapes
    dy = jax.random.normal(jax.random.key(4), (256, 128), jnp.float32)
    g, u = x @ wg, x @ wu
    dh = dy @ wd.T
    sig = jax.nn.sigmoid(g)
    silu = g * sig
    dg_ref = dh * u * (sig + silu * (1 - sig))
    du_ref = dh * silu
    dg_p, du_p = dgdu(dy, wd, g, u)
    np.testing.assert_allclose(dg_p, dg_ref, atol=1e-4)
    np.testing.assert_allclose(du_p, du_ref, atol=1e-4)


def test_dwd_kernel_unit_multistep_accumulation(shapes):
    """block_k halves until it divides T, so T=256 runs several
    accumulation steps — covering the init/accumulate/emit phases."""
    x, wg, wu, wd = shapes
    dy = jax.random.normal(jax.random.key(5), (256, 128), jnp.float32)
    g, u = x @ wg, x @ wu
    h = jax.nn.silu(g) * u
    ref = h.T @ dy
    got = dwd(g, u, dy, block_k=64)
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=1e-4)


@pytest.mark.slow  # ~60s e2e train step; the kernel-unit cases ride the fast lane
def test_transformer_pallas_backward_path():
    """The mlp_backward='pallas' config wires through _block and trains
    (grad finite) on the CPU mesh."""
    import dataclasses

    from dlnetbench_tpu.core.model_card import load_model_card
    from dlnetbench_tpu.models import transformer as tfm

    card = load_model_card("llama3_8b")
    cfg = dataclasses.replace(
        tfm.TransformerConfig.from_card(card, seq_len=128, num_layers=2,
                                        vocab_size=512),
        mlp_backward="pallas", attention_impl="xla")
    params = tfm.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (1, 129), 0, 512)
    loss, grads = jax.value_and_grad(tfm.loss_fn)(params, tokens, cfg)
    assert jnp.isfinite(loss)
    flat = jax.tree.leaves(grads)
    assert all(bool(jnp.all(jnp.isfinite(g))) for g in flat)
