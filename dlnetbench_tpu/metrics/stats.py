"""Artifact-grade sample summaries: every short-chain stat self-describes.

DLNetBench's contract is "the artifact is the result" — but a single
number from a 3-sample chain on a tunnel-fenced backend is not a result,
it is one draw from a distribution the round-5 verdict showed to be
bimodal (tunnel throughput states).  This module is the ONE definition
of how such samples ship:

    {"value": median, "best": min, "band": [lo, hi], "n": N}

* ``value`` — the median, the figure downstream comparisons use;
* ``best`` — the minimum, the least-noise observation (host/tunnel
  jitter only ever inflates a wall-clock sample);
* ``band`` — the full observed range.  With n this small, percentiles
  would be theater; the honest statement is "samples fell in here";
* ``n`` — how many samples back the claim.

``flag_low_mode`` mirrors ``bench._flag_above_peak``: a physically
suspicious reading must never ship unannotated.  When the best sample
sits far below the median, the samples straddle two modes (fast-path
vs slow-path tunnel states) and the median is a mixture statistic, not
a central tendency — the line is stamped with a ``note`` saying so.

Used by bench.py's auxiliary JSON lines, by ``metrics.emit``'s
schema-v2 per-timer summaries, and available to any analysis that
wants one consistent band convention.
"""
from __future__ import annotations

import statistics

# best/value ratio below which the samples are declared bimodal: the
# fastest observation is >30% under the median, which honest unimodal
# wall-clock noise (inflation-only) does not produce
LOW_MODE_RATIO = 0.7


def summarize(samples: list[float], ndigits: int | None = None) -> dict:
    """``{"value": median, "best": min, "band": [lo, hi], "n": N}`` for a
    list of same-unit samples.  Empty input summarizes to zeros with
    n=0 rather than raising — emitters must not die on a timer that
    never fired."""
    if not samples:
        return {"value": 0.0, "best": 0.0, "band": [0.0, 0.0], "n": 0}
    vals = [float(v) for v in samples]
    out = {
        "value": statistics.median(vals),
        "best": min(vals),
        "band": [min(vals), max(vals)],
        "n": len(vals),
    }
    if ndigits is not None:
        out["value"] = round(out["value"], ndigits)
        out["best"] = round(out["best"], ndigits)
        out["band"] = [round(v, ndigits) for v in out["band"]]
    return out


def overlap_fraction(full, compute, comm) -> list[float]:
    """Measured communication–compute overlap from the A/B decomposition
    (proxies/base.py: full / compute-only / comm-only variants), per
    matched sample:

        overlap_i = (Tc_i + Tm_i - T_both_i) / min(Tc_i, Tm_i)

    1.0 = the shorter leg is fully hidden behind the longer; 0.0 = fully
    serialized (T_both = Tc + Tm); negative = interference (running
    together is SLOWER than back-to-back — contention for the same
    HBM/ICI resources).  Values are not clamped: an out-of-[0, 1]
    reading is a measurement statement, and the band convention
    (``summarize``) is how it ships.  Samples whose min leg is ~0 —
    below 0.1% of the largest leg, e.g. a time_chain sample nearly
    cancelled by the RTT subtraction — yield 0.0 (nothing to hide; an
    unbounded ratio from a degenerate denominator must never dominate a
    summary mean)."""
    out = []
    for f, c, m in zip(full, compute, comm):
        denom = min(c, m)
        if denom <= 0 or denom <= 1e-3 * max(f, c, m):
            out.append(0.0)
        else:
            out.append((c + m - f) / denom)
    return out


def bands_overlap(a, b) -> bool | None:
    """Do two ``[lo, hi]`` bands overlap?  ``None`` when either side is
    missing/malformed — the caller (the regression sentinel) treats an
    unknown overlap as "bands cannot veto", falling back to its
    %-threshold alone.  Two bands that merely touch DO overlap: with
    n=3 samples the band edges are observations, and sharing one is
    exactly the "indistinguishable from noise" case the bands exist to
    name."""
    try:
        alo, ahi = float(a[0]), float(a[1])
        blo, bhi = float(b[0]), float(b[1])
    except (TypeError, ValueError, IndexError):
        return None
    return blo <= ahi and alo <= bhi


def flag_low_mode(line: dict, ratio: float = LOW_MODE_RATIO) -> dict:
    """Annotate a summary-carrying dict whose samples straddle two modes.

    Operates on the ``value``/``best`` keys (any unit) so it applies to
    a raw ``summarize`` result and to a bench JSON line alike; appends
    to an existing ``note`` (e.g. the above-peak flag) instead of
    clobbering it."""
    value = line.get("value") or 0.0
    best = line.get("best")
    n = line.get("n", 0)
    if best is None or n < 2 or value <= 0:
        return line
    if best < ratio * value:
        note = (f"bimodal samples: best {best:g} is "
                f"{100 * (1 - best / value):.0f}% below the median over "
                f"n={n} — the median mixes two modes (tunnel/host "
                f"throughput states); read [band] not value")
        line["note"] = f"{line['note']}; {note}" if line.get("note") else note
    return line
