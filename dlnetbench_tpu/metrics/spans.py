"""Host-side span tracing — the observability spine of the harness.

Every emitted number in this repo is a *host* wall-clock measurement of
an async device program; the device side already has a self-describing
trace channel (``jax.profiler`` -> ``metrics/profiling.py``), but the
host side — build, compile, warmup, calibration, fence waits, per-config
sweep points — lived only in session logs.  This module gives the host
side the same artifact-grade story:

* ``span("name", key=value)`` is a context manager timing a region on
  the process-wide monotonic clock (``time.perf_counter``), nestable
  across threads (each thread keeps its own depth stack).
* Tracing is OFF by default and the disabled path is near-zero cost:
  ``span()`` returns a shared no-op singleton — no span object is
  allocated, no clock is read, nothing is recorded.  (A keyword-attrs
  call still builds its kwargs dict, so the hot measurement sites in
  ``utils/timing.py`` additionally gate on ``is_enabled()`` — a timed
  fence window in an untraced run pays nothing at all.)
* ``write_chrome_trace`` exports the collected spans as Chrome-trace
  ("Trace Event Format") complete events and MERGES them with the
  device-op events the JAX profiler emitted for the same run, so ONE
  ``trace.json`` (loadable in Perfetto / chrome://tracing) shows where
  wall-clock went: host track on top (compile vs warmup vs timed vs
  fence), per-device tracks below, collective ops colored by kind via
  ``profiling.classify_op``.

The tracer is deliberately NOT a per-collective measurement channel —
that is the decomposition harness (proxies/base.py) and the device
trace (metrics/profiling.py).  Spans attribute *phases* of the harness
itself, the layer neither channel covers.
"""
from __future__ import annotations

import json
import threading
import time
from pathlib import Path

# ---------------------------------------------------------------------
# Tracer core.

class _NullSpan:
    """Shared disabled-mode span: entering/exiting does nothing and the
    module hands out this one instance for every disabled ``span()``
    call — the per-span allocation count when disabled is zero."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records start on __enter__, appends a finished
    record to its tracer on __exit__.  Exceptions propagate (the span
    still closes, marked ``error``) so a failing phase stays visible in
    the timeline instead of vanishing with its context."""
    __slots__ = ("_tracer", "name", "attrs", "_t0", "_depth")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict | None):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._depth = self._tracer._push(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        tr = self._tracer
        tr._pop()
        if exc_type is not None:
            attrs = dict(self.attrs or {})
            attrs["error"] = exc_type.__name__
            self.attrs = attrs
        tr._record(self.name, self._t0, t1, self._depth, self.attrs)
        return False


class Tracer:
    """Collects finished spans as plain dicts (name, ts/dur in us on the
    tracer's own origin, thread id, nesting depth, attrs).  Thread-safe;
    one tracer per measured run is the intended shape."""

    def __init__(self):
        self.origin = time.perf_counter()
        self.spans: list[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()
        # tid -> stack of OPEN span names, readable from other threads:
        # the watchdog's stall handler fires on a Timer thread and must
        # see where the measuring thread currently is (the span stack is
        # the postmortem breadcrumb the stall message dumps)
        self._active: dict[int, list[str]] = {}

    # -- called by _Span --------------------------------------------
    def _push(self, name: str) -> int:
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        with self._lock:
            self._active.setdefault(threading.get_ident(), []).append(name)
        return depth

    def _pop(self) -> None:
        self._local.depth = getattr(self._local, "depth", 1) - 1
        with self._lock:
            stack = self._active.get(threading.get_ident())
            if stack:
                stack.pop()

    def active_stacks(self) -> dict[int, list[str]]:
        """Snapshot of every thread's open-span stack (outermost first)."""
        with self._lock:
            return {tid: list(stack)
                    for tid, stack in self._active.items() if stack}

    def _record(self, name: str, t0: float, t1: float, depth: int,
                attrs: dict | None) -> None:
        rec = {
            "name": name,
            "ts_us": (t0 - self.origin) * 1e6,
            "dur_us": (t1 - t0) * 1e6,
            "tid": threading.get_ident(),
            "depth": depth,
        }
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            self.spans.append(rec)

    # -- public ------------------------------------------------------
    def span(self, name: str, **attrs) -> _Span:
        return _Span(self, name, attrs or None)


# Module-level current tracer.  ``None`` means disabled — the common
# case — and the ``span()`` fast path below is one global load, one
# ``is None`` test, one return of the shared singleton.
_TRACER: Tracer | None = None


def enable() -> Tracer:
    """Install (and return) a fresh tracer as the process tracer.
    Subsequent ``span()`` calls record into it."""
    global _TRACER
    _TRACER = Tracer()
    return _TRACER


def disable() -> Tracer | None:
    """Stop tracing; returns the tracer that was active (with its
    collected spans) so callers can export after the measured region."""
    global _TRACER
    t, _TRACER = _TRACER, None
    return t


def current() -> Tracer | None:
    return _TRACER


def is_enabled() -> bool:
    return _TRACER is not None


def span(name: str, **attrs):
    """Time a region when tracing is enabled; free when it is not."""
    t = _TRACER
    if t is None:
        return NULL_SPAN
    return t.span(name, **attrs)


def active_stacks() -> dict[int, list[str]]:
    """Every thread's currently-open span stack ({} when tracing is
    off) — the watchdog's stall-time breadcrumb channel."""
    t = _TRACER
    return t.active_stacks() if t is not None else {}


# ---------------------------------------------------------------------
# Chrome-trace / Perfetto export.

HOST_PID = 0          # host spans live on one process track
_DEVICE_PID_BASE = 1  # device events keep their own pids shifted up

# chrome://tracing reserved color names per collective kind — Perfetto
# falls back to hashing the name, so the kind also rides in args.kind
_KIND_CNAME = {
    "allreduce": "thread_state_running",
    "allgather": "thread_state_runnable",
    "reduce_scatter": "thread_state_iowait",
    "alltoall": "rail_animation",
    "permute": "rail_response",
    "send_recv": "rail_idle",
}


def host_events(tracer: Tracer, *, pid: int = HOST_PID) -> list[dict]:
    """Tracer spans -> Chrome complete ('X') events on the host track."""
    events: list[dict] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "host (harness phases)"}},
        {"ph": "M", "pid": pid, "name": "process_sort_index",
         "args": {"sort_index": -1}},  # host track above device tracks
    ]
    for s in tracer.spans:
        ev = {
            "ph": "X",
            "pid": pid,
            "tid": s["tid"],
            "name": s["name"],
            "ts": s["ts_us"],
            "dur": s["dur_us"],
        }
        args = dict(s.get("attrs") or {})
        args["depth"] = s["depth"]
        ev["args"] = args
        events.append(ev)
    return events


def _colored_device_events(device_events: list[dict],
                           align_to_us: float | None) -> list[dict]:
    """Shift device events onto the host timeline and color collectives.

    The device trace's timestamps are on the profiler's own epoch; only
    their relative layout is meaningful here, so the earliest device
    event is aligned to ``align_to_us`` on the host clock (the start of
    the span that bracketed the profiled iteration when the caller
    knows it, else 0).  Pids are shifted past the host pid so the
    tracks never collide."""
    from dlnetbench_tpu.metrics.profiling import classify_op

    if not device_events:
        return []
    t_min = min(float(e.get("ts", 0.0)) for e in device_events)
    shift = (align_to_us if align_to_us is not None else 0.0) - t_min
    out = []
    for e in device_events:
        ev = dict(e)
        ev.pop("_thread", None)  # loader annotation, not trace data
        ev["ts"] = float(e.get("ts", 0.0)) + shift
        ev["pid"] = int(e.get("pid", 0)) + _DEVICE_PID_BASE
        kind = classify_op(str(e.get("name", "")))
        if kind is not None:
            ev["cname"] = _KIND_CNAME[kind]
            args = dict(ev.get("args") or {})
            args["kind"] = kind
            ev["args"] = args
        out.append(ev)
    return out


def write_chrome_trace(path: str | Path, tracer: Tracer | None,
                       device_events: list[dict] | None = None,
                       align_span: str | None = "profile",
                       extra_events: list[dict] | None = None) -> dict:
    """Write ONE merged Chrome trace: host spans + device-op events.

    ``align_span`` names the host span whose start the earliest device
    event is pinned to (the span that wrapped the profiled iteration);
    when absent the device timeline starts at host ts 0.
    ``extra_events`` are appended verbatim — the attribution counter
    tracks and record-derived per-rank tracks ride this channel.
    Returns the trace dict that was written (callers/tests can inspect
    it without re-reading the file)."""
    events: list[dict] = []
    align_to = None
    if tracer is not None:
        events.extend(host_events(tracer))
        if align_span is not None:
            for s in tracer.spans:
                if s["name"] == align_span:
                    align_to = s["ts_us"]
                    break
    if device_events:
        events.extend(_colored_device_events(device_events, align_to))
    if extra_events:
        events.extend(extra_events)
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    path = Path(path)
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace


# ---------------------------------------------------------------------
# Record-derived tracks: per-rank timers + attribution counters.
#
# The merged host+device timeline above covers the python tier, whose
# process runs the tracer.  Native-tier runs emit only their JSON
# record — but that record carries everything a timeline needs:
# per-rank per-run timer samples, their band summaries, and (post
# merge) the attribution block.  These exporters turn a record into
# Chrome/Perfetto counter + duration tracks so ``--trace-out`` (via
# ``metrics.merge --trace-out``) is useful for native runs too.

ATTRIBUTION_PID = 50       # attribution counter track
TELEMETRY_PID = 60         # flight-recorder counter tracks (ISSUE 14)
_RECORD_PID_BASE = 100     # per-rank record tracks start here


def attribution_counter_events(attr: dict, *, dur_us: float = 1.0,
                               pid: int = ATTRIBUTION_PID) -> list[dict]:
    """Counter tracks for an ``attribution`` block's fractions: one
    Chrome 'C' series per resource over [0, dur_us], so Perfetto shows
    the compute/hbm/comm/host split next to the timelines it explains.
    The ``bound`` verdict rides the track name."""
    fractions = (attr or {}).get("fractions")
    if not fractions:
        return []
    name = f"attribution (bound: {attr.get('bound', '?')})"
    events: list[dict] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": name}},
        {"ph": "M", "pid": pid, "name": "process_sort_index",
         "args": {"sort_index": 40}},
    ]
    for ts in (0.0, max(dur_us, 1.0)):
        events.append({"ph": "C", "pid": pid, "name": "fractions",
                       "ts": ts, "args": {k: round(float(v), 4)
                                          for k, v in fractions.items()}})
    return events


def telemetry_counter_events(block: dict, anomalies: dict | None = None,
                             *, pid: int = TELEMETRY_PID) -> list[dict]:
    """Flight-recorder samples -> Perfetto counter tracks: every
    numeric field of the telemetry samples becomes one 'C' series over
    the samples' own ``t_s`` clock (us on the trace timeline), and each
    anomaly event lands as a global instant ('i', scope process) at its
    trigger time, named by its trigger kind.  Accepts a record's
    ``global.telemetry`` block (tail samples), a flight dump payload
    (full ring), or a live ``FlightRecorder.telemetry_block()``."""
    samples = (block or {}).get("samples") or (block or {}).get("last") \
        or []
    events: list[dict] = []
    if samples:
        events += [
            {"ph": "M", "pid": pid, "name": "process_name",
             "args": {"name": "telemetry (flight recorder)"}},
            {"ph": "M", "pid": pid, "name": "process_sort_index",
             "args": {"sort_index": 45}},
        ]
        for s in samples:
            ts = float(s.get("t_s", 0.0)) * 1e6
            for k, v in s.items():
                if k in ("t_s", "source", "step") \
                        or not isinstance(v, (int, float)):
                    continue
                events.append({"ph": "C", "pid": pid, "name": k,
                               "ts": ts, "args": {"value": float(v)}})
    for ev in ((anomalies or {}).get("events") or []):
        events.append({"ph": "i", "pid": pid, "tid": 0, "s": "p",
                       "name": f"anomaly: {ev.get('trigger', '?')}",
                       "ts": float(ev.get("t_s", 0.0)) * 1e6,
                       "args": {k: v for k, v in ev.items()
                                if k != "detail"}})
    return events


def record_track_events(record: dict,
                        pid_base: int = _RECORD_PID_BASE) -> list[dict]:
    """Per-rank tracks from a run record (either tier): each rank
    becomes one process track whose 'runtimes' samples lay out runs as
    duration events end-to-end, every other timer rides as a counter
    series sampled per run, and the schema-v2 band summaries annotate
    the track as instant events (args = the {value, best, band, n}
    dict).  The record's attribution block (stamped by emit, or
    mirrored at merge time for native records) is appended as a counter
    track spanning the laid-out run window."""
    events: list[dict] = []
    rows = record.get("ranks") or []
    max_end = 0.0
    for i, row in enumerate(rows):
        pid = pid_base + i
        rank = row.get("rank", i)
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": f"rank {rank} "
                                        f"({record.get('section', '?')})"}})
        events.append({"ph": "M", "pid": pid, "name": "process_sort_index",
                       "args": {"sort_index": 50 + i}})
        runtimes = [float(v) for v in row.get("runtimes") or []]
        # runs laid out end-to-end on the rank's own clock: ts of run j
        # is the sum of runs 0..j-1 (wall-adjacent, gaps unknowable)
        starts = []
        t = 0.0
        for v in runtimes:
            starts.append(t)
            t += v
        max_end = max(max_end, t)
        for j, (ts, dur) in enumerate(zip(starts, runtimes)):
            events.append({"ph": "X", "pid": pid, "tid": 0,
                           "name": f"run {j}", "ts": ts, "dur": dur,
                           "args": {"us": dur}})
        for timer, vals in row.items():
            # skip structural list fields (chip coords are not a timer
            # series) alongside the runtimes already laid out above
            if timer in ("runtimes", "coords") or not isinstance(vals,
                                                                 list):
                continue
            for j, v in enumerate(vals):
                if j >= len(starts):
                    break
                try:
                    fv = float(v)
                except (TypeError, ValueError):
                    break
                events.append({"ph": "C", "pid": pid, "name": timer,
                               "ts": starts[j], "args": {"value": fv}})
        for timer, summary in (row.get("summary") or {}).items():
            events.append({"ph": "i", "pid": pid, "tid": 0, "s": "p",
                           "name": f"{timer} band", "ts": 0.0,
                           "args": dict(summary)})
    attr = (record.get("global") or {}).get("attribution")
    if attr:
        events.extend(attribution_counter_events(attr, dur_us=max_end))
    tele = (record.get("global") or {}).get("telemetry")
    anom = (record.get("global") or {}).get("anomalies")
    if tele or anom:
        events.extend(telemetry_counter_events(tele or {}, anom))
    return events
