"""Host-side span tracing — the observability spine of the harness.

Every emitted number in this repo is a *host* wall-clock measurement of
an async device program; the device side already has a self-describing
trace channel (``jax.profiler`` -> ``metrics/profiling.py``), but the
host side — build, compile, warmup, calibration, fence waits, per-config
sweep points — lived only in session logs.  This module gives the host
side the same artifact-grade story:

* ``span("name", key=value)`` is a context manager timing a region on
  the process-wide monotonic clock (``time.perf_counter``), nestable
  across threads (each thread keeps its own depth stack).
* Tracing is OFF by default and the disabled path is near-zero cost:
  ``span()`` returns a shared no-op singleton — no span object is
  allocated, no clock is read, nothing is recorded.  (A keyword-attrs
  call still builds its kwargs dict, so the hot measurement sites in
  ``utils/timing.py`` additionally gate on ``is_enabled()`` — a timed
  fence window in an untraced run pays nothing at all.)
* ``write_chrome_trace`` exports the collected spans as Chrome-trace
  ("Trace Event Format") complete events and MERGES them with the
  device-op events the JAX profiler emitted for the same run, so ONE
  ``trace.json`` (loadable in Perfetto / chrome://tracing) shows where
  wall-clock went: host track on top (compile vs warmup vs timed vs
  fence), per-device tracks below, collective ops colored by kind via
  ``profiling.classify_op``.

The tracer is deliberately NOT a per-collective measurement channel —
that is the decomposition harness (proxies/base.py) and the device
trace (metrics/profiling.py).  Spans attribute *phases* of the harness
itself, the layer neither channel covers.
"""
from __future__ import annotations

import json
import threading
import time
from pathlib import Path

# ---------------------------------------------------------------------
# Tracer core.

class _NullSpan:
    """Shared disabled-mode span: entering/exiting does nothing and the
    module hands out this one instance for every disabled ``span()``
    call — the per-span allocation count when disabled is zero."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class _Span:
    """One live span: records start on __enter__, appends a finished
    record to its tracer on __exit__.  Exceptions propagate (the span
    still closes, marked ``error``) so a failing phase stays visible in
    the timeline instead of vanishing with its context."""
    __slots__ = ("_tracer", "name", "attrs", "_t0", "_depth")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict | None):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        self._depth = self._tracer._push()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter()
        tr = self._tracer
        tr._pop()
        if exc_type is not None:
            attrs = dict(self.attrs or {})
            attrs["error"] = exc_type.__name__
            self.attrs = attrs
        tr._record(self.name, self._t0, t1, self._depth, self.attrs)
        return False


class Tracer:
    """Collects finished spans as plain dicts (name, ts/dur in us on the
    tracer's own origin, thread id, nesting depth, attrs).  Thread-safe;
    one tracer per measured run is the intended shape."""

    def __init__(self):
        self.origin = time.perf_counter()
        self.spans: list[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()

    # -- called by _Span --------------------------------------------
    def _push(self) -> int:
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        return depth

    def _pop(self) -> None:
        self._local.depth = getattr(self._local, "depth", 1) - 1

    def _record(self, name: str, t0: float, t1: float, depth: int,
                attrs: dict | None) -> None:
        rec = {
            "name": name,
            "ts_us": (t0 - self.origin) * 1e6,
            "dur_us": (t1 - t0) * 1e6,
            "tid": threading.get_ident(),
            "depth": depth,
        }
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            self.spans.append(rec)

    # -- public ------------------------------------------------------
    def span(self, name: str, **attrs) -> _Span:
        return _Span(self, name, attrs or None)


# Module-level current tracer.  ``None`` means disabled — the common
# case — and the ``span()`` fast path below is one global load, one
# ``is None`` test, one return of the shared singleton.
_TRACER: Tracer | None = None


def enable() -> Tracer:
    """Install (and return) a fresh tracer as the process tracer.
    Subsequent ``span()`` calls record into it."""
    global _TRACER
    _TRACER = Tracer()
    return _TRACER


def disable() -> Tracer | None:
    """Stop tracing; returns the tracer that was active (with its
    collected spans) so callers can export after the measured region."""
    global _TRACER
    t, _TRACER = _TRACER, None
    return t


def current() -> Tracer | None:
    return _TRACER


def is_enabled() -> bool:
    return _TRACER is not None


def span(name: str, **attrs):
    """Time a region when tracing is enabled; free when it is not."""
    t = _TRACER
    if t is None:
        return NULL_SPAN
    return t.span(name, **attrs)


# ---------------------------------------------------------------------
# Chrome-trace / Perfetto export.

HOST_PID = 0          # host spans live on one process track
_DEVICE_PID_BASE = 1  # device events keep their own pids shifted up

# chrome://tracing reserved color names per collective kind — Perfetto
# falls back to hashing the name, so the kind also rides in args.kind
_KIND_CNAME = {
    "allreduce": "thread_state_running",
    "allgather": "thread_state_runnable",
    "reduce_scatter": "thread_state_iowait",
    "alltoall": "rail_animation",
    "permute": "rail_response",
    "send_recv": "rail_idle",
}


def host_events(tracer: Tracer, *, pid: int = HOST_PID) -> list[dict]:
    """Tracer spans -> Chrome complete ('X') events on the host track."""
    events: list[dict] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "host (harness phases)"}},
        {"ph": "M", "pid": pid, "name": "process_sort_index",
         "args": {"sort_index": -1}},  # host track above device tracks
    ]
    for s in tracer.spans:
        ev = {
            "ph": "X",
            "pid": pid,
            "tid": s["tid"],
            "name": s["name"],
            "ts": s["ts_us"],
            "dur": s["dur_us"],
        }
        args = dict(s.get("attrs") or {})
        args["depth"] = s["depth"]
        ev["args"] = args
        events.append(ev)
    return events


def _colored_device_events(device_events: list[dict],
                           align_to_us: float | None) -> list[dict]:
    """Shift device events onto the host timeline and color collectives.

    The device trace's timestamps are on the profiler's own epoch; only
    their relative layout is meaningful here, so the earliest device
    event is aligned to ``align_to_us`` on the host clock (the start of
    the span that bracketed the profiled iteration when the caller
    knows it, else 0).  Pids are shifted past the host pid so the
    tracks never collide."""
    from dlnetbench_tpu.metrics.profiling import classify_op

    if not device_events:
        return []
    t_min = min(float(e.get("ts", 0.0)) for e in device_events)
    shift = (align_to_us if align_to_us is not None else 0.0) - t_min
    out = []
    for e in device_events:
        ev = dict(e)
        ev["ts"] = float(e.get("ts", 0.0)) + shift
        ev["pid"] = int(e.get("pid", 0)) + _DEVICE_PID_BASE
        kind = classify_op(str(e.get("name", "")))
        if kind is not None:
            ev["cname"] = _KIND_CNAME[kind]
            args = dict(ev.get("args") or {})
            args["kind"] = kind
            ev["args"] = args
        out.append(ev)
    return out


def write_chrome_trace(path: str | Path, tracer: Tracer | None,
                       device_events: list[dict] | None = None,
                       align_span: str | None = "profile") -> dict:
    """Write ONE merged Chrome trace: host spans + device-op events.

    ``align_span`` names the host span whose start the earliest device
    event is pinned to (the span that wrapped the profiled iteration);
    when absent the device timeline starts at host ts 0.  Returns the
    trace dict that was written (callers/tests can inspect it without
    re-reading the file)."""
    events: list[dict] = []
    align_to = None
    if tracer is not None:
        events.extend(host_events(tracer))
        if align_span is not None:
            for s in tracer.spans:
                if s["name"] == align_span:
                    align_to = s["ts_us"]
                    break
    if device_events:
        events.extend(_colored_device_events(device_events, align_to))
    trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    path = Path(path)
    with open(path, "w") as f:
        json.dump(trace, f)
    return trace
