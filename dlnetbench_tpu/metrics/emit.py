"""Structured metrics emission.

The reference emits, per proxy run, a named section with rank-0 global
key/values (model, grid dims, message sizes, backend, device) plus per-rank
JSON arrays of timer values (reference cpp/data_parallel/dp.cpp:275-295 via
ccutils macros), parsed downstream into pandas DataFrames.

Here a run emits ONE self-describing JSON object (one line when streamed):

    {"section": "<proxy>", "version": 2,
     "global": {..., "transport": "ici"},   # the rank-0 globals
     "ranks": [{"rank": 0, "device_id": ..., "runtimes": [...],
                "barrier_time": [...],
                "summary": {"runtimes": {"value": ..., "best": ...,
                                         "band": [lo, hi], "n": N}, ...},
                ...}, ...]}

Per-"rank" rows are per *device*.  Timing is host-measured per iteration
(single-controller), so timer arrays are shared across rows on a single
host; rows still carry device identity/coords so multi-host runs and the
analysis layer keep the reference's rank-resolved shape.

Schema history:
  v1 — raw timer arrays only.
  v2 — adds (a) per-rank ``summary``: every timer array summarized to
       the artifact-grade band form (``metrics.stats.summarize``), so a
       record is self-describing without re-deriving statistics; (b) a
       ``transport`` global naming what the timed bytes actually moved
       over (``ici`` / virtual host mesh), so loopback numbers can never
       be read as fabric physics.  v1 records still parse everywhere
       (parser/merge treat both; ``summary`` is derived data and absent
       from v1).
"""
from __future__ import annotations

import json
import socket
import sys

from dlnetbench_tpu.metrics.stats import summarize
from dlnetbench_tpu.proxies.base import ProxyResult

SCHEMA_VERSION = 2


def scheduler_variables(environ=None) -> dict:
    """Job variables auto-collected from the launching scheduler's
    environment — the external-launcher hook.  The reference leans on
    sbatchman job.variables for its sweep grids (reference
    plots/parser.py:4,221-237); TPU fleets launch via SLURM, GKE JobSet
    or multislice runtimes instead, so any of their identity variables
    present in the environment are stamped into the record's
    ``variables`` (hoisted to DataFrame columns by metrics.parser), and
    ``DLNB_TAG_<name>=<value>`` tags arbitrary sweep axes from ANY
    launcher without touching the command line.  Explicit ``--tag``
    flags override same-named entries."""
    import os
    env = os.environ if environ is None else environ
    out = {}
    for k, v in env.items():
        if k.startswith("DLNB_TAG_") and v:
            out[k[len("DLNB_TAG_"):].lower()] = v
    for k in ("SLURM_JOB_ID", "SLURM_PROCID", "SLURM_NNODES",
              "JOB_COMPLETION_INDEX",      # k8s indexed Job / JobSet
              "TPU_WORKER_ID", "MEGASCALE_SLICE_ID"):
        if env.get(k):
            out[k.lower()] = env[k]
    return out


def _process_identity() -> tuple[int, int]:
    """(this process's index, process count) — the multi-controller
    coordinates a multi-host merge keys on; (0, 1) without a runtime."""
    try:
        import jax
        return jax.process_index(), jax.process_count()
    except Exception:  # jax absent or uninitialized: single-process
        return 0, 1


def transport_label(mesh_info: dict) -> str:
    """What the timed bytes actually moved over, from the mesh header.

    A virtual CPU mesh's collectives move thread/loopback bytes — its
    bandwidth rows must be labeled as such, not read as fabric physics
    (the native tier stamps its own transports: shm, tcp:loopback,
    tcp:ethernet, host, ici)."""
    platform = mesh_info.get("platform")
    if platform == "cpu":
        return "virtual-host"
    if platform == "tpu":
        return "ici+dcn" if mesh_info.get("num_hosts", 1) > 1 else "ici"
    return platform or "unknown"


def result_to_record(result: ProxyResult) -> dict:
    mesh_info = result.global_meta.get("mesh", {})
    devices = mesh_info.get("devices", [{"id": 0, "process": 0}])
    hostname = socket.gethostname()
    proc, num_procs = _process_identity()
    # schema v2: each timer array ships with its band summary — the
    # record states value/best/band/n itself instead of leaving every
    # reader to re-derive (and disagree on) the statistics.  One dict,
    # shared across the per-device rows like the arrays themselves.
    summary = {name: summarize(vals, ndigits=3)
               for name, vals in result.timers_us.items()}
    # degraded (shrunk) runs: the surviving devices keep their ORIGINAL
    # rank ids — row i of the survivor mesh is global rank
    # degraded_world[i], so a merged/analyzed record never renumbers
    # the survivors into a fake dense world (faults/policy.py)
    rank_ids = result.global_meta.get("degraded_world")
    if rank_ids is not None and len(rank_ids) != len(devices):
        raise ValueError(
            f"degraded_world names {len(rank_ids)} survivors but the "
            f"mesh has {len(devices)} devices — the shrink rebuild and "
            f"the plan disagree")
    ranks = []
    for i, dev in enumerate(devices):
        row = {
            "rank": int(rank_ids[i]) if rank_ids is not None else i,
            "device_id": dev.get("id", i),
            "process_index": dev.get("process", 0),
            "hostname": hostname,
            **({"coords": dev["coords"]} if "coords" in dev else {}),
        }
        row.update(result.timers_us)
        # outer dict copied per row: consumers that drop a key from one
        # row's summary (metrics.merge's per-host energy dedup) must not
        # silently edit every sibling row; the inner band dicts are
        # never mutated per-row and stay shared
        row["summary"] = dict(summary)
        ranks.append(row)
    g = {k: v for k, v in result.global_meta.items() if k != "mesh"}
    # transport provenance (schema v2): proxies that know better (the
    # native tier, future DCN-aware builds) pre-stamp their own
    g.setdefault("transport", transport_label(mesh_info))
    # tuning provenance (ISSUE 9): which tuned configs this process ran
    # under — {db_dir, hits, misses, sites: {op|key -> config/hit/
    # band}}.  Absent on untuned runs (tuning disabled or no tunable
    # site consulted), so v1/pre-tuning records and this build's
    # untuned records are byte-compatible; a DB-miss run (misses > 0,
    # hits == 0) and a DB-hit run are distinguishable by construction.
    # Derived data: a failure here must never cost the measurement.
    try:
        from dlnetbench_tpu import tuning
        tp = tuning.provenance()
        if tp is not None:
            g.setdefault("tuning", tp)
    except Exception as e:  # pragma: no cover - defensive
        print(f"tuning provenance stamping failed "
              f"({type(e).__name__}: {e}); record unaffected",
              file=sys.stderr)
    # continuous telemetry (ISSUE 14): ring geometry + tail and the
    # anomaly events of the run that produced this record.  Disabled
    # telemetry stamps NOTHING — records from an untelemetered run stay
    # byte-identical to a pre-telemetry build's (fixture-locked).
    # Derived data: a failure here must never cost the measurement.
    try:
        from dlnetbench_tpu.metrics import telemetry
        rec_now = telemetry.current()
        if rec_now is not None:
            g.setdefault("telemetry", rec_now.telemetry_block())
            anom = rec_now.anomalies_block()
            if anom is not None:
                g.setdefault("anomalies", anom)
    except Exception as e:  # pragma: no cover - defensive
        print(f"telemetry stamping failed ({type(e).__name__}: {e}); "
              f"record unaffected", file=sys.stderr)
    if num_procs > 1:
        g.setdefault("num_processes", num_procs)
    record = {
        "section": result.name,
        "version": SCHEMA_VERSION,
        # which process measured this record's clocks — metrics.merge
        # keeps exactly the rows owned by it (multi-host reassembly)
        "process": proc,
        "global": g,
        "mesh": {k: v for k, v in mesh_info.items() if k != "devices"},
        "num_runs": result.num_runs,
        "warmup_times": result.warmup_times_us,
        "ranks": ranks,
    }
    # bottleneck attribution (schema v2+): join the AOT cost analysis,
    # the chip roofline, the measured decomposition timers, and the
    # transport peak into one {fractions, bound} verdict riding the
    # record — derived data, so a failure here must never cost the
    # measurement it describes
    try:
        from dlnetbench_tpu.analysis.attribution import attribute_record
        block = attribute_record(record)
        if block is not None:
            g["attribution"] = block
    except Exception as e:  # pragma: no cover - defensive
        print(f"attribution stamping failed ({type(e).__name__}: {e}); "
              f"record unaffected", file=sys.stderr)
    return record


def emit_result(result: ProxyResult, stream=None, path: str | None = None) -> dict:
    record = result_to_record(result)
    line = json.dumps(record)
    if path:
        with open(path, "a") as f:
            f.write(line + "\n")
    else:
        (stream or sys.stdout).write(line + "\n")
    return record
