"""Energy sampling — the rebuild's ``power_profiler`` equivalent.

The reference optionally links a vendor power profiler
(``-DPROXY_ENERGY_PROFILING -lpower_profiler``, reference
Makefile.flags.mk:119-124) sampling at ``POWER_SAMPLING_RATE_MS 5``
(dp.cpp:67) and emits per-rank ``energy_consumed`` arrays
(plots/parser.py:172) that feed the runtime-energy Pareto analysis.

TPU chips expose no public per-chip energy counter through JAX/PJRT, so
this is a *host-side* pluggable sampler chain, best available source wins:

  * RaplSampler   — Linux RAPL cumulative counters
                    (/sys/class/powercap/intel-rapl*/energy_uj) with
                    wraparound handling.  Real measured joules for the
                    CPU-mesh runs and the host share of TPU runs.
  * HwmonSampler  — /sys/class/hwmon power_input (uW) integrated by a
                    5 ms background thread (the reference's sampling rate).
  * none          — energy is simply absent from the emitted record (the
                    reference behaves the same when built without the
                    profiler).

``run_proxy`` brackets each timed run with ``read_joules()`` and emits the
per-run deltas as ``energy_consumed``, keeping the reference's record
schema so the Pareto plots work unchanged.

Continuous telemetry (ISSUE 14): the same per-chain deltas also feed
the flight-recorder ring per step (``energy_j`` on each ``proxy``
sample — proxies/base.py gates on ``telemetry.is_enabled()``), so
anomaly flight dumps show the energy trend into the event and the
critical-path report carries a per-rank energy axis
(``analysis/critical_path.py`` sums the ``energy_consumed`` timer over
the analysis window) wherever a sampler exists.
"""
from __future__ import annotations

import glob
import threading
import time

POWER_SAMPLING_RATE_MS = 5   # reference dp.cpp:67


class TpuChipSampler:
    """Best-effort TPU *chip* energy counter (VERDICT r5 #7).

    TPU chips expose no public per-chip energy counter through JAX/PJRT
    today, but that is a probed fact, not an assumption — this sampler
    attempts every plausible channel and reports what it found:

      1. PJRT/libtpu device attributes via jax: any attribute whose
         name mentions energy/power on a TPU device (none exist in
         current libtpu releases; ``probe_notes`` records the attribute
         names actually seen so a future libtpu that adds one is
         noticed, not silently missed).
      2. sysfs hwmon: a device whose ``name`` mentions tpu, with
         cumulative ``energy*_input`` channels (microjoules).
      3. the accel class: ``/sys/class/accel/accel*/device/energy_uj``.

    When a counter exists the record's ``energy_source`` reads ``tpu``
    and the figures are chip-side.  When every probe misses (the
    current state on Cloud TPU images — docs/PERF.md documents the dead
    end), the chain falls through to the HOST samplers below, whose
    axes are host-side by construction and labeled so."""

    def __init__(self, hwmon_root: str = "/sys/class/hwmon",
                 accel_root: str = "/sys/class/accel"):
        self.probe_notes: list[str] = []
        self._files: list[str] = []
        # 1. PJRT device attributes (only meaningful on a TPU backend;
        # cheap and exception-safe elsewhere)
        try:
            import jax
            devs = jax.devices()
            if devs and devs[0].platform == "tpu":
                attrs = [a for a in dir(devs[0]) if not a.startswith("_")]
                hits = [a for a in attrs
                        if "energy" in a.lower() or "power" in a.lower()]
                if hits:
                    self.probe_notes.append(
                        f"pjrt device attributes matched: {hits}")
                else:
                    self.probe_notes.append(
                        f"pjrt tpu device exposes no energy/power "
                        f"attribute ({len(attrs)} attributes probed)")
        except Exception:
            self.probe_notes.append("jax/pjrt probe unavailable")
        # 2. tpu-named hwmon with cumulative energy channels
        for path in sorted(glob.glob(f"{hwmon_root}/hwmon*")):
            try:
                with open(f"{path}/name") as f:
                    name = f.read().strip()
            except OSError:
                continue
            if "tpu" not in name.lower():
                continue
            chans = sorted(glob.glob(f"{path}/energy*_input"))
            if chans:
                self._files.extend(chans)
                self.probe_notes.append(
                    f"hwmon {name}: {len(chans)} energy channel(s)")
            else:
                self.probe_notes.append(
                    f"hwmon {name}: present but no energy*_input")
        # 3. accel-class cumulative counters
        for path in sorted(glob.glob(f"{accel_root}/accel*/device/energy_uj")):
            self._files.append(path)
            self.probe_notes.append(f"accel counter: {path}")
        if not self._files:
            self.probe_notes.append("no TPU chip energy counter found")
        self.source = "tpu"
        self._last: list[float] = []
        self._acc = 0.0
        if self._files:
            self._last = [self._read_raw(i)
                          for i in range(len(self._files))]

    @property
    def available(self) -> bool:
        return bool(self._files)

    def _read_raw(self, i: int) -> float:
        with open(self._files[i]) as f:
            return float(f.read())

    def read_joules(self) -> float:
        """Monotonic cumulative joules summed over chip counters
        (counters are cumulative uJ; a wrapped/reset counter drops that
        sample rather than going backwards)."""
        for i in range(len(self._files)):
            cur = self._read_raw(i)
            delta = cur - self._last[i]
            if delta > 0:
                self._acc += delta
            self._last[i] = cur
        return self._acc / 1e6


class RaplSampler:
    """Cumulative joules from Linux RAPL package domains."""

    def __init__(self, root: str = "/sys/class/powercap"):
        packages, psys = [], []
        for path in sorted(glob.glob(f"{root}/intel-rapl:*")):
            # top-level zones only: subzones (intel-rapl:0:0) are already
            # included in their parent's counter
            if path.rsplit("/", 1)[-1].count(":") != 1:
                continue
            try:
                with open(f"{path}/energy_uj") as f:
                    float(f.read())
                try:
                    with open(f"{path}/name") as f:
                        zone = f.read().strip()
                except OSError:
                    zone = "package-?"
                try:
                    with open(f"{path}/max_energy_range_uj") as f:
                        rng = float(f.read())
                except OSError:
                    rng = 0.0   # unknown range: drop wrapped samples
                entry = (f"{path}/energy_uj", rng)
                # psys already contains the packages — never sum both
                (psys if zone == "psys" else packages).append(entry)
            except (OSError, ValueError):
                continue
        self._domains = psys if psys else packages
        self._last: list[float] = []
        self._acc = 0.0
        if self._domains:
            self._last = [self._read_raw(i)
                          for i in range(len(self._domains))]

    @property
    def available(self) -> bool:
        return bool(self._domains)

    def _read_raw(self, i: int) -> float:
        with open(self._domains[i][0]) as f:
            return float(f.read())

    def read_joules(self) -> float:
        """Monotonic cumulative joules across packages (wraparound-safe)."""
        for i, (_, rng) in enumerate(self._domains):
            cur = self._read_raw(i)
            delta = cur - self._last[i]
            if delta < 0:  # counter wrapped; drop the sample if the
                delta = delta + rng if rng > 0 else 0.0  # range is unknown
            self._acc += delta
            self._last[i] = cur
        return self._acc / 1e6


class HwmonSampler:
    """Integrate instantaneous /sys/class/hwmon power (uW) in a background
    thread at the reference's 5 ms sampling period."""

    def __init__(self, root: str = "/sys/class/hwmon"):
        # channels from ONE hwmon device only — summing across devices
        # double-counts when aggregate (battery/ACPI) and component (CPU
        # package) sensors coexist.  DLNB_HWMON_DEVICE selects by name.
        import os
        want = os.environ.get("DLNB_HWMON_DEVICE", "")
        by_dev: dict[str, list[str]] = {}
        names: dict[str, str] = {}
        for path in sorted(glob.glob(f"{root}/hwmon*/power*_input")):
            dev = path.rsplit("/", 2)[-2]
            try:
                with open(path) as f:
                    float(f.read())
                by_dev.setdefault(dev, []).append(path)
                try:
                    with open(f"{root}/{dev}/name") as f:
                        names[dev] = f.read().strip()
                except OSError:
                    names[dev] = dev
            except (OSError, ValueError):
                continue
        if want:
            # explicit selection: no match means unavailable, never a
            # silent fallback to some other sensor
            chosen = next((d for d, n in names.items() if want in n), None)
            if chosen is None and by_dev:
                import sys
                print(f"[energy] DLNB_HWMON_DEVICE={want!r} matches none of "
                      f"{sorted(names.values())}; hwmon sampling disabled",
                      file=sys.stderr)
        else:
            # unconfigured: prefer CPU-package-like sensors — the
            # alphabetically-first device could be a battery, NVMe or
            # wifi sensor, silently attributing energy to the wrong part
            preferred = ("cpu", "package", "core", "soc", "rapl")
            chosen = next((d for d in sorted(by_dev)
                           if any(p in names[d].lower() for p in preferred)),
                          next(iter(sorted(by_dev)), None))
        self._inputs = by_dev.get(chosen, []) if chosen else []
        # surfaced in the emitted record (energy_source) so a
        # misattributed sensor is visible, not silent
        self.source = f"hwmon:{names[chosen]}" if self._inputs else ""
        self._joules = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def available(self) -> bool:
        return bool(self._inputs)

    def _ensure_running(self):
        """Lazy-start (or restart after close) the integration thread —
        the 5 ms poller only spins while a measurement is in progress."""
        if self._inputs and (self._thread is None
                             or not self._thread.is_alive()):
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True)
            self._thread.start()

    def _loop(self):
        prev = time.monotonic()
        while not self._stop.is_set():
            time.sleep(POWER_SAMPLING_RATE_MS / 1e3)
            now = time.monotonic()
            watts = 0.0
            for path in self._inputs:
                try:
                    with open(path) as f:
                        watts += float(f.read()) / 1e6
                except (OSError, ValueError):
                    continue
            with self._lock:
                self._joules += watts * (now - prev)
            prev = now

    def read_joules(self) -> float:
        self._ensure_running()
        with self._lock:
            return self._joules

    def close(self):
        """Stop the integration thread; a later read_joules restarts it.
        Joins before returning so a read that follows immediately sees a
        dead thread and restarts cleanly (otherwise it could observe the
        stopping-but-alive thread, skip the restart, and integrate
        nothing for the whole next measured phase)."""
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=1.0)


_CACHED = None
_PROBED = False


def detect_sampler():
    """Best available energy source, or None (cached per process).
    Chip-side beats host-side: a real TPU chip counter (energy_source
    ``tpu``) wins over host RAPL/hwmon — on current images the TPU
    probe is a documented dead end (docs/PERF.md) and the chain falls
    through to the host counters, whose records say ``rapl``/``hwmon:*``
    so their figures are never read as chip energy."""
    global _CACHED, _PROBED
    if _PROBED:
        return _CACHED
    _PROBED = True
    tpu = TpuChipSampler()
    if tpu.available:
        _CACHED = tpu
        return _CACHED
    rapl = RaplSampler()
    if rapl.available:
        rapl.source = "rapl"
        _CACHED = rapl
        return _CACHED
    hw = HwmonSampler()
    if hw.available:
        # safety net: never leave the poller spinning past process end
        # even if a caller forgets close_sampler()
        import atexit
        atexit.register(hw.close)
        _CACHED = hw
        return _CACHED
    return None


def close_sampler(sampler) -> None:
    """Release a sampler's background resources after a measured phase
    (restartable — the cached sampler keeps working for later runs)."""
    close = getattr(sampler, "close", None)
    if close is not None:
        close()
