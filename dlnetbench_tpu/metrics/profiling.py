"""Profiler-trace-derived per-collective timing.

SURVEY.md §7.3 hard-part 1: the reference times each collective by
bracketing a host-blocking call — on TPU, fencing every collective would
destroy the compute/comm overlap being measured.  The harness therefore
measures by schedule *decomposition* (proxies/base.py); this module is the
independent cross-check channel: run ONE schedule iteration under the JAX
profiler, parse the Chrome-trace it emits, and report per-collective
device-op durations (count / total / mean per collective kind).  The two
channels bound the truth from different sides — decomposition gives
end-to-end exposed cost including queueing, the trace gives pure device
occupancy of each collective op.

Works on every backend (CPU-mesh traces name ops ``psum.N`` etc.; TPU
traces ``all-reduce.N`` / ``collective-permute.N`` / fusions) with no
TensorFlow dependency — the trace.json.gz is stdlib-parseable.
"""
from __future__ import annotations

import glob
import gzip
import json
import re
import tempfile
from pathlib import Path

import jax

# HLO/op-name fragments -> collective kind (lowercased substring match)
COLLECTIVE_PATTERNS: dict[str, tuple[str, ...]] = {
    "allreduce": ("all-reduce", "all_reduce", "allreduce", "psum"),
    "allgather": ("all-gather", "all_gather", "allgather"),
    "reduce_scatter": ("reduce-scatter", "reduce_scatter", "psum-scatter",
                       "psum_scatter"),
    "alltoall": ("all-to-all", "all_to_all", "alltoall"),
    "permute": ("collective-permute", "collective_permute", "ppermute"),
    "send_recv": ("send-done", "recv-done", "send.", "recv."),
}
# reduce_scatter names contain "psum" -> check more specific kinds first
_KIND_ORDER = ("reduce_scatter", "allgather", "alltoall", "permute",
               "send_recv", "allreduce")


def classify_op(name: str) -> str | None:
    """Collective kind for a trace-event name, or None."""
    n = name.lower()
    if n.startswith("end: "):   # async completion markers, not the op
        return None
    for kind in _KIND_ORDER:
        if any(p in n for p in COLLECTIVE_PATTERNS[kind]):
            return kind
    return None


def load_trace_events(trace_dir: str | Path) -> list[dict]:
    """All complete ('X') events from a Chrome trace.

    Accepts either a directory (the layout ``jax.profiler.trace``
    writes — the newest ``*.trace.json.gz`` under it is read) or a
    single trace file, plain ``.json`` or gzipped — which is how the
    merged host+device timelines ``metrics.spans.write_chrome_trace``
    emits round-trip through the same loader.

    Each event is annotated with its lane's thread name (``_thread``,
    resolved from the trace's metadata events) when the trace carries
    one: the occupancy functions below use it to keep host-lane events
    out of the device buckets.  Merged/synthetic traces without thread
    metadata get no annotation."""
    p = Path(trace_dir)
    if p.is_file():
        opener = gzip.open if p.name.endswith(".gz") else open
        with opener(p) as f:
            trace = json.load(f)
    else:
        paths = sorted(glob.glob(f"{trace_dir}/**/*.trace.json.gz",
                                 recursive=True))
        if not paths:
            raise FileNotFoundError(f"no trace.json.gz under {trace_dir}")
        with gzip.open(paths[-1]) as f:
            trace = json.load(f)
    raw = trace.get("traceEvents", [])
    threads = {(e.get("pid"), e.get("tid")): (e.get("args") or {}).get("name")
               for e in raw
               if e.get("ph") == "M" and e.get("name") == "thread_name"}
    out = []
    for e in raw:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        t = threads.get((e.get("pid"), e.get("tid")))
        out.append({**e, "_thread": t} if t is not None else e)
    return out


# XLA HLO op names are bare identifiers (fusion.12, copy.3,
# while.1.remat) — no spaces, paths, parens or $-prefixes; this shape
# test drops runtime bookkeeping ("ThreadpoolListener::StartRegion",
# "ThunkExecutor::Execute (wait for completion)") and most host python
# spans ("$profiler.py:226 trace", "PjitFunction(<lambda>)"), which
# share the raw trace's event stream.
_XLA_OP_RE = re.compile(r"^[A-Za-z0-9_.\-]+$")

# SOME host events are bare identifiers too — compiler passes ("dce",
# "algsimp", "backend_compile") whenever a compile lands inside the
# profiled window, argument bookkeeping ("ParseArguments") — but they
# all run on the python dispatch thread, while XLA executor ops run on
# the runtime's own pools (tf_XLAEigen/..., tf_XLATfrtCpuClient/...,
# device lanes on TPU).  The ``_thread`` annotation from
# ``load_trace_events`` separates them where the shape test cannot.
_HOST_THREAD = "python"

# the CPU thunk executor emits a "call" event whose duration encloses
# the child ops it dispatches on the same lane — counting it would
# double-count every child
_WRAPPER_OPS = frozenset({"call"})


def _device_op_name(e: dict) -> str | None:
    """The event's op name when it is device occupancy, else None."""
    if e.get("_thread") == _HOST_THREAD:
        return None
    name = str(e.get("name", ""))
    if name in _WRAPPER_OPS or not _XLA_OP_RE.match(name):
        return None
    return name


def collective_stats(events: list[dict]) -> dict[str, dict]:
    """Per-collective-kind device-occupancy summary (durations in us).

    Ops ``classify_op`` cannot name — fusions, convolutions, copies,
    anything XLA renamed — are NOT dropped: device ops
    (``_device_op_name``) bucket under ``other`` so occupancy
    *fractions* computed from this summary (the attribution engine
    divides a kind's total by the sum over all kinds) are conservative.
    Silently dropping them made every collective look like a larger
    share of device time than it was.  Host-lane events (python spans,
    compiler passes when a compile lands in the window), thunk wrapper
    events, and async completion markers (``end: ...``, which duplicate
    the op they close) stay excluded."""
    by_kind: dict[str, list[float]] = {}
    for e in events:
        name = _device_op_name(e)
        if name is None:
            continue
        kind = classify_op(name) or "other"
        by_kind.setdefault(kind, []).append(float(e["dur"]))
    return {
        kind: {
            "count": len(durs),
            "total_us": sum(durs),
            "mean_us": sum(durs) / len(durs),
            "max_us": max(durs),
        }
        for kind, durs in sorted(by_kind.items())
    }


def top_device_ops(events: list[dict], k: int = 5) -> list[dict]:
    """Top-k device ops by total duration (name-aggregated): the
    per-op channel ``cli.py --profile`` stamps as ``device_top_ops``
    and the attribution engine prefers for its ``top_ops`` field.
    Host-lane events, thunk wrappers, and async completion markers are
    excluded like in ``collective_stats``."""
    totals: dict[str, list[float]] = {}
    for e in events:
        name = _device_op_name(e)
        if name is None:
            continue
        totals.setdefault(name, []).append(float(e["dur"]))
    ranked = sorted(totals.items(), key=lambda kv: -sum(kv[1]))[:max(k, 0)]
    return [{"op": name, "total_us": round(sum(durs), 1),
             "count": len(durs)} for name, durs in ranked]


def profile_collectives(fn, *args, trace_dir: str | Path | None = None,
                        **kwargs) -> dict[str, dict]:
    """Run ``fn`` once under the profiler; return ``collective_stats``.

    ``fn`` should be compiled already (profile the steady state, not
    tracing/compilation).  ``trace_dir`` defaults to a fresh temp dir.
    """
    from dlnetbench_tpu.utils.timing import time_callable

    d = str(trace_dir) if trace_dir else tempfile.mkdtemp(prefix="dlnb_prof_")
    with jax.profiler.trace(d):
        # time_callable's transfer fence truly waits for the device work
        # before the profiler context closes — on the tunnel backend a
        # bare block_until_ready only acks dispatch and would truncate
        # the trace mid-execution
        time_callable(fn, *args, reps=1, **kwargs)
    return collective_stats(load_trace_events(d))


# ---------------------------------------------------------------------
# Structural overlap analysis.  Whether two collectives CAN ride the
# links together is a property of the program's dataflow: XLA may only
# overlap ops with no dependency path between them.  A CPU-mesh trace
# cannot show device-channel overlap (host thunks timeshare cores), so
# the schedulability check is done on the jaxpr — 1F1B's steady up/down
# hop pairs must be mutually independent, GPipe's hops must chain.

def _iter_subjaxprs(jaxpr):
    """The jaxpr and every nested sub-jaxpr (pjit / shard_map / scan...)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for v in eqn.params.values():
            inner = getattr(v, "jaxpr", v)
            if hasattr(inner, "eqns"):
                yield from _iter_subjaxprs(inner)


def permute_dependencies(fn, *args) -> tuple[int, set[tuple[int, int]]]:
    """Trace ``fn`` and analyze its ``ppermute`` ops' mutual dataflow.

    Returns ``(n_permutes, deps)`` where ``deps`` holds ordered pairs
    ``(i, j)``: the j-th permute (program order) transitively consumes the
    i-th's output, so the two can never be in flight together.  Pairs
    absent from ``deps`` are schedulable concurrently by XLA — the 1F1B
    overlap property is ``(i, i+1) not in deps`` for its steady pairs.

    An AOT-compiled program (core/executor.py CompiledProgram) is opaque
    to ``make_jaxpr``; its kept python callable + argument buffers are
    traced instead.
    """
    if not args and hasattr(fn, "traceable"):
        args = fn.example_args
        fn = fn.traceable
    closed = jax.make_jaxpr(fn)(*args)
    # find the (deepest) jaxpr level that actually contains the permutes
    level = None
    for j in _iter_subjaxprs(closed.jaxpr):
        if any(e.primitive.name == "ppermute" for e in j.eqns):
            level = j
            break
    if level is None:
        return 0, set()

    producer: dict = {}            # var -> eqn index
    depsets: list[set] = []        # eqn index -> transitive eqn deps
    permute_eqns: list[int] = []
    for idx, eqn in enumerate(level.eqns):
        deps: set = set()
        for v in eqn.invars:
            if hasattr(v, "count") and v in producer:  # Var, not Literal
                p = producer[v]
                deps.add(p)
                deps |= depsets[p]
        depsets.append(deps)
        for v in eqn.outvars:
            producer[v] = idx
        if eqn.primitive.name == "ppermute":
            permute_eqns.append(idx)

    pairs: set[tuple[int, int]] = set()
    for j_pos, j_eqn in enumerate(permute_eqns):
        for i_pos, i_eqn in enumerate(permute_eqns[:j_pos]):
            if i_eqn in depsets[j_eqn]:
                pairs.add((i_pos, j_pos))
    return len(permute_eqns), pairs
