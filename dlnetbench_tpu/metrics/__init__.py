"""Metrics package: emission, parsing, merging, profiling, spans, stats.

Re-exports are resolved lazily (PEP 562): ``emit`` imports the proxy
harness, which imports ``utils.timing``, which imports ``metrics.spans``
— an eager ``from .emit import ...`` here would close that loop into a
circular-import failure the moment anything imports the timing module
first.
"""
from __future__ import annotations

__all__ = ["emit_result", "result_to_record", "load_records",
           "records_to_dataframe", "get_metrics_dataframe"]

_HOMES = {
    "emit_result": "emit", "result_to_record": "emit",
    "load_records": "parser", "records_to_dataframe": "parser",
    "get_metrics_dataframe": "parser",
}


def __getattr__(name: str):
    home = _HOMES.get(name)
    if home is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    return getattr(importlib.import_module(f"{__name__}.{home}"), name)
