from dlnetbench_tpu.metrics.emit import emit_result, result_to_record
from dlnetbench_tpu.metrics.parser import (
    load_records, records_to_dataframe, get_metrics_dataframe)

__all__ = ["emit_result", "result_to_record", "load_records",
           "records_to_dataframe", "get_metrics_dataframe"]
