"""Merge one-record-per-process multi-host outputs into a single record.

The reference's native tier prints per-rank JSON from every MPI rank into
one job stdout, so its parser sees genuinely per-rank timers
(reference cpp/data_parallel/dp.cpp:291-294, plots/parser.py:139-196).
The rebuild's multi-controller runtime has one *process* per host: each
process measures its own wall-clock timers and emits one record whose
rank rows cover every device of the global mesh — but only the rows of
the emitting process carry that process's real measurements (emit.py
documents the duplication).

``merge_records`` reassembles the reference's shape: given the records
the N processes wrote (one JSONL file per process, or one combined
file), it keeps from each record exactly the rows measured by the
emitting process and returns one record with true per-process timers.
Rank coverage and process coverage are validated; mismatched schedule
metadata aborts the merge (records from different runs must never
silently combine).

CLI:  python -m dlnetbench_tpu.metrics.merge out.jsonl in0.jsonl in1.jsonl ...
"""
from __future__ import annotations

import json
import sys
from pathlib import Path

from dlnetbench_tpu.metrics.parser import load_records, validate_record

# global keys that legitimately differ between the emitting processes:
# per-process measurements (each process calibrates its own burn kernel)
# and host-local identity — never evidence of records from different runs
# (energy_scope rides with energy_source: a host without a counter emits
# neither key, and that heterogeneity must not abort the merge)
_VOLATILE_GLOBALS = {"energy_source", "energy_scope", "burn_ns_per_iter",
                     "cache_hits", "cache_misses", "tcp_bytes_sent",
                     # per-PROCESS share of an uneven-locals hier run
                     # (world % procs != 0): differs by construction;
                     # the process-invariant layout rides "local_worlds"
                     "local_world",
                     # per-process fault measurements (faults/,
                     # fault_plan.hpp): each process detects/recovers
                     # on its own clock and counts its own injected
                     # drops/retries/sleeps; the PLAN ITSELF
                     # (fault_plan/fault_policy/degraded_world,
                     # fault_rejoin_step) must still match — different
                     # plans ARE different runs
                     "detection_ms", "recovery_ms", "fault_drops",
                     "fault_retries", "fault_injected_delay_us",
                     "fault_iteration", "watchdog_heartbeat_age_s",
                     "watchdog_stalls", "watchdog_stall_spans",
                     # elastic-recovery measurements (ISSUE 7): each
                     # process times its own grow re-split, saves on
                     # its own disk, and accounts its own lost work /
                     # goodput; the rejoin TRIGGER (fault_rejoin_step,
                     # plan-derived) must still match
                     "rejoin_ms", "checkpoint_ms", "checkpoint_stall_ms",
                     "checkpoint_ms_samples", "checkpoint_saves",
                     "checkpoint_drain_saved", "restore_ms",
                     "lost_steps", "goodput", "goodput_useful_steps",
                     "goodput_wall_s", "last_checkpoint_age_s",
                     "last_checkpoint_step",
                     # each process times its own fence RTT, profiles
                     # its own device ops, and attributes its own
                     # clocks; the merged record gets attribution
                     # recomputed over the pooled rows below
                     "host_rtt_us", "attribution", "device_top_ops",
                     # serving-tier measurements (serving/): each
                     # process clocks its own requests; the ARRIVAL
                     # PLAN itself ("arrival_plan") stays comparable —
                     # different traffic schedules ARE different runs,
                     # exactly like fault plans.  So does the
                     # ISSUE-12 "kv_cache_dtype" global (differently-
                     # quantized caches are different runs).  The
                     # prefix-sharing STATS are volatile: whether a
                     # prefix owner is still resident when a later
                     # request admits depends on wall-clock arrival
                     # timing vs engine speed, so hit counts
                     # legitimately differ across hosts/reruns of ONE
                     # plan — like every other serving measurement
                     "serving", "prefix_hit_rate", "prefix_bytes_saved",
                     # spec-decode acceptance vs temperature (ISSUE
                     # 19): a MEASUREMENT — acceptance moves with
                     # params/load, so reruns of one plan legitimately
                     # differ.  The "sampling" identity block
                     # (temperature/top_k/top_p/sample_seed/grammar)
                     # is deliberately NOT here: it stays comparable
                     # automatically, so records drawn under different
                     # seeds or temperatures refuse to merge — mixed
                     # draw keys would average incomparable streams
                     "spec_acceptance_by_temp",
                     # tuning provenance (ISSUE 9): each process
                     # consults its own DB on its own disk (and a host
                     # without the env set consults nothing) — per-
                     # process warm state, not run identity.  Process
                     # 0's block survives in the merged record.
                     "tuning",
                     # continuous telemetry (ISSUE 14): each process
                     # records its own flight ring on its own clock and
                     # detects its own anomalies (a straggler's ring
                     # looks different from its victims') — per-process
                     # measurements, never run identity.  Process 0's
                     # blocks survive in the merged record.
                     "telemetry", "anomalies",
                     "watchdog_stall_telemetry",
                     # MoE imbalance measurements (ISSUE 15): each
                     # process measures its own expert-load histogram
                     # and overflow-round counts; the routing KNOBS
                     # (moe_experts/top_k/capacity/skew — in
                     # serving_config and the moe_* globals) stay
                     # comparable: differently-routed runs are
                     # different runs
                     "moe",
                     # fleet-serving measurements (ISSUE 18): the load
                     # histogram, per-replica request counts, affinity
                     # hit rates, scale-event timings and chip-second
                     # spend all depend on live load and host speed —
                     # measurements, pooled like every serving block.
                     # The ROUTING POLICY and fleet width stay
                     # comparable (fleet_routing/fleet_replicas below)
                     "fleet"}
# NOT volatile, by design (ISSUE 18): "fleet_routing" and
# "fleet_replicas" are run IDENTITY — a p2c record must never merge
# with a round_robin one, nor a 2-replica fleet with a 4-replica one
# (their serving latencies answer different questions), exactly like
# mismatched fault or arrival plans.
# NOT volatile, by design (ISSUE 16): the "disaggregated" global (and
# the prefill_ranks/decode_ranks split inside serving_config) is run
# IDENTITY — a disaggregated record must never merge with a monolithic
# one, exactly like mismatched fault or arrival plans.  The migration
# MEASUREMENTS (bytes/ms/overlap) ride inside the already-volatile
# "serving" block.

# scheduler-stamped variables that identify the PROCESS, not the run
# (metrics.emit.scheduler_variables): they legitimately differ between
# the per-host records of one run and must not abort the merge, while
# genuine sweep-axis variables still must match
_PER_PROCESS_VARIABLES = {"slurm_procid", "tpu_worker_id",
                          "job_completion_index", "megascale_slice_id"}


def _comparable_global(g: dict) -> dict:
    out = {k: v for k, v in g.items() if k not in _VOLATILE_GLOBALS}
    if isinstance(out.get("variables"), dict):
        out["variables"] = {k: v for k, v in out["variables"].items()
                            if k not in _PER_PROCESS_VARIABLES}
        if not out["variables"]:
            del out["variables"]
    return out


def merge_records(records: list[dict]) -> dict:
    """Combine per-process records of ONE run into a single record.

    Each input record contributes the rank rows whose ``process_index``
    equals its emitting ``process`` (every process measures only its own
    clock).  The result carries the union of rows, per-process warmup
    times, and process-0's globals.
    """
    if not records:
        raise ValueError("merge_records: no records given")
    by_process: dict[int, dict] = {}
    for rec in records:
        proc = rec.get("process", 0)
        if proc in by_process:
            raise ValueError(
                f"merge_records: two records claim process {proc} — inputs "
                f"must be one record per process of one run")
        by_process[proc] = rec

    base = by_process.get(0)
    if base is None:
        # degraded pathway (fault-plan shrink runs): rank 0's process
        # may BE the scripted victim — record-less by design.  Accept
        # the lowest surviving process as the base iff the survivors
        # themselves declare the degradation; anything else is still a
        # missing host.
        first = by_process[min(by_process)]
        if first["global"].get("degraded_world") is None:
            raise ValueError("merge_records: no record from process 0")
        base = first
    want = _comparable_global(base["global"])
    for proc, rec in sorted(by_process.items()):
        if rec.get("section") != base.get("section"):
            raise ValueError(
                f"merge_records: section mismatch "
                f"({rec.get('section')!r} vs {base.get('section')!r})")
        if _comparable_global(rec["global"]) != want:
            diff = {k for k in set(want) | set(_comparable_global(rec["global"]))
                    if want.get(k) != rec["global"].get(k)}
            raise ValueError(
                f"merge_records: process {proc} global metadata differs on "
                f"{sorted(diff)} — records are not from the same run")
        if rec.get("num_runs") != base.get("num_runs"):
            raise ValueError(
                f"merge_records: process {proc} ran {rec.get('num_runs')} "
                f"iterations, process 0 ran {base.get('num_runs')}")
        # v1 and v2 records both merge, but never with each other — a
        # mixed set means the hosts ran different harness builds, and
        # half the merged rows would silently lack their band summaries
        if rec.get("version") != base.get("version"):
            raise ValueError(
                f"merge_records: process {proc} emitted schema version "
                f"{rec.get('version')}, process 0 emitted "
                f"{base.get('version')} — records are from different "
                f"harness builds")

    declared = base["global"].get("num_processes")
    degraded = base["global"].get("degraded_world")
    if declared is not None and sorted(by_process) != list(range(declared)):
        if degraded is None:
            raise ValueError(
                f"merge_records: have records from processes "
                f"{sorted(by_process)}, expected range({declared}) — a "
                f"host's output is missing")
        # shrink run: dead ranks' processes emit nothing.  The survivor
        # records must still jointly cover degraded_world exactly (the
        # final validate_record), so a missing SURVIVOR is still caught.
        if any(p < 0 or p >= declared for p in by_process):
            raise ValueError(
                f"merge_records: process ids {sorted(by_process)} outside "
                f"range({declared})")

    ranks = []
    for proc, rec in sorted(by_process.items()):
        local = [row for row in rec.get("ranks", [])
                 if row.get("process_index", 0) == proc]
        if not local:
            raise ValueError(
                f"merge_records: process {proc}'s record has no rows for "
                f"its own process_index")
        ranks.extend(local)

    # energy_consumed brackets a HOST counter (RAPL/hwmon), but every
    # process's designated rank records it — with several processes per
    # host (the --procs N hier runs, co-hosted congestion pairs) the
    # merged record would carry the host's energy once per process and
    # Pareto/average analyses would double-count.  Keep one energy row
    # per hostname: the lowest (process, rank) wins, the rest drop the
    # key.  Rows without a hostname are conservatively left alone.
    seen_hosts: set = set()
    for row in sorted(ranks, key=lambda r: (r.get("process_index", 0),
                                            r.get("rank", 0))):
        if "energy_consumed" not in row:
            continue
        host = row.get("hostname")
        if host is None:
            continue
        if host in seen_hosts:
            del row["energy_consumed"]
            # the v2 band summary is the channel readers are told to
            # consume — it must not keep reporting the deduped energy
            if isinstance(row.get("summary"), dict):
                row["summary"].pop("energy_consumed", None)
        else:
            seen_hosts.add(host)

    ranks.sort(key=lambda row: row["rank"])

    merged = {k: v for k, v in base.items() if k != "ranks"}
    merged["ranks"] = ranks
    merged["warmup_times_by_process"] = {
        str(proc): rec.get("warmup_times", [])
        for proc, rec in sorted(by_process.items())
    }
    validate_record(merged)
    # anomalies pooled over the processes (ISSUE 14): each process's
    # flight recorder detects on its own clock, and an anomaly anywhere
    # in the fleet matters — base-process-only globals would silently
    # drop a straggler's step_time trigger recorded on another host.
    # Events keep their origin via a "process" tag; the telemetry RING
    # stays per-process (process 0's block) like every other volatile.
    pooled_events, pooled_counts = [], {}
    for proc, rec in sorted(by_process.items()):
        anom = rec["global"].get("anomalies")
        if not isinstance(anom, dict):
            continue
        for k, v in (anom.get("triggers") or {}).items():
            pooled_counts[k] = pooled_counts.get(k, 0) + int(v)
        for ev in anom.get("events") or []:
            pooled_events.append({**ev, "process": proc})
    if pooled_counts:
        merged["global"] = dict(merged["global"])
        merged["global"]["anomalies"] = {
            "count": sum(pooled_counts.values()),
            "triggers": pooled_counts,
            "events": pooled_events[-16:]}
    # attribution over the POOLED per-process rows (each input record's
    # block covered only its own clocks).  This is also where NATIVE
    # records — whose C++ emitter stamps no attribution — get theirs
    # mirrored from the timer summaries they do carry.  Derived data: a
    # failure must never abort a merge of valid measurements.
    try:
        from dlnetbench_tpu.analysis.attribution import attribute_record
        merged["global"] = dict(merged["global"])
        block = attribute_record(merged)
        if block is not None:
            merged["global"]["attribution"] = block
        else:
            merged["global"].pop("attribution", None)
    except Exception as e:  # pragma: no cover - defensive
        print(f"merge attribution failed ({type(e).__name__}: {e}); "
              f"merged record keeps its inputs", file=sys.stderr)
    return merged


def merge_files(out_path: str | Path, in_paths: list[str | Path],
                section: str | None = None) -> dict:
    """Load one record per input file (per process), merge, append the
    merged record to ``out_path``."""
    records = []
    for p in in_paths:
        recs = load_records(p, section)
        if len(recs) != 1:
            raise ValueError(
                f"{p}: expected exactly one record"
                f"{f' for section {section!r}' if section else ''}, "
                f"found {len(recs)} — merge one run at a time")
        records.append(recs[0])
    merged = merge_records(records)
    with open(out_path, "a") as f:
        f.write(json.dumps(merged) + "\n")
    return merged


def main(argv: list[str] | None = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    usage = ("usage: python -m dlnetbench_tpu.metrics.merge "
             "[--section NAME] [--trace-out TRACE.json] "
             "OUT.jsonl IN0.jsonl IN1.jsonl ...")
    section = None
    trace_out = None
    while args and args[0] in ("--section", "--trace-out", "--trace_out"):
        if len(args) < 2:
            print(usage, file=sys.stderr)
            return 2
        if args[0] == "--section":
            section = args[1]
        else:
            trace_out = args[1]
        args = args[2:]
    if len(args) < 2:
        print(usage, file=sys.stderr)
        return 2
    merged = merge_files(args[0], args[1:], section)
    print(f"merged {len(args) - 1} process record(s): "
          f"{merged['section']}, {len(merged['ranks'])} ranks "
          f"-> {args[0]}", file=sys.stderr)
    if trace_out:
        # the native tier has no in-process tracer, but its record
        # carries per-rank timers + band summaries + (post-merge) the
        # attribution block — rendered as Perfetto counter/duration
        # tracks so --trace-out serves native runs too
        from dlnetbench_tpu.metrics import spans
        try:
            spans.write_chrome_trace(
                trace_out, None, align_span=None,
                extra_events=spans.record_track_events(merged))
            print(f"record trace -> {trace_out}", file=sys.stderr)
        except OSError as e:
            print(f"trace-out write failed ({e}); merged record "
                  f"unaffected", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
