"""Metrics parsing: JSON-lines run records -> pandas DataFrames.

Counterpart of the reference's analysis ingest (reference
plots/parser.py:139-256): where that walks sbatchman job stdout through the
ccutils MPIOutputParser and builds one row per rank x run, this walks
JSON-lines files produced by ``metrics.emit`` and builds the same shape:
one row per rank x run with ``runtime`` and the per-collective timers, plus
the globals (model, world size, message sizes) replicated onto each row —
ready for groupby/plotting.

Validation mirrors ``validate_dp_output`` (reference plots/parser.py:102-136):
every emitted record must cover the full expected rank set.
"""
from __future__ import annotations

import json
from pathlib import Path

# non-timer per-rank keys; "summary" is the schema-v2 per-timer band
# summaries (dict, not a sample array — must never be iterated as runs)
_TIMER_KEYS_EXCLUDE = {"rank", "device_id", "process_index", "hostname",
                       "coords", "summary"}


def load_records(path: str | Path, section: str | None = None) -> list[dict]:
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: bad JSON record: {e}") from e
            if section is None or rec.get("section") == section:
                records.append(rec)
    return records


def validate_record(rec: dict) -> None:
    """Every device rank must have reported (reference
    plots/parser.py:102-136 'did every rank report'), every declared
    process must be represented, and the host set must be plausible for
    the process count (the reference's hostname-vs-node-count check).

    Degraded pathway: a record whose globals declare ``degraded_world``
    (a fault-plan ``shrink`` run — faults/, native fault_plan.hpp) must
    cover exactly the SURVIVOR rank set instead of range(world), and
    processes owned entirely by dead ranks may legitimately be absent.
    Only an explicit declaration relaxes the checks — a record missing
    ranks without saying why still fails."""
    world = rec["global"].get("world_size")
    rows = rec.get("ranks", [])
    ranks = [r["rank"] for r in rows]
    degraded = rec["global"].get("degraded_world")
    if degraded is not None:
        degraded = sorted(int(r) for r in degraded)
        if world is not None and not all(0 <= r < world for r in degraded):
            raise ValueError(
                f"record for {rec.get('section')}: degraded_world "
                f"{degraded} outside range({world})")
        if sorted(ranks) != degraded:
            raise ValueError(
                f"record for {rec.get('section')}/"
                f"{rec['global'].get('model')}: rank set {sorted(ranks)} "
                f"!= declared degraded_world {degraded}")
    elif world is not None and sorted(ranks) != list(range(world)):
        raise ValueError(
            f"record for {rec.get('section')}/{rec['global'].get('model')}: "
            f"rank set {sorted(ranks)} != range({world})")
    n = rec.get("num_runs")
    for row in rows:
        for k, v in row.items():
            if k not in _TIMER_KEYS_EXCLUDE and isinstance(v, list) and n \
                    and len(v) != n:
                raise ValueError(
                    f"rank {row['rank']} timer {k!r} has {len(v)} entries, "
                    f"expected {n}")
        # schema v2: a summary must describe the samples it rides with
        for k, s in (row.get("summary") or {}).items():
            vals = row.get(k)
            if isinstance(vals, list) and s.get("n") != len(vals):
                raise ValueError(
                    f"rank {row['rank']} summary for {k!r} claims n="
                    f"{s.get('n')} but the timer has {len(vals)} samples")
    num_procs = rec["global"].get("num_processes")
    if num_procs is not None:
        procs = sorted({row.get("process_index", 0) for row in rows})
        if degraded is not None:
            # a dead rank's process (tcp: one rank per process) emits
            # nothing; the surviving processes must still be a sane
            # subset of the declared set
            if not procs or not all(0 <= p < num_procs for p in procs):
                raise ValueError(
                    f"record for {rec.get('section')}: degraded process "
                    f"coverage {procs} outside range({num_procs})")
        elif procs != list(range(num_procs)):
            raise ValueError(
                f"record for {rec.get('section')}: process coverage "
                f"{procs} != range({num_procs}) — a host did not report")
        hosts = {row.get("hostname") for row in rows}
        if len(hosts) > num_procs:
            raise ValueError(
                f"record for {rec.get('section')}: {len(hosts)} distinct "
                f"hostnames for {num_procs} processes")


def records_to_dataframe(records: list[dict], validate: bool = True):
    """One row per rank x run; globals and mesh info as columns."""
    import pandas as pd

    rows = []
    for rec in records:
        if validate:
            validate_record(rec)
        g = rec.get("global", {})
        mesh = rec.get("mesh", {})
        for rank_row in rec.get("ranks", []):
            timers = {k: v for k, v in rank_row.items()
                      if k not in _TIMER_KEYS_EXCLUDE and isinstance(v, list)}
            n = rec.get("num_runs") or max((len(v) for v in timers.values()),
                                           default=0)
            for run in range(n):
                row = {
                    "section": rec.get("section"),
                    "run": run,
                    "rank": rank_row["rank"],
                    "device_id": rank_row.get("device_id"),
                    "hostname": rank_row.get("hostname"),
                    "platform": mesh.get("platform"),
                    "device_kind": mesh.get("device_kind"),
                }
                # sweep/job variables (reference: sbatchman job.variables,
                # plots/parser.py:238) hoisted to plain columns.  Globals
                # win over same-named (string-typed) tags, and neither may
                # clobber the structural columns already in the row.
                for k, v in {**g.get("variables", {}), **g}.items():
                    if k in row:
                        continue
                    if isinstance(v, list):
                        row[k] = tuple(v)  # hashable, groupby-safe
                    elif not isinstance(v, dict):
                        row[k] = v
                # attribution verdict (a dict global, skipped above):
                # the one-word bound is groupby-grade and rides as its
                # own column; v1/pre-attribution records simply lack it
                attr = g.get("attribution")
                if isinstance(attr, dict) and attr.get("bound"):
                    row["attr_bound"] = attr["bound"]
                # anomaly engine (a dict global, skipped above): the
                # groupby-grade count rides as a plain column — "did
                # this run trip its flight recorder" is the first
                # question a sweep post-mortem asks.  Clean/untelemetered
                # records simply lack the block (column absent/NaN).
                anom = g.get("anomalies")
                if isinstance(anom, dict) and anom.get("count"):
                    row["anomaly_count"] = int(anom["count"])
                # tuning provenance (a dict global, skipped above): the
                # groupby-grade summary — "hits/consults" — rides as a
                # plain column; untuned/v1 records simply lack it
                tun = g.get("tuning")
                if isinstance(tun, dict):
                    hits = int(tun.get("hits", 0))
                    total = hits + int(tun.get("misses", 0))
                    row["tuned"] = f"{hits}/{total}"
                # MoE imbalance block (a dict global, skipped above —
                # ISSUE 15): hoist the expert-load axes a skew study
                # grids by; dense/pre-MoE records simply lack them
                moe = g.get("moe")
                if isinstance(moe, dict):
                    for mk in ("load_imbalance", "rounds_mean",
                               "drop_rate", "router_entropy"):
                        if mk in moe:
                            row[f"moe_{mk}"] = moe[mk]
                    if "expert_load" in moe:
                        row["moe_expert_load_max"] = max(
                            moe["expert_load"], default=0.0)
                # serving block (a dict global, skipped above): hoist
                # the latency-vs-load axes — offered load, the tail
                # percentiles and goodput-at-SLO — to plain columns so
                # a load sweep groups like any other study grid;
                # training records simply lack them
                srv = g.get("serving")
                if isinstance(srv, dict):
                    row["serving_offered_rps"] = srv.get("offered_rps")
                    row["serving_goodput_rps"] = srv.get("goodput_rps")
                    row["serving_goodput_frac"] = srv.get("goodput_frac")
                    # ISSUE 12 capacity axis: peak concurrent resident
                    # sequences (the equal-pool-bytes A/B's y-axis);
                    # pre-density records simply lack the key.  The
                    # cache-dtype / prefix-hit globals are plain
                    # scalars and hoist via the generic loop above.
                    if "admitted_concurrency_peak" in srv:
                        row["serving_admitted_peak"] = \
                            srv["admitted_concurrency_peak"]
                    for base in ("ttft_ms", "tpot_ms", "e2e_ms"):
                        pcts = srv.get(base)
                        if isinstance(pcts, dict):
                            for p in ("p50", "p99"):
                                if p in pcts:
                                    row[f"serving_{base[:-3]}_{p}_ms"] \
                                        = pcts[p]
                    # the ISSUE 11 dispatch decomposition: how many
                    # device decode steps each host dispatch amortized
                    # and what a crossing cost — the columns the
                    # N-step A/B grids by
                    dl = srv.get("decode_loop")
                    if isinstance(dl, dict):
                        row["serving_steps_per_dispatch"] = \
                            dl.get("steps_per_dispatch")
                        row["serving_tokens_per_sync"] = \
                            dl.get("tokens_per_sync")
                        hd = dl.get("host_dispatch_us")
                        if isinstance(hd, dict):
                            row["serving_host_dispatch_us_p50"] = \
                                hd.get("p50")
                        spec = dl.get("spec")
                        if isinstance(spec, dict):
                            row["serving_spec_acceptance"] = \
                                spec.get("acceptance_rate")
                    # ISSUE 16 disaggregation: the page-migration wire
                    # accounting rides as plain columns so a Pareto
                    # sweep grids by wire cost next to the latency
                    # axes; monolithic/pre-disagg records simply lack
                    # the block (the `disaggregated` global itself is
                    # a plain scalar and hoists via the generic loop)
                    mig = srv.get("migration")
                    if isinstance(mig, dict):
                        row["serving_migration_bytes"] = \
                            mig.get("bytes")
                        row["serving_migration_bytes_ratio"] = \
                            mig.get("bytes_ratio_vs_bf16")
                        row["serving_migration_overlap"] = \
                            mig.get("overlap")
                        ms = mig.get("ms")
                        if isinstance(ms, dict):
                            row["serving_migration_ms_p50"] = \
                                ms.get("p50")
                # fleet block (a dict global, skipped above — ISSUE
                # 18): hoist the routing-comparison axes — per-replica
                # spread, affinity wins, elastic chip-second spend —
                # to plain columns so a policy A/B grids like any
                # other study (fleet_routing/fleet_replicas are plain
                # scalars and hoist via the generic loop above);
                # single-engine records simply lack the block
                flt = g.get("fleet")
                if isinstance(flt, dict):
                    rpr = flt.get("requests_per_replica")
                    if isinstance(rpr, list) and rpr:
                        row["fleet_replica_req_max"] = max(rpr)
                        row["fleet_replica_req_min"] = min(rpr)
                    for fk in ("affinity_hit_rate",
                               "prefix_reuse_tokens",
                               "chip_seconds_used",
                               "chip_seconds_saved",
                               "slo_goodput_per_chip_s"):
                        if fk in flt:
                            row[f"fleet_{fk}"] = flt[fk]
                    ev = flt.get("scale_events")
                    if isinstance(ev, list):
                        row["fleet_scale_events"] = len(ev)
                for tname, tvals in timers.items():
                    if run < len(tvals):
                        # singular column names a la reference ('runtime')
                        col = tname[:-1] if tname.endswith("s") else tname
                        row[col] = tvals[run]
                rows.append(row)
    return pd.DataFrame(rows)


def get_metrics_dataframe(path: str | Path, strategy: str | None = None,
                          validate: bool = True):
    """Reference-parity convenience: ``get_metrics_dataframe('runs.jsonl',
    'dp')`` -> DataFrame (reference plots/parser.py:213-256)."""
    return records_to_dataframe(load_records(path, strategy), validate)
