"""Continuous telemetry: the per-step flight recorder + anomaly engine.

Every observability channel the harness had before this module is
post-mortem and run-granular: spans time harness *phases*, attribution
fractions and the serving block summarize a *finished* run into medians
and bands.  A mid-run anomaly — a straggler window, an SLO breach, a
KV-pool squeeze — was only visible as a fatter band after the fact.

``FlightRecorder`` is the missing channel: a fixed-capacity ring buffer
of per-step samples (step wall, phase timers, serving queue depth /
admitted concurrency / KV occupancy / prefix hit rate / spec
acceptance, decode-loop sync costs, per-step energy, heartbeat ages),
fed from the measurement loops (``proxies/base.py``,
``serving/scheduler.py``) and from the watchdog.  Like ``spans.py``,
telemetry is OFF by default and the disabled path allocates nothing
per step: every sampling site gates on ``is_enabled()`` (one global
load + one ``is None`` test), so an untelemetered run's records are
byte-identical to a pre-telemetry build's (fixture-locked in
``tests/test_telemetry.py``).

The **anomaly engine** rides the recorder.  Triggers:

  ``stall``      — the watchdog's deadline fired (utils/watchdog.py)
  ``fault``      — a scripted crash/preemption was detected
                   (faults/policy.py, serving/scheduler.run_serving)
  ``slo``        — a rolling window of completions breached the SLO
                   (serving/metrics.rolling_slo_breach — the
                   ``goodput_timeline`` windowing applied live)
  ``step_time``  — band-aware step-time change detection
                   (``observe_step_wall``: the trailing window's band
                   sits above — and disjoint from — the baseline band,
                   metrics/stats.py conventions)

Each trigger appends an anomaly event, dumps the aligned ring window as
``flight_<trigger>.json`` into ``dump_dir`` (cooldown + per-kind dump
cap, so a pathological run cannot dump-storm the disk), and the
engine's ``anomalies_block``/``telemetry_block`` are stamped into the
emitted record by ``metrics/emit.py`` (volatile at merge — each
process records its own ring; the parser hoists an ``anomaly_count``
column).  ``spans.telemetry_counter_events`` renders the ring as
Perfetto counter tracks next to the host/device timelines.

``analysis/critical_path.py`` consumes the per-rank step series this
module (and its native twin, ``timers.hpp`` ``TelemetryRing``)
produces, merging rank timelines into per-step critical-path blame.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path

from dlnetbench_tpu.metrics.stats import bands_overlap, summarize

DEFAULT_CAPACITY = 512

# step-time change detector: the trailing RECENT_K samples' band must
# sit entirely above the baseline band, with the recent median at least
# (1 + STEP_TIME_MARGIN) x the baseline median — band-disjointness
# alone would trip on clock-resolution jitter for microsecond steps
RECENT_K = 5
BASELINE_MIN = 8
STEP_TIME_MARGIN = 0.5

TRIGGER_KINDS = ("stall", "fault", "slo", "step_time")


class FlightRecorder:
    """Fixed-capacity ring of per-step telemetry samples + the anomaly
    engine over them.  Thread-safe (the watchdog's Timer thread and the
    measuring thread both touch it); one recorder per process is the
    intended shape (module-level ``enable``/``current``)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: str | Path | None = None, *,
                 cooldown_s: float = 1.0, max_dumps_per_trigger: int = 4):
        if capacity < 1:
            raise ValueError(f"telemetry: capacity must be >= 1, "
                             f"got {capacity}")
        self.capacity = int(capacity)
        self.dump_dir = Path(dump_dir) if dump_dir is not None else None
        self.origin = time.monotonic()
        self.cooldown_s = float(cooldown_s)
        self.max_dumps_per_trigger = int(max_dumps_per_trigger)
        self._buf: list[dict | None] = [None] * self.capacity
        self._n = 0                       # total samples ever recorded
        self._lock = threading.Lock()
        self.anomalies: list[dict] = []
        self._dump_counts: dict[str, int] = {}
        self._last_trigger_t: dict[str, float] = {}
        # step-time detector state: source -> deque of recent walls
        self._walls: dict[str, deque] = {}

    # ---- the ring ----------------------------------------------------
    def now_s(self) -> float:
        return time.monotonic() - self.origin

    def record(self, source: str, step: int | None = None,
               **fields) -> dict:
        """Append one per-step sample.  ``source`` names the feeding
        loop (``proxy``, ``serving``, ``watchdog`` ...); ``fields`` are
        numeric series (units in the name: ``step_wall_us``,
        ``queue_depth``, ``kv_occupancy`` ...)."""
        sample = {"t_s": round(self.now_s(), 6), "source": source}
        if step is not None:
            sample["step"] = int(step)
        sample.update(fields)
        with self._lock:
            self._buf[self._n % self.capacity] = sample
            self._n += 1
        return sample

    @property
    def recorded(self) -> int:
        return self._n

    @property
    def dropped(self) -> int:
        """Samples that fell off the ring (recorded - resident)."""
        return max(0, self._n - self.capacity)

    def samples(self) -> list[dict]:
        """Resident samples, oldest first."""
        with self._lock:
            n, cap = self._n, self.capacity
            if n <= cap:
                return [s for s in self._buf[:n] if s is not None]
            head = n % cap
            return [s for s in self._buf[head:] + self._buf[:head]
                    if s is not None]

    def last(self, k: int) -> list[dict]:
        return self.samples()[-max(int(k), 0):]

    def window(self, t_lo: float | None = None,
               t_hi: float | None = None) -> list[dict]:
        """Resident samples with ``t_lo <= t_s <= t_hi`` (None = open)."""
        return [s for s in self.samples()
                if (t_lo is None or s["t_s"] >= t_lo)
                and (t_hi is None or s["t_s"] <= t_hi)]

    # ---- band-aware step-time change detection -----------------------
    def reset_walls(self, source: str | None = None) -> None:
        """Drop the change detector's wall history for ``source`` (all
        sources when None).  Callers starting a structurally new run
        over a live recorder (a different engine in a bench A/B, a
        fresh run_proxy invocation) must re-baseline — the new run's
        honest steady state is not an anomaly against the old run's."""
        if source is None:
            self._walls.clear()
        else:
            self._walls.pop(source, None)

    def observe_step_wall(self, source: str, wall_us: float,
                          step: int | None = None) -> dict | None:
        """Feed one step's wall time to the change detector.  Fires a
        ``step_time`` anomaly when the last ``RECENT_K`` samples'
        band sits entirely above the preceding baseline's band
        (``metrics/stats`` conventions: disjoint bands are the one
        honest statement of "distinguishable from noise") AND the
        recent median exceeds the baseline median by
        ``STEP_TIME_MARGIN``.  Returns the anomaly event when fired."""
        hist = self._walls.get(source)
        if hist is None:
            hist = self._walls[source] = deque(
                maxlen=BASELINE_MIN * 8 + RECENT_K)
        hist.append(float(wall_us))
        if len(hist) < BASELINE_MIN + RECENT_K:
            return None
        vals = list(hist)
        base = summarize(vals[:-RECENT_K])
        recent = summarize(vals[-RECENT_K:])
        if bands_overlap(base["band"], recent["band"]) is not False:
            return None
        if recent["value"] <= base["value"] * (1.0 + STEP_TIME_MARGIN) \
                or recent["best"] <= base["band"][1]:
            return None
        ev = self.trigger("step_time", step=step, detail={
            "source": source,
            "baseline_us": base, "recent_us": recent,
            "ratio": round(recent["value"] / base["value"], 3)
            if base["value"] > 0 else None})
        # re-baseline so a sustained shift fires once, not every step
        hist.clear()
        return ev

    # ---- the anomaly engine ------------------------------------------
    def trigger(self, kind: str, step: int | None = None,
                detail: dict | None = None) -> dict | None:
        """Record one anomaly; dumps the aligned ring window as
        ``flight_<kind>.json`` when ``dump_dir`` is set.  Per-kind
        cooldown: re-triggers inside ``cooldown_s`` are dropped (a
        breach spanning many steps is ONE anomaly, not a dump storm).
        Returns the event, or None when throttled."""
        t = self.now_s()
        with self._lock:
            last = self._last_trigger_t.get(kind)
            if last is not None and t - last < self.cooldown_s:
                return None
            self._last_trigger_t[kind] = t
        ev: dict = {"trigger": kind, "t_s": round(t, 6)}
        if step is not None:
            ev["step"] = int(step)
        if detail:
            ev["detail"] = detail
        dump = self._write_dump(kind, ev)
        if dump is not None:
            ev["dump"] = dump
        with self._lock:
            self.anomalies.append(ev)
        return ev

    def _write_dump(self, kind: str, ev: dict) -> str | None:
        if self.dump_dir is None:
            return None
        with self._lock:
            count = self._dump_counts.get(kind, 0)
            if count >= self.max_dumps_per_trigger:
                return None
            self._dump_counts[kind] = count + 1
        name = (f"flight_{kind}.json" if count == 0
                else f"flight_{kind}_{count + 1}.json")
        payload = {
            "trigger": kind,
            "t_s": ev["t_s"],
            **({"step": ev["step"]} if "step" in ev else {}),
            **({"detail": ev["detail"]} if "detail" in ev else {}),
            "capacity": self.capacity,
            "recorded": self._n,
            # the aligned ring window INTO the anomaly: everything the
            # ring still holds up to the trigger instant — the trend
            # into the event, not just the frozen instant
            "samples": self.window(t_hi=ev["t_s"]),
        }
        try:
            self.dump_dir.mkdir(parents=True, exist_ok=True)
            path = self.dump_dir / name
            with open(path, "w") as f:
                json.dump(payload, f)
            return str(path)
        except OSError as e:  # derived data must never cost the run
            import sys
            print(f"telemetry: flight dump {name} failed ({e}); "
                  f"anomaly recorded without it", file=sys.stderr)
            return None

    # ---- record stamping ---------------------------------------------
    def telemetry_block(self, last: int = 16) -> dict:
        """The record's ``telemetry`` global: ring geometry + the last
        few resident samples (the FULL ring rides flight dumps, not
        records — a 512-sample ring would bloat every artifact).
        Volatile at merge: each process records its own ring."""
        tail = self.last(last)
        return {
            "capacity": self.capacity,
            "recorded": self._n,
            "dropped": self.dropped,
            "sources": sorted({s["source"] for s in tail}
                              | set(self._walls)),
            "last": tail,
        }

    def anomalies_block(self) -> dict | None:
        """The record's ``anomalies`` global, or None when the run was
        clean (a clean telemetered record carries the telemetry block
        but no anomalies key — absence IS the verdict)."""
        with self._lock:
            events = list(self.anomalies)
        if not events:
            return None
        counts: dict[str, int] = {}
        for ev in events:
            counts[ev["trigger"]] = counts.get(ev["trigger"], 0) + 1
        return {"count": len(events), "triggers": counts,
                "events": events[-16:]}


# ---------------------------------------------------------------------
# Module-level current recorder — the spans.py no-op-singleton pattern:
# ``None`` means disabled (the common case) and every hot sampling site
# gates on ``is_enabled()`` (one global load + one ``is None`` test)
# before building its kwargs, so the disabled path allocates NOTHING
# per step (locked by tests/test_telemetry.py).

_RECORDER: FlightRecorder | None = None


def enable(capacity: int | None = None,
           dump_dir: str | Path | None = None) -> FlightRecorder:
    """Install (and return) a fresh recorder as the process recorder.
    ``capacity``/``dump_dir`` default from ``DLNB_TELEMETRY_CAPACITY``
    and ``DLNB_FLIGHT_DIR``."""
    global _RECORDER
    if capacity is None:
        capacity = int(os.environ.get("DLNB_TELEMETRY_CAPACITY",
                                      DEFAULT_CAPACITY))
    if dump_dir is None:
        dump_dir = os.environ.get("DLNB_FLIGHT_DIR") or None
    _RECORDER = FlightRecorder(capacity, dump_dir)
    return _RECORDER


def disable() -> FlightRecorder | None:
    """Stop recording; returns the recorder that was active (with its
    ring and anomalies) so callers can stamp/export after the run."""
    global _RECORDER
    r, _RECORDER = _RECORDER, None
    return r


def current() -> FlightRecorder | None:
    return _RECORDER


def is_enabled() -> bool:
    return _RECORDER is not None


def enable_from_env() -> FlightRecorder | None:
    """Enable iff ``DLNB_TELEMETRY`` is set truthy (the env channel for
    drivers that cannot pass flags); an already-active recorder wins."""
    if _RECORDER is not None:
        return _RECORDER
    if os.environ.get("DLNB_TELEMETRY", "") in ("", "0", "false", "off"):
        return None
    return enable()


def record_step(source: str, step: int | None = None, **fields) -> None:
    """Record one sample when enabled; free when not.  Hot sites should
    additionally gate on ``is_enabled()`` BEFORE assembling ``fields``
    — a kwargs dict is an allocation the disabled contract forbids."""
    r = _RECORDER
    if r is None:
        return
    r.record(source, step, **fields)


def trigger(kind: str, step: int | None = None,
            detail: dict | None = None) -> dict | None:
    """Fire an anomaly on the current recorder ({} -> noop when off)."""
    r = _RECORDER
    if r is None:
        return None
    return r.trigger(kind, step=step, detail=detail)


def observe_step_wall(source: str, wall_us: float,
                      step: int | None = None) -> None:
    r = _RECORDER
    if r is None:
        return
    r.observe_step_wall(source, wall_us, step=step)
