"""Continuous-batching engine: the serving schedule worth reproducing.

The Orca/vLLM loop, measured honestly on the wall clock: requests
arrive on an OPEN-LOOP schedule (serving/arrivals.py — arrivals never
wait for the server), are admitted from the queue into free decode
slots whenever pages for their worst case (prompt + output) can be
reserved, prefill either as a separate phase at admit time or
inline-chunked one chunk per engine step, decode one token per active
slot per step over the paged KV cache, and evict on completion.  A
saturated engine builds a queue; TTFT p99 blows up — the knee
``examples/pod_study.py --serving`` sweeps for.

The host/device state split (ISSUE 11): the engine keeps HOST-side
scheduling state — the arrival queue, pending list, page free-list,
per-request stamps — while decode-phase slot state (last tokens,
positions, active/done bits, remaining budgets, block tables) lives on
DEVICE between syncs (``serving/device_state.py``) whenever
``multi_step_n > 1`` or speculative decode is on: one fused
``lax.while_loop`` program runs up to N decode steps (or draft/verify
rounds) per host dispatch, and the host crosses the boundary only at
admission points, every crossing a recorded timer.  ``multi_step_n=1``
without speculation keeps the classic one-dispatch-per-token engine
bit-identically (the loop program is not even built — locked by test).

Fault composition (the payoff of riding the existing record schema):
``run_serving`` takes the SAME fault plan the training tier uses —
``delay``/``jitter`` events sleep at engine-step boundaries inside the
measured loop (a straggler decode step inflates every in-flight
request's latency, which is what a straggler does to a serving fleet),
and a ``crash`` under policy ``shrink`` costs capacity: the engine
loses the dead rank's share of decode slots, in-flight requests are
re-queued on a rebuilt (recompiled — priced) engine with their ORIGINAL
arrival stamps, so the disruption lands in their latency and the
record's SLO-goodput timeline shows the dip and the recovery arc
(the segmentation mirrors ``faults/policy.run_faulted``).
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax
import jax.numpy as jnp
import numpy as np

from dlnetbench_tpu.core import executor
from dlnetbench_tpu.metrics import spans, telemetry
from dlnetbench_tpu.models.transformer import (TransformerConfig,
                                               init_params)
from dlnetbench_tpu.serving import decode as D
from dlnetbench_tpu.serving import metrics as M
from dlnetbench_tpu.serving import requeue
from dlnetbench_tpu.serving.arrivals import ArrivalPlan, Request
from dlnetbench_tpu.serving.kv_cache import (CACHE_DTYPES, CacheConfig,
                                             PagedKVCache,
                                             device_buffers)

PREFILL_MODES = ("separate", "inline")


@dataclasses.dataclass
class ServingConfig:
    """Engine knobs (docs/SERVING.md documents the trade-offs)."""
    slots: int = 4              # decode slots = max continuous batch
    page_size: int = 8          # tokens per KV page
    num_pages: int = 64         # physical pages shared by all slots
    max_seq_len: int = 64       # per-request cap (prompt + output)
    prefill: str = "separate"   # "separate" (drain at admit) | "inline"
    prefill_chunk: int = 16     # prompt tokens per prefill program call
    slo_ttft_ms: float = 500.0
    slo_tpot_ms: float = 200.0
    world: int = 1              # capacity ranks (fault-shrink unit):
                                # slots are split evenly across ranks,
                                # a crashed rank takes its share down
    attn_impl: str = "auto"     # kv_cache.paged_attention_decode impl
    kv_shard: int = 1           # >1: shard_map along GQA KV heads over
                                # the first kv_shard devices
    multi_step_n: int = 1       # decode steps fused per host dispatch
                                # (ISSUE 11): 1 = the classic one-
                                # dispatch-per-token engine, BIT-
                                # identical by construction (the loop
                                # program is not even built); >1 runs
                                # up to N steps inside one compiled
                                # lax.while_loop with slot state on
                                # device, host sync at admission
                                # boundaries only
    adaptive_n: bool = True     # cap N by the shortest remaining
                                # output among active slots + queue
                                # pressure, so a fused loop never
                                # starves an admissible request (TTFT
                                # guard; docs/SERVING.md)
    speculative: bool = False   # self-drafting speculative decode
                                # inside the fused loop: draft spec_k
                                # tokens, verify in ONE batched target
                                # pass, accept on device — lossless
                                # under greedy (serving/speculative.py)
    spec_k: int = 4             # draft tokens per verify round
    drafter: str = "ngram"      # "ngram" (per-slot bigram table) |
                                # "truncated" (first drafter_layers
                                # layers of the target + shared head)
    drafter_layers: int = 1     # truncated drafter depth (must be
                                # < num_layers; checked at build)
    temperature: float = 0.0    # ISSUE 19: softmax temperature for
                                # on-device seeded sampling.  0.0 =
                                # greedy argmax (the sampler is not
                                # even built — bit-identical engine);
                                # > 0 samples every generated token
                                # in-graph, keyed by (sample_seed,
                                # request rid, position) — stateless,
                                # so N-step == 1-step bit-identically
                                # and crash re-queues replay tokens
    top_k: int = 0              # keep the k highest logits before the
                                # draw (0 = off; needs temperature>0)
    top_p: float = 1.0          # nucleus cutoff in (0, 1]; 1.0 = off
                                # (needs temperature > 0)
    sample_seed: int = 0        # the sampling stream seed (run
                                # identity — COMPARABLE at merge)
    grammar: str = ""           # "" = unconstrained; "json" masks
                                # every generated token through the
                                # JSON-mode automaton
                                # (serving/sampling.compile_grammar);
                                # composes with speculative (out-of-
                                # grammar drafts auto-reject) and
                                # with prefix_sharing
    cache_dtype: str = "bf16"   # paged-KV pool storage (ISSUE 12):
                                # "bf16" = unquantized (pools in the
                                # model dtype — the quant path is not
                                # even built, bit-identical engine);
                                # "int8"/"fp8" = quantized pools with
                                # per-page-per-head f32 scales — ~2x
                                # the pages per pool byte of a bf16
                                # cache (~4x of f32 CPU-mesh pools)
    prefix_sharing: bool = False  # cross-request prefix sharing
                                # (ISSUE 12): a radix trie over prompt
                                # tokens maps a new request's shared
                                # prefix onto a RESIDENT sequence's
                                # physical pages (refcounted, copy-on-
                                # write at the divergence page);
                                # admission charges only unshared
                                # pages and the shared prefix skips
                                # prefill (the TTFT win)
    moe_skew: float = 0.0       # ISSUE 15: seeded expert-skew
                                # injection — added to the router
                                # logits of a MoE model's decode path
                                # (serving/moe_decode.skew_bias), the
                                # imbalance-shaped sibling of the
                                # fault plans' seeded delays.  0.0 =
                                # no bias built (bit-identical
                                # routing).  COMPARABLE via
                                # serving_config: a skewed run must
                                # never merge with a balanced one
    moe_skew_seed: int = 0      # which experts the skew favors
    warmup_requests: int = 8    # run_serving drives this many synthetic
                                # requests through the engine BEFORE the
                                # measured run (0 disables): first-call
                                # dispatch/allocator warm-in must not
                                # ride the measured latencies — the
                                # run_proxy warmup discipline applied to
                                # the serving loop
    disaggregate: bool = False  # ISSUE 16: split the run into a
                                # prefill replica and a decode replica
                                # on DISJOINT device subsets
                                # (serving/disagg.run_disagg) — prompts
                                # prefill into the prefill replica's
                                # local pool and the finished pages
                                # migrate decode-ward in their stored
                                # dtype.  COMPARABLE at merge: a
                                # disaggregated record never merges
                                # with a monolithic one
    prefill_ranks: int = 1      # disaggregate: device ranks
                                # [0, prefill_ranks) hold the prefill
                                # replica (fault-shrink unit, like
                                # world ranks on the monolithic engine)
    decode_ranks: int = 1       # disaggregate: ranks [prefill_ranks,
                                # world) hold the decode replica;
                                # world must equal their sum
    migration_chunk_pages: int = 8  # pages per migration-channel
                                # chunk transfer (the PR-4 decomposed
                                # chunk-loop granularity on the
                                # page-migration wire)

    def validate(self) -> "ServingConfig":
        if self.prefill not in PREFILL_MODES:
            raise ValueError(f"serving: prefill must be one of "
                             f"{PREFILL_MODES}, got {self.prefill!r}")
        for name in ("slots", "page_size", "num_pages", "max_seq_len",
                     "prefill_chunk", "world", "kv_shard"):
            if getattr(self, name) < 1:
                raise ValueError(f"serving: {name} must be >= 1")
        if self.max_seq_len % self.page_size:
            raise ValueError("serving: max_seq_len must be a multiple "
                             "of page_size (block tables are "
                             "page-granular)")
        if self.num_pages < self.max_seq_len // self.page_size:
            raise ValueError(
                f"serving: num_pages {self.num_pages} cannot hold even "
                f"one max_seq_len request "
                f"({self.max_seq_len // self.page_size} pages) — the "
                f"admission gate would starve the queue head forever")
        if not self.disaggregate and self.slots % self.world:
            # disaggregate replaces this with the per-replica rule
            # below: each replica's fault-shrink unit is its OWN rank
            # share, and world = prefill_ranks + decode_ranks need not
            # divide the slot count (e.g. slots=4 on a 2p+1d world)
            raise ValueError("serving: slots must divide evenly across "
                             "world ranks (the fault-shrink unit)")
        if self.multi_step_n < 1:
            raise ValueError(f"serving: multi_step_n must be >= 1, "
                             f"got {self.multi_step_n}")
        if self.cache_dtype not in CACHE_DTYPES:
            raise ValueError(f"serving: unknown cache_dtype "
                             f"{self.cache_dtype!r} (one of "
                             f"{CACHE_DTYPES})")
        if self.speculative and self.cache_dtype != "bf16":
            raise ValueError(
                f"serving: speculative decode supports the bf16 cache "
                f"only — cache_dtype={self.cache_dtype!r} re-quantizes "
                f"pages on every draft/verify overwrite and has no "
                f"stated parity bar (docs/SERVING.md 'Cache density')")
        # ISSUE 19: the ONE sampling validator (check_spec_config
        # pattern) — the same call cli.py runs at arg-parse time, so
        # invalid combos fail identically in both places.  Speculative
        # sampling is LOSSLESS now (rejection-sampling acceptance);
        # what it needs is a drafter with a distribution.
        from dlnetbench_tpu.serving.sampling import check_sampling_config
        check_sampling_config(
            temperature=self.temperature, top_k=self.top_k,
            top_p=self.top_p, sample_seed=self.sample_seed,
            grammar=self.grammar, speculative=self.speculative,
            drafter=self.drafter)
        if self.moe_skew < 0:
            raise ValueError(f"serving: moe_skew must be >= 0, got "
                             f"{self.moe_skew}")
        if self.speculative:
            from dlnetbench_tpu.serving.speculative import DRAFTERS
            if self.spec_k < 1:
                raise ValueError(f"serving: spec_k must be >= 1, got "
                                 f"{self.spec_k}")
            if self.drafter not in DRAFTERS:
                raise ValueError(
                    f"serving: unknown drafter {self.drafter!r} "
                    f"(one of {DRAFTERS})")
        if self.disaggregate:
            if self.prefill_ranks < 1 or self.decode_ranks < 1:
                raise ValueError(
                    "serving: disaggregate needs prefill_ranks >= 1 "
                    "and decode_ranks >= 1 — each phase is a replica")
            if self.world != self.prefill_ranks + self.decode_ranks:
                raise ValueError(
                    f"serving: disaggregate splits world into disjoint "
                    f"replica meshes — world {self.world} must equal "
                    f"prefill_ranks {self.prefill_ranks} + decode_ranks "
                    f"{self.decode_ranks}")
            if self.slots % self.prefill_ranks \
                    or self.slots % self.decode_ranks:
                raise ValueError(
                    f"serving: disaggregate needs slots {self.slots} "
                    f"divisible by prefill_ranks {self.prefill_ranks} "
                    f"AND decode_ranks {self.decode_ranks} (each "
                    f"replica's fault-shrink unit is its own rank "
                    f"share)")
            if self.speculative:
                raise ValueError(
                    "serving: speculative + disaggregate is refused — "
                    "the draft/verify ngram state has no stated parity "
                    "story across a page migration")
            if self.prefix_sharing:
                raise ValueError(
                    "serving: prefix_sharing + disaggregate is refused "
                    "— refcounted shared pages live in ONE pool and "
                    "cannot migrate by reference across replicas")
            if self.kv_shard > 1:
                raise ValueError(
                    "serving: kv_shard + disaggregate is refused — the "
                    "migration channel moves single-device pools; a "
                    "sharded pool would need a per-shard wire")
            if self.prefill == "inline":
                raise ValueError(
                    "serving: disaggregate implies separate-phase "
                    "prefill (the prefill replica has no decode slots "
                    "to interleave with) — prefill='inline' is a "
                    "contradiction, not a knob setting")
            if self.migration_chunk_pages < 1:
                raise ValueError(
                    f"serving: migration_chunk_pages must be >= 1, "
                    f"got {self.migration_chunk_pages}")
        return self


class _SlotState:
    """One in-flight request's host-side state."""

    def __init__(self, req: Request, admitted_s: float):
        self.req = req
        self.admitted_s = admitted_s
        self.prompt = None          # jnp [prompt_len] int32, lazy
        self.prefill_done = 0       # prompt tokens already cached
        self.generated = 0
        self.last_token = 0
        self.first_token_s: float | None = None
        self.gstate = 0             # grammar-automaton state after the
        #                             last generated token (ISSUE 19;
        #                             stays 0 when unconstrained)


class Engine:
    """One serving engine instance over a fixed slot/page capacity.

    The decode step and the prefill-chunk program are AOT-compiled at
    construction (``core/executor.CompiledStep`` — compile cost
    recorded in ``global_meta``, never inside the measured loop); the
    KV page pools are donated and rebound functionally each call."""

    # subclass hook (serving/disagg._PrefillReplica): a replica that
    # never decodes skips building the decode program entirely —
    # compile cost and pool-sized executable state must not ride a
    # phase that will never dispatch it
    _decode_needed = True

    def __init__(self, model_cfg: TransformerConfig,
                 cfg: ServingConfig, *, params=None, devices=None,
                 mesh=None):
        self.model_cfg = D.check_config(model_cfg)
        self.cfg = cfg.validate()
        if cfg.disaggregate:
            raise ValueError(
                "serving: a disaggregated config drives TWO engines — "
                "use serving/disagg.run_disagg, not Engine/run_serving")
        self.devices = (list(devices) if devices is not None
                        else jax.devices()[:max(cfg.world,
                                                cfg.kv_shard)])
        if len(self.devices) < cfg.world:
            raise ValueError(
                f"serving: world {cfg.world} needs {cfg.world} devices, "
                f"have {len(self.devices)}")
        self.cache_cfg = CacheConfig(
            num_layers=model_cfg.num_layers,
            num_kv_heads=model_cfg.num_kv_heads,
            head_dim=model_cfg.head_dim,
            num_pages=cfg.num_pages, page_size=cfg.page_size,
            max_seqs=cfg.slots,
            max_pages_per_seq=cfg.max_seq_len // cfg.page_size,
            dtype=model_cfg.dtype, cache_dtype=cfg.cache_dtype)
        self._quant = self.cache_cfg.quantized
        if mesh is None and cfg.kv_shard > 1:
            from dlnetbench_tpu.parallel.mesh import make_flat_mesh
            if model_cfg.num_kv_heads % cfg.kv_shard:
                raise ValueError(
                    f"serving: kv_shard {cfg.kv_shard} must divide "
                    f"num_kv_heads {model_cfg.num_kv_heads}")
            # the mesh comes from THIS engine's device set — a shrink
            # rebuild over the survivors must never keep sharding onto
            # the dead rank's device (refused loudly when too few
            # survivors remain to hold the shard)
            if len(self.devices) < cfg.kv_shard:
                raise ValueError(
                    f"serving: kv_shard {cfg.kv_shard} needs "
                    f"{cfg.kv_shard} devices, engine has "
                    f"{len(self.devices)} — a shrunk world cannot keep "
                    f"the KV shard; lower kv_shard with it")
            mesh = make_flat_mesh(devices=self.devices[:cfg.kv_shard],
                                  axis="kv")
        if mesh is not None and "kv" not in mesh.axis_names:
            raise ValueError("serving: the KV-shard mesh must name its "
                             "axis 'kv' (sharded_paged_attention's "
                             "specs)")
        self.mesh = mesh
        self.params = params if params is not None else init_params(
            jax.random.key(0), model_cfg)
        self.meta: dict = {}
        # the host/device state split (ISSUE 11): multi_step_n == 1 and
        # no speculation keeps the CLASSIC engine — same single-step
        # program, same per-token dispatch, bit-identical by
        # construction (the loop program is not even built); otherwise
        # the decode path is ONE fused program (lax.while_loop) with
        # slot state device-resident between admission syncs
        self._loop_mode = cfg.multi_step_n > 1 or cfg.speculative
        self._decode = self._loop = None
        # ISSUE 15: MoE decode — per-expert token batching with
        # overflow rounds inside both decode paths; the seeded skew
        # bias is an engine-build constant (serving/moe_decode.py)
        self._moe = model_cfg.num_experts > 1
        if self._moe and cfg.speculative:
            raise ValueError(
                "serving: speculative decode covers dense models only "
                "— the draft/verify overwrite cycle has no stated "
                "parity story through the MoE overflow rounds")
        self._moe_bias = None
        if self._moe:
            from dlnetbench_tpu.serving.moe_decode import skew_bias
            self._moe_bias = skew_bias(model_cfg.num_experts,
                                       cfg.moe_skew, cfg.moe_skew_seed)
        # ISSUE 19: the device sampler is an engine-build constant —
        # knobs + compiled grammar tables closed over every decode
        # program.  None when greedy/unconstrained: the sampler-less
        # programs are byte-identical to pre-ISSUE-19 builds.
        from dlnetbench_tpu.serving import sampling as SMP
        scfg = SMP.check_sampling_config(
            temperature=cfg.temperature, top_k=cfg.top_k,
            top_p=cfg.top_p, sample_seed=cfg.sample_seed,
            grammar=cfg.grammar, speculative=cfg.speculative,
            drafter=cfg.drafter)
        self._sampler = (SMP.DeviceSampler(scfg,
                                           model_cfg.vocab_size)
                         if scfg.enabled else None)
        with spans.span("build", what="serving engine"):
            if self._loop_mode:
                if cfg.speculative:
                    from dlnetbench_tpu.serving import speculative as S
                    S.check_spec_config(
                        model_cfg, spec_k=cfg.spec_k,
                        drafter=cfg.drafter,
                        drafter_layers=cfg.drafter_layers)
                    loop_fn = S.make_spec_decode_loop(
                        model_cfg, self.cache_cfg, cfg.multi_step_n,
                        spec_k=cfg.spec_k, drafter=cfg.drafter,
                        drafter_layers=cfg.drafter_layers,
                        attn_impl=cfg.attn_impl, mesh=mesh,
                        sampler=self._sampler)
                    carries = (1, 2, 3, 4)  # pools + packed state +
                    #                          ngram table
                else:
                    loop_fn = D.make_multi_step_decode(
                        model_cfg, self.cache_cfg, cfg.multi_step_n,
                        attn_impl=cfg.attn_impl, mesh=mesh,
                        moe_bias=self._moe_bias,
                        sampler=self._sampler)
                    # pools (+ scale arrays on a quantized cache) +
                    # packed state — all loop carries
                    carries = (tuple(range(1, 6)) if self._quant
                               else (1, 2, 3))
                self._loop = executor.CompiledLoop(
                    loop_fn, self._loop_example_args(),
                    carry_argnums=carries)
            elif self._decode_needed:
                self._decode = executor.CompiledStep(
                    D.make_decode_step(model_cfg, self.cache_cfg,
                                       attn_impl=cfg.attn_impl,
                                       mesh=mesh,
                                       moe_bias=self._moe_bias,
                                       sampler=self._sampler),
                    self._decode_example_args(),
                    donate_argnums=self._pool_argnums)
            self._prefill = executor.CompiledStep(
                D.make_prefill_chunk(model_cfg, self.cache_cfg,
                                     cfg.prefill_chunk,
                                     moe_bias=self._moe_bias,
                                     sampler=self._sampler),
                self._prefill_example_args(),
                donate_argnums=self._pool_argnums)
        decode_prog = self._loop if self._loop_mode else self._decode
        decode_name = "decode_loop" if self._loop_mode else "decode_step"
        self.meta["compile_ms"] = {
            "prefill_chunk": self._prefill.stats["compile_ms"]}
        self.meta["aot"] = {
            "prefill_chunk": {k: v for k, v in self._prefill.stats.items()
                              if k != "compile_ms"}}
        if decode_prog is not None:
            self.meta["compile_ms"][decode_name] = \
                decode_prog.stats["compile_ms"]
            self.meta["aot"][decode_name] = {
                k: v for k, v in decode_prog.stats.items()
                if k != "compile_ms"}
        # live windowed metrics stream (serving/metrics.LiveMetricsWriter
        # or None) — attached by bench --live-metrics / run_serving;
        # survives _reset_state so a warm round and the measured run
        # share one stream
        self.live = None
        self._reset_state()

    # ---- construction helpers ----------------------------------------
    @property
    def _pool_argnums(self) -> tuple:
        """Positional argnums of the pool buffers in every program
        signature: (k, v) or (k, v, k_scale, v_scale) — the donated,
        functionally-rebound set."""
        return (1, 2, 3, 4) if self._quant else (1, 2)

    def _pools(self):
        """Fresh zeroed page pools (+ scale arrays on a quantized
        cache), pre-placed with the KV-head-sharded layout when a mesh
        is in play: the AOT executables are lowered against THESE
        shardings and their outputs keep them, so every later call sees
        exactly the sharding it was compiled for (an AOT program never
        auto-reshards — the /verify catch that motivated this
        helper)."""
        bufs = device_buffers(self.cache_cfg)
        if self.mesh is None:
            return bufs
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        pool_s = NamedSharding(self.mesh, P(None, "kv", None, None,
                                            None))
        scale_s = NamedSharding(self.mesh, P(None, "kv", None))
        out = [jax.device_put(bufs[0], pool_s),
               jax.device_put(bufs[1], pool_s)]
        for sc in bufs[2:]:
            out.append(jax.device_put(sc, scale_s))
        return tuple(out)

    def _pool_avals(self):
        """Abstract stand-ins for the page pools at lowering time —
        ``jax.jit(...).lower`` takes ShapeDtypeStructs, so the example
        args need not ALLOCATE two extra full-size pool pairs (the
        largest buffers in the tier; on a memory-tight chip the
        redundant copies could OOM a config the steady-state engine
        fits).  Carries the same sharding ``_pools`` places."""
        cc = self.cache_cfg
        shape = (cc.num_layers, cc.num_kv_heads, cc.num_pages,
                 cc.page_size, cc.head_dim)
        pool_s = scale_s = None
        if self.mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            pool_s = NamedSharding(self.mesh,
                                   P(None, "kv", None, None, None))
            scale_s = NamedSharding(self.mesh, P(None, "kv", None))
        aval = jax.ShapeDtypeStruct(shape, cc.pool_jnp_dtype,
                                    sharding=pool_s)
        if not self._quant:
            return aval, aval
        saval = jax.ShapeDtypeStruct(shape[:3], jnp.float32,
                                     sharding=scale_s)
        return aval, aval, saval, saval

    def _pool_args(self) -> tuple:
        """The engine's CURRENT pool buffers, in signature order."""
        if self._quant:
            return (self.k_pages, self.v_pages, self.k_scale,
                    self.v_scale)
        return (self.k_pages, self.v_pages)

    def _adopt_pools(self, outs):
        """Rebind the engine's pool references from a program's leading
        outputs; returns the remaining outputs."""
        n = len(self._pool_argnums)
        if self._quant:
            (self.k_pages, self.v_pages, self.k_scale,
             self.v_scale) = outs[:n]
        else:
            self.k_pages, self.v_pages = outs[:n]
        return outs[n:]

    def _decode_example_args(self):
        cc = self.cache_cfg
        b = cc.max_seqs
        args = (self.params, *self._pool_avals(),
                jnp.zeros((b,), jnp.int32), jnp.zeros((b,), jnp.int32),
                jnp.zeros((b, cc.max_pages_per_seq), jnp.int32),
                jnp.zeros((b,), bool))
        if self._sampler is not None:
            # ISSUE 19: per-slot request uids + grammar states
            args += (jnp.zeros((b,), jnp.int32),
                     jnp.zeros((b,), jnp.int32))
        return args

    def _prefill_example_args(self):
        cc = self.cache_cfg
        args = (self.params, *self._pool_avals(),
                jnp.zeros((self.cfg.prefill_chunk,), jnp.int32),
                jnp.int32(0), jnp.int32(0),
                jnp.zeros((cc.max_pages_per_seq,), jnp.int32))
        if self._sampler is not None:
            args += (jnp.int32(0),)   # ISSUE 19: the request uid
        return args

    def _loop_example_args(self):
        """Abstract args for the fused decode-loop program (the
        CompiledLoop contract: pools + slot-state carries lead, then
        the read-only block tables, then the dynamic trip count)."""
        cc = self.cache_cfg
        b = cc.max_seqs
        args = (self.params, *self._pool_avals(),
                jnp.zeros((D.STATE_ROWS, b), jnp.int32))  # packed state
        if self.cfg.speculative:
            args += (jnp.zeros((b, self.model_cfg.vocab_size),
                               jnp.int32),)   # ngram table
        args += (jnp.zeros((b, cc.max_pages_per_seq), jnp.int32),
                 jnp.int32(1))                # n_steps / n_rounds
        return args

    def _reset_state(self):
        self.cache = PagedKVCache(self.cache_cfg)
        self.k_scale = self.v_scale = None
        self._adopt_pools(self._pools())
        self._cow_fns = None   # lazily-jitted page-copy programs
        self.concurrent_peak = 0
        self._prompt_memo: dict[int, object] = {}
        self.slots: list[_SlotState | None] = [None] * self.cfg.slots
        self.completed: list[M.Completed] = []
        self.queue: deque[Request] = deque()
        self.pending: list[Request] = []
        self.engine_steps = 0
        self.queue_depth_max = 0
        self._occupancy_samples: list[int] = []
        # ISSUE 11 instrumentation + device-resident slot state.  All
        # host-side bookkeeping — the 1-step path's MATH is untouched.
        self.dstate = None
        if self._loop_mode:
            from dlnetbench_tpu.serving.device_state import \
                DeviceDecodeState
            self.dstate = DeviceDecodeState(
                self.cfg.slots, self.cache_cfg.max_pages_per_seq,
                vocab=(self.model_cfg.vocab_size if self.cfg.speculative
                       else None))
        self.token_streams: dict[int, list[int]] = {}
        self._host_dispatch_us: list[float] = []
        self._dispatches = 0
        self._device_steps = 0
        self._device_time_s = 0.0    # ALL compiled-call legs (prefill
        #                              included) — attribution's
        #                              measured-compute basis
        self._decode_device_s = 0.0  # decode dispatches only — the
        #                              per-step basis the dispatch-
        #                              floor solve divides by
        self._tokens_emitted = 0
        self._drafted = 0
        self._accepted = 0
        # ISSUE 15 MoE imbalance telemetry: per-expert routed-token
        # totals, per-dispatch overflow-round counts (decode and
        # prefill tracked SEPARATELY — their capacity regimes differ,
        # so mixing them would let prompt length move the decode
        # rounds_mean the imbalance study grids by), and the last
        # dispatch's snapshot for the flight ring.  _moe_pending holds
        # intermediate prefill chunks' (load, rounds) DEVICE arrays:
        # converting them eagerly would fence every chunk, violating
        # the _prefill_one fence contract — they fold at the
        # prompt-completing chunk's existing fence
        self._moe_load = (np.zeros(self.model_cfg.num_experts,
                                   np.int64) if self._moe else None)
        self._moe_rounds: list[int] = []
        self._moe_prefill_rounds: list[int] = []
        self._moe_pending: list[tuple] = []
        self._moe_last: dict = {}
        self._step_ewma_s = 0.0
        # disaggregation (ISSUE 16): the driver sets this to the
        # engine-clock second the next migrated sequence is expected
        # to arrive; _pick_n_steps caps the fused trip count so a
        # handoff never waits out a full N-step loop.  None (always,
        # on a monolithic engine) keeps _pick_n_steps bit-identical.
        self._migration_eta_s: float | None = None
        self._n_scalars: dict[int, jax.Array] = {}
        # flight recorder (ISSUE 14): refreshed per run; None (the
        # default) keeps the engine step bit-identical and
        # allocation-free — the telemetry branch is never entered
        self._tele = telemetry.current()
        if self._tele is not None:
            # new run = new step-time baseline: without this, the first
            # steps of a structurally different run (a fused-N engine
            # after a 1-step engine in a bench A/B) would band-escape
            # the PREVIOUS run's walls and fire a bogus step_time
            # anomaly on a clean benchmark
            self._tele.reset_walls("serving")
        if self.live is not None:
            self.live.reset_run()  # the engine clock restarts at 0

    # ---- the loop ----------------------------------------------------
    def run(self, requests: list[Request], *, injector=None,
            t_origin: float | None = None
            ) -> tuple[list[M.Completed], float]:
        """Drive the engine until every request completes; returns
        ``(completed, wall_s)``.  ``t_origin`` anchors the admission
        clock — a fault-segmented continuation passes the FIRST
        segment's origin so arrival stamps stay on one timeline.  A
        scripted ``RankFailure``/``RankPreempted`` from the injector
        propagates with all progress retained on the engine
        (``drain_unfinished`` hands the leftovers to the rebuilt
        engine)."""
        self._reset_state()
        for r in requests:
            if r.prompt_len + r.output_len > self.cfg.max_seq_len:
                raise ValueError(
                    f"serving: request {r.rid} needs "
                    f"{r.prompt_len + r.output_len} tokens > max_seq_len "
                    f"{self.cfg.max_seq_len}")
        self.queue = deque(sorted(requests, key=lambda r: r.arrival_s))
        self._t0 = time.monotonic() if t_origin is None else t_origin
        while self.queue or self.pending or any(
                s is not None for s in self.slots):
            now = self._now()
            self._admit_arrivals(now)
            if not any(s is not None for s in self.slots) \
                    and not self.pending:
                # idle: sleep to the next arrival (open loop — the
                # engine must not busy-spin the clock forward)
                if self.queue:
                    dt = self.queue[0].arrival_s - self._now()
                    if dt > 0:
                        time.sleep(dt)
                continue
            if injector is not None:
                injector.before_step()  # faults land INSIDE the loop
            self._step()
        wall = self._now()
        return self.completed, wall

    def drain_unfinished(self) -> list[Request]:
        """Everything not completed, for a fault-segmented continuation:
        in-flight requests lose their decode progress (their cache dies
        with this engine) but KEEP their arrival stamps — the rebuilt
        engine redoes their work and the disruption lands in their
        measured latency.  Slots and pages are freed."""
        leftovers = [s.req for s in self.slots if s is not None]
        if self._loop_mode and any(s is not None for s in self.slots):
            # the drain IS a sync boundary: deactivate the in-flight
            # slots device-side too, so a reused engine's next flush
            # starts from an all-idle carry
            self.dstate.pull()
        for i, s in enumerate(self.slots):
            if s is not None:
                self.cache.free(i)
                self.slots[i] = None
                if self._loop_mode:
                    self.dstate.evict(i)
        leftovers += self.pending
        leftovers += list(self.queue)
        self.pending, self.queue = [], deque()
        return sorted(leftovers, key=lambda r: r.arrival_s)

    # ---- internals ---------------------------------------------------
    def _now(self) -> float:
        return time.monotonic() - self._t0

    def _admit_arrivals(self, now: float) -> None:
        while self.queue and self.queue[0].arrival_s <= now:
            self.pending.append(self.queue.popleft())
        self.queue_depth_max = max(self.queue_depth_max,
                                   len(self.pending))
        for i in range(self.cfg.slots):
            if not self.pending:
                break
            if self.slots[i] is not None:
                continue
            req = self.pending[0]
            prompt = self._prompt_of(req)
            # admission control: reserve the WORST CASE (prompt +
            # output) so a running sequence can never OOM mid-decode.
            # With prefix sharing the plan charges only UNSHARED pages
            # (fully-matched prefix pages map by reference; the
            # divergence page's copy-on-write copy is pre-charged).
            # A disaggregated prefill replica overrides the token
            # count to prompt-only — its pool never decodes.
            plan = self.cache.plan_admission(
                self._admission_tokens(req),
                prompt if self.cfg.prefix_sharing else None)
            if plan.need_pages > self.cache.free_pages:
                break  # FIFO: do not starve the head by admitting later
            self.pending.pop(0)
            cow_dst = self.cache.admit(i, plan)
            if cow_dst is not None:
                # COW resolved eagerly at the admission sync boundary:
                # the divergence page's prefix rows are copied into the
                # private page BEFORE any prefill/decode write lands
                self._cow_copy(plan.cow_src, cow_dst)
            st = _SlotState(req, admitted_s=self._now())
            st.prompt = prompt
            # the shared prefix is already cached — prefill resumes at
            # the divergence point (the TTFT win prefix sharing buys)
            st.prefill_done = plan.shared_tokens
            self.slots[i] = st
            self.concurrent_peak = max(
                self.concurrent_peak,
                sum(1 for s in self.slots if s is not None))
            if self.cfg.prefill == "separate":
                # drain the whole prompt now (the separate-phase mode:
                # prefill monopolizes the engine while it runs, which
                # is the interference inline chunking exists to cut)
                while self.slots[i] is not None \
                        and st.prefill_done < req.prompt_len:
                    self._prefill_one(i, st)

    def _admission_tokens(self, req: Request) -> int:
        """Tokens to reserve pages for at admission — the worst case
        (prompt + output).  The disaggregated prefill replica overrides
        this to ``prompt_len``: decode happens on the OTHER replica's
        pool, and reserving output pages here would halve the prefill
        pool's admission capacity for nothing."""
        return req.prompt_len + req.output_len

    def _prompt_of(self, req: Request):
        """Request -> prompt tokens, memoized: a blocked queue head is
        re-planned every engine iteration and must not regenerate (or
        re-hash) its prompt each time."""
        toks = self._prompt_memo.get(req.rid)
        if toks is None:
            toks = D.prompt_tokens_for(req, self.model_cfg.vocab_size)
            self._prompt_memo[req.rid] = toks
        return toks

    def _cow_copy(self, src: int, dst: int) -> None:
        """Device-side page copy for an admission-time COW: the shared
        page's rows (and, on a quantized cache, its scales) land in the
        newly charged private page.  One tiny jitted program, traced
        once per array rank; runs at the admission boundary, never
        inside the compiled decode programs."""
        if self._cow_fns is None:
            self._cow_fns = jax.jit(
                lambda a, s, d: a.at[:, :, d].set(a[:, :, s]),
                donate_argnums=(0,))
        f = self._cow_fns
        s, d = jnp.int32(src), jnp.int32(dst)
        self.k_pages = f(self.k_pages, s, d)
        self.v_pages = f(self.v_pages, s, d)
        if self._quant:
            self.k_scale = f(self.k_scale, s, d)
            self.v_scale = f(self.v_scale, s, d)

    def _prefill_one(self, slot: int, st: _SlotState) -> float:
        """One prefill chunk; returns the compiled-call wall seconds
        (the device leg of the host_dispatch_us decomposition).

        Fence honesty: only the PROMPT-COMPLETING chunk fences (its
        ``int(nxt)`` is load-bearing — the TTFT token).  Intermediate
        chunks return dispatch-acknowledged wall only; forcing a
        device->host fence on each would cost a full RTT per chunk on
        a tunnel backend for timing's sake.  On an async backend their
        queued compute therefore completes inside a LATER fenced
        window — in separate-prefill mode that is still the admission
        phase (the final chunk's fence), but in inline mode it can be
        the next decode dispatch, which is why the bench A/B and the
        dispatch-floor solve use separate-mode prefill
        (``dispatch_decomposition`` documents the caveat)."""
        c = self.cfg.prefill_chunk
        start = st.prefill_done
        n = min(c, st.req.prompt_len - start)
        # pad on the HOST: a jnp dynamic-length slice here would cache
        # one compiled dispatch per distinct tail length
        chunk_np = np.zeros((c,), np.int32)
        chunk_np[:n] = st.prompt[start:start + n]
        chunk = jnp.asarray(chunk_np)
        row = jnp.asarray(self.cache.block_tables[slot])
        t0 = time.perf_counter()
        extra = (() if self._sampler is None
                 else (jnp.int32(st.req.rid),))
        outs = self._prefill(
            self.params, *self._pool_args(), chunk,
            jnp.int32(start), jnp.int32(n), row, *extra)
        if self._moe:
            # stash the DEVICE arrays — no np.asarray here, an
            # intermediate chunk must not fence (the contract above);
            # they fold at the completing chunk's int(nxt) fence,
            # which orders after every prior chunk on the stream
            nxt, load, rounds = self._adopt_pools(outs)
            self._moe_pending.append((load, rounds))
        else:
            (nxt,) = self._adopt_pools(outs)
        st.prefill_done += n
        self.cache.append(slot, n)
        dev_s = 0.0
        if st.prefill_done >= st.req.prompt_len:
            # the chunk completing the prompt produces the request's
            # FIRST generated token — its TTFT stamp
            st.last_token = int(nxt)  # the fence: device work done here
            self._fold_moe_pending()
            dev_s = time.perf_counter() - t0
            st.generated = 1
            if (self._sampler is not None
                    and self._sampler.grammar is not None):
                # grammar state AFTER the TTFT token (the device-side
                # loop picks up from here)
                st.gstate = self._sampler.host_advance(
                    self._sampler.start_state, st.last_token)
            st.first_token_s = self._now()
            self.token_streams.setdefault(st.req.rid, []).append(
                st.last_token)
            if self.cfg.prefix_sharing:
                # the prompt is fully cached: publish its pages so
                # later arrivals can share them (prompt only —
                # generated tokens are request-specific)
                self.cache.publish(slot, st.prompt)
            self._maybe_finish(slot, st)
            if self.slots[slot] is st:
                # entering the decode phase: seed the device-resident
                # slot state (loop mode's admission sync boundary)
                self._activate_decode_slot(slot, st)
        else:
            dev_s = time.perf_counter() - t0
        self._device_time_s += dev_s
        return dev_s

    def _activate_decode_slot(self, slot: int, st: _SlotState) -> None:
        """Loop mode: a slot finished prefill — push its decode state
        to the device mirrors (flushed, priced, at the next dispatch)."""
        if not self._loop_mode:
            return
        ds = self.dstate
        ds.pull()  # sync boundary: refresh before mutating (priced)
        ngram_row = None
        if ds.ngram_table is not None:
            from dlnetbench_tpu.serving.speculative import seed_ngram_row
            ngram_row = seed_ngram_row(st.prompt, st.last_token,
                                       self.model_cfg.vocab_size)
        ds.admit(slot, last_token=st.last_token,
                 position=int(self.cache.lengths[slot]),
                 remaining=st.req.output_len - st.generated,
                 seq_limit=st.req.prompt_len + st.req.output_len,
                 block_row=self.cache.block_tables[slot],
                 ngram_row=ngram_row,
                 uid=st.req.rid, grammar_state=st.gstate)

    def admit_prefilled(self, req: Request, *, last_token: int,
                        admitted_s: float, first_token_s: float,
                        generated: int, pending_send,
                        channel) -> bool:
        """Disaggregation (ISSUE 16): admit a sequence whose prompt was
        prefilled on the OTHER replica.  Reserves the worst case
        (prompt + output) like any admission, rebuilds lengths/block
        tables to exactly the monolithic post-prefill state
        (``lengths = prompt_len``; the first generated token is NOT
        cached — decode writes it at position prompt_len, same as
        ``_prefill_one``'s contract), scatters the migrated pages into
        this pool's allocation, and seeds the decode slot.  The stamps
        (arrival, admission, TTFT) travel WITH the sequence — they
        were taken prefill-side at the existing stamp points.  Returns
        False when no slot or pages are free (the driver retries at
        the next sync boundary)."""
        slot = next((i for i, s in enumerate(self.slots) if s is None),
                    None)
        if slot is None:
            return False
        plan = self.cache.plan_admission(req.prompt_len
                                         + req.output_len)
        if plan.need_pages > self.cache.free_pages:
            return False
        self.cache.admit(slot, plan)
        # the migrated payload covers exactly the prompt's pages;
        # advancing the length makes append/decode see the monolithic
        # post-prefill state
        self.cache.append(slot, req.prompt_len)
        s = self.cfg.page_size
        n_pages = (req.prompt_len + s - 1) // s
        dst_ids = self.cache.block_tables[slot][:n_pages]
        self._adopt_pools(channel.scatter(self._pool_args(),
                                          pending_send, dst_ids))
        st = _SlotState(req, admitted_s=admitted_s)
        st.prompt = self._prompt_of(req)
        st.prefill_done = req.prompt_len
        st.generated = generated
        st.last_token = last_token
        st.first_token_s = first_token_s
        if (self._sampler is not None
                and self._sampler.grammar is not None):
            # migration happens at the TTFT boundary (generated == 1):
            # the automaton has consumed exactly the first token
            st.gstate = self._sampler.host_advance(
                self._sampler.start_state, st.last_token)
        self.slots[slot] = st
        self.concurrent_peak = max(
            self.concurrent_peak,
            sum(1 for s_ in self.slots if s_ is not None))
        self._maybe_finish(slot, st)
        if self.slots[slot] is st:
            self._activate_decode_slot(slot, st)
        return True

    def _step(self) -> None:
        """One engine step: inline prefill chunks first (one per
        prefilling slot), then decode — one token per active slot
        (classic mode) or up to N fused device steps (loop mode).
        Either way ``host_dispatch_us`` records the step wall MINUS
        the compiled-call wall: the marshalling/bookkeeping/dispatch
        overhead the fused loop exists to amortize (ISSUE 11
        satellite — the A/B's measured before-number)."""
        tele = self._tele
        if tele is None and self.live is None:
            # the zero-overhead path: no clock read, no dict built,
            # no branch into the sampling below (ISSUE 14 disabled
            # contract — locked by tests/test_telemetry.py)
            if self._loop_mode:
                self._step_fused()
            else:
                self._step_single()
            return
        t0 = time.perf_counter()
        sync0 = (self.dstate.sync_total_us() if self.dstate is not None
                 else 0.0)
        if self._loop_mode:
            self._step_fused()
        else:
            self._step_single()
        self._sample_step((time.perf_counter() - t0) * 1e6, sync0)

    def _sample_step(self, wall_us: float, sync0: float) -> None:
        """One flight-ring sample per engine step (ISSUE 14): the
        serving tier's per-step TIME SERIES — queue depth, admitted
        concurrency, KV occupancy/fragmentation, prefix hit rate, spec
        acceptance, decode sync-crossing cost — plus the band-aware
        step-time detector feed and the rolling-window SLO breach
        check (``serving/metrics.rolling_slo_breach``, the
        goodput_timeline windowing applied live)."""
        tele = self._tele
        now = self._now()
        step = self.engine_steps
        if tele is not None:
            cs = self.cache.stats()
            fields = {
                "phase": "engine_step",
                "step_wall_us": round(wall_us, 1),
                "queue_depth": len(self.pending),
                "active_slots": sum(1 for s in self.slots
                                    if s is not None),
                "kv_occupancy": cs["occupancy"],
                "kv_fragmentation": cs["fragmentation"],
            }
            prefix = cs.get("prefix")
            if prefix:
                fields["prefix_hit_rate"] = prefix["hit_rate"]
            if self.cfg.speculative and self._drafted:
                fields["spec_acceptance"] = round(
                    self._accepted / self._drafted, 4)
            if self._moe and self._moe_last:
                # expert-imbalance telemetry (ISSUE 15): the last
                # dispatch's overflow rounds + load imbalance ride
                # the flight ring next to queue depth
                fields.update(self._moe_last)
            if self.dstate is not None:
                fields["sync_us"] = round(
                    self.dstate.sync_total_us() - sync0, 1)
            tele.record("serving", step=step, **fields)
            tele.observe_step_wall("serving", wall_us, step=step)
            # bounded tail: completions append in finish order, so the
            # trailing window is a suffix — scanning the whole list
            # every step would put an O(completed) cost inside the very
            # step wall being measured
            breach = M.rolling_slo_breach(
                self.completed[-64:], slo_ttft_ms=self.cfg.slo_ttft_ms,
                slo_tpot_ms=self.cfg.slo_tpot_ms, now_s=now)
            if breach is not None:
                tele.trigger("slo", step=step, detail={
                    **breach,
                    "slo": {"ttft_ms": self.cfg.slo_ttft_ms,
                            "tpot_ms": self.cfg.slo_tpot_ms}})
        if self.live is not None:
            self.live.maybe_emit(self, now)

    def _step_preamble(self) -> tuple[list[int], float]:
        """The per-step work BOTH decode paths share (one definition —
        the A/B pairing depends on the baselines never desyncing):
        inline prefill chunks, the decode-phase slot list, occupancy
        sampling, the step count.  Returns ``(decode_ix, prefill
        device seconds)``."""
        dev_s = 0.0
        for i, st in enumerate(self.slots):
            if st is not None and st.prefill_done < st.req.prompt_len:
                dev_s += self._prefill_one(i, st)
        decode_ix = [i for i, st in enumerate(self.slots)
                     if st is not None
                     and st.prefill_done >= st.req.prompt_len]
        self._occupancy_samples.append(len(decode_ix))
        self.engine_steps += 1
        return decode_ix, dev_s

    def _step_single(self) -> None:
        self._step_complete(self._step_dispatch())

    def _step_fused(self) -> None:
        """Loop mode: ONE fused device program runs up to N decode
        steps with slot state resident on device; the host syncs only
        here — admission updates flushed in, the per-sync token block
        pulled out, both priced (device_state.py)."""
        self._step_complete(self._step_dispatch())

    # ---- the dispatch/complete split (ISSUE 16) ----------------------
    # Both decode paths are split at the async-dispatch boundary: the
    # DISPATCH phase marshals inputs and launches the compiled program
    # WITHOUT fencing; the COMPLETE phase fences the outputs and runs
    # the host postprocess.  The monolithic engine calls them
    # back-to-back (_step_single/_step_fused above) — same statements
    # in the same order, bit-identical math AND timing attribution.
    # The disaggregated driver opens the window: while the decode
    # replica's program runs on its device, the prefill replica's
    # chunks and the page-migration sends run on the OTHER device —
    # the measured interference reduction the disagg study prices.

    def _step_dispatch(self) -> dict | None:
        """Preamble + program launch, no fence.  Returns the in-flight
        step context for ``_step_complete``, or None when no slot is in
        the decode phase (nothing was dispatched)."""
        if self._loop_mode:
            return self._dispatch_fused()
        return self._dispatch_single()

    def _step_complete(self, ctx: dict | None) -> float:
        """Fence the dispatched step's outputs and run the host
        postprocess; returns the step's decode device-leg seconds (the
        compute arm of the disagg driver's overlap measurement)."""
        if ctx is None:
            return 0.0
        if ctx["fused"]:
            return self._complete_fused(ctx)
        return self._complete_single(ctx)

    def _dispatch_single(self) -> dict | None:
        t_step = time.perf_counter()
        decode_ix, dev_s = self._step_preamble()
        if not decode_ix:
            return None
        b = self.cfg.slots
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        active = np.zeros((b,), bool)
        for i in decode_ix:
            st = self.slots[i]
            tokens[i] = st.last_token
            positions[i] = int(self.cache.lengths[i])
            active[i] = True
        extra = ()
        if self._sampler is not None:
            uids = np.zeros((b,), np.int32)
            gst = np.zeros((b,), np.int32)
            for i in decode_ix:
                uids[i] = self.slots[i].req.rid
                gst[i] = self.slots[i].gstate
            extra = (jnp.asarray(uids), jnp.asarray(gst))
        t0 = time.perf_counter()
        outs = self._decode(
            self.params, *self._pool_args(),
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(self.cache.block_tables), jnp.asarray(active),
            *extra)
        rest = self._adopt_pools(outs)
        return {"fused": False, "t_step": t_step, "t0": t0,
                "dev_s": dev_s, "decode_ix": decode_ix, "rest": rest}

    def _complete_single(self, ctx: dict) -> float:
        decode_ix, dev_s = ctx["decode_ix"], ctx["dev_s"]
        if self._moe:
            nxt, load, rounds = ctx["rest"]
            self._record_moe(load, rounds)
        else:
            (nxt,) = ctx["rest"]
        nxt = np.asarray(nxt)        # the fence rides the device leg
        t1 = time.perf_counter()
        leg = t1 - ctx["t0"]
        dev_s += leg
        self._device_time_s += leg
        self._decode_device_s += leg
        self._dispatches += 1
        self._device_steps += 1
        for i in decode_ix:
            st = self.slots[i]
            self.cache.append(i)          # the fed token is now cached
            st.last_token = int(nxt[i])
            if (self._sampler is not None
                    and self._sampler.grammar is not None):
                # the per-token fence IS the grammar transition point
                # in classic mode — host-side, same automaton table
                st.gstate = self._sampler.host_advance(
                    st.gstate, st.last_token)
            st.generated += 1
            self._tokens_emitted += 1
            self.token_streams.setdefault(st.req.rid, []).append(
                st.last_token)
            self._maybe_finish(i, st)
        self._host_dispatch_us.append(
            max(0.0, (time.perf_counter() - ctx["t_step"] - dev_s))
            * 1e6)
        return leg

    def _dispatch_fused(self) -> dict | None:
        t_step = time.perf_counter()
        sync0 = self.dstate.sync_total_us()
        decode_ix, dev_s = self._step_preamble()
        if not decode_ix:
            return None
        ds = self.dstate
        n = self._pick_n_steps(decode_ix)
        carries = ds.carries()            # flushes if dirty (priced)
        bt = ds.block_tables_device()
        t0 = time.perf_counter()
        outs = self._loop(self.params, *self._pool_args(),
                          *carries, bt, self._n_scalar(n))
        new_carries, extras = self._loop.split(outs)
        ds.rebind(self._adopt_pools(new_carries))
        return {"fused": True, "t_step": t_step, "t0": t0,
                "sync0": sync0, "dev_s": dev_s,
                "decode_ix": decode_ix, "extras": extras}

    def _complete_fused(self, ctx: dict) -> float:
        decode_ix, dev_s = ctx["decode_ix"], ctx["dev_s"]
        extras = ctx["extras"]
        if self.cfg.speculative:
            toks, cnts, steps, drafted, accepted = extras
        elif self._moe:
            toks, cnts, steps, moe_load, moe_rounds = extras
            self._record_moe(moe_load, moe_rounds)
        else:
            toks, cnts, steps = extras
        # the per-sync results (token block, counts, stats): np.asarray
        # is the FENCE, so [t0, t2) is the device leg as one unit —
        # priced into device_us only (sync_d2h_us prices the mirror
        # pull()s; pricing this interval into both channels would
        # double-count it against the wall)
        toks = np.asarray(toks)
        cnts = np.asarray(cnts)
        steps = int(steps)
        if self.cfg.speculative:
            self._drafted += int(drafted)
            self._accepted += int(accepted)
        t2 = time.perf_counter()
        leg = t2 - ctx["t0"]
        dev_s += leg
        self._device_time_s += leg
        self._decode_device_s += leg
        self._dispatches += 1
        self._device_steps += steps
        if steps > 0:
            per_step = leg / steps
            self._step_ewma_s = (per_step if not self._step_ewma_s else
                                 0.5 * self._step_ewma_s
                                 + 0.5 * per_step)
        for i in decode_ix:
            st = self.slots[i]
            m = int(cnts[i])
            if m == 0:
                continue
            self.cache.append(i, m)   # all fed tokens, one batched call
            stream = toks[i, :m].tolist()
            st.generated += m
            st.last_token = stream[-1]
            self._tokens_emitted += m
            self.token_streams.setdefault(st.req.rid, []).extend(stream)
            self._maybe_finish(i, st)
        # exclude in-step sync time: flush/pull are priced in their own
        # channels and each crossing must count against the wall ONCE
        # (serving_host_us sums host_dispatch + both sync channels)
        sync_s = (self.dstate.sync_total_us() - ctx["sync0"]) * 1e-6
        self._host_dispatch_us.append(
            max(0.0, (time.perf_counter() - ctx["t_step"] - dev_s
                      - sync_s))
            * 1e6)
        return leg

    def _record_moe(self, load, rounds) -> None:
        """Fold one DECODE dispatch's MoE stats (device outputs riding
        the same fence as the tokens) into the run accumulators and
        the last-dispatch snapshot the flight ring samples."""
        load = np.asarray(load, np.int64)
        rounds = int(rounds)
        self._moe_load += load
        self._moe_rounds.append(rounds)
        total = float(load.sum())
        if total > 0:
            frac = load / total
            imb = float(frac.max()) / max(float(frac.mean()), 1e-12)
        else:
            imb = 1.0
        self._moe_last = {"moe_rounds": rounds,
                          "moe_imbalance": round(imb, 4)}

    def _fold_moe_pending(self) -> None:
        """Fold the stashed prefill chunks' MoE stats.  Called under a
        fence that already covers them (the completing chunk's TTFT
        token, or record assembly), so the np.asarray conversions here
        cost a copy, never a wait.  Prefill rounds accumulate apart
        from decode rounds — prefill capacity is sized over the chunk,
        decode capacity over the slot batch, and the decode
        rounds_mean column must not move with prompt length."""
        for load, rounds in self._moe_pending:
            self._moe_load += np.asarray(load, np.int64)
            self._moe_prefill_rounds.append(int(rounds))
        self._moe_pending.clear()

    def moe_block(self) -> dict | None:
        """The record's MoE-imbalance block (ISSUE 15): measured
        per-expert load distribution (prefill + decode routing — the
        router is the router), its imbalance (max/mean), and the
        DECODE overflow-round stats that turned imbalance into latency
        (prefill rounds reported apart: their capacity is sized over
        the chunk, not the slot batch).  None on dense engines —
        pre-MoE records are byte-identical."""
        if not self._moe:
            return None
        self._fold_moe_pending()   # a drained mid-prefill slot's stats
        total = float(self._moe_load.sum())
        load = (self._moe_load / total if total > 0
                else np.zeros_like(self._moe_load, float))
        rounds = self._moe_rounds
        pf = self._moe_prefill_rounds
        mean = max(float(load.mean()), 1e-12)
        return {
            "num_experts": int(self.model_cfg.num_experts),
            "top_k": int(self.model_cfg.top_k),
            "capacity_factor": float(
                self.model_cfg.moe_capacity_factor),
            "skew": self.cfg.moe_skew,
            "skew_seed": self.cfg.moe_skew_seed,
            "expert_load": [round(float(v), 6) for v in load],
            "load_imbalance": round(float(load.max()) / mean, 4),
            "rounds_mean": (round(sum(rounds) / len(rounds), 3)
                            if rounds else 0.0),
            "rounds_p99": (round(M.percentile(rounds, 99), 3)
                           if rounds else 0.0),
            "dispatches": len(rounds),
            "prefill_rounds_mean": (round(sum(pf) / len(pf), 3)
                                    if pf else 0.0),
            "prefill_dispatches": len(pf),
        }

    def _n_scalar(self, n: int):
        """Cached device scalar for the dynamic trip count (a fresh
        jnp.int32 per dispatch is a measurable host cost at decode
        rates)."""
        s = self._n_scalars.get(n)
        if s is None:
            s = self._n_scalars[n] = jnp.int32(n)
        return s

    def _pick_n_steps(self, decode_ix: list[int]) -> int:
        """Adaptive N (ISSUE 11 satellite): the fused loop must never
        starve an admissible request.  Cap the trip count by the
        SHORTEST remaining output among active slots whenever work is
        waiting (the loop then returns exactly when the first slot can
        free capacity), and by the measured steps-until-next-arrival
        when the queue's head would land mid-loop.  A slot mid-prefill
        (inline mode) caps at 1 — the one-chunk-per-engine-step
        interleaving contract."""
        n = self.cfg.multi_step_n
        if not self.cfg.adaptive_n:
            return max(1, n)
        if any(st is not None and st.prefill_done < st.req.prompt_len
               for st in self.slots):
            return 1
        if n <= 1:
            return max(1, n)
        rem_min = min(self.slots[i].req.output_len
                      - self.slots[i].generated for i in decode_ix)
        # disaggregation (ISSUE 16): the decode replica has no arrival
        # queue of its own — its "next arrival" is the next migrated
        # sequence, whose ETA the driver maintains.  Cap the trip
        # count the same way the queue-head cap does, so a finished
        # handoff waits at most ~one device step for a free sync
        # boundary instead of a full N-step loop.  None (always, on a
        # monolithic engine) leaves every path below bit-identical.
        eta = self._migration_eta_s
        if eta is not None:
            dt = eta - self._now()
            est = self._step_ewma_s
            if est > 0 and dt < n * est:
                n = max(1, min(n, max(1, int(dt / est) + 1)))
        if self.pending:
            return max(1, min(n, rem_min))
        if self.queue:
            dt = self.queue[0].arrival_s - self._now()
            est = self._step_ewma_s
            if est > 0 and dt < n * est:
                steps_until = max(1, int(dt / est) + 1)
                return max(1, min(n, rem_min, steps_until))
        if eta is not None:
            return max(1, min(n, rem_min))
        return n

    def _maybe_finish(self, slot: int, st: _SlotState) -> None:
        if st.generated < st.req.output_len:
            return
        now = self._now()
        self.completed.append(M.Completed(
            rid=st.req.rid, arrival_s=st.req.arrival_s,
            admitted_s=st.admitted_s, first_token_s=st.first_token_s,
            finish_s=now, prompt_len=st.req.prompt_len,
            output_len=st.req.output_len))
        self.cache.free(slot)
        self.slots[slot] = None

    # ---- record assembly ---------------------------------------------
    def batch_occupancy_mean(self) -> float:
        if not self._occupancy_samples:
            return 0.0
        return sum(self._occupancy_samples) / len(self._occupancy_samples)

    def decode_loop_block(self) -> dict:
        """The record's dispatch-decomposition block (ISSUE 11): how
        many device decode steps each host dispatch amortized, what
        each host crossing cost, and the speculative acceptance stats.
        Present in BOTH modes — the 1-step engine's block (steps per
        dispatch = 1, per-step host_dispatch_us) is the measured
        before-number the A/B flips against."""
        d = self._dispatches
        hd = self._host_dispatch_us
        block = {
            "multi_step_n": self.cfg.multi_step_n,
            "adaptive_n": self.cfg.adaptive_n,
            "speculative": self.cfg.speculative,
            "dispatches": d,
            "device_steps": self._device_steps,
            "steps_per_dispatch": (round(self._device_steps / d, 3)
                                   if d else 0.0),
            "tokens_per_sync": (round(self._tokens_emitted / d, 3)
                                if d else 0.0),
            "device_us": {"total": round(self._device_time_s * 1e6, 1)},
            "decode_device_us": {
                "total": round(self._decode_device_s * 1e6, 1)},
            "host_dispatch_us": {
                "total": round(sum(hd), 1),
                "p50": round(M.percentile(hd, 50), 1) if hd else 0.0,
                "mean": round(sum(hd) / len(hd), 1) if hd else 0.0,
                "n": len(hd)},
        }
        if self.dstate is not None:
            block.update(self.dstate.sync_stats())
        if self.cfg.speculative:
            block["spec"] = {
                "k": self.cfg.spec_k,
                "drafter": self.cfg.drafter,
                **({"drafter_layers": self.cfg.drafter_layers}
                   if self.cfg.drafter == "truncated" else {}),
                "drafted": self._drafted,
                "accepted": self._accepted,
                "acceptance_rate": (round(self._accepted
                                          / self._drafted, 4)
                                    if self._drafted else 0.0),
            }
        return block

    def global_meta(self, plan: ArrivalPlan) -> dict:
        from dlnetbench_tpu.parallel.mesh import (describe_mesh,
                                                  make_flat_mesh)
        cfg = self.cfg
        return {
            "proxy": "serving",
            "model": (f"decode_d{self.model_cfg.embed_dim}"
                      f"_l{self.model_cfg.num_layers}"
                      f"_h{self.model_cfg.num_heads}"
                      f"kv{self.model_cfg.num_kv_heads}"
                      f"_v{self.model_cfg.vocab_size}"),
            "world_size": cfg.world,
            "arrival_plan": plan.to_dict(),
            # comparable global (ISSUE 12): records from differently-
            # quantized caches must never merge — metrics/merge refuses
            # a mismatch exactly like a mismatched fault plan
            "kv_cache_dtype": cfg.cache_dtype,
            # comparable global (ISSUE 19): sampled runs carry their
            # full draw identity — records with different temperature/
            # top_k/top_p/seed/grammar must never merge (draws are
            # keyed by (seed, uid, position); mixing seeds would
            # average incomparable token streams).  Absent on greedy
            # runs so pre-sampling records stay byte-identical.
            **({"sampling": {"temperature": cfg.temperature,
                             "top_k": cfg.top_k,
                             "top_p": cfg.top_p,
                             "sample_seed": cfg.sample_seed,
                             "grammar": cfg.grammar}}
               if self._sampler is not None else {}),
            "serving_config": {
                "slots": cfg.slots, "page_size": cfg.page_size,
                "num_pages": cfg.num_pages,
                "max_seq_len": cfg.max_seq_len,
                "pool_bytes": self.cache_cfg.pool_bytes,
                "cache_dtype": cfg.cache_dtype,
                "prefix_sharing": cfg.prefix_sharing,
                "prefill": cfg.prefill,
                "prefill_chunk": cfg.prefill_chunk,
                "kv_shard": cfg.kv_shard,
                "multi_step_n": cfg.multi_step_n,
                "adaptive_n": cfg.adaptive_n,
                "speculative": cfg.speculative,
                **({"spec_k": cfg.spec_k, "drafter": cfg.drafter}
                   if cfg.speculative else {}),
                # the skew KNOBS are run identity (serving_config is
                # comparable): a skewed run never merges with a
                # balanced one, exactly like mismatched fault plans
                **({"moe_experts": self.model_cfg.num_experts,
                    "moe_top_k": self.model_cfg.top_k,
                    "moe_capacity_factor":
                        self.model_cfg.moe_capacity_factor,
                    "moe_skew": cfg.moe_skew,
                    "moe_skew_seed": cfg.moe_skew_seed}
                   if self._moe else {}),
            },
            "mesh": describe_mesh(make_flat_mesh(devices=self.devices)),
            **self.meta,
        }


def run_serving(model_cfg: TransformerConfig, cfg: ServingConfig,
                plan: ArrivalPlan, *, fault_plan=None, params=None,
                devices=None, live_metrics=None):
    """One measured serving run -> ``ProxyResult`` (-> ``metrics.emit``).

    Clean runs drive one engine.  With ``fault_plan``: delay/jitter
    events sleep at step boundaries inside the loop; a crash under
    policy ``shrink`` segments the run like ``faults/policy.run_faulted``
    segments a training run — detection measured at the catch, the
    engine rebuilt over the survivor ranks' slot share (recompile
    priced into ``recovery_ms``), unfinished requests re-queued with
    their original arrival stamps, and the record stamps
    ``degraded_world``/``fault_*`` so the analysis layer reads serving
    faults exactly like training faults."""
    engine = Engine(model_cfg, cfg, params=params, devices=devices)
    if live_metrics is not None:
        # path or writer: the windowed live JSONL stream (ISSUE 14
        # satellite; serving/metrics.LiveMetricsWriter)
        engine.live = (live_metrics if hasattr(live_metrics,
                                               "maybe_emit")
                       else M.LiveMetricsWriter(live_metrics))
    requests = plan.sample()
    if cfg.warmup_requests > 0:
        # warm-in: saturating synthetic mini-workload, discarded — the
        # measured run starts with hot dispatch paths (run_proxy's
        # warmup phase, serving-shaped)
        p_len = min(cfg.prefill_chunk + 1, cfg.max_seq_len - 2)
        warm = [Request(rid=-1 - i, arrival_s=0.0, prompt_len=p_len,
                        output_len=2)
                for i in range(cfg.warmup_requests)]
        with spans.span("warmup", what="serving engine",
                        reps=len(warm)):
            engine.run(warm)
    injector = None
    if fault_plan is not None:
        from dlnetbench_tpu.faults.inject import FaultInjector
        fault_plan.validate()
        injector = FaultInjector(fault_plan, world=cfg.world)

    meta = engine.global_meta(plan)
    extra: dict = {}
    try:
        with spans.span("serving_run", requests=len(requests)):
            completed, wall = engine.run(requests, injector=injector)
        final = engine
    except Exception as e:
        # capacity shrink: the dead rank takes its slot share down.
        # Mirrors faults/policy.run_faulted's segmentation: detect,
        # rebuild (recompile priced), finish degraded.  The detection
        # stamp, fault trigger and survivor set are the shared arc
        # (serving/requeue.py — re-raises non-shrinkable faults).
        detection_ms, survivors = requeue.detect_shrink(
            e, injector=injector, fault_plan=fault_plan,
            world=cfg.world, step=engine.engine_steps)
        if not survivors:
            raise
        leftovers = requeue.requeue_unfinished(engine)
        done0 = list(engine.completed)
        t_origin = engine._t0
        steps0 = engine.engine_steps
        occ0 = list(engine._occupancy_samples)
        qmax0 = engine.queue_depth_max
        t0 = time.monotonic()
        shrunk = dataclasses.replace(
            cfg, world=len(survivors),
            slots=cfg.slots // cfg.world * len(survivors))
        with spans.span("serving_rebuild", survivors=len(survivors)):
            engine2 = Engine(model_cfg, shrunk, params=params,
                             devices=[engine.devices[r]
                                      for r in survivors])
        engine2.live = engine.live  # the stream outlives the shrink
        recovery_ms = (time.monotonic() - t0) * 1e3
        done1, wall = requeue.run_requeued(
            engine2, leftovers, injector=injector, t_origin=t_origin)
        completed = done0 + done1
        final = engine2
        final.engine_steps += steps0
        final._occupancy_samples = occ0 + final._occupancy_samples
        final.queue_depth_max = max(qmax0, final.queue_depth_max)
        final.concurrent_peak = max(engine.concurrent_peak,
                                    final.concurrent_peak)
        meta["mesh"] = engine2.global_meta(plan)["mesh"]
        extra = {"detection_ms": round(detection_ms, 3),
                 "recovery_ms": round(recovery_ms, 3),
                 "degraded_world": survivors,
                 "degraded_slots": shrunk.slots}

    # measured MoE imbalance block (ISSUE 15): stamped from the FINAL
    # engine AFTER the measured run (a crash-shrink continuation's
    # stats are the degraded engine's); volatile at merge like every
    # measurement; absent on dense engines
    moe_blk = final.moe_block()
    if moe_blk is not None:
        meta["moe"] = moe_blk
    meta["serving"] = M.serving_block(
        completed, plan, slo_ttft_ms=cfg.slo_ttft_ms,
        slo_tpot_ms=cfg.slo_tpot_ms, wall_s=wall,
        engine_steps=final.engine_steps,
        cache_stats=final.cache.stats(),
        queue_depth_max=final.queue_depth_max,
        batch_occupancy_mean=final.batch_occupancy_mean(),
        decode_loop=final.decode_loop_block(),
        admitted_peak=final.concurrent_peak)
    if cfg.prefix_sharing:
        # record globals (ISSUE 12 acceptance: a sharing run must
        # stamp its measured hit rate and bytes saved).  VOLATILE in
        # merge: residency at admission time depends on wall-clock
        # arrival vs engine speed, so the counts can differ across
        # hosts/reruns of one plan (metrics/merge.py)
        pstats = final.cache.stats().get("prefix", {})
        meta["prefix_hit_rate"] = pstats.get("hit_rate", 0.0)
        meta["prefix_bytes_saved"] = pstats.get("bytes_saved", 0)
    if cfg.speculative and final._sampler is not None:
        # VOLATILE at merge (metrics/merge.py): the measured
        # acceptance-vs-temperature point for THIS run — acceptance is
        # a measurement (it varies with params/load), unlike the
        # `sampling` identity block above
        meta["spec_acceptance_by_temp"] = M.acceptance_by_temp([
            (cfg.temperature,
             (final._accepted / final._drafted
              if final._drafted else 0.0))])
    if fault_plan is not None:
        meta["fault_plan"] = fault_plan.to_dict()
        meta["fault_policy"] = fault_plan.policy
        meta["fault_injected_delay_us"] = round(
            injector.injected_delay_us, 1)
    meta.update(extra)
    return M.build_result(completed, plan, meta)
