"""The decode-path transformer: prefill/decode split over the paged
KV cache.

Shares weights (``models/transformer.init_params``) and math (RoPE,
RMSNorm, SwiGLU — the gated/llama family) with the training forward, so
decode output is bit-checkable against ``transformer.forward`` on the
same token prefix (tests/test_serving.py does exactly that).  Two
programs cover serving:

* ``make_decode_step``   — ONE token per active decode slot, full-batch
  (shape ``[slots]``, inactive slots masked by dropping their cache
  writes): project q/k/v for the fed token, write k/v into the slot's
  current page, run paged attention over everything cached, MLP, and
  greedy-sample the next token.  This is the program the engine runs
  every step of the continuous-batching loop — AOT-compiled via
  ``core/executor.CompiledStep`` with the page pools donated.
* ``make_prefill_chunk`` — one sequence, one CHUNK of its prompt
  (static chunk length, ``n_valid`` masking): writes the chunk's K/V
  into the slot's pages and attends causally over cache + chunk.
  ``scheduler`` drives it either to completion at admit time (separate
  prefill phase) or one chunk per engine step (inline-chunked).
* ``make_multi_step_decode`` — ISSUE 11's tentpole: N decode steps
  fused into ONE compiled program via ``lax.while_loop``, slot state
  (last tokens, positions, active flags, per-slot remaining budgets)
  carried ON DEVICE between steps, so the host pays one dispatch per N
  tokens instead of one per token.  The loop body is the SAME
  ``_step_tokens`` math the single-step program runs (token parity
  with the 1-step engine is a locked test), the trip count is dynamic
  (``n_steps`` operand + all-slots-done early exit), and a slot that
  exhausts its budget mid-loop deactivates itself without a host
  round-trip.  ``serving/speculative.py`` builds the draft/verify loop
  on the same body.

Only the dense gated (SwiGLU + RMSNorm + RoPE) config is supported —
the same subset every low-precision path in this repo covers first.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from dlnetbench_tpu.models import layers as L
from dlnetbench_tpu.models.transformer import TransformerConfig
from dlnetbench_tpu.serving.kv_cache import (CacheConfig,
                                             dequant_gathered,
                                             paged_attention_decode,
                                             quant_write_span,
                                             sharded_paged_attention)

_F32 = jnp.float32


def check_config(cfg: TransformerConfig,
                 decode: bool = False) -> TransformerConfig:
    if not cfg.gated or cfg.max_positions:
        raise ValueError(
            "serving decode covers the gated (SwiGLU+RMSNorm+RoPE) "
            "family only — non-gated / learned-position configs have "
            "no decode path yet")
    if cfg.attention_seg_avg:
        raise ValueError(
            "serving decode supports sliding-window attention masks "
            "only (attention_window); document-segment masks have no "
            "serving path — a request is one document")
    if decode and cfg.attention_window:
        # the decode step attends the FULL cached history (the paged
        # kernel has no lower-bound mask), so generating under a
        # window config would silently use different attention
        # semantics than the windowed prefill/training — refuse until
        # a lower-bound-aware paged kernel exists
        raise ValueError(
            "serving decode has no sliding-window path yet (the paged "
            "attention kernel attends the full cache): "
            "attention_window covers the PREFILL chunk only — decode "
            "under a window config would silently diverge from the "
            "training mask")
    return cfg


def _rope_decode(q, k, positions, theta=10000.0):
    """RoPE with a PER-ELEMENT position (decode: every slot sits at its
    own sequence offset).  q: [B, H, Dh], k: [B, Hkv, Dh],
    positions: [B].  Same split-halves convention as ``layers.rope``."""
    dh = q.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dh, 2, dtype=_F32) / dh))
    angles = positions.astype(_F32)[:, None] * inv_freq[None, :]
    cos = jnp.cos(angles)[:, None, :]   # [B, 1, Dh/2]
    sin = jnp.sin(angles)[:, None, :]

    def rot(x):
        x1, x2 = jnp.split(x.astype(_F32), 2, axis=-1)
        return jnp.concatenate([x1 * cos - x2 * sin,
                                x1 * sin + x2 * cos],
                               axis=-1).astype(x.dtype)

    return rot(q), rot(k)


def _attn_fn(cache_cfg: CacheConfig, attn_impl: str, mesh):
    """One uniform internal attention signature for both cache forms:
    ``attn(q, k_l, v_l, ks_l, vs_l, lengths, block_tables)`` — the
    scale slices are ``None`` on the dense cache (where the underlying
    call is EXACTLY the pre-ISSUE-12 dispatch)."""
    quant = cache_cfg.quantized
    fmt = cache_cfg.quant_fmt
    if mesh is not None:
        sharded = sharded_paged_attention(mesh, impl=attn_impl,
                                          quantized=quant, fmt=fmt)
        if quant:
            return sharded
        return (lambda q, k, v, ks, vs, lengths, bt:
                sharded(q, k, v, lengths, bt))
    if quant:
        return (lambda q, k, v, ks, vs, lengths, bt:
                paged_attention_decode(q, k, v, lengths, bt,
                                       k_scale=ks, v_scale=vs, fmt=fmt,
                                       impl=attn_impl))
    return (lambda q, k, v, ks, vs, lengths, bt:
            paged_attention_decode(q, k, v, lengths, bt,
                                   impl=attn_impl))


def _split_pools(cache_cfg: CacheConfig, pools: tuple):
    """``(k_pages, v_pages, k_scale, v_scale)`` with None scales on the
    dense cache — the one unpacking both step bodies share."""
    if cache_cfg.quantized:
        return pools
    k_pages, v_pages = pools
    return k_pages, v_pages, None, None


def _step_tokens(cfg: TransformerConfig, cache_cfg: CacheConfig, attn,
                 params, pools, tokens, positions, write_ok,
                 block_tables, *, layers: int | None = None,
                 moe_bias=None, sampler=None, uids=None, gstate=None,
                 return_logits: bool = False):
    """ONE batched single-token step over the paged cache — the math
    both the single-step program and the fused multi-step loop body run
    (sharing the definition is what makes N-step-vs-1-step token parity
    a structural property, not a numerics hope).

    ``pools`` is ``(k_pages, v_pages)`` on the dense cache (the exact
    pre-ISSUE-12 program) or ``(k_pages, v_pages, k_scale, v_scale)``
    on a quantized one, where each cache write re-quantizes its page
    against a fresh amax (``kv_cache.quant_write_span``) and the
    attention dispatch dequantizes on read.  ``write_ok`` [B] gates the
    k/v cache write (inactive slots write nowhere: out-of-bounds page
    index + ``drop`` mode; their next_token is garbage the caller
    masks).  Attention covers ``positions + 1`` tokens (write-then-
    read: the fed token's k/v land first).  ``layers`` truncates the
    stack — the speculative TRUNCATED drafter is literally the first
    ``layers`` layers of the target plus the shared final-norm/head
    (serving/speculative.py); ``None`` runs the full depth.

    MoE configs (``cfg.num_experts > 1`` — ISSUE 15) run the MLP as
    per-expert token batches with overflow rounds
    (``serving/moe_decode.moe_mlp_rounds``; ``moe_bias`` is the seeded
    skew-injection knob) and the return value grows a third element:
    ``(pools, next_tokens, (expert_load [E], rounds))`` summed over
    the layer stack — the imbalance telemetry the engine records.

    SAMPLING (ISSUE 19): with a ``serving/sampling.DeviceSampler``,
    ``next_tokens`` is the seeded counter-keyed draw instead of the
    argmax — keyed by ``(sample_seed, uids[b], positions[b], lane)``,
    i.e. the FED position is the counter, so every program built on
    this body (1-step, fused N-step, spec verify) draws bit-identical
    tokens at the same stream position.  ``gstate`` [B] is the
    grammar-automaton state used to mask logits; TRANSITIONS are the
    caller's job (the fused loop advances its state row in-carry, the
    classic engine advances host-side at the fence).  ``sampler=None``
    is the byte-identical pre-ISSUE-19 greedy path.  ``return_logits``
    appends the raw logits to the return (the speculative drafter
    needs the distribution, not just a token; mutually exclusive with
    MoE, which spec refuses anyway)."""
    b = tokens.shape[0]
    scale = cfg.head_dim ** -0.5
    page_size = cache_cfg.page_size
    num_pages = cache_cfg.num_pages
    quant = cache_cfg.quantized
    k_pages, v_pages, k_scale, v_scale = _split_pools(cache_cfg, pools)
    x = params["embed"][tokens]                      # [B, D]
    page_col = positions // page_size
    page_id = jnp.take_along_axis(block_tables, page_col[:, None],
                                  axis=1)[:, 0]
    w_pages = jnp.where(write_ok, page_id, num_pages)  # OOB -> drop
    slots = positions % page_size
    att_lengths = positions + 1
    depth = cfg.num_layers if layers is None else layers
    moe = cfg.num_experts > 1
    moe_load = jnp.zeros((cfg.num_experts,), jnp.int32) if moe else None
    moe_rounds = jnp.int32(0)
    for li in range(depth):
        lp = jax.tree.map(lambda a: a[li], params["layers"])
        y = L.rmsnorm(x, lp["norm1"])
        q = jnp.dot(y, lp["wq"]).reshape(b, cfg.num_heads,
                                         cfg.head_dim)
        k = jnp.dot(y, lp["wk"]).reshape(b, cfg.num_kv_heads,
                                         cfg.head_dim)
        v = jnp.dot(y, lp["wv"]).reshape(b, cfg.num_kv_heads,
                                         cfg.head_dim)
        q, k = _rope_decode(q, k, positions)
        # write-then-read: the new token's k/v land in the page pool
        # first, so attention covers it like every cached token
        if quant:
            k_pages, k_scale = quant_write_span(
                k_pages, k_scale, li, k[:, None], positions,
                write_ok[:, None], block_tables,
                fmt=cache_cfg.quant_fmt, page_size=page_size,
                num_pages=num_pages)
            v_pages, v_scale = quant_write_span(
                v_pages, v_scale, li, v[:, None], positions,
                write_ok[:, None], block_tables,
                fmt=cache_cfg.quant_fmt, page_size=page_size,
                num_pages=num_pages)
        else:
            k_pages = k_pages.at[li, :, w_pages, slots, :].set(
                k, mode="drop")
            v_pages = v_pages.at[li, :, w_pages, slots, :].set(
                v, mode="drop")
        att = attn(q * scale, k_pages[li], v_pages[li],
                   k_scale[li] if quant else None,
                   v_scale[li] if quant else None, att_lengths,
                   block_tables)
        x = x + jnp.dot(att.reshape(b, cfg.embed_dim), lp["wo"])
        y = L.rmsnorm(x, lp["norm2"])
        if moe:
            from dlnetbench_tpu.serving.moe_decode import (
                decode_capacity, moe_mlp_rounds)
            cap = decode_capacity(b, cfg.top_k, cfg.num_experts,
                                  cfg.moe_capacity_factor)
            y2, load_l, rounds_l = moe_mlp_rounds(
                y, lp["w_router"], lp["w_gate"], lp["w_up"],
                lp["w_down"], top_k=cfg.top_k, capacity=cap,
                bias=moe_bias, active=write_ok)
            moe_load = moe_load + load_l
            moe_rounds = moe_rounds + rounds_l
            x = x + y2
        else:
            x = x + L.swiglu(y, lp["w_gate"], lp["w_up"], lp["w_down"])
    x = L.rmsnorm(x, params["final_norm"])
    head = params["embed"].T if cfg.tied_embeddings else params["head"]
    logits = jnp.dot(x, head, preferred_element_type=_F32)
    if sampler is None:
        next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        next_tokens = sampler.draw_tokens(logits, uids, positions,
                                          gstate)
    pools_out = ((k_pages, v_pages, k_scale, v_scale) if quant
                 else (k_pages, v_pages))
    if moe:
        return pools_out, next_tokens, (moe_load, moe_rounds)
    if return_logits:
        return pools_out, next_tokens, logits
    return pools_out, next_tokens


def make_decode_step(cfg: TransformerConfig, cache_cfg: CacheConfig,
                     *, attn_impl: str = "auto", mesh=None,
                     moe_bias=None, sampler=None):
    """``decode_step(params, k_pages, v_pages, tokens, positions,
    block_tables, active) -> (k_pages, v_pages, next_tokens)``.

    tokens/positions/active: ``[slots]`` (int32/int32/bool); a slot's
    ``position`` is the cache index its token is written at (= tokens
    already cached), so attention covers ``position + 1`` tokens.
    Inactive slots write nowhere (out-of-bounds page index + ``drop``
    mode) and their next_token is garbage the engine ignores.

    On a QUANTIZED cache (ISSUE 12) the signature grows the scale
    arrays after the pools — ``decode_step(params, k_pages, v_pages,
    k_scale, v_scale, tokens, positions, block_tables, active) ->
    (k_pages, v_pages, k_scale, v_scale, next_tokens)`` — threaded
    functionally exactly like the pools themselves.  The dense
    signature (and its compiled program) is untouched.

    MoE configs (ISSUE 15) append the per-step imbalance stats to the
    outputs — ``(..., next_tokens, expert_load, rounds)`` — and take
    the seeded ``moe_bias`` skew knob (serving/moe_decode.py).

    With a ``sampler`` (serving/sampling.DeviceSampler — ISSUE 19)
    the signature grows two trailing operands: ``decode_step(...,
    active, uids, gstate)`` — per-slot request uids (the draw key) and
    grammar-automaton states (the logit mask; grammar transitions stay
    HOST-side here, since the classic engine fences every token
    anyway).  The sampler-less signature and program are untouched."""
    check_config(cfg, decode=True)
    attn = _attn_fn(cache_cfg, attn_impl, mesh)
    moe = cfg.num_experts > 1

    def _run(params, pools, tokens, positions, block_tables, active,
             uids=None, gstate=None):
        out = _step_tokens(cfg, cache_cfg, attn, params, pools, tokens,
                           positions, active, block_tables,
                           moe_bias=moe_bias, sampler=sampler,
                           uids=uids, gstate=gstate)
        if moe:
            pools, nxt, (load, rounds) = out
            return (*pools, nxt, load, rounds)
        pools, nxt = out
        return (*pools, nxt)

    if sampler is not None:
        if cache_cfg.quantized:
            def decode_step(params, k_pages, v_pages, k_scale, v_scale,
                            tokens, positions, block_tables, active,
                            uids, gstate):
                return _run(params,
                            (k_pages, v_pages, k_scale, v_scale),
                            tokens, positions, block_tables, active,
                            uids, gstate)
            return decode_step

        def decode_step(params, k_pages, v_pages, tokens, positions,
                        block_tables, active, uids, gstate):
            return _run(params, (k_pages, v_pages), tokens, positions,
                        block_tables, active, uids, gstate)
        return decode_step

    if cache_cfg.quantized:
        def decode_step(params, k_pages, v_pages, k_scale, v_scale,
                        tokens, positions, block_tables, active):
            return _run(params, (k_pages, v_pages, k_scale, v_scale),
                        tokens, positions, block_tables, active)
        return decode_step

    def decode_step(params, k_pages, v_pages, tokens, positions,
                    block_tables, active):
        return _run(params, (k_pages, v_pages), tokens, positions,
                    block_tables, active)

    return decode_step


# rows of the packed device slot-state carry ([6, slots] int32 — ONE
# array crosses the host<->device boundary per sync direction, not
# six; device_state.py mirrors the same layout).  ISSUE 19 grew the
# block past 4 rows: STATE_UID carries the request id every sampled
# draw is keyed by, STATE_GRAMMAR the per-slot grammar-automaton
# state.  Both rows ride (as zeros) even in greedy engines — the loop
# carries them untouched, so greedy token streams are unchanged.
(STATE_LAST, STATE_POS, STATE_REM, STATE_LIMIT, STATE_UID,
 STATE_GRAMMAR) = 0, 1, 2, 3, 4, 5
STATE_ROWS = 6


def make_multi_step_decode(cfg: TransformerConfig,
                           cache_cfg: CacheConfig, n_max: int, *,
                           attn_impl: str = "auto", mesh=None,
                           moe_bias=None, sampler=None):
    """The device-resident fused decode loop (ISSUE 11 tentpole).

    ``multi_step(params, k_pages, v_pages, state, block_tables,
    n_steps) -> (k_pages, v_pages, state, tokens_out, counts,
    steps_run)``.

    Runs up to ``min(n_steps, n_max)`` decode steps inside ONE compiled
    program (``lax.while_loop`` — dynamic trip count, so an adaptive
    ``n_steps`` needs no recompile and the loop exits early the moment
    every slot is done).  Slot state lives in the packed ``state``
    carry (``[6, slots]`` int32 — rows ``STATE_LAST`` the token each
    slot feeds next, ``STATE_POS`` the cache write index = tokens
    cached, ``STATE_REM`` output tokens still owed, ``STATE_LIMIT``
    the prompt+output reservation cap, ``STATE_UID`` the request id
    sampled draws key by, ``STATE_GRAMMAR`` the grammar-automaton
    state — the last two carried untouched when greedy/unconstrained).
    ``remaining > 0`` IS the
    active/done bit: a slot whose budget hits 0 deactivates itself
    in-loop, stops writing the cache, and waits for the host to evict
    it at the next sync.  ``tokens_out[b, j]`` holds slot ``b``'s j-th
    generated token of this call, ``counts[b]`` how many are valid,
    and ``steps_run`` the loop trips actually executed (the host's
    steps-per-dispatch metric).  Per step each active slot feeds one
    token and generates one, so ``positions`` advances exactly
    ``counts`` — the host-side page-table ``append`` is one batched
    call per SYNC, not per token.

    The loop body is ``_step_tokens`` — the same math
    ``make_decode_step`` runs — so the N-step greedy token stream
    equals the 1-step engine's exactly (locked by test).  On a
    QUANTIZED cache the scale arrays join the loop carry right after
    the pools (``multi_step(params, k_pages, v_pages, k_scale,
    v_scale, state, ...)``) — same write sequence as the 1-step
    quantized engine, so N-step-vs-1-step parity holds per cache
    dtype.

    MoE configs (ISSUE 15) run the per-expert batched MLP inside the
    loop body and append the ACCUMULATED imbalance stats to the
    outputs — ``(..., steps_run, expert_load, rounds)`` summed over
    the loop trips — so one host sync still carries the whole
    dispatch window's telemetry.

    With a ``sampler`` (ISSUE 19) each in-loop step draws via the
    counter-keyed sampler (uid row + fed position — NO PRNG state in
    the carry, which is exactly why N-step sampling is bit-identical
    to 1-step and adaptive ``n_steps`` still recompiles nothing) and
    the body advances the ``STATE_GRAMMAR`` row through the automaton
    after each accepted token.  The signature is UNCHANGED — the state
    block already carries everything sampling needs."""
    check_config(cfg, decode=True)
    if n_max < 1:
        raise ValueError(f"multi_step_decode: n_max must be >= 1, "
                         f"got {n_max}")
    attn = _attn_fn(cache_cfg, attn_impl, mesh)
    n_pools = 4 if cache_cfg.quantized else 2
    moe = cfg.num_experts > 1

    def _multi_step(params, pools, state, block_tables, n_steps):
        b = state.shape[1]
        n = jnp.minimum(n_steps.astype(jnp.int32), n_max)
        out0 = jnp.zeros((b, n_max), jnp.int32)
        counts0 = jnp.zeros((b,), jnp.int32)
        load0 = jnp.zeros((cfg.num_experts,), jnp.int32)
        rounds0 = jnp.int32(0)

        def cond(carry):
            i, st = carry[0], carry[1 + n_pools]
            return (i < n) & jnp.any(st[STATE_REM] > 0)

        def body(carry):
            i = carry[0]
            pc = carry[1:1 + n_pools]
            st, out, cnt, load, rounds = carry[1 + n_pools:]
            last, pos, rem = (st[STATE_LAST], st[STATE_POS],
                              st[STATE_REM])
            act = rem > 0
            step_out = _step_tokens(cfg, cache_cfg, attn, params, pc,
                                    last, pos, act, block_tables,
                                    moe_bias=moe_bias, sampler=sampler,
                                    uids=st[STATE_UID],
                                    gstate=st[STATE_GRAMMAR])
            if moe:
                pc, nxt, (load_s, rounds_s) = step_out
                load = load + load_s
                rounds = rounds + rounds_s
            else:
                pc, nxt = step_out
            # append each active slot's token at its own count index;
            # inactive slots aim past the buffer edge and drop
            idx = jnp.where(act, cnt, n_max)
            out = out.at[jnp.arange(b), idx].set(nxt, mode="drop")
            step = act.astype(jnp.int32)
            st = st.at[STATE_LAST].set(jnp.where(act, nxt, last))
            st = st.at[STATE_POS].set(pos + step)
            st = st.at[STATE_REM].set(rem - step)
            if sampler is not None and sampler.trans_dev is not None:
                g = st[STATE_GRAMMAR]
                st = st.at[STATE_GRAMMAR].set(
                    jnp.where(act, sampler.advance(g, nxt), g))
            cnt = cnt + step
            return (i + 1, *pc, st, out, cnt, load, rounds)

        final = lax.while_loop(
            cond, body,
            (jnp.int32(0), *pools, state, out0, counts0, load0,
             rounds0))
        i = final[0]
        pc = final[1:1 + n_pools]
        st, out, cnt, load, rounds = final[1 + n_pools:]
        if moe:
            return (*pc, st, out, cnt, i, load, rounds)
        return (*pc, st, out, cnt, i)

    if cache_cfg.quantized:
        def multi_step(params, k_pages, v_pages, k_scale, v_scale,
                       state, block_tables, n_steps):
            return _multi_step(params,
                               (k_pages, v_pages, k_scale, v_scale),
                               state, block_tables, n_steps)
        return multi_step

    def multi_step(params, k_pages, v_pages, state, block_tables,
                   n_steps):
        return _multi_step(params, (k_pages, v_pages), state,
                           block_tables, n_steps)

    return multi_step


def make_prefill_chunk(cfg: TransformerConfig, cache_cfg: CacheConfig,
                       chunk: int, *, moe_bias=None, sampler=None):
    """``prefill_chunk(params, k_pages, v_pages, tokens, start, n_valid,
    block_row) -> (k_pages, v_pages, next_token)``.

    One sequence, one chunk: ``tokens`` is ``[chunk]`` (padded),
    ``start`` the sequence offset of its first token, ``n_valid`` how
    many entries are real.  The chunk's K/V are written into the pages
    ``block_row`` maps, attention is causal over cache + chunk, and
    ``next_token`` is the greedy continuation after the LAST valid
    token — meaningful only on the chunk that completes the prompt
    (that token IS the request's first generated token; its TTFT
    stamp).

    With ``cfg.attention_window = W`` the prefill is SPARSE (ISSUE 10
    satellite): the chunk's queries can only see keys in ``(q-W, q]``,
    so the gather walks just the ``ceil((W-1+chunk)/page) + 1`` pages
    that window can touch instead of all ``max_pages_per_seq`` — the
    score grid shrinks from ``[C, pmax*page]`` to ``[C, pages_w*page]``
    — and the mask comes from the SAME builder the training paths use
    (ops/attention_mask.allowed with the equivalent MaskSpec), so a
    sliding-window model config prefills with the training mask
    semantics exactly (token-parity-tested against the dense path).

    QUANTIZED caches (ISSUE 12) add the scale arrays after the pools
    (``prefill_chunk(params, k_pages, v_pages, k_scale, v_scale,
    ...)``): chunk writes re-quantize their pages against a fresh amax
    (``kv_cache.quant_write_span``) and the gathered pages dequantize
    before the score matmul; the dense signature/program is
    untouched.

    With a ``sampler`` (ISSUE 19) the signature grows ONE trailing
    ``uid`` scalar operand (the request id) and the TTFT token becomes
    the seeded draw keyed by ``(sample_seed, uid, start + last)`` —
    the fed position of the last prompt token, i.e. the same counter
    convention as every decode program, so the whole stream is one
    consistent key sequence.  The grammar state for the FIRST
    generated token is the automaton's start state (the synthetic
    prompt is not grammar-conformant; the grammar constrains GENERATED
    tokens only)."""
    check_config(cfg)
    scale = cfg.head_dim ** -0.5
    page_size = cache_cfg.page_size
    num_pages = cache_cfg.num_pages
    pmax = cache_cfg.max_pages_per_seq
    quant = cache_cfg.quantized
    window = cfg.attention_window
    spec = None
    pages_w = pmax
    if window:
        from dlnetbench_tpu.ops.attention_mask import MaskSpec
        spec = MaskSpec(causal=True, window=window)
        # pages the window can reach from any chunk query: the span
        # (q-W, q] over the chunk covers W-1+chunk positions, plus one
        # page for alignment slack
        pages_w = min(pmax, -(-(window - 1 + chunk) // page_size) + 1)

    def _prefill(params, pools, tokens, start, n_valid, block_row,
                 uid=None):
        k_pages, v_pages, k_scale, v_scale = _split_pools(cache_cfg,
                                                          pools)
        positions = start + jnp.arange(chunk, dtype=jnp.int32)
        valid = jnp.arange(chunk) < n_valid
        x = params["embed"][tokens]                        # [C, D]
        page_col = jnp.minimum(positions // page_size, pmax - 1)
        page_id = block_row[page_col]
        w_pages = jnp.where(valid, page_id, num_pages)     # OOB -> drop
        slots = positions % page_size
        last = jnp.maximum(n_valid - 1, 0)
        moe_load = (jnp.zeros((cfg.num_experts,), jnp.int32)
                    if cfg.num_experts > 1 else None)
        moe_rounds = jnp.int32(0)
        for li in range(cfg.num_layers):
            lp = jax.tree.map(lambda a: a[li], params["layers"])
            y = L.rmsnorm(x, lp["norm1"])
            q = jnp.dot(y, lp["wq"]).reshape(chunk, cfg.num_heads,
                                             cfg.head_dim)
            k = jnp.dot(y, lp["wk"]).reshape(chunk, cfg.num_kv_heads,
                                             cfg.head_dim)
            v = jnp.dot(y, lp["wv"]).reshape(chunk, cfg.num_kv_heads,
                                             cfg.head_dim)
            # layers.rope wants [B, S, H, Dh] + positions [S]
            q, k = L.rope(q[None], k[None], positions)
            q, k = q[0], k[0]
            if quant:
                k_pages, k_scale = quant_write_span(
                    k_pages, k_scale, li, k[None], start[None],
                    valid[None], block_row[None],
                    fmt=cache_cfg.quant_fmt, page_size=page_size,
                    num_pages=num_pages)
                v_pages, v_scale = quant_write_span(
                    v_pages, v_scale, li, v[None], start[None],
                    valid[None], block_row[None],
                    fmt=cache_cfg.quant_fmt, page_size=page_size,
                    num_pages=num_pages)
            else:
                k_pages = k_pages.at[li, :, w_pages, slots, :].set(
                    k, mode="drop")
                v_pages = v_pages.at[li, :, w_pages, slots, :].set(
                    v, mode="drop")
            # causal attention over cache + chunk: gather the pages the
            # mask can reach (ALL of them when no window; just the
            # window span otherwise — pages beyond it are provably
            # masked, so their DMA and score columns are skipped),
            # chunk included (just written), mask per key position
            if window:
                first_page = jnp.maximum(
                    start - (window - 1), 0) // page_size
                pcols = first_page + jnp.arange(pages_w)
                # clamp the LOOKUP only: an overshooting column's key
                # positions exceed every query (causal-masked), so the
                # duplicated page it reads contributes nothing
                rows = block_row[jnp.clip(pcols, 0, pmax - 1)]
                k_pos = (pcols[:, None] * page_size
                         + jnp.arange(page_size)[None, :]).reshape(-1)
            else:
                rows = block_row
                k_pos = jnp.arange(pmax * page_size)
            if quant:
                kseq = dequant_gathered(k_pages[li][:, rows],
                                        k_scale[li][:, rows])
                vseq = dequant_gathered(v_pages[li][:, rows],
                                        v_scale[li][:, rows])
            else:
                kseq = k_pages[li][:, rows].astype(_F32)
                vseq = v_pages[li][:, rows].astype(_F32)
            hkv, npg, _, dh = kseq.shape   # [Hkv, pages_w, page, Dh]
            t = npg * page_size
            kseq = kseq.reshape(hkv, t, dh)
            vseq = vseq.reshape(hkv, t, dh)
            g = cfg.num_heads // hkv
            qg = (q * scale).reshape(chunk, hkv, g, dh).astype(_F32)
            scores = jnp.einsum("chgd,htd->hgct", qg, kseq)
            if spec is not None:
                from dlnetbench_tpu.ops.attention_mask import allowed
                keep = allowed(spec, positions[:, None],
                               k_pos[None, :])             # [C, T]
            else:
                keep = k_pos[None, :] <= positions[:, None]
            from dlnetbench_tpu.serving.kv_cache import MASK_VALUE
            scores = jnp.where(keep[None, None], scores, MASK_VALUE)
            p = jax.nn.softmax(scores, axis=-1)
            att = jnp.einsum("hgct,htd->chgd", p, vseq)
            att = att.reshape(chunk, cfg.embed_dim).astype(x.dtype)
            x = x + jnp.dot(att, lp["wo"])
            y = L.rmsnorm(x, lp["norm2"])
            if cfg.num_experts > 1:
                from dlnetbench_tpu.serving.moe_decode import (
                    decode_capacity, moe_mlp_rounds)
                cap = decode_capacity(chunk, cfg.top_k,
                                      cfg.num_experts,
                                      cfg.moe_capacity_factor)
                y2, load_l, rounds_l = moe_mlp_rounds(
                    y, lp["w_router"], lp["w_gate"], lp["w_up"],
                    lp["w_down"], top_k=cfg.top_k, capacity=cap,
                    bias=moe_bias, active=valid)
                moe_load = moe_load + load_l
                moe_rounds = moe_rounds + rounds_l
                x = x + y2
            else:
                x = x + L.swiglu(y, lp["w_gate"], lp["w_up"],
                                 lp["w_down"])
        x = L.rmsnorm(x, params["final_norm"])
        head = params["embed"].T if cfg.tied_embeddings else params["head"]
        logits = jnp.dot(x[last], head, preferred_element_type=_F32)
        if sampler is None:
            next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            # the TTFT draw: counter = fed position of the LAST valid
            # prompt token; grammar state = automaton start (batch of
            # one through the shared batched draw)
            g0 = jnp.full((1,), sampler.start_state, jnp.int32)
            next_token = sampler.draw_tokens(
                logits[None], jnp.reshape(uid, (1,)).astype(jnp.int32),
                (start + last)[None], g0)[0]
        pools_out = ((k_pages, v_pages, k_scale, v_scale) if quant
                     else (k_pages, v_pages))
        if cfg.num_experts > 1:
            return pools_out, next_token, (moe_load, moe_rounds)
        return pools_out, next_token

    moe = cfg.num_experts > 1

    def _wrap(params, pools, tokens, start, n_valid, block_row,
              uid=None):
        out = _prefill(params, pools, tokens, start, n_valid,
                       block_row, uid)
        if moe:
            pools, nxt, (load, rounds) = out
            return (*pools, nxt, load, rounds)
        pools, nxt = out
        return (*pools, nxt)

    if sampler is not None:
        if quant:
            def prefill_chunk(params, k_pages, v_pages, k_scale,
                              v_scale, tokens, start, n_valid,
                              block_row, uid):
                return _wrap(params,
                             (k_pages, v_pages, k_scale, v_scale),
                             tokens, start, n_valid, block_row, uid)
            return prefill_chunk

        def prefill_chunk(params, k_pages, v_pages, tokens, start,
                          n_valid, block_row, uid):
            return _wrap(params, (k_pages, v_pages), tokens, start,
                         n_valid, block_row, uid)
        return prefill_chunk

    if quant:
        def prefill_chunk(params, k_pages, v_pages, k_scale, v_scale,
                          tokens, start, n_valid, block_row):
            return _wrap(params, (k_pages, v_pages, k_scale, v_scale),
                         tokens, start, n_valid, block_row)
        return prefill_chunk

    def prefill_chunk(params, k_pages, v_pages, tokens, start, n_valid,
                      block_row):
        return _wrap(params, (k_pages, v_pages), tokens, start,
                     n_valid, block_row)

    return prefill_chunk


def prompt_tokens(rid: int, prompt_len: int, vocab_size: int):
    """Deterministic synthetic prompt for request ``rid`` (the serving
    analogue of the proxies' seeded buffers): the workload is
    replayable from the arrival plan alone.  splitmix64 on the host —
    a ``jax.random.randint`` here would jit-compile once per distinct
    prompt length, a hidden multi-hundred-ms admission stall."""
    import numpy as np

    from dlnetbench_tpu.serving.arrivals import _Rng
    rng = _Rng((rid + 1) * 0x9E3779B9)
    return np.fromiter((rng.uniform_int(0, vocab_size - 1)
                        for _ in range(prompt_len)),
                       dtype=np.int32, count=prompt_len)


def prompt_tokens_for(req, vocab_size: int):
    """The request's full prompt: when the arrival plan stamped a
    shared system-prompt prefix (``Request.prefix_id``/``prefix_len``,
    serving/arrivals.py — ISSUE 12), the first ``prefix_len`` tokens
    come from the PREFIX POOL's seeded stream (the same tokens for
    every request drawing that prefix — which is what makes them
    page-shareable), the tail from the request's own ``rid`` stream.
    Without a prefix this is exactly ``prompt_tokens``."""
    import numpy as np

    from dlnetbench_tpu.serving.arrivals import _Rng
    if getattr(req, "prefix_id", -1) < 0 or req.prefix_len <= 0:
        return prompt_tokens(req.rid, req.prompt_len, vocab_size)
    n_pre = min(req.prefix_len, req.prompt_len)
    rng = _Rng((req.prefix_id + 1) * 0xC2B2AE3D)
    pre = np.fromiter((rng.uniform_int(0, vocab_size - 1)
                       for _ in range(n_pre)),
                      dtype=np.int32, count=n_pre)
    tail = prompt_tokens(req.rid, req.prompt_len, vocab_size)
    return np.concatenate([pre, tail[n_pre:]])
