"""MoE decode for the serving tier (ISSUE 15 tentpole leg d):
per-expert token batching under continuous batching.

Training-side MoE enforces capacity by DROPPING over-capacity tokens —
the residual carries them and the loss absorbs it.  A serving engine
cannot drop: every admitted slot's token must produce its next token
this step.  So the serving MoE MLP batches tokens per expert into
capacity-``C`` buffers and, when routing overflows an expert, runs
ADDITIONAL rounds (a ``lax.while_loop`` with a dynamic trip count)
until every token is processed — losslessly, with wall time
proportional to ``ceil(max_expert_load / C)``.

That makes expert load imbalance a LATENCY story, not a loss story:
balanced routing fits one round; skewed routing pays
``ceil(top_k * B / C)`` rounds on the hot expert while the others'
capacity idles — which is exactly the p99 effect the committed study
measures under a seeded skew.  The skew itself is an injection knob
(``skew_bias``): a seeded per-expert router-logit bias, the
imbalance-shaped sibling of the fault plans' seeded delays — measured
telemetry (per-expert load, rounds per step) rides the flight ring and
the record either way.

The routing math builds on ``layers.router_logits`` / top-k softmax —
the same spelling the training tiers use — so a serving MoE model is
the training model, not a fork.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dlnetbench_tpu.models import layers as L

_F32 = jnp.float32


def skew_bias(num_experts: int, skew: float, seed: int):
    """Seeded per-expert router-logit bias emulating expert-load skew
    (host-side, plan-replayable — the splitmix64 generator every
    seeded injection in this repo uses).  ``skew = 0`` returns None
    (the bias is not even added — bit-identical routing); larger skew
    concentrates routing mass on the seeded draw's favorites."""
    if skew == 0.0:
        return None
    import numpy as np

    from dlnetbench_tpu.serving.arrivals import _Rng
    rng = _Rng((seed + 1) * 0xA24BAED4)
    draws = np.array([rng.u01() for _ in range(num_experts)],
                     dtype=np.float32)
    return jnp.asarray(float(skew) * draws)


def decode_capacity(batch: int, top_k: int, num_experts: int,
                    capacity_factor: float) -> int:
    """Per-round per-expert slots of the serving MoE MLP — the
    training tier's capacity arithmetic (models/moe.group_capacity)
    over the decode batch."""
    from dlnetbench_tpu.models.moe import group_capacity
    return group_capacity(batch, top_k, num_experts, capacity_factor)


def moe_mlp_rounds(x, w_router, w_gate, w_up, w_down, *, top_k: int,
                   capacity: int, bias=None, active=None):
    """The serving MoE MLP: ``x`` [B, d] one token per slot ->
    ``(y [B, d], load [E] int32, rounds int32)``.

    Tokens are batched per expert into ``capacity`` dispatch slots per
    round; overflow runs further rounds (dynamic ``while_loop`` trip
    count = ``ceil(max_load / capacity)``) until every routed
    (token, expert) pair is computed — LOSSLESS: the result is the
    top-k gated sum ``sum_e gate[b,e] * f_e(x_b)`` exactly, whatever
    the round count.  ``bias`` (the seeded skew) is added to the
    router logits; ``active`` [B] masks inactive slots out of routing
    (they occupy no capacity and report no load).  ``load`` is this
    call's per-expert routed-token histogram and ``rounds`` the trip
    count — the expert-imbalance telemetry the engine records."""
    b, d = x.shape
    e = w_gate.shape[0]
    logits = L.router_logits(x, w_router)
    if bias is not None:
        logits = logits + bias[None, :]
    top_vals, idx = lax.top_k(logits, top_k)
    weights = jax.nn.softmax(top_vals, axis=-1)
    onehot = jax.nn.one_hot(idx, e, dtype=_F32)          # [B, k, E]
    gate = jnp.sum(onehot * weights[..., None], axis=1)  # [B, E]
    mask = jnp.sum(onehot, axis=1)                       # [B, E]
    if active is not None:
        act = active.astype(_F32)[:, None]
        mask = mask * act
        gate = gate * act
    pos = jnp.cumsum(mask, axis=0) - 1.0                 # queue order
    load = jnp.sum(mask, axis=0)                         # [E]
    rounds = jnp.ceil(jnp.max(load) / capacity).astype(jnp.int32)
    xf = x.astype(_F32)

    def cond(carry):
        return carry[0] < rounds

    def body(carry):
        r, y = carry
        lo = r.astype(_F32) * capacity
        sel = mask * (pos >= lo) * (pos < lo + capacity)
        disp = jax.nn.one_hot((pos - lo).astype(jnp.int32), capacity,
                              dtype=_F32) * sel[..., None]  # [B, E, C]
        xe = jnp.einsum("bec,bd->ecd", disp, xf).astype(x.dtype)
        h = jax.nn.silu(jnp.einsum("ecd,edh->ech", xe, w_gate,
                                   preferred_element_type=_F32))
        h = h * jnp.einsum("ecd,edh->ech", xe, w_up,
                           preferred_element_type=_F32)
        out = jnp.einsum("ech,ehd->ecd", h.astype(x.dtype), w_down,
                         preferred_element_type=_F32)
        y = y + jnp.einsum("ecd,bec->bd", out, disp * gate[..., None])
        return r + 1, y

    _, y = lax.while_loop(cond, body,
                          (jnp.int32(0), jnp.zeros((b, d), _F32)))
    return y.astype(x.dtype), load.astype(jnp.int32), rounds
