"""Seeded counter-based on-device sampling + grammar-constrained
decode (ISSUE 19 tentpole).

Everything decode-side was greedy argmax until this module: the fused
N-step loop, speculative decode, the whole PR-11 dispatch-floor story
were unreachable for the workloads production serving actually runs —
temperature/top-k/top-p sampling and JSON-constrained outputs.  A host
round-trip per sampled token would resurrect the measured ~566 µs
dispatch floor, so sampling must live INSIDE the compiled programs.

The key-derivation contract (the load-bearing design decision): every
uniform draw is a stateless function of ``(sample_seed, slot_uid,
counter, lane)`` — there is NO carried PRNG state.

* ``slot_uid`` is the request id (``Request.rid``; warm requests ride
  negative rids), NOT the slot index — so a crash-shrink re-queue that
  lands the request in a different slot of a rebuilt engine replays the
  SAME tokens for every position it decodes again.
* ``counter`` is the cache position of the token being FED when the
  draw happens — i.e. the token that lands at absolute stream position
  ``P`` is drawn with ``counter = P - 1`` whatever program drew it
  (prefill's TTFT token, the classic 1-step step, the fused N-step
  loop, a speculative bonus draw).  N-step fused sampling is therefore
  **bit-identical** to 1-step sampling by construction: the key IS the
  position, and adaptive-N recompiles nothing because no PRNG state
  crosses the carry.
* ``lane`` separates the independent draws one position needs
  (``LANE_TOKEN`` the emitted-token draw, ``LANE_ACCEPT`` the
  speculative accept test, ``LANE_RESID`` the residual resample,
  ``LANE_DRAFT`` the drafter's own draw).  A (lane, counter) pair is
  consumed for an EMITTED token at most once across a request's whole
  lifetime — re-draws of discarded speculative overshoot reuse keys
  whose values never entered the output, which is exactly as good as
  fresh randomness under the PRF reading of the derivation.

The derivation itself is a murmur3-fmix32-style 32-bit finalizer chain
(host twin in plain masked Python ints, device twin in ``jnp.uint32``
— bit-equal, locked by a golden-value test like the splitmix64 streams
in serving/arrivals.py).  uint64 is unavailable in-graph under the
repo's default x64-disabled JAX, which is why the derivation is 32-bit
end to end; 24 high bits make the uniform (exact in f32).

The filtering pipeline (one definition for the direct sampler, the
speculative target distribution AND the truncated drafter's
distribution — sharing it is what makes the rejection-sampling
equality a structural property): grammar mask -> temperature ->
top-k (kth-value threshold; ties keep extra entries, deterministically)
-> top-p (sorted exclusive-cumsum mask; the top-1 token always
survives) -> softmax.  ``temperature == 0`` is defined as the ONE-HOT
distribution on the (masked) argmax, so the speculative accept rule
``u·q(t) < p(t)`` degenerates to exact-match greedy acceptance and the
whole stack has a single acceptance story.

Grammar-constrained decode compiles a JSON-mode grammar to a dense
``[states, vocab]`` token mask + transition table over the synthetic
vocab (token class = ``token % 4``: ``[`` / ``]`` / scalar / comma —
a depth-bounded balanced-bracket automaton whose every state admits at
least one class, so a constrained slot can never strand maskless).
The per-slot automaton state rides the packed device-state carry
(decode.STATE_GRAMMAR); constrained + speculative composes because
out-of-grammar drafts have zero target probability and auto-reject.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np

_F32 = jnp.float32
_M32 = 0xFFFFFFFF
_GOLDEN = 0x9E3779B9          # the same golden ratio the prompt
#                               streams key by (serving/decode.py)
_FMIX_C1 = 0x85EBCA6B         # murmur3 fmix32 constants
_FMIX_C2 = 0xC2B2AE35
_NEG = jnp.float32(-1e30)     # masked-logit sentinel (kv_cache's
#                               MASK_VALUE discipline)

# draw lanes: the independent uniforms one stream position can consume
LANE_TOKEN = 0    # the emitted-token draw (and the spec bonus draw)
LANE_ACCEPT = 1   # speculative accept test at this position
LANE_RESID = 2    # residual-distribution resample at this position
LANE_DRAFT = 3    # the drafter's own draw at this position


# ---------------------------------------------------------------------
# the keyed derivation: host twin (python ints) + device twin (uint32)

def _fmix32_host(x: int) -> int:
    x &= _M32
    x ^= x >> 16
    x = (x * _FMIX_C1) & _M32
    x ^= x >> 13
    x = (x * _FMIX_C2) & _M32
    x ^= x >> 16
    return x


def key_bits(seed: int, uid: int, counter: int, lane: int) -> int:
    """The 32-bit draw key for ``(seed, uid, counter, lane)`` — host
    reference the device twin is golden-locked against.  Negative
    uids (warm requests) fold as their two's-complement uint32, the
    same value an in-graph int32->uint32 cast produces."""
    h = _fmix32_host((seed & _M32) ^ _GOLDEN)
    for v in (uid, counter, lane):
        h = _fmix32_host(h ^ (v & _M32))
    return h


def key_u01(seed: int, uid: int, counter: int, lane: int) -> float:
    """The uniform in [0, 1) the device draws for this key: the top
    24 bits of ``key_bits`` (exact in f32)."""
    return (key_bits(seed, uid, counter, lane) >> 8) / float(1 << 24)


def _fmix32_dev(x):
    x = x ^ (x >> 16)
    x = x * np.uint32(_FMIX_C1)
    x = x ^ (x >> 13)
    x = x * np.uint32(_FMIX_C2)
    x = x ^ (x >> 16)
    return x


# ---------------------------------------------------------------------
# grammar: JSON-mode token-mask automaton over the synthetic vocab

GRAMMARS = ("json",)
N_TOKEN_CLASSES = 4
CLASS_OPEN, CLASS_CLOSE, CLASS_SCALAR, CLASS_COMMA = 0, 1, 2, 3
JSON_MAX_DEPTH = 3


@dataclasses.dataclass
class Grammar:
    """A compiled token-mask automaton: ``mask[s, t]`` says token ``t``
    is legal in state ``s``; ``trans[s, t]`` the state after emitting
    it (meaningful only where ``mask`` holds — masked entries carry 0
    and are unreachable by construction)."""
    name: str
    mask: np.ndarray    # [states, vocab] bool
    trans: np.ndarray   # [states, vocab] int32
    start: int

    @property
    def num_states(self) -> int:
        return self.mask.shape[0]


def _json_automaton(depth: int):
    """The depth-bounded balanced-bracket JSON-mode automaton over the
    four token classes.  States: ``S0`` (top level, expects a value —
    a stream of scalars and balanced arrays), ``A_d`` (just opened
    depth ``d``, expects a value or an immediate close), ``B_d``
    (inside depth ``d`` after a value, expects comma or close),
    ``V_d`` (after a comma at depth ``d``, strictly expects a value).
    Every state admits at least one class — the automaton is total, so
    a constrained slot always has a nonempty mask (locked by test)."""
    s0 = 0

    def a(d):
        return d                       # 1..depth

    def b(d):
        return depth + d               # depth+1..2*depth

    def v(d):
        return 2 * depth + d           # 2*depth+1..3*depth

    n = 3 * depth + 1
    allowed = np.zeros((n, N_TOKEN_CLASSES), bool)
    nxt = np.zeros((n, N_TOKEN_CLASSES), np.int32)

    def arc(s, c, t):
        allowed[s, c] = True
        nxt[s, c] = t

    arc(s0, CLASS_SCALAR, s0)
    arc(s0, CLASS_OPEN, a(1))
    for d in range(1, depth + 1):
        arc(a(d), CLASS_SCALAR, b(d))
        arc(a(d), CLASS_CLOSE, s0 if d == 1 else b(d - 1))
        arc(b(d), CLASS_COMMA, v(d))
        arc(b(d), CLASS_CLOSE, s0 if d == 1 else b(d - 1))
        arc(v(d), CLASS_SCALAR, b(d))
        if d < depth:
            arc(a(d), CLASS_OPEN, a(d + 1))
            arc(v(d), CLASS_OPEN, a(d + 1))
    return allowed, nxt, s0


def compile_grammar(name: str, vocab: int) -> Grammar:
    """Grammar name -> dense ``[states, vocab]`` tables.  The synthetic
    vocab maps token -> class as ``token % 4`` (the serving analogue of
    the seeded synthetic prompts: replayable structure with no
    tokenizer dependency)."""
    if name not in GRAMMARS:
        raise ValueError(f"sampling: unknown grammar {name!r} "
                         f"(one of {GRAMMARS})")
    if vocab < N_TOKEN_CLASSES:
        raise ValueError(
            f"sampling: grammar {name!r} needs vocab >= "
            f"{N_TOKEN_CLASSES} (token class = token % "
            f"{N_TOKEN_CLASSES}), got {vocab}")
    allowed, nxt, start = _json_automaton(JSON_MAX_DEPTH)
    cls = np.arange(vocab) % N_TOKEN_CLASSES
    return Grammar(name=name, mask=allowed[:, cls],
                   trans=nxt[:, cls].astype(np.int32), start=start)


def validate_stream(grammar: Grammar, tokens, state: int | None = None
                    ) -> bool:
    """Host replay of the mask/transition tables over an emitted token
    stream — the study's per-grid-point validity check (and the
    table's own correctness oracle in tests)."""
    s = grammar.start if state is None else state
    for t in tokens:
        if not grammar.mask[s, int(t)]:
            return False
        s = int(grammar.trans[s, int(t)])
    return True


# ---------------------------------------------------------------------
# config + the consolidated validator (engine build AND arg-parse time)

@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """The sampling knobs in one frozen record.  ``temperature == 0``
    IS greedy (the one-hot distribution); ``grammar`` alone turns the
    sampler on in masked-greedy mode."""
    temperature: float = 0.0
    top_k: int = 0          # 0 = off; >= 1 keeps the k highest logits
    top_p: float = 1.0      # 1.0 = off; (0, 1) keeps the nucleus
    sample_seed: int = 0
    grammar: str = ""       # "" = unconstrained; else one of GRAMMARS

    @property
    def enabled(self) -> bool:
        return self.temperature > 0.0 or bool(self.grammar)


def check_sampling_config(*, temperature: float, top_k: int,
                          top_p: float, sample_seed: int, grammar: str,
                          speculative: bool = False,
                          drafter: str = "ngram") -> SamplingConfig:
    """The ONE sampling validator (the PR-11 ``check_spec_config``
    pattern): ``ServingConfig.validate`` runs it at engine build and
    ``cli serve`` runs it at arg-parse time, so every invalid combo is
    a tidy usage error in both places, never an engine traceback.

    The old blanket "speculative requires greedy" refusal is dead —
    speculation is lossless under sampling via rejection-sampling
    acceptance.  What speculation DOES require is a drafter with a
    distribution: the ngram drafter proposes tokens but carries no
    probabilities, and the accept rule ``u·q(t) < p(t)`` needs ``q``.
    Greedy speculation keeps both drafters."""
    if temperature < 0.0:
        raise ValueError(f"sampling: temperature must be >= 0 "
                         f"(0 = greedy), got {temperature}")
    if top_k < 0:
        raise ValueError(f"sampling: top_k must be >= 1 when set "
                         f"(0 = off), got {top_k}")
    if not (0.0 < top_p <= 1.0):
        raise ValueError(f"sampling: top_p must be in (0, 1], "
                         f"got {top_p}")
    if grammar and grammar not in GRAMMARS:
        raise ValueError(f"sampling: unknown grammar {grammar!r} "
                         f"(one of {GRAMMARS})")
    if temperature == 0.0 and top_k > 0:
        raise ValueError(
            f"sampling: top_k={top_k} without temperature > 0 — "
            f"greedy ignores the cutoff; set --temperature or drop "
            f"top_k")
    if temperature == 0.0 and top_p < 1.0:
        raise ValueError(
            f"sampling: top_p={top_p} without temperature > 0 — "
            f"greedy ignores the nucleus; set --temperature or drop "
            f"top_p")
    cfg = SamplingConfig(temperature=temperature, top_k=top_k,
                         top_p=top_p, sample_seed=sample_seed,
                         grammar=grammar)
    if cfg.enabled and speculative and drafter != "truncated":
        raise ValueError(
            f"sampling: speculative sampling requires drafter probs — "
            f"the {drafter!r} drafter proposes tokens without a "
            f"distribution, and the rejection-sampling accept rule "
            f"needs q(draft); use drafter='truncated' (greedy "
            f"speculation keeps both drafters)")
    return cfg


# ---------------------------------------------------------------------
# the device sampler

class DeviceSampler:
    """The in-graph half of the sampling stack, built once per engine:
    closes over the knobs and the compiled grammar tables, and exposes
    the keyed-uniform, filtered-distribution and inverse-CDF-draw
    primitives every decode program shares (decode._step_tokens, the
    fused loop, the speculative draft/verify bodies, the prefill TTFT
    draw).  Also carries the HOST twins the classic 1-step engine's
    bookkeeping uses (grammar transitions between fenced steps)."""

    def __init__(self, cfg: SamplingConfig, vocab: int):
        self.cfg = cfg
        self.vocab = vocab
        # fold the seed host-side once: the device chain starts at the
        # already-mixed seed word (one fewer in-graph round per draw)
        self._seed_h = _fmix32_host((cfg.sample_seed & _M32) ^ _GOLDEN)
        self.grammar = (compile_grammar(cfg.grammar, vocab)
                        if cfg.grammar else None)
        self.mask_dev = (jnp.asarray(self.grammar.mask)
                         if self.grammar else None)
        self.trans_dev = (jnp.asarray(self.grammar.trans)
                          if self.grammar else None)
        self.start_state = self.grammar.start if self.grammar else 0

    # ---- keyed uniforms ---------------------------------------------
    def u01(self, uids, counters, lane: int):
        """[B] uniforms in [0, 1) for ``(seed, uid, counter, lane)`` —
        the device twin of ``key_u01`` (bit-equal, golden-locked)."""
        h = jnp.uint32(self._seed_h)
        h = _fmix32_dev(h ^ uids.astype(jnp.uint32))
        h = _fmix32_dev(h ^ counters.astype(jnp.uint32))
        h = _fmix32_dev(h ^ jnp.uint32(lane & _M32))
        return (h >> 8).astype(_F32) * _F32(1.0 / (1 << 24))

    # ---- the filtering pipeline -------------------------------------
    def gmask(self, gstate):
        """Per-slot legal-token mask [B, vocab] from the automaton
        states, or None when unconstrained."""
        if self.mask_dev is None:
            return None
        return self.mask_dev[gstate]

    def _filter(self, logits):
        """temperature -> top-k -> top-p on [B, vocab] logits (grammar
        already masked by the caller); returns filtered logits ready
        for the final softmax."""
        x = logits / _F32(self.cfg.temperature)
        k = self.cfg.top_k
        if k and k < self.vocab:
            kth = jnp.sort(x, axis=-1)[..., self.vocab - k]
            x = jnp.where(x >= kth[..., None], x, _NEG)
        p = self.cfg.top_p
        if p < 1.0:
            order = jnp.argsort(-x, axis=-1)
            xs = jnp.take_along_axis(x, order, axis=-1)
            ps = jax.nn.softmax(xs, axis=-1)
            cum = jnp.cumsum(ps, axis=-1) - ps     # exclusive
            keep_s = cum < _F32(p)                 # top-1 always kept
            rows = jnp.arange(x.shape[0])[:, None]
            keep = jnp.zeros(x.shape, bool).at[rows, order].set(keep_s)
            x = jnp.where(keep, x, _NEG)
        return x

    def probs(self, logits, gstate=None):
        """The filtered target distribution [B, vocab] — the ONE
        definition the direct draw, the speculative accept/residual
        math and the drafter distribution all share.  ``temperature ==
        0`` returns the one-hot on the masked argmax (the greedy
        distribution — the accept rule then IS exact-match greedy)."""
        x = logits.astype(_F32)
        m = self.gmask(gstate) if gstate is not None else None
        if m is not None:
            x = jnp.where(m, x, _NEG)
        if self.cfg.temperature <= 0.0:
            hot = jnp.argmax(x, axis=-1)
            return jax.nn.one_hot(hot, self.vocab, dtype=_F32)
        return jax.nn.softmax(self._filter(x), axis=-1)

    # ---- draws ------------------------------------------------------
    def draw_from_probs(self, p, u):
        """Inverse-CDF categorical draw: one uniform per token.  The
        ``u * cdf_total`` rescale + the ``p > 0`` guard make the edge
        cases exact: a zero-probability (grammar-masked, filtered)
        token is unreachable even at ``u == 0`` or at float-rounding
        boundaries of the cumsum."""
        cdf = jnp.cumsum(p, axis=-1)
        lim = u * cdf[..., -1]
        hit = (cdf >= lim[..., None]) & (p > 0)
        return jnp.argmax(hit, axis=-1).astype(jnp.int32)

    def draw_tokens(self, logits, uids, counters, gstate=None):
        """The emitted-token draw (``LANE_TOKEN``) for one batched
        step: ``counters`` is the fed position per slot (the key IS
        the position — the whole bit-identity contract)."""
        if self.cfg.temperature <= 0.0:
            x = logits.astype(_F32)
            m = self.gmask(gstate) if gstate is not None else None
            if m is not None:
                x = jnp.where(m, x, _NEG)
            return jnp.argmax(x, axis=-1).astype(jnp.int32)
        u = self.u01(uids, counters, LANE_TOKEN)
        return self.draw_from_probs(self.probs(logits, gstate), u)

    # ---- grammar state ----------------------------------------------
    def advance(self, gstate, tokens):
        """Automaton step [B] — identity when unconstrained (the
        grammar row then just carries zeros)."""
        if self.trans_dev is None:
            return gstate
        return self.trans_dev[gstate, tokens]

    def host_advance(self, gstate: int, token: int) -> int:
        """The classic 1-step engine's host-side twin of ``advance``
        (it fences every token anyway, so the transition costs one
        numpy lookup between steps, not a program operand)."""
        if self.grammar is None:
            return gstate
        return int(self.grammar.trans[gstate, token])


# ---------------------------------------------------------------------
# distribution-equality machinery (the speculative parity lock)

def chi_square(counts, probs, min_expected: float = 5.0
               ) -> tuple[float, int]:
    """Pearson chi-square of observed ``counts`` against the exact
    distribution ``probs``, with small-expected bins pooled (the
    textbook validity rule) — ``(statistic, degrees_of_freedom)``.
    Plain numpy, no scipy: the container doesn't ship it and the test
    must not gate on an optional dependency."""
    counts = np.asarray(counts, float)
    probs = np.asarray(probs, float)
    n = counts.sum()
    if n <= 0:
        raise ValueError("chi_square: no samples")
    exp = probs / probs.sum() * n
    order = np.argsort(exp)
    c_bins: list[float] = []
    e_bins: list[float] = []
    c_acc = e_acc = 0.0
    for i in order:
        c_acc += counts[i]
        e_acc += exp[i]
        if e_acc >= min_expected:
            c_bins.append(c_acc)
            e_bins.append(e_acc)
            c_acc = e_acc = 0.0
    if e_acc > 0:
        if e_bins:
            c_bins[-1] += c_acc
            e_bins[-1] += e_acc
        else:
            c_bins.append(c_acc)
            e_bins.append(e_acc)
    stat = float(sum((c - e) ** 2 / e
                     for c, e in zip(c_bins, e_bins) if e > 0))
    return stat, max(len(e_bins) - 1, 1)


def chi_square_critical(df: int, z: float = 3.090) -> float:
    """Upper critical value via the Wilson–Hilferty cube approximation
    (``z = 3.090`` is the normal quantile for p ~= 0.001).  Within a
    fraction of a percent of the exact table for df >= 3 — plenty for
    a pass/fail bar with seeded, deterministic statistics."""
    if df < 1:
        raise ValueError(f"chi_square_critical: df must be >= 1, "
                         f"got {df}")
    t = 1.0 - 2.0 / (9.0 * df) + z * math.sqrt(2.0 / (9.0 * df))
    return df * t ** 3
