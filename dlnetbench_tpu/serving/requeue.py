"""Crash-shrink segmentation helpers shared by every serving runner.

The re-queue-with-ORIGINAL-arrival-stamps arc was spelled three ways
before ISSUE 18 — ``run_serving``'s crash-shrink except block,
``run_disagg``'s prefill-crash block, and the fleet router's
drain/crash paths would have made a third — and three copies of a
fault-accounting contract is a bug farm.  This module is the single
spelling:

* ``detect_shrink`` — the catch-side head: re-raise unless the fault
  is a shrinkable crash/preempt under policy ``shrink``, stamp
  detection at the catch, fire the ISSUE-14 ``fault`` anomaly trigger
  (the flight ring into the crash dumps), and hand back the survivor
  ranks (``FaultPlan.shrink_survivors``).
* ``requeue_unfinished`` — the re-queue step: drain a source engine or
  server and hand its leftovers back in arrival order WITH their
  original arrival stamps, so the disruption lands in the re-run
  requests' measured latency (never reset — a re-stamped arrival would
  hide the outage from the SLO timeline).
* ``run_requeued`` — the continuation: re-run the rebuilt target over
  the leftovers anchored at the FIRST segment's clock origin, keeping
  every stamp on one timeline.

Callers keep their own stat-merge bookkeeping (each runner's engines
carry different accumulators); the fault CONTRACT — what counts as
shrinkable, when detection is stamped, which ranks survive, and what
happens to an unfinished request's stamps — lives here once.
"""
from __future__ import annotations

import time

from dlnetbench_tpu.metrics import telemetry
from dlnetbench_tpu.serving.arrivals import Request


def detect_shrink(e: BaseException, *, injector, fault_plan, world: int,
                  step: int, detail: dict | None = None
                  ) -> tuple[float, list[int]]:
    """Classify a caught fault for a crash-shrink segmentation.

    Re-raises ``e`` unless it is a ``RankFailure``/``RankPreempted``
    under policy ``shrink`` (any other exception — or a crash under
    fail_fast/retry — is not this arc's to absorb).  Otherwise stamps
    ``detection_ms`` at the catch (wall time from the injector's raise
    to here — the detection latency every resilience record prices),
    fires the ``fault`` anomaly trigger with the fault's provenance
    (``detail`` adds caller context, e.g. which replica owned the dead
    rank), and returns ``(detection_ms, survivors)``.  An empty
    survivor list is returned, not raised — liveness rules differ per
    runner (a disaggregated server also dies when one whole PHASE is
    gone), so the caller decides when to give up."""
    from dlnetbench_tpu.faults.inject import RankFailure, RankPreempted
    if not isinstance(e, (RankFailure, RankPreempted)) \
            or fault_plan is None or fault_plan.policy != "shrink":
        raise e
    detection_ms = (time.monotonic() - injector.crash_raised_at) * 1e3
    telemetry.trigger("fault", step=step, detail={
        "kind": type(e).__name__,
        "rank": getattr(e, "rank", None),
        "iteration": getattr(e, "iteration", None),
        "detection_ms": round(detection_ms, 3),
        **(detail or {})})
    return detection_ms, fault_plan.shrink_survivors(world)


def requeue_unfinished(source) -> list[Request]:
    """Drain ``source`` (an Engine, DisaggServer, or FleetServer — any
    object with ``drain_unfinished()``) and hand back its unfinished
    requests in arrival order, ORIGINAL arrival stamps kept.  The
    drain frees the source's slots and pages; in-flight requests lose
    their decode progress (their cache dies with the drained capacity)
    and the rebuilt capacity redoes their work — the disruption lands
    in their measured latency, which is the honesty bar every
    fault-composition study in this repo holds to."""
    return sorted(source.drain_unfinished(),
                  key=lambda r: (r.arrival_s, r.rid))


def run_requeued(target, leftovers: list[Request], *, injector,
                 t_origin: float):
    """Finish a fault-segmented run: drive ``target`` (the rebuilt,
    degraded engine/server) over the re-queued leftovers with the
    FIRST segment's clock origin, so every stamp — the survivors'
    and the re-run requests' — lives on one timeline and the SLO
    goodput timeline shows the dip AND the recovery.  The injector
    rides along: later scripted events still land in the degraded
    segment."""
    return target.run(leftovers, injector=injector, t_origin=t_origin)
