"""Self-drafting speculative decode inside the fused device loop.

The multi-step loop (serving/decode.make_multi_step_decode) already
buys one host dispatch per N tokens; speculative decode buys MORE
tokens per device step: draft ``k`` tokens cheaply, verify all ``k``
in ONE batched target pass, accept the longest matching prefix plus
the target's bonus token — entirely on device, inside the same
``lax.while_loop``, so a round emits between 1 and ``k + 1`` tokens
for roughly the device cost of one wide step.

Under greedy acceptance this is LOSSLESS: the verify pass computes the
target model's own greedy continuation at every drafted position, and
only drafts that MATCH it are kept — the emitted stream is exactly the
1-step greedy stream whatever the drafter proposes (locked by test).
Under SAMPLING (ISSUE 19) it is lossless too, via rejection-sampling
acceptance against the filtered target distribution (accept draft t
with probability min(1, p(t)/q(t)), residual resample at the first
reject, bonus draw on full acceptance — see make_spec_decode_loop;
the chi-square distribution-equality test in tests/test_sampling.py
is the parity lock).  One basis caveat: the verify pass runs the
dense-gather
attention math (the Pallas ``paged_attention`` kernel is single-query
and cannot serve K1 positions), so the parity lock is EXACT where the
1-step engine shares that math — the CPU mesh, or ``attn_impl=
"gather"`` on chip.  Against the on-chip Pallas 1-step path the two
argmaxes agree to kernel-parity tolerance (the tpu_only
pallas-vs-gather case bounds it), not bit-exactly — a near-tie in the
logits can diverge.  The drafter only moves the ACCEPTANCE RATE, i.e.
throughput:

* ``ngram``     — a per-slot bigram table ``[slots, vocab]`` on device:
  ``table[s, t]`` is the token that last followed ``t`` in slot ``s``'s
  stream (host seeds it from the prompt at admission; the loop updates
  it from emitted tokens).  Drafting is ``k`` chained table lookups —
  near-zero device cost, so even modest acceptance wins.
* ``truncated`` — the first ``drafter_layers`` layers of the target
  plus the shared final-norm/head (self-drafting: no second model, no
  extra weights).  Layer-truncated activations are exact for the
  layers they run, so the drafter writes the SAME k/v the verify pass
  would for layers ``< drafter_layers`` — the overlap is idempotent,
  and rejected positions are overwritten on the next round's feed.

Cache discipline mirrors the engine's admission contract: a fed token
writes k/v only while ``position < seq_limit`` (the slot's
prompt+output page reservation) — draft overshoot beyond the budget
writes nowhere, and every token the accept logic can USE is provably
inside the reservation (``emit <= remaining``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dlnetbench_tpu.models import layers as L
from dlnetbench_tpu.models.transformer import TransformerConfig
from dlnetbench_tpu.serving.decode import (_attn_fn, _rope_decode,
                                           _step_tokens, check_config)
from dlnetbench_tpu.serving.kv_cache import MASK_VALUE, CacheConfig

_F32 = jnp.float32

DRAFTERS = ("ngram", "truncated")


def check_spec_config(cfg: TransformerConfig, *, spec_k: int,
                      drafter: str, drafter_layers: int) -> None:
    """Speculative knobs the model shape must also agree with (the
    ServingConfig-level checks live in scheduler.ServingConfig)."""
    if cfg.num_experts > 1:
        raise ValueError(
            "speculative: MoE models are not supported — the "
            "draft/verify overwrite cycle has no stated parity story "
            "through the MoE overflow rounds (ISSUE 15)")
    if spec_k < 1:
        raise ValueError(f"speculative: spec_k must be >= 1, got "
                         f"{spec_k}")
    if drafter not in DRAFTERS:
        raise ValueError(f"speculative: unknown drafter {drafter!r} "
                         f"(one of {DRAFTERS})")
    if drafter == "truncated" and not (
            1 <= drafter_layers < cfg.num_layers):
        raise ValueError(
            f"speculative: truncated drafter needs 1 <= drafter_layers "
            f"< num_layers ({cfg.num_layers}), got {drafter_layers} — "
            f"a full-depth drafter is the target itself (no draft "
            f"speedup, double the cost)")


def _verify_tokens(cfg: TransformerConfig, cache_cfg: CacheConfig,
                   params, k_pages, v_pages, tokens, positions,
                   write_ok, block_tables, *,
                   return_logits: bool = False):
    """The batched multi-token TARGET pass: feed ``tokens`` [B, K1]
    starting at cache index ``positions`` [B] per slot, write their k/v
    (where ``write_ok`` [B, K1] allows), attend causally over
    cache + fed tokens, and return the greedy continuation after EVERY
    fed position — ``out[b, j]`` is the target's next token given
    ``tokens[b, :j+1]``, which is all the accept rule needs.

    One dispatch-free pass costs ~K1x a single decode step on the MXU
    but verifies K1 positions — the speculative trade.  Attention is
    the dense gather form (length-masked fp32 softmax over the slot's
    gathered pages — kv_cache._gather_attention's math extended to K1
    queries); the Pallas decode kernel is single-query and does not
    apply.

    ``return_logits`` (ISSUE 19) appends the raw ``[B, K1, vocab]``
    logits to the return — the rejection-sampling accept pass needs
    the full target distribution at every drafted position, not just
    its argmax."""
    b, k1 = tokens.shape
    page_size = cache_cfg.page_size
    num_pages = cache_cfg.num_pages
    pmax = block_tables.shape[1]
    scale = cfg.head_dim ** -0.5
    hkv = cfg.num_kv_heads
    g = cfg.num_heads // hkv
    pos2 = positions[:, None] + jnp.arange(k1, dtype=jnp.int32)[None, :]
    x = params["embed"][tokens]                       # [B, K1, D]
    page_col = jnp.minimum(pos2 // page_size, pmax - 1)
    page_id = jnp.take_along_axis(block_tables, page_col, axis=1)
    w_pages = jnp.where(write_ok, page_id, num_pages)  # OOB -> drop
    slots = pos2 % page_size
    t_len = pmax * page_size
    k_pos = jnp.arange(t_len, dtype=jnp.int32)
    keep = k_pos[None, None, :] <= pos2[:, :, None]    # [B, K1, T]
    for li in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[li], params["layers"])
        y = L.rmsnorm(x, lp["norm1"])
        q = jnp.dot(y, lp["wq"]).reshape(b, k1, cfg.num_heads,
                                         cfg.head_dim)
        k = jnp.dot(y, lp["wk"]).reshape(b, k1, hkv, cfg.head_dim)
        v = jnp.dot(y, lp["wv"]).reshape(b, k1, hkv, cfg.head_dim)
        qf, kf = _rope_decode(
            q.reshape(b * k1, cfg.num_heads, cfg.head_dim),
            k.reshape(b * k1, hkv, cfg.head_dim), pos2.reshape(-1))
        q = qf.reshape(b, k1, cfg.num_heads, cfg.head_dim)
        k = kf.reshape(b, k1, hkv, cfg.head_dim)
        k_pages = k_pages.at[li, :, w_pages, slots, :].set(
            k, mode="drop")
        v_pages = v_pages.at[li, :, w_pages, slots, :].set(
            v, mode="drop")
        # gather the slot's whole page row (stale/garbage tail masked
        # by the per-query causal length, same as _gather_attention)
        kseq = jnp.moveaxis(k_pages[li][:, block_tables], 0, 1)
        vseq = jnp.moveaxis(v_pages[li][:, block_tables], 0, 1)
        kseq = kseq.reshape(b, hkv, t_len, cfg.head_dim).astype(_F32)
        vseq = vseq.reshape(b, hkv, t_len, cfg.head_dim).astype(_F32)
        qg = (q * scale).reshape(b, k1, hkv, g,
                                 cfg.head_dim).astype(_F32)
        scores = jnp.einsum("bjhgd,bhtd->bhgjt", qg, kseq)
        scores = jnp.where(keep[:, None, None], scores, MASK_VALUE)
        p = jax.nn.softmax(scores, axis=-1)
        att = jnp.einsum("bhgjt,bhtd->bjhgd", p, vseq)
        att = att.reshape(b, k1, cfg.embed_dim).astype(x.dtype)
        x = x + jnp.dot(att, lp["wo"])
        y = L.rmsnorm(x, lp["norm2"])
        x = x + L.swiglu(y, lp["w_gate"], lp["w_up"], lp["w_down"])
    x = L.rmsnorm(x, params["final_norm"])
    head = params["embed"].T if cfg.tied_embeddings else params["head"]
    logits = jnp.dot(x, head, preferred_element_type=_F32)
    out = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [B, K1]
    if return_logits:
        return k_pages, v_pages, out, logits
    return k_pages, v_pages, out


def _draft_ngram(table, last_tokens, k: int):
    """k chained bigram lookups per slot: [B, vocab] table, [B] seed."""
    b = table.shape[0]
    rows = jnp.arange(b)
    drafts = []
    prev = last_tokens
    for _ in range(k):
        prev = table[rows, prev]
        drafts.append(prev)
    return jnp.stack(drafts, axis=1)                      # [B, k]


def make_spec_decode_loop(cfg: TransformerConfig,
                          cache_cfg: CacheConfig, n_max: int, *,
                          spec_k: int, drafter: str,
                          drafter_layers: int = 1,
                          attn_impl: str = "auto", mesh=None,
                          sampler=None):
    """The fused draft/verify/accept loop (ISSUE 11 tentpole, spec
    flavor).

    ``spec_loop(params, k_pages, v_pages, state, ngram_table,
    block_tables, n_rounds) -> (k_pages, v_pages, state, ngram_table,
    tokens_out, counts, rounds_run, drafted, accepted)`` — ``state``
    is the packed ``[6, slots]`` int32 carry (decode.STATE_* rows;
    ``remaining > 0`` is the active bit, ``STATE_LIMIT`` the per-slot
    reservation cap the write guard enforces).

    Per round, per active slot: draft ``spec_k`` tokens, verify them
    in one batched target pass, emit ``min(accept + 1, remaining)``
    target tokens (the accepted prefix IS the target's own greedy
    stream; the +1 is the bonus token from the first mismatched
    position), advance position/remaining by the same amount (fed ==
    emitted, so the host-side page append stays one batched call per
    sync).  ``tokens_out`` is ``[B, n_max * (spec_k + 1)]`` — the
    worst-case all-accepted capacity; ``counts`` says how much is
    real.  ``drafted``/``accepted`` accumulate the RAW acceptance
    stats (pre-clamp — the drafter's quality, not the budget's), which
    ride the record as the acceptance-rate metric.

    With a ``sampler`` (ISSUE 19) the loop runs LOSSLESS speculative
    SAMPLING — standard rejection-sampling acceptance against the
    target distribution instead of greedy exact-match:

    * drafts are SAMPLED from the truncated drafter's own filtered
      distribution ``q_j`` (``LANE_DRAFT`` keyed uniforms; the ngram
      drafter is refused here — it proposes tokens with no
      distribution, and the accept rule needs ``q``);
    * draft ``j`` is accepted iff ``u_j · q_j(d_j) < p_j(d_j)``
      (``LANE_ACCEPT``) where ``p_j`` is the FILTERED target
      distribution at that position — exactly the min(1, p/q) accept
      probability, strict so a zero-target-probability draft (e.g.
      out-of-grammar) can NEVER be accepted;
    * the first rejected position resamples from the normalized
      residual ``max(p - q, 0)`` (``LANE_RESID``; falls back to ``p``
      itself when the residual is empty, which happens exactly when
      ``q`` dominates ``p`` nowhere — e.g. identical one-hots at
      temperature 0);
    * full acceptance draws the bonus token from ``p_k``
      (``LANE_TOKEN`` at the bonus position — the same key the
      non-spec sampler would use there).

    The emitted-stream distribution provably equals the unfused
    single-step sampler's (the chi-square parity lock in
    tests/test_sampling.py); ``temperature == 0`` distributions are
    one-hots, so the rule degenerates to exact-match greedy and the
    greedy parity lock still holds.  Grammar states ride
    ``STATE_GRAMMAR`` and advance along the EMITTED tokens; target
    probs are masked per-position through the draft chain's automaton
    states, so constrained + speculative composes for free."""
    check_config(cfg, decode=True)
    check_spec_config(cfg, spec_k=spec_k, drafter=drafter,
                      drafter_layers=drafter_layers)
    if sampler is not None and drafter != "truncated":
        # mirrored at sampling.check_sampling_config — rejection
        # sampling needs q(draft), which only the truncated drafter has
        raise ValueError(
            "spec_decode_loop: speculative sampling requires drafter "
            f"probs — drafter {drafter!r} has no distribution; use "
            "drafter='truncated'")
    if cache_cfg.quantized:
        # the ServingConfig-level refusal, mirrored at the builder:
        # the verify pass overwrites drafter rows and every overwrite
        # re-quantizes the page — a parity bar for that write cycling
        # has not been stated, so the combination is refused loudly
        # rather than shipped untested (docs/SERVING.md)
        raise ValueError(
            "speculative decode supports the bf16 cache only — "
            f"cache_dtype={cache_cfg.cache_dtype!r} re-quantizes "
            "pages on every draft/verify overwrite and has no stated "
            "parity bar; run speculative on the dense cache")
    if n_max < 1:
        raise ValueError(f"spec_decode_loop: n_max must be >= 1, "
                         f"got {n_max}")
    attn = _attn_fn(cache_cfg, attn_impl, mesh)
    k1 = spec_k + 1
    cap = n_max * k1

    from dlnetbench_tpu.serving.decode import (STATE_GRAMMAR,
                                               STATE_LAST, STATE_LIMIT,
                                               STATE_POS, STATE_REM,
                                               STATE_UID)
    from dlnetbench_tpu.serving.sampling import (LANE_ACCEPT,
                                                 LANE_DRAFT,
                                                 LANE_RESID,
                                                 LANE_TOKEN)

    def spec_loop(params, k_pages, v_pages, state, ngram_table,
                  block_tables, n_rounds):
        b = state.shape[1]
        rows = jnp.arange(b)
        n = jnp.minimum(n_rounds.astype(jnp.int32), n_max)
        out0 = jnp.zeros((b, cap), jnp.int32)
        counts0 = jnp.zeros((b,), jnp.int32)

        def cond(carry):
            i, _, _, st = carry[0], carry[1], carry[2], carry[3]
            return (i < n) & jnp.any(st[STATE_REM] > 0)

        def body(carry):
            (i, kp, vp, st, table, out, cnt, drafted,
             accepted) = carry
            last, pos, rem, limits = (st[STATE_LAST], st[STATE_POS],
                                      st[STATE_REM], st[STATE_LIMIT])
            act = rem > 0
            uids = st[STATE_UID]
            g0 = st[STATE_GRAMMAR]
            q_list, q_at_draft = [], []
            # ---- draft k tokens per slot
            if drafter == "ngram":
                drafts = _draft_ngram(table, last, spec_k)
            else:
                dkp, dvp = kp, vp
                prev, dpos, ds = last, pos, []
                gd = g0
                for _ in range(spec_k):
                    ok = act & (dpos < limits)
                    if sampler is None:
                        (dkp, dvp), prev = _step_tokens(
                            cfg, cache_cfg, attn, params, (dkp, dvp),
                            prev, dpos, ok, block_tables,
                            layers=drafter_layers)
                    else:
                        # SAMPLE the draft from the drafter's own
                        # filtered distribution q_j (grammar-masked
                        # through the draft chain's automaton states)
                        (dkp, dvp), _, dlog = _step_tokens(
                            cfg, cache_cfg, attn, params, (dkp, dvp),
                            prev, dpos, ok, block_tables,
                            layers=drafter_layers, return_logits=True)
                        qj = sampler.probs(dlog, gd)
                        u_d = sampler.u01(uids, dpos, LANE_DRAFT)
                        prev = sampler.draw_from_probs(qj, u_d)
                        q_list.append(qj)
                        q_at_draft.append(qj[rows, prev])
                        gd = sampler.advance(gd, prev)
                    ds.append(prev)
                    dpos = dpos + 1
                kp, vp = dkp, dvp
                drafts = jnp.stack(ds, axis=1)
            # ---- one batched target pass over [last, drafts]
            fed = jnp.concatenate([last[:, None], drafts], axis=1)
            pos2 = pos[:, None] + jnp.arange(k1, dtype=jnp.int32)
            write_ok = act[:, None] & (pos2 < limits[:, None])
            if sampler is None:
                kp, vp, tgt = _verify_tokens(cfg, cache_cfg, params,
                                             kp, vp, fed, pos,
                                             write_ok, block_tables)
                # greedy accept: longest prefix where draft == target
                match = (drafts == tgt[:, :spec_k]).astype(jnp.int32)
                acc = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
                emit = jnp.where(act, jnp.minimum(acc + 1, rem), 0)
                etoks = tgt
            else:
                kp, vp, tgt, vlogits = _verify_tokens(
                    cfg, cache_cfg, params, kp, vp, fed, pos,
                    write_ok, block_tables, return_logits=True)
                # grammar state BEFORE emitting at index j = start
                # state advanced through drafts[:j]
                gs = [g0]
                for j in range(spec_k):
                    gs.append(sampler.advance(gs[j], drafts[:, j]))
                p_js = [sampler.probs(vlogits[:, j], gs[j])
                        for j in range(k1)]
                # rejection-sampling accept: u·q(d) < p(d), strict —
                # an out-of-grammar draft has p(d) == 0 and can never
                # pass, whatever u
                p_at_draft = jnp.stack(
                    [p_js[j][rows, drafts[:, j]]
                     for j in range(spec_k)], axis=1)
                q_d = jnp.stack(q_at_draft, axis=1)
                u_acc = jnp.stack(
                    [sampler.u01(uids, pos + j, LANE_ACCEPT)
                     for j in range(spec_k)], axis=1)
                accept = (u_acc * q_d < p_at_draft).astype(jnp.int32)
                acc = jnp.sum(jnp.cumprod(accept, axis=1), axis=1)
                emit = jnp.where(act, jnp.minimum(acc + 1, rem), 0)
                # emitted token at index j: the draft while j < acc,
                # the residual resample at the first reject, the bonus
                # draw from p_k after full acceptance
                cols = []
                for j in range(k1):
                    if j < spec_k:
                        resid = jnp.maximum(p_js[j] - q_list[j], 0.0)
                        z = jnp.sum(resid, axis=-1, keepdims=True)
                        rdist = jnp.where(
                            z > 0, resid / jnp.maximum(z, 1e-30),
                            p_js[j])
                        u_r = sampler.u01(uids, pos + j, LANE_RESID)
                        r_j = sampler.draw_from_probs(rdist, u_r)
                        cols.append(jnp.where(j < acc, drafts[:, j],
                                              r_j))
                    else:
                        u_b = sampler.u01(uids, pos + spec_k,
                                          LANE_TOKEN)
                        cols.append(sampler.draw_from_probs(
                            p_js[spec_k], u_b))
                etoks = jnp.stack(cols, axis=1)
            # ---- append emitted tokens at each slot's count
            for j in range(k1):
                w = act & (j < emit)
                idx = jnp.where(w, cnt + j, cap)
                out = out.at[rows, idx].set(etoks[:, j], mode="drop")
            # ---- ngram table learns every emitted (prev -> next) pair
            if drafter == "ngram":
                prevs = jnp.concatenate([last[:, None],
                                         etoks[:, :spec_k]], axis=1)
                vocab = table.shape[1]
                for j in range(k1):
                    w = act & (j < emit)
                    row = jnp.where(w, prevs[:, j], vocab)
                    table = table.at[rows, row].set(etoks[:, j],
                                                    mode="drop")
            st = st.at[STATE_LAST].set(jnp.where(
                act, etoks[rows, jnp.maximum(emit - 1, 0)], last))
            st = st.at[STATE_POS].set(pos + emit)
            st = st.at[STATE_REM].set(rem - emit)
            if sampler is not None and sampler.trans_dev is not None:
                # grammar state advances along the EMITTED tokens only
                g_new = g0
                for j in range(k1):
                    g_new = jnp.where(j < emit,
                                      sampler.advance(g_new,
                                                      etoks[:, j]),
                                      g_new)
                st = st.at[STATE_GRAMMAR].set(g_new)
            cnt = cnt + emit
            drafted = drafted + jnp.sum(jnp.where(act, spec_k, 0))
            accepted = accepted + jnp.sum(jnp.where(act, acc, 0))
            return (i + 1, kp, vp, st, table, out, cnt, drafted,
                    accepted)

        (i, kp, vp, st, table, out, cnt, drafted,
         accepted) = lax.while_loop(
            cond, body,
            (jnp.int32(0), k_pages, v_pages, state, ngram_table, out0,
             counts0, jnp.int32(0), jnp.int32(0)))
        return kp, vp, st, table, out, cnt, i, drafted, accepted

    return spec_loop


def seed_ngram_row(prompt_tokens, first_token: int, vocab: int):
    """The host half of the ngram drafter: a fresh ``[vocab]`` bigram
    row for a newly admitted slot, seeded from the prompt (plus the
    prefill's first generated token continuing the last prompt token)
    so round one drafts from real context instead of zeros.  Called by
    the engine at admission — part of the priced h2d sync."""
    import numpy as np
    row = np.zeros((vocab,), np.int32)
    toks = np.append(np.asarray(prompt_tokens, np.int32),
                     np.int32(first_token))
    # repeated-index assignment keeps the LAST write — the most recent
    # continuation, matching the device-side sequential update rule
    row[toks[:-1]] = toks[1:]
    return row
