"""Device-resident decode-loop state + the priced host<->device sync.

The ISSUE 11 engine split: ``scheduler.Engine`` keeps HOST-side
scheduling state (the arrival queue, pending list, page free-list,
per-request bookkeeping) while everything the fused decode loop needs
per step lives HERE, on device, between syncs — packed into ONE
``[6, slots]`` int32 carry (``decode.STATE_*`` rows: last token,
position, remaining budget, reservation limit, request uid, grammar
state; ``remaining > 0`` IS the active/done bit — the uid/grammar
rows are ISSUE 19's sampling provenance and ride as zeros in greedy
engines) plus the block tables and, when speculative, the per-slot
ngram table.  Packing matters: a sync crosses the boundary
as one transfer per array, and the first draft of this module moved
six tiny arrays per direction — the sync cost rivaled the dispatch
cost the loop exists to amortize.

The sync contract (every crossing is a recorded timer):

* ``flush()``  — host -> device: upload the host mirrors.  Happens at
  ADMISSION BOUNDARIES only (a new slot entering decode phase, a
  drain) — never per token.  Priced into ``sync_h2d_us``.
* ``rebind()`` — the fused program returned updated carries: the
  device refs move forward and the host mirrors go STALE.  Free (no
  transfer) — the whole point of the device-resident loop.
* ``pull()``   — device -> host: refresh the mirrors from the device
  carries.  Required before mutating a stale mirror (``admit``/
  ``evict`` on stale state raise ``SyncContractError`` — the loud
  guard that keeps a scheduler change from silently clobbering
  device-advanced slots with stale host values).  Priced into
  ``sync_d2h_us``.

``host_view()`` round-trips losslessly with the device arrays under
any interleaving of admit/evict/flush/advance/pull — the property test
in tests/test_decode_loop.py drives exactly that.
"""
from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from dlnetbench_tpu.serving.decode import (STATE_GRAMMAR, STATE_LAST,
                                           STATE_LIMIT, STATE_POS,
                                           STATE_REM, STATE_ROWS,
                                           STATE_UID)


class SyncContractError(RuntimeError):
    """A host mirror was mutated while stale — the engine must
    ``pull()`` at the sync boundary before admitting/evicting, or the
    flush would overwrite device-advanced slot state with stale host
    values (silent token corruption, not a crash — hence the loud
    guard)."""


class DeviceDecodeState:
    """Slot state for the fused decode loop, mirrored host-side.

    Carries (loop-carried, donated by the executor, rebound from the
    program's leading outputs): the packed ``state`` array — plus
    ``ngram_table`` when speculative.  Operand (pushed at flush,
    read-only inside the loop): ``block_tables``.
    """

    def __init__(self, slots: int, max_pages_per_seq: int,
                 vocab: int | None = None):
        self.slots = slots
        self.vocab = vocab
        self.state = np.zeros((STATE_ROWS, slots), np.int32)
        self.block_tables = np.zeros((slots, max_pages_per_seq),
                                     np.int32)
        self.ngram_table = (np.zeros((slots, vocab), np.int32)
                            if vocab else None)
        self._dev: dict[str, object] = {}
        # per-FIELD dirty tracking: an evict touches one int of the
        # packed state, and re-uploading the block tables (or a
        # [slots, vocab] ngram table) alongside it would put exactly
        # the sync cost this module exists to minimize back on the
        # admission path.  (Finer still — row-granular device-side
        # updates — is a future optimization; admission itself does
        # rewrite whole rows.)
        self._dirty: set = set(self.carry_fields) | {"block_tables"}
        self.stale = False
        self.sync_h2d_us: list[float] = []
        self.sync_d2h_us: list[float] = []

    # the loop-carried fields, in the loop programs' argument order
    @property
    def carry_fields(self) -> tuple[str, ...]:
        return (("state", "ngram_table")
                if self.ngram_table is not None else ("state",))

    # ---- host-side mutation (admission boundaries) -------------------
    def _require_fresh(self, what: str) -> None:
        if self.stale:
            raise SyncContractError(
                f"device_state: {what} on STALE host mirrors — the "
                f"device advanced since the last pull(); sync first or "
                f"the next flush clobbers device state")

    def admit(self, slot: int, *, last_token: int, position: int,
              remaining: int, seq_limit: int, block_row,
              ngram_row=None, uid: int = 0,
              grammar_state: int = 0) -> None:
        """A slot enters the decode phase (prefill just completed):
        seed its device-visible state.  ``remaining`` is the output
        budget still owed (``remaining > 0`` is the active bit);
        ``seq_limit`` the prompt+output page reservation the loop must
        never write past.

        Prefix sharing (ISSUE 12) rides this same sync contract:
        ``block_row`` may map shared physical pages, and an
        admission-time copy-on-write already rewrote the divergence
        column host-side BEFORE this call — so the whole shared-page
        admission (aliased columns + the COW replacement) reaches the
        device in the ONE dirty-tracked block-table flush at the next
        dispatch, never as an extra crossing.

        ``uid`` (ISSUE 19) is the request id every sampled draw keys
        by (warm requests ride negative rids — the int32 row holds
        them; the sampler folds the two's-complement bits), and
        ``grammar_state`` the slot's automaton state after its TTFT
        token.  Both default to 0 — greedy engines never set them."""
        self._require_fresh("admit")
        self.state[STATE_LAST, slot] = last_token
        self.state[STATE_POS, slot] = position
        self.state[STATE_REM, slot] = remaining
        self.state[STATE_LIMIT, slot] = seq_limit
        self.state[STATE_UID, slot] = uid
        self.state[STATE_GRAMMAR, slot] = grammar_state
        self.block_tables[slot, :] = block_row
        self._dirty |= {"state", "block_tables"}
        if self.ngram_table is not None:
            self.ngram_table[slot, :] = (
                0 if ngram_row is None else ngram_row)
            self._dirty.add("ngram_table")

    def evict(self, slot: int) -> None:
        """Host-forced removal (drain, crash segmentation).  A slot
        that ran its budget down deactivated ITSELF on device (the
        in-loop done bit) and needs no evict call."""
        self._require_fresh("evict")
        self.state[STATE_REM, slot] = 0
        self._dirty.add("state")

    # ---- the sync points (each priced) -------------------------------
    def flush(self) -> None:
        """Host -> device, priced.  No-op when nothing changed; only
        the fields a host mutation actually touched are uploaded."""
        if not self._dirty:
            return
        t0 = time.perf_counter()
        for name in sorted(self._dirty):
            self._dev[name] = jnp.asarray(getattr(self, name))
        self.sync_h2d_us.append((time.perf_counter() - t0) * 1e6)
        self._dirty = set()
        self.stale = False

    def carries(self) -> tuple:
        """The loop-carried device arrays, flushing first if a host
        mutation is pending."""
        self.flush()
        return tuple(self._dev[name] for name in self.carry_fields)

    def block_tables_device(self):
        self.flush()
        return self._dev["block_tables"]

    def rebind(self, new_carries: tuple) -> None:
        """Adopt the fused program's updated carries (device refs only
        — no transfer); host mirrors go stale until the next pull."""
        names = self.carry_fields
        if len(new_carries) != len(names):
            raise ValueError(
                f"device_state: rebind got {len(new_carries)} carries, "
                f"expected {len(names)} ({names})")
        for name, arr in zip(names, new_carries):
            self._dev[name] = arr
        self.stale = True

    def pull(self) -> None:
        """Device -> host, priced: refresh the mirrors so the host may
        mutate again."""
        if not self.stale:
            return
        t0 = time.perf_counter()
        for name in self.carry_fields:
            np.copyto(getattr(self, name), np.asarray(self._dev[name]))
        self.sync_d2h_us.append((time.perf_counter() - t0) * 1e6)
        self.stale = False

    def sync_total_us(self) -> float:
        """Both channels' running total — the engine samples it around
        a step so in-step sync time can be EXCLUDED from
        host_dispatch_us (each crossing must be priced exactly once;
        serving_host_us sums the channels back together)."""
        return sum(self.sync_h2d_us) + sum(self.sync_d2h_us)

    # ---- inspection ---------------------------------------------------
    def host_view(self) -> dict:
        """Copies of the host mirrors (pull() first if stale to see
        device truth) — the property-test surface."""
        out = {
            "last_tokens": self.state[STATE_LAST].copy(),
            "positions": self.state[STATE_POS].copy(),
            "remaining": self.state[STATE_REM].copy(),
            "seq_limits": self.state[STATE_LIMIT].copy(),
            "uids": self.state[STATE_UID].copy(),
            "grammar_states": self.state[STATE_GRAMMAR].copy(),
            "active": (self.state[STATE_REM] > 0).copy(),
            "block_tables": self.block_tables.copy(),
        }
        if self.ngram_table is not None:
            out["ngram_table"] = self.ngram_table.copy()
        return out

    def sync_stats(self) -> dict:
        return {
            "sync_h2d_us": {"total": round(sum(self.sync_h2d_us), 1),
                            "n": len(self.sync_h2d_us)},
            "sync_d2h_us": {"total": round(sum(self.sync_d2h_us), 1),
                            "n": len(self.sync_d2h_us)},
        }
