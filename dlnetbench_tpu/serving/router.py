"""Fleet front-end router: which replica gets the next request.

The router is the ONLY fleet-level scheduling decision (ISSUE 18) —
per-replica admission control is untouched, the router just picks a
queue.  Every policy is seeded and replayable: the same plan + seed +
policy produces the same request->replica assignment on any machine
(the assignment log is part of the record's provenance, and the replay
test locks it).

Policies (``ROUTING_POLICIES``):

  round_robin     — the baseline: cycle over the active replicas in
                    index order.  No RNG draws, no load signal.
  p2c             — power-of-two-choices: draw TWO distinct active
                    replicas from the router's splitmix64 stream
                    (serving/arrivals._Rng — the same generator every
                    committed plan uses), route to the one with the
                    lower live load score, first draw wins ties.  The
                    classic balanced-allocations result: max load drops
                    from O(log n / log log n) to O(log log n) vs random
                    placement, at two probes per request.
  prefix_affinity — consult each active replica's radix trie
                    (``PagedKVCache.prefix_match_len`` — a read-only
                    probe that never touches the pool's hit-rate
                    counters) and route to the replica holding the
                    longest shared-prefix page run; ties (including
                    the no-match case) fall back to p2c.  A FULL
                    replica — every slot spoken for by resident or
                    already-queued work — bounces to p2c even on a
                    match, so affinity can never starve a request
                    behind one hot replica while others sit idle.

Load score: ``len(queue) + len(pending) + occupied slots`` — everything
the replica has accepted but not finished, the signal a front-end can
actually observe without touching the engine's measured loop.

Replayability note: p2c consumes exactly two draws per routed request
(none when only one replica is active), round_robin consumes zero, and
prefix_affinity consumes two only on its fallback path.  Routing is
timing-sensitive by design — live load scores ARE the policy — so the
locked determinism tests use plans whose arrivals all land at t=0: the
whole batch routes before any engine step, and the router-visible state
evolves identically run over run.
"""
from __future__ import annotations

from dlnetbench_tpu.serving.arrivals import _Rng

ROUTING_POLICIES = ("round_robin", "p2c", "prefix_affinity")


class Router:
    """Seeded request->replica router over ``num_replicas`` queues.

    The fleet driver calls ``pick`` once per routed request with the
    CURRENT engine list and active index set; the router returns a
    replica index and keeps its own provenance: the full assignment
    log, per-replica counts, the chosen-replica load-score samples
    (the fleet block's load histogram), and the affinity accounting
    (hits, bounces, migration-free prefix tokens reused)."""

    def __init__(self, policy: str, num_replicas: int, *, seed: int = 0):
        if policy not in ROUTING_POLICIES:
            raise ValueError(f"router: unknown policy {policy!r} "
                             f"(one of {ROUTING_POLICIES})")
        if num_replicas < 1:
            raise ValueError(f"router: num_replicas must be >= 1, got "
                             f"{num_replicas}")
        self.policy = policy
        self.num_replicas = num_replicas
        self.seed = seed
        self.reset()

    def reset(self) -> None:
        """Back to the initial state — fresh RNG stream, empty log.
        The fleet warmup drives synthetic requests through the SAME
        router; the measured run must start from the seeded origin or
        the warmup count would shift every measured draw."""
        self._rng = _Rng(self.seed)
        self._rr_next = 0
        self.assignments: list[tuple[int, int]] = []   # (rid, replica)
        self.counts = [0] * self.num_replicas
        self.load_samples: list[int] = []  # chosen replica's score
        self.affinity_hits = 0
        self.affinity_bounces = 0
        self.prefix_reuse_tokens = 0

    # ---- the load signal ---------------------------------------------
    @staticmethod
    def load_score(engine) -> int:
        """Accepted-but-unfinished work: routed-not-yet-admitted queue,
        pending (due, waiting for a slot), and occupied slots."""
        return (len(engine.queue) + len(engine.pending)
                + sum(1 for s in engine.slots if s is not None))

    @staticmethod
    def _is_full(engine) -> bool:
        """Every slot spoken for by resident or queued work — the
        affinity bounce condition (routing here queues the request
        behind a hot replica; p2c spreads it instead)."""
        return Router.load_score(engine) >= engine.cfg.slots

    # ---- policies ----------------------------------------------------
    def _round_robin(self, active: list[int]) -> int:
        active_set = set(active)
        for _ in range(self.num_replicas):
            r = self._rr_next % self.num_replicas
            self._rr_next += 1
            if r in active_set:
                return r
        raise RuntimeError("router: no active replica")  # caller's bug

    def _p2c(self, active: list[int], engines) -> int:
        if len(active) == 1:
            return active[0]
        n = len(active)
        i = self._rng.uniform_int(0, n - 1)
        j = self._rng.uniform_int(0, n - 2)
        if j >= i:
            j += 1  # second draw over the OTHER n-1 replicas
        a, b = active[i], active[j]
        # strict <: the first draw wins ties, so the stream alone
        # determines the pick when scores agree
        return b if self.load_score(engines[b]) \
            < self.load_score(engines[a]) else a

    def _prefix_affinity(self, active: list[int], engines,
                         prompt_tokens) -> int:
        best, best_len = None, 0
        for r in active:
            m = engines[r].cache.prefix_match_len(prompt_tokens)
            if m > best_len:
                best, best_len = r, m
        if best is None:
            # no replica holds any of this prompt — a tie, not a
            # bounce: fall through to p2c placement
            return self._p2c(active, engines)
        if self._is_full(engines[best]):
            self.affinity_bounces += 1
            return self._p2c(active, engines)
        self.affinity_hits += 1
        self.prefix_reuse_tokens += best_len
        return best

    # ---- the decision ------------------------------------------------
    def pick(self, req, engines, active: list[int], *,
             prompt_tokens=None) -> int:
        """Route one request; returns the chosen replica's GLOBAL
        index.  ``active`` lists the currently-live replica indices in
        ascending order; ``engines[r]`` must be live for every r in
        ``active``.  ``prompt_tokens`` feeds the affinity probe (only
        consulted under prefix_affinity)."""
        if not active:
            raise RuntimeError("router: no active replica to route to")
        if self.policy == "round_robin":
            r = self._round_robin(active)
        elif self.policy == "p2c":
            r = self._p2c(active, engines)
        else:
            r = self._prefix_affinity(active, engines, prompt_tokens)
        self.assignments.append((req.rid, r))
        self.counts[r] += 1
        self.load_samples.append(self.load_score(engines[r]))
        return r

    # ---- record assembly ---------------------------------------------
    def load_histogram(self) -> list[int]:
        """Counts of the chosen replica's load score at each routing
        decision, indexed by score — the fleet block's picture of how
        loaded the picked queues were (a good policy keeps the mass at
        low scores)."""
        if not self.load_samples:
            return []
        hist = [0] * (max(self.load_samples) + 1)
        for s in self.load_samples:
            hist[s] += 1
        return hist

    def affinity_hit_rate(self) -> float:
        routed = len(self.assignments)
        return round(self.affinity_hits / routed, 4) if routed else 0.0
