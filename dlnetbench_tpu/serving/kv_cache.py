"""Block-table paged KV cache + the paged-attention decode path.

The cache is split along the host/device line the way a real serving
engine splits it:

* ``PagedKVCache`` (host) — the page allocator: a free list over
  ``num_pages`` physical pages, per-slot block tables mapping logical
  token positions to (page, slot-in-page), allocate/append/free, and
  occupancy/fragmentation stats.  Pure numpy bookkeeping; nothing here
  touches a device.
* device page buffers — ``k_pages``/``v_pages`` arrays of shape
  ``[layers, kv_heads, num_pages, page_size, head_dim]`` (the layout
  the Pallas TPU ``paged_attention`` kernel consumes per layer),
  created by ``device_buffers`` and threaded FUNCTIONALLY through the
  compiled decode/prefill programs (serving/decode.py) — the engine
  rebinds them from program outputs, the executor donates them.

``paged_attention_decode`` dispatches the per-layer decode attention:
the Pallas ``jax.experimental.pallas.ops.tpu.paged_attention`` kernel
on a TPU backend, and a dense gather-attention fallback (gather the
sequence's pages into a contiguous [T, d] view, mask by length) on the
CPU mesh — the same backend split ``ops/pallas_common.interpret_mode``
gates every kernel in ops/ on, so the whole serving tier is
unit-testable on a laptop.  ``sharded_paged_attention`` wraps either
impl in ``shard_map`` sharded along GQA KV heads (the SNIPPETS.md [3]
recipe): KV pages are partitioned by head, query heads follow their
group, and no collective is needed until the output projection.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from dlnetbench_tpu.ops import pallas_common
from dlnetbench_tpu.utils.jax_compat import shard_map

MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)


class CacheOOM(RuntimeError):
    """The free list is empty — the admission-control contract was
    violated (the scheduler must reserve a request's worst-case pages
    at admit time, so a running sequence can always append)."""


@dataclasses.dataclass
class CacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    num_pages: int           # physical pages shared by every slot
    page_size: int           # tokens per page
    max_seqs: int            # decode slots (the block table's rows)
    max_pages_per_seq: int   # block-table width = max seq len / page_size
    dtype: str = "float32"

    @property
    def max_seq_len(self) -> int:
        return self.max_pages_per_seq * self.page_size

    def validate(self) -> "CacheConfig":
        for name in ("num_layers", "num_kv_heads", "head_dim",
                     "num_pages", "page_size", "max_seqs",
                     "max_pages_per_seq"):
            if getattr(self, name) < 1:
                raise ValueError(f"kv cache: {name} must be >= 1")
        return self


class PagedKVCache:
    """Host-side page allocator + block tables (one row per decode
    slot).  Page 0 is a real, allocatable page; block-table padding
    also points at 0 — harmless, every consumer masks by length."""

    def __init__(self, cfg: CacheConfig):
        self.cfg = cfg.validate()
        self._free: list[int] = list(range(cfg.num_pages - 1, -1, -1))
        self.block_tables = np.zeros(
            (cfg.max_seqs, cfg.max_pages_per_seq), np.int32)
        self.lengths = np.zeros((cfg.max_seqs,), np.int32)
        self._pages_of: list[list[int]] = [[] for _ in range(cfg.max_seqs)]
        self.peak_pages_in_use = 0

    # ---- allocator ---------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.cfg.num_pages - len(self._free)

    def can_fit(self, n_tokens: int) -> bool:
        need = -(-n_tokens // self.cfg.page_size)
        return need <= len(self._free)

    def allocate(self, slot: int, n_tokens: int) -> None:
        """Reserve pages for ``n_tokens`` on an empty slot (admission:
        the scheduler reserves prompt+output worst case up front, so
        ``append`` can never OOM mid-sequence)."""
        if self._pages_of[slot]:
            raise ValueError(f"kv cache: slot {slot} already allocated")
        need = -(-n_tokens // self.cfg.page_size)
        if need > self.cfg.max_pages_per_seq:
            raise ValueError(
                f"kv cache: {n_tokens} tokens need {need} pages > "
                f"max_pages_per_seq {self.cfg.max_pages_per_seq}")
        if need > len(self._free):
            raise CacheOOM(
                f"kv cache: need {need} pages, {len(self._free)} free — "
                f"admission control must gate on can_fit()")
        for i in range(need):
            page = self._free.pop()
            self._pages_of[slot].append(page)
            self.block_tables[slot, i] = page
        self.lengths[slot] = 0
        self.peak_pages_in_use = max(self.peak_pages_in_use,
                                     self.pages_in_use)

    def append(self, slot: int, n: int = 1) -> None:
        """Advance the slot's length by ``n`` tokens (the device write
        happened inside the compiled step); grows into the reserved
        pages — exceeding the reservation is a scheduler bug."""
        new_len = int(self.lengths[slot]) + n
        if new_len > len(self._pages_of[slot]) * self.cfg.page_size:
            raise CacheOOM(
                f"kv cache: slot {slot} grew to {new_len} tokens past "
                f"its {len(self._pages_of[slot])}-page reservation")
        self.lengths[slot] = new_len

    def free(self, slot: int) -> None:
        for page in self._pages_of[slot]:
            self._free.append(page)
        self._pages_of[slot] = []
        self.block_tables[slot, :] = 0
        self.lengths[slot] = 0

    # ---- stats (ride the serving record block) -----------------------
    def stats(self) -> dict:
        """Occupancy = fraction of physical pages in use; fragmentation
        = fraction of ALLOCATED token capacity holding no token (the
        cost of page-granular allocation + worst-case reservation)."""
        cap = self.pages_in_use * self.cfg.page_size
        toks = int(self.lengths.sum())
        return {
            "num_pages": self.cfg.num_pages,
            "page_size": self.cfg.page_size,
            "pages_in_use": self.pages_in_use,
            "peak_pages_in_use": self.peak_pages_in_use,
            "occupancy": round(self.pages_in_use / self.cfg.num_pages, 4),
            "peak_occupancy": round(
                self.peak_pages_in_use / self.cfg.num_pages, 4),
            "fragmentation": (round((cap - toks) / cap, 4) if cap else 0.0),
        }


def device_buffers(cfg: CacheConfig) -> tuple[jax.Array, jax.Array]:
    """Zeroed K/V page pools: ``[L, H_kv, num_pages, page_size, Dh]``
    (the Pallas kernel's per-layer layout, stacked over layers)."""
    shape = (cfg.num_layers, cfg.num_kv_heads, cfg.num_pages,
             cfg.page_size, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return jnp.zeros(shape, dt), jnp.zeros(shape, dt)


# ---------------------------------------------------------------------
# decode attention over the page pool


def _gather_attention(q, k_pages, v_pages, lengths, page_indices):
    """Dense fallback: gather each sequence's pages contiguous, mask by
    length, fp32 softmax.  ``q`` arrives PRE-SCALED (both impls share
    the convention; the Pallas kernel applies no sm_scale either).

    q: [B, Hq, Dh]; k/v_pages: [Hkv, P, S, Dh]; lengths: [B] (valid
    tokens incl. the one just written); page_indices: [B, Pmax]."""
    hkv = k_pages.shape[0]
    s = k_pages.shape[2]
    # [Hkv, B, Pmax, S, Dh] -> [B, Hkv, T, Dh]
    k = jnp.moveaxis(k_pages[:, page_indices], 0, 1)
    v = jnp.moveaxis(v_pages[:, page_indices], 0, 1)
    b, _, pmax, _, dh = k.shape
    k = k.reshape(b, hkv, pmax * s, dh)
    v = v.reshape(b, hkv, pmax * s, dh)
    g = q.shape[1] // hkv
    qg = q.reshape(b, hkv, g, dh).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bhtd->bhgt", qg, k.astype(jnp.float32))
    mask = jnp.arange(pmax * s)[None, :] < lengths[:, None]  # [B, T]
    scores = jnp.where(mask[:, None, None, :], scores, MASK_VALUE)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgt,bhtd->bhgd", p, v.astype(jnp.float32))
    return out.reshape(b, hkv * g, dh).astype(q.dtype)


def resolve_pages_per_compute_block(q, k_pages, page_indices,
                                    pages_per_compute_block: int | None
                                    ) -> int:
    """The Pallas kernel's ``pages_per_compute_block`` knob: an
    EXPLICIT value always wins and must divide the per-sequence page
    count exactly (an experiment knob fails loud — a silently adjusted
    block would record a time for a config nobody asked for); ``None``
    consults the tuning DB (dlnetbench_tpu/tuning, keyed per cache
    geometry x chip) and falls back to the historical default
    ``fit_block(pages_per_seq, min(pages_per_seq, 8))`` bit-identically
    on a miss (ISSUE 9 satellite — this replaces the old inline
    hard-code)."""
    pages_per_seq = page_indices.shape[1]
    if pages_per_compute_block is not None:
        p = pages_per_compute_block
        if not isinstance(p, int) or p < 1 or pages_per_seq % p:
            raise ValueError(
                f"paged_attention: pages_per_compute_block={p!r} does "
                f"not divide pages_per_seq {pages_per_seq}")
        return p
    default = pallas_common.fit_block(pages_per_seq,
                                      min(pages_per_seq, 8))
    from dlnetbench_tpu import tuning

    def check(cfg: dict) -> None:
        p = cfg.get("pages_per_compute_block")
        if not isinstance(p, int) or p < 1 or pages_per_seq % p:
            raise ValueError(
                f"pages_per_compute_block={p!r} does not divide "
                f"pages_per_seq {pages_per_seq}")
    b, hq, dh = q.shape
    hkv, _, page_size, _ = k_pages.shape
    cfg = tuning.consult(
        "paged_attention",
        tuning.params.paged_attention_key(pages_per_seq, page_size, b,
                                          hq, hkv, dh),
        {"pages_per_compute_block": default}, validate=check)
    return cfg["pages_per_compute_block"]


def paged_attention_decode(q, k_pages, v_pages, lengths, page_indices,
                           *, impl: str = "auto",
                           pages_per_compute_block: int | None = None):
    """One decode step's attention for a batch of slots.  ``impl``:
    ``auto`` picks the Pallas TPU kernel on a TPU backend and the dense
    gather fallback elsewhere (the ``pallas_common`` backend split);
    ``pallas``/``gather`` force a path.  ``q`` must be pre-scaled by
    ``head_dim**-0.5`` — neither impl applies a softmax scale.

    ``pages_per_compute_block`` sizes the Pallas kernel's per-grid-lane
    page block (tuning-consulted when None — see
    ``resolve_pages_per_compute_block``; validated either way).  The
    dense gather fallback computes the mathematically identical full
    attention regardless of blocking, so results are block-invariant by
    construction on both impls (tests/test_serving.py parity)."""
    if impl == "auto":
        impl = "gather" if pallas_common.interpret_mode() else "pallas"
    if impl == "gather":
        if pages_per_compute_block is not None:
            # validate even on the path that ignores it: a bad explicit
            # knob must fail identically on every backend, not only
            # where the Pallas kernel happens to run
            resolve_pages_per_compute_block(q, k_pages, page_indices,
                                            pages_per_compute_block)
        return _gather_attention(q, k_pages, v_pages, lengths,
                                 page_indices)
    if impl != "pallas":
        raise ValueError(f"paged_attention_decode: unknown impl "
                         f"{impl!r} (auto|pallas|gather)")
    from jax.experimental.pallas.ops.tpu.paged_attention import \
        paged_attention
    return paged_attention(
        q, k_pages, v_pages, lengths.astype(jnp.int32),
        page_indices.astype(jnp.int32),
        pages_per_compute_block=resolve_pages_per_compute_block(
            q, k_pages, page_indices, pages_per_compute_block))


def sharded_paged_attention(mesh, axis: str = "kv",
                            impl: str = "auto",
                            pages_per_compute_block: int | None = None):
    """Shard the decode attention along GQA KV heads via ``shard_map``
    (the SNIPPETS.md [3] recipe): KV pages partition by head
    (``P(axis, None, None, None)``), query heads follow their group
    (``P(None, axis, None)``), lengths/block tables replicate.  Each
    shard attends over its own heads only — embarrassingly parallel, no
    collective until the caller's output projection (jit inserts the
    resharding there).  Requires ``num_kv_heads % axis_size == 0``."""
    from jax.sharding import PartitionSpec as P

    def fn(q, k_pages, v_pages, lengths, page_indices):
        return paged_attention_decode(
            q, k_pages, v_pages, lengths, page_indices, impl=impl,
            pages_per_compute_block=pages_per_compute_block)

    return shard_map(
        fn, mesh=mesh,
        in_specs=(P(None, axis, None), P(axis, None, None, None),
                  P(axis, None, None, None), P(), P()),
        out_specs=P(None, axis, None),
        check_rep=False)
