"""Block-table paged KV cache + the paged-attention decode path.

The cache is split along the host/device line the way a real serving
engine splits it:

* ``PagedKVCache`` (host) — the page allocator: a free list over
  ``num_pages`` physical pages, per-slot block tables mapping logical
  token positions to (page, slot-in-page), allocate/append/free, and
  occupancy/fragmentation stats.  Pure numpy bookkeeping; nothing here
  touches a device.  ISSUE 12 grows it two serving-density levers:

  - **refcounts + prefix sharing**: every physical page carries a
    refcount, and a radix-style trie over prompt token ids
    (``_PrefixTrie``) lets a new request whose prompt shares a prefix
    with a RESIDENT, fully-prefilled sequence map its block-table
    entries onto the same physical pages.  Fully-covered pages are
    shared by reference (admission charges only the UNSHARED pages);
    a partially-covered boundary page is shared copy-on-write — the
    sequence will write into it (remaining prompt or its first decode
    token), so admission resolves the COW eagerly: a private page is
    charged to the reservation and the engine copies the prefix rows
    device-side.  ``append`` asserts it never grows into a page with
    refcount > 1 — block tables must never alias a written page.
  - **quantized pools**: ``CacheConfig.cache_dtype`` selects int8 or
    fp8(e4m3) page pools with a per-page-per-head f32 scale array
    beside each pool (``[L, Hkv, num_pages]``) — ~2x the pages per
    pool byte of a bf16 cache, ~4x of an f32 one.  ``"bf16"`` (the
    default label for the UNQUANTIZED cache — pools stay in the
    model's compute dtype) builds none of the quant machinery, so the
    dense path is bit-identical to the pre-ISSUE-12 cache.

* device page buffers — ``k_pages``/``v_pages`` arrays of shape
  ``[layers, kv_heads, num_pages, page_size, head_dim]`` (the layout
  the Pallas TPU ``paged_attention`` kernel consumes per layer),
  created by ``device_buffers`` and threaded FUNCTIONALLY through the
  compiled decode/prefill programs (serving/decode.py) — the engine
  rebinds them from program outputs, the executor donates them.
  Quantized configs add ``k_scale``/``v_scale`` arrays riding the same
  functional thread (written beside every page write, donated carries
  of the fused loop like the pools themselves).

Quantized cache writes go through ``quant_write_span``: the touched
page is re-quantized against a FRESH amax over its valid rows (masked
to the sequence's own content, so page reuse can never inherit a stale
scale), sharing ``scale_from_amax``/``quantize_tensor``'s ``_cast_q``
definitions with ops/quantized_matmul.py — one spelling of the scale
math across the repo's quant recipes.

``paged_attention_decode`` dispatches the per-layer decode attention:
the Pallas ``jax.experimental.pallas.ops.tpu.paged_attention`` kernel
on a TPU backend, and a dense gather-attention fallback (gather the
sequence's pages into a contiguous [T, d] view, mask by length) on the
CPU mesh — the same backend split ``ops/pallas_common.interpret_mode``
gates every kernel in ops/ on, so the whole serving tier is
unit-testable on a laptop.  With scale arrays the dispatch routes to
``ops/paged_attention_quant.quant_paged_attention`` (pages gathered
QUANTIZED — int8/fp8 through HBM, never round-tripped as bf16 — and
dequantized in the kernel's VMEM prologue against the prefetched
scales) or a dequantizing gather fallback off-TPU.
``sharded_paged_attention`` wraps either impl in ``shard_map`` sharded
along GQA KV heads (the SNIPPETS.md [3] recipe): KV pages are
partitioned by head, query heads follow their group, and no collective
is needed until the output projection.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from dlnetbench_tpu.ops import pallas_common
from dlnetbench_tpu.utils.jax_compat import shard_map

MASK_VALUE = -0.7 * float(np.finfo(np.float32).max)

_F32 = jnp.float32

# cache dtypes: "bf16" labels the UNQUANTIZED pool (stored in the
# model's own compute dtype — float32 on the CPU mesh, bf16 on chip),
# where none of the quant machinery is even built.  The quantized
# formats map onto ops/quantized_matmul's recipe table, so the scale
# definitions (and the int8/fp8 tolerance story) are shared.
CACHE_DTYPES = ("bf16", "int8", "fp8")
_QUANT_FMT = {"int8": "int8", "fp8": "float8"}
_QUANT_JNP = {"int8": jnp.int8, "fp8": jnp.float8_e4m3fn}

# stated decode-parity tolerance bars, per recipe (ISSUE 12): max
# absolute error of the paged-attention output vs the bf16 cache on
# unit-scale activations.  int8 carries ~1/254 per-element rounding
# plus bounded fresh-amax requant drift; fp8(e4m3) carries ~6%
# relative per element, softmax-averaged down.  Tests, the bench
# kv_density_ab line and the committed study all enforce THESE bars —
# one spelling of the tolerance story (observed on the CPU mesh:
# int8 ~0.01, fp8 ~0.08).
QUANT_DECODE_TOL = {"int8": 0.05, "fp8": 0.15}


class CacheOOM(RuntimeError):
    """The free list is empty — the admission-control contract was
    violated (the scheduler must reserve a request's worst-case pages
    at admit time, so a running sequence can always append)."""


@dataclasses.dataclass
class CacheConfig:
    num_layers: int
    num_kv_heads: int
    head_dim: int
    num_pages: int           # physical pages shared by every slot
    page_size: int           # tokens per page
    max_seqs: int            # decode slots (the block table's rows)
    max_pages_per_seq: int   # block-table width = max seq len / page_size
    dtype: str = "float32"
    cache_dtype: str = "bf16"   # "bf16" (unquantized, pools in `dtype`)
    #                             | "int8" | "fp8" (e4m3) — quantized
    #                             pools + per-page-per-head f32 scales

    @property
    def max_seq_len(self) -> int:
        return self.max_pages_per_seq * self.page_size

    @property
    def quantized(self) -> bool:
        return self.cache_dtype != "bf16"

    @property
    def quant_fmt(self) -> str | None:
        """The ops/quantized_matmul format name, or None when dense."""
        return _QUANT_FMT.get(self.cache_dtype)

    @property
    def pool_jnp_dtype(self):
        return (_QUANT_JNP[self.cache_dtype] if self.quantized
                else jnp.dtype(self.dtype))

    # ---- pool-bytes accounting (the honest "same pool bytes" axis of
    # the density A/B: scale arrays COUNT — a quantized pool that got
    # its scales for free would overstate the capacity win)
    @property
    def page_bytes(self) -> int:
        """Device bytes ONE physical page costs across both pools:
        k+v payload rows plus, when quantized, the per-page-per-head
        scale entries."""
        payload = (2 * self.num_layers * self.num_kv_heads
                   * self.page_size * self.head_dim
                   * jnp.dtype(self.pool_jnp_dtype).itemsize)
        scales = (2 * self.num_layers * self.num_kv_heads * 4
                  if self.quantized else 0)
        return payload + scales

    @property
    def pool_bytes(self) -> int:
        """Total device bytes of the paged cache (pools + scales)."""
        return self.num_pages * self.page_bytes

    def validate(self) -> "CacheConfig":
        for name in ("num_layers", "num_kv_heads", "head_dim",
                     "num_pages", "page_size", "max_seqs",
                     "max_pages_per_seq"):
            if getattr(self, name) < 1:
                raise ValueError(f"kv cache: {name} must be >= 1")
        if self.cache_dtype not in CACHE_DTYPES:
            raise ValueError(
                f"kv cache: unknown cache_dtype {self.cache_dtype!r} "
                f"(one of {CACHE_DTYPES})")
        # the loud-refusal guard, extended to every cache dtype
        # (ISSUE 12 satellite): a pool that cannot hold even ONE
        # max-seq-len request starves the admission gate forever —
        # quantized configs hit this exactly like dense ones when a
        # byte budget (scale arrays included) converts to too few pages
        if self.num_pages < self.max_pages_per_seq:
            raise ValueError(
                f"kv cache: num_pages {self.num_pages} cannot hold even "
                f"one max-seq request ({self.max_pages_per_seq} pages "
                f"at cache_dtype={self.cache_dtype}, "
                f"{self.page_bytes} B/page incl. scales) — the "
                f"admission gate would starve the queue head forever")
        return self


def pages_for_pool_bytes(budget_bytes: int, cfg: CacheConfig) -> int:
    """How many physical pages a byte budget buys under ``cfg``'s
    geometry and cache dtype — the equal-pool-bytes axis of the density
    A/B (scale arrays priced in via ``page_bytes``).  The returned
    count still has to pass ``validate``'s one-request guard; a budget
    too small for that fails THERE, loudly."""
    if budget_bytes < 1:
        raise ValueError(f"pages_for_pool_bytes: budget {budget_bytes}")
    return max(1, budget_bytes // cfg.page_bytes)


# ---------------------------------------------------------------------
# prefix trie (host): prompt token ids -> resident physical pages


class _TrieNode:
    """One cached page's worth of prompt tokens.  ``key`` is the token
    tuple the page holds (length == page_size for interior nodes; a
    shorter tuple is a partial boundary page, shareable copy-on-write
    up to its length).  Children may overlap in prefix (a loose radix:
    lookup scans the few children of a node for the best match)."""

    __slots__ = ("key", "page", "parent", "children")

    def __init__(self, key: tuple, page: int, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: dict[tuple, _TrieNode] = {}


class _PrefixTrie:
    """Radix-style trie over PUBLISHED prompt pages.  Non-owning: a
    node exists exactly while its physical page has readers (the
    allocator removes the node when the refcount hits zero), so a
    lookup can never hand out a freed page."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.root = _TrieNode((), -1, None)
        self._node_of_page: dict[int, _TrieNode] = {}

    def match(self, tokens) -> tuple[int, list[int], int | None]:
        """Longest shared prefix of ``tokens`` against the published
        pages: ``(shared_tokens, full_page_ids, partial_page_id)``.
        ``full_page_ids`` are the fully-covered physical pages (in
        column order); ``partial_page_id`` is the boundary page whose
        first ``shared_tokens % page_size`` rows match (None when the
        match is page-aligned)."""
        toks = tuple(int(t) for t in tokens)
        node, pos, full = self.root, 0, []
        s = self.page_size
        while len(toks) - pos >= s:
            child = node.children.get(toks[pos:pos + s])
            if child is None:
                break
            full.append(child.page)
            node = child
            pos += s
        # partial boundary: the child sharing the longest token-level
        # prefix with the remaining tokens (>= 1 token to be worth a
        # copy-on-write share)
        rest = toks[pos:]
        best_len, best_page = 0, None
        for key, child in node.children.items():
            n = 0
            for a, b in zip(key, rest):
                if a != b:
                    break
                n += 1
            if n > best_len:
                best_len, best_page = n, child.page
        if best_len > 0:
            return pos + best_len, full, best_page
        return pos, full, None

    def publish(self, tokens, pages: list[int]) -> None:
        """Register a fully-prefilled prompt's pages.  Idempotent: a
        path already present (the publisher shared it) is left alone —
        first publisher wins, content is identical by construction."""
        toks = tuple(int(t) for t in tokens)
        s = self.page_size
        node, pos, col = self.root, 0, 0
        while pos < len(toks):
            key = toks[pos:pos + s]
            child = node.children.get(key)
            if child is None:
                page = pages[col]
                if page in self._node_of_page:
                    # this physical page already backs another path
                    # node (a shared page republished under a longer
                    # prompt): never double-register
                    child = self._node_of_page[page]
                    if child.key != key:
                        break
                else:
                    child = _TrieNode(key, page, node)
                    node.children[key] = child
                    self._node_of_page[page] = child
            node = child
            pos += len(key)
            col += 1
            if len(key) < s:      # partial tail published; path ends
                break

    def drop_page(self, page: int) -> None:
        """The page's refcount hit zero: unlink its node.  Holders of a
        child page always hold the parent too (they matched the whole
        path), so a dying node can have no live children."""
        node = self._node_of_page.pop(page, None)
        if node is not None and node.parent is not None:
            node.parent.children.pop(node.key, None)


@dataclasses.dataclass
class AdmissionPlan:
    """What admitting one request costs and shares (``plan_admission``
    -> ``admit``): the UNSHARED page charge, the matched prefix, and
    the eager copy-on-write source for a partially-shared boundary
    page (the engine performs the device copy)."""
    n_tokens: int
    need_pages: int
    shared_tokens: int = 0
    shared_pages: list = dataclasses.field(default_factory=list)
    cow_src: int | None = None       # physical page to copy from
    cow_rows: int = 0                # valid prefix rows in cow_src


class PagedKVCache:
    """Host-side page allocator + block tables (one row per decode
    slot).  Page 0 is a real, allocatable page; block-table padding
    also points at 0 — harmless, every consumer masks by length.

    Every physical page carries a refcount; prefix sharing maps one
    page into several block tables and a page returns to the free list
    exactly when its LAST reader frees it.  ``append`` refuses to grow
    into a page with refcount > 1 (a shared page is read-only; writes
    land only after the admission-time copy-on-write)."""

    def __init__(self, cfg: CacheConfig):
        self.cfg = cfg.validate()
        self._free: list[int] = list(range(cfg.num_pages - 1, -1, -1))
        self._ref = np.zeros((cfg.num_pages,), np.int32)
        self.block_tables = np.zeros(
            (cfg.max_seqs, cfg.max_pages_per_seq), np.int32)
        self.lengths = np.zeros((cfg.max_seqs,), np.int32)
        self._pages_of: list[list[int]] = [[] for _ in range(cfg.max_seqs)]
        self.peak_pages_in_use = 0
        self.trie = _PrefixTrie(cfg.page_size)
        # prefix-sharing stats (ride the record via stats())
        self.admissions = 0
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_shared_tokens = 0
        self.prefix_pages_shared = 0
        self.prefix_bytes_saved = 0
        self.cow_copies = 0

    # ---- allocator ---------------------------------------------------
    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.cfg.num_pages - len(self._free)

    def can_fit(self, n_tokens: int) -> bool:
        need = -(-n_tokens // self.cfg.page_size)
        return need <= len(self._free)

    def prefix_match_len(self, prompt_tokens) -> int:
        """Read-only trie probe (ISSUE 18): how many of this prompt's
        leading tokens are already RESIDENT in this pool's published
        pages.  The fleet router's prefix-affinity placement signal —
        the replica with the longest match serves the prompt with the
        fewest prefill chunks and zero cross-replica page motion.
        Capped at ``prompt_len - 1`` exactly like ``plan_admission``
        (the final prompt token always re-prefills), and deliberately
        NOT counted in ``prefix_lookups``/hit stats: a routing probe
        across N replicas is not an admission and must not dilute the
        per-pool hit rate the density study reports."""
        if prompt_tokens is None or len(prompt_tokens) < 2:
            return 0
        matched, _full, _partial = self.trie.match(
            np.asarray(prompt_tokens)[: len(prompt_tokens) - 1])
        return int(matched)

    def plan_admission(self, n_tokens: int,
                       prompt_tokens=None) -> AdmissionPlan:
        """Price one admission.  With ``prompt_tokens`` (prefix sharing
        on) the trie is consulted: fully-matched pages are shared by
        reference and the charge covers only the unshared pages — the
        partially-matched boundary page is charged (its copy-on-write
        private copy) but its prefix rows skip prefill.  The match is
        capped at ``prompt_len - 1``: the final prompt token always
        re-prefills, because its forward pass produces the request's
        FIRST generated token (the TTFT stamp)."""
        s = self.cfg.page_size
        total = -(-n_tokens // s)
        if total > self.cfg.max_pages_per_seq:
            raise ValueError(
                f"kv cache: {n_tokens} tokens need {total} pages > "
                f"max_pages_per_seq {self.cfg.max_pages_per_seq}")
        if prompt_tokens is None or len(prompt_tokens) < 2:
            return AdmissionPlan(n_tokens=n_tokens, need_pages=total)
        self.prefix_lookups += 1
        matched, full_pages, partial_page = self.trie.match(
            np.asarray(prompt_tokens)[: len(prompt_tokens) - 1])
        full = len(full_pages)
        partial = matched - full * s
        if partial <= 0:
            partial_page = None
            matched = full * s
        return AdmissionPlan(
            n_tokens=n_tokens, need_pages=total - full,
            shared_tokens=matched, shared_pages=list(full_pages),
            cow_src=partial_page, cow_rows=partial)

    def admit(self, slot: int, plan: AdmissionPlan) -> int | None:
        """Execute an admission plan on an empty slot: shared pages by
        reference (refcount bump), the rest freshly allocated — the
        boundary-page private copy included.  Returns the physical COW
        DESTINATION page when the plan carries one (the engine copies
        ``plan.cow_src``'s rows into it device-side) or None.
        ``lengths[slot]`` starts at ``shared_tokens`` — that content is
        already cached."""
        if self._pages_of[slot]:
            raise ValueError(f"kv cache: slot {slot} already allocated")
        if plan.need_pages > len(self._free):
            raise CacheOOM(
                f"kv cache: need {plan.need_pages} pages, "
                f"{len(self._free)} free — admission control must gate "
                f"on the plan (can_fit() for the no-sharing path)")
        total = -(-plan.n_tokens // self.cfg.page_size)
        self.admissions += 1
        cow_dst = None
        for i in range(total):
            if i < len(plan.shared_pages):
                page = plan.shared_pages[i]
                self._ref[page] += 1
            else:
                page = self._free.pop()
                self._ref[page] = 1
                if i == len(plan.shared_pages) and plan.cow_src is not None:
                    cow_dst = page
            self._pages_of[slot].append(page)
            self.block_tables[slot, i] = page
        self.lengths[slot] = plan.shared_tokens
        if plan.shared_tokens > 0:
            self.prefix_hits += 1
            self.prefix_shared_tokens += plan.shared_tokens
            self.prefix_pages_shared += len(plan.shared_pages)
            self.prefix_bytes_saved += (len(plan.shared_pages)
                                        * self.cfg.page_bytes)
        if cow_dst is not None:
            self.cow_copies += 1
        self.peak_pages_in_use = max(self.peak_pages_in_use,
                                     self.pages_in_use)
        return cow_dst

    def allocate(self, slot: int, n_tokens: int) -> None:
        """Reserve pages for ``n_tokens`` on an empty slot (admission:
        the scheduler reserves prompt+output worst case up front, so
        ``append`` can never OOM mid-sequence).  The no-sharing path —
        ``plan_admission``/``admit`` with no prompt tokens."""
        self.admit(slot, self.plan_admission(n_tokens))

    def append(self, slot: int, n: int = 1) -> None:
        """Advance the slot's length by ``n`` tokens (the device write
        happened inside the compiled step); grows into the reserved
        pages — exceeding the reservation is a scheduler bug, and so is
        writing into a page another sequence still reads (COW must have
        replaced it at admission)."""
        s = self.cfg.page_size
        old_len = int(self.lengths[slot])
        new_len = old_len + n
        if new_len > len(self._pages_of[slot]) * s:
            raise CacheOOM(
                f"kv cache: slot {slot} grew to {new_len} tokens past "
                f"its {len(self._pages_of[slot])}-page reservation")
        for col in range(old_len // s, (new_len - 1) // s + 1):
            page = self._pages_of[slot][col]
            if self._ref[page] > 1:
                raise RuntimeError(
                    f"kv cache: slot {slot} wrote into shared page "
                    f"{page} (refcount {int(self._ref[page])}) — a "
                    f"shared page is read-only; copy-on-write must "
                    f"have replaced it at admission")
        self.lengths[slot] = new_len

    def free(self, slot: int) -> None:
        for page in self._pages_of[slot]:
            self._ref[page] -= 1
            if self._ref[page] == 0:
                self._free.append(page)
                self.trie.drop_page(page)
            elif self._ref[page] < 0:  # pragma: no cover - invariant
                raise RuntimeError(
                    f"kv cache: page {page} refcount went negative")
        self._pages_of[slot] = []
        self.block_tables[slot, :] = 0
        self.lengths[slot] = 0

    def publish(self, slot: int, prompt_tokens) -> None:
        """Register the slot's fully-prefilled PROMPT pages in the
        trie so later arrivals can share them.  Only the prompt is
        published — generated tokens are request-specific."""
        toks = np.asarray(prompt_tokens)
        n = -(-len(toks) // self.cfg.page_size)
        self.trie.publish(toks, self._pages_of[slot][:n])

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    # ---- stats (ride the serving record block) -----------------------
    def stats(self) -> dict:
        """Occupancy = fraction of physical pages in use; fragmentation
        = fraction of ALLOCATED token capacity holding no token (the
        cost of page-granular allocation + worst-case reservation;
        shared pages count once).  Prefix-sharing counters ride along
        whenever a lookup happened."""
        cap = self.pages_in_use * self.cfg.page_size
        toks = int(self.lengths.sum())
        out = {
            "num_pages": self.cfg.num_pages,
            "page_size": self.cfg.page_size,
            "cache_dtype": self.cfg.cache_dtype,
            "pool_bytes": self.cfg.pool_bytes,
            "pages_in_use": self.pages_in_use,
            "peak_pages_in_use": self.peak_pages_in_use,
            "admissions": self.admissions,
            "occupancy": round(self.pages_in_use / self.cfg.num_pages, 4),
            "peak_occupancy": round(
                self.peak_pages_in_use / self.cfg.num_pages, 4),
            "fragmentation": (round(max(cap - toks, 0) / cap, 4)
                              if cap else 0.0),
        }
        if self.prefix_lookups:
            out["prefix"] = {
                "lookups": self.prefix_lookups,
                "hits": self.prefix_hits,
                # per ADMISSION, not per lookup: a blocked queue
                # head is re-planned every engine iteration and must
                # not dilute the rate
                "hit_rate": round(self.prefix_hits
                                  / max(self.admissions, 1), 4),
                "shared_tokens": self.prefix_shared_tokens,
                "pages_shared": self.prefix_pages_shared,
                "bytes_saved": self.prefix_bytes_saved,
                "cow_copies": self.cow_copies,
            }
        return out


def device_buffers(cfg: CacheConfig):
    """Zeroed K/V page pools: ``[L, H_kv, num_pages, page_size, Dh]``
    (the Pallas kernel's per-layer layout, stacked over layers).
    Dense configs return ``(k, v)`` exactly as before ISSUE 12;
    quantized configs return ``(k, v, k_scale, v_scale)`` with the
    per-page-per-head f32 scale arrays (``[L, H_kv, num_pages]``,
    initialized to 1.0 — a zeroed page dequantizes to zeros)."""
    shape = (cfg.num_layers, cfg.num_kv_heads, cfg.num_pages,
             cfg.page_size, cfg.head_dim)
    dt = cfg.pool_jnp_dtype
    k, v = jnp.zeros(shape, dt), jnp.zeros(shape, dt)
    if not cfg.quantized:
        return k, v
    sshape = shape[:3]
    return k, v, jnp.ones(sshape, _F32), jnp.ones(sshape, _F32)


# ---------------------------------------------------------------------
# quantized page writes (the decode/prefill cache-write epilogue)


def quant_write_span(pages, scales, li: int, new, positions, write_ok,
                     block_tables, *, fmt: str, page_size: int,
                     num_pages: int):
    """Write a span of tokens into a QUANTIZED page pool: token ``j``
    of slot ``b`` lands at position ``positions[b] + j`` (gated by
    ``write_ok[b, j]``), and every touched page is re-quantized against
    a FRESH amax over its valid rows — the already-cached prefix
    (dequantized at the old scale) plus the new rows, masked to the
    sequence's own content so a reused page can never inherit garbage
    into its scale.  Shares ``scale_from_amax``/``_cast_q`` with
    ops/quantized_matmul.py (the PR-3 recipes — one scale spelling).

    pages: ``[L, Hkv, P, S, Dh]`` quantized; scales: ``[L, Hkv, P]``
    f32; new: ``[B, K, Hkv, Dh]`` master dtype; positions: ``[B]``;
    write_ok: ``[B, K]``; block_tables: ``[B, pmax]``.  Returns the
    updated ``(pages, scales)``.  Slots (or whole page columns) with
    no enabled write scatter out-of-bounds and drop — an inactive
    slot's stale block table is never touched."""
    from dlnetbench_tpu.ops.quantized_matmul import (_cast_q,
                                                     scale_from_amax)
    b, k1 = write_ok.shape
    s = page_size
    pmax = block_tables.shape[1]
    j_idx = jnp.arange(k1, dtype=jnp.int32)
    tok_pos = positions[:, None] + j_idx[None, :]            # [B, K]
    tok_col = tok_pos // s
    row_of_tok = tok_pos % s
    rows = jnp.arange(s, dtype=jnp.int32)
    # static bound on distinct page columns one span can touch
    ncols = (k1 + s - 2) // s + 1
    for c in range(ncols):
        col = positions // s + c                             # [B]
        in_col = (tok_col == col[:, None]) & write_ok        # [B, K]
        any_w = jnp.any(in_col, axis=1)                      # [B]
        page = jnp.take_along_axis(
            block_tables, jnp.clip(col, 0, pmax - 1)[:, None],
            axis=1)[:, 0]                                    # [B]
        w_page = jnp.where(any_w, page, num_pages)           # OOB drop
        pc = jnp.minimum(page, num_pages - 1)                # gather ok
        oldq = pages[li][:, pc]                              # [H,B,S,D]
        olds = scales[li][:, pc]                             # [H,B]
        deq = oldq.astype(_F32) * olds[:, :, None, None]
        old_valid = rows[None, :] < jnp.clip(
            positions - col * s, 0, s)[:, None]              # [B, S]
        base = jnp.where(old_valid[None, :, :, None], deq, 0.0)
        onehot = (in_col[:, :, None]
                  & (rows[None, None, :] == row_of_tok[:, :, None]))
        new_rows = jnp.einsum("bks,bkhd->hbsd",
                              onehot.astype(_F32), new.astype(_F32))
        new_mask = jnp.any(onehot, axis=1)                   # [B, S]
        pagef = jnp.where(new_mask[None, :, :, None], new_rows, base)
        amax = jnp.max(jnp.abs(pagef), axis=(2, 3))          # [H, B]
        scale = scale_from_amax(amax, fmt)
        q = _cast_q(pagef / scale[:, :, None, None], fmt)
        # jax scatter puts advanced-index dims FIRST: the slice shape
        # of ``[li, :, w_page]`` is [B, Hkv, S, Dh], so the head-major
        # page tile transposes on the way in (a silent wrong-data
        # broadcast when B == Hkv — caught by the parity tests)
        pages = pages.at[li, :, w_page].set(
            jnp.swapaxes(q, 0, 1), mode="drop")
        scales = scales.at[li, :, w_page].set(scale.T, mode="drop")
    return pages, scales


def dequant_gathered(pages_g, scales_g):
    """Gathered quantized pages -> f32: ``pages_g`` [..., pages, S, Dh]
    times the matching [..., pages] scales (broadcast over rows)."""
    return pages_g.astype(_F32) * scales_g[..., None, None]


# ---------------------------------------------------------------------
# decode attention over the page pool


def _gather_attention(q, k_pages, v_pages, lengths, page_indices,
                      k_scale=None, v_scale=None):
    """Dense fallback: gather each sequence's pages contiguous, mask by
    length, fp32 softmax.  ``q`` arrives PRE-SCALED (both impls share
    the convention; the Pallas kernel applies no sm_scale either).
    With scale arrays the gathered pages are dequantized first — the
    CPU-mesh form of the quantized decode path.

    q: [B, Hq, Dh]; k/v_pages: [Hkv, P, S, Dh]; lengths: [B] (valid
    tokens incl. the one just written); page_indices: [B, Pmax];
    k/v_scale: [Hkv, P] f32 or None."""
    hkv = k_pages.shape[0]
    s = k_pages.shape[2]
    # [Hkv, B, Pmax, S, Dh] -> [B, Hkv, Pmax, S, Dh]
    k = jnp.moveaxis(k_pages[:, page_indices], 0, 1).astype(jnp.float32)
    v = jnp.moveaxis(v_pages[:, page_indices], 0, 1).astype(jnp.float32)
    if k_scale is not None:
        k = k * jnp.moveaxis(k_scale[:, page_indices], 0, 1)[..., None,
                                                             None]
        v = v * jnp.moveaxis(v_scale[:, page_indices], 0, 1)[..., None,
                                                             None]
    b, _, pmax, _, dh = k.shape
    k = k.reshape(b, hkv, pmax * s, dh)
    v = v.reshape(b, hkv, pmax * s, dh)
    g = q.shape[1] // hkv
    qg = q.reshape(b, hkv, g, dh).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bhtd->bhgt", qg, k)
    mask = jnp.arange(pmax * s)[None, :] < lengths[:, None]  # [B, T]
    scores = jnp.where(mask[:, None, None, :], scores, MASK_VALUE)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgt,bhtd->bhgd", p, v)
    return out.reshape(b, hkv * g, dh).astype(q.dtype)


def resolve_pages_per_compute_block(q, k_pages, page_indices,
                                    pages_per_compute_block: int | None,
                                    fmt: str | None = None) -> int:
    """The Pallas kernel's ``pages_per_compute_block`` knob: an
    EXPLICIT value always wins and must divide the per-sequence page
    count exactly (an experiment knob fails loud — a silently adjusted
    block would record a time for a config nobody asked for); ``None``
    consults the tuning DB (dlnetbench_tpu/tuning, keyed per cache
    geometry x chip) and falls back to the historical default
    ``fit_block(pages_per_seq, min(pages_per_seq, 8))`` bit-identically
    on a miss (ISSUE 9 satellite — this replaces the old inline
    hard-code).  With ``fmt`` the QUANTIZED kernel is the consumer —
    its own DB site (op ``paged_attention_quant``, format in the key):
    dequant changes the kernel's arithmetic intensity, so a dense
    optimum must never answer a quantized consult (ISSUE 12)."""
    pages_per_seq = page_indices.shape[1]
    if pages_per_compute_block is not None:
        p = pages_per_compute_block
        if not isinstance(p, int) or p < 1 or pages_per_seq % p:
            raise ValueError(
                f"paged_attention: pages_per_compute_block={p!r} does "
                f"not divide pages_per_seq {pages_per_seq}")
        return p
    default = pallas_common.fit_block(pages_per_seq,
                                      min(pages_per_seq, 8))
    from dlnetbench_tpu import tuning

    def check(cfg: dict) -> None:
        p = cfg.get("pages_per_compute_block")
        if not isinstance(p, int) or p < 1 or pages_per_seq % p:
            raise ValueError(
                f"pages_per_compute_block={p!r} does not divide "
                f"pages_per_seq {pages_per_seq}")
    b, hq, dh = q.shape
    hkv, _, page_size, _ = k_pages.shape
    if fmt is None:
        op = "paged_attention"
        key = tuning.params.paged_attention_key(pages_per_seq,
                                                page_size, b, hq, hkv,
                                                dh)
    else:
        op = "paged_attention_quant"
        key = tuning.params.paged_attention_quant_key(
            pages_per_seq, page_size, b, hq, hkv, dh, fmt)
    cfg = tuning.consult(
        op, key, {"pages_per_compute_block": default}, validate=check)
    return cfg["pages_per_compute_block"]


def paged_attention_decode(q, k_pages, v_pages, lengths, page_indices,
                           *, k_scale=None, v_scale=None,
                           fmt: str | None = None, impl: str = "auto",
                           pages_per_compute_block: int | None = None):
    """One decode step's attention for a batch of slots.  ``impl``:
    ``auto`` picks the Pallas TPU kernel on a TPU backend and the dense
    gather fallback elsewhere (the ``pallas_common`` backend split);
    ``pallas``/``gather`` force a path.  ``q`` must be pre-scaled by
    ``head_dim**-0.5`` — neither impl applies a softmax scale.

    With ``k_scale``/``v_scale`` (+``fmt``) the pools are QUANTIZED:
    ``pallas`` routes to the dequantizing kernel
    (ops/paged_attention_quant — pages gathered in their quantized
    dtype, dequantized in the VMEM prologue against the prefetched
    per-page scales) and ``gather`` dequantizes the gathered pages in
    XLA — the CPU-mesh fallback per the pallas_common backend split.

    ``pages_per_compute_block`` sizes the kernel's per-grid-lane page
    block (tuning-consulted when None — see
    ``resolve_pages_per_compute_block``; the quantized kernel is its
    own DB site).  The dense gather fallback computes the
    mathematically identical full attention regardless of blocking, so
    results are block-invariant by construction on both impls
    (tests/test_serving.py parity)."""
    quant = k_scale is not None
    if quant and fmt is None:
        raise ValueError("paged_attention_decode: scale arrays need "
                         "fmt ('int8'|'float8')")
    if impl == "auto":
        impl = "gather" if pallas_common.interpret_mode() else "pallas"
    if impl == "gather":
        if pages_per_compute_block is not None:
            # validate even on the path that ignores it: a bad explicit
            # knob must fail identically on every backend, not only
            # where the Pallas kernel happens to run
            resolve_pages_per_compute_block(
                q, k_pages, page_indices, pages_per_compute_block,
                fmt=fmt if quant else None)
        return _gather_attention(q, k_pages, v_pages, lengths,
                                 page_indices, k_scale, v_scale)
    if impl != "pallas":
        raise ValueError(f"paged_attention_decode: unknown impl "
                         f"{impl!r} (auto|pallas|gather)")
    if quant:
        from dlnetbench_tpu.ops.paged_attention_quant import \
            quant_paged_attention
        return quant_paged_attention(
            q, k_pages, v_pages, k_scale, v_scale, lengths,
            page_indices, fmt=fmt,
            pages_per_compute_block=resolve_pages_per_compute_block(
                q, k_pages, page_indices, pages_per_compute_block,
                fmt=fmt))
    from jax.experimental.pallas.ops.tpu.paged_attention import \
        paged_attention
    return paged_attention(
        q, k_pages, v_pages, lengths.astype(jnp.int32),
        page_indices.astype(jnp.int32),
        pages_per_compute_block=resolve_pages_per_compute_block(
            q, k_pages, page_indices, pages_per_compute_block))


def sharded_paged_attention(mesh, axis: str = "kv",
                            impl: str = "auto",
                            pages_per_compute_block: int | None = None,
                            quantized: bool = False,
                            fmt: str | None = None):
    """Shard the decode attention along GQA KV heads via ``shard_map``
    (the SNIPPETS.md [3] recipe): KV pages partition by head
    (``P(axis, None, None, None)``), query heads follow their group
    (``P(None, axis, None)``), lengths/block tables replicate.  Each
    shard attends over its own heads only — embarrassingly parallel, no
    collective until the caller's output projection (jit inserts the
    resharding there).  Requires ``num_kv_heads % axis_size == 0``.
    With ``quantized`` the callable takes the scale arrays after the
    pools (sharded along the same head axis — a head's pages and its
    scales live together)."""
    from jax.sharding import PartitionSpec as P

    if not quantized:
        def fn(q, k_pages, v_pages, lengths, page_indices):
            return paged_attention_decode(
                q, k_pages, v_pages, lengths, page_indices, impl=impl,
                pages_per_compute_block=pages_per_compute_block)

        return shard_map(
            fn, mesh=mesh,
            in_specs=(P(None, axis, None), P(axis, None, None, None),
                      P(axis, None, None, None), P(), P()),
            out_specs=P(None, axis, None),
            check_rep=False)

    def qfn(q, k_pages, v_pages, k_scale, v_scale, lengths,
            page_indices):
        return paged_attention_decode(
            q, k_pages, v_pages, lengths, page_indices,
            k_scale=k_scale, v_scale=v_scale, fmt=fmt, impl=impl,
            pages_per_compute_block=pages_per_compute_block)

    return shard_map(
        qfn, mesh=mesh,
        in_specs=(P(None, axis, None), P(axis, None, None, None),
                  P(axis, None, None, None), P(axis, None),
                  P(axis, None), P(), P()),
        out_specs=P(None, axis, None),
        check_rep=False)
