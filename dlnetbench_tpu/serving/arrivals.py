"""ArrivalPlan: the JSON-serializable open-loop traffic schedule.

Deliberately mirrors ``faults/plan.py`` — ``to_dict``/``from_dict``/
``validate``/``loads("@path")`` and seeded splitmix64 draws (the same
generator the native tier's ``fault_plan.hpp`` uses, so a plan's
randomness is reproducible from its JSON alone) — because traffic plans
are committable artifacts exactly like fault plans: a latency-vs-load
study's arrival process must be replayable from the record.

Kinds:
  poisson — memoryless arrivals at ``rate_rps`` (exponential
            inter-arrival draws).  The open-loop baseline: arrivals do
            NOT wait for the server, so a saturated engine builds a
            queue and TTFT blows up — the knee the study looks for.
  bursty  — piecewise poisson: within every ``period_s`` window the
            first ``duty`` fraction runs at ``rate_rps * factor``, the
            rest at ``rate_rps / factor`` — same *mean* arrival count
            per period only when duty balances factor; the point is
            tail pressure, and the plan states its own shape.
  diurnal — day-shaped poisson (ISSUE 18): ``phases`` is a piecewise
            rate curve, ``[[fraction_of_run, rate_multiplier], ...]``
            — the phase starting at fraction f of the plan's nominal
            span (``num_requests / rate_rps`` seconds) runs at
            ``rate_rps * multiplier`` until the next phase begins (the
            last phase holds to the end).  The load curve an elastic
            autoscaler study needs: a trough the fleet can scale down
            into and a peak it must scale back up for, committed in
            the plan JSON like every other traffic shape.
  replay  — explicit trace of ``{"t": seconds, "prompt_len", ...}``
            entries (a recorded production trace, replayed verbatim).

Per-request prompt/output lengths are fixed ints or seeded-uniform
``[lo, hi]`` ranges.  Arrival times are RELATIVE seconds from the run's
admission clock start.
"""
from __future__ import annotations

import dataclasses
import json
import math

KINDS = ("poisson", "bursty", "diurnal", "replay")

_M64 = (1 << 64) - 1


def splitmix64(state: int) -> tuple[int, int]:
    """One splitmix64 draw; returns ``(value, next_state)``.  Constants
    match the native tier (fault_plan.hpp:147) so a seed means the same
    stream on every tier."""
    state = (state + 0x9E3779B97F4A7C15) & _M64
    z = state
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _M64
    return (z ^ (z >> 31)), state


class _Rng:
    """Seeded splitmix64 stream with the native tier's u01 convention
    (``value >> 11`` over 2^53)."""

    def __init__(self, seed: int):
        self.state = seed & _M64

    def u01(self) -> float:
        v, self.state = splitmix64(self.state)
        return (v >> 11) / float(1 << 53)

    def uniform_int(self, lo: int, hi: int) -> int:
        """Inclusive [lo, hi]."""
        if hi <= lo:
            return lo
        v, self.state = splitmix64(self.state)
        return lo + v % (hi - lo + 1)

    def expovariate(self, rate: float) -> float:
        # 1 - u01() is in (0, 1]: log never sees 0
        return -math.log(1.0 - self.u01()) / rate


def _len_range(v) -> tuple[int, int]:
    if isinstance(v, (list, tuple)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


@dataclasses.dataclass(frozen=True)
class Request:
    """One request of the open-loop workload (plan-derived, so the
    whole request stream is replayable from the plan JSON)."""
    rid: int
    arrival_s: float     # relative to the admission clock start
    prompt_len: int
    output_len: int      # decode tokens to generate (EOS stand-in: the
                         # trace/production knowledge of response length)
    prefix_id: int = -1  # shared system-prompt id (ISSUE 12): >= 0
                         # means the first prefix_len prompt tokens
                         # come from prefix pool entry prefix_id's
                         # seeded stream (decode.prompt_tokens_for) —
                         # the page-shareable prefix
    prefix_len: int = 0


@dataclasses.dataclass
class ArrivalPlan:
    kind: str = "poisson"
    rate_rps: float = 0.0          # poisson/bursty mean request rate
    num_requests: int = 0          # poisson/bursty: how many to draw
    seed: int = 0
    prompt_len: object = 16        # int or [lo, hi] inclusive
    output_len: object = 8         # int or [lo, hi] inclusive
    # bursty shape: duty fraction of each period at rate*factor
    period_s: float = 1.0
    duty: float = 0.2
    factor: float = 4.0
    # diurnal shape (ISSUE 18): [[fraction_of_run, rate_multiplier],
    # ...] — phase i runs at rate_rps * multiplier from fraction f_i of
    # the nominal span (num_requests / rate_rps seconds) until f_{i+1}
    phases: list = dataclasses.field(default_factory=list)
    # replay: explicit trace entries {"t", "prompt_len", "output_len"}
    trace: list = dataclasses.field(default_factory=list)
    # prefix-heavy traffic (ISSUE 12): every request's first
    # shared_prefix_len prompt tokens come from one of prefix_pool
    # seeded "system prompts" (seeded choice per request) — the
    # replayable shape of shared-system-prompt production traffic, so
    # prefix-sharing wins are a committable scenario like every other
    shared_prefix_len: int = 0     # 0 disables (no prefix stamped)
    prefix_pool: int = 1           # distinct system prompts to draw from

    def validate(self) -> "ArrivalPlan":
        if self.kind not in KINDS:
            raise ValueError(f"arrival plan: unknown kind {self.kind!r} "
                             f"(one of {KINDS})")
        if self.kind in ("poisson", "bursty", "diurnal"):
            if not self.rate_rps > 0:
                raise ValueError(
                    f"arrival plan: {self.kind} needs rate_rps > 0, got "
                    f"{self.rate_rps!r} — a non-positive rate draws no "
                    f"(or infinitely-spaced) arrivals")
            if self.num_requests < 1:
                raise ValueError(
                    f"arrival plan: {self.kind} needs num_requests >= 1, "
                    f"got {self.num_requests}")
        if self.kind == "bursty":
            if not self.period_s > 0 or not 0.0 < self.duty < 1.0 \
                    or not self.factor >= 1.0:
                raise ValueError(
                    "arrival plan: bursty needs period_s > 0, "
                    "0 < duty < 1 and factor >= 1")
        if self.kind == "diurnal":
            if not self.phases:
                raise ValueError(
                    "arrival plan: diurnal needs a non-empty 'phases' "
                    "curve [[fraction_of_run, rate_multiplier], ...] — "
                    "a diurnal plan without a day shape is just "
                    "poisson, and the plan must state its own shape")
            last_f = -1.0
            for i, ph in enumerate(self.phases):
                if not (isinstance(ph, (list, tuple)) and len(ph) == 2):
                    raise ValueError(
                        f"arrival plan: diurnal phase {i} must be a "
                        f"[fraction_of_run, rate_multiplier] pair, got "
                        f"{ph!r}")
                f, mult = float(ph[0]), float(ph[1])
                if not 0.0 <= f < 1.0:
                    raise ValueError(
                        f"arrival plan: diurnal phase {i} starts at "
                        f"fraction {f!r} — fractions must be in [0, 1)")
                if f <= last_f:
                    raise ValueError(
                        f"arrival plan: diurnal phase {i} starts at "
                        f"fraction {f!r} <= the previous phase's "
                        f"{last_f!r} — phases must be strictly "
                        f"increasing")
                if not mult > 0:
                    raise ValueError(
                        f"arrival plan: diurnal phase {i} has rate "
                        f"multiplier {mult!r} — multipliers must be "
                        f"> 0 (a zero-rate phase never draws the next "
                        f"arrival)")
                last_f = f
            if float(self.phases[0][0]) != 0.0:
                raise ValueError(
                    "arrival plan: the first diurnal phase must start "
                    "at fraction 0.0 — the curve must cover the whole "
                    "run")
        if self.kind == "replay":
            if not self.trace:
                raise ValueError(
                    "arrival plan: replay needs a non-empty 'trace' — "
                    "an empty trace is a zero-request study, which is "
                    "a configuration error, not a measurement")
            last = -1.0
            for i, e in enumerate(self.trace):
                t = float(e.get("t", -1.0))
                if t < 0 or t < last:
                    raise ValueError(
                        f"arrival plan: trace entry {i} has t={t!r} — "
                        f"times must be >= 0 and non-decreasing")
                last = t
        for name in ("prompt_len", "output_len"):
            lo, hi = _len_range(getattr(self, name))
            if lo < 1 or hi < lo:
                raise ValueError(
                    f"arrival plan: {name} must be >= 1 (range "
                    f"[lo, hi] with lo <= hi), got "
                    f"{getattr(self, name)!r}")
        if self.shared_prefix_len < 0:
            raise ValueError(
                f"arrival plan: shared_prefix_len must be >= 0, got "
                f"{self.shared_prefix_len}")
        if self.prefix_pool < 1:
            raise ValueError(
                f"arrival plan: prefix_pool must be >= 1, got "
                f"{self.prefix_pool}")
        if self.shared_prefix_len:
            p_lo, _ = _len_range(self.prompt_len)
            # replay traces may carry explicit per-entry prompt
            # lengths that bypass the plan-level range — the guard
            # must see the SHORTEST prompt any request can get
            if self.kind == "replay":
                p_lo = min([p_lo] + [int(e["prompt_len"])
                                     for e in self.trace
                                     if "prompt_len" in e])
            if self.shared_prefix_len >= p_lo:
                raise ValueError(
                    f"arrival plan: shared_prefix_len "
                    f"{self.shared_prefix_len} must be < the minimum "
                    f"prompt_len {p_lo} — every request needs at "
                    f"least one private prompt token (the final "
                    f"prompt token always re-prefills: it produces "
                    f"the first generated token)")
        return self

    # ---- serialization (the committable wire format) -----------------
    def to_dict(self) -> dict:
        out = {"kind": self.kind, "seed": self.seed,
               "prompt_len": self.prompt_len,
               "output_len": self.output_len}
        if self.kind in ("poisson", "bursty", "diurnal"):
            out["rate_rps"] = self.rate_rps
            out["num_requests"] = self.num_requests
        if self.kind == "bursty":
            out.update(period_s=self.period_s, duty=self.duty,
                       factor=self.factor)
        if self.kind == "diurnal":
            # JSON-canonical pairs: a fixture round-trips byte-
            # identically through json.dumps whatever pair type the
            # caller built the plan with
            out["phases"] = [[float(f), float(m)]
                             for f, m in self.phases]
        if self.kind == "replay":
            out["trace"] = list(self.trace)
        if self.shared_prefix_len:
            # absent unless set: committed pre-ISSUE-12 plan fixtures
            # round-trip byte-identically
            out["shared_prefix_len"] = self.shared_prefix_len
            out["prefix_pool"] = self.prefix_pool
        return out

    def dumps(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_dict(cls, d: dict) -> "ArrivalPlan":
        return cls(
            kind=d.get("kind", "poisson"),
            rate_rps=float(d.get("rate_rps", 0.0)),
            num_requests=int(d.get("num_requests", 0)),
            seed=int(d.get("seed", 0)),
            prompt_len=d.get("prompt_len", 16),
            output_len=d.get("output_len", 8),
            period_s=float(d.get("period_s", 1.0)),
            duty=float(d.get("duty", 0.2)),
            factor=float(d.get("factor", 4.0)),
            phases=[list(p) for p in d.get("phases", [])],
            trace=list(d.get("trace", [])),
            shared_prefix_len=int(d.get("shared_prefix_len", 0)),
            prefix_pool=int(d.get("prefix_pool", 1)),
        ).validate()

    @classmethod
    def loads(cls, text: str) -> "ArrivalPlan":
        """Parse an inline JSON plan or an ``@path`` file reference
        (same convention as ``FaultPlan.loads``)."""
        text = text.strip()
        if text.startswith("@"):
            with open(text[1:]) as f:
                text = f.read()
        return cls.from_dict(json.loads(text))

    # ---- the request stream ------------------------------------------
    def sample(self) -> list[Request]:
        """The plan's deterministic request stream.  Same plan JSON ->
        same arrivals, lengths and ids, on any machine."""
        self.validate()
        rng = _Rng(self.seed)
        p_lo, p_hi = _len_range(self.prompt_len)
        o_lo, o_hi = _len_range(self.output_len)

        def prefix():
            # drawn ONLY when the knob is set, so legacy plans keep
            # their exact pre-ISSUE-12 request streams
            if not self.shared_prefix_len:
                return {}
            return {"prefix_id": rng.uniform_int(0,
                                                 self.prefix_pool - 1),
                    "prefix_len": self.shared_prefix_len}
        out: list[Request] = []
        if self.kind == "replay":
            for i, e in enumerate(self.trace):
                out.append(Request(
                    rid=i, arrival_s=float(e["t"]),
                    prompt_len=int(e.get("prompt_len",
                                         rng.uniform_int(p_lo, p_hi))),
                    output_len=int(e.get("output_len",
                                         rng.uniform_int(o_lo, o_hi))),
                    **prefix()))
            return out
        # diurnal clock: the curve is stated in fractions of the
        # NOMINAL span (num_requests at the base rate) so the same
        # phases list means the same day shape at any scale
        span = (self.num_requests / self.rate_rps
                if self.kind == "diurnal" else 0.0)
        t = 0.0
        for i in range(self.num_requests):
            rate = self.rate_rps
            if self.kind == "bursty":
                phase = (t % self.period_s) / self.period_s
                rate = (self.rate_rps * self.factor if phase < self.duty
                        else self.rate_rps / self.factor)
            elif self.kind == "diurnal":
                frac = t / span
                mult = self.phases[0][1]
                for f, m in self.phases:
                    if frac >= float(f):
                        mult = m   # last phase holds past fraction 1.0
                rate = self.rate_rps * float(mult)
            t += rng.expovariate(rate)
            out.append(Request(rid=i, arrival_s=t,
                               prompt_len=rng.uniform_int(p_lo, p_hi),
                               output_len=rng.uniform_int(o_lo, o_hi),
                               **prefix()))
        return out

    def offered_rps(self) -> float:
        """The plan's realized offered load: requests per second of the
        sampled stream's span (the x-axis of latency-vs-load plots; for
        poisson it converges on ``rate_rps``)."""
        reqs = self.sample()
        span = max((r.arrival_s for r in reqs), default=0.0)
        if span <= 0:
            return float(len(reqs))
        return len(reqs) / span
