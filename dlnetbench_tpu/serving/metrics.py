"""Serving metrics: per-request TTFT/TPOT, percentiles, goodput-at-SLO.

The figures shift from step time (the training tier's currency) to the
serving tier's:

* **TTFT** — time to first token, ``first_token - arrival``.  Includes
  queue wait: an open-loop arrival that waited for a slot pays for it
  here, which is how saturation shows up as a TTFT p99 blowup.
* **TPOT** — time per output token AFTER the first,
  ``(finish - first_token) / (output_len - 1)`` (NaN-free: requests
  with a single output token contribute no TPOT sample).
* **e2e**  — ``finish - arrival``.
* **goodput-at-SLO** — completed requests meeting BOTH SLOs (TTFT and
  TPOT budgets) per wall second; the serving analogue of the elastic
  tier's useful-steps-per-second.  ``goodput_timeline`` windows the
  same predicate over finish times so a fault's dip AND recovery are
  visible in one record.

``build_result`` shapes everything as a ``ProxyResult`` so the serving
tier rides the EXISTING record schema v2 unchanged: per-request
ttft/tpot/e2e arrays are per-rank "timers" (``metrics.emit``
band-summarizes them like any timer), the aggregate block is a
``serving`` global, and the arrival plan is a comparable global —
``metrics.merge`` refuses to combine records from different plans
exactly as it refuses different fault plans.
"""
from __future__ import annotations

import dataclasses
import math

from dlnetbench_tpu.proxies.base import ProxyResult
from dlnetbench_tpu.serving.arrivals import ArrivalPlan


@dataclasses.dataclass
class Completed:
    """One finished request's stamps (seconds, engine-clock relative)."""
    rid: int
    arrival_s: float
    admitted_s: float
    first_token_s: float
    finish_s: float
    prompt_len: int
    output_len: int

    @property
    def ttft_ms(self) -> float:
        return (self.first_token_s - self.arrival_s) * 1e3

    @property
    def tpot_ms(self) -> float:
        """NaN for single-token outputs (no inter-token interval)."""
        if self.output_len < 2:
            return float("nan")
        return ((self.finish_s - self.first_token_s)
                / (self.output_len - 1)) * 1e3

    @property
    def e2e_ms(self) -> float:
        return (self.finish_s - self.arrival_s) * 1e3


def percentile(vals: list[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]); NaN on empty input.
    With serving-study sample counts, interpolation would be theater —
    same honesty rule as ``metrics.stats`` bands."""
    vals = sorted(v for v in vals if not math.isnan(v))
    if not vals:
        return float("nan")
    rank = max(1, math.ceil(q / 100.0 * len(vals)))
    return vals[min(rank, len(vals)) - 1]


def latency_summary(vals_ms: list[float], ndigits: int = 3) -> dict:
    clean = [v for v in vals_ms if not math.isnan(v)]
    if not clean:
        return {"p50": float("nan"), "p95": float("nan"),
                "p99": float("nan"), "mean": float("nan"), "n": 0}
    return {
        "p50": round(percentile(clean, 50), ndigits),
        "p95": round(percentile(clean, 95), ndigits),
        "p99": round(percentile(clean, 99), ndigits),
        "mean": round(sum(clean) / len(clean), ndigits),
        "n": len(clean),
    }


def meets_slo(c: Completed, slo_ttft_ms: float, slo_tpot_ms: float) -> bool:
    """Both budgets must hold; a request without a TPOT sample (one
    output token) is judged on TTFT alone."""
    if c.ttft_ms > slo_ttft_ms:
        return False
    tpot = c.tpot_ms
    return math.isnan(tpot) or tpot <= slo_tpot_ms


def goodput_timeline(completed: list[Completed], slo_ttft_ms: float,
                     slo_tpot_ms: float, window_s: float = 0.5) -> list:
    """Windowed SLO-goodput over finish times: one
    ``{"t_s", "completed", "slo_ok", "goodput_frac"}`` entry per
    ``window_s`` bucket — the channel a crash's SLO dip and the
    post-recovery climb are visible in (docs/RESILIENCE.md)."""
    if not completed:
        return []
    horizon = max(c.finish_s for c in completed)
    n_win = max(1, math.ceil(horizon / window_s))
    out = []
    for w in range(n_win):
        lo, hi = w * window_s, (w + 1) * window_s
        done = [c for c in completed if lo <= c.finish_s < hi]
        ok = sum(1 for c in done if meets_slo(c, slo_ttft_ms,
                                              slo_tpot_ms))
        out.append({
            "t_s": round(hi, 3),
            "completed": len(done),
            "slo_ok": ok,
            # a window with NO completions states "no data" (null), not
            # a fabricated 1.0 — a crash outage spanning whole windows
            # must never read as perfect goodput
            "goodput_frac": round(ok / len(done), 4) if done else None,
        })
    return out


def rolling_slo_breach(recent: list[Completed], *, slo_ttft_ms: float,
                       slo_tpot_ms: float, now_s: float,
                       window_s: float = 0.5, min_completed: int = 4,
                       breach_frac: float = 0.5) -> dict | None:
    """Live SLO-breach detection: the ``goodput_timeline`` windowing
    applied to the trailing window at ``now_s``.  Returns the breaching
    window entry ``{"t_s", "completed", "slo_ok", "goodput_frac"}``
    when the last ``window_s`` seconds completed at least
    ``min_completed`` requests and their SLO-ok fraction fell below
    ``breach_frac`` — the mid-run form of the dip the post-mortem
    timeline shows after the fact.  None otherwise."""
    tail = [c for c in recent if c.finish_s >= now_s - window_s]
    if len(tail) < min_completed:
        return None
    ok = sum(1 for c in tail if meets_slo(c, slo_ttft_ms, slo_tpot_ms))
    frac = ok / len(tail)
    if frac >= breach_frac:
        return None
    return {"t_s": round(now_s, 3), "completed": len(tail),
            "slo_ok": ok, "goodput_frac": round(frac, 4)}


class LiveMetricsWriter:
    """Windowed live-metrics JSONL stream (the ``bench.py
    --live-metrics`` channel): one snapshot line per ``window_s`` of
    engine time — rolling TTFT/TPOT percentiles over the window's
    completions, queue depth, admitted concurrency, KV occupancy.
    Schema locked by tests/test_bench_aux.py; pure except for the
    appends to ``path``."""

    def __init__(self, path, *, window_s: float = 0.5):
        self.path = path
        self.window_s = float(window_s)
        self._last_emit_s: float | None = None
        self._run = 0
        self.lines_written = 0
        # one invocation = one stream: a re-run appending to last
        # time's file would interleave stale lines into the feed
        open(self.path, "w").close()

    def reset_run(self) -> None:
        """New engine run: the engine clock restarts at 0 (``t_s`` in
        the stream is run-relative), so the window stamps must too — a
        stale prior-round stamp would silence the whole next round
        (``now - last`` negative) and compare finish times across
        incomparable clocks.  Bumps the ``run`` stamp so a consumer
        can attribute each line despite the restarting clock.  Wired
        from ``Engine._reset_state``."""
        self._last_emit_s = None
        self._run += 1

    @staticmethod
    def snapshot_line(*, t_s: float, window_s: float,
                      window_completed: list[Completed],
                      queue_depth: int, active_slots: int,
                      kv_occupancy: float,
                      engine_steps: int, run: int = 0,
                      replica_id: int | None = None) -> dict:
        """One snapshot's dict (pure — the schema-lock test calls this
        directly).  Latency percentiles cover the WINDOW's completions
        only: a live stream must show the current state, not the
        run-to-date mixture.  ``run`` counts engine runs on this
        stream: ``t_s`` is run-relative (every Engine.run restarts the
        clock at 0), so (run, t_s) — not t_s alone — orders the feed.
        ``replica_id`` (ISSUE 18) attributes the line in a fleet run's
        interleaved stream; the key is ABSENT on single-engine runs,
        so existing consumers keep parsing byte-identical lines."""
        ttft = [c.ttft_ms for c in window_completed]
        tpot = [c.tpot_ms for c in window_completed]
        line = {
            "run": int(run),
            "t_s": round(t_s, 3),
            "window_s": window_s,
            "completed": len(window_completed),
            "ttft_ms": latency_summary(ttft),
            "tpot_ms": latency_summary(tpot),
            "queue_depth": int(queue_depth),
            "active_slots": int(active_slots),
            "kv_occupancy": round(float(kv_occupancy), 4),
            "engine_steps": int(engine_steps),
        }
        if replica_id is not None:
            line["replica_id"] = int(replica_id)
        return line

    def maybe_emit(self, engine, now_s: float) -> dict | None:
        """Called by the engine once per step; writes (and returns) a
        snapshot when a full window elapsed since the last one."""
        if self._last_emit_s is not None \
                and now_s - self._last_emit_s < self.window_s:
            return None
        t0 = (self._last_emit_s if self._last_emit_s is not None
              else max(0.0, now_s - self.window_s))
        self._last_emit_s = now_s
        line = self.snapshot_line(
            t_s=now_s, window_s=self.window_s,
            window_completed=[c for c in engine.completed
                              if c.finish_s >= t0],
            queue_depth=len(engine.pending),
            active_slots=sum(1 for s in engine.slots if s is not None),
            kv_occupancy=engine.cache.stats()["occupancy"],
            engine_steps=engine.engine_steps, run=self._run,
            replica_id=getattr(engine, "replica_id", None))
        import json
        with open(self.path, "a") as f:
            f.write(json.dumps(line) + "\n")
        self.lines_written += 1
        return line


def serving_block(completed: list[Completed], plan: ArrivalPlan, *,
                  slo_ttft_ms: float, slo_tpot_ms: float,
                  wall_s: float, engine_steps: int,
                  cache_stats: dict | None = None,
                  queue_depth_max: int = 0,
                  batch_occupancy_mean: float = 0.0,
                  decode_loop: dict | None = None,
                  admitted_peak: int | None = None,
                  migration: dict | None = None) -> dict:
    """The record's ``serving`` global: aggregate latency percentiles,
    throughput, and goodput-at-SLO for one run.  ``decode_loop``
    (ISSUE 11, ``Engine.decode_loop_block``) adds the dispatch
    decomposition — steps/tokens per host sync, priced host crossings,
    speculative acceptance — the attribution engine folds into the
    host fraction (analysis/attribution.attribute_serving).
    ``migration`` (ISSUE 16, ``MigrationChannel.stats_block``) adds the
    disaggregated run's page-migration wire accounting; absent on
    monolithic runs so their records stay byte-identical."""
    ttft = [c.ttft_ms for c in completed]
    tpot = [c.tpot_ms for c in completed]
    e2e = [c.e2e_ms for c in completed]
    tokens = sum(c.output_len for c in completed)
    ok = sum(1 for c in completed if meets_slo(c, slo_ttft_ms,
                                               slo_tpot_ms))
    block = {
        "offered_rps": round(plan.offered_rps(), 4),
        "completed": len(completed),
        "measured_rps": round(len(completed) / wall_s, 4) if wall_s > 0
        else 0.0,
        "tokens_per_s": round(tokens / wall_s, 4) if wall_s > 0 else 0.0,
        "wall_s": round(wall_s, 4),
        "engine_steps": engine_steps,
        "ttft_ms": latency_summary(ttft),
        "tpot_ms": latency_summary(tpot),
        "e2e_ms": latency_summary(e2e),
        "slo": {"ttft_ms": slo_ttft_ms, "tpot_ms": slo_tpot_ms},
        "goodput_frac": round(ok / len(completed), 4) if completed
        else 0.0,
        "goodput_rps": round(ok / wall_s, 4) if wall_s > 0 else 0.0,
        "queue_depth_max": queue_depth_max,
        "batch_occupancy_mean": round(batch_occupancy_mean, 4),
        "goodput_timeline": goodput_timeline(completed, slo_ttft_ms,
                                             slo_tpot_ms),
    }
    if admitted_peak is not None:
        # peak CONCURRENT resident sequences — the capacity axis the
        # kv-density A/B compares at equal pool bytes (ISSUE 12)
        block["admitted_concurrency_peak"] = admitted_peak
    if cache_stats:
        block["kv_cache"] = cache_stats
    if decode_loop:
        block["decode_loop"] = decode_loop
    if migration:
        block["migration"] = migration
    return block


def acceptance_by_temp(points: list[tuple[float, float]]) -> list[dict]:
    """Shape measured (temperature, acceptance_rate) pairs into the
    ``spec_acceptance_by_temp`` record global (ISSUE 19): sorted by
    temperature, rates clamped to [0, 1] and rounded.  VOLATILE at
    merge — acceptance is a measurement (it moves with params and
    load), unlike the comparable ``sampling`` identity block.  The
    study sweeps temperature and concatenates per-run points into the
    acceptance-vs-temperature curve artifact."""
    out = []
    for temp, rate in sorted(points, key=lambda p: float(p[0])):
        out.append({"temperature": round(float(temp), 4),
                    "acceptance_rate": round(
                        min(1.0, max(0.0, float(rate))), 4)})
    return out


def build_result(completed: list[Completed], plan: ArrivalPlan,
                 global_meta: dict, *, section: str = "serving"
                 ) -> ProxyResult:
    """Shape a serving run as a ProxyResult for ``metrics.emit``: one
    "run" per completed request, per-request ttft/tpot/e2e arrays as
    the per-rank timers (band-summarized by emit like every timer), the
    aggregate ``serving`` block + ``arrival_plan`` already in
    ``global_meta`` (scheduler stamps them)."""
    order = sorted(completed, key=lambda c: c.finish_s)
    # ms-unit per-request arrays (the names deliberately carry no
    # trailing 's' — the parser's singular-column rule would mangle
    # "ttft_ms" into "ttft_m"); units documented here + docs/SERVING.md
    timers = {
        "ttft": [round(c.ttft_ms, 3) for c in order],
        # single-token outputs have no inter-token interval: their
        # timer entry is 0.0 (arrays must stay numeric and num_runs
        # long); the serving block's percentiles NaN-filter instead
        "tpot": [0.0 if math.isnan(c.tpot_ms) else round(c.tpot_ms, 3)
                 for c in order],
        "e2e": [round(c.e2e_ms, 3) for c in order],
        # "output_len", not "output_tokens": the trailing 's' would be
        # stripped by the parser's singular-column rule too
        "output_len": [c.output_len for c in order],
    }
    return ProxyResult(
        name=section,
        global_meta=global_meta,
        timers_us=timers,   # ms/count units — names say so; the record
                            # schema carries arbitrary named timers
        warmup_times_us=[],
        num_runs=len(order),
    )
