"""Disaggregated prefill/decode serving (ISSUE 16): the
DistServe/Splitwise split, measured honestly on one harness run.

The monolithic ``scheduler.Engine`` interleaves compute-bound, bursty
prefill with memory-bound, steady decode on one device — every
admitted prompt steals decode steps and inflates in-flight requests'
TPOT (the interference ``examples/pod_study.py --serving`` measures at
the knee).  This module splits the run into TWO engines on DISJOINT
device subsets of the same harness world:

* ranks ``[0, prefill_ranks)`` — a prefill replica that admits from
  the shared arrival queue, reserves PROMPT-ONLY pages, and drains
  each prompt into its local pool (producing the TTFT token at the
  existing ``_prefill_one`` stamp);
* ranks ``[prefill_ranks, world)`` — a decode replica that receives
  finished sequences over the page-migration channel
  (``ops/page_migration.py``: pages + scales contiguous in their
  STORED int8/fp8 dtype, chunk-loop transfers) and decodes them to
  completion.

The overlap is real, not narrated: the decode replica's fused program
is DISPATCHED without fencing (``Engine._step_dispatch``), the
migration send runs on the prefill device while the decode device
computes, and the fence closes both (``_step_complete``) — the
classic async-dispatch overlap, measured as comm-solo / compute-solo /
together legs and reduced through ``stats.overlap_fraction`` like
every collective A/B in this repo.  The decode replica's adaptive-N
trip count is capped at the next expected migration arrival
(``Engine._pick_n_steps`` ETA cap) so a finished handoff never waits
out a full N-step loop.

Token parity is the bar: both replicas run the SAME compiled program
families over the SAME weights, the migrated pages are bit-identical
to what a monolithic engine would have written locally (stored dtype +
scales move verbatim), and the decode replica rebuilds
lengths/block-tables to exactly the monolithic post-prefill state —
so greedy output is token-identical to ``run_serving`` per cache
dtype (locked by tests/test_disagg.py for bf16 AND int8).

Faults compose: a crash under policy ``shrink`` takes down ONE
replica's rank share.  A dead prefill rank re-queues mid-prefill
requests (original arrival stamps kept) onto a rebuilt, smaller
prefill replica while the decode replica's in-flight sequences keep
streaming — TTFT p99 blows up while TPOT holds, a scenario the
monolithic engine cannot express.
"""
from __future__ import annotations

import dataclasses
import math
import time
from collections import deque

import jax

from dlnetbench_tpu.metrics import spans
from dlnetbench_tpu.models.transformer import (TransformerConfig,
                                               init_params)
from dlnetbench_tpu.ops.page_migration import MigrationChannel
from dlnetbench_tpu.serving import metrics as M
from dlnetbench_tpu.serving import requeue
from dlnetbench_tpu.serving.arrivals import ArrivalPlan, Request
from dlnetbench_tpu.serving.scheduler import Engine, ServingConfig


class _PrefillReplica(Engine):
    """The prefill-phase engine: admits with PROMPT-ONLY page
    reservations (its pool never decodes) and, where the monolithic
    engine would activate a decode slot, hands the finished sequence
    to the migration queue instead.  ``_decode_needed = False`` skips
    the decode program build — a replica that never dispatches decode
    must not pay its compile or carry its executable."""

    _decode_needed = False

    def _reset_state(self):
        super()._reset_state()
        # (slot, _SlotState) pairs whose prompt is fully cached,
        # awaiting a migration send — the handoff queue the driver
        # drains.  The slot stays occupied (its pages are the payload)
        # until the send is dispatched.
        self._handoff: list = []

    def _admission_tokens(self, req: Request) -> int:
        return req.prompt_len

    def _activate_decode_slot(self, slot, st) -> None:
        self._handoff.append((slot, st))

    def pump(self, budget: int = 2) -> None:
        """One driver-loop iteration's prefill work: dispatch up to
        ``budget`` chunks, oldest admission first.  Intermediate
        chunks never fence (``_prefill_one``'s contract), so each
        costs ~one host dispatch and its COMPUTE runs on the prefill
        device underneath the decode trips the driver overlaps it
        with; by the time the prompt-completing chunk's ``int(nxt)``
        first-token fence lands, the earlier chunks have been cooking
        for several trips.  The budget is the load-bearing part: an
        unbounded pump under an admission backlog queues the whole
        backlog's prefill compute at once (measured: ~16 ms for four
        48-token prompts), and every decode fence behind it absorbs
        that queue — the same head-of-line blocking the monolithic
        inline engine suffers, just relocated.  Draining whole
        prompts at admission instead (separate mode) stalls the
        shared host thread for the full prompt wall per arrival while
        the decode replica emits nothing."""
        mids = [(st.admitted_s, i, st)
                for i, st in enumerate(self.slots)
                if st is not None
                and st.prefill_done < st.req.prompt_len]
        mids.sort()
        for _, i, st in mids:
            while budget and st.prefill_done < st.req.prompt_len:
                self._prefill_one(i, st)
                budget -= 1
            if not budget:
                break


class DisaggServer:
    """One disaggregated serving run: a prefill replica, a decode
    replica, and the migration channel between their pools.  Drives
    both engines from one host thread (the single-controller harness)
    — the decode program's async dispatch window is where prefill and
    migration work hide."""

    def __init__(self, model_cfg: TransformerConfig,
                 cfg: ServingConfig, *, params=None, devices=None,
                 prefill_slots: int | None = None,
                 decode_slots: int | None = None):
        cfg.validate()
        if not cfg.disaggregate:
            raise ValueError("disagg: DisaggServer needs "
                             "cfg.disaggregate=True — a monolithic "
                             "config belongs to run_serving")
        self.model_cfg = model_cfg
        self.cfg = cfg
        devs = (list(devices) if devices is not None
                else jax.devices()[:cfg.world])
        if len(devs) < cfg.world:
            raise ValueError(
                f"disagg: world {cfg.world} "
                f"(prefill {cfg.prefill_ranks} + decode "
                f"{cfg.decode_ranks}) needs {cfg.world} devices, have "
                f"{len(devs)} — the replica meshes must be disjoint")
        self.devices = devs[:cfg.world]
        self.prefill_devices = self.devices[:cfg.prefill_ranks]
        self.decode_devices = self.devices[cfg.prefill_ranks:]
        if params is None:
            params = init_params(jax.random.key(0), model_cfg)
        p_slots = cfg.slots if prefill_slots is None else prefill_slots
        d_slots = cfg.slots if decode_slots is None else decode_slots
        # inline mode on the prefill replica: admission must NOT drain
        # the prompt (separate mode's admission-time drain would stall
        # the shared driver thread for the prompt's full device wall);
        # the driver pumps chunks dispatch-only under the decode window
        pcfg = dataclasses.replace(
            cfg, disaggregate=False, world=cfg.prefill_ranks,
            slots=p_slots, multi_step_n=1, prefill="inline")
        dcfg = dataclasses.replace(
            cfg, disaggregate=False, world=cfg.decode_ranks,
            slots=d_slots, prefill="separate")
        # each replica's programs/pools are built UNDER its device so
        # the AOT executables target it; the weights are copied once
        # per replica (same values — parity is unaffected)
        with jax.default_device(self.prefill_devices[0]):
            self.prefill = _PrefillReplica(
                model_cfg, pcfg,
                params=jax.device_put(params, self.prefill_devices[0]),
                devices=self.prefill_devices)
        with jax.default_device(self.decode_devices[0]):
            self.decode = Engine(
                model_cfg, dcfg,
                params=jax.device_put(params, self.decode_devices[0]),
                devices=self.decode_devices)
        self.channel = MigrationChannel(
            self.decode.cache_cfg, self.decode_devices[0],
            chunk_pages=cfg.migration_chunk_pages)
        # sent-and-fenced payloads awaiting a free decode slot/pages:
        # (PendingSend, handoff meta) in prefill-completion order
        self._ready: deque = deque()
        self._handoff_ewma_s = 0.0

    # ---- device contexts ---------------------------------------------
    def _pctx(self):
        return jax.default_device(self.prefill_devices[0])

    def _dctx(self):
        return jax.default_device(self.decode_devices[0])

    # ---- the driver loop ---------------------------------------------
    def run(self, requests: list[Request], *, injector=None,
            t_origin: float | None = None
            ) -> tuple[list[M.Completed], float]:
        """Drive both replicas until every request completes; returns
        ``(completed, wall_s)`` on the shared admission clock.  Same
        contract as ``Engine.run`` (t_origin anchors a fault-segmented
        continuation; a scripted RankFailure propagates with progress
        retained on both engines)."""
        pe, de = self.prefill, self.decode
        for r in requests:
            if r.prompt_len + r.output_len > self.cfg.max_seq_len:
                raise ValueError(
                    f"serving: request {r.rid} needs "
                    f"{r.prompt_len + r.output_len} tokens > "
                    f"max_seq_len {self.cfg.max_seq_len}")
        with self._pctx():
            pe._reset_state()
        with self._dctx():
            de._reset_state()
        self.channel.reset()
        self._ready.clear()
        pe.queue = deque(sorted(requests, key=lambda r: r.arrival_s))
        t0 = time.monotonic() if t_origin is None else t_origin
        pe._t0 = t0
        de._t0 = t0
        while (pe.queue or pe.pending or pe._handoff or self._ready
               or any(s is not None for s in pe.slots)
               or any(s is not None for s in de.slots)):
            now = pe._now()
            if injector is not None:
                injector.before_step()  # faults land INSIDE the loop
            with self._pctx():
                pe._admit_arrivals(now)
            de_active = any(s is not None for s in de.slots)
            if de_active:
                self._decode_step()
            else:
                # decode idle: nothing to hide behind — chunks pump
                # unoverlapped and the fenced send IS the comm-solo
                # overlap leg
                with self._pctx():
                    pe.pump()
                if pe._handoff:
                    self._ready.append(
                        self._send_next(overlapped=False))
            # land arrived payloads at the sync boundary (never while
            # a decode dispatch holds the pool buffers in flight)
            while self._ready:
                pending, meta = self._ready[0]
                with self._dctx():
                    ok = de.admit_prefilled(
                        meta["req"], last_token=meta["last_token"],
                        admitted_s=meta["admitted_s"],
                        first_token_s=meta["first_token_s"],
                        generated=meta["generated"],
                        pending_send=pending, channel=self.channel)
                if not ok:
                    break  # no slot/pages: retry next boundary
                self._ready.popleft()
            self._update_eta()
            if (not pe.pending and not pe._handoff and not self._ready
                    and not any(s is not None for s in pe.slots)
                    and not any(s is not None for s in de.slots)
                    and pe.queue):
                # idle: sleep to the next arrival (open loop — the
                # engine must not busy-spin the clock forward)
                dt = pe.queue[0].arrival_s - pe._now()
                if dt > 0:
                    time.sleep(dt)
        wall = pe._now()
        return pe.completed + de.completed, wall

    def _decode_step(self) -> None:
        """One decode-replica step with the migration overlap window:
        dispatch the decode program (no fence), pump the prefill
        replica's chunks and run the next handoff's send on the prefill
        device while the decode device computes, then fence both.  The
        three overlap legs land in the channel; the engine's own
        telemetry sampling (SLO breach triggers, live stream) rides the
        step exactly as in ``Engine._step``."""
        pe, de, ch = self.prefill, self.decode, self.channel
        tele_on = de._tele is not None or de.live is not None
        t_w = time.perf_counter()
        sync0 = (de.dstate.sync_total_us()
                 if tele_on and de.dstate is not None else 0.0)
        with self._dctx():
            ctx = de._step_dispatch()
        with self._pctx():
            pe.pump()   # chunk dispatches ride under the decode trip
        sent = None
        if ctx is not None and pe._handoff:
            sent = self._send_next(overlapped=True)
        with self._dctx():
            de._step_complete(ctx)
        if sent is not None:
            sent[0].wait()  # decode fenced first: the together window
            ch.note_both(time.perf_counter() - t_w)
            self._ready.append(sent)
        elif ctx is not None:
            # compute-solo leg: a decode window with no send in flight
            ch.note_compute_solo(time.perf_counter() - t_w)
        if tele_on:
            de._sample_step((time.perf_counter() - t_w) * 1e6, sync0)

    def _send_next(self, *, overlapped: bool):
        """Dispatch the oldest handoff's page migration.  The gather
        captures the prefill pool buffers at dispatch, so the slot's
        pages return to the allocator immediately — the runtime orders
        the device reads before any reuse write."""
        pe, de = self.prefill, self.decode
        slot, st = pe._handoff.pop(0)
        s = pe.cfg.page_size
        n_pages = (st.req.prompt_len + s - 1) // s
        ids = [int(p) for p in pe.cache.block_tables[slot][:n_pages]]
        with self._pctx():
            pending = self.channel.send(
                pe._pool_args(), ids, fence=not overlapped,
                overlapped=overlapped)
        pe.cache.free(slot)
        pe.slots[slot] = None
        done_s = pe._now()
        lat = max(0.0, done_s - st.admitted_s)
        self._handoff_ewma_s = (lat if not self._handoff_ewma_s
                                else 0.5 * self._handoff_ewma_s
                                + 0.5 * lat)
        if de._tele is not None:
            # migration provenance in the flight ring: a stalled
            # handoff is visible next to the decode step walls when an
            # anomaly dumps the window (docs/OBSERVABILITY.md)
            de._tele.record(
                "migration", step=de.engine_steps, pages=len(ids),
                bytes=self.channel.bytes_for_pages(len(ids)),
                overlapped=overlapped,
                queue_depth=len(pe._handoff))
        meta = {"req": st.req, "last_token": st.last_token,
                "admitted_s": st.admitted_s,
                "first_token_s": st.first_token_s,
                "generated": st.generated}
        return (pending, meta)

    def _update_eta(self) -> None:
        """Feed the decode replica's adaptive-N cap: when is the next
        migrated sequence expected?  Ready/handoff work means NOW (the
        loop should sync at the first opportunity) — but ONLY while a
        decode slot is free to land it.  With every slot occupied the
        payload cannot land before a sequence completes, and the
        rem_min cap already times that boundary exactly; a dt~0 ETA
        there would force 1-step trips that slow the very completions
        the payload is waiting on (a measured saturation death spiral:
        full slots -> n=1 -> slower decode -> fuller slots).  An inf
        ETA keeps the rem_min cap armed without the dt clamp.
        Admitted-but-unserved arrivals add the measured handoff
        latency; a future queue head adds it on top of its arrival
        time."""
        pe, de = self.prefill, self.decode
        now = pe._now()
        if self._ready or pe._handoff:
            eta = (now if any(s is None for s in de.slots)
                   else math.inf)
        elif pe.pending or any(s is not None for s in pe.slots):
            eta = now + self._handoff_ewma_s
        elif pe.queue:
            eta = pe.queue[0].arrival_s + self._handoff_ewma_s
        else:
            eta = None
        de._migration_eta_s = eta

    # ---- fault segmentation ------------------------------------------
    def drain_unfinished(self) -> list[Request]:
        """Everything not completed, across BOTH replicas and the
        channel, for a crash-shrink continuation: mid-prefill and
        handoff-pending requests come off the prefill replica, sent-
        but-unadmitted payloads are abandoned (their pages' work is
        redone — the disruption lands in their latency), and the
        decode replica's in-flight sequences lose their migrated pages
        exactly like a monolithic drain.  Arrival stamps are KEPT."""
        pe, de = self.prefill, self.decode
        left = pe.drain_unfinished()
        pe._handoff.clear()
        left += [meta["req"] for _p, meta in self._ready]
        self._ready.clear()
        left += de.drain_unfinished()
        return sorted(left, key=lambda r: r.arrival_s)

    # ---- record assembly ---------------------------------------------
    @property
    def token_streams(self) -> dict:
        """Per-request greedy streams, prefill-side TTFT token first —
        the token-parity surface against a monolithic engine's
        ``token_streams``."""
        out = {rid: list(toks)
               for rid, toks in self.prefill.token_streams.items()}
        for rid, toks in self.decode.token_streams.items():
            out.setdefault(rid, []).extend(toks)
        return out

    def engine_steps(self) -> int:
        return self.prefill.engine_steps + self.decode.engine_steps

    def global_meta(self, plan: ArrivalPlan) -> dict:
        from dlnetbench_tpu.parallel.mesh import (describe_mesh,
                                                  make_flat_mesh)
        cfg = self.cfg
        meta = self.decode.global_meta(plan)
        meta["world_size"] = cfg.world
        # COMPARABLE global (not in merge._VOLATILE_GLOBALS, by
        # design): a disaggregated record must never merge with a
        # monolithic one — the serving block's latency decomposition
        # means something different on each
        meta["disaggregated"] = True
        meta["serving_config"].update({
            "slots": cfg.slots,
            "disaggregate": True,
            "prefill_ranks": cfg.prefill_ranks,
            "decode_ranks": cfg.decode_ranks,
            "prefill_slots": self.prefill.cfg.slots,
            "decode_slots": self.decode.cfg.slots,
            "migration_chunk_pages": cfg.migration_chunk_pages,
        })
        meta["mesh"] = describe_mesh(
            make_flat_mesh(devices=self.devices))
        cm = dict(meta.get("compile_ms", {}))
        for k, v in self.prefill.meta.get("compile_ms", {}).items():
            cm[f"prefill_replica_{k}"] = v
        meta["compile_ms"] = cm
        return meta


def run_disagg(model_cfg: TransformerConfig, cfg: ServingConfig,
               plan: ArrivalPlan, *, fault_plan=None, params=None,
               devices=None, live_metrics=None):
    """One measured disaggregated serving run -> ``ProxyResult`` —
    the ``run_serving`` contract (warmup, fault segmentation, record
    stamping) over the two-replica server.

    Crash under policy ``shrink``: the victim rank identifies its
    replica by range (``rank < prefill_ranks`` is a prefill rank).
    The WHOLE server is rebuilt over the survivors with the dead
    rank's slot share removed from ITS replica only; unfinished
    requests re-queue with original arrival stamps and the migration
    stats of both segments fold into one record."""
    cfg.validate()
    if params is None:
        params = init_params(jax.random.key(0), model_cfg)
    server = DisaggServer(model_cfg, cfg, params=params,
                          devices=devices)
    if live_metrics is not None:
        server.decode.live = (
            live_metrics if hasattr(live_metrics, "maybe_emit")
            else M.LiveMetricsWriter(live_metrics))
    requests = plan.sample()
    if cfg.warmup_requests > 0:
        p_len = min(cfg.prefill_chunk + 1, cfg.max_seq_len - 2)
        warm = [Request(rid=-1 - i, arrival_s=0.0, prompt_len=p_len,
                        output_len=2)
                for i in range(cfg.warmup_requests)]
        with spans.span("warmup", what="disagg engines",
                        reps=len(warm)):
            server.run(warm)
    injector = None
    if fault_plan is not None:
        from dlnetbench_tpu.faults.inject import FaultInjector
        fault_plan.validate()
        injector = FaultInjector(fault_plan, world=cfg.world)

    meta = server.global_meta(plan)
    extra: dict = {}
    try:
        with spans.span("serving_run", requests=len(requests)):
            completed, wall = server.run(requests, injector=injector)
        final = server
    except Exception as e:
        # the shared crash-shrink head (serving/requeue.py): detection
        # stamp, fault trigger, survivor set — re-raises non-shrinkable
        # faults.  The replica tag rides the trigger as caller detail.
        detection_ms, survivors = requeue.detect_shrink(
            e, injector=injector, fault_plan=fault_plan,
            world=cfg.world, step=server.engine_steps(),
            detail={"replica": ("prefill"
                                if (getattr(e, "rank", 0) or 0)
                                < cfg.prefill_ranks else "decode")})
        p_surv = [r for r in survivors if r < cfg.prefill_ranks]
        d_surv = [r for r in survivors if r >= cfg.prefill_ranks]
        if not p_surv or not d_surv:
            # a disaggregated run needs BOTH phases alive — losing a
            # whole replica is unrecoverable under shrink
            raise
        leftovers = requeue.requeue_unfinished(server)
        done0 = server.prefill.completed + server.decode.completed
        t_origin = server.prefill._t0
        steps0 = server.engine_steps()
        occ0 = list(server.decode._occupancy_samples)
        qmax0 = server.prefill.queue_depth_max
        peak0 = server.decode.concurrent_peak
        sends0 = list(server.channel._sends)
        legs0 = (list(server.channel._compute_solo_s),
                 list(server.channel._both_s))
        p_slots = (server.prefill.cfg.slots // cfg.prefill_ranks
                   * len(p_surv))
        d_slots = (server.decode.cfg.slots // cfg.decode_ranks
                   * len(d_surv))
        t0 = time.monotonic()
        shrunk = dataclasses.replace(
            cfg, world=len(survivors), prefill_ranks=len(p_surv),
            decode_ranks=len(d_surv), slots=d_slots)
        with spans.span("serving_rebuild", survivors=len(survivors)):
            server2 = DisaggServer(
                model_cfg, shrunk, params=params,
                devices=[server.devices[r] for r in survivors],
                prefill_slots=p_slots, decode_slots=d_slots)
        server2.decode.live = server.decode.live
        recovery_ms = (time.monotonic() - t0) * 1e3
        done1, wall = requeue.run_requeued(
            server2, leftovers, injector=injector, t_origin=t_origin)
        completed = done0 + done1
        final = server2
        final.decode.engine_steps += steps0
        final.decode._occupancy_samples = \
            occ0 + final.decode._occupancy_samples
        final.prefill.queue_depth_max = max(
            qmax0, final.prefill.queue_depth_max)
        final.decode.concurrent_peak = max(
            peak0, final.decode.concurrent_peak)
        # both segments' migrations are ONE run's wire traffic
        final.channel._sends[:0] = sends0
        final.channel._compute_solo_s[:0] = legs0[0]
        final.channel._both_s[:0] = legs0[1]
        meta["mesh"] = server2.global_meta(plan)["mesh"]
        extra = {"detection_ms": round(detection_ms, 3),
                 "recovery_ms": round(recovery_ms, 3),
                 "degraded_world": survivors,
                 "degraded_slots": d_slots}

    moe_blk = final.decode.moe_block()
    if moe_blk is not None:
        meta["moe"] = moe_blk
    meta["serving"] = M.serving_block(
        completed, plan, slo_ttft_ms=cfg.slo_ttft_ms,
        slo_tpot_ms=cfg.slo_tpot_ms, wall_s=wall,
        engine_steps=final.engine_steps(),
        cache_stats=final.decode.cache.stats(),
        queue_depth_max=final.prefill.queue_depth_max,
        batch_occupancy_mean=final.decode.batch_occupancy_mean(),
        decode_loop=final.decode.decode_loop_block(),
        admitted_peak=final.decode.concurrent_peak,
        migration=final.channel.stats_block())
    if fault_plan is not None:
        meta["fault_plan"] = fault_plan.to_dict()
        meta["fault_policy"] = fault_plan.policy
        meta["fault_injected_delay_us"] = round(
            injector.injected_delay_us, 1)
    meta.update(extra)
    return M.build_result(completed, plan, meta)
