"""Serving tier: paged-KV decode, continuous batching, latency-vs-load.

Everything else in this repo benchmarks *training* — step time, busbw,
overlap, goodput under faults.  The north star serves "heavy traffic
from millions of users", and the serving schedule worth reproducing is
the Orca/vLLM line: a prefill/decode split transformer over a paged KV
cache, continuously batched under an open-loop arrival process, judged
by latency percentiles vs offered load instead of step time.

Modules:

* ``arrivals``  — ``ArrivalPlan``: the committable JSON traffic schema
  (poisson / bursty / replay; seeded splitmix64 draws), deliberately
  mirroring ``faults/plan.py`` so traffic plans are artifacts.
* ``kv_cache``  — block-table paged KV cache (allocate/append/free
  pages, occupancy + fragmentation stats) with the Pallas
  ``paged_attention`` decode path on TPU, a dense gather-attention
  fallback everywhere else, and a ``shard_map`` wrapper sharding along
  GQA KV heads.
* ``decode``    — the decode-path transformer: one AOT-compiled
  single-token decode step + one chunked prefill program, sharing
  ``models/transformer`` weights — and the ISSUE 11 fused loop:
  ``make_multi_step_decode`` runs N decode steps inside ONE compiled
  ``lax.while_loop`` with slot state device-resident.
* ``speculative`` — self-drafting speculative decode inside the fused
  loop (ngram-table or truncated-layer drafter, one batched verify
  pass, on-device greedy acceptance — lossless, parity-locked).
* ``device_state`` — the host/device state split: packed device slot
  state with a priced, loudly-guarded host<->device sync contract.
* ``scheduler`` — the continuous-batching engine loop (admit from the
  queue into free decode slots each step, evict on finish, prefill
  inline-chunked or as a separate phase) plus the fault-composed run
  (straggler delays inflate measured latency; crash+shrink loses
  capacity, re-queues in-flight work, and prices recovery).
* ``metrics``   — per-request TTFT/TPOT/e2e, p50/p95/p99, tokens/s and
  goodput-at-SLO, emitted through the existing record schema v2 (a
  ``serving`` global block + per-request timer arrays riding
  ``metrics/emit`` -> ``parser`` -> ``merge`` ->
  ``analysis/bandwidth``).

docs/SERVING.md documents the knobs, the plan schema and the SLO
metric definitions.
"""
from dlnetbench_tpu.serving.arrivals import ArrivalPlan, Request  # noqa: F401
