"""Fleet-scale serving: N engine replicas behind one seeded router.

The capacity scaling axis the single-engine tier stops short of
(ISSUE 18): a front-end ``Router`` (serving/router.py) over N
INDEPENDENT ``Engine`` replicas — each over its own disjoint device
subset with its own page pool and its own untouched admission control —
driven by one host loop from one shared ``ArrivalPlan``.  Per-replica
scheduling stays bit-identical to ``run_serving``'s engine; the fleet
adds exactly one decision (which replica's queue a request joins) and
measures what that decision costs/buys at equal chips.

The driver mirrors ``disagg.DisaggServer``: every replica's programs
and pools are built under its own device, the loop dispatches all
replicas' decode programs before fencing any of them (the cross-device
overlap a real fleet gets for free), and all replicas share ONE clock
origin so every stamp lives on one timeline.

Elastic capacity (``FleetConfig.autoscale``): an SLO autoscaler watches
the same rolling windowed signals the flight recorder uses
(``serving/metrics.rolling_slo_breach`` over pooled recent completions,
plus raw queue pressure) and resizes the fleet mid-run.  Scale-down
drains the lightest replica through the shared preempt arc
(serving/requeue.py — in-flight requests re-queue with their ORIGINAL
arrival stamps) and retires its devices: wall time spent retired is
chip-seconds SAVED, the denominator win the diurnal study prices.
Scale-up rebuilds the replica's engine with the recompile priced into
the scale event's ``scale_up_ms`` — the p99 blip at each scale event is
measured, not assumed.  A replica crash (``FaultPlan`` crash/preempt
under policy ``shrink``, one fault rank per replica) takes the same
drain arc with no rebuild: the router simply stops offering the dead
replica and the survivors absorb the re-queued work.

Record shape: the ``fleet`` global is a VOLATILE measurement block
(per-replica request counts, the routing load histogram, affinity hit
rate, scale events, chip-second accounting); ``fleet_routing`` and
``fleet_replicas`` are COMPARABLE globals — records routed by different
policies, or over different fleet widths, must never merge
(metrics/merge.py), exactly like mismatched fault plans.
"""
from __future__ import annotations

import dataclasses
import time
from collections import deque

import jax

from dlnetbench_tpu.metrics import spans
from dlnetbench_tpu.models.transformer import (TransformerConfig,
                                               init_params)
from dlnetbench_tpu.serving import decode as D
from dlnetbench_tpu.serving import metrics as M
from dlnetbench_tpu.serving import requeue
from dlnetbench_tpu.serving.arrivals import ArrivalPlan, Request
from dlnetbench_tpu.serving.router import ROUTING_POLICIES, Router
from dlnetbench_tpu.serving.scheduler import Engine, ServingConfig


@dataclasses.dataclass
class FleetConfig:
    """Fleet-level knobs (docs/SERVING.md 'Fleet serving')."""
    replicas: int = 2            # engine replicas (each gets its own
    #                              cfg.world-device subset + page pool)
    routing: str = "round_robin"  # serving/router.ROUTING_POLICIES
    route_seed: int = 0          # the router's splitmix64 stream
    autoscale: bool = False      # elastic capacity (diurnal studies)
    min_replicas: int = 1        # autoscale floor — never drain below
    scale_window_s: float = 0.5  # breach window + idle-tick cadence
    scale_idle_frac: float = 0.25  # scale down when accepted work /
    #                                total slots falls below this (and
    #                                no routed backlog remains)
    scale_cooldown_s: float = 1.0  # min seconds between scale actions
    #                                (flap damping; the clock starts at
    #                                run start, so an idle fleet cannot
    #                                scale down before traffic arrives)

    def validate(self) -> "FleetConfig":
        if self.replicas < 1:
            raise ValueError(f"fleet: replicas must be >= 1, got "
                             f"{self.replicas}")
        if self.routing not in ROUTING_POLICIES:
            raise ValueError(f"fleet: unknown routing "
                             f"{self.routing!r} (one of "
                             f"{ROUTING_POLICIES})")
        if not 1 <= self.min_replicas <= self.replicas:
            raise ValueError(
                f"fleet: min_replicas {self.min_replicas} must be in "
                f"[1, replicas={self.replicas}]")
        if self.autoscale and self.replicas < 2:
            raise ValueError(
                "fleet: autoscale needs replicas >= 2 — a one-replica "
                "fleet has nothing to drain or rebuild")
        for name in ("scale_window_s", "scale_cooldown_s"):
            if not getattr(self, name) > 0:
                raise ValueError(f"fleet: {name} must be > 0")
        if not 0.0 < self.scale_idle_frac < 1.0:
            raise ValueError(
                f"fleet: scale_idle_frac must be in (0, 1), got "
                f"{self.scale_idle_frac}")
        return self


class FleetServer:
    """N independent engines, one router, one clock.  One instance
    drives ONE measured run (plus per-engine warmup) — replicas retired
    by a crash stay retired, like ``run_serving`` builds a fresh engine
    per run."""

    def __init__(self, model_cfg: TransformerConfig, cfg: ServingConfig,
                 fleet: FleetConfig, *, params=None, devices=None):
        self.model_cfg = model_cfg
        self.cfg = cfg.validate()
        self.fleet = fleet.validate()
        if cfg.disaggregate:
            raise ValueError(
                "serving: fleet replicas are monolithic engines — "
                "disaggregate + fleet has no stated device-budget "
                "split (route to run_disagg OR run_fleet, not both)")
        if fleet.routing == "prefix_affinity" and not cfg.prefix_sharing:
            raise ValueError(
                "fleet: prefix_affinity consults each replica's radix "
                "trie — it requires prefix_sharing=True (without "
                "sharing every probe returns 0 and the policy is just "
                "a slower p2c)")
        need = fleet.replicas * cfg.world
        devs = (list(devices) if devices is not None
                else jax.devices()[:need])
        if len(devs) < need:
            raise ValueError(
                f"fleet: {fleet.replicas} replicas x world {cfg.world} "
                f"need {need} devices, have {len(devs)}")
        self._params = (params if params is not None
                        else init_params(jax.random.key(0), model_cfg))
        self._replica_devices = [devs[r * cfg.world:(r + 1) * cfg.world]
                                 for r in range(fleet.replicas)]
        self.devices = devs[:need]
        self.engines: list[Engine | None] = []
        for r in range(fleet.replicas):
            self.engines.append(self._build_engine(r))
        self.router = Router(fleet.routing, fleet.replicas,
                             seed=fleet.route_seed)
        self.live = None          # fleet-level LiveMetricsWriter: the
        #                           engines' own .live stays None so ONE
        #                           stream serves all replicas, each
        #                           line stamped with its replica_id
        self._prompt_memo: dict[int, object] = {}
        self._parked: dict[int, Engine] = {}   # warm standby pool:
        #   autoscaler retirees keep their COMPILED programs + resident
        #   weights; scale-up revives (host-state reset) instead of
        #   recompiling.  Crash-dead replicas never park — their chips
        #   are gone, and a post-crash rebuild pays the full compile.
        self.scale_events: list[dict] = []

    def _build_engine(self, r: int) -> Engine:
        """One replica's engine, programs and pools built UNDER its
        device set; the weights are copied once per replica (same
        values — token parity with a single engine is unaffected)."""
        devs = self._replica_devices[r]
        with jax.default_device(devs[0]):
            e = Engine(self.model_cfg, self.cfg,
                       params=jax.device_put(self._params, devs[0]),
                       devices=devs)
        e.replica_id = r   # rides the live-metrics stream (ISSUE 18)
        return e

    def _ctx(self, r: int):
        return jax.default_device(self._replica_devices[r][0])

    def _active_ix(self) -> list[int]:
        return [r for r, e in enumerate(self.engines) if e is not None]

    # ---- the driver loop ---------------------------------------------
    def run(self, requests: list[Request], *, injector=None,
            fault_plan=None, t_origin: float | None = None
            ) -> tuple[list[M.Completed], float]:
        """Drive the fleet until every request completes; returns
        ``(completed, wall_s)``.  ``fault_plan`` rides along for the
        in-loop crash arc (fleet world = one fault rank per replica)."""
        cfg = self.cfg
        for r in requests:
            if r.prompt_len + r.output_len > cfg.max_seq_len:
                raise ValueError(
                    f"serving: request {r.rid} needs "
                    f"{r.prompt_len + r.output_len} tokens > "
                    f"max_seq_len {cfg.max_seq_len}")
        for r in range(self.fleet.replicas):
            if self.engines[r] is None:
                # a fresh run starts at FULL strength: replicas the
                # previous run's autoscaler (or crash) retired are
                # revived from the warm pool (or rebuilt), exactly
                # like run_serving builds a fresh engine per run
                self.engines[r] = self._parked.pop(
                    r, None) or self._build_engine(r)
        for i in self._active_ix():
            with self._ctx(i):
                self.engines[i]._reset_state()
        self.router.reset()
        self._prompt_memo.clear()
        self.scale_events = []
        self._retired_completed: list[M.Completed] = []
        self._retired_streams: dict[int, list[int]] = {}
        self._retired_steps = 0
        self._retired_occupancy: list[int] = []
        self._retired_stats: dict[int, dict] = {}
        self._standby: list[int] = []   # scale-down retirees, can return
        self.queue_depth_max = 0
        self.concurrent_peak = 0
        R = self.fleet.replicas
        self._used_s = [0.0] * R       # serving intervals, engine clock
        self._saved_s = [0.0] * R      # retired-by-autoscaler intervals
        self._active_from: list[float | None] = [
            0.0 if self.engines[r] is not None else None
            for r in range(R)]
        self._idle_from: list[float | None] = [None] * R
        self._queue: deque[Request] = deque(
            sorted(requests, key=lambda r: (r.arrival_s, r.rid)))
        self._t0 = time.monotonic() if t_origin is None else t_origin
        for i in self._active_ix():
            self.engines[i]._t0 = self._t0
        self._last_scale_s = 0.0
        if self.live is not None:
            self.live.reset_run()

        while self._queue or self._any_engine_work():
            now = self._now()
            try:
                if injector is not None:
                    injector.before_step()  # faults land INSIDE the loop
            except Exception as e:
                self._on_fault(e, injector, fault_plan, now)
                continue
            self._autoscale_tick(now)
            self._route_due(now)
            active = self._active_ix()
            for i in active:
                with self._ctx(i):
                    self.engines[i]._admit_arrivals(now)
            self.concurrent_peak = max(
                self.concurrent_peak,
                sum(1 for i in active for s in self.engines[i].slots
                    if s is not None))
            if not self._any_slot_work():
                # fleet idle: sleep to the next arrival (open loop),
                # but keep waking at the autoscaler cadence so a
                # diurnal trough still gets its scale-down ticks
                if self._queue:
                    dt = self._queue[0].arrival_s - self._now()
                    if self.fleet.autoscale:
                        dt = min(dt, self.fleet.scale_window_s)
                    if dt > 0:
                        time.sleep(dt)
                continue
            self._step_all(active)
            if self.live is not None:
                now2 = self._now()
                for i in self._active_ix():
                    self.live.maybe_emit(self.engines[i], now2)
        wall = self._now()
        for r in range(R):
            if self._active_from[r] is not None:
                self._used_s[r] += wall - self._active_from[r]
                self._active_from[r] = None
            if self._idle_from[r] is not None:
                self._saved_s[r] += wall - self._idle_from[r]
                self._idle_from[r] = None
        completed = sorted(self._all_completed(),
                           key=lambda c: c.finish_s)
        return completed, wall

    def _now(self) -> float:
        return time.monotonic() - self._t0

    def _any_engine_work(self) -> bool:
        return any(e.queue or e.pending
                   or any(s is not None for s in e.slots)
                   for e in self.engines if e is not None)

    def _any_slot_work(self) -> bool:
        return any(e.pending or any(s is not None for s in e.slots)
                   for e in self.engines if e is not None)

    def _route_due(self, now: float) -> None:
        """Pop every due arrival off the fleet queue and hand it to the
        router's pick — the ONE fleet-level decision.  The chosen
        replica's own admission control takes it from there."""
        affinity = self.fleet.routing == "prefix_affinity"
        while self._queue and self._queue[0].arrival_s <= now:
            req = self._queue.popleft()
            toks = self._prompt_of(req) if affinity else None
            r = self.router.pick(req, self.engines, self._active_ix(),
                                 prompt_tokens=toks)
            self.engines[r].queue.append(req)
        backlog = sum(len(e.queue) + len(e.pending)
                      for e in self.engines if e is not None)
        self.queue_depth_max = max(self.queue_depth_max, backlog)

    def _prompt_of(self, req: Request):
        toks = self._prompt_memo.get(req.rid)
        if toks is None:
            toks = D.prompt_tokens_for(req, self.model_cfg.vocab_size)
            self._prompt_memo[req.rid] = toks
        return toks

    def _step_all(self, active: list[int]) -> None:
        """One fleet step: dispatch every working replica's decode
        program, THEN fence them in dispatch order — while one
        replica's program runs on its device, the others' dispatches
        (and inline prefill chunks) run on theirs, the cross-device
        overlap the disagg driver pioneered, N-wide."""
        inflight = []
        for i in active:
            e = self.engines[i]
            if not (e.pending
                    or any(s is not None for s in e.slots)):
                continue
            tele_on = e._tele is not None or self.live is not None
            t_w = time.perf_counter()
            sync0 = (e.dstate.sync_total_us()
                     if tele_on and e.dstate is not None else 0.0)
            with self._ctx(i):
                ctx = e._step_dispatch()
            inflight.append((i, e, ctx, tele_on, t_w, sync0))
        for i, e, ctx, tele_on, t_w, sync0 in inflight:
            with self._ctx(i):
                e._step_complete(ctx)
            if tele_on:
                e._sample_step((time.perf_counter() - t_w) * 1e6,
                               sync0)

    # ---- elastic capacity --------------------------------------------
    def _autoscale_tick(self, now: float) -> None:
        """One control decision per cooldown window: scale UP when the
        pooled rolling SLO window breaches or the routed backlog
        exceeds the active slot capacity (and a standby replica
        exists); scale DOWN when accepted work sits below the idle
        fraction of capacity with nothing routed and waiting."""
        if not self.fleet.autoscale:
            return
        if now - self._last_scale_s < self.fleet.scale_cooldown_s:
            return
        active = self._active_ix()
        if not active:
            return
        cap = sum(self.engines[i].cfg.slots for i in active)
        load = sum(Router.load_score(self.engines[i]) for i in active)
        if self._standby:
            recent: list[M.Completed] = []
            for i in active:
                recent += self.engines[i].completed[-32:]
            recent.sort(key=lambda c: c.finish_s)
            breach = M.rolling_slo_breach(
                recent, slo_ttft_ms=self.cfg.slo_ttft_ms,
                slo_tpot_ms=self.cfg.slo_tpot_ms, now_s=now,
                window_s=self.fleet.scale_window_s)
            if breach is not None or load > cap:
                self._scale_up(now, reason=("slo_breach"
                                            if breach is not None
                                            else "queue_pressure"))
                return
        # "nothing routed and waiting" means DUE work: a diurnal
        # trough holds the whole next peak in the fleet queue as
        # future arrivals, and those must not pin idle capacity
        due = bool(self._queue) and self._queue[0].arrival_s <= now
        if (len(active) > self.fleet.min_replicas
                and not due
                and load < self.fleet.scale_idle_frac * cap):
            self._scale_down(now)

    def _scale_down(self, now: float) -> None:
        """Drain the lightest-loaded replica through the shared
        preempt arc and retire its devices: in-flight work re-queues
        with ORIGINAL stamps (the disruption lands in its latency),
        and every retired second is a chip-second saved."""
        active = self._active_ix()
        victim = min(active,
                     key=lambda r: (Router.load_score(self.engines[r]),
                                    r))
        t0 = time.perf_counter()
        leftovers = requeue.requeue_unfinished(self.engines[victim])
        drain_ms = (time.perf_counter() - t0) * 1e3
        self._requeue_to_fleet(leftovers)
        self._retire(victim, now, dead=False)
        self._standby.append(victim)
        self.scale_events.append({
            "t_s": round(now, 4), "kind": "scale_down",
            "replica": victim, "requeued": len(leftovers),
            "drain_ms": round(drain_ms, 3)})
        self._last_scale_s = now

    def _scale_up(self, now: float, *, reason: str) -> None:
        """Bring a standby replica back — from the WARM pool when the
        autoscaler parked it (compiled programs + resident weights
        survive retirement; revival is a host-state reset), or a cold
        rebuild when it never parked.  Either way the spin-up is
        priced into the scale event (``scale_up_ms``), because elastic
        capacity that hides its spin-up cost would overstate the
        autoscaler's win exactly the way an unpriced recovery would
        overstate a fault policy's."""
        r = self._standby.pop(0)
        t0 = time.perf_counter()
        warm = self._parked.pop(r, None)
        with spans.span("fleet_scale_up", replica=r,
                        warm=warm is not None):
            if warm is not None:
                e = warm
                with self._ctx(r):
                    e._reset_state()
            else:
                e = self._build_engine(r)
        scale_up_ms = (time.perf_counter() - t0) * 1e3
        e._t0 = self._t0           # the shared timeline
        self.engines[r] = e
        if self._idle_from[r] is not None:
            self._saved_s[r] += now - self._idle_from[r]
            self._idle_from[r] = None
        self._active_from[r] = now  # the rebuild wall counts as USED:
        #                             those chips were compiling, not
        #                             saving anything
        self.scale_events.append({
            "t_s": round(now, 4), "kind": "scale_up", "replica": r,
            "scale_up_ms": round(scale_up_ms, 3), "reason": reason,
            "warm": warm is not None})
        self._last_scale_s = self._now()

    def _retire(self, r: int, now: float, *, dead: bool) -> None:
        """Take replica ``r`` out of the fleet, folding its run stats
        into the retired accumulators (its engine object is dropped —
        pools freed).  ``dead`` replicas (crashes) accrue NEITHER used
        nor saved chip-seconds after retirement; autoscaler retirees
        accrue saved time until rebuilt."""
        e = self.engines[r]
        self._retired_completed += e.completed
        for rid, toks in e.token_streams.items():
            self._retired_streams.setdefault(rid, []).extend(toks)
        self._retired_steps += e.engine_steps
        self._retired_occupancy += e._occupancy_samples
        self._retired_stats[r] = e.cache.stats()
        if self._active_from[r] is not None:
            self._used_s[r] += now - self._active_from[r]
            self._active_from[r] = None
        self._idle_from[r] = None if dead else now
        if not dead:
            self._parked[r] = e   # warm pool: programs stay compiled
        self.engines[r] = None

    # ---- fault segmentation ------------------------------------------
    def _on_fault(self, e: BaseException, injector, fault_plan,
                  now: float) -> None:
        """A scripted crash/preempt under policy shrink takes whole
        REPLICAS down (fleet world = one fault rank per replica): the
        victims drain through the shared re-queue arc, the router stops
        offering them, and the survivors — never rebuilt, never
        resurrected — absorb the re-queued work.  Re-raises when no
        active replica survives (or the fault is not this arc's)."""
        detection_ms, survivors = requeue.detect_shrink(
            e, injector=injector, fault_plan=fault_plan,
            world=self.fleet.replicas, step=self.engine_steps(),
            detail={"scope": "fleet"})
        surv = set(survivors)
        if not any(r in surv for r in self._active_ix()):
            raise e
        for v in range(self.fleet.replicas):
            if v in surv:
                continue
            if v in self._standby:
                self._standby.remove(v)   # dead chips never scale up
            if self.engines[v] is None:
                continue
            leftovers = requeue.requeue_unfinished(self.engines[v])
            self._requeue_to_fleet(leftovers)
            self._retire(v, now, dead=True)
            self.scale_events.append({
                "t_s": round(now, 4), "kind": "replica_crash",
                "replica": v, "requeued": len(leftovers),
                "detection_ms": round(detection_ms, 3)})

    def _requeue_to_fleet(self, leftovers: list[Request]) -> None:
        """Drained requests rejoin the FLEET queue with their original
        (past) stamps — the very next ``_route_due`` offers them to the
        surviving replicas, which is the router-retry the crash study
        measures."""
        self._queue = deque(sorted(
            list(leftovers) + list(self._queue),
            key=lambda r: (r.arrival_s, r.rid)))

    # ---- record assembly ---------------------------------------------
    @property
    def token_streams(self) -> dict:
        """Per-request greedy streams merged across replicas (rids are
        disjoint by construction — a request lives on one replica at a
        time) — the token-parity surface against a single engine."""
        out = {rid: list(toks)
               for rid, toks in self._retired_streams.items()}
        for e in self.engines:
            if e is None:
                continue
            for rid, toks in e.token_streams.items():
                out.setdefault(rid, []).extend(toks)
        return out

    def _all_completed(self) -> list[M.Completed]:
        done = list(self._retired_completed)
        for e in self.engines:
            if e is not None:
                done += e.completed
        return done

    def engine_steps(self) -> int:
        return self._retired_steps + sum(
            e.engine_steps for e in self.engines if e is not None)

    def batch_occupancy_mean(self) -> float:
        samples = list(self._retired_occupancy)
        for e in self.engines:
            if e is not None:
                samples += e._occupancy_samples
        if not samples:
            return 0.0
        return sum(samples) / len(samples)

    def replica_cache_stats(self) -> list[dict | None]:
        """Final per-replica pool stats (retired replicas' snapshots
        taken at retirement) — the per-replica trie hit rates the
        affinity study's artifact reports."""
        out: list[dict | None] = []
        for r in range(self.fleet.replicas):
            e = self.engines[r]
            if e is not None:
                out.append(e.cache.stats())
            else:
                out.append(self._retired_stats.get(r))
        return out

    def chip_seconds(self) -> tuple[float, float]:
        """(used, saved), device-weighted: every second a replica was
        serving (or rebuilding) x its device count, and every second
        the autoscaler kept it retired x the same."""
        ndev = len(self._replica_devices[0])
        used = sum(self._used_s) * ndev
        saved = sum(self._saved_s) * ndev
        return used, saved

    def fleet_block(self, completed: list[M.Completed]) -> dict:
        """The record's ``fleet`` global: VOLATILE measurements (live
        load scores, scale timings and chip-second spend depend on the
        host), pooled at merge like every measurement block."""
        used, saved = self.chip_seconds()
        slo_ok = sum(1 for c in completed
                     if M.meets_slo(c, self.cfg.slo_ttft_ms,
                                    self.cfg.slo_tpot_ms))
        rstats = self.replica_cache_stats()
        block = {
            "replicas": self.fleet.replicas,
            "routing": self.fleet.routing,
            "route_seed": self.fleet.route_seed,
            "requests_per_replica": list(self.router.counts),
            "load_histogram": self.router.load_histogram(),
            "scale_events": list(self.scale_events),
            "chip_seconds_used": round(used, 4),
            "chip_seconds_saved": round(saved, 4),
            "slo_goodput_per_chip_s": (round(slo_ok / used, 4)
                                       if used > 0 else 0.0),
            "queue_depth_max": self.queue_depth_max,
        }
        if self.fleet.routing == "prefix_affinity":
            block["affinity_hit_rate"] = self.router.affinity_hit_rate()
            block["affinity_bounces"] = self.router.affinity_bounces
            # migration-free reuse: prefix tokens served off pages the
            # request was ROUTED to (no cross-replica page motion — the
            # win over a policy-blind fleet with per-replica tries)
            block["prefix_reuse_tokens"] = \
                self.router.prefix_reuse_tokens
            block["replica_prefix_hit_rate"] = [
                (s.get("prefix", {}).get("hit_rate", 0.0)
                 if s else None) for s in rstats]
        return block

    def global_meta(self, plan: ArrivalPlan) -> dict:
        from dlnetbench_tpu.parallel.mesh import (describe_mesh,
                                                  make_flat_mesh)
        first = next(e for e in self.engines if e is not None)
        meta = first.global_meta(plan)
        meta["world_size"] = self.fleet.replicas * self.cfg.world
        meta["mesh"] = describe_mesh(
            make_flat_mesh(devices=self.devices))
        # COMPARABLE globals (not in merge._VOLATILE_GLOBALS, by
        # design): the routing policy and fleet width are run identity
        # — a p2c record must never merge with a round_robin one, and
        # a 2-replica fleet never with a 4-replica one (the serving
        # block's latencies answer different questions)
        meta["fleet_routing"] = self.fleet.routing
        meta["fleet_replicas"] = self.fleet.replicas
        return meta


def run_fleet(model_cfg: TransformerConfig, cfg: ServingConfig,
              plan: ArrivalPlan, fleet: FleetConfig | None = None, *,
              fault_plan=None, params=None, devices=None,
              live_metrics=None):
    """One measured fleet run -> ``ProxyResult`` (-> ``metrics.emit``).

    Every replica is warmed DIRECTLY (its own synthetic mini-workload,
    discarded) before the measured run — warmup must not ride the
    router's seeded stream, or the measured assignment sequence would
    shift with the warmup count."""
    fleet = (fleet if fleet is not None else FleetConfig()).validate()
    server = FleetServer(model_cfg, cfg, fleet, params=params,
                         devices=devices)
    if live_metrics is not None:
        server.live = (live_metrics if hasattr(live_metrics,
                                               "maybe_emit")
                       else M.LiveMetricsWriter(live_metrics))
    requests = plan.sample()
    if cfg.warmup_requests > 0:
        p_len = min(cfg.prefill_chunk + 1, cfg.max_seq_len - 2)
        warm = [Request(rid=-1 - i, arrival_s=0.0, prompt_len=p_len,
                        output_len=2)
                for i in range(cfg.warmup_requests)]
        with spans.span("warmup", what="serving fleet",
                        reps=len(warm) * fleet.replicas):
            for i in server._active_ix():
                with server._ctx(i):
                    server.engines[i].run(warm)
    injector = None
    if fault_plan is not None:
        from dlnetbench_tpu.faults.inject import FaultInjector
        fault_plan.validate()
        # fleet fault geometry: ONE fault rank per replica — a crash
        # rank r kills replica r whole (its engine is the capacity unit
        # at this tier, like world ranks are the engine's)
        injector = FaultInjector(fault_plan, world=fleet.replicas)

    meta = server.global_meta(plan)
    with spans.span("serving_run", requests=len(requests)):
        completed, wall = server.run(requests, injector=injector,
                                     fault_plan=fault_plan)
    meta["serving"] = M.serving_block(
        completed, plan, slo_ttft_ms=cfg.slo_ttft_ms,
        slo_tpot_ms=cfg.slo_tpot_ms, wall_s=wall,
        engine_steps=server.engine_steps(),
        queue_depth_max=server.queue_depth_max,
        batch_occupancy_mean=server.batch_occupancy_mean(),
        admitted_peak=server.concurrent_peak)
    meta["fleet"] = server.fleet_block(completed)
    if cfg.prefix_sharing:
        # pooled across replicas: per-POOL rates live in the fleet
        # block's replica_prefix_hit_rate; these globals keep the
        # single-engine meaning (volatile at merge, ISSUE 12)
        hits = admits = saved = 0
        for s in server.replica_cache_stats():
            if not s:
                continue
            p = s.get("prefix", {})
            hits += p.get("hits", 0)
            saved += p.get("bytes_saved", 0)
            admits += s.get("admissions", 0)
        meta["prefix_hit_rate"] = round(hits / max(admits, 1), 4)
        meta["prefix_bytes_saved"] = saved
    if fault_plan is not None:
        meta["fault_plan"] = fault_plan.to_dict()
        meta["fault_policy"] = fault_plan.policy
        meta["fault_injected_delay_us"] = round(
            injector.injected_delay_us, 1)
    return M.build_result(completed, plan, meta)
