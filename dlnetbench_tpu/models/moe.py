"""Expert-parallel MoE core (ISSUE 15): seeded deterministic routing,
capacity/drop accounting, routing stats, and the grouped expert FFN.

The reference's ``hybrid_3d_moe`` proxy replays all-to-all VOLUMES; the
SPMD tier (models/spmd.py) has run the GShard capacity dispatch with
real math since the seed — but its token-drop rule was per-rank arrival
order, so the set of dropped tokens depended on how the batch happened
to be sharded.  This module makes routing a first-class, certifiable
schedule:

* **Seeded grouped token-drop** — capacity is enforced per GROUP of
  ``group_tokens`` consecutive tokens in canonical (batch-row,
  sequence) order, and within a group the dispatch queue order is a
  seeded splitmix-style priority over GLOBAL token ids instead of
  arrival order.  Because a group never straddles a shard boundary
  (``group_tokens`` must divide the sequence shard), the kept/dropped
  set is a pure function of ``(tokens, router weights, seed,
  group_tokens)`` — IDENTICAL across shard counts, which is what lets
  the dryrun certify token-identical routing between sharded and
  single-device execution (the acceptance bar the arrival-order rule
  could never meet).  ``drop_seed=None`` + one group delegates to
  ``layers.moe_dispatch`` — bit-identical legacy behavior.
* **Drop closed form** — ``expected_drops`` states the capacity
  arithmetic (``sum_e,g max(0, n_ge - cap_g)``) the property tests pin
  the measured drop counts against.
* **Routing stats** — per-expert load, drop rate and router entropy as
  in-graph arrays (``dispatch(..., with_stats=True)``) plus the
  ``stats_globals`` formatter that shapes them as record globals
  (hoisted by ``metrics/parser.py``, volatile at merge like every
  measured quantity).
* **Grouped expert FFN** — ``moe_grouped`` runs the sparse MoE through
  the Pallas grouped-matmul kernels (ops/grouped_matmul.py): per-expert
  token batching with count-aware block skipping and the PR-3 int8/fp8
  VMEM-prologue quantization recipes.
* **Schedule twin** — ``a2a_elems_per_rank`` mirrors the native
  schedule's all-to-all message arithmetic
  (``core/schedule.moe_schedule``), so the native-vs-SPMD MoE parity
  test compares one formula against the twin's ACTUAL dispatch buffer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from dlnetbench_tpu.models import layers as L

_F32 = jnp.float32


# ----------------------------------------------------------- priority
def token_priority(seed: int, gids):
    """Seeded per-token drop priority: a 32-bit murmur3-style finalizer
    over the GLOBAL token id, xor-folded with the seed.  Pure function
    of (seed, gid) — the same token gets the same priority on every
    rank of every mesh, which is the whole point."""
    h = gids.astype(jnp.uint32) ^ jnp.uint32((seed * 0x9E3779B9)
                                             & 0xFFFFFFFF)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def group_capacity(group_tokens: int, top_k: int, num_experts: int,
                   capacity_factor: float) -> int:
    """Per-(group, expert) dispatch slots — the ONE capacity spelling
    (``layers.moe_dispatch`` uses the same arithmetic at group =
    the whole batch)."""
    return max(1, int(capacity_factor * group_tokens * top_k
                      / num_experts))


def expected_drops(counts, cap_g: int):
    """The capacity-factor closed form: tokens routed beyond their
    (group, expert) capacity.  ``counts``: [G, E] routed-assignment
    histogram.  The property tests pin measured drops to this."""
    over = jnp.maximum(counts - cap_g, 0)
    return jnp.sum(over)


# ----------------------------------------------------------- dispatch
def dispatch(x2d, w_router, num_experts: int, top_k: int,
             capacity_factor: float, *, drop_seed: int | None = None,
             group_tokens: int = 0, gids=None, with_stats: bool = False):
    """Capacity-based token dispatch with seeded grouped token-drop.

    Returns ``(xe [E, C_total, d], disp [T, E, C_total], gate [T, E])``
    (+ ``stats`` with ``with_stats``) — the ``layers.moe_dispatch``
    contract with the expert buffer subdivided into per-group capacity
    blocks (``C_total = G * cap_g``).

    * ``group_tokens = 0`` (one group) + ``drop_seed = None`` is the
      LEGACY path — it delegates to ``layers.moe_dispatch`` outright,
      bit-identical to the pre-ISSUE-15 harness.
    * ``drop_seed`` set: within each group the dispatch queue order is
      the seeded priority over ``gids`` (global token ids; defaults to
      ``arange(T)`` for single-device callers) instead of arrival
      order.
    * ``group_tokens > 0``: capacity is per group of that many
      CONSECUTIVE tokens; T must divide evenly.  Because groups nest
      inside every shard's local block (validated by the SPMD config),
      assignments are shard-count invariant.
    """
    t, _ = x2d.shape
    e = num_experts
    g = group_tokens or t
    if t % g:
        raise ValueError(f"moe.dispatch: {t} tokens not divisible by "
                         f"group_tokens={g}")
    if drop_seed is None and g == t:
        xe, disp, gate = L.moe_dispatch(x2d, w_router, e, top_k,
                                        capacity_factor)
        if not with_stats:
            return xe, disp, gate
        cap = group_capacity(t, top_k, e, capacity_factor)
        _, idx = L.moe_router(x2d, w_router, top_k)
        counts = jnp.sum(jax.nn.one_hot(idx, e, dtype=_F32),
                         axis=(0, 1))[None]          # [1, E]
        stats = _routing_stats(x2d, w_router, counts, disp, cap)
        return xe, disp, gate, stats

    n_groups = t // g
    cap_g = group_capacity(g, top_k, e, capacity_factor)
    weights, idx = L.moe_router(x2d, w_router, top_k)
    onehot = jax.nn.one_hot(idx, e, dtype=_F32)          # [T, k, E]
    gate = jnp.sum(onehot * weights[..., None], axis=1)  # [T, E]
    mask = jnp.sum(onehot, axis=1)                       # [T, E] 0/1
    maskg = mask.reshape(n_groups, g, e)
    if drop_seed is not None:
        if gids is None:
            gids = jnp.arange(t, dtype=jnp.int32)
        prio = token_priority(drop_seed, gids).reshape(n_groups, g)
        order = jnp.argsort(prio, axis=1)                # queue order
        inv = jnp.argsort(order, axis=1)
        ms = jnp.take_along_axis(maskg, order[..., None], axis=1)
        pos_s = jnp.cumsum(ms, axis=1) - 1.0
        pos = jnp.take_along_axis(pos_s, inv[..., None], axis=1)
    else:
        pos = jnp.cumsum(maskg, axis=1) - 1.0
    keep = maskg * (pos < cap_g)                         # [G, g, E]
    slot = pos + (jnp.arange(n_groups, dtype=_F32)
                  * cap_g)[:, None, None]
    c_total = n_groups * cap_g
    disp = jax.nn.one_hot(slot.astype(jnp.int32).reshape(t, e),
                          c_total, dtype=_F32) \
        * keep.reshape(t, e)[..., None]                  # [T, E, C]
    xe = jnp.einsum("tec,td->ecd", disp, x2d.astype(_F32))
    if not with_stats:
        return xe, disp, gate
    counts = jnp.sum(maskg, axis=1)                      # [G, E]
    stats = _routing_stats(x2d, w_router, counts, disp, cap_g)
    return xe, disp, gate, stats


def _routing_stats(x2d, w_router, counts, disp, cap_g: int) -> dict:
    """In-graph routing stats: routed/kept histograms, drop count (and
    its closed form — equal by construction, pinned by tests), router
    entropy of the MEAN full-softmax distribution (normalized to
    [0, 1] by ln E)."""
    e = counts.shape[-1]
    probs = jax.nn.softmax(L.router_logits(x2d, w_router), axis=-1)
    p_mean = jnp.mean(probs, axis=0)                     # [E]
    entropy = -jnp.sum(p_mean * jnp.log(p_mean + 1e-12))
    routed = jnp.sum(counts, axis=0)                     # [E]
    kept = jnp.sum(disp, axis=(0, 2))                    # [E]
    return {
        "routed": routed,
        "kept": kept,
        "dropped": jnp.sum(routed) - jnp.sum(kept),
        "expected_dropped": expected_drops(counts, cap_g),
        "entropy": entropy / jnp.log(jnp.asarray(float(e))),
    }


def stats_globals(stats, *, num_experts: int, top_k: int,
                  capacity_factor: float, drop_seed: int | None,
                  group_tokens: int) -> dict:
    """Shape measured routing stats (host-side numpy-ables) as record
    globals: the knobs are COMPARABLE (different routing configs are
    different runs), the measured load/drop/entropy ride the volatile
    ``moe`` block (metrics/merge) and hoist as ``moe_*`` columns
    (metrics/parser)."""
    import numpy as np
    routed = np.asarray(stats["routed"], dtype=float)
    total = max(float(routed.sum()), 1.0)
    load = routed / total
    mean = max(float(load.mean()), 1e-12)
    return {
        "moe_experts": int(num_experts),
        "moe_top_k": int(top_k),
        "moe_capacity_factor": float(capacity_factor),
        "moe_drop_seed": (int(drop_seed) if drop_seed is not None
                          else None),
        "moe_group_tokens": int(group_tokens),
        "moe": {
            "expert_load": [round(float(v), 6) for v in load],
            "load_imbalance": round(float(load.max()) / mean, 4),
            "drop_rate": round(float(stats["dropped"]) / total, 6),
            "router_entropy": round(float(stats["entropy"]), 6),
        },
    }


# -------------------------------------------------- grouped expert FFN
def expert_ffn(xe, w_gate, w_up, w_down, *, impl: str = "einsum",
               quant: str | None = None, counts=None,
               mlp_int8: bool = False):
    """The expert-FFN dispatch point shared by the single-device MoE
    below and the EP-sharded SPMD path: ``xe`` [E, C, d] dispatch
    buffers -> [E, C, d].

    * ``impl="einsum"`` — the XLA batched-einsum path (the pre-ISSUE-15
      spelling; ``mlp_int8`` keeps the r5 int8_dot_batched recipe).
    * ``impl="grouped"`` — the Pallas grouped-matmul kernels with
      optional fused int8/fp8 quantization (``quant``) and count-aware
      block skipping (``counts``).
    """
    if impl == "grouped":
        from dlnetbench_tpu.ops.grouped_matmul import grouped_ffn
        return grouped_ffn(xe, w_gate, w_up, w_down, counts=counts,
                           fmt=quant).astype(_F32)
    if impl != "einsum":
        raise ValueError(f"moe.expert_ffn: unknown impl {impl!r} "
                         f"(einsum | grouped)")
    if mlp_int8:
        from dlnetbench_tpu.ops.int8 import int8_dot_batched
        dt = xe.dtype
        g = int8_dot_batched(xe, w_gate.astype(dt))
        u = int8_dot_batched(xe, w_up.astype(dt))
        h = jax.nn.silu(g.astype(_F32)) * u.astype(_F32)
        out = int8_dot_batched(h.astype(dt), w_down.astype(dt))
        return out.astype(_F32)
    h = jax.nn.silu(jnp.einsum("ecd,edh->ech", xe, w_gate,
                               preferred_element_type=_F32))
    h = h * jnp.einsum("ecd,edh->ech", xe, w_up,
                       preferred_element_type=_F32)
    return jnp.einsum("ech,ehd->ecd", h.astype(xe.dtype), w_down,
                      preferred_element_type=_F32)


def moe_grouped(x2d, w_router, w_gate, w_up, w_down, top_k: int,
                capacity_factor: float = 1.25, *,
                quant: str | None = None,
                drop_seed: int | None = None):
    """Single-device sparse MoE through the grouped Pallas kernels
    (``TransformerConfig.moe_impl="grouped"``): the ``layers.moe_sparse``
    schedule with the expert FFN running as per-expert token batches —
    blocks past an expert's kept-token count are skipped, and ``quant``
    selects the fused int8/fp8 recipes."""
    e = w_gate.shape[0]
    out = dispatch(x2d, w_router, e, top_k, capacity_factor,
                   drop_seed=drop_seed, with_stats=True)
    xe, disp, gate, stats = out
    counts = jnp.minimum(
        stats["kept"],
        jnp.float32(xe.shape[1])).astype(jnp.int32)
    y = expert_ffn(xe.astype(x2d.dtype), w_gate, w_up, w_down,
                   impl="grouped", quant=quant, counts=counts)
    return L.moe_combine(y, disp, gate).astype(x2d.dtype)


# ------------------------------------------------------- schedule twin
def a2a_elems_per_rank(tokens_per_mb: int, top_k: int, embed_dim: int,
                       ep: int) -> int:
    """The native schedule's per-rank all-to-all message arithmetic
    (``core/schedule.moe_schedule``: ``tokens_per_mb * top_k *
    embed_dim // num_expert_shards`` — reference
    hybrid_3d_moe.cpp:354-359), restated here so the parity test can
    pin BOTH tiers to one formula."""
    return tokens_per_mb * top_k * embed_dim // ep


def spmd_a2a_elems(cfg, dp: int, tp: int) -> int:
    """The JAX twin's ACTUAL per-rank dispatch-buffer elements per
    microbatch tick: the [E, C, d] buffer ``_moe_block`` hands the
    EP all-to-all.  At ``capacity_factor == 1`` (and divisible shapes)
    this equals ``a2a_elems_per_rank`` over this rank's token share —
    the native-vs-SPMD schedule-parity certification
    (tests/test_moe.py)."""
    mb_size = cfg.batch // (dp * cfg.num_microbatches)
    t_loc = mb_size * (cfg.seq_len // tp)
    cap = group_capacity(cfg.moe_group_tokens or t_loc, cfg.top_k,
                         cfg.num_experts, cfg.capacity_factor)
    n_groups = t_loc // (cfg.moe_group_tokens or t_loc)
    return cfg.num_experts * n_groups * cap * cfg.embed_dim
