"""Vision Transformer family (vit_b / vit_l / vit_h cards), pure JAX.

Encoder-only: patchify -> [cls] + positions -> pre-norm encoder blocks
(GELU MLP, bidirectional attention) -> cls-token classifier head.  Layers
scan-stacked like the decoder family.
"""
from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp

from dlnetbench_tpu import ops
from dlnetbench_tpu.core.model_card import ModelCard
from dlnetbench_tpu.models import layers as L


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int
    patch_size: int
    embed_dim: int
    num_heads: int
    ff_dim: int
    num_layers: int
    num_classes: int
    dtype: str = "bfloat16"
    attention_impl: str = "auto"   # ops.attention dispatch: auto | flash | xla

    @classmethod
    def from_card(cls, card: ModelCard, *, num_layers: int | None = None,
                  image_size: int | None = None) -> "ViTConfig":
        if not card.is_vit:
            raise ValueError(f"{card.name} is not a ViT card")
        return cls(
            image_size=image_size or card.image_size,
            patch_size=card.patch_size,
            embed_dim=card.embed_dim,
            num_heads=card.num_heads,
            ff_dim=card.ff_dim,
            num_layers=num_layers or card.num_layers,
            num_classes=card.num_classes,
        )

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)




def init_params(key, cfg: ViTConfig) -> dict:
    d, h, L_ = cfg.embed_dim, cfg.ff_dim, cfg.num_layers
    p = cfg.patch_size
    dt = cfg.jdtype
    s_d = 1.0 / math.sqrt(d)
    keys = iter(jax.random.split(key, 12))
    return {
        "patch_embed": L.init_dense(next(keys), (p * p * 3, d),
                             1.0 / math.sqrt(p * p * 3), dt),
        "patch_bias": jnp.zeros((d,), dt),
        "cls_token": jnp.zeros((1, 1, d), dt),
        "pos_embed": L.init_dense(next(keys), (cfg.num_patches + 1, d), 0.02, dt),
        "layers": {
            "wq": L.init_dense(next(keys), (L_, d, d), s_d, dt),
            "wk": L.init_dense(next(keys), (L_, d, d), s_d, dt),
            "wv": L.init_dense(next(keys), (L_, d, d), s_d, dt),
            "wo": L.init_dense(next(keys), (L_, d, d), s_d, dt),
            "norm1": jnp.ones((L_, d), dt),
            "norm1_b": jnp.zeros((L_, d), dt),
            "norm2": jnp.ones((L_, d), dt),
            "norm2_b": jnp.zeros((L_, d), dt),
            "w_in": L.init_dense(next(keys), (L_, d, h), s_d, dt),
            "b_in": jnp.zeros((L_, h), dt),
            "w_out": L.init_dense(next(keys), (L_, h, d), 1.0 / math.sqrt(h), dt),
            "b_out": jnp.zeros((L_, d), dt),
        },
        "final_norm": jnp.ones((d,), dt),
        "final_norm_b": jnp.zeros((d,), dt),
        "head_w": L.init_dense(next(keys), (d, cfg.num_classes), s_d, dt),
        "head_b": jnp.zeros((cfg.num_classes,), dt),
    }


def patchify(images, cfg: ViTConfig):
    """[B, H, W, 3] -> [B, N, p*p*3]."""
    b, hh, ww, c = images.shape
    p = cfg.patch_size
    gh, gw = hh // p, ww // p
    x = images.reshape(b, gh, p, gw, p, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(b, gh * gw, p * p * c)


def _block(cfg: ViTConfig, x, lp):
    b, s, d = x.shape
    y = L.layernorm(x, lp["norm1"], lp["norm1_b"])
    q = jnp.dot(y, lp["wq"]).reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = jnp.dot(y, lp["wk"]).reshape(b, s, cfg.num_heads, cfg.head_dim)
    v = jnp.dot(y, lp["wv"]).reshape(b, s, cfg.num_heads, cfg.head_dim)
    att = ops.attention(q, k, v, causal=False,
                        impl=cfg.attention_impl).reshape(b, s, d)
    x = x + jnp.dot(att, lp["wo"])
    y = L.layernorm(x, lp["norm2"], lp["norm2_b"])
    return x + L.gelu_mlp(y, lp["w_in"], lp["b_in"], lp["w_out"], lp["b_out"])


def forward(params: dict, images, cfg: ViTConfig):
    """images [B, H, W, 3] -> class logits [B, num_classes]."""
    x = jnp.dot(patchify(images.astype(cfg.jdtype), cfg),
                params["patch_embed"]) + params["patch_bias"]
    b = x.shape[0]
    cls = jnp.broadcast_to(params["cls_token"], (b, 1, cfg.embed_dim))
    x = jnp.concatenate([cls, x], axis=1) + params["pos_embed"][None]

    def body(carry, lp):
        return _block(cfg, carry, lp), None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = L.layernorm(x[:, 0], params["final_norm"], params["final_norm_b"])
    return (jnp.dot(x, params["head_w"], preferred_element_type=jnp.float32)
            + params["head_b"].astype(jnp.float32))


def loss_fn(params: dict, images, labels, cfg: ViTConfig):
    return L.cross_entropy(forward(params, images, cfg), labels)
