"""Real model families — the rebuild's compute tier.

The reference does no real math (compute is usleep, SURVEY.md intro); its
model knowledge lives only in architecture cards and roofline stat files.
This package implements the card architectures for real: a llama/gpt2
decoder family, ViT encoders, and Mixtral-style MoE — pure-JAX pytrees
with scan-stacked layers, bfloat16 compute, and (in ``spmd``) a manual
shard_map training step exercising dp/pp/tp/sp/ep on a device mesh.  The
same harness can therefore run both proxy mode (burn + collectives) and
real-math mode, and calibration can compare the two.
"""
