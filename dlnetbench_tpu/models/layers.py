"""Building-block layers (pure JAX, no flax): norms, RoPE, attention,
MLPs, dense MoE routing.

Conventions: parameters are plain dict pytrees; compute dtype is the
input's dtype (bfloat16 on TPU) with float32 accumulation where precision
matters (norm statistics, softmax, router logits); matmuls request float32
``preferred_element_type`` so the MXU accumulates in fp32.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_F32 = jnp.float32


def init_dense(key, shape, scale, dtype):
    """Gaussian init in fp32, cast to the compute dtype (shared by all
    model families)."""
    return (jax.random.normal(key, shape, _F32) * scale).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rmsnorm(x, scale, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(_F32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + eps).astype(x.dtype)) * scale


def _rmsnorm_fwd(x, scale, eps):
    var = jnp.mean(jnp.square(x.astype(_F32)), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    return (x * rstd.astype(x.dtype)) * scale, (x, scale, rstd)


def _rmsnorm_bwd(eps, res, dy):
    # Hand-written so the scale gradient and the input gradient stay
    # SEPARATE fusions: the autodiff-generated single fusion (dscale
    # cross-row reduction + per-token cross-lane reduction + full dx, one
    # loop) runs ~26x slower than memory bandwidth on v5e (3.4 ms vs
    # 0.13 ms for the same bytes; ~15% of a whole train step).
    x, scale, rstd = res
    xhat = x * rstd.astype(x.dtype)
    dscale = jnp.sum(dy.astype(_F32) * xhat.astype(_F32),
                     axis=tuple(range(x.ndim - 1))).astype(scale.dtype)
    xhat, dy, dscale = jax.lax.optimization_barrier((xhat, dy, dscale))
    t = dy * scale
    c = jnp.mean(t.astype(_F32) * xhat.astype(_F32), axis=-1, keepdims=True)
    # second barrier: fusing the per-token reduction INTO the dx
    # elementwise pass regenerates the same slow mixed-reduction loop
    xhat, t, c = jax.lax.optimization_barrier((xhat, t, c))
    dx = (t.astype(_F32) - xhat.astype(_F32) * c) * rstd
    return dx.astype(x.dtype), dscale


rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)


def layernorm(x, scale, bias, eps=1e-5):
    xf = x.astype(_F32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + eps)
    return y.astype(x.dtype) * scale + bias


def rope(q, k, positions, theta=10000.0):
    """Rotary embeddings; q/k: [..., S, H, Dh], positions: [S]."""
    dh = q.shape[-1]
    inv_freq = 1.0 / (theta ** (jnp.arange(0, dh, 2, dtype=_F32) / dh))
    angles = positions.astype(_F32)[:, None] * inv_freq[None, :]  # [S, Dh/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]

    def rot(x):
        x1, x2 = jnp.split(x.astype(_F32), 2, axis=-1)
        return jnp.concatenate([x1 * cos - x2 * sin,
                                x1 * sin + x2 * cos], axis=-1).astype(x.dtype)

    return rot(q), rot(k)


def attention(q, k, v, causal: bool, dense_mask=None):
    """q: [B, S, Hq, Dh], k/v: [B, S, Hkv, Dh] (GQA broadcast).
    Softmax in fp32.

    ``dense_mask`` (an [S, S] bool, True = attend — built by
    ops/attention_mask.dense_mask) replaces the causal tril when given:
    it already encodes the causal half, so the two are never composed.
    This is the reference path the block-sparse kernels are
    parity-tested against — it pays the full S x S grid by design."""
    b, s, hq, dh = q.shape
    hkv = k.shape[2]
    group = hq // hkv
    q = q.reshape(b, s, hkv, group, dh)
    scores = jnp.einsum("bqhgd,bkhd->bhgqk", q, k,
                        preferred_element_type=_F32)
    scores = scores / jnp.sqrt(jnp.asarray(dh, _F32))
    if dense_mask is not None:
        mask = jnp.asarray(dense_mask, bool)
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
    elif causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", probs.astype(v.dtype), v,
                     preferred_element_type=_F32)
    return out.reshape(b, s, hq, dh).astype(v.dtype)


def swiglu_fwd_res(x, w_gate, w_up, w_down):
    """The SwiGLU forward, returning (y, residuals): the ONE place the
    three-dot body lives — the autodiff path (swiglu), the split-dot
    VJP below, and the Pallas VJP (ops/mlp_backward.py) all call it, so
    the bf16 rounding discipline cannot silently diverge between the
    variants that are A/B'd against each other.

    Rounds each projection to the compute dtype IMMEDIATELY so the
    saved residuals are bf16, not f32 (the MXU still accumulates in
    f32; silu stays f32 elementwise and fuses).  Measured perf-neutral
    on v5e at B=2 S=2048 — the save traffic overlaps MXU work — but it
    halves activation memory, which is what lets larger B/S fit without
    remat.
    """
    g = jnp.dot(x, w_gate, preferred_element_type=_F32).astype(x.dtype)
    u = jnp.dot(x, w_up, preferred_element_type=_F32).astype(x.dtype)
    h = (jax.nn.silu(g.astype(_F32)) * u.astype(_F32)).astype(g.dtype)
    y = jnp.dot(h, w_down, preferred_element_type=_F32).astype(x.dtype)
    return y, (x, g, u, w_gate, w_up, w_down)


def swiglu(x, w_gate, w_up, w_down):
    return swiglu_fwd_res(x, w_gate, w_up, w_down)[0]


@jax.custom_vjp
def swiglu_split_bwd(x, w_gate, w_up, w_down):
    """SwiGLU whose BACKWARD is hand-structured: six pure dot_generals
    with the silu-gradient elementwise pass isolated behind
    optimization barriers.

    Why: on v5e, XLA's autodiff backward for this block compiles to
    generic matmul fusions measured at ~0.80 of the bf16 MXU peak
    (docs/PERF.md r3 budget), while the same-shape PURE dots run at
    0.99 of peak (r4 experiment).  Keeping the elementwise work out of
    the matmuls' fusions trades a small explicit HBM round trip of the
    [T, ff] tensors (~2 ms/layer at bench shape) for matmuls that the
    compiler schedules at full rate (~9 ms/layer at bench shape).
    Forward is the same three dots as ``swiglu``; residuals saved are
    bf16 (x, g, u), matching swiglu's memory discipline.
    """
    return swiglu(x, w_gate, w_up, w_down)


def _swiglu_split_fwd(x, w_gate, w_up, w_down):
    return swiglu_fwd_res(x, w_gate, w_up, w_down)


def _swiglu_split_bwd(res, dy):
    x, g, u, w_gate, w_up, w_down = res
    t_nk = (((1,), (1,)), ((), ()))   # a @ b^T  (contract both dim 1)
    t_km = (((0,), (0,)), ((), ()))   # a^T @ b  (contract both dim 0)
    # dh = dy @ Wd^T — a pure dot; the barrier keeps the elementwise
    # silu-grad block below OUT of its fusion
    dh = jax.lax.dot_general(dy, w_down, t_nk,
                             preferred_element_type=_F32)
    (dh,) = jax.lax.optimization_barrier((dh,))
    gf = g.astype(_F32)
    sig = jax.nn.sigmoid(gf)
    silu = gf * sig
    dg = (dh * u.astype(_F32) * (sig + silu * (1.0 - sig))).astype(g.dtype)
    du = (dh * silu).astype(u.dtype)
    h = (silu * u.astype(_F32)).astype(g.dtype)
    dg, du, h = jax.lax.optimization_barrier((dg, du, h))
    dx = (jax.lax.dot_general(dg, w_gate, t_nk,
                              preferred_element_type=_F32)
          + jax.lax.dot_general(du, w_up, t_nk,
                                preferred_element_type=_F32)).astype(x.dtype)
    dwg = jax.lax.dot_general(x, dg, t_km,
                              preferred_element_type=_F32)
    dwu = jax.lax.dot_general(x, du, t_km,
                              preferred_element_type=_F32)
    dwd = jax.lax.dot_general(h, dy, t_km,
                              preferred_element_type=_F32)
    return (dx, dwg.astype(w_gate.dtype), dwu.astype(w_up.dtype),
            dwd.astype(w_down.dtype))


swiglu_split_bwd.defvjp(_swiglu_split_fwd, _swiglu_split_bwd)


def quantized_swiglu(x, w_gate, w_up, w_down, *, mlp_dtype: str,
                     quant_fusion: str = "composed",
                     int8_backward: str = "master", amax_state=None):
    """The ONE dispatch point for the low-precision SwiGLU recipes
    (transformer._block calls this; TransformerConfig validates the
    combinations):

    * ``quant_fusion="composed"`` — the original XLA paths
      (ops/int8.py swiglu_int8 / swiglu_int8_sb, ops/fp8.py
      swiglu_fp8): quantization as separate amax/rescale passes.
    * ``quant_fusion="fused"`` — the fused-quantization Pallas kernels
      (ops/quantized_matmul.py): scale application inlined into the
      matmul prologue/epilogue.
    * ``amax_state`` (a ``[amax_x, amax_h]`` f32 pair, fused only) —
      delayed scaling: scales come from the PREVIOUS step's amaxes and
      the return value is ``(y, new_state)`` instead of ``y``.

    Imports are lazy (ops imports this module's sibling namespace)."""
    if amax_state is not None and quant_fusion != "fused":
        # mirror TransformerConfig's validation for direct callers: the
        # carried amax is a fused-kernel side output — a composed call
        # handing state would otherwise silently get the fused path
        raise ValueError(
            "quantized_swiglu: amax_state (delayed scaling) requires "
            "quant_fusion='fused'")
    if mlp_dtype == "int8":
        from dlnetbench_tpu.ops import int8 as q8
        if amax_state is not None:
            return q8.swiglu_int8_fused_delayed(x, w_gate, w_up, w_down,
                                                amax_state)
        if quant_fusion == "fused":
            return q8.swiglu_int8_fused(x, w_gate, w_up, w_down)
        if int8_backward == "switchback":
            return q8.swiglu_int8_sb(x, w_gate, w_up, w_down)
        return q8.swiglu_int8(x, w_gate, w_up, w_down)
    if mlp_dtype == "float8":
        from dlnetbench_tpu.ops import fp8 as qf8
        if amax_state is not None:
            return qf8.swiglu_fp8_fused_delayed(x, w_gate, w_up, w_down,
                                                amax_state)
        if quant_fusion == "fused":
            return qf8.swiglu_fp8_fused(x, w_gate, w_up, w_down)
        return qf8.swiglu_fp8(x, w_gate, w_up, w_down)
    raise ValueError(f"quantized_swiglu: not a quantized mlp_dtype "
                     f"{mlp_dtype!r}")


def gelu_mlp(x, w_in, b_in, w_out, b_out):
    # same bf16-rounding discipline as swiglu: don't let autodiff save
    # the f32 [B, S, ff_dim] pre-activation
    a = (jnp.dot(x, w_in, preferred_element_type=_F32)
         + b_in.astype(_F32)).astype(x.dtype)
    h = jax.nn.gelu(a.astype(_F32)).astype(x.dtype)
    return (jnp.dot(h, w_out,
                    preferred_element_type=_F32) + b_out).astype(x.dtype)


def router_logits(x, w_router):
    """The ONE spelling of the router projection (f32 — router logits
    are precision-sensitive): ``moe_router`` here, the seeded grouped
    routing in ``models/moe.py`` and the serving MoE decode all build
    on it, so their expert assignments can never drift apart."""
    return jnp.dot(x.astype(_F32), w_router.astype(_F32))


def moe_router(x, w_router, top_k: int):
    """Token router: returns (weights [T, k], expert indices [T, k]).
    Softmax over the selected top-k (Mixtral convention)."""
    logits = router_logits(x, w_router)
    top_vals, top_idx = jax.lax.top_k(logits, top_k)
    weights = jax.nn.softmax(top_vals, axis=-1)
    return weights, top_idx


def moe_dense(x2d, w_router, w_gate, w_up, w_down, top_k: int):
    """Dense (every-expert-computes-selected-tokens) MoE for single-device
    execution: experts stacked on the leading axis of w_* ([E, ...]).
    Selection via one-hot combine — compiler-friendly, no dynamic shapes.
    """
    t, d = x2d.shape
    e = w_gate.shape[0]
    weights, idx = moe_router(x2d, w_router, top_k)        # [T,k], [T,k]
    # combine[t, e] = sum_k weights[t,k] * (idx[t,k]==e)
    combine = jnp.sum(jax.nn.one_hot(idx, e, dtype=_F32)
                      * weights[..., None], axis=1)        # [T, E]
    h = jnp.einsum("td,edh->teh", x2d, w_gate, preferred_element_type=_F32)
    u = jnp.einsum("td,edh->teh", x2d, w_up, preferred_element_type=_F32)
    h = jax.nn.silu(h) * u
    y = jnp.einsum("teh,ehd->ted", h.astype(x2d.dtype), w_down,
                   preferred_element_type=_F32)            # [T, E, D]
    return jnp.einsum("ted,te->td", y, combine).astype(x2d.dtype)


def moe_dispatch(x2d, w_router, num_experts: int, top_k: int,
                 capacity_factor: float):
    """Capacity-based token dispatch (GShard/Switch style), shared by the
    single-device sparse MoE below and the EP-sharded SPMD step
    (models/spmd.py _moe_block — identical math, with all_to_alls
    inserted around the expert compute).  Tokens land in per-expert
    buffers of C = floor(T*k/E * capacity_factor) slots via a
    cumsum-position one-hot; tokens beyond capacity are dropped (their
    combine weight is zero, the residual carries them).

    Returns (xe [E, C, d] f32 expert inputs, disp [T, E, C] dispatch
    one-hots, gate [T, E] combine weights); combine with
    ``moe_combine``."""
    t, _ = x2d.shape
    e = num_experts
    weights, idx = moe_router(x2d, w_router, top_k)         # [T,k] each
    cap = max(1, int(capacity_factor * t * top_k / e))

    onehot = jax.nn.one_hot(idx, e, dtype=_F32)             # [T, k, E]
    gate = jnp.sum(onehot * weights[..., None], axis=1)     # [T, E]
    mask = jnp.sum(onehot, axis=1)                          # [T, E] 0/1
    pos = jnp.cumsum(mask, axis=0) - 1.0                    # arrival order
    keep = mask * (pos < cap)
    disp = jax.nn.one_hot(pos.astype(jnp.int32), cap, dtype=_F32) \
        * keep[..., None]                                   # [T, E, C]
    xe = jnp.einsum("tec,td->ecd", disp, x2d.astype(_F32))  # [E, C, d]
    return xe, disp, gate


def moe_combine(out, disp, gate):
    """Scatter per-expert outputs [E, C, d] back to tokens [T, d] with
    the dispatch one-hots and combine weights from ``moe_dispatch``."""
    return jnp.einsum("ecd,tec->td", out, disp * gate[..., None])


def moe_sparse(x2d, w_router, w_gate, w_up, w_down, top_k: int,
               capacity_factor: float = 1.25):
    """Capacity-based sparse MoE for single-device execution.  Expert
    FLOPs are E*C*ffn ~ k*cf*T*ffn instead of moe_dense's E*T*ffn.  At
    capacity_factor >= E/top_k nothing drops and the result matches
    moe_dense exactly (tests/test_models.py pins this)."""
    e = w_gate.shape[0]
    xe, disp, gate = moe_dispatch(x2d, w_router, e, top_k, capacity_factor)
    xe = xe.astype(x2d.dtype)
    h = jax.nn.silu(jnp.einsum("ecd,edh->ech", xe, w_gate,
                               preferred_element_type=_F32))
    h = h * jnp.einsum("ecd,edh->ech", xe, w_up,
                       preferred_element_type=_F32)
    out = jnp.einsum("ech,ehd->ecd", h.astype(x2d.dtype), w_down,
                     preferred_element_type=_F32)           # [E, C, d]
    return moe_combine(out, disp, gate).astype(x2d.dtype)


def cross_entropy(logits, targets):
    """Mean token cross-entropy; logits [.., V] in any dtype, fp32 inside.
    Computed as mean(logsumexp - logits[target]) so the full [.., V]
    log-probability tensor is never materialized (log_softmax would write
    and re-read it — half a GB at B=2 S=2048 V=32k)."""
    lse = jax.scipy.special.logsumexp(logits.astype(_F32), axis=-1)
    tgt = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - tgt.astype(_F32))
