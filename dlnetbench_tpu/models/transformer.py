"""Decoder-family transformer (llama / gpt2 variants), pure JAX.

Architecture is read off a ``ModelCard``: ``gated_mlp`` selects
SwiGLU+RMSNorm+RoPE (llama/minerva/mixtral family) vs GELU+LayerNorm+learned
positions (gpt2 family); ``num_kv_heads`` gives GQA; ``moe_params`` turns
every layer's MLP into a dense-dispatch MoE (Mixtral-style).  Layers are
stacked on a leading axis and executed with ``lax.scan`` so compile time is
O(1) in depth and XLA sees one fused block body.

This is the compute that the reference only *simulates* (usleep from
roofline stat files); here the same cards drive real math, so measured step
times can be compared against the roofline predictions (see bench.py).
"""
from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp

from dlnetbench_tpu import ops
from dlnetbench_tpu.core.model_card import ModelCard
from dlnetbench_tpu.models import layers as L


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int
    embed_dim: int
    num_heads: int
    num_kv_heads: int
    ff_dim: int
    num_layers: int
    seq_len: int
    gated: bool              # SwiGLU+RMSNorm+RoPE vs GELU+LayerNorm+learned
    max_positions: int       # learned positions (gpt2 family), 0 = RoPE
    num_experts: int = 1
    top_k: int = 1
    tied_embeddings: bool = False
    dtype: str = "bfloat16"
    remat: bool = False      # jax.checkpoint each block: recompute activations
                             # in backward instead of storing S x S residuals
    remat_policy: str = "full"     # "full" (recompute everything) or "dots"
                             # (keep matmul outputs, recompute elementwise —
                             # measured ~6% faster than full at S=2048 on v5e
                             # for a fraction of full-remat's memory saving)
    remat_scope: str = "block"     # "block" checkpoints the whole decoder
                             # block; "mlp" checkpoints ONLY the gated MLP —
                             # the [B, S, ff] g/u pre-activation saves are
                             # the dominant residuals (roofline
                             # train_step_bytes), and for mlp_dtype="int8"
                             # the int32/f32 quantization intermediates
                             # stay transient in BOTH passes (the r5
                             # no-remat OOM source), at the price of
                             # recomputing 3 MLP matmuls per layer
    attention_impl: str = "auto"   # ops.attention dispatch: auto | flash | xla
    attention_window: int = 0      # sliding-window attention: each token
                             # attends its W most recent tokens (itself
                             # included); 0 = full causal.  Dispatches
                             # the block-sparse splash kernels on TPU
                             # (ops/attention_mask.py MaskSpec) and the
                             # dense-masked reference on the CPU mesh
    attention_seg_avg: int = 0     # document-segment masking: tokens are
                             # partitioned into documents by the seeded
                             # segment plan (splitmix64 lengths around
                             # this average); attention never crosses a
                             # document boundary.  0 = off
    attention_seg_seed: int = 0    # the segment plan's seed (a plan IS
                             # (seed, avg): replayable, committable)
    scan_layers: bool = True       # lax.scan over the layer stack (O(1)
                             # compile time in depth); False unrolls the
                             # Python loop — measured ~5% faster at 4 layers
                             # on v5e (no dynamic-slice save/restore of
                             # per-layer activations), at O(depth) compile
    logits_f32: bool = True        # emit f32 logits (training-grade CE
                             # numerics); False keeps them bf16 — halves
                             # the [B, S, V] logits traffic for benches
    mlp_dtype: str = "bfloat16"    # "float8" runs the (dense) MLP matmuls
                             # in e4m3 with per-tensor dynamic scales and
                             # bf16 master weights (ops/fp8.py; measured
                             # r5: native on the MXU at 0.70 of fp8 peak
                             # in isolation — the r3/r4 "upcast" verdict
                             # was an HBM-residency artifact);
                             # "int8" likewise via ops/int8.py — 0.98 of
                             # the 2x int8 peak in isolation and a
                             # measured 1.087x END-TO-END step win at
                             # the headline's no-remat config (494.3 vs
                             # 537.5 ms, r5 docs/PERF.md — needs the
                             # fused swiglu_int8 VJP);
                             # backward stays in the master dtype
                             # (straight-through) for both
    moe_impl: str = "dense"        # "dense" (every expert computes every
                             # selected token — exact, E/k x the FLOPs),
                             # "sparse" (capacity-based dispatch, GShard
                             # style: ~k*cf*T*ffn FLOPs, over-capacity
                             # tokens dropped — the production semantics)
                             # or "grouped" (sparse routing with the
                             # expert FFN as Pallas grouped-matmul
                             # kernels, ops/grouped_matmul.py — blocks
                             # past an expert's kept-token count are
                             # skipped; ISSUE 15)
    moe_capacity_factor: float = 1.25
    int8_backward: str = "master"  # mlp_dtype="int8" backward mode:
                             # "master" = straight-through bf16 (the
                             # conservative default); "switchback" =
                             # the dx-side matmuls (dh, dx) also
                             # quantized to int8, dW stays master —
                             # a RECIPE change, opt-in; loss-drift
                             # measured in docs/studies/int8_step_r5
    quant_fusion: str = "composed" # low-precision MLP matmul impl
                             # (mlp_dtype float8/int8 only): "composed"
                             # = quantization as separate XLA passes
                             # (amax reduce, rescale/cast, post-matmul
                             # sa*sb — each an HBM round trip);
                             # "fused" = the Pallas kernels in
                             # ops/quantized_matmul.py, which quantize
                             # the activation tile in VMEM and apply
                             # sa*sb in the epilogue (the r6 attack on
                             # the fp8 chain's 0.56-of-peak and the
                             # int8 step's quantization overhead)
    quant_scaling: str = "dynamic" # "dynamic" = fresh per-tensor amax
                             # each call; "delayed" (fused only) = the
                             # amax is CARRIED from the previous step
                             # as per-layer state threaded through the
                             # train step (init_qstate/forward's
                             # qstate arg; SwitchBack / FP8-recipe
                             # style), so the fresh-amax HBM reduction
                             # leaves the hot path — scales lag one
                             # step and saturate on overflow
    mlp_backward: str = "fused"    # SwiGLU backward: "fused" = plain
                             # autodiff (the r4-measured winner);
                             # "split" = pure dots behind barriers
                             # (layers.swiglu_split_bwd, 0.9975 paired
                             # ratio — noise); "pallas" = fused dg/du +
                             # dWd kernels (ops/mlp_backward.py, 1.012 —
                             # slower).  All three measured end-to-end
                             # on v5e; docs/PERF.md r4 records why the
                             # XLA schedule is already at the wall

    def __post_init__(self):
        if self.attention_window < 0 or self.attention_seg_avg < 0:
            raise ValueError(
                f"attention_window={self.attention_window} / "
                f"attention_seg_avg={self.attention_seg_avg} must be "
                f">= 0 (0 = off)")
        if self.remat_policy not in ("full", "dots"):
            raise ValueError(f"unknown remat_policy {self.remat_policy!r}; "
                             f"expected 'full' or 'dots'")
        if self.remat_scope not in ("block", "mlp"):
            raise ValueError(f"unknown remat_scope {self.remat_scope!r}; "
                             f"expected 'block' or 'mlp'")
        if self.remat_scope == "mlp" and (self.num_experts > 1
                                          or not self.gated):
            raise ValueError(
                "remat_scope='mlp' covers the dense gated (SwiGLU) MLP "
                "path only")
        if self.moe_impl not in ("dense", "sparse", "grouped"):
            raise ValueError(f"unknown moe_impl {self.moe_impl!r}; "
                             f"expected 'dense', 'sparse' or 'grouped'")
        if self.mlp_dtype not in ("bfloat16", "float8", "int8"):
            raise ValueError(f"unknown mlp_dtype {self.mlp_dtype!r}; "
                             f"expected 'bfloat16', 'float8' or 'int8'")
        if self.int8_backward not in ("master", "switchback"):
            raise ValueError(
                f"unknown int8_backward {self.int8_backward!r}; "
                f"expected 'master' or 'switchback'")
        if self.int8_backward != "master" and self.mlp_dtype != "int8":
            raise ValueError(
                "int8_backward='switchback' requires mlp_dtype='int8'")
        if self.mlp_dtype != "bfloat16" and (self.num_experts > 1
                                             or not self.gated):
            raise ValueError(
                f"mlp_dtype={self.mlp_dtype!r} currently covers the "
                f"dense SwiGLU path only")
        if self.quant_fusion not in ("composed", "fused"):
            raise ValueError(f"unknown quant_fusion {self.quant_fusion!r}; "
                             f"expected 'composed' or 'fused'")
        if self.quant_scaling not in ("dynamic", "delayed"):
            raise ValueError(
                f"unknown quant_scaling {self.quant_scaling!r}; "
                f"expected 'dynamic' or 'delayed'")
        if self.quant_fusion == "fused" and self.mlp_dtype == "bfloat16":
            raise ValueError(
                "quant_fusion='fused' requires mlp_dtype='float8' or "
                "'int8' (there is nothing to quantize in bf16)")
        if self.quant_scaling == "delayed" and self.quant_fusion != "fused":
            raise ValueError(
                "quant_scaling='delayed' requires quant_fusion='fused' "
                "(the carried amax is a fused-kernel side output)")
        if self.quant_fusion == "fused" and self.int8_backward != "master":
            raise ValueError(
                "quant_fusion='fused' covers the master-dtype "
                "(straight-through) backward only; SwitchBack's "
                "quantized dx dots are a composed-path recipe")
        if self.mlp_backward not in ("split", "fused", "pallas"):
            raise ValueError(f"unknown mlp_backward {self.mlp_backward!r}; "
                             f"expected 'split', 'fused' or 'pallas'")
        if self.mlp_backward != "fused" and (self.num_experts > 1
                                             or self.mlp_dtype != "bfloat16"
                                             or not self.gated):
            # the MoE / fp8 / int8 / gelu branches would win the
            # dispatch and silently measure the WRONG backward in an A/B
            raise ValueError(
                f"mlp_backward={self.mlp_backward!r} covers the dense "
                f"bf16 SwiGLU path only (MoE, float8/int8 and non-gated "
                f"MLPs dispatch elsewhere)")

    @classmethod
    def from_card(cls, card: ModelCard, *, seq_len: int | None = None,
                  num_layers: int | None = None,
                  vocab_size: int | None = None) -> "TransformerConfig":
        """Build from an architecture card, optionally overriding size knobs
        (tests and single-chip benches shrink seq/layers/vocab)."""
        if card.is_vit:
            raise ValueError(f"{card.name} is a ViT card; use models.vit")
        return cls(
            vocab_size=vocab_size or card.vocab_size or 32000,
            embed_dim=card.embed_dim,
            num_heads=card.num_heads,
            num_kv_heads=card.kv_heads,
            ff_dim=card.ff_dim,
            num_layers=num_layers or card.num_layers,
            seq_len=seq_len or card.seq_len,
            gated=card.gated_mlp,
            max_positions=card.max_position_embeddings,
            num_experts=card.num_experts,
            top_k=card.top_k,
            tied_embeddings=card.tied_embeddings,
        )

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @property
    def mask_spec(self):
        """The attention ``MaskSpec`` these knobs declare, or ``None``
        for the dense-causal default (ops.attention's mask=None path —
        bit-identical to the pre-mask harness)."""
        from dlnetbench_tpu.ops.attention_mask import MaskSpec
        return MaskSpec.from_knobs(self.attention_window,
                                   self.attention_seg_avg,
                                   self.attention_seg_seed)




def needs_qstate(cfg: TransformerConfig) -> bool:
    """True when the step must thread delayed-scaling amax state
    (``init_qstate`` -> ``forward(..., qstate=...)`` ->
    ``(out, new_qstate)``)."""
    return cfg.quant_scaling == "delayed"


def init_qstate(cfg: TransformerConfig):
    """Initial delayed-scaling state: per layer ``[amax_x, amax_h]``
    (gate/up share the x amax; down uses the h amax), f32.

    Initialized to 1.0 — an order-of-magnitude guess for unit-variance
    bf16 activations, NOT a calibration: the first step quantizes
    against it (saturating at the format edge if it is low) and emits
    the true amaxes, so the state self-corrects after one step (the
    standard delayed-scaling warm-in; arXiv:2209.05433 §4)."""
    if not needs_qstate(cfg):
        raise ValueError("init_qstate: cfg.quant_scaling != 'delayed'")
    return jnp.ones((cfg.num_layers, 2), jnp.float32)


def init_params(key, cfg: TransformerConfig) -> dict:
    d, dh = cfg.embed_dim, cfg.head_dim
    dkv = cfg.num_kv_heads * dh
    h, L_, v = cfg.ff_dim, cfg.num_layers, cfg.vocab_size
    dt = cfg.jdtype
    s_d = 1.0 / math.sqrt(d)
    s_h = 1.0 / math.sqrt(h)
    keys = iter(jax.random.split(key, 16))

    layer = {
        "wq": L.init_dense(next(keys), (L_, d, d), s_d, dt),
        "wk": L.init_dense(next(keys), (L_, d, dkv), s_d, dt),
        "wv": L.init_dense(next(keys), (L_, d, dkv), s_d, dt),
        "wo": L.init_dense(next(keys), (L_, d, d), s_d, dt),
        "norm1": jnp.ones((L_, d), dt),
        "norm2": jnp.ones((L_, d), dt),
    }
    if not cfg.gated:
        layer.update({
            "norm1_b": jnp.zeros((L_, d), dt),
            "norm2_b": jnp.zeros((L_, d), dt),
            "w_in": L.init_dense(next(keys), (L_, d, h), s_d, dt),
            "b_in": jnp.zeros((L_, h), dt),
            "w_out": L.init_dense(next(keys), (L_, h, d), s_h, dt),
            "b_out": jnp.zeros((L_, d), dt),
        })
    elif cfg.num_experts > 1:
        e = cfg.num_experts
        layer.update({
            "w_router": L.init_dense(next(keys), (L_, d, e), s_d, dt),
            "w_gate": L.init_dense(next(keys), (L_, e, d, h), s_d, dt),
            "w_up": L.init_dense(next(keys), (L_, e, d, h), s_d, dt),
            "w_down": L.init_dense(next(keys), (L_, e, h, d), s_h, dt),
        })
    else:
        layer.update({
            "w_gate": L.init_dense(next(keys), (L_, d, h), s_d, dt),
            "w_up": L.init_dense(next(keys), (L_, d, h), s_d, dt),
            "w_down": L.init_dense(next(keys), (L_, h, d), s_h, dt),
        })

    params = {
        "embed": L.init_dense(next(keys), (v, d), 1.0, dt),
        "layers": layer,
        "final_norm": jnp.ones((d,), dt),
    }
    if not cfg.gated:
        params["final_norm_b"] = jnp.zeros((d,), dt)
    if cfg.max_positions:
        params["pos_embed"] = L.init_dense(next(keys), (cfg.max_positions, d),
                                    0.01, dt)
    if not cfg.tied_embeddings:
        params["head"] = L.init_dense(next(keys), (d, v), s_d, dt)
    return params


def _block(cfg: TransformerConfig, x, lp, positions, qs_row=None):
    """One decoder block; x: [B, S, D], lp: this layer's param slice.
    ``qs_row`` is this layer's delayed-scaling amax state (delayed
    quant only) — when given, returns ``(x, new_qs_row)``."""
    b, s, d = x.shape
    if cfg.gated:
        y = L.rmsnorm(x, lp["norm1"])
    else:
        y = L.layernorm(x, lp["norm1"], lp["norm1_b"])
    q = jnp.dot(y, lp["wq"]).reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = jnp.dot(y, lp["wk"]).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = jnp.dot(y, lp["wv"]).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    if not cfg.max_positions:  # RoPE family
        q, k = L.rope(q, k, positions)
    att = ops.attention(q, k, v, causal=True, impl=cfg.attention_impl,
                        mask=cfg.mask_spec).reshape(b, s, d)
    x = x + jnp.dot(att, lp["wo"])

    if cfg.gated:
        y = L.rmsnorm(x, lp["norm2"])
        if cfg.num_experts > 1:
            if cfg.moe_impl == "dense":
                moe = L.moe_dense
            elif cfg.moe_impl == "grouped":
                from dlnetbench_tpu.models.moe import moe_grouped
                moe = functools.partial(
                    moe_grouped,
                    capacity_factor=cfg.moe_capacity_factor)
            else:
                moe = functools.partial(
                    L.moe_sparse,
                    capacity_factor=cfg.moe_capacity_factor)
            y2 = moe(y.reshape(b * s, d), lp["w_router"],
                     lp["w_gate"], lp["w_up"], lp["w_down"],
                     cfg.top_k).reshape(b, s, d)
        else:
            new_qs_row = None
            if cfg.mlp_dtype in ("float8", "int8"):
                mlp_fn = functools.partial(
                    L.quantized_swiglu, mlp_dtype=cfg.mlp_dtype,
                    quant_fusion=cfg.quant_fusion,
                    int8_backward=cfg.int8_backward)
                if qs_row is not None:
                    mlp_fn = functools.partial(mlp_fn, amax_state=qs_row)
            elif cfg.mlp_backward == "pallas":
                from dlnetbench_tpu.ops.mlp_backward import \
                    swiglu_pallas_bwd

                def mlp_fn(y, wg, wu, wd):
                    return swiglu_pallas_bwd(
                        y.reshape(b * s, d), wg, wu, wd).reshape(b, s, d)
            elif cfg.mlp_backward == "split":
                def mlp_fn(y, wg, wu, wd):
                    return L.swiglu_split_bwd(
                        y.reshape(b * s, d), wg, wu, wd).reshape(b, s, d)
            else:
                mlp_fn = L.swiglu
            if cfg.remat and cfg.remat_scope == "mlp":
                # checkpoint ONLY the MLP: recompute the g/u
                # pre-activations (and, for int8/fp8, the quantization
                # intermediates) in backward instead of saving them
                mlp_fn = jax.checkpoint(mlp_fn)
            y2 = mlp_fn(y, lp["w_gate"], lp["w_up"], lp["w_down"])
            if qs_row is not None:
                y2, new_qs_row = y2
    else:
        y = L.layernorm(x, lp["norm2"], lp["norm2_b"])
        y2 = L.gelu_mlp(y, lp["w_in"], lp["b_in"], lp["w_out"], lp["b_out"])
    if qs_row is not None:
        return x + y2, new_qs_row
    return x + y2


def forward(params: dict, tokens, cfg: TransformerConfig, qstate=None):
    """tokens [B, S] int32 -> logits [B, S, V].

    With ``cfg.quant_scaling == "delayed"``, ``qstate`` (the
    ``init_qstate``-shaped [L, 2] amax carry) is REQUIRED and the
    return value is ``(logits, new_qstate)`` — the caller threads the
    new state into the next step."""
    delayed = needs_qstate(cfg)
    if delayed and qstate is None:
        raise ValueError("cfg.quant_scaling='delayed' requires the "
                         "qstate carry (models.transformer.init_qstate)")
    x = params["embed"][tokens]
    s = tokens.shape[1]
    positions = jnp.arange(s)
    if cfg.max_positions:
        x = x + params["pos_embed"][positions][None]

    block = _block
    if cfg.remat and cfg.remat_scope == "block":
        policy = (jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                  if cfg.remat_policy == "dots" else None)
        block = jax.checkpoint(_block, static_argnums=(0,), policy=policy)

    new_qstate = None
    if cfg.scan_layers:
        if delayed:
            def body(carry, xs):
                lp, qs_row = xs
                return block(cfg, carry, lp, positions, qs_row)

            x, new_qstate = jax.lax.scan(body, x,
                                         (params["layers"], qstate))
        else:
            def body(carry, lp):
                return block(cfg, carry, lp, positions), None

            x, _ = jax.lax.scan(body, x, params["layers"])
    else:
        new_rows = []
        for li in range(cfg.num_layers):
            lp = jax.tree.map(lambda a: a[li], params["layers"])
            if delayed:
                x, row = block(cfg, x, lp, positions, qstate[li])
                new_rows.append(row)
            else:
                x = block(cfg, x, lp, positions)
        if delayed:
            new_qstate = jnp.stack(new_rows)
    if cfg.gated:
        x = L.rmsnorm(x, params["final_norm"])
    else:
        x = L.layernorm(x, params["final_norm"], params["final_norm_b"])
    head = params["embed"].T if cfg.tied_embeddings else params["head"]
    logits = jnp.dot(x, head,
                     preferred_element_type=(jnp.float32 if cfg.logits_f32
                                             else x.dtype))
    if delayed:
        return logits, new_qstate
    return logits


def loss_fn(params: dict, tokens, cfg: TransformerConfig, qstate=None):
    """Next-token cross-entropy on a [B, S+1] token batch.  With
    delayed quantization scaling the return value is
    ``(loss, new_qstate)`` (``jax.value_and_grad(..., has_aux=True)``
    shape — the state is an aux output, not part of the loss)."""
    if needs_qstate(cfg):
        logits, new_qstate = forward(params, tokens[:, :-1], cfg, qstate)
        return L.cross_entropy(logits, tokens[:, 1:]), new_qstate
    logits = forward(params, tokens[:, :-1], cfg)
    return L.cross_entropy(logits, tokens[:, 1:])
