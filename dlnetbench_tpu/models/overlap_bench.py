"""Paired overlap-vs-baseline A/B of the SPMD train step.

The measurement half of the r7 overlap work (ISSUE 4): build the real
dp x pp x tp training step twice — baseline (blocking psum TP schedule +
monolithic grad sync) and overlapped (``tp_overlap="decomposed"`` +
``grad_sync="bucketed"``) — each in the three A/B decomposition variants
(``models/spmd.py`` full / compute / comm), then time all six programs in
interleaved rounds (the r4 pairing protocol: adjacent in time, so ratios
cancel drift) and report

* wall time per config with artifact-grade ``{value, best, band, n}``
  stat bands (metrics/stats.py),
* the paired per-round ratio band (ratio < 1.0 = overlap wins), and
* the **measured overlap fraction** per config
  (``metrics/stats.overlap_fraction``: (Tc + Tm - T_both)/min(Tc, Tm))
  — the number the decomposition exists to move.

Used by ``bench.py`` (real chips, >= 2 devices) and the multichip
driver's dryrun (virtual 8-CPU mesh — scheduling-level signal only, the
transport is loopback).  ``assemble_line`` is pure so the JSON schema is
locked by tests without building a mesh.
"""
from __future__ import annotations

import dataclasses
from functools import partial

import jax

from dlnetbench_tpu.metrics import stats as stats_mod


def assemble_line(metric: str, walls_s: dict[str, list[float]],
                  overlaps: dict[str, list[float]]) -> dict:
    """One paired overlap-vs-baseline JSON line (pure — the schema is
    locked by tests/test_bench_aux.py).

    ``walls_s``: per-round full-step seconds for "baseline" and
    "overlapped"; ``overlaps``: per-round measured overlap fractions for
    the same two configs.  The headline ``value`` is the OVERLAPPED
    median (the path under test); the baseline ships as a sub-object and
    the per-round ratio band pairs them."""
    summaries = {name: stats_mod.summarize(ts) for name, ts in
                 walls_s.items()}
    over = summaries["overlapped"]
    line = {
        "metric": metric,
        "value": round(over["value"] * 1e3, 3),
        "unit": "ms",
        "best": round(over["best"] * 1e3, 3),
        "band": [round(v * 1e3, 3) for v in over["band"]],
        "n": over["n"],
    }
    for name, s in summaries.items():
        line[name] = {
            "value": round(s["value"] * 1e3, 3),
            "best": round(s["best"] * 1e3, 3),
            "band": [round(v * 1e3, 3) for v in s["band"]],
            "n": s["n"],
        }
    # a 0.0 baseline wall (a time_chain sample fully cancelled by the
    # RTT subtraction) makes the pair meaningless — drop it rather than
    # shipping an unbounded ratio; n on the band says how many survived
    ratios = [o / b for o, b in zip(walls_s["overlapped"],
                                    walls_s["baseline"]) if b > 0]
    line["ratio_overlapped_vs_baseline"] = stats_mod.summarize(ratios,
                                                               ndigits=4)
    line["overlap_fraction"] = {
        name: stats_mod.summarize(vals, ndigits=4)
        for name, vals in overlaps.items()}
    return stats_mod.flag_low_mode(line)


def _mesh_desc(mesh) -> str:
    return "x".join(f"{a}={s}" for a, s in
                    zip(mesh.axis_names, mesh.devices.shape))


def build_programs(n_devices: int | None = None, devices=None,
                   cfg_kwargs: dict | None = None):
    """(mesh, cfgs, programs, params, tokens): six jitted step programs —
    {config: {variant: fn(params, tokens)}} for the baseline and
    overlapped configs in all three A/B variants, on one mesh."""
    from dlnetbench_tpu.models import spmd

    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    dp, pp, tp = spmd.factor_mesh(n)
    from dlnetbench_tpu.parallel.mesh import make_grid_mesh
    mesh = make_grid_mesh(dp=dp, pp=pp, tp=tp, devices=devices[:n])
    kw = dict(cfg_kwargs or {})
    kw.setdefault("batch", dp * 2 * 2)
    kw.setdefault("num_microbatches", 2)
    base = spmd.SpmdConfig(tp_overlap="none", grad_sync="monolithic", **kw)
    # resolve tuned-or-default knobs HERE (explicit cfg_kwargs win) so
    # the metric string names the chunk grain the programs actually ran
    base = base.resolve_tuned(dp, pp, tp)
    over = dataclasses.replace(base, tp_overlap="decomposed",
                               grad_sync="bucketed")
    cfgs = {"baseline": base, "overlapped": over}
    programs = {name: {v: spmd.make_train_step(mesh, cfg, variant=v)
                       for v in spmd.VARIANTS}
                for name, cfg in cfgs.items()}
    params = spmd.init_params(jax.random.key(0), base)
    tokens = jax.random.randint(jax.random.key(1),
                                (base.batch, base.seq_len + 1), 0,
                                base.vocab_size)
    return mesh, cfgs, programs, params, tokens


def measure(n_devices: int | None = None, devices=None,
            cfg_kwargs: dict | None = None, rounds: int = 3,
            reps: int = 2) -> dict:
    """Run the paired A/B and return the JSON-able line (not printed).

    Needs >= 2 devices (a 1-device "mesh" has no communication to
    overlap) — raises ValueError below that, which bench.py's ``_aux``
    degrades to a skipped marker."""
    from dlnetbench_tpu.utils.timing import time_chain

    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    if n < 2:
        raise ValueError(f"overlap A/B needs >= 2 devices, have {n}")
    mesh, cfgs, programs, params, tokens = build_programs(
        n, devices, cfg_kwargs)

    thunks = {name: {v: partial(fn, params, tokens)
                     for v, fn in vs.items()}
              for name, vs in programs.items()}
    for vs in thunks.values():            # compile + warm outside timing
        for fn in vs.values():
            jax.block_until_ready(fn())

    times: dict[str, dict[str, list[float]]] = {
        name: {v: [] for v in vs} for name, vs in thunks.items()}
    for _ in range(rounds):
        # every (config, variant) timed back-to-back within the round:
        # per-round ratios and overlap fractions use MATCHED samples
        for name, vs in thunks.items():
            for v, fn in vs.items():
                times[name][v].append(time_chain(fn, k=reps))

    walls = {name: ts["full"] for name, ts in times.items()}
    overlaps = {name: stats_mod.overlap_fraction(
        ts["full"], ts["compute"], ts["comm"])
        for name, ts in times.items()}
    cfg = cfgs["baseline"]
    metric = (f"spmd overlap A/B: tp_overlap=decomposed"
              f"(chunks={cfgs['overlapped'].tp_overlap_chunks}) + "
              f"grad_sync=bucketed vs blocking baseline, "
              f"mesh {_mesh_desc(mesh)}, L={cfg.num_layers} "
              f"S={cfg.seq_len} B={cfg.batch}, "
              f"overlap_fraction=(Tc+Tm-Tboth)/min(Tc,Tm) from the "
              f"full/compute/comm decomposition")
    line = assemble_line(metric, walls, overlaps)
    # attribution from the OVERLAPPED config's measured decomposition
    # (the line's headline value): exposed comm = full - compute per
    # matched sample, compute measured, residual host — the one aux
    # line whose block is built from an A/B measurement, not a FLOP
    # model (analysis/attribution.py)
    from dlnetbench_tpu.analysis.attribution import attribute_decomposition
    on_tpu = getattr(mesh.devices.flat[0], "platform", "") == "tpu"
    attr = attribute_decomposition(
        times["overlapped"]["full"], times["overlapped"]["compute"],
        times["overlapped"]["comm"],
        transport="ici" if on_tpu else None, on_accelerator=on_tpu)
    if attr is not None:
        line["attribution"] = attr
    return line
