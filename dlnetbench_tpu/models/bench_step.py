"""The headline bench train step, in ONE place.

``bench.py`` (the driver-run headline) and
``examples/xla_knob_study.py`` (the compiler-knob sweep) must measure
the SAME program — a sweep winner tuned for a drifted copy of the step
would be adopted into a different program than it was measured on.
Both build their step through this module, and both execute it through
the AOT engine (``core/executor.py``) with the params/optimizer-state
carry donated (``DONATE_ARGNUMS``): compile time is recorded out of
band, and the optimizer update reuses the param buffers in place
(aliasing visible in the recorded ``memory_analysis``).

Recipe rationale (shapes, remat, scan, logits dtype, VMEM option) is
documented at the call site in bench.py, where the measured history
lives.
"""
from __future__ import annotations

import dataclasses

import jax

from dlnetbench_tpu.utils.tpu_probe import env_int

# Shape knobs, frozen at import (the DLNB_FLASH_BWD_BLOCKS discipline):
# the driver's headline shape by default; DLNB_BENCH_* overrides let the
# sentinel lane (Makefile `check-bench`, tests/test_sentinel.py) run the
# EXACT bench.py pipeline — headline compile, stat bands, --check — on a
# tiny CPU-feasible model.  Every consumer imports these constants, so a
# run's shape is one coherent choice, never a mix.
BATCH = env_int("DLNB_BENCH_BATCH", 2)
SEQ = env_int("DLNB_BENCH_SEQ", 6144)
LAYERS = env_int("DLNB_BENCH_LAYERS", 4)
VOCAB = env_int("DLNB_BENCH_VOCAB", 32768)
# 0 = the llama3_8b card's own dims
EMBED = env_int("DLNB_BENCH_EMBED", 0)
FF = env_int("DLNB_BENCH_FF", 0)
HEADS = env_int("DLNB_BENCH_HEADS", 0)
# kv heads default to HEADS when that is overridden (a tiny lane model
# wants kv == q); set this too to keep a GQA ratio under a HEADS
# override instead of silently converting the card to MHA
KV_HEADS = env_int("DLNB_BENCH_KV_HEADS", 0)

# which train_k argument the AOT call sites donate: the params /
# optimizer-state carry (argument 0); tokens are read-only
DONATE_ARGNUMS = (0,)


def bench_card():
    from dlnetbench_tpu.core.model_card import ModelCard, load_model_card
    base = load_model_card("llama3_8b")
    return ModelCard(name="llama3_8b_bench",
                     embed_dim=EMBED or base.embed_dim,
                     num_heads=HEADS or base.num_heads,
                     num_kv_heads=KV_HEADS or HEADS or base.num_kv_heads,
                     ff_dim=FF or base.ff_dim,
                     seq_len=SEQ, num_decoder_blocks=LAYERS,
                     vocab_size=VOCAB, gated_mlp=True)


def bench_cfg(card, **overrides):
    from dlnetbench_tpu.models import transformer as tfm
    return dataclasses.replace(tfm.TransformerConfig.from_card(card),
                               scan_layers=False, logits_f32=False,
                               **overrides)


def make_train_k(cfg, k: int):
    """K optimizer steps chained in one program: on the tunnel backend
    every dispatch costs ~2-7 ms of host->device latency a real
    training loop never serializes on; chaining measures the DEVICE.

    With ``cfg.quant_scaling == "delayed"`` the scan carry is
    ``(params, qstate)`` — the per-layer amax state rides the chain
    exactly as it would ride a real training loop, which is the point
    of delayed scaling (the fresh-amax reduction is off the hot path,
    its replacement data flows step to step)."""
    from dlnetbench_tpu.models import transformer as tfm

    if tfm.needs_qstate(cfg):
        def train_k(carry, t):
            def body(carry, _):
                p, qs = carry
                (loss, new_qs), g = jax.value_and_grad(
                    tfm.loss_fn, has_aux=True)(p, t, cfg, qs)
                p = jax.tree.map(lambda a, b: a - 1e-3 * b.astype(a.dtype),
                                 p, g)
                return (p, new_qs), loss
            return jax.lax.scan(body, carry, None, length=k)
        return train_k

    def train_k(p, t):
        def body(p, _):
            loss, g = jax.value_and_grad(tfm.loss_fn)(p, t, cfg)
            p = jax.tree.map(lambda a, b: a - 1e-3 * b.astype(a.dtype),
                             p, g)
            return p, loss
        return jax.lax.scan(body, p, None, length=k)
    return train_k


def build(k: int = 10, **cfg_overrides):
    """(train_k_fn, carry, tokens, card, cfg) at the bench shape; the
    carry is the params pytree, or ``(params, qstate)`` when the config
    threads delayed-scaling state (both donate as argument 0)."""
    import jax.numpy as jnp  # noqa: F401  (jax initialized before use)
    from dlnetbench_tpu.models import transformer as tfm
    card = bench_card()
    cfg = bench_cfg(card, **cfg_overrides)
    carry = tfm.init_params(jax.random.key(0), cfg)
    if tfm.needs_qstate(cfg):
        carry = (carry, tfm.init_qstate(cfg))
    tokens = jax.random.randint(jax.random.key(1), (BATCH, SEQ + 1), 0,
                                VOCAB)
    return make_train_k(cfg, k), carry, tokens, card, cfg
