"""Fully-sharded training step: dp x pp x tp(+sp,+ep) on one mesh.

This is the real-compute counterpart of the hybrid proxies — one manual
``shard_map`` program over a (dp, pp, tp) mesh implementing, with actual
math, every parallelism family the proxies replay as traffic (SURVEY.md
§2.5) plus the sequence dimension the reference lacks:

  dp  batch sharding; gradient psum over the dp axis
      (the proxies' dp allreduce, reference dp.cpp:87-106)
  pp  GPipe: layers split into stages, microbatches streamed with
      ``ppermute``; stage s works on microbatch t-s at tick t, bubbles
      masked (the hybrid_2d schedule, reference hybrid_2d.cpp:90-169)
  tp  Megatron attention/head sharding: column-parallel QKV, row-parallel
      output proj with psum_scatter (the hybrid_3d TP allreduces,
      reference hybrid_3d.cpp:142-148)
  sp  Megatron-style sequence parallelism: activations between blocks are
      sequence-sharded over the tp axis; all_gather to enter attention,
      psum_scatter to leave (no reference counterpart — SURVEY.md §5.7)
  ep  GShard/Mixtral expert parallelism: capacity-based top-k dispatch via
      one-hot matmuls, experts sharded over the tp axis, all_to_all to
      dispatch and combine (the hybrid_3d_moe A2As, reference
      hybrid_3d_moe.cpp:161-165)

Backward is ``jax.grad`` *through the collectives* (XLA transposes
ppermute/psum/all_to_all), then gradients are psum'd over every mesh axis
a parameter is replicated on.  The driver's ``dryrun_multichip`` entry
jit-compiles and runs this step on an N-virtual-device mesh.
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dlnetbench_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dlnetbench_tpu import ops
from dlnetbench_tpu.models import layers as Lyr
from dlnetbench_tpu.ops import sequence_parallel as SP
from dlnetbench_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_TP, make_grid_mesh

_F32 = jnp.float32


@dataclasses.dataclass(frozen=True)
class SpmdConfig:
    vocab_size: int = 128
    embed_dim: int = 64
    num_heads: int = 4
    num_kv_heads: int = 4
    ff_dim: int = 128
    num_layers: int = 4          # total; split over pp
    seq_len: int = 32            # split over tp (sequence parallelism)
    num_experts: int = 4         # split over tp (expert parallelism)
    top_k: int = 2
    capacity_factor: float = 2.0
    batch: int = 8               # split over dp
    num_microbatches: int = 2
    lr: float = 0.1
    dtype: str = "float32"       # bfloat16 on real TPU
    attention_impl: str = "auto"   # ops.attention dispatch: auto | flash | xla
    mlp_int8: bool = False       # run the three expert matmuls in int8
                             # (per-tensor scales, int32 MXU accumulation,
                             # straight-through backward —
                             # ops/int8.py int8_dot_batched): the r5
                             # single-chip 1.087x step win extended to
                             # the EP-sharded MoE path; the dispatch/
                             # combine all_to_alls and the router stay
                             # master-dtype
    # How attention handles the sequence sharding on the tp axis:
    #   megatron  gather the sequence, shard the heads (2 collectives per
    #             block: all_gather in, psum_scatter out) — the reference's
    #             hybrid_3d TP pattern re-expressed (hybrid_3d.cpp:142-148)
    #   ring      keep the sequence sharded; rotate KV around the axis with
    #             ppermute, online-softmax merge (ops/sequence_parallel.py)
    #             — heads replicated, attention weights replicated over tp
    #   ulysses   all_to_all to head-sharding and back; full-sequence local
    #             attention in between (flash kernel eligible)
    sp_mode: str = "megatron"

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    def validate(self, dp: int, pp: int, tp: int) -> None:
        # ring keeps all heads local, so head divisibility only binds the
        # modes that shard heads over tp (megatron statically, ulysses via
        # its all_to_all)
        heads_sharded = self.sp_mode in ("megatron", "ulysses")
        checks = [
            (self.sp_mode in ("megatron", "ring", "ulysses"),
             f"unknown sp_mode {self.sp_mode!r}"),
            (self.num_layers % pp == 0, "layers % pp != 0"),
            (self.batch % (dp * self.num_microbatches) == 0,
             "batch % (dp*microbatches) != 0"),
            (self.seq_len % tp == 0, "seq_len % tp != 0 (sp sharding)"),
            (not heads_sharded or self.num_heads % tp == 0,
             "heads % tp != 0"),
            (not heads_sharded or self.num_kv_heads % tp == 0,
             "kv_heads % tp != 0"),
            (self.num_experts % tp == 0, "experts % tp != 0 (ep sharding)"),
            (self.vocab_size % tp == 0, "vocab % tp != 0 (parallel head)"),
        ]
        for ok, what in checks:
            if not ok:
                raise ValueError(f"SpmdConfig invalid for mesh "
                                 f"({dp},{pp},{tp}): {what}")


# --------------------------------------------------------------------- #
# Parameter init + sharding specs (GLOBAL shapes; specs map to the mesh)
# --------------------------------------------------------------------- #


def init_params(key, cfg: SpmdConfig) -> dict:
    d, dh = cfg.embed_dim, cfg.head_dim
    dkv = cfg.num_kv_heads * dh
    h, L, v, e = cfg.ff_dim, cfg.num_layers, cfg.vocab_size, cfg.num_experts
    dt = cfg.jdtype
    s_d, s_h = 1.0 / math.sqrt(d), 1.0 / math.sqrt(h)
    ks = iter(jax.random.split(key, 16))
    return {
        "embed": Lyr.init_dense(next(ks), (v, d), 1.0, dt),
        "layers": {
            "wq": Lyr.init_dense(next(ks), (L, d, d), s_d, dt),
            "wk": Lyr.init_dense(next(ks), (L, d, dkv), s_d, dt),
            "wv": Lyr.init_dense(next(ks), (L, d, dkv), s_d, dt),
            "wo": Lyr.init_dense(next(ks), (L, d, d), s_d, dt),
            "norm1": jnp.ones((L, d), dt),
            "norm2": jnp.ones((L, d), dt),
            "w_router": Lyr.init_dense(next(ks), (L, d, e), s_d, dt),
            "w_gate": Lyr.init_dense(next(ks), (L, e, d, h), s_d, dt),
            "w_up": Lyr.init_dense(next(ks), (L, e, d, h), s_d, dt),
            "w_down": Lyr.init_dense(next(ks), (L, e, h, d), s_h, dt),
        },
        "final_norm": jnp.ones((d,), dt),
        "head": Lyr.init_dense(next(ks), (d, v), s_d, dt),
    }


def param_specs(sp_mode: str = "megatron") -> dict:
    """PartitionSpec per leaf: layer stack over pp; Megatron TP on qkv/o
    (megatron mode) or attention weights replicated over tp (ring/ulysses,
    which shard activations, not weights); experts over tp (ep); parallel
    head over tp on vocab."""
    if sp_mode == "megatron":
        wq = wk = wv = P(AXIS_PP, None, AXIS_TP)   # column parallel
        wo = P(AXIS_PP, AXIS_TP, None)             # row parallel
    else:
        wq = wk = wv = wo = P(AXIS_PP, None, None)
    return {
        "embed": P(),                              # replicated
        "layers": {
            "wq": wq,
            "wk": wk,
            "wv": wv,
            "wo": wo,
            "norm1": P(AXIS_PP, None),
            "norm2": P(AXIS_PP, None),
            "w_router": P(AXIS_PP, None, None),
            "w_gate": P(AXIS_PP, AXIS_TP, None, None),   # expert sharded
            "w_up": P(AXIS_PP, AXIS_TP, None, None),
            "w_down": P(AXIS_PP, AXIS_TP, None, None),
        },
        "final_norm": P(),
        "head": P(None, AXIS_TP),                  # parallel vocab head
    }


def param_shardings(mesh: Mesh, sp_mode: str = "megatron") -> dict:
    """NamedSharding per parameter — e.g. a checkpoint-restore template
    (utils/checkpoint.py) that lands each shard on its mesh device."""
    from jax.sharding import NamedSharding
    return jax.tree.map(lambda sp: NamedSharding(mesh, sp),
                        param_specs(sp_mode),
                        is_leaf=lambda x: isinstance(x, P))


def _replicated_axes(spec: P) -> tuple:
    """Mesh axes (excluding dp, which every grad is already mean-reduced
    over) that a parameter is replicated across — its gradient must be
    psum'd over exactly these."""
    used = {a for part in spec if part
            for a in ((part,) if isinstance(part, str) else part)}
    return tuple(a for a in (AXIS_PP, AXIS_TP) if a not in used)


# --------------------------------------------------------------------- #
# Per-device (shard_map) forward
# --------------------------------------------------------------------- #
def _moe_block(cfg: SpmdConfig, tp: int, y, lp):
    """y: [mb, S/tp, d] local tokens; experts sharded over tp (EP)."""
    mb, s_loc, d = y.shape
    x2 = y.reshape(mb * s_loc, d)
    # capacity-based one-hot dispatch (GShard style) — the shared math in
    # models/layers.py, so the single-device sparse MoE and this
    # EP-sharded path can never drift apart
    ein, disp, gate = Lyr.moe_dispatch(x2, lp["w_router"], cfg.num_experts,
                                       cfg.top_k, cfg.capacity_factor)
    # EP all_to_all: [E, C, d] -> [E/tp, C*tp, d] (each rank gets its experts'
    # tokens from every peer — the hybrid_3d_moe dispatch A2A)
    if tp > 1:
        ein = lax.all_to_all(ein, AXIS_TP, split_axis=0, concat_axis=1,
                             tiled=True)
    ein = ein.astype(cfg.jdtype)
    if cfg.mlp_int8:
        from dlnetbench_tpu.ops.int8 import int8_dot_batched
        g = int8_dot_batched(ein, lp["w_gate"].astype(cfg.jdtype))
        u = int8_dot_batched(ein, lp["w_up"].astype(cfg.jdtype))
        h = jax.nn.silu(g.astype(_F32)) * u.astype(_F32)
        out = int8_dot_batched(h.astype(cfg.jdtype),
                               lp["w_down"].astype(cfg.jdtype))
        out = out.astype(_F32)
    else:
        h = jax.nn.silu(jnp.einsum("ecd,edh->ech", ein, lp["w_gate"],
                                   preferred_element_type=_F32))
        h = h * jnp.einsum("ecd,edh->ech", ein, lp["w_up"],
                           preferred_element_type=_F32)
        out = jnp.einsum("ech,ehd->ecd", h.astype(cfg.jdtype),
                         lp["w_down"], preferred_element_type=_F32)
    if tp > 1:  # combine A2A (reverse reshard)
        out = lax.all_to_all(out, AXIS_TP, split_axis=1, concat_axis=0,
                             tiled=True)
    y2 = Lyr.moe_combine(out, disp, gate)
    return y2.reshape(mb, s_loc, d).astype(y.dtype)


def _stage_block(cfg: SpmdConfig, tp: int, x, lp, positions):
    """One decoder block under TP+SP; x: [mb, S/tp, d] sequence-sharded.

    ``positions``: the GLOBAL positions matching the sequence length rope
    sees — the full [S] in megatron mode (rope runs after the gather),
    this shard's [S/tp] slice in ring/ulysses mode (rope runs locally).
    """
    mb, s_loc, d = x.shape
    dh = cfg.head_dim

    y = Lyr.rmsnorm(x, lp["norm1"])
    if cfg.sp_mode == "megatron" and tp > 1:
        # gather the full sequence, shard the heads (Megatron SP)
        h_loc = cfg.num_heads // tp
        hkv_loc = cfg.num_kv_heads // tp
        y = lax.all_gather(y, AXIS_TP, axis=1, tiled=True)   # [mb, S, d]
        s_full = y.shape[1]
        q = jnp.dot(y, lp["wq"]).reshape(mb, s_full, h_loc, dh)
        k = jnp.dot(y, lp["wk"]).reshape(mb, s_full, hkv_loc, dh)
        v = jnp.dot(y, lp["wv"]).reshape(mb, s_full, hkv_loc, dh)
        q, k = Lyr.rope(q, k, positions)
        att = ops.attention(q, k, v, causal=True,
                            impl=cfg.attention_impl).reshape(
            mb, s_full, d // tp)
        out = jnp.dot(att, lp["wo"])                          # partial sums
        # reduce partials and scatter back to sequence shards
        out = lax.psum_scatter(out, AXIS_TP, scatter_dimension=1, tiled=True)
    else:
        # sequence stays sharded: project this shard with ALL heads
        # (attention weights replicated over tp in these modes)
        q = jnp.dot(y, lp["wq"]).reshape(mb, s_loc, cfg.num_heads, dh)
        k = jnp.dot(y, lp["wk"]).reshape(mb, s_loc, cfg.num_kv_heads, dh)
        v = jnp.dot(y, lp["wv"]).reshape(mb, s_loc, cfg.num_kv_heads, dh)
        q, k = Lyr.rope(q, k, positions)
        if tp > 1 and cfg.sp_mode == "ring":
            att = SP.ring_attention(q, k, v, AXIS_TP, causal=True)
        elif tp > 1 and cfg.sp_mode == "ulysses":
            att = SP.ulysses_attention(q, k, v, AXIS_TP, causal=True,
                                       impl=cfg.attention_impl)
        else:   # tp == 1: plain local attention
            att = ops.attention(q, k, v, causal=True,
                                impl=cfg.attention_impl)
        out = jnp.dot(att.reshape(mb, s_loc, d), lp["wo"])
    x = x + out

    y = Lyr.rmsnorm(x, lp["norm2"])
    return x + _moe_block(cfg, tp, y, lp)


def _vocab_parallel_ce(logits_loc, targets, tp: int, vocab: int):
    """Megatron-style vocab-parallel cross entropy.

    ``logits_loc``: [..., V/tp] — this rank's vocab shard of the logits for
    the FULL (gathered) token set; ``targets``: [...] global vocab ids.
    Softmax normalization and the target logit are assembled with
    pmax/psum over the tp axis; every rank returns the same scalar.
    """
    v_loc = logits_loc.shape[-1]
    shard = lax.axis_index(AXIS_TP)
    lg = logits_loc.astype(_F32)
    # the max shift is numerical stabilization only — constant wrt autodiff
    m = jnp.max(lax.stop_gradient(lg), axis=-1)
    gmax = lax.pmax(m, AXIS_TP)
    sumexp = jnp.sum(jnp.exp(lg - gmax[..., None]), axis=-1)
    denom = lax.psum(sumexp, AXIS_TP)
    local_t = targets - shard * v_loc
    in_range = (local_t >= 0) & (local_t < v_loc)
    tval = jnp.take_along_axis(
        lg, jnp.clip(local_t, 0, v_loc - 1)[..., None], axis=-1)[..., 0]
    tval = lax.psum(jnp.where(in_range, tval, 0.0), AXIS_TP)
    return jnp.mean(jnp.log(denom) + gmax - tval)


def make_train_step(mesh: Mesh, cfg: SpmdConfig):
    dp, pp, tp = (mesh.devices.shape[mesh.axis_names.index(a)]
                  for a in (AXIS_DP, AXIS_PP, AXIS_TP))
    cfg.validate(dp, pp, tp)
    specs = param_specs(cfg.sp_mode)
    mb_size = cfg.batch // (dp * cfg.num_microbatches)
    m = cfg.num_microbatches

    def local_loss(params_loc, tokens_loc):
        """Per-device pipeline forward; tokens_loc: [B/dp, S+1]."""
        stage = lax.axis_index(AXIS_PP)
        tp_idx = lax.axis_index(AXIS_TP)
        s_loc = cfg.seq_len // tp
        inputs = tokens_loc[:, :-1].reshape(m, mb_size, cfg.seq_len)
        targets = tokens_loc[:, 1:].reshape(m, mb_size, cfg.seq_len)
        # rope positions: full sequence in megatron mode (rope follows the
        # gather), this shard's global slice in ring/ulysses mode
        if cfg.sp_mode == "megatron":
            positions = jnp.arange(cfg.seq_len)
        else:
            positions = tp_idx * s_loc + jnp.arange(s_loc)

        def run_stage(x):
            def body(carry, lp):
                return _stage_block(cfg, tp, carry, lp, positions), None
            out, _ = lax.scan(body, x, params_loc["layers"])
            return out

        ticks = m + pp - 1
        x_carry = jnp.zeros((mb_size, s_loc, cfg.embed_dim), cfg.jdtype)
        loss_sum = jnp.zeros((), _F32)
        for t in range(ticks):
            mb_me = t - stage                       # my microbatch this tick
            mb_c = jnp.clip(mb_me, 0, m - 1)
            valid = (mb_me >= 0) & (mb_me < m)
            inp = lax.dynamic_index_in_dim(inputs, mb_c, 0, keepdims=False)
            # sequence shard for SP: my slice of the sequence
            inp_loc = lax.dynamic_slice_in_dim(inp, tp_idx * s_loc, s_loc, 1)
            emb = params_loc["embed"][inp_loc]      # [mb, S/tp, d]
            x_in = jnp.where(stage == 0, emb, x_carry)
            x_out = run_stage(x_in)
            # last stage: loss for this tick's microbatch
            xh = Lyr.rmsnorm(x_out, params_loc["final_norm"])
            tgt = lax.dynamic_index_in_dim(targets, mb_c, 0, keepdims=False)
            if tp > 1:
                # gather the sequence so every rank scores all tokens
                # against its vocab shard, then vocab-parallel CE
                xh = lax.all_gather(xh, AXIS_TP, axis=1, tiled=True)
                logits_loc = jnp.dot(xh, params_loc["head"],
                                     preferred_element_type=_F32)
                # divided by tp: every tp rank computes the same replicated
                # scalar, so each seeds 1/tp of the cotangent — the psum
                # transposes inside the CE then deliver exactly 1 in total
                mb_loss = _vocab_parallel_ce(logits_loc, tgt, tp,
                                             cfg.vocab_size) / tp
            else:
                logits = jnp.dot(xh, params_loc["head"],
                                 preferred_element_type=_F32)
                mb_loss = Lyr.cross_entropy(logits, tgt)
            is_last = stage == pp - 1
            loss_sum = loss_sum + jnp.where(valid & is_last, mb_loss, 0.0)
            # stream activations to the next stage
            if pp > 1:
                perm = [(i, i + 1) for i in range(pp - 1)]
                x_carry = lax.ppermute(x_out, AXIS_PP, perm)
            else:
                x_carry = x_out
        # LOCAL loss (nonzero only on the last stage; 1/tp share per tp
        # rank).  Deliberately NOT psum'd here: a psum inside the
        # differentiated function transposes to a broadcast that double
        # counts every rank's unit cotangent seed (grads would scale by
        # the axis size).  step_local psums the value for reporting.
        return loss_sum / m

    def step_local(params_loc, tokens_loc):
        loss, grads = jax.value_and_grad(local_loss)(params_loc, tokens_loc)
        # grad sync: psum over dp (data parallel, mean) ...
        grads = jax.tree.map(lambda g: lax.psum(g, AXIS_DP) / dp, grads)
        # ... and over every axis the param is replicated on (transpose of
        # the implicit broadcast in the manual-sharding forward)
        grads = jax.tree.map(
            lambda g, sp: lax.psum(g, _replicated_axes(sp))
            if _replicated_axes(sp) else g,
            grads, specs, is_leaf=lambda x: isinstance(x, P))
        # reassemble the replicated loss value for reporting: sum the
        # last-stage / per-tp-rank shares, mean over dp groups
        loss = lax.psum(loss, (AXIS_PP, AXIS_TP))
        loss = lax.psum(loss, AXIS_DP) / dp
        new_params = jax.tree.map(lambda p_, g: p_ - cfg.lr * g.astype(p_.dtype),
                                  params_loc, grads)
        return new_params, loss

    in_specs = (specs, P(AXIS_DP, None))
    out_specs = (specs, P())
    fn = shard_map(step_local, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    return jax.jit(fn)


def factor_mesh(n_devices: int) -> tuple[int, int, int]:
    """(dp, pp, tp) for an n-device dry run: prefer 2-way pp and tp."""
    tp = 2 if n_devices % 2 == 0 else 1
    pp = 2 if n_devices % (2 * tp) == 0 else 1
    dp = n_devices // (pp * tp)
    return dp, pp, tp


def build(n_devices: int | None = None, cfg: SpmdConfig | None = None,
          devices=None):
    """Convenience: mesh + params + tokens + jitted step."""
    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    dp, pp, tp = factor_mesh(n)
    mesh = make_grid_mesh(dp=dp, pp=pp, tp=tp, devices=devices[:n])
    cfg = cfg or SpmdConfig()
    step = make_train_step(mesh, cfg)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1),
                                (cfg.batch, cfg.seq_len + 1), 0,
                                cfg.vocab_size)
    return mesh, cfg, step, params, tokens
