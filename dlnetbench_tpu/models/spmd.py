"""Fully-sharded training step: dp x pp x tp(+sp,+ep) on one mesh.

This is the real-compute counterpart of the hybrid proxies — one manual
``shard_map`` program over a (dp, pp, tp) mesh implementing, with actual
math, every parallelism family the proxies replay as traffic (SURVEY.md
§2.5) plus the sequence dimension the reference lacks:

  dp  batch sharding; gradient psum over the dp axis
      (the proxies' dp allreduce, reference dp.cpp:87-106)
  pp  GPipe: layers split into stages, microbatches streamed with
      ``ppermute``; stage s works on microbatch t-s at tick t, bubbles
      masked (the hybrid_2d schedule, reference hybrid_2d.cpp:90-169)
  tp  Megatron attention/head sharding: column-parallel QKV, row-parallel
      output proj with psum_scatter (the hybrid_3d TP allreduces,
      reference hybrid_3d.cpp:142-148)
  sp  Megatron-style sequence parallelism: activations between blocks are
      sequence-sharded over the tp axis; all_gather to enter attention,
      psum_scatter to leave (no reference counterpart — SURVEY.md §5.7)
  ep  GShard/Mixtral expert parallelism: capacity-based top-k dispatch via
      one-hot matmuls, experts sharded over the tp axis, all_to_all to
      dispatch and combine (the hybrid_3d_moe A2As, reference
      hybrid_3d_moe.cpp:161-165)

Backward is ``jax.grad`` *through the collectives* (XLA transposes
ppermute/psum/all_to_all), then gradients are psum'd over every mesh axis
a parameter is replicated on.  The driver's ``dryrun_multichip`` entry
jit-compiles and runs this step on an N-virtual-device mesh.

r7 overlap layer (docs/PERF.md round 7): ``tp_overlap="decomposed"``
replaces the blocking TP collectives with ppermute-pipelined collective
matmuls (ops/collective_matmul.py, forward and backward);
``grad_sync="bucketed"`` streams the DP grad psums per layer group in
reverse-layer order during backward instead of one end-of-step psum; and
``make_train_step(variant=...)`` provides the compute-only / comm-only
legs of the proxy tier's A/B decomposition for the real step, feeding
the measured overlap-fraction metric (metrics/stats.overlap_fraction,
models/overlap_bench.py).
"""
from __future__ import annotations

import dataclasses
import math
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dlnetbench_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dlnetbench_tpu import ops
from dlnetbench_tpu.models import layers as Lyr
from dlnetbench_tpu.ops import collective_matmul as CM
from dlnetbench_tpu.ops import sequence_parallel as SP
from dlnetbench_tpu.parallel import collectives as col
from dlnetbench_tpu.parallel.mesh import AXIS_DP, AXIS_PP, AXIS_TP, make_grid_mesh

_F32 = jnp.float32

# A/B decomposition variants of the train step (proxies/base.py timing
# protocol applied to the real-compute tier): "compute" strips every
# collective (local shape-preserving stand-ins), "comm" strips the heavy
# math (broadcast stubs with the same dataflow edges) — so the measured
# overlap fraction (metrics/stats.overlap_fraction) has its Tc and Tm.
VARIANTS = ("full", "compute", "comm")


@dataclasses.dataclass(frozen=True)
class SpmdConfig:
    vocab_size: int = 128
    embed_dim: int = 64
    num_heads: int = 4
    num_kv_heads: int = 4
    ff_dim: int = 128
    num_layers: int = 4          # total; split over pp
    seq_len: int = 32            # split over tp (sequence parallelism)
    num_experts: int = 4         # split over tp (expert parallelism)
    top_k: int = 2
    capacity_factor: float = 2.0
    batch: int = 8               # split over dp
    num_microbatches: int = 2
    lr: float = 0.1
    dtype: str = "float32"       # bfloat16 on real TPU
    attention_impl: str = "auto"   # ops.attention dispatch: auto | flash | xla
    mlp_int8: bool = False       # run the three expert matmuls in int8
                             # (per-tensor scales, int32 MXU accumulation,
                             # straight-through backward —
                             # ops/int8.py int8_dot_batched): the r5
                             # single-chip 1.087x step win extended to
                             # the EP-sharded MoE path; the dispatch/
                             # combine all_to_alls and the router stay
                             # master-dtype
    # How attention handles the sequence sharding on the tp axis:
    #   megatron  gather the sequence, shard the heads (2 collectives per
    #             block: all_gather in, psum_scatter out) — the reference's
    #             hybrid_3d TP pattern re-expressed (hybrid_3d.cpp:142-148)
    #   ring      keep the sequence sharded; rotate KV around the axis with
    #             ppermute, online-softmax merge (ops/sequence_parallel.py)
    #             — heads replicated, attention weights replicated over tp
    #   ulysses   all_to_all to head-sharding and back; full-sequence local
    #             attention in between (flash kernel eligible)
    sp_mode: str = "megatron"
    # Long-context attention-mask knobs (ISSUE 10; the TransformerConfig
    # trio mirrored): a sliding window and/or a seeded document-segment
    # plan.  megatron/ulysses modes apply the mask on the gathered
    # sequence (splash kernels on TPU, dense-masked reference on the
    # CPU mesh); ring mode additionally SKIPS whole ring hops whose
    # (my queries x remote keys) tile the mask kills — the ppermute
    # still runs, and the skipped-hop fraction is reported via
    # ``ring_hop_stats`` (the overlap-fraction metric's sibling).
    attention_window: int = 0
    attention_seg_avg: int = 0
    attention_seg_seed: int = 0
    # How the TP-block collectives execute (megatron QKV/out projections
    # and the vocab-parallel head):
    #   none        blocking all_gather / psum_scatter around plain dots
    #   decomposed  ppermute-pipelined collective matmuls
    #               (ops/collective_matmul.py): the gather/scatter is
    #               broken into ring chunks interleaved with the
    #               dependent matmul, forward AND backward (custom VJPs)
    tp_overlap: str = "none"
    # row chunks per ring block (overlap grain).  None = consult the
    # tuning DB (dlnetbench_tpu/tuning, keyed per shape x tp x chip)
    # and fall back to the frozen default 2 on a miss — an explicit
    # int ALWAYS wins (resolve_tuned; resolved in make_train_step)
    tp_overlap_chunks: int | None = None
    # DP gradient sync schedule:
    #   monolithic  one psum of the whole grad tree after backward
    #   bucketed    per-layer-group psums issued in reverse-layer order,
    #               chained with collectives.tie so each bucket's sync
    #               streams as soon as its grads materialize (ZeRO/FSDP
    #               bucketing, the dp proxy's schedule made real)
    grad_sync: str = "monolithic"
    # local layers per bucket.  None = tuning-DB consult, frozen
    # default 1 on a miss; explicit ints always win (resolve_tuned)
    grad_bucket_layers: int | None = None
    # --- ISSUE 15: expert-parallel MoE knobs -------------------------
    # How the EP dispatch/combine all-to-alls execute:
    #   monolithic  blocking lax.all_to_all pair around the expert FFN
    #               (the pre-ISSUE-15 spelling, bit-identical)
    #   decomposed  ppermute chunk loop fused with the expert FFN
    #               (ops/moe_dispatch.a2a_expert_ffn): each peer
    #               block's dispatch hop / expert compute / combine
    #               hop interleave, forward AND backward (custom VJP)
    moe_a2a: str = "monolithic"
    # FFN capacity-axis chunks per peer block (decomposed overlap
    # grain — the moe sibling of tp_overlap_chunks)
    moe_chunks: int = 1
    # Token-drop determinism (models/moe.py): None keeps the legacy
    # per-rank arrival-order drop (bit-identical); an int switches to
    # the seeded priority over GLOBAL token ids, which (with
    # moe_group_tokens) makes the kept/dropped set identical across
    # shard counts — the dryrun's token-identical-routing bar
    moe_drop_seed: int | None = None
    # Capacity-group size in tokens (0 = this rank's whole per-tick
    # buffer, the legacy semantics).  Must divide the sequence shard
    # (seq_len/tp) so groups never straddle shard boundaries
    moe_group_tokens: int = 0
    # Expert FFN implementation: "einsum" (XLA batched einsums, the
    # legacy spelling) | "grouped" (Pallas grouped-matmul kernels,
    # ops/grouped_matmul.py — block shapes a tuning-DB site)
    moe_ffn_impl: str = "einsum"
    # Fused-quantization recipe for the grouped expert FFN ("none" |
    # "int8" | "float8"): per-expert dynamic scales quantize the
    # activation tile in the kernel's VMEM prologue (the PR-3 recipe);
    # requires moe_ffn_impl="grouped" and excludes mlp_int8 (two
    # quant recipes on one matmul would measure neither)
    moe_ffn_quant: str = "none"

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    def resolve_tuned(self, dp: int, pp: int, tp: int) -> "SpmdConfig":
        """Concrete overlap-grain / bucket-size knobs: explicit user
        values pass through untouched; ``None`` fields consult the
        tuning DB (dlnetbench_tpu/tuning — frozen after first consult)
        and fall back to the frozen defaults (chunks=2, bucket=1) on a
        miss, so an empty DB is bit-identical to the pre-tuning
        harness.  A knob whose mode is off (``tp_overlap='none'`` /
        ``grad_sync='monolithic'``) resolves straight to its default
        WITHOUT a consult — the compiled program doesn't depend on it,
        and a logged "hit" on an inert knob would stamp tuned
        provenance onto a bit-identical-to-untuned run.  Returns self
        when nothing needed resolving."""
        chunks, bucket = self.tp_overlap_chunks, self.grad_bucket_layers
        if chunks is None or bucket is None:
            from dlnetbench_tpu import tuning

            def positive(field):
                def check(cfg):
                    v = cfg.get(field)
                    if not isinstance(v, int) or v < 1:
                        raise ValueError(f"{field}={v!r} is not a "
                                         f"positive int")
                return check
            if chunks is None:
                if self.tp_overlap != "decomposed":
                    chunks = 2   # inert knob: frozen default, no consult
                else:
                    chunks = tuning.consult(
                        "tp_overlap_chunks",
                        tuning.params.tp_overlap_chunks_key(
                            self.embed_dim, self.ff_dim, self.seq_len,
                            tp, self.dtype),
                        {"chunks": 2},
                        validate=positive("chunks"))["chunks"]
            if bucket is None:
                if self.grad_sync != "bucketed":
                    bucket = 1   # inert knob: frozen default, no consult
                else:
                    bucket = tuning.consult(
                        "grad_bucket_layers",
                        tuning.params.grad_bucket_layers_key(
                            self.num_layers, dp, pp, self.embed_dim,
                            self.ff_dim),
                        {"layers": 1},
                        validate=positive("layers"))["layers"]
        if (chunks, bucket) == (self.tp_overlap_chunks,
                                self.grad_bucket_layers):
            return self
        return dataclasses.replace(self, tp_overlap_chunks=chunks,
                                   grad_bucket_layers=bucket)

    @property
    def jdtype(self):
        return jnp.dtype(self.dtype)

    @property
    def mask_spec(self):
        """The attention MaskSpec these knobs declare, or None for the
        dense-causal default (bit-identical pre-mask behavior) — the
        shared TransformerConfig mapping (MaskSpec.from_knobs)."""
        from dlnetbench_tpu.ops.attention_mask import MaskSpec
        return MaskSpec.from_knobs(self.attention_window,
                                   self.attention_seg_avg,
                                   self.attention_seg_seed)

    def ring_hop_stats(self, tp: int) -> dict:
        """Skipped-hop accounting for sp_mode='ring' on a tp-wide ring
        (host-side, plan-derived — the record stamps it next to the
        mask globals; ops/attention_mask.ring_skipped_hop_fraction)."""
        from dlnetbench_tpu.ops import attention_mask as amask
        frac = amask.ring_skipped_hop_fraction(self.mask_spec,
                                               self.seq_len, tp)
        return {"ring_hops": tp * tp,
                "ring_skipped_hop_fraction": round(frac, 6)}

    def validate(self, dp: int, pp: int, tp: int) -> None:
        # ring keeps all heads local, so head divisibility only binds the
        # modes that shard heads over tp (megatron statically, ulysses via
        # its all_to_all)
        heads_sharded = self.sp_mode in ("megatron", "ulysses")
        checks = [
            (self.sp_mode in ("megatron", "ring", "ulysses"),
             f"unknown sp_mode {self.sp_mode!r}"),
            (self.tp_overlap in ("none", "decomposed"),
             f"unknown tp_overlap {self.tp_overlap!r}"),
            (self.tp_overlap_chunks is None or self.tp_overlap_chunks >= 1,
             "tp_overlap_chunks < 1"),
            (self.grad_sync in ("monolithic", "bucketed"),
             f"unknown grad_sync {self.grad_sync!r}"),
            (self.grad_bucket_layers is None or
             self.grad_bucket_layers >= 1, "grad_bucket_layers < 1"),
            (self.attention_window >= 0, "attention_window < 0"),
            (self.attention_seg_avg >= 0, "attention_seg_avg < 0"),
            (self.moe_a2a in ("monolithic", "decomposed"),
             f"unknown moe_a2a {self.moe_a2a!r}"),
            (self.moe_chunks >= 1, "moe_chunks < 1"),
            (self.moe_group_tokens >= 0, "moe_group_tokens < 0"),
            (self.moe_group_tokens == 0
             or (self.seq_len // tp) % self.moe_group_tokens == 0,
             f"moe_group_tokens {self.moe_group_tokens} must divide "
             f"the sequence shard {self.seq_len // tp} (groups may "
             f"not straddle shard boundaries)"),
            (self.moe_ffn_impl in ("einsum", "grouped"),
             f"unknown moe_ffn_impl {self.moe_ffn_impl!r}"),
            (self.moe_ffn_quant in ("none", "int8", "float8"),
             f"unknown moe_ffn_quant {self.moe_ffn_quant!r}"),
            (self.moe_ffn_quant == "none"
             or self.moe_ffn_impl == "grouped",
             "moe_ffn_quant requires moe_ffn_impl='grouped' (the "
             "fused recipes live in the grouped kernel)"),
            (not (self.mlp_int8 and self.moe_ffn_impl == "grouped"),
             "mlp_int8 and moe_ffn_impl='grouped' are two quant "
             "recipes on one matmul — pick one"),
            (self.num_layers % pp == 0, "layers % pp != 0"),
            (self.batch % (dp * self.num_microbatches) == 0,
             "batch % (dp*microbatches) != 0"),
            (self.seq_len % tp == 0, "seq_len % tp != 0 (sp sharding)"),
            (not heads_sharded or self.num_heads % tp == 0,
             "heads % tp != 0"),
            (not heads_sharded or self.num_kv_heads % tp == 0,
             "kv_heads % tp != 0"),
            (self.num_experts % tp == 0, "experts % tp != 0 (ep sharding)"),
            (self.vocab_size % tp == 0, "vocab % tp != 0 (parallel head)"),
        ]
        for ok, what in checks:
            if not ok:
                raise ValueError(f"SpmdConfig invalid for mesh "
                                 f"({dp},{pp},{tp}): {what}")


# --------------------------------------------------------------------- #
# Parameter init + sharding specs (GLOBAL shapes; specs map to the mesh)
# --------------------------------------------------------------------- #


def init_params(key, cfg: SpmdConfig) -> dict:
    d, dh = cfg.embed_dim, cfg.head_dim
    dkv = cfg.num_kv_heads * dh
    h, L, v, e = cfg.ff_dim, cfg.num_layers, cfg.vocab_size, cfg.num_experts
    dt = cfg.jdtype
    s_d, s_h = 1.0 / math.sqrt(d), 1.0 / math.sqrt(h)
    ks = iter(jax.random.split(key, 16))
    return {
        "embed": Lyr.init_dense(next(ks), (v, d), 1.0, dt),
        "layers": {
            "wq": Lyr.init_dense(next(ks), (L, d, d), s_d, dt),
            "wk": Lyr.init_dense(next(ks), (L, d, dkv), s_d, dt),
            "wv": Lyr.init_dense(next(ks), (L, d, dkv), s_d, dt),
            "wo": Lyr.init_dense(next(ks), (L, d, d), s_d, dt),
            "norm1": jnp.ones((L, d), dt),
            "norm2": jnp.ones((L, d), dt),
            "w_router": Lyr.init_dense(next(ks), (L, d, e), s_d, dt),
            "w_gate": Lyr.init_dense(next(ks), (L, e, d, h), s_d, dt),
            "w_up": Lyr.init_dense(next(ks), (L, e, d, h), s_d, dt),
            "w_down": Lyr.init_dense(next(ks), (L, e, h, d), s_h, dt),
        },
        "final_norm": jnp.ones((d,), dt),
        "head": Lyr.init_dense(next(ks), (d, v), s_d, dt),
    }


def param_specs(sp_mode: str = "megatron") -> dict:
    """PartitionSpec per leaf: layer stack over pp; Megatron TP on qkv/o
    (megatron mode) or attention weights replicated over tp (ring/ulysses,
    which shard activations, not weights); experts over tp (ep); parallel
    head over tp on vocab."""
    if sp_mode == "megatron":
        wq = wk = wv = P(AXIS_PP, None, AXIS_TP)   # column parallel
        wo = P(AXIS_PP, AXIS_TP, None)             # row parallel
    else:
        wq = wk = wv = wo = P(AXIS_PP, None, None)
    return {
        "embed": P(),                              # replicated
        "layers": {
            "wq": wq,
            "wk": wk,
            "wv": wv,
            "wo": wo,
            "norm1": P(AXIS_PP, None),
            "norm2": P(AXIS_PP, None),
            "w_router": P(AXIS_PP, None, None),
            "w_gate": P(AXIS_PP, AXIS_TP, None, None),   # expert sharded
            "w_up": P(AXIS_PP, AXIS_TP, None, None),
            "w_down": P(AXIS_PP, AXIS_TP, None, None),
        },
        "final_norm": P(),
        "head": P(None, AXIS_TP),                  # parallel vocab head
    }


def param_shardings(mesh: Mesh, sp_mode: str = "megatron") -> dict:
    """NamedSharding per parameter — e.g. a checkpoint-restore template
    (utils/checkpoint.py) that lands each shard on its mesh device."""
    from jax.sharding import NamedSharding
    return jax.tree.map(lambda sp: NamedSharding(mesh, sp),
                        param_specs(sp_mode),
                        is_leaf=lambda x: isinstance(x, P))


def _replicated_axes(spec: P) -> tuple:
    """Mesh axes (excluding dp, which every grad is already mean-reduced
    over) that a parameter is replicated across — its gradient must be
    psum'd over exactly these."""
    used = {a for part in spec if part
            for a in ((part,) if isinstance(part, str) else part)}
    return tuple(a for a in (AXIS_PP, AXIS_TP) if a not in used)


# --------------------------------------------------------------------- #
# Per-device (shard_map) forward
# --------------------------------------------------------------------- #
def _local_a2a(x, tp: int, split_axis: int, concat_axis: int):
    """Shape-equivalent local stand-in for a tiled all_to_all (compute
    A/B variant: same output shape, zero wire traffic)."""
    parts = jnp.split(x, tp, axis=split_axis)
    return jnp.concatenate(parts, axis=concat_axis)


def _moe_block(cfg: SpmdConfig, tp: int, y, lp, gids, comm_on=True,
               compute_on=True):
    """y: [mb, S/tp, d] local tokens; experts sharded over tp (EP).

    ``gids``: [mb, S/tp] GLOBAL token ids — the seeded drop priority's
    domain (models/moe.py), so routing is identical however the batch
    is sharded.  Routing dispatches through ``models/moe.dispatch``
    (legacy knobs delegate to ``layers.moe_dispatch`` bit-identically);
    the a2a pair runs blocking (``moe_a2a="monolithic"``) or as the
    ppermute chunk loop fused with the expert FFN
    (``"decomposed"`` — ops/moe_dispatch.a2a_expert_ffn, the
    hybrid_3d_moe dispatch/combine A2As overlapped)."""
    from dlnetbench_tpu.models import moe as MoE
    from dlnetbench_tpu.ops import moe_dispatch as MD
    mb, s_loc, d = y.shape
    t = mb * s_loc
    x2 = y.reshape(t, d)
    quant = None if cfg.moe_ffn_quant == "none" else cfg.moe_ffn_quant
    if compute_on:
        ein, disp, gate = MoE.dispatch(
            x2, lp["w_router"], cfg.num_experts, cfg.top_k,
            cfg.capacity_factor, drop_seed=cfg.moe_drop_seed,
            group_tokens=cfg.moe_group_tokens, gids=gids.reshape(t))
    else:   # comm variant: dispatch stubbed, buffer shapes preserved
        g = cfg.moe_group_tokens or t
        c_total = (t // g) * MoE.group_capacity(
            g, cfg.top_k, cfg.num_experts, cfg.capacity_factor)
        ein = CM.comm_stub((cfg.num_experts, c_total, d), _F32, x2,
                           lp["w_router"])
        disp = gate = None
    if cfg.moe_a2a == "decomposed" and tp > 1:
        # dispatch a2a + expert FFN + combine a2a as ONE fused
        # ppermute chunk loop — each peer block's hops overlap the
        # blocks already computing, forward and backward
        out = MD.a2a_expert_ffn(
            ein.astype(cfg.jdtype), lp["w_gate"], lp["w_up"],
            lp["w_down"], AXIS_TP, chunks=cfg.moe_chunks,
            fake_compute=not compute_on, fake_comm=not comm_on,
            ffn_impl=cfg.moe_ffn_impl, quant=quant,
            mlp_int8=cfg.mlp_int8)
    else:
        # EP all_to_all: [E, C, d] -> [E/tp, C*tp, d] (each rank gets
        # its experts' tokens from every peer — the hybrid_3d_moe
        # dispatch A2A)
        if tp > 1:
            ein = (lax.all_to_all(ein, AXIS_TP, split_axis=0,
                                  concat_axis=1, tiled=True) if comm_on
                   else _local_a2a(ein, tp, 0, 1))
        ein = ein.astype(cfg.jdtype)
        if not compute_on:
            out = CM.comm_stub(ein.shape, _F32, ein, lp["w_gate"],
                               lp["w_up"], lp["w_down"])
        else:
            # the shared expert-FFN dispatch point (models/moe.py):
            # einsum (bit-identical legacy spelling, incl. the r5
            # mlp_int8 recipe) or the grouped Pallas kernels
            out = MoE.expert_ffn(ein, lp["w_gate"], lp["w_up"],
                                 lp["w_down"], impl=cfg.moe_ffn_impl,
                                 quant=quant, mlp_int8=cfg.mlp_int8)
        if tp > 1:  # combine A2A (reverse reshard)
            out = (lax.all_to_all(out, AXIS_TP, split_axis=1,
                                  concat_axis=0, tiled=True) if comm_on
                   else _local_a2a(out, tp, 1, 0))
    if compute_on:
        y2 = Lyr.moe_combine(out, disp, gate)
    else:
        y2 = CM.comm_stub((t, d), _F32, out)
    return y2.reshape(mb, s_loc, d).astype(y.dtype)


def _stage_block(cfg: SpmdConfig, tp: int, x, lp, positions, gids,
                 comm_on=True, compute_on=True):
    """One decoder block under TP+SP; x: [mb, S/tp, d] sequence-sharded.

    ``positions``: the GLOBAL positions matching the sequence length rope
    sees — the full [S] in megatron mode (rope runs after the gather),
    this shard's [S/tp] slice in ring/ulysses mode (rope runs locally).
    ``gids``: [mb, S/tp] global token ids for the seeded MoE drop
    priority (models/moe.py — shard-layout invariant routing).
    """
    mb, s_loc, d = x.shape
    dh = cfg.head_dim
    decomposed = cfg.tp_overlap == "decomposed"

    y = Lyr.rmsnorm(x, lp["norm1"])
    if cfg.sp_mode == "megatron" and tp > 1:
        # gather the full sequence, shard the heads (Megatron SP)
        h_loc = cfg.num_heads // tp
        hkv_loc = cfg.num_kv_heads // tp
        qw, kvw = h_loc * dh, hkv_loc * dh
        if decomposed:
            # collective matmul: the gather rides the QKV projection as
            # ppermute-pipelined chunks (one fused weight so a single
            # ring serves all three column-parallel projections —
            # concatenated ONCE per step outside the layer scan by
            # local_loss, not per layer per microbatch here)
            qkv = CM.all_gather_matmul(
                y, lp["w_qkv"], AXIS_TP, gather_axis=1,
                chunks=cfg.tp_overlap_chunks,
                fake_compute=not compute_on, fake_comm=not comm_on)
            s_full = qkv.shape[1]
            q = qkv[..., :qw].reshape(mb, s_full, h_loc, dh)
            k = qkv[..., qw:qw + kvw].reshape(mb, s_full, hkv_loc, dh)
            v = qkv[..., qw + kvw:].reshape(mb, s_full, hkv_loc, dh)
        else:
            y = (lax.all_gather(y, AXIS_TP, axis=1, tiled=True)
                 if comm_on else jnp.concatenate([y] * tp, axis=1))
            s_full = y.shape[1]
            if compute_on:
                q = jnp.dot(y, lp["wq"]).reshape(mb, s_full, h_loc, dh)
                k = jnp.dot(y, lp["wk"]).reshape(mb, s_full, hkv_loc, dh)
                v = jnp.dot(y, lp["wv"]).reshape(mb, s_full, hkv_loc, dh)
            else:
                q = CM.comm_stub((mb, s_full, h_loc, dh), y.dtype, y,
                                 lp["wq"])
                k = CM.comm_stub((mb, s_full, hkv_loc, dh), y.dtype, y,
                                 lp["wk"])
                v = CM.comm_stub((mb, s_full, hkv_loc, dh), y.dtype, y,
                                 lp["wv"])
        if compute_on:
            q, k = Lyr.rope(q, k, positions)
            att = ops.attention(q, k, v, causal=True,
                                impl=cfg.attention_impl,
                                mask=cfg.mask_spec).reshape(
                mb, s_full, d // tp)
        else:
            att = CM.comm_stub((mb, s_full, d // tp), q.dtype, q, k, v)
        if decomposed:
            # reduce partials and scatter back to sequence shards, the
            # ring way: each hop overlaps the next block's partial matmul
            out = CM.matmul_reduce_scatter(
                att, lp["wo"], AXIS_TP, scatter_axis=1,
                chunks=cfg.tp_overlap_chunks,
                fake_compute=not compute_on, fake_comm=not comm_on)
        else:
            out = (jnp.dot(att, lp["wo"]) if compute_on
                   else CM.comm_stub((mb, s_full, d), att.dtype, att,
                                     lp["wo"]))         # partial sums
            # reduce partials and scatter back to sequence shards
            out = (lax.psum_scatter(out, AXIS_TP, scatter_dimension=1,
                                    tiled=True) if comm_on
                   else lax.slice_in_dim(out, 0, s_loc, axis=1))
    elif not compute_on:
        # comm variant reaching here means tp == 1 (the megatron-only
        # variant guard): the block has no collectives at all — stub it
        out = CM.comm_stub((mb, s_loc, d), x.dtype, y, lp["wq"],
                           lp["wo"])
    else:
        # sequence stays sharded: project this shard with ALL heads
        # (attention weights replicated over tp in these modes)
        q = jnp.dot(y, lp["wq"]).reshape(mb, s_loc, cfg.num_heads, dh)
        k = jnp.dot(y, lp["wk"]).reshape(mb, s_loc, cfg.num_kv_heads, dh)
        v = jnp.dot(y, lp["wv"]).reshape(mb, s_loc, cfg.num_kv_heads, dh)
        q, k = Lyr.rope(q, k, positions)
        if tp > 1 and cfg.sp_mode == "ring":
            att = SP.ring_attention(q, k, v, AXIS_TP, causal=True,
                                    spec=cfg.mask_spec)
        elif tp > 1 and cfg.sp_mode == "ulysses":
            att = SP.ulysses_attention(q, k, v, AXIS_TP, causal=True,
                                       impl=cfg.attention_impl,
                                       spec=cfg.mask_spec)
        else:   # tp == 1: plain local attention
            att = ops.attention(q, k, v, causal=True,
                                impl=cfg.attention_impl,
                                mask=cfg.mask_spec)
        out = jnp.dot(att.reshape(mb, s_loc, d), lp["wo"])
    x = x + out

    y = Lyr.rmsnorm(x, lp["norm2"])
    return x + _moe_block(cfg, tp, y, lp, gids, comm_on, compute_on)


def _vocab_parallel_ce(logits_loc, targets, tp: int, vocab: int,
                       comm_on=True):
    """Megatron-style vocab-parallel cross entropy.

    ``logits_loc``: [..., V/tp] — this rank's vocab shard of the logits for
    the FULL (gathered) token set; ``targets``: [...] global vocab ids.
    Softmax normalization and the target logit are assembled with
    pmax/psum over the tp axis; every rank returns the same scalar.
    """
    v_loc = logits_loc.shape[-1]
    shard = lax.axis_index(AXIS_TP)
    lg = logits_loc.astype(_F32)
    # the max shift is numerical stabilization only — constant wrt autodiff
    m = jnp.max(lax.stop_gradient(lg), axis=-1)
    gmax = lax.pmax(m, AXIS_TP) if comm_on else m
    sumexp = jnp.sum(jnp.exp(lg - gmax[..., None]), axis=-1)
    denom = lax.psum(sumexp, AXIS_TP) if comm_on else sumexp
    local_t = targets - shard * v_loc
    in_range = (local_t >= 0) & (local_t < v_loc)
    tval = jnp.take_along_axis(
        lg, jnp.clip(local_t, 0, v_loc - 1)[..., None], axis=-1)[..., 0]
    tval = jnp.where(in_range, tval, 0.0)
    if comm_on:
        tval = lax.psum(tval, AXIS_TP)
    return jnp.mean(jnp.log(denom) + gmax - tval)


def _bucketed_grad_sync(cfg: SpmdConfig, grads: dict, specs: dict,
                        dp: int, pp: int):
    """ZeRO/FSDP-style bucketed DP grad sync: per-layer-group psums in
    reverse-layer order (later layers' grads materialize first in
    backward), each bucket ``tie``-d to the previous bucket's result so
    XLA streams the syncs during backward instead of fusing them into
    one end-of-step collective.  Elementwise-identical math to the
    monolithic path (psum commutes with slicing)."""
    def sync_leaf(g, sp):
        g = lax.psum(g, AXIS_DP) / dp
        rep = _replicated_axes(sp)
        return lax.psum(g, rep) if rep else g

    is_p = lambda x: isinstance(x, P)  # noqa: E731
    dep = None

    def sync_part(part, spec_part):
        nonlocal dep
        if dep is not None:
            part = jax.tree.map(lambda g: col.tie(g, dep), part)
        out = jax.tree.map(sync_leaf, part, spec_part, is_leaf=is_p)
        dep = jax.tree.leaves(out)[0]
        return out

    # head + final_norm first: their grads are ready at the start of
    # backward; then layer groups last-to-first; embed's grads complete
    # only when backward finishes, so its bucket goes last
    tail = sync_part({"head": grads["head"],
                      "final_norm": grads["final_norm"]},
                     {"head": specs["head"],
                      "final_norm": specs["final_norm"]})
    layers_local = cfg.num_layers // pp
    step_l = min(cfg.grad_bucket_layers, layers_local)
    bounds = list(range(0, layers_local, step_l)) + [layers_local]
    slices = {}
    for b in reversed(range(len(bounds) - 1)):
        lo, hi = bounds[b], bounds[b + 1]
        part = {k: v[lo:hi] for k, v in grads["layers"].items()}
        slices[b] = sync_part(part, specs["layers"])
    head_bucket = sync_part({"embed": grads["embed"]},
                            {"embed": specs["embed"]})
    layers = {k: jnp.concatenate([slices[b][k]
                                  for b in range(len(bounds) - 1)], axis=0)
              for k in grads["layers"]}
    return {"embed": head_bucket["embed"], "layers": layers,
            "final_norm": tail["final_norm"], "head": tail["head"]}


def make_train_step(mesh: Mesh, cfg: SpmdConfig, variant: str = "full"):
    dp, pp, tp = (mesh.devices.shape[mesh.axis_names.index(a)]
                  for a in (AXIS_DP, AXIS_PP, AXIS_TP))
    # tuned-or-default knob resolution FIRST (explicit values pass
    # through; dlnetbench_tpu/tuning) so everything below — including
    # validate — sees concrete ints
    cfg = cfg.resolve_tuned(dp, pp, tp)
    cfg.validate(dp, pp, tp)
    if variant not in VARIANTS:
        raise ValueError(f"unknown variant {variant!r}; one of {VARIANTS}")
    comm_on = variant != "compute"
    compute_on = variant != "comm"
    if variant != "full" and cfg.sp_mode != "megatron":
        raise ValueError(
            "A/B decomposition variants are defined for sp_mode='megatron' "
            "(ring/ulysses interleave comm and compute inside "
            "ops/sequence_parallel.py, where the split has no meaning)")
    specs = param_specs(cfg.sp_mode)
    mb_size = cfg.batch // (dp * cfg.num_microbatches)
    m = cfg.num_microbatches

    def local_loss(params_loc, tokens_loc):
        """Per-device pipeline forward; tokens_loc: [B/dp, S+1]."""
        stage = lax.axis_index(AXIS_PP)
        tp_idx = lax.axis_index(AXIS_TP)
        s_loc = cfg.seq_len // tp
        inputs = tokens_loc[:, :-1].reshape(m, mb_size, cfg.seq_len)
        targets = tokens_loc[:, 1:].reshape(m, mb_size, cfg.seq_len)
        # rope positions: full sequence in megatron mode (rope follows the
        # gather), this shard's global slice in ring/ulysses mode
        if cfg.sp_mode == "megatron":
            positions = jnp.arange(cfg.seq_len)
        else:
            positions = tp_idx * s_loc + jnp.arange(s_loc)

        layers_xs = params_loc["layers"]
        if (cfg.tp_overlap == "decomposed" and cfg.sp_mode == "megatron"
                and tp > 1):
            # fuse the stacked column-parallel QKV weights ONCE per step
            # (autodiff splits the grad back through the concat): doing
            # this inside the scan body would copy the full QKV weight
            # per layer per microbatch — XLA cannot hoist a concat of
            # loop-carried slices out of a differentiated scan
            layers_xs = {**layers_xs,
                         "w_qkv": jnp.concatenate(
                             [layers_xs["wq"], layers_xs["wk"],
                              layers_xs["wv"]], axis=-1)}

        def run_stage(x, gids):
            def body(carry, lp):
                return _stage_block(cfg, tp, carry, lp, positions,
                                    gids, comm_on, compute_on), None
            out, _ = lax.scan(body, x, layers_xs)
            return out

        ticks = m + pp - 1
        x_carry = jnp.zeros((mb_size, s_loc, cfg.embed_dim), cfg.jdtype)
        loss_sum = jnp.zeros((), _F32)
        for t in range(ticks):
            mb_me = t - stage                       # my microbatch this tick
            mb_c = jnp.clip(mb_me, 0, m - 1)
            valid = (mb_me >= 0) & (mb_me < m)
            inp = lax.dynamic_index_in_dim(inputs, mb_c, 0, keepdims=False)
            # sequence shard for SP: my slice of the sequence
            inp_loc = lax.dynamic_slice_in_dim(inp, tp_idx * s_loc, s_loc, 1)
            emb = params_loc["embed"][inp_loc]      # [mb, S/tp, d]
            x_in = jnp.where(stage == 0, emb, x_carry)
            # global token ids of this rank's (microbatch, seq-shard)
            # block — the seeded MoE drop priority's domain: the same
            # token gets the same id on every mesh shape
            dp_idx = lax.axis_index(AXIS_DP)
            rows = (dp_idx * (cfg.batch // dp) + mb_c * mb_size
                    + jnp.arange(mb_size, dtype=jnp.int32))
            gids = (rows[:, None] * cfg.seq_len
                    + tp_idx * s_loc
                    + jnp.arange(s_loc, dtype=jnp.int32)[None, :])
            x_out = run_stage(x_in, gids)
            # last stage: loss for this tick's microbatch
            xh = Lyr.rmsnorm(x_out, params_loc["final_norm"])
            tgt = lax.dynamic_index_in_dim(targets, mb_c, 0, keepdims=False)
            if tp > 1:
                # gather the sequence so every rank scores all tokens
                # against its vocab shard, then vocab-parallel CE
                if cfg.tp_overlap == "decomposed":
                    # the gather rides the parallel-head projection as a
                    # decomposed collective matmul
                    logits_loc = CM.all_gather_matmul(
                        xh, params_loc["head"], AXIS_TP, gather_axis=1,
                        chunks=cfg.tp_overlap_chunks,
                        fake_compute=not compute_on,
                        fake_comm=not comm_on,
                        preferred_element_type=_F32)
                else:
                    xh = (lax.all_gather(xh, AXIS_TP, axis=1, tiled=True)
                          if comm_on
                          else jnp.concatenate([xh] * tp, axis=1))
                    logits_loc = (
                        jnp.dot(xh, params_loc["head"],
                                preferred_element_type=_F32) if compute_on
                        else CM.comm_stub(
                            xh.shape[:-1] + (params_loc["head"].shape[-1],),
                            _F32, xh, params_loc["head"]))
                # divided by tp: every tp rank computes the same replicated
                # scalar, so each seeds 1/tp of the cotangent — the psum
                # transposes inside the CE then deliver exactly 1 in total
                mb_loss = _vocab_parallel_ce(logits_loc, tgt, tp,
                                             cfg.vocab_size,
                                             comm_on) / tp
            else:
                logits = jnp.dot(xh, params_loc["head"],
                                 preferred_element_type=_F32)
                mb_loss = Lyr.cross_entropy(logits, tgt)
            is_last = stage == pp - 1
            loss_sum = loss_sum + jnp.where(valid & is_last, mb_loss, 0.0)
            # stream activations to the next stage
            if pp > 1 and comm_on:
                perm = [(i, i + 1) for i in range(pp - 1)]
                x_carry = lax.ppermute(x_out, AXIS_PP, perm)
            else:
                x_carry = x_out
        # LOCAL loss (nonzero only on the last stage; 1/tp share per tp
        # rank).  Deliberately NOT psum'd here: a psum inside the
        # differentiated function transposes to a broadcast that double
        # counts every rank's unit cotangent seed (grads would scale by
        # the axis size).  step_local psums the value for reporting.
        return loss_sum / m

    def step_local(params_loc, tokens_loc):
        loss, grads = jax.value_and_grad(local_loss)(params_loc, tokens_loc)
        if not comm_on:
            # compute variant: no sync, no loss reassembly — values are
            # wrong by construction, only the wall time is consumed
            new_params = jax.tree.map(
                lambda p_, g: p_ - cfg.lr * g.astype(p_.dtype),
                params_loc, grads)
            return new_params, loss
        if cfg.grad_sync == "bucketed":
            grads = _bucketed_grad_sync(cfg, grads, specs, dp, pp)
        else:
            # grad sync: psum over dp (data parallel, mean) ...
            grads = jax.tree.map(lambda g: lax.psum(g, AXIS_DP) / dp, grads)
            # ... and over every axis the param is replicated on
            # (transpose of the implicit broadcast in the manual-sharding
            # forward)
            grads = jax.tree.map(
                lambda g, sp: lax.psum(g, _replicated_axes(sp))
                if _replicated_axes(sp) else g,
                grads, specs, is_leaf=lambda x: isinstance(x, P))
        # reassemble the replicated loss value for reporting: sum the
        # last-stage / per-tp-rank shares, mean over dp groups
        loss = lax.psum(loss, (AXIS_PP, AXIS_TP))
        loss = lax.psum(loss, AXIS_DP) / dp
        new_params = jax.tree.map(lambda p_, g: p_ - cfg.lr * g.astype(p_.dtype),
                                  params_loc, grads)
        return new_params, loss

    in_specs = (specs, P(AXIS_DP, None))
    out_specs = (specs, P())
    fn = shard_map(step_local, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs, check_vma=False)
    return jax.jit(fn)


def factor_mesh(n_devices: int) -> tuple[int, int, int]:
    """(dp, pp, tp) for an n-device dry run: prefer 2-way pp and tp."""
    tp = 2 if n_devices % 2 == 0 else 1
    pp = 2 if n_devices % (2 * tp) == 0 else 1
    dp = n_devices // (pp * tp)
    return dp, pp, tp


def build(n_devices: int | None = None, cfg: SpmdConfig | None = None,
          devices=None):
    """Convenience: mesh + params + tokens + jitted step."""
    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    dp, pp, tp = factor_mesh(n)
    mesh = make_grid_mesh(dp=dp, pp=pp, tp=tp, devices=devices[:n])
    cfg = cfg or SpmdConfig()
    step = make_train_step(mesh, cfg)
    params = init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1),
                                (cfg.batch, cfg.seq_len + 1), 0,
                                cfg.vocab_size)
    return mesh, cfg, step, params, tokens
