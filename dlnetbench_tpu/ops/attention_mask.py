"""Block-sparse attention masks: declarative specs -> per-block verdicts.

The repo's attention paths were dense-causal only: every kernel paid the
full S x S score grid and masked half of it to -inf — masked-out but
still-paid MXU work, growing as S^2.  This module is the HOST-side mask
layer the splash kernels (ops/flash_attention.py), the sparse ring
attention (ops/sequence_parallel.py) and the serving prefill
(serving/decode.py) all consume:

* ``MaskSpec`` — a tiny declarative, hashable spec: ``causal``,
  ``sliding window(W)`` (each query attends its W most recent keys,
  itself included), and ``document segments`` from a SEEDED segment-id
  plan (splitmix64, the fault/arrival-plan generator — the plan is
  replayable from ``(seg_seed, seg_avg)`` alone), intersected freely.
* ``row_intervals`` — the load-bearing observation: for every spec this
  module admits, the allowed keys of a query row form ONE contiguous
  interval ``[lo[q], hi[q]]``, and both bounds are non-decreasing in
  ``q``.  Everything downstream (block verdicts, ring-hop verdicts,
  the in-kernel partial-block mask, the serving page window) is
  interval arithmetic on those two arrays — never an S x S
  materialization, which at S=64k would be the 4-billion-entry matrix
  this layer exists to avoid.
* ``BlockMask`` — per (q-block, kv-block) verdicts {skip, full,
  partial} precomputed on host from the intervals, plus the transposed
  (per-kv-block) visit ranges the dk/dv kernel grid needs and the
  ``sparsity_fraction`` stat the bench/record layer stamps.
* ``ring_hop_work`` — the same verdict at ring-hop granularity: an
  [n, n] table saying whether shard ``me``'s queries see shard
  ``src``'s keys at all; hops whose whole tile is SKIP never run their
  compute leg (ops/sequence_parallel.py).

``dense_mask`` builds the equivalent boolean S x S mask for the
CPU-mesh reference path (models/layers.py applies it densely), which is
what every parity test checks the sparse paths against.
"""
from __future__ import annotations

import dataclasses
import functools

import numpy as np

from dlnetbench_tpu.serving.arrivals import splitmix64

# BlockMask verdicts
SKIP, PARTIAL, FULL = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class MaskSpec:
    """Declarative attention-mask spec; hashable, so it rides as a
    static argument through ``jax.custom_vjp`` / ``functools.lru_cache``.

    window=W (W > 0): query q attends keys in ``(q - W, q]`` — the W
    most recent, itself included; requires ``causal`` (a non-causal
    sliding window has no consumer in this repo and would break the
    contiguous-interval property the block math relies on when
    intersected with segments).  seg_avg > 0 turns on the seeded
    document-segment plan: token positions are partitioned into
    documents whose lengths are splitmix64 draws around ``seg_avg``,
    and attention never crosses a document boundary."""
    causal: bool = True
    window: int = 0          # 0 = unbounded
    seg_avg: int = 0         # 0 = no segment structure (tokens)
    seg_seed: int = 0

    def __post_init__(self):
        if self.window < 0 or self.seg_avg < 0:
            raise ValueError(f"MaskSpec: window={self.window} / "
                             f"seg_avg={self.seg_avg} must be >= 0")
        if self.window and not self.causal:
            raise ValueError("MaskSpec: window requires causal=True "
                             "(non-causal sliding windows are not "
                             "supported)")
        if not (self.causal or self.seg_avg):
            raise ValueError("MaskSpec: the trivial all-allowed mask "
                             "has no sparse path — use causal=False "
                             "attention directly")

    @property
    def is_plain_causal(self) -> bool:
        """True when the spec is exactly the dense-causal default."""
        return self.causal and not self.window and not self.seg_avg

    def label(self) -> str:
        """Stable human/record key: 'causal', 'causal&window(512)',
        'causal&seg(avg=64,seed=0)', ..."""
        parts = []
        if self.causal:
            parts.append("causal")
        if self.window:
            parts.append(f"window({self.window})")
        if self.seg_avg:
            parts.append(f"seg(avg={self.seg_avg},seed={self.seg_seed})")
        return "&".join(parts)

    def to_dict(self) -> dict:
        return {"causal": self.causal, "window": self.window,
                "seg_avg": self.seg_avg, "seg_seed": self.seg_seed}

    @classmethod
    def from_dict(cls, d: dict) -> "MaskSpec":
        return cls(causal=bool(d.get("causal", True)),
                   window=int(d.get("window", 0)),
                   seg_avg=int(d.get("seg_avg", 0)),
                   seg_seed=int(d.get("seg_seed", 0)))

    @classmethod
    def from_knobs(cls, window: int, seg_avg: int,
                   seg_seed: int) -> "MaskSpec | None":
        """The config-knob trio (TransformerConfig / SpmdConfig
        ``attention_window``/``attention_seg_avg``/``attention_seg_seed``)
        -> spec, or None when both are off (the dense-causal default —
        bit-identical pre-mask behavior).  The ONE mapping both configs
        share, so their mask semantics can never drift apart."""
        if not (window or seg_avg):
            return None
        return cls(causal=True, window=window, seg_avg=seg_avg,
                   seg_seed=seg_seed)


@functools.lru_cache(maxsize=64)
def segment_ids(seg_seed: int, seg_avg: int, s: int) -> np.ndarray:
    """[S] int32 document ids from the seeded plan: lengths are
    splitmix64 draws uniform in [max(1, avg/2), avg + avg/2] (the
    arrival-plan length-range convention), ids monotone from 0.
    Deterministic in (seed, avg, S) — the plan is the JSON-able pair,
    not the array."""
    if seg_avg <= 0:
        raise ValueError(f"segment_ids: seg_avg={seg_avg} must be > 0")
    lo, hi = max(1, seg_avg // 2), seg_avg + seg_avg // 2
    state = (seg_seed * 0x9E3779B9 + 0xD1B54A32D192ED03) & ((1 << 64) - 1)
    ids = np.empty(s, np.int32)
    pos = doc = 0
    while pos < s:
        v, state = splitmix64(state)
        length = lo + v % (hi - lo + 1)
        ids[pos:pos + length] = doc
        pos += length
        doc += 1
    return ids


@functools.lru_cache(maxsize=64)
def row_intervals(spec: MaskSpec, s: int) -> tuple[np.ndarray, np.ndarray]:
    """Per-query allowed-key interval: ([S] lo, [S] hi), inclusive.

    Both arrays are non-decreasing (causal hi=q; window lo=q-W+1;
    segment bounds step monotonically), which is what makes every
    block-level union of row intervals itself contiguous — the property
    the verdict math and the ring-hop plan rely on."""
    q = np.arange(s, dtype=np.int64)
    lo = np.zeros(s, np.int64)
    hi = (q if spec.causal else np.full(s, s - 1, np.int64)).copy()
    if spec.window:
        lo = np.maximum(lo, q - spec.window + 1)
    if spec.seg_avg:
        ids = segment_ids(spec.seg_seed, spec.seg_avg, s).astype(np.int64)
        # first/last position of each row's document
        starts = np.searchsorted(ids, ids, side="left")
        ends = np.searchsorted(ids, ids, side="right") - 1
        lo = np.maximum(lo, starts)
        hi = np.minimum(hi, ends)
    if not np.all(lo <= hi):
        raise AssertionError("row_intervals: empty row interval — every "
                             "admitted spec keeps q in its own interval")
    return lo, hi


def dense_mask(spec: MaskSpec, s: int) -> np.ndarray:
    """[S, S] bool, mask[q, k] = k allowed for q — the CPU-mesh
    reference the sparse paths are parity-tested against.  O(S^2):
    reference/tests only; the sparse paths never call this."""
    lo, hi = row_intervals(spec, s)
    k = np.arange(s, dtype=np.int64)
    return (k[None, :] >= lo[:, None]) & (k[None, :] <= hi[:, None])


def allowed(spec: MaskSpec, q_pos, k_pos, seg_ids=None):
    """Traceable (jnp-broadcasting) mask predicate over POSITION arrays
    — the one definition of the mask semantics for code that works on
    dynamic positions (ring attention's per-hop tiles, the serving
    prefill's cache+chunk window).  ``q_pos``/``k_pos`` broadcast
    against each other; ``seg_ids`` must be given (a [S]-indexable
    array) when the spec has segments."""
    import jax.numpy as jnp
    m = True
    if spec.causal:
        m = q_pos >= k_pos
    if spec.window:
        m = m & (q_pos - k_pos < spec.window)
    if spec.seg_avg:
        if seg_ids is None:
            raise ValueError("allowed: spec has segments but no seg_ids "
                             "array was provided")
        seg_ids = jnp.asarray(seg_ids)
        m = m & (seg_ids[q_pos] == seg_ids[k_pos])
    return m


def sparsity_fraction(spec: MaskSpec, s: int) -> float:
    """Fraction of the S x S score grid that is MASKED (0.5 for plain
    causal as S -> inf).  Exact, from the row intervals."""
    lo, hi = row_intervals(spec, s)
    return float(1.0 - (hi - lo + 1).sum() / (s * s))


@dataclasses.dataclass(frozen=True)
class BlockMask:
    """Per-block verdicts for one (spec, S, block_q, block_k) choice —
    everything the splash kernels prefetch, as host numpy int32:

    q_first_k/q_last_k   [nq]  kv-block visit range per q block (the
                               fwd/dq grid bounds; blocks outside issue
                               no DMA and no MXU work)
    kv_first_q/kv_last_q [nk]  q-block visit range per kv block (the
                               dk/dv grid, whose minor axis walks q)
    blk_lo_max/blk_hi_min [nq] max(lo)/min(hi) over the block's rows —
                               a kv block j is FULL for q block i iff
                               blk_lo_max[i] <= j*bk and
                               blk_hi_min[i] >= (j+1)*bk - 1 (full
                               blocks skip the in-register mask apply)
    lo/hi                [S]   the row intervals (the in-kernel partial
                               mask: k in [lo[q], hi[q]])
    """
    spec: MaskSpec
    seq_len: int
    block_q: int
    block_k: int
    q_first_k: np.ndarray
    q_last_k: np.ndarray
    kv_first_q: np.ndarray
    kv_last_q: np.ndarray
    blk_lo_max: np.ndarray
    blk_hi_min: np.ndarray
    lo: np.ndarray
    hi: np.ndarray

    @property
    def nq(self) -> int:
        return self.seq_len // self.block_q

    @property
    def nk(self) -> int:
        return self.seq_len // self.block_k

    def verdicts(self) -> np.ndarray:
        """[nq, nk] uint8 verdict table (SKIP/PARTIAL/FULL) — derived
        from the interval arrays; tests and stats, not the kernels
        (which consume the arrays directly)."""
        j = np.arange(self.nk, dtype=np.int64)
        visit = ((j[None, :] >= self.q_first_k[:, None])
                 & (j[None, :] <= self.q_last_k[:, None]))
        full = ((self.blk_lo_max[:, None] <= j[None, :] * self.block_k)
                & (self.blk_hi_min[:, None]
                   >= (j[None, :] + 1) * self.block_k - 1))
        out = np.where(visit, np.where(full, FULL, PARTIAL), SKIP)
        return out.astype(np.uint8)

    def stats(self) -> dict:
        """Block-level work accounting: the expected-speedup side of
        the bench line's measured speedup-vs-sparsity ratio."""
        v = self.verdicts()
        total = v.size
        skipped = int((v == SKIP).sum())
        return {
            "blocks_total": total,
            "blocks_skipped": skipped,
            "blocks_full": int((v == FULL).sum()),
            "blocks_partial": int((v == PARTIAL).sum()),
            "block_skip_fraction": round(skipped / total, 6),
            "sparsity_fraction": round(
                sparsity_fraction(self.spec, self.seq_len), 6),
        }


@functools.lru_cache(maxsize=64)
def block_mask(spec: MaskSpec, s: int, block_q: int,
               block_k: int) -> BlockMask:
    """Precompute the BlockMask for (spec, S, blocks) — pure interval
    arithmetic, O(S + nq*nk) host work, cached (the same mask serves
    every layer and both fwd/bwd trace sites)."""
    if s % block_q or s % block_k:
        raise ValueError(f"block_mask: blocks ({block_q}, {block_k}) "
                         f"do not divide seq_len {s}")
    lo, hi = row_intervals(spec, s)
    nq, nk = s // block_q, s // block_k
    lo_b = lo.reshape(nq, block_q)
    hi_b = hi.reshape(nq, block_q)
    # row-interval unions per q block are contiguous (monotone bounds):
    # the kv blocks to visit span [min(lo)//bk, max(hi)//bk]
    q_first_k = (lo_b.min(axis=1) // block_k).astype(np.int32)
    q_last_k = (hi_b.max(axis=1) // block_k).astype(np.int32)
    # transposed: the q rows that see key k are [searchsorted(hi, k),
    # searchsorted(lo, k, right) - 1] (monotone bounds again); per kv
    # block take the union over its first/last key
    k_lo = np.arange(nk, dtype=np.int64) * block_k
    k_hi = k_lo + block_k - 1
    kv_first_q = (np.searchsorted(hi, k_lo, side="left")
                  // block_q).astype(np.int32)
    kv_last_q = ((np.searchsorted(lo, k_hi, side="right") - 1)
                 // block_q).astype(np.int32)
    if not (np.all(kv_first_q <= kv_last_q)
            and np.all(kv_first_q >= 0)):
        raise AssertionError("block_mask: empty kv-block q range — "
                             "admitted specs leave no orphan key")
    return BlockMask(
        spec=spec, seq_len=s, block_q=block_q, block_k=block_k,
        q_first_k=q_first_k, q_last_k=q_last_k,
        kv_first_q=kv_first_q, kv_last_q=kv_last_q,
        blk_lo_max=lo_b.max(axis=1).astype(np.int32),
        blk_hi_min=hi_b.min(axis=1).astype(np.int32),
        lo=lo.astype(np.int32), hi=hi.astype(np.int32))


def ring_hop_work(spec: MaskSpec | None, s: int, n: int) -> np.ndarray:
    """[n, n] bool: does ring shard ``me``'s query range see shard
    ``src``'s key range at all?  ``work[me, src]`` False = the whole
    (S/n x S/n) tile is masked and the hop's compute leg can be
    skipped (the ppermute still runs — the collective schedule stays
    identical).  ``spec=None`` means plain causal (the default every
    caller had before masks existed): work iff src <= me."""
    me = np.arange(n)
    if spec is None:
        return me[None, :] <= me[:, None]   # src <= me
    if s % n:
        raise ValueError(f"ring_hop_work: seq_len {s} % shards {n} != 0")
    bm = block_mask(spec, s, s // n, s // n)
    return bm.verdicts() != SKIP


def ring_skipped_hop_fraction(spec: MaskSpec | None, s: int,
                              n: int) -> float:
    """Fraction of the n^2 ring (shard, hop) compute legs the mask
    skips — the sparse-ring analogue of the overlap-fraction metric
    (nonzero even for plain causal: the strictly-future hops)."""
    work = ring_hop_work(spec, s, n)
    return float(1.0 - work.mean())


def record_globals(spec: MaskSpec, s: int, *, n_shards: int | None = None
                   ) -> dict:
    """The mask's record-schema globals: COMPARABLE by design (not in
    metrics/merge._VOLATILE_GLOBALS), so records measured under
    different masks refuse to merge exactly like mismatched fault or
    arrival plans — a different mask IS a different run.  Scalars, so
    metrics/parser hoists them to plain DataFrame columns."""
    out = {"attention_mask": spec.label(),
           "mask_sparsity": round(sparsity_fraction(spec, s), 6)}
    if n_shards is not None:
        out["ring_skipped_hop_fraction"] = round(
            ring_skipped_hop_fraction(spec, s, n_shards), 6)
    return out
