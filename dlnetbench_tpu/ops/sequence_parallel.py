"""Sequence-parallel attention for the real-compute tier: ring + Ulysses.

The reference has NO sequence/context parallelism anywhere (SURVEY.md §2.5,
§5.7) — its proxy tier only ever scales message sizes by ``seq_len``.  The
rebuild makes long context first-class twice over: schedule-level proxies
(proxies/ring_attention.py, proxies/ulysses.py) and, here, the *real math*
running inside ``shard_map`` on a mesh axis that shards the sequence:

* ``ring_attention`` — blockwise online-softmax attention where KV shards
  rotate around the ring axis via ``lax.ppermute`` (the natural idiom on an
  ICI torus) while fp32 accumulators (running max / sum / output) merge one
  KV block per step.  The full S x S score matrix and the full-sequence KV
  never exist on any device: HBM stays O(S/n) per device, which is the
  whole point at 32k+ tokens.  Hops whose entire (my queries x remote
  keys) tile is masked — strictly-future shards under causality, keys
  beyond the sliding window, foreign document segments — SKIP the
  compute leg via the host-precomputed hop-verdict table
  (ops/attention_mask.ring_hop_work); the ppermute still runs every
  hop, so the collective schedule is identical to the dense ring and
  the skip is pure recovered FLOPs.  (Pre-ISSUE-10 this file said
  "causality skips nothing": every hop merged a provably-zero
  contribution through a full ``_block_scores`` — the fixed bug.)
* ``ulysses_attention`` — two ``lax.all_to_all`` reshards per call
  (sequence-sharded -> head-sharded and back); between them every device
  holds the FULL sequence for its head subset, so the local attention can
  use the Pallas flash kernel (ops.attention "auto" dispatch).

Both are pure jnp + collectives, so ``jax.grad`` differentiates through
them (``ppermute``/``all_to_all`` transpose to their inverses), giving
correct distributed gradients with no custom VJP.  Tested on the virtual
CPU mesh against full attention on the gathered sequence
(tests/test_sequence_parallel_ops.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from dlnetbench_tpu import ops

_F32 = jnp.float32
_NEG_INF = -1e30


def _block_scores(q, k, scale):
    """Grouped (GQA) scores: q [B, Sq, Hq, Dh], k [B, Sk, Hkv, Dh]
    -> [B, Hkv, G, Sq, Sk] fp32."""
    b, sq, hq, dh = q.shape
    hkv = k.shape[2]
    qg = q.reshape(b, sq, hkv, hq // hkv, dh)
    return jnp.einsum("bqhgd,bkhd->bhgqk", qg * scale, k,
                      preferred_element_type=_F32)


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   spec=None):
    """Ring attention inside ``shard_map``; all inputs sequence-sharded.

    q: [B, S/n, Hq, Dh], k/v: [B, S/n, Hkv, Dh] — this device's shard of
    the sequence, all heads resident.  Returns [B, S/n, Hq, Dh].

    ``spec`` (a ``MaskSpec``, ops/attention_mask.py) turns on
    block-sparse hop skipping: the host-precomputed hop-verdict table
    says which (me, src) tiles contain any allowed pair, and hops whose
    whole tile is masked run NO compute leg — the ``lax.cond`` identity
    branch — while the ppermute rotation still runs unconditionally
    (identical collective schedule; skipped-hop accounting:
    ``attention_mask.ring_skipped_hop_fraction``).  Plain causal
    (spec=None, causal=True) gets the same gating from the causal
    verdict table — strictly-future hops used to pay a full
    ``_block_scores`` for a provably-zero merge.  The skipped merge is
    exactly the f32 identity (masked scores underflow to p == 0.0 after
    the first diagonal hop), so gating is numerics-preserving by
    construction and regression-tested against the gathered reference.
    """
    from dlnetbench_tpu.ops import attention_mask as amask

    b, s_loc, hq, dh = q.shape
    hkv = k.shape[2]
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    scale = 1.0 / (dh ** 0.5)
    s_full = n * s_loc
    q_pos = me * s_loc + jnp.arange(s_loc)                  # global rows

    if spec is not None and spec.causal != causal:
        raise ValueError(
            f"ring_attention: mask spec {spec.label()!r} has "
            f"causal={spec.causal} but the call says causal={causal}")
    # host-side hop verdicts: [n, n] bool, work[me, src].  None when no
    # hop can be skipped (non-causal, unmasked) — gating elided.
    work_tbl = None
    if spec is not None or causal:
        work_tbl = jnp.asarray(
            amask.ring_hop_work(spec if spec is not None
                                and not spec.is_plain_causal else None,
                                s_full, n))
    seg_ids = None
    if spec is not None and spec.seg_avg:
        seg_ids = jnp.asarray(
            amask.segment_ids(spec.seg_seed, spec.seg_avg, s_full))

    # fp32 online-softmax state, grouped layout [B, Hkv, G, Sq(, Dh)]
    g = hq // hkv
    m0 = jnp.full((b, hkv, g, s_loc), _NEG_INF, _F32)
    l0 = jnp.zeros((b, hkv, g, s_loc), _F32)
    acc0 = jnp.zeros((b, hkv, g, s_loc, dh), _F32)
    shift = [(i, (i + 1) % n) for i in range(n)]            # ring step

    def merge_block(k_cur, v_cur, m, l, acc, t):
        """Fold one KV block (originally from shard (me - t) mod n) into
        the online-softmax state."""
        src = (me - t) % n                                  # shard origin
        s = _block_scores(q, k_cur, scale)                  # [B,Hkv,G,Sq,Sk]
        k_pos = src * s_loc + jnp.arange(s_loc)
        if spec is not None and not spec.is_plain_causal:
            mask = amask.allowed(spec, q_pos[:, None], k_pos[None, :],
                                 seg_ids=seg_ids)           # [Sq, Sk]
            s = jnp.where(mask[None, None, None], s, _NEG_INF)
        elif causal:
            mask = q_pos[:, None] >= k_pos[None, :]         # [Sq, Sk]
            s = jnp.where(mask[None, None, None], s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])                   # [B,Hkv,G,Sq,Sk]
        l = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhgqk,bkhd->bhgqd", p.astype(v_cur.dtype), v_cur,
                        preferred_element_type=_F32)
        return m_new, l, acc * alpha[..., None] + pv

    def gated_merge(k_cur, v_cur, m, l, acc, t):
        """The hop's compute leg, behind its verdict: a fully-masked
        tile runs the identity branch (no scores, no MXU work)."""
        if work_tbl is None:
            return merge_block(k_cur, v_cur, m, l, acc, t)
        src = (me - t) % n
        return lax.cond(
            work_tbl[me, src],
            lambda args: merge_block(*args),
            lambda args: (args[2], args[3], args[4]),
            (k_cur, v_cur, m, l, acc, t))

    def body(carry, t):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = gated_merge(k_cur, v_cur, m, l, acc, t)
        # rotate KV one hop around the ring UNCONDITIONALLY — gating
        # must never perturb the collective schedule (overlappable with
        # the next block's compute by XLA's async collective scheduling)
        k_nxt = lax.ppermute(k_cur, axis_name, shift)
        v_nxt = lax.ppermute(v_cur, axis_name, shift)
        return (k_nxt, v_nxt, m, l, acc), None

    # n-1 (compute, rotate) steps, then the last block unrotated — the
    # nth hop would only feed a discarded carry (pure wasted ICI traffic)
    (k_last, v_last, m, l, acc), _ = lax.scan(
        body, (k, v, m0, l0, acc0), jnp.arange(n - 1))
    m, l, acc = gated_merge(k_last, v_last, m, l, acc, n - 1)
    out = acc / l[..., None]                                # [B,Hkv,G,Sq,Dh]
    return jnp.transpose(out, (0, 3, 1, 2, 4)).reshape(
        b, s_loc, hq, dh).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      impl: str = "auto", spec=None):
    """Ulysses (DeepSpeed-style) inside ``shard_map``: all-to-all from
    sequence-sharded to head-sharded, full-sequence local attention (flash
    kernel via ``impl``), all-to-all back.

    q: [B, S/n, Hq, Dh] -> returns [B, S/n, Hq, Dh].  Requires both head
    counts divisible by the axis size (lax.all_to_all enforces it).
    ``spec`` (MaskSpec) rides into the local attention, which holds the
    full sequence — the splash/dense-masked dispatch applies unchanged.
    """
    def seq_to_heads(x):
        # [B, S/n, H, Dh] -> [B, S, H/n, Dh]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    out = ops.attention(qh, kh, vh, causal=causal, impl=impl, mask=spec)
    return heads_to_seq(out)
