"""Fused-quantization Pallas matmuls: amax/scale application inlined
into the int8 and fp8(e4m3) MXU path.

Why this file exists (r5 evidence, docs/PERF.md): the BARE int8 matmul
runs at 0.98-0.99 of the 394 TOP/s int8 peak and e4m3 executes natively
at 274 TF/s, yet the end-to-end quantized paths lose their margin to
quantization overhead — the composed recipe (ops/int8.py, ops/fp8.py)
runs per-tensor amax reduction, rescale/cast, and the post-matmul
``sa*sb`` application as SEPARATE XLA passes, each a full HBM round
trip of the [T, K] activation (the quantized copy is materialized in
HBM and read back by the matmul).  That is exactly the
dequant/rescale-fusion gap SwitchBack (Wortsman et al. 2023,
arXiv:2304.13013) and the FP8-formats recipe (Micikevicius et al. 2022,
arXiv:2209.05433) identify between paper-rate and achieved-rate
low-precision training.

The kernel here fuses all three stages into the matmul itself:

* **Prologue**: the activation tile is loaded in the master dtype
  (bf16), quantized in VMEM against a PROVIDED per-tensor scale —
  the quantized activation never exists in HBM, and the activation is
  read exactly once.
* **Body**: int8 x int8 -> int32 (or e4m3 x e4m3 -> f32) MXU dots,
  accumulated in a VMEM scratch across the contraction grid axis.
* **Epilogue**: ``sa * sb`` applied in-register to the final
  accumulator tile, output written once in the master dtype.

Weights are pre-quantized ONCE per step by the caller
(``quantize_tensor`` — a [K, N] pass, small next to the [T, K]
activation traffic the fusion removes).

Scaling recipes, selected by the wrapper:

* **dynamic (fresh)** — ``*_dot_fused``: the scale comes from a fresh
  amax of the CURRENT activation.  One XLA reduction pass over x
  remains, but the separate quantize-write + quantized-read passes of
  the composed path are gone.
* **delayed** — ``*_dot_fused_delayed``: the scale is derived from an
  amax CARRIED from the previous step (SwitchBack / FP8-recipe style,
  threaded through the train step as state), and the kernel emits the
  fresh amax as a per-tile side output reduced by the wrapper — the
  fresh-amax HBM reduction leaves the hot path entirely.  Stale-scale
  overflow is handled the standard way: values are clamped to the
  format's range (saturation), and the state self-corrects next step.

All kernels run under ``interpret=True`` off-TPU (pallas_common), so
the CPU-mesh tier-1 lane unit-tests them (tests/test_quantized_matmul).
The reference has no quantized compute at all — its low-precision
support is comm-buffer dtype selection (data_types.hpp:36-79).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlnetbench_tpu.ops.pallas_common import (
    F32,
    compiler_params,
    fit_block,
    interpret_mode,
)

# format table: (quantized dtype, symmetric max, MXU accumulator dtype)
_FORMATS = {
    "int8": (jnp.int8, 127.0, jnp.int32),
    "float8": (jnp.float8_e4m3fn, 448.0, F32),
}


def formats() -> tuple[str, ...]:
    return tuple(_FORMATS)


def scale_from_amax(amax, fmt: str):
    """The ONE definition of the per-tensor symmetric scale:
    ``max(amax, eps) / qmax`` — shared by the composed paths
    (ops/int8.py, ops/fp8.py ``_quantize``) and the fused kernels, so
    the int8 fused-vs-composed comparison is exact, not just close."""
    _, qmax, _ = _FORMATS[fmt]
    return jnp.maximum(amax, 1e-12) / qmax


def _cast_q(scaled, fmt: str):
    """Scaled master-dtype values -> quantized dtype, saturating at the
    format's range (delayed scaling can hand a stale, too-small scale;
    clamping is the standard recipe).  For a fresh scale the clamp is
    the identity, which is what keeps the fused int8 result EXACTLY
    equal to the composed one."""
    qdtype, qmax, _ = _FORMATS[fmt]
    if fmt == "int8":
        return jnp.clip(jnp.round(scaled), -qmax, qmax).astype(qdtype)
    return jnp.clip(scaled, -qmax, qmax).astype(qdtype)


def quantize_tensor(x, fmt: str):
    """Per-tensor symmetric quantization via XLA: ``(x_q, scale)`` with
    ``x ~= x_q * scale``.  This is the ONCE-PER-STEP weight path (and
    the composed recipe's activation path — ops/int8.py and ops/fp8.py
    delegate here)."""
    xf = x.astype(F32)
    scale = scale_from_amax(jnp.max(jnp.abs(xf)), fmt)
    return _cast_q(xf / scale, fmt), scale


# ------------------------------------------------------------- kernel

def _fused_matmul_kernel(x_ref, wq_ref, sx_ref, sw_ref, *refs,
                         fmt: str, collect_amax: bool):
    """Grid (i, j, k) = (row blocks, col blocks, contraction blocks);
    k is the minor accumulation axis.  The amax side output (delayed
    scaling) is written on EVERY visit of its (i, k) block — the value
    is identical for every j, and an unwritten revisit would flush
    stale VMEM over a good value (Pallas re-emits the buffer whenever
    the output block index changes)."""
    if collect_amax:
        out_ref, amax_ref, acc_ref = refs
    else:
        out_ref, acc_ref = refs
    k = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    _, _, acc_dtype = _FORMATS[fmt]
    xf = x_ref[...].astype(F32)
    sx = sx_ref[0, 0]
    # prologue: quantize the activation tile in VMEM — x_q never
    # exists in HBM, x is read once in the master dtype
    xq = _cast_q(xf / sx, fmt)
    acc_ref[...] += jax.lax.dot_general(
        xq, wq_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=acc_dtype)

    if collect_amax:
        amax_ref[0, 0] = jnp.max(jnp.abs(xf))

    @pl.when(k == nk - 1)
    def _emit():
        # epilogue: sa*sb applied in-register to the accumulator tile
        out_ref[...] = (acc_ref[...].astype(F32)
                        * (sx * sw_ref[0, 0])).astype(out_ref.dtype)


# frozen default grid blocks (the pre-tuning constants): what every
# call without explicit blocks and without a tuning-DB hit runs on —
# locked bit-identical by tests/test_tuning.py
DEFAULT_BLOCKS = {"block_m": 1024, "block_n": 2048, "block_k": 2048}


def _tuned_blocks(t: int, kdim: int, n: int, fmt: str, xdtype) -> dict:
    """Grid blocks for an (t, kdim) @ (kdim, n) fused matmul: the
    tuning DB's answer (dlnetbench_tpu/tuning — frozen after first
    consult per shape key) or ``DEFAULT_BLOCKS``.  Tuned values are
    validated positive (``fit_block`` then shrinks them to divisors
    exactly as it does the defaults, so any positive tuned block is
    runnable — the committed value records the search's intent, the
    fit the shape's constraint)."""
    from dlnetbench_tpu import tuning

    def check(cfg: dict) -> None:
        for name in DEFAULT_BLOCKS:
            blk = cfg.get(name)
            if not isinstance(blk, int) or blk <= 0:
                raise ValueError(f"fused_matmul: tuned {name}={blk!r} "
                                 f"is not a positive int")
    return tuning.consult(
        "quantized_matmul",
        tuning.params.quantized_matmul_key(t, kdim, n, fmt, xdtype),
        DEFAULT_BLOCKS, validate=check)


def fused_matmul(x, wq, sw, sx, *, fmt: str, out_dtype=None,
                 collect_amax: bool = False, block_m: int | None = None,
                 block_n: int | None = None, block_k: int | None = None):
    """[..., K] master-dtype x  @  [K, N] pre-quantized w  ->  [..., N].

    ``sx`` is the PROVIDED activation scale (fresh or carried), ``sw``
    the weight scale from ``quantize_tensor``.  With ``collect_amax``
    the fresh amax of x rides out as a per-(row, contraction)-tile side
    output, reduced here to one scalar — the delayed-scaling state for
    the next step.  Returns ``y`` or ``(y, amax)``.

    Grid blocks: explicit arguments win; with none given the tuning DB
    is consulted per (shape, dtype, chip) key and an empty DB keeps the
    frozen ``DEFAULT_BLOCKS`` bit-identically (ISSUE 9).
    """
    if fmt not in _FORMATS:
        raise ValueError(f"unknown quantization format {fmt!r}; "
                         f"expected one of {formats()}")
    _, _, acc_dtype = _FORMATS[fmt]
    lead = x.shape[:-1]
    kdim = x.shape[-1]
    n = wq.shape[1]
    if wq.shape[0] != kdim:
        raise ValueError(f"fused_matmul: contraction mismatch "
                         f"x[..., {kdim}] @ wq[{wq.shape[0]}, {n}]")
    t = math.prod(lead) if lead else 1
    x2 = x.reshape(t, kdim)
    if block_m is None and block_n is None and block_k is None:
        blocks = _tuned_blocks(t, kdim, n, fmt, x.dtype)
    else:  # explicit caller blocks: fill gaps from the frozen defaults
        blocks = {"block_m": block_m or DEFAULT_BLOCKS["block_m"],
                  "block_n": block_n or DEFAULT_BLOCKS["block_n"],
                  "block_k": block_k or DEFAULT_BLOCKS["block_k"]}
    bm = fit_block(t, blocks["block_m"])
    bn = fit_block(n, blocks["block_n"])
    bk = fit_block(kdim, blocks["block_k"])
    grid = (t // bm, n // bn, kdim // bk)

    out_dtype = out_dtype or x.dtype
    out_shape = [jax.ShapeDtypeStruct((t, n), out_dtype)]
    out_specs = [pl.BlockSpec((bm, bn), lambda i, j, k: (i, j),
                              memory_space=pltpu.VMEM)]
    if collect_amax:
        out_shape.append(jax.ShapeDtypeStruct((grid[0], grid[2]), F32))
        out_specs.append(pl.BlockSpec((1, 1), lambda i, j, k: (i, k),
                                      memory_space=pltpu.SMEM))
    # the amax side output's (i, k) block is revisited along j, so j
    # must stay sequential when it is emitted; without it the kernel
    # keeps the dwd-style (parallel, parallel, arbitrary) semantics
    sem = (("parallel", "arbitrary", "arbitrary") if collect_amax
           else ("parallel", "parallel", "arbitrary"))
    res = pl.pallas_call(
        functools.partial(_fused_matmul_kernel, fmt=fmt,
                          collect_amax=collect_amax),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i, j, k: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i, j, k: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((bm, bn), acc_dtype)],
        compiler_params=compiler_params(sem),
        interpret=interpret_mode(),
    )(x2, wq,
      jnp.asarray(sx, F32).reshape(1, 1),
      jnp.asarray(sw, F32).reshape(1, 1))
    if collect_amax:
        y, amax_tiles = res
        return y.reshape(*lead, n), jnp.max(amax_tiles)
    (y,) = res
    return y.reshape(*lead, n)


# -------------------------------------------------- forward-only dots

def fused_dot(x, w, fmt: str):
    """Fresh-scaling fused dot (forward only — custom-VJP wrappers below
    and the swiglu-level VJPs in ops/int8.py / ops/fp8.py define the
    backward): one XLA amax reduction over x, weight quantized via
    ``quantize_tensor``, everything else in-kernel."""
    sx = scale_from_amax(jnp.max(jnp.abs(x.astype(F32))), fmt)
    wq, sw = quantize_tensor(w, fmt)
    return fused_matmul(x, wq, sw, sx, fmt=fmt)


def fused_dot_delayed(x, w, fmt: str, amax_in, *,
                      collect_amax: bool = True):
    """Delayed-scaling fused dot: the activation scale comes from
    ``amax_in`` (carried state from the previous step) — NO reduction
    over x on the hot path.  Returns ``(y, amax_out)`` when
    ``collect_amax`` (the state for the next step), else ``y`` (a
    second consumer of the same activation, e.g. the up projection,
    reuses the sibling's collected amax)."""
    sx = scale_from_amax(amax_in, fmt)
    wq, sw = quantize_tensor(w, fmt)
    return fused_matmul(x, wq, sw, sx, fmt=fmt, collect_amax=collect_amax)


# ------------------------------------------- differentiable wrappers

def straight_through_dot_bwd(res, g):
    """Master-dtype backward shared by every quantized dot (the fused
    ones here, the composed ones in ops/fp8.py and ops/int8.py — both
    import this definition): quantization treated as identity, so the
    gradient matmuls are the plain bf16/f32 ones."""
    x, w = res
    gf = g.astype(F32)
    dx = jnp.dot(gf, w.astype(F32).T).astype(x.dtype)
    # contract all leading (batch) axes of x against g: dw [K, N]
    lead = tuple(range(x.ndim - 1))
    dw = jax.lax.dot_general(
        x.astype(F32), gf, ((lead, lead), ((), ()))).astype(w.dtype)
    return dx, dw


# ----------------------------------------------- shared SwiGLU bodies

def swiglu_fused_fwd_res(x, w_gate, w_up, w_down, fmt: str):
    """Fresh-scaling fused-SwiGLU forward, returning (y, residuals).
    The residuals are (x, g, u, weights) — the hidden ``h`` is NOT
    saved (the r5 no-remat OOM contract, same as ops.int8.swiglu_int8):
    the backward recomputes it elementwise from g/u."""
    g = fused_dot(x, w_gate, fmt)
    u = fused_dot(x, w_up, fmt)
    h = (jax.nn.silu(g.astype(F32)) * u.astype(F32)).astype(g.dtype)
    out = fused_dot(h, w_down, fmt)
    return out, (x, g, u, w_gate, w_up, w_down)


def swiglu_fused_delayed_fwd_res(x, w_gate, w_up, w_down, qs, fmt: str):
    """Delayed-scaling fused-SwiGLU forward: ``qs`` is this layer's
    carried ``[amax_x, amax_h]`` state; gate and up share the x scale
    (one collected amax), down uses the h scale.  Returns
    ((y, new_qs), residuals) — same residual contract as above."""
    g, amax_x = fused_dot_delayed(x, w_gate, fmt, qs[0])
    u = fused_dot_delayed(x, w_up, fmt, qs[0], collect_amax=False)
    h = (jax.nn.silu(g.astype(F32)) * u.astype(F32)).astype(g.dtype)
    out, amax_h = fused_dot_delayed(h, w_down, fmt, qs[1])
    new_qs = jnp.stack([amax_x, amax_h])
    return (out, new_qs), (x, g, u, w_gate, w_up, w_down)


def swiglu_bwd_impl(res, dy, act_dot):
    """Shared SwiGLU backward (moved here from ops/int8.py so the fp8
    fused path can use it without an import cycle): ``act_dot(a, b)``
    (master-dtype result) runs the three ACTIVATION-GRADIENT matmuls
    (dh, and the two dx legs) — a plain matmul for the
    straight-through recipe, the quantized int8 dot for SwitchBack.
    Everything else (h recompute instead of save, silu derivative, the
    three master-dtype dW matmuls) exists ONCE here."""
    x, g, u, w_gate, w_up, w_down = res
    gf, uf = g.astype(F32), u.astype(F32)
    silu_g = jax.nn.silu(gf)
    h = (silu_g * uf).astype(g.dtype)          # recomputed, not saved

    # down projection: activation grad via act_dot, dW in master dtype
    dh = act_dot(dy, w_down.T).astype(F32)
    d_wd = jnp.matmul(h.reshape(-1, h.shape[-1]).T,
                      dy.reshape(-1, dy.shape[-1])).astype(w_down.dtype)

    # silu(g) * u elementwise backward
    sg = jax.nn.sigmoid(gf)
    d_g = (dh * uf * (sg * (1.0 + gf * (1.0 - sg)))).astype(g.dtype)
    d_u = (dh * silu_g).astype(u.dtype)

    # gate/up projections
    d_wg = jnp.matmul(x.reshape(-1, x.shape[-1]).T,
                      d_g.reshape(-1, d_g.shape[-1])).astype(w_gate.dtype)
    d_wu = jnp.matmul(x.reshape(-1, x.shape[-1]).T,
                      d_u.reshape(-1, d_u.shape[-1])).astype(w_up.dtype)
    d_x = (act_dot(d_g, w_gate.T) + act_dot(d_u, w_up.T)).astype(x.dtype)
    return d_x, d_wg, d_wu, d_wd


def swiglu_master_bwd(res, dy):
    """The master-dtype (straight-through) SwiGLU backward — the ONE
    definition both the int8 and fp8 fused swiglus defvjp with, so the
    recipes the A/B bench assumes symmetric cannot silently diverge."""
    return swiglu_bwd_impl(res, dy, jnp.matmul)


def swiglu_delayed_master_bwd(res, cots):
    """``swiglu_master_bwd`` for the delayed-scaling swiglus: the
    second cotangent (the emitted amax state) is dropped and the
    carried ``[amax_x, amax_h]`` input gets a zero gradient."""
    dy, _d_qs = cots
    return (*swiglu_bwd_impl(res, dy, jnp.matmul), jnp.zeros((2,), F32))


@jax.custom_vjp
def int8_dot_fused(x, w):
    """[..., K] x [K, N] -> [..., N]: the fused-kernel sibling of
    ops.int8.int8_dot — same recipe, same straight-through backward,
    quantization fused into the matmul.  int32 accumulation makes the
    result EXACTLY equal to the composed form (same scales, associative
    int32 sums, same f32 epilogue)."""
    return fused_dot(x, w, "int8")


def _int8_dot_fused_fwd(x, w):
    return fused_dot(x, w, "int8"), (x, w)


int8_dot_fused.defvjp(_int8_dot_fused_fwd, straight_through_dot_bwd)


@jax.custom_vjp
def fp8_dot_fused(x, w):
    """The fused-kernel sibling of ops.fp8.fp8_dot (e4m3, f32
    accumulation); matches the composed form to e4m3 quantization
    tolerance (tiled f32 accumulation order differs)."""
    return fused_dot(x, w, "float8")


def _fp8_dot_fused_fwd(x, w):
    return fused_dot(x, w, "float8"), (x, w)


fp8_dot_fused.defvjp(_fp8_dot_fused_fwd, straight_through_dot_bwd)


def _dot_delayed_fwd(x, w, amax_in, fmt):
    y, amax_out = fused_dot_delayed(x, w, fmt, amax_in)
    return (y, amax_out), (x, w)


def _dot_delayed_bwd(res, cots):
    dy, _d_amax = cots      # the carried amax is state, not a weight
    dx, dw = straight_through_dot_bwd(res, dy)
    return dx, dw, jnp.zeros((), F32)


@jax.custom_vjp
def int8_dot_fused_delayed(x, w, amax_in):
    """Delayed-scaling int8 dot: ``(y, amax_out)`` with the activation
    scale taken from ``amax_in`` (previous step's state) and the fresh
    amax emitted by the kernel for the next step.  Backward is
    straight-through; the state carries no gradient."""
    y, amax_out = fused_dot_delayed(x, w, "int8", amax_in)
    return y, amax_out


int8_dot_fused_delayed.defvjp(
    functools.partial(_dot_delayed_fwd, fmt="int8"), _dot_delayed_bwd)


@jax.custom_vjp
def fp8_dot_fused_delayed(x, w, amax_in):
    """Delayed-scaling e4m3 dot; see ``int8_dot_fused_delayed``."""
    y, amax_out = fused_dot_delayed(x, w, "float8", amax_in)
    return y, amax_out


fp8_dot_fused_delayed.defvjp(
    functools.partial(_dot_delayed_fwd, fmt="float8"), _dot_delayed_bwd)
