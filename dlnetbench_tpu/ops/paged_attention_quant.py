"""Quantized paged-attention decode kernel (ISSUE 12 tentpole).

The serving tier's paged KV pools can be stored int8 / fp8(e4m3) with
per-page-per-head f32 scales (serving/kv_cache.py).  The jax-shipped
``pallas.ops.tpu.paged_attention`` kernel reads bf16/f32 pools only, so
the quantized cache gets its own decode kernel here:

* the sequence's pages are gathered CONTIGUOUS in their quantized
  dtype (one XLA gather of int8/fp8 — half the HBM traffic of a bf16
  gather, a quarter of an f32 one; the quantized pages never
  round-trip through HBM as a wider dtype), along with the matching
  per-page scales;
* the Pallas kernel walks the gathered sequence in
  ``pages_per_compute_block``-page KV blocks and **dequantizes each
  page tile in the VMEM prologue** against the prefetched scales (the
  Pallas input pipeline has the scale block resident before the body
  runs — the PR-3 VMEM-prologue recipe applied to the attention read
  path), then runs the usual f32 online-softmax accumulation;
* masking is by sequence length, exactly like the dense gather
  fallback (``kv_cache._gather_attention`` with scales), which is the
  parity reference the CPU-mesh tests lock this kernel against under
  ``interpret=True`` — and the ``tpu_only`` case locks on real silicon.

``q`` arrives PRE-SCALED by ``head_dim**-0.5`` (the convention every
paged-attention impl in this repo shares).  ``pages_per_compute_block``
is this kernel's tuning-DB site (op ``paged_attention_quant`` — keyed
with the quant format, since dequant changes the arithmetic intensity;
see ``kv_cache.resolve_pages_per_compute_block``).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlnetbench_tpu.ops.pallas_common import (F32, compiler_params,
                                              interpret_mode)

# finite mask value (matches kv_cache.MASK_VALUE): exp(mask - m)
# underflows to exactly 0, and a fully-masked tail block can never
# produce an inf - inf NaN in the online-softmax rescale
_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _kernel(q_ref, k_ref, v_ref, ks_ref, vs_ref, len_ref, o_ref,
            acc_ref, m_ref, l_ref, *, ppcb: int, page_size: int):
    """Grid (b, h_kv, t): t walks the gathered sequence in blocks of
    ``ppcb`` pages; accumulators carry the online softmax across t
    (minor, "arbitrary"), emitted on the last block."""
    t = pl.program_id(2)
    nt = pl.num_programs(2)
    bt = ppcb * page_size

    @pl.when(t == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # VMEM prologue: dequantize this block's page tiles against their
    # (prefetched) per-page scales — the quantized copy never exists
    # outside VMEM in a wider dtype
    ks = ks_ref[0, 0]                                     # [ppcb]
    vs = vs_ref[0, 0]
    dh = k_ref.shape[-1]
    kf = (k_ref[0, 0].astype(F32).reshape(ppcb, page_size, dh)
          * ks[:, None, None]).reshape(bt, dh)
    vf = (v_ref[0, 0].astype(F32).reshape(ppcb, page_size, dh)
          * vs[:, None, None]).reshape(bt, dh)

    q = q_ref[0, 0].astype(F32)                           # [G, Dh]
    s = jax.lax.dot_general(q, kf, (((1,), (1,)), ((), ())),
                            preferred_element_type=F32)   # [G, bt]
    pos = t * bt + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(pos < len_ref[0, 0], s, _NEG_INF)

    m_prev = m_ref[:, :1]                                 # [G, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)                                # [G, bt]
    l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(p, vf, (((1,), (0,)), ((), ())),
                             preferred_element_type=F32)  # [G, Dh]
    acc_ref[:] = acc_ref[:] * alpha + pv
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(t == nt - 1)
    def _emit():
        o_ref[0, 0] = (acc_ref[:] / l_ref[:, :1]).astype(o_ref.dtype)


def quant_paged_attention(q, k_pages, v_pages, k_scale, v_scale,
                          lengths, page_indices, *, fmt: str,
                          pages_per_compute_block: int):
    """Decode attention over a quantized page pool.

    q: [B, Hq, Dh] pre-scaled; k/v_pages: [Hkv, P, S, Dh] int8/fp8;
    k/v_scale: [Hkv, P] f32; lengths: [B]; page_indices: [B, Pmax].
    ``fmt`` names the recipe ('int8' | 'float8' — validation only; the
    stored dtype already encodes it)."""
    if fmt not in ("int8", "float8"):
        raise ValueError(f"quant_paged_attention: unknown fmt {fmt!r}")
    b, hq, dh = q.shape
    hkv, _, page_size, _ = k_pages.shape
    pmax = page_indices.shape[1]
    ppcb = pages_per_compute_block
    if pmax % ppcb:
        raise ValueError(
            f"quant_paged_attention: pages_per_compute_block {ppcb} "
            f"does not divide pages_per_seq {pmax}")
    g = hq // hkv
    t_len = pmax * page_size

    # gather QUANTIZED (int8/fp8 through HBM — 1/2 the bytes of a
    # bf16 gather, 1/4 of an f32 one) + the per-page scales that ride
    # beside the pages
    kg = jnp.moveaxis(k_pages[:, page_indices], 0, 1).reshape(
        b, hkv, t_len, dh)
    vg = jnp.moveaxis(v_pages[:, page_indices], 0, 1).reshape(
        b, hkv, t_len, dh)
    ksg = jnp.moveaxis(k_scale[:, page_indices], 0, 1)   # [B, Hkv, Pmax]
    vsg = jnp.moveaxis(v_scale[:, page_indices], 0, 1)
    q4 = q.reshape(b, hkv, g, dh)
    len2 = lengths.astype(jnp.int32).reshape(b, 1)

    bt = ppcb * page_size
    grid = (b, hkv, pmax // ppcb)
    out = pl.pallas_call(
        functools.partial(_kernel, ppcb=ppcb, page_size=page_size),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, g, dh), lambda bi, h, t: (bi, h, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bt, dh), lambda bi, h, t: (bi, h, t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bt, dh), lambda bi, h, t: (bi, h, t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, ppcb), lambda bi, h, t: (bi, h, t),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, ppcb), lambda bi, h, t: (bi, h, t),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda bi, h, t: (bi, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, g, dh),
                               lambda bi, h, t: (bi, h, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((g, dh), F32),
            pltpu.VMEM((g, 128), F32),
            pltpu.VMEM((g, 128), F32),
        ],
        compiler_params=compiler_params(
            ("parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(q4, kg, vg, ksg, vsg, len2)
    return out.reshape(b, hq, dh)
