"""Hot-op kernels (Pallas where it pays).

The reference has no kernel layer at all — its "compute" is ``usleep``
(reference cpp/data_parallel/dp.cpp:93).  The rebuild's real-compute tier
does real math, so the FLOP-dominant op — attention — gets a TPU-native
blockwise (flash) kernel here: online-softmax tiles sized to VMEM, MXU
matmuls with fp32 accumulation, and a custom VJP so long sequences never
materialize the S x S score matrix in HBM.

``attention`` is the dispatcher the model families call: it routes to the
Pallas kernel when the backend and shapes support it and otherwise falls
back to the plain-XLA einsum implementation (models/layers.py), which is
also the numerical reference in tests.
"""
from __future__ import annotations

import jax

from dlnetbench_tpu.models import layers as _L
from dlnetbench_tpu.ops import attention_mask as _M
from dlnetbench_tpu.ops.flash_attention import (
    LONG_SEQ,
    flash_attention,
    flash_supported,
    splash_attention,
)

__all__ = ["attention", "flash_attention", "flash_supported",
           "splash_attention"]

# Measured on a v5e chip (llama3_8b-shaped 4-layer train step, remat on):
# flash loses ~2% at S=1024 (attention is a sliver of the step and the
# recomputed fwd kernel costs more than XLA's fused softmax) and wins 18%
# at S=2048 / 29% at S=4096.  "auto" only picks flash where it pays.
_AUTO_MIN_SEQ = 2048


def _dense_mask_np(spec: _M.MaskSpec, s: int):
    """Host-side dense mask for the reference path.  Deliberately NOT
    cached: jit tracing already folds it into the compiled computation
    once per shape, and pinning [S, S] bool arrays for the process
    lifetime would only duplicate XLA's copy (the underlying row
    intervals ARE cached — rebuilding is one O(S^2) broadcast)."""
    return _M.dense_mask(spec, s)


def attention(q, k, v, causal: bool, impl: str = "auto", mask=None):
    """q: [B, S, Hq, Dh], k/v: [B, S, Hkv, Dh] -> [B, S, Hq, Dh].

    impl: "flash" (Pallas kernel, error if unsupported shape),
    "xla" (einsum reference), or "auto" (flash on TPU when the shape
    qualifies, xla otherwise — CPU interpret-mode flash is for tests).

    ``mask`` (a ``MaskSpec``, ops/attention_mask.py) turns on the
    block-sparse path: "flash" dispatches the splash kernels (skipped
    blocks cost no DMA/MXU work), "xla" applies the SAME mask densely
    (the CPU-mesh reference the sparse paths are parity-tested
    against).  The spec's ``causal`` must agree with the ``causal``
    argument — a silent disagreement would A/B two different maths.
    """
    s = q.shape[1]
    if mask is not None:
        if mask.causal != causal:
            raise ValueError(
                f"attention: mask spec {mask.label()!r} has "
                f"causal={mask.causal} but the call says causal={causal}")
        if mask.is_plain_causal:
            mask = None   # the dense-causal default IS this mask
    if impl == "xla":
        if mask is not None:
            return _L.attention(q, k, v, causal=causal,
                                dense_mask=_dense_mask_np(mask, s))
        return _L.attention(q, k, v, causal=causal)
    if impl == "flash":
        if mask is not None:
            return splash_attention(q, k, v, mask)
        return flash_attention(q, k, v, causal=causal)
    if impl != "auto":
        raise ValueError(f"unknown attention impl {impl!r}")
    supported = flash_supported(q, k, v)   # raises at S>=64k w/o blocks
    if (jax.default_backend() == "tpu" and s >= _AUTO_MIN_SEQ
            and supported):
        if mask is not None:
            return splash_attention(q, k, v, mask)
        return flash_attention(q, k, v, causal=causal)
    if s >= LONG_SEQ:
        # the dense fallback at 64k+ materializes the S^2 score matrix
        # — never a sane degradation (ISSUE 10 satellite: fail loud,
        # naming the length; impl="xla" stays available explicitly)
        raise ValueError(
            f"attention: impl='auto' refuses the dense fallback at "
            f"seq_len {s} >= {LONG_SEQ} (the S^2 score matrix would "
            f"materialize); use the flash/splash path on TPU or pass "
            f"impl='xla' explicitly")
    if mask is not None:
        return _L.attention(q, k, v, causal=causal,
                            dense_mask=_dense_mask_np(mask, s))
    return _L.attention(q, k, v, causal=causal)
