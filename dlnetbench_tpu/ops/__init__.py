"""Hot-op kernels (Pallas where it pays).

The reference has no kernel layer at all — its "compute" is ``usleep``
(reference cpp/data_parallel/dp.cpp:93).  The rebuild's real-compute tier
does real math, so the FLOP-dominant op — attention — gets a TPU-native
blockwise (flash) kernel here: online-softmax tiles sized to VMEM, MXU
matmuls with fp32 accumulation, and a custom VJP so long sequences never
materialize the S x S score matrix in HBM.

``attention`` is the dispatcher the model families call: it routes to the
Pallas kernel when the backend and shapes support it and otherwise falls
back to the plain-XLA einsum implementation (models/layers.py), which is
also the numerical reference in tests.
"""
from __future__ import annotations

import jax

from dlnetbench_tpu.models import layers as _L
from dlnetbench_tpu.ops.flash_attention import (
    flash_attention,
    flash_supported,
)

__all__ = ["attention", "flash_attention", "flash_supported"]

# Measured on a v5e chip (llama3_8b-shaped 4-layer train step, remat on):
# flash loses ~2% at S=1024 (attention is a sliver of the step and the
# recomputed fwd kernel costs more than XLA's fused softmax) and wins 18%
# at S=2048 / 29% at S=4096.  "auto" only picks flash where it pays.
_AUTO_MIN_SEQ = 2048


def attention(q, k, v, causal: bool, impl: str = "auto"):
    """q: [B, S, Hq, Dh], k/v: [B, S, Hkv, Dh] -> [B, S, Hq, Dh].

    impl: "flash" (Pallas kernel, error if unsupported shape),
    "xla" (einsum reference), or "auto" (flash on TPU when the shape
    qualifies, xla otherwise — CPU interpret-mode flash is for tests).
    """
    if impl == "xla":
        return _L.attention(q, k, v, causal=causal)
    if impl == "flash":
        return flash_attention(q, k, v, causal=causal)
    if impl != "auto":
        raise ValueError(f"unknown attention impl {impl!r}")
    if (jax.default_backend() == "tpu" and q.shape[1] >= _AUTO_MIN_SEQ
            and flash_supported(q, k, v)):
        return flash_attention(q, k, v, causal=causal)
    return _L.attention(q, k, v, causal=causal)
