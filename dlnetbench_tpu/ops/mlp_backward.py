"""Fused Pallas kernels for the SwiGLU backward — the r4 attack on the
train step's dominant bucket (docs/PERF.md: the backward matmul fusions
are 41.6% of the step at ~0.80 of MXU peak, while bare same-shape dots
measure 0.99).

What the fusion buys (per layer, bench shape T=12288, D=4096, F=14336):
the autodiff backward materializes two [T, F] intermediates in HBM —
``dh`` (the down-projection gradient) and ``h`` (the recomputed hidden)
— each a write plus one or two reads of ~350 MB.  Here:

* ``dgdu_kernel``: dg, du are produced directly from (dy, Wd, g, u);
  the ``dh = dy @ Wd^T`` tile lives only in VMEM as the dot accumulator
  and the silu-gradient epilogue consumes it in-register.
* ``dwd_kernel``: dWd = h^T @ dy with the ``h = silu(g) * u`` tile
  recomputed elementwise in VMEM per contraction step — h never exists
  in HBM.

dx / dWg / dWu remain plain XLA dots (measured at ~0.99 of peak in
isolation; no fusion value to add).  Both kernels run under
``interpret=True`` off-TPU so the path is unit-testable on the CPU mesh
(tests/test_mlp_backward.py).

The reference has no kernels at all — its backward is a simulated-time
roofline entry (reference python/model_stats.py:140); this file exists
because the rebuild executes the real compute tier.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlnetbench_tpu.ops.pallas_common import (
    F32 as _F32,
    compiler_params as _compiler_params,
    fit_block,
    interpret_mode as _interpret,
)


def _silu_parts(g_f32):
    sig = jax.nn.sigmoid(g_f32)
    silu = g_f32 * sig
    return silu, sig + silu * (1.0 - sig)   # silu(g), silu'(g)


# --------------------------------------------------------- dg/du kernel

def _dgdu_kernel(dy_ref, wd_ref, g_ref, u_ref, dg_ref, du_ref):
    # dh tile = dy (bm, D) @ Wd^T (D, bn) — accumulator only, in VMEM
    dh = jax.lax.dot_general(dy_ref[...], wd_ref[...],
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=_F32)
    silu, dsilu = _silu_parts(g_ref[...].astype(_F32))
    u = u_ref[...].astype(_F32)
    dg_ref[...] = (dh * u * dsilu).astype(dg_ref.dtype)
    du_ref[...] = (dh * silu).astype(du_ref.dtype)


def dgdu(dy, wd, g, u, *, block_m: int = 1024, block_n: int = 2048):
    """dg, du [T, F] from dy [T, D], Wd [F, D], saved g, u [T, F].

    The full D axis is contracted per grid lane (D tiles of dy and Wd
    fit VMEM at these block sizes), so there is no k loop and the
    silu-gradient epilogue runs in the same lane as the dot.
    """
    t, d = dy.shape
    f = wd.shape[0]
    block_m = fit_block(t, block_m)
    block_n = fit_block(f, block_n)
    grid = (t // block_m, f // block_n)
    return pl.pallas_call(
        _dgdu_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, d), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_n, d), lambda i, j: (j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, block_n), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, block_n), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((block_m, block_n), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_m, block_n), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((t, f), g.dtype),
            jax.ShapeDtypeStruct((t, f), u.dtype),
        ],
        compiler_params=_compiler_params(("parallel", "parallel")),
        interpret=_interpret(),
    )(dy, wd, g, u)


# ----------------------------------------------------------- dWd kernel

def _dwd_kernel(g_ref, u_ref, dy_ref, dwd_ref, acc_ref):
    k = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    silu, _ = _silu_parts(g_ref[...].astype(_F32))
    h = (silu * u_ref[...].astype(_F32)).astype(g_ref.dtype)  # [bk, bm]
    acc_ref[...] += jax.lax.dot_general(h, dy_ref[...],
                                        (((0,), (0,)), ((), ())),
                                        preferred_element_type=_F32)

    @pl.when(k == nk - 1)
    def _emit():
        dwd_ref[...] = acc_ref[...].astype(dwd_ref.dtype)


def dwd(g, u, dy, *, block_f: int = 2048, block_d: int = 2048,
        block_k: int = 1024):
    """dWd [F, D] = h^T @ dy with h = silu(g) * u recomputed per tile."""
    t, f = g.shape
    d = dy.shape[1]
    block_f = fit_block(f, block_f)
    block_d = fit_block(d, block_d)
    block_k = fit_block(t, block_k)
    grid = (f // block_f, d // block_d, t // block_k)
    return pl.pallas_call(
        _dwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_k, block_f), lambda i, j, k: (k, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_k, block_f), lambda i, j, k: (k, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((block_k, block_d), lambda i, j, k: (k, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_f, block_d), lambda i, j, k: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((f, d), _F32),
        scratch_shapes=[pltpu.VMEM((block_f, block_d), _F32)],
        compiler_params=_compiler_params(("parallel", "parallel",
                                          "arbitrary")),
        interpret=_interpret(),
    )(g, u, dy)


# ------------------------------------------------- fused-backward SwiGLU

@jax.custom_vjp
def swiglu_pallas_bwd(x, w_gate, w_up, w_down):
    """SwiGLU whose backward runs the two fused Pallas kernels above
    (dh and h never reach HBM) plus three pure XLA dots (dx, dWg, dWu).
    Forward is the shared three-dot body (models.layers.swiglu_fwd_res),
    residuals saved bf16 (x, g, u)."""
    from dlnetbench_tpu.models.layers import swiglu_fwd_res
    return swiglu_fwd_res(x, w_gate, w_up, w_down)[0]


def _fwd(x, w_gate, w_up, w_down):
    from dlnetbench_tpu.models.layers import swiglu_fwd_res
    return swiglu_fwd_res(x, w_gate, w_up, w_down)


def _bwd(res, dy):
    x, g, u, w_gate, w_up, w_down = res
    t_nk = (((1,), (1,)), ((), ()))   # a @ b^T
    t_km = (((0,), (0,)), ((), ()))   # a^T @ b
    dg, du = dgdu(dy, w_down, g, u)
    dx = (jax.lax.dot_general(dg, w_gate, t_nk,
                              preferred_element_type=_F32)
          + jax.lax.dot_general(du, w_up, t_nk,
                                preferred_element_type=_F32)).astype(x.dtype)
    dwg = jax.lax.dot_general(x, dg, t_km, preferred_element_type=_F32)
    dwu = jax.lax.dot_general(x, du, t_km, preferred_element_type=_F32)
    dwd_ = dwd(g, u, dy)
    return (dx, dwg.astype(w_gate.dtype), dwu.astype(w_up.dtype),
            dwd_.astype(w_down.dtype))


swiglu_pallas_bwd.defvjp(_fwd, _bwd)
