"""int8 matmul with per-tensor dynamic scales — the quantized MLP
compute path that is ACTUALLY fast on this hardware.

Measured on v5e (r4/r5, docs/PERF.md): chained int8->int32 matmuls run
at 387-390 TOP/s = 0.98-0.99 of the 394 TOP/s int8 peak, and the
END-TO-END int8-MLP train step runs the HEADLINE config (no remat) at
494.3 ms vs 537.5 bf16 — a 1.087x step-level win at loss parity (r5,
bench.py int8_step; needs the fused swiglu_int8 VJP below) — the only
low-precision path with a measured end-to-end win on this chip (fp8
reaches 0.70 of its peak in isolation but has no step-level win
recorded).

Same recipe shape as fp8_dot: bf16 master weights/activations,
per-tensor symmetric scaling to [-127, 127], int32 accumulation on the
MXU, scales re-applied to the result; the backward is straight-through
in the master dtype (quantization treated as identity — the standard
recipe when gradients are not quantized).

The reference's low-precision support is communication-buffer dtype
selection only (`PROXY_FLOAT8`, data_types.hpp:36-79); it has no
quantized compute path at all.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from dlnetbench_tpu.ops import quantized_matmul as qmm

_F32 = jnp.float32
_QMAX = 127.0


def _quantize(x):
    """Per-tensor symmetric scaling to int8: (x_q, scale) with
    x ~= x_q * scale; the scale is clamped so an all-zero tensor stays
    representable.  Delegates to the ONE definition in
    ops/quantized_matmul.py (shared with the fused Pallas kernels,
    which is what makes the fused-vs-composed int8 results EXACTLY
    equal, not just close)."""
    return qmm.quantize_tensor(x, "int8")


@jax.custom_vjp
def int8_dot(x, w):
    """[..., K] x [K, N] -> [..., N]: int8 operands, int32 MXU
    accumulation, result in x.dtype.  Backward is straight-through in
    the master dtype."""
    out, _ = _int8_dot_fwd(x, w)
    return out


def _int8_matmul(a, b_mat, out_dtype):
    """Quantized a @ b_mat with per-tensor scales and int32 MXU
    accumulation — the ONE definition of the int8 dot recipe (forward
    and SwitchBack activation-grad dots share it)."""
    aq, sa = _quantize(a)
    bq, sb = _quantize(b_mat)
    acc = jax.lax.dot_general(aq, bq,
                              (((a.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return (acc.astype(_F32) * (sa * sb)).astype(out_dtype)


def _int8_dot_fwd(x, w):
    return _int8_matmul(x, w, x.dtype), (x, w)


# master-dtype straight-through backward, shared with the fp8 path
from dlnetbench_tpu.ops.fp8 import straight_through_dot_bwd  # noqa: E402

int8_dot.defvjp(_int8_dot_fwd, straight_through_dot_bwd)


@jax.custom_vjp
def int8_dot_batched(x, w):
    """[E, C, K] x [E, K, N] -> [E, C, N]: per-tensor-scaled int8
    operands, int32 MXU accumulation batched over the leading (expert)
    axis — the MoE/EP sibling of ``int8_dot`` (models/spmd.py expert
    einsums).  Backward is straight-through in the master dtype."""
    out, _ = _int8_dot_batched_fwd(x, w)
    return out


def _int8_dot_batched_fwd(x, w):
    xq, sx = _quantize(x)
    wq, sw = _quantize(w)
    acc = jax.lax.dot_general(xq, wq,
                              (((2,), (1,)), ((0,), (0,))),
                              preferred_element_type=jnp.int32)
    out = acc.astype(_F32) * (sx * sw)
    return out.astype(x.dtype), (x, w)


def _int8_dot_batched_bwd(res, dy):
    x, w = res
    d_x = jax.lax.dot_general(
        dy, w, (((2,), (2,)), ((0,), (0,)))).astype(x.dtype)
    d_w = jax.lax.dot_general(
        x, dy, (((1,), (1,)), ((0,), (0,)))).astype(w.dtype)
    return d_x, d_w


int8_dot_batched.defvjp(_int8_dot_batched_fwd, _int8_dot_batched_bwd)


@jax.custom_vjp
def swiglu_int8(x, w_gate, w_up, w_down):
    """SwiGLU with all three matmuls in int8 (the int8 sibling of
    layers.swiglu / ops.fp8.swiglu_fp8 — same bf16-rounding discipline
    for saved residuals).

    Whole-op custom VJP rather than three composed ``int8_dot``s: the
    composition's down-projection dot saves its input ``h`` ([B, S, ff]
    — ~345 MB/layer at bench shape) as a residual, which the bf16
    path's XLA-fused backward never materializes.  Here the backward
    recomputes ``h`` elementwise from the (anyway-saved) g/u
    pre-activations, so the residual footprint matches the bf16 path
    and the int8 step fits where the composition OOM'd (r5,
    docs/PERF.md).  Backward stays straight-through in the master
    dtype, identical in semantics to the composed form."""
    out, _ = _swiglu_int8_fwd(x, w_gate, w_up, w_down)
    return out


def _swiglu_int8_fwd(x, w_gate, w_up, w_down):
    g = int8_dot(x, w_gate)
    u = int8_dot(x, w_up)
    h = (jax.nn.silu(g.astype(_F32)) * u.astype(_F32)).astype(g.dtype)
    out = int8_dot(h, w_down)
    return out, (x, g, u, w_gate, w_up, w_down)


# shared SwiGLU backward — one definition for the composed, fused and
# SwitchBack recipes, living beside the fused kernels (ops/
# quantized_matmul.py) so the fp8 fused path uses it without an import
# cycle; ``act_dot`` selects plain-matmul vs quantized activation-grad
# dots, everything else (h recompute, silu derivative, master-dtype dW
# matmuls) exists once there
_swiglu_bwd_impl = qmm.swiglu_bwd_impl


# the master-dtype backward shared with the fp8 swiglus (one
# definition, ops/quantized_matmul.py)
_swiglu_int8_bwd = qmm.swiglu_master_bwd


swiglu_int8.defvjp(_swiglu_int8_fwd, _swiglu_int8_bwd)


@jax.custom_vjp
def swiglu_int8_fused(x, w_gate, w_up, w_down):
    """SwiGLU with all three matmuls through the fused-quantization
    Pallas kernel (ops/quantized_matmul.py): activation quantization in
    the kernel prologue, int32 MXU accumulation, ``sa*sb`` epilogue
    in-register — the composed recipe's separate amax/rescale HBM
    passes are gone and the quantized activation never exists in HBM.
    Numerically EXACTLY equal to ``swiglu_int8`` (shared scale
    definition, associative int32 accumulation); same residual
    contract (``h`` recomputed, not saved) and the same master-dtype
    straight-through backward."""
    out, _ = qmm.swiglu_fused_fwd_res(x, w_gate, w_up, w_down, "int8")
    return out


def _swiglu_int8_fused_fwd(x, w_gate, w_up, w_down):
    return qmm.swiglu_fused_fwd_res(x, w_gate, w_up, w_down, "int8")


swiglu_int8_fused.defvjp(_swiglu_int8_fused_fwd, _swiglu_int8_bwd)


@jax.custom_vjp
def swiglu_int8_fused_delayed(x, w_gate, w_up, w_down, qs):
    """Delayed-scaling fused-SwiGLU (int8): ``qs`` is this layer's
    carried ``[amax_x, amax_h]`` f32 state from the PREVIOUS step
    (SwitchBack-style delayed scaling, arXiv:2304.13013) — no
    fresh-amax HBM reduction on the hot path; the kernel emits this
    step's amaxes as next-step state.  A stale scale saturates at
    +-127 and self-corrects the following step.  Returns
    ``(y, new_qs)``; the state carries no gradient."""
    (out, new_qs), _ = qmm.swiglu_fused_delayed_fwd_res(
        x, w_gate, w_up, w_down, qs, "int8")
    return out, new_qs


def _swiglu_int8_fused_delayed_fwd(x, w_gate, w_up, w_down, qs):
    return qmm.swiglu_fused_delayed_fwd_res(
        x, w_gate, w_up, w_down, qs, "int8")


swiglu_int8_fused_delayed.defvjp(_swiglu_int8_fused_delayed_fwd,
                                 qmm.swiglu_delayed_master_bwd)


@jax.custom_vjp
def swiglu_int8_sb(x, w_gate, w_up, w_down):
    """SwiGLU, int8 forward AND int8 activation-gradient (dx-side)
    backward — the SwitchBack recipe (arXiv:2304.13013 pattern: the
    three dL/dactivation matmuls are quantized per-tensor; the three
    dL/dW matmuls stay in the master dtype, where gradient accuracy
    lives).  Relative to ``swiglu_int8`` this moves the backward's
    dh = dy@Wd^T and dx = dg@Wg^T + du@Wu^T onto the 2x int8 MXU rate.

    Numerics are a RECIPE CHANGE (quantization error enters upstream
    gradients), so this is opt-in via
    ``TransformerConfig.int8_backward="switchback"``; the r5 loss-
    trajectory study (docs/studies/int8_step_r5) measures the drift
    against the master-dtype backward before trusting the speed."""
    out, _ = _swiglu_int8_fwd(x, w_gate, w_up, w_down)
    return out


def _sb_dot(a, b_mat):
    return _int8_matmul(a, b_mat, a.dtype)


def _swiglu_int8_sb_bwd(res, dy):
    return _swiglu_bwd_impl(res, dy, _sb_dot)


swiglu_int8_sb.defvjp(_swiglu_int8_fwd, _swiglu_int8_sb_bwd)
