"""int8 matmul with per-tensor dynamic scales — the quantized MLP
compute path that is ACTUALLY fast on this hardware.

Measured on v5e (r4/r5, docs/PERF.md): chained int8->int32 matmuls run
at 387-390 TOP/s = 0.98-0.99 of the 394 TOP/s int8 peak, and the
END-TO-END int8-MLP train step beats the paired bf16 step by 1.089x
(r5, bench.py int8_step) — the only low-precision path with a measured
end-to-end win on this chip (fp8 reaches 0.70 of its peak in isolation
but has no step-level win recorded).

Same recipe shape as fp8_dot: bf16 master weights/activations,
per-tensor symmetric scaling to [-127, 127], int32 accumulation on the
MXU, scales re-applied to the result; the backward is straight-through
in the master dtype (quantization treated as identity — the standard
recipe when gradients are not quantized).

The reference's low-precision support is communication-buffer dtype
selection only (`PROXY_FLOAT8`, data_types.hpp:36-79); it has no
quantized compute path at all.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_F32 = jnp.float32
_QMAX = 127.0


def _quantize(x):
    """Per-tensor symmetric scaling to int8: (x_q, scale) with
    x ~= x_q * scale; the scale is clamped so an all-zero tensor stays
    representable."""
    amax = jnp.max(jnp.abs(x.astype(_F32)))
    scale = jnp.maximum(amax, 1e-12) / _QMAX
    xq = jnp.clip(jnp.round(x.astype(_F32) / scale), -_QMAX, _QMAX)
    return xq.astype(jnp.int8), scale


@jax.custom_vjp
def int8_dot(x, w):
    """[..., K] x [K, N] -> [..., N]: int8 operands, int32 MXU
    accumulation, result in x.dtype.  Backward is straight-through in
    the master dtype."""
    out, _ = _int8_dot_fwd(x, w)
    return out


def _int8_dot_fwd(x, w):
    xq, sx = _quantize(x)
    wq, sw = _quantize(w)
    acc = jax.lax.dot_general(xq, wq,
                              (((x.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    out = acc.astype(_F32) * (sx * sw)
    return out.astype(x.dtype), (x, w)


# master-dtype straight-through backward, shared with the fp8 path
from dlnetbench_tpu.ops.fp8 import straight_through_dot_bwd  # noqa: E402

int8_dot.defvjp(_int8_dot_fwd, straight_through_dot_bwd)


def swiglu_int8(x, w_gate, w_up, w_down):
    """SwiGLU with all three matmuls in int8 (the int8 sibling of
    layers.swiglu / ops.fp8.swiglu_fp8 — same bf16-rounding discipline
    for saved residuals)."""
    g = int8_dot(x, w_gate)
    u = int8_dot(x, w_up)
    h = (jax.nn.silu(g.astype(_F32)) * u.astype(_F32)).astype(g.dtype)
    return int8_dot(h, w_down)
