"""KV-page migration channel: prefill pool -> decode pool, in the
STORED dtype.

The disaggregated engine (serving/disagg.py) finishes a prompt on the
prefill replica and must hand its KV pages to the decode replica.
This module is that wire, built in the spirit of the PR-4 decomposed
chunk loops: the sequence's pages move as a small host-driven loop of
contiguous chunk transfers, each chunk one ``jax.device_put`` of a
gathered ``[L, Hkv, chunk, S, Dh]`` slab — the single-controller
harness's honest inter-device transport — with the per-page-per-head
f32 scales riding alongside in their own slab.  On a quantized cache
the payload stays int8/fp8 END TO END: the slab is gathered from the
stored pool, moved, and scattered into the destination pool without
ever widening to bf16, so the wire bytes are the quantized pool's
bytes (scales included) and decode-side math is BIT-IDENTICAL to a
monolithic engine that wrote the same pages locally — the token-parity
bar rests on this.

Byte accounting is CLOSED FORM, not measured: a migrated page costs
exactly ``CacheConfig.page_bytes`` (k+v payload rows plus, when
quantized, the 2 * L * Hkv f32 scales) — the same algebra the
kv-density A/B prices pools with, so ``migration_bytes`` in a record
cross-checks against ``pool_bytes`` by construction.  The bf16
equivalent (what the same pages would cost unquantized, no scales) is
kept next to it so the record states its own compression ratio.

Overlap: sends are dispatched either FENCED (solo — the comm-only leg)
or UNFENCED under an in-flight decode dispatch (the overlapped leg).
The channel only records the raw legs; ``overlap_block`` reduces them
through ``metrics/stats.overlap_fraction`` — the SAME A/B overlap
definition every collective in this repo ships — and emits NaN unless
both solo legs AND an overlapped sample were measured (an overlap
number synthesized from one leg would be fiction).
"""
from __future__ import annotations

import dataclasses
import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from dlnetbench_tpu.metrics import stats
from dlnetbench_tpu.serving.kv_cache import CacheConfig


def bf16_equiv_page_bytes(cfg: CacheConfig) -> int:
    """What one page's k+v payload would cost stored as bf16 with no
    scale arrays — the denominator of the migration compression ratio
    (the quantized wire moves ``cfg.page_bytes`` against this)."""
    return (2 * cfg.num_layers * cfg.num_kv_heads * cfg.page_size
            * cfg.head_dim * 2)


@dataclasses.dataclass
class SendRecord:
    """One sequence's migration: closed-form bytes, measured wall."""
    pages: int
    bytes: int
    wall_ms: float
    chunks: int
    overlapped: bool   # dispatched under an in-flight decode program


class PendingSend:
    """An unfenced send: the payload slabs are device futures on the
    destination.  ``wait()`` fences and closes the timing window —
    called by the driver AFTER the overlapped decode dispatch fences,
    so the recorded wall covers dispatch -> arrival like any
    async-collective measurement."""

    def __init__(self, channel: "MigrationChannel", slabs: tuple,
                 page_ids: list[int], t0: float, overlapped: bool):
        self._channel = channel
        self.slabs = slabs
        self.page_ids = list(page_ids)
        self._t0 = t0
        self._overlapped = overlapped
        self._record: SendRecord | None = None

    def wait(self) -> SendRecord:
        if self._record is None:
            for slab in self.slabs:
                for arr in slab:
                    arr.block_until_ready()
            ch = self._channel
            rec = SendRecord(
                pages=len(self.page_ids),
                bytes=ch.bytes_for_pages(len(self.page_ids)),
                wall_ms=(time.perf_counter() - self._t0) * 1e3,
                chunks=len(self.slabs),
                overlapped=self._overlapped)
            ch._sends.append(rec)
            self._record = rec
        return self._record


class MigrationChannel:
    """Pages (+ scales) from a source pool to ``dst_device``, moved as
    a chunk loop in the stored dtype.  One channel per disaggregated
    engine pair; its accumulated sends become the record's
    ``migration`` block."""

    def __init__(self, cache_cfg: CacheConfig, dst_device, *,
                 chunk_pages: int = 8):
        if chunk_pages < 1:
            raise ValueError(
                f"page migration: chunk_pages must be >= 1, got "
                f"{chunk_pages}")
        self.cfg = cache_cfg
        self.dst_device = dst_device
        self.chunk_pages = int(chunk_pages)
        self._sends: list[SendRecord] = []
        # overlap legs (seconds): decode-only walls come from the
        # disagg driver (it owns the decode dispatch window)
        self._compute_solo_s: list[float] = []
        self._both_s: list[float] = []
        # gather/scatter are tiny jitted index programs, traced once —
        # they run at handoff boundaries, never inside the compiled
        # decode/prefill programs
        self._gather = jax.jit(lambda pool, ids: pool[:, :, ids])
        self._scatter = jax.jit(
            lambda pool, ids, slab: pool.at[:, :, ids].set(slab),
            donate_argnums=(0,))

    def reset(self) -> None:
        """Clear the accumulated sends and overlap legs (a new measured
        run starts from zero) — the jitted gather/scatter programs are
        kept, so a warm round's traces survive into the measured one."""
        self._sends.clear()
        self._compute_solo_s.clear()
        self._both_s.clear()

    # ---- closed-form byte accounting ---------------------------------
    def bytes_for_pages(self, n_pages: int) -> int:
        """Wire bytes for ``n_pages`` — exactly ``n * page_bytes``
        (scales included when quantized): the record's byte field is
        the pool algebra, cross-checkable, not a transport guess."""
        return int(n_pages) * self.cfg.page_bytes

    def bf16_equiv_bytes(self, n_pages: int) -> int:
        return int(n_pages) * bf16_equiv_page_bytes(self.cfg)

    # ---- the wire ----------------------------------------------------
    def send(self, pools: tuple, page_ids, *, fence: bool = True,
             overlapped: bool = False) -> "PendingSend":
        """Move ``page_ids`` (source-pool physical ids) to the
        destination device.  ``pools`` is the source engine's pool
        tuple — ``(k, v)`` or ``(k, v, k_scale, v_scale)`` — and the
        payload slabs keep that structure and its dtypes: a quantized
        pool's pages cross the wire as int8/fp8 plus f32 scales, never
        as bf16.

        Returns the ``PendingSend`` either way (``scatter`` consumes
        it): ``fence=True`` blocks first, recording the solo comm leg;
        ``fence=False`` leaves the slabs in flight for the driver to
        ``wait()`` after the decode dispatch it overlapped."""
        ids = [int(p) for p in page_ids]
        if not ids:
            raise ValueError("page migration: empty page list — a "
                             "zero-page send is a scheduler bug, not "
                             "a transfer")
        t0 = time.perf_counter()
        slabs = []
        for lo in range(0, len(ids), self.chunk_pages):
            chunk = jnp.asarray(np.asarray(ids[lo:lo + self.chunk_pages],
                                           np.int32))
            moved = tuple(
                jax.device_put(self._gather(pool, chunk),
                               self.dst_device)
                for pool in pools)
            slabs.append(moved)
        pending = PendingSend(self, tuple(slabs), ids, t0,
                              overlapped=overlapped)
        if fence:
            pending.wait()
        return pending

    def scatter(self, dst_pools: tuple, pending: PendingSend,
                dst_page_ids) -> tuple:
        """Land a fenced send's slabs in the destination pools at
        ``dst_page_ids`` (the decode cache's allocation for this
        sequence, positional: source page k -> dst page k).  Returns
        the rebound pool tuple (pools are donated, functional-update
        style, like every pool program in the engine)."""
        dst = [int(p) for p in dst_page_ids]
        if len(dst) != len(pending.page_ids):
            raise ValueError(
                f"page migration: {len(pending.page_ids)} pages sent "
                f"but {len(dst)} destination pages allocated — the "
                f"block tables would desync from the payload")
        pools = tuple(dst_pools)
        off = 0
        for slab in pending.slabs:
            n = int(slab[0].shape[2])
            ids = jnp.asarray(np.asarray(dst[off:off + n], np.int32))
            pools = tuple(self._scatter(pool, ids, part)
                          for pool, part in zip(pools, slab))
            off += n
        return pools

    # ---- overlap legs (driver-fed) -----------------------------------
    def note_compute_solo(self, wall_s: float) -> None:
        """A decode dispatch window with NO send in flight (the
        compute-only leg)."""
        self._compute_solo_s.append(float(wall_s))

    def note_both(self, wall_s: float) -> None:
        """A decode dispatch window that covered an in-flight send,
        measured dispatch -> both fenced (the together leg)."""
        self._both_s.append(float(wall_s))

    # ---- the record block --------------------------------------------
    def overlap(self) -> float:
        """Median-leg overlap fraction, or NaN: the metric exists only
        when the comm-solo, compute-solo AND together legs were all
        measured this run — anything less and the A/B decomposition
        has a missing arm."""
        comm = [r.wall_ms * 1e-3 for r in self._sends
                if not r.overlapped]
        if not comm or not self._compute_solo_s or not self._both_s:
            return float("nan")
        med = stats.summarize
        tm = med(comm)["value"]
        tc = med(self._compute_solo_s)["value"]
        tb = med(self._both_s)["value"]
        return stats.overlap_fraction([tb], [tc], [tm])[0]

    def stats_block(self) -> dict | None:
        """The serving record's ``migration`` sub-block; None when the
        channel never carried a sequence (a monolithic run's record is
        byte-identical to pre-disagg)."""
        if not self._sends:
            return None
        pages = sum(r.pages for r in self._sends)
        walls = [r.wall_ms for r in self._sends]
        ov = self.overlap()
        from dlnetbench_tpu.serving.metrics import percentile
        return {
            "sends": len(self._sends),
            "pages": pages,
            "bytes": self.bytes_for_pages(pages),
            "bf16_equiv_bytes": self.bf16_equiv_bytes(pages),
            "bytes_ratio_vs_bf16": round(
                self.bytes_for_pages(pages)
                / max(1, self.bf16_equiv_bytes(pages)), 4),
            "chunk_pages": self.chunk_pages,
            "ms": {"total": round(sum(walls), 3),
                   "p50": round(percentile(walls, 50), 3),
                   "mean": round(sum(walls) / len(walls), 3),
                   "n": len(walls)},
            "overlap": (round(ov, 4) if not math.isnan(ov)
                        else float("nan")),
            "overlapped_sends": sum(1 for r in self._sends
                                    if r.overlapped),
        }
