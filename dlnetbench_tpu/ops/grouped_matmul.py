"""Grouped (per-expert) Pallas matmuls: the MoE expert-FFN kernel.

The expert FFN of a dispatched MoE layer is E independent matmuls over
per-expert token buffers — ``[E, C, K] @ [E, K, N] -> [E, C, N]`` —
where ``C`` is the capacity (dispatch slots) per expert.  XLA runs it
as one batched einsum that pays the FULL ``E*C`` token grid even when
routing left most slots empty.  This kernel family makes the dispatch
layout a first-class grid:

* **gather/scatter skipping** — the per-expert VALID-token counts ride
  as a scalar-prefetch operand (the splash-kernel pattern, ISSUE 10):
  a token block lying wholly beyond its expert's count issues no MXU
  work and no fresh DMA (its index map clamps to an already-resident
  block) and writes zeros — under skewed routing the kernel does the
  work the tokens need, not the work the padding implies.
* **fused quantization** (the PR-3 recipe, ops/quantized_matmul.py):
  with ``fmt`` int8/float8 the activation tile is quantized in the
  VMEM PROLOGUE against a provided PER-EXPERT scale, int32/f32 MXU
  accumulation, ``sx[e] * sw[e]`` applied in-register in the epilogue
  — the quantized activation never exists in HBM.  Scale spelling is
  shared with the composed paths (``scale_from_amax`` / ``_cast_q``),
  so the int8 grouped result is EXACTLY the composed reference.
* **tuning-DB site** (ISSUE 9): the grid blocks consult the DB under
  op ``grouped_ffn`` keyed per (E, C, K, N, fmt, dtype); an empty DB
  keeps the frozen ``DEFAULT_BLOCKS`` bit-identically, explicit block
  arguments always win.

``grouped_ffn`` stacks three grouped matmuls into the SwiGLU expert
FFN with a straight-through (master-dtype) custom VJP — the same
backward recipe every quantized path in this repo uses.  All kernels
run under ``interpret=True`` off-TPU (pallas_common), so the CPU-mesh
tier-1 lane unit-tests them.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlnetbench_tpu.ops.pallas_common import (
    F32,
    compiler_params,
    fit_block,
    interpret_mode,
)
from dlnetbench_tpu.ops.quantized_matmul import (
    _FORMATS,
    _cast_q,
    scale_from_amax,
)

# frozen default grid blocks (the pre-tuning constants): what every
# call without explicit blocks and without a tuning-DB hit runs on —
# locked bit-identical by tests/test_moe.py
DEFAULT_BLOCKS = {"block_c": 512, "block_n": 1024, "block_k": 1024}


def _tuned_blocks(e: int, c: int, kdim: int, n: int, fmt: str | None,
                  xdtype) -> dict:
    """Tuning-DB consult for the grouped-FFN grid blocks (op
    ``grouped_ffn``), or ``DEFAULT_BLOCKS``; tuned values validated
    positive (``fit_block`` then shrinks to divisors exactly as it
    does the defaults)."""
    from dlnetbench_tpu import tuning

    def check(cfg: dict) -> None:
        for name in DEFAULT_BLOCKS:
            blk = cfg.get(name)
            if not isinstance(blk, int) or blk <= 0:
                raise ValueError(f"grouped_matmul: tuned {name}={blk!r} "
                                 f"is not a positive int")
    return tuning.consult(
        "grouped_ffn",
        tuning.params.grouped_ffn_key(e, c, kdim, n, fmt or "none",
                                      xdtype),
        DEFAULT_BLOCKS, validate=check)


def _grouped_kernel(counts_ref, x_ref, w_ref, sx_ref, sw_ref, out_ref,
                    acc_ref, *, fmt: str | None, block_c: int):
    """Grid (e, ci, ni, ki); ki is the minor accumulation axis.  A
    token block wholly beyond its expert's count contributes no dot
    (its inputs were never re-DMA'd — the index map clamped to block 0)
    and emits zeros."""
    e = pl.program_id(0)
    ci = pl.program_id(1)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)
    live = ci * block_c < counts_ref[e]

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_dtype = _FORMATS[fmt][2] if fmt else F32

    @pl.when(live)
    def _dot():
        xf = x_ref[0].astype(F32)
        if fmt:
            # prologue: quantize the activation tile in VMEM against
            # this EXPERT's scale — x_q never exists in HBM
            xq = _cast_q(xf / sx_ref[0, 0], fmt)
            wblk = w_ref[0]
        else:
            xq, wblk = xf, w_ref[0].astype(F32)
        acc_ref[...] += jax.lax.dot_general(
            xq, wblk, (((1,), (0,)), ((), ())),
            preferred_element_type=acc_dtype)

    @pl.when(ki == nk - 1)
    def _emit():
        scale = (sx_ref[0, 0] * sw_ref[0, 0]) if fmt \
            else jnp.float32(1.0)
        val = acc_ref[...].astype(F32) * scale
        out_ref[0] = jnp.where(live, val, 0.0).astype(out_ref.dtype)


def grouped_matmul(x, w, *, counts=None, sx=None, sw=None,
                   fmt: str | None = None, out_dtype=None,
                   block_c: int | None = None,
                   block_n: int | None = None,
                   block_k: int | None = None):
    """``[E, C, K] @ [E, K, N] -> [E, C, N]`` per-expert matmul.

    ``counts`` ([E] int32, optional): valid tokens per expert — token
    blocks wholly past the count are SKIPPED (no MXU work, no fresh
    DMA, zero output).  ``None`` computes every block (the dense
    capacity-buffer contract: padded rows are zeros and produce
    zeros).

    Quantized form (``fmt`` = "int8" | "float8"): ``w`` must be
    PRE-QUANTIZED per expert ([E, K, N] in the quantized dtype), with
    ``sw`` [E] its per-expert scales and ``sx`` [E] the per-expert
    activation scales the prologue quantizes against.

    Grid blocks: explicit arguments win; with none given the tuning DB
    is consulted (op ``grouped_ffn``) and an empty DB keeps the frozen
    ``DEFAULT_BLOCKS`` bit-identically (ISSUE 9)."""
    e, c, kdim = x.shape
    if w.shape[0] != e or w.shape[1] != kdim:
        raise ValueError(f"grouped_matmul: shape mismatch "
                         f"x{x.shape} @ w{w.shape}")
    n = w.shape[2]
    if fmt is not None:
        if fmt not in _FORMATS:
            raise ValueError(f"grouped_matmul: unknown fmt {fmt!r}; "
                             f"one of {tuple(_FORMATS)}")
        if sx is None or sw is None:
            raise ValueError("grouped_matmul: fmt set but sx/sw "
                             "per-expert scales missing")
    if block_c is None and block_n is None and block_k is None:
        blocks = _tuned_blocks(e, c, kdim, n, fmt, x.dtype)
    else:
        blocks = {"block_c": block_c or DEFAULT_BLOCKS["block_c"],
                  "block_n": block_n or DEFAULT_BLOCKS["block_n"],
                  "block_k": block_k or DEFAULT_BLOCKS["block_k"]}
        for name, blk in blocks.items():
            if not isinstance(blk, int) or blk <= 0:
                raise ValueError(f"grouped_matmul: {name}={blk!r} must "
                                 f"be a positive int")
    bc = fit_block(c, blocks["block_c"])
    bn = fit_block(n, blocks["block_n"])
    bk = fit_block(kdim, blocks["block_k"])
    grid = (e, c // bc, n // bn, kdim // bk)

    if counts is None:
        counts = jnp.full((e,), c, jnp.int32)
    counts = counts.astype(jnp.int32)
    sx_a = (jnp.asarray(sx, F32).reshape(e, 1) if fmt
            else jnp.zeros((e, 1), F32))
    sw_a = (jnp.asarray(sw, F32).reshape(e, 1) if fmt
            else jnp.zeros((e, 1), F32))

    def x_index(ei, ci, ni, ki, counts_ref):
        # skipped blocks clamp to the expert's block 0: an already-
        # visited block, so the revisit issues no fresh DMA
        cc = jnp.where(ci * bc < counts_ref[ei], ci, 0)
        return (ei, cc, ki)

    def w_index(ei, ci, ni, ki, counts_ref):
        return (ei, ki, ni)

    def s_index(ei, ci, ni, ki, counts_ref):
        return (ei, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bc, bk), x_index),
            pl.BlockSpec((1, bk, bn), w_index),
            pl.BlockSpec((1, 1), s_index,
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), s_index,
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, bc, bn), lambda ei, ci, ni, ki, _c: (ei, ci, ni)),
        scratch_shapes=[pltpu.VMEM((bc, bn),
                                   _FORMATS[fmt][2] if fmt else F32)],
    )
    out = pl.pallas_call(
        functools.partial(_grouped_kernel, fmt=fmt, block_c=bc),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((e, c, n), out_dtype or x.dtype),
        compiler_params=compiler_params(
            ("parallel", "parallel", "parallel", "arbitrary")),
        interpret=interpret_mode(),
    )(counts, x, w, sx_a, sw_a)
    return out


def quantize_experts(w, fmt: str):
    """Per-expert symmetric quantization of a stacked weight
    ``[E, K, N]`` -> ``(wq [E, K, N], sw [E])`` — the once-per-step
    weight path of the grouped kernels (``quantize_tensor`` vmapped
    over the expert axis; same ``scale_from_amax`` spelling)."""
    wf = w.astype(F32)
    amax = jnp.max(jnp.abs(wf), axis=(1, 2))
    sw = scale_from_amax(amax, fmt)
    return _cast_q(wf / sw[:, None, None], fmt), sw


def expert_amax(x):
    """Per-expert activation amax of a dispatch buffer ``[E, C, K]``
    (padded rows are zeros and cannot inflate it) -> [E] f32."""
    return jnp.max(jnp.abs(x.astype(F32)), axis=(1, 2))


def _ffn_fwd(x, w_gate, w_up, w_down, counts, fmt, blocks):
    """The three grouped dots of the expert SwiGLU; bf16-residual
    discipline matches ``layers.swiglu_fwd_res``.  ``blocks`` is the
    (block_c, block_n, block_k) triple (hashable — it rides a
    custom_vjp nondiff argnum)."""
    kw = dict(counts=counts,
              **dict(zip(("block_c", "block_n", "block_k"), blocks)))
    if fmt:
        sx = scale_from_amax(expert_amax(x), fmt)
        wgq, swg = quantize_experts(w_gate, fmt)
        wuq, swu = quantize_experts(w_up, fmt)
        g = grouped_matmul(x, wgq, sx=sx, sw=swg, fmt=fmt, **kw)
        u = grouped_matmul(x, wuq, sx=sx, sw=swu, fmt=fmt, **kw)
        h = (jax.nn.silu(g.astype(F32)) * u.astype(F32)).astype(g.dtype)
        sh = scale_from_amax(expert_amax(h), fmt)
        wdq, swd = quantize_experts(w_down, fmt)
        return grouped_matmul(h, wdq, sx=sh, sw=swd, fmt=fmt, **kw)
    g = grouped_matmul(x, w_gate, **kw)
    u = grouped_matmul(x, w_up, **kw)
    h = (jax.nn.silu(g.astype(F32)) * u.astype(F32)).astype(g.dtype)
    return grouped_matmul(h, w_down, **kw)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def _grouped_ffn(x, w_gate, w_up, w_down, counts, fmt, blocks):
    return _ffn_fwd(x, w_gate, w_up, w_down, counts, fmt, blocks)


def _grouped_ffn_fwd(x, w_gate, w_up, w_down, counts, fmt, blocks):
    y = _ffn_fwd(x, w_gate, w_up, w_down, counts, fmt, blocks)
    return y, (x, w_gate, w_up, w_down, counts)


def _grouped_ffn_bwd(fmt, blocks, res, dy):
    """Straight-through master-dtype backward (the recipe every
    quantized path shares): batched einsums over the expert axis, h
    recomputed instead of saved.  Rows beyond an expert's count carry
    zero cotangent by construction (their combine weights are zero),
    so no count mask is needed here."""
    x, w_gate, w_up, w_down, counts = res
    xf = x.astype(F32)
    g = jnp.einsum("ecd,edh->ech", xf, w_gate.astype(F32))
    u = jnp.einsum("ecd,edh->ech", xf, w_up.astype(F32))
    sig = jax.nn.sigmoid(g)
    silu = g * sig
    h = silu * u
    dyf = dy.astype(F32)
    dh = jnp.einsum("ecd,ehd->ech", dyf, w_down.astype(F32))
    dwd = jnp.einsum("ech,ecd->ehd", h, dyf).astype(w_down.dtype)
    dg = dh * u * (sig + silu * (1.0 - sig))
    du = dh * silu
    dx = (jnp.einsum("ech,edh->ecd", dg, w_gate.astype(F32))
          + jnp.einsum("ech,edh->ecd", du, w_up.astype(F32)))
    dwg = jnp.einsum("ecd,ech->edh", xf, dg).astype(w_gate.dtype)
    dwu = jnp.einsum("ecd,ech->edh", xf, du).astype(w_up.dtype)
    # counts is state, not a weight: zero cotangent (it rides the
    # primal signature as f32 precisely so this zero is well-typed)
    return (dx.astype(x.dtype), dwg, dwu, dwd,
            jnp.zeros_like(counts))


_grouped_ffn.defvjp(_grouped_ffn_fwd, _grouped_ffn_bwd)


def grouped_ffn(x, w_gate, w_up, w_down, *, counts=None,
                fmt: str | None = None, block_c: int | None = None,
                block_n: int | None = None, block_k: int | None = None):
    """The grouped expert SwiGLU: ``x`` [E, C, d] dispatch buffers,
    weights [E, d, h] / [E, h, d] stacked per expert -> [E, C, d].

    ``counts`` enables the gather/scatter block skipping, ``fmt``
    selects the fused-quantization recipes (per-expert dynamic scales,
    straight-through backward).  Block shapes are a tuning-DB site
    (op ``grouped_ffn``); ``None`` consults, explicit ints win."""
    if fmt is not None and fmt not in _FORMATS:
        raise ValueError(f"grouped_ffn: unknown fmt {fmt!r}; one of "
                         f"{tuple(_FORMATS)} or None")
    e, c, _ = x.shape
    counts_f = (jnp.full((e,), float(c), F32) if counts is None
                else counts.astype(F32))
    return _grouped_ffn(x, w_gate, w_up, w_down, counts_f, fmt,
                        (block_c, block_n, block_k))
