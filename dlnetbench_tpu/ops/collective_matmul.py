"""Decomposed collective matmuls: overlap the collective with its matmul.

DLNetBench's subject is the communication schedule, yet the TP blocks in
``models/spmd.py`` end in *blocking* collectives: ``all_gather`` feeding a
projection, and a projection feeding ``psum_scatter``.  PERF.md r4 showed
XLA will not line these up with the dependent compute by itself ("what
they save in traffic they lose in scheduling") — so this module does it by
hand, the classic TPU-native way (Wang et al., *Overlap Communication with
Dependent Computation via Decomposition*, ASPLOS'23): break the collective
into per-shard chunks moved with ``lax.ppermute`` and interleave each
chunk's hop with the part of the matmul that is already data-complete.

Two ops, both called *inside* ``shard_map`` over a named mesh axis:

* ``all_gather_matmul(x, w, axis)`` ==
  ``dot(lax.all_gather(x, axis, axis=gather_axis, tiled=True), w)``.
  Each rank computes its own block's matmul immediately, then receives
  peer blocks over a **bidirectional ring** (half the peers arrive over
  the +1 direction, half over the -1 direction — both ICI link
  directions busy) and matmuls each block as it lands.  Per-row math is
  identical to gather-then-dot, so the forward matches the fused path
  exactly up to dot tiling.

* ``matmul_reduce_scatter(a, w, axis)`` ==
  ``lax.psum_scatter(dot(a, w), axis, scatter_dimension=scatter_axis,
  tiled=True)``.  A ring reduce-scatter where each hop's transfer
  overlaps the *next* destination block's partial matmul; bidirectional
  by splitting the output columns in half, one half per ring direction.
  Accumulation order is ring order, not XLA's psum_scatter order, so
  results match the fused path to f32 reduction tolerance (documented;
  tests pin it).

``chunks`` subdivides every block matmul along its row axis, shrinking
the compute quantum between permutes so the schedule has finer grain to
hide hops behind (the chunk-count axis of the r7 overlap study).

Backward also overlaps, via custom VJPs that reuse the same decomposed
machinery (the transposes of tiled all_gather / psum_scatter are each
other): ``d(all_gather_matmul)/dx`` is a decomposed
matmul-reduce-scatter, ``d(matmul_reduce_scatter)/da`` is a decomposed
all-gather-matmul, and both ``dw`` terms are bidirectional-ring
accumulations over the rotating activation blocks.

``fake_compute=True`` keeps every ppermute (identical wire schedule) but
replaces each block matmul with a broadcast stub — the comm-only leg of
the SPMD A/B decomposition (``models/spmd.py`` variants) that feeds the
measured overlap-fraction metric (``metrics/stats.overlap_fraction``).
``fake_comm=True`` is the mirror image: every ppermute becomes the
identity (each "received" block is the local one again) so the compute
leg performs the full schedule's FLOPs with zero wire traffic.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from dlnetbench_tpu.utils.jax_compat import axis_size as _axis_size

_F32 = jnp.float32


def _shift(x, axis_name: str, direction: int, fake_comm: bool):
    """One ring hop: direction +1 sends to the next rank (so this rank
    then holds the block of rank ``me - 1``), -1 the reverse.  With
    ``fake_comm`` the hop is the identity (compute-only A/B leg)."""
    if fake_comm:
        return x
    n = _axis_size(axis_name)
    perm = [(i, (i + direction) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def comm_stub(shape, dtype, *deps):
    """Shape-correct stand-in whose value depends (cheaply) on every
    ``dep`` — keeps the dataflow edges of the real compute so the comm
    variant's collectives schedule exactly like the full program's."""
    s = sum(d.reshape(-1)[0].astype(_F32) for d in deps)
    return jnp.broadcast_to(s, shape).astype(dtype)


def _block_mm(xblk, w, chunks: int, row_axis: int, pet, fake: bool):
    """Local matmul of one ring block, optionally split into ``chunks``
    row slices so each slice's MXU work can interleave with in-flight
    permutes at finer grain."""
    if fake:
        return comm_stub(xblk.shape[:-1] + (w.shape[-1],),
                         pet or jnp.result_type(xblk.dtype, w.dtype),
                         xblk, w)

    def dot(a):
        return lax.dot_general(a, w, (((a.ndim - 1,), (0,)), ((), ())),
                               preferred_element_type=pet)

    size = xblk.shape[row_axis]
    if chunks <= 1 or size < 2:
        return dot(xblk)
    bounds = [round(i * size / chunks) for i in range(chunks + 1)]
    parts = [dot(lax.slice_in_dim(xblk, lo, hi, axis=row_axis))
             for lo, hi in zip(bounds, bounds[1:]) if hi > lo]
    return jnp.concatenate(parts, axis=row_axis)


def _contract_dw(ablk, dblk, fake: bool):
    """dw contribution of one block pair: contract every dim except the
    last of each ([..., s, d] x [..., s, k] -> [d, k])."""
    if fake:
        return comm_stub((ablk.shape[-1], dblk.shape[-1]),
                         jnp.result_type(ablk.dtype, dblk.dtype),
                         ablk, dblk)
    dims = tuple(range(ablk.ndim - 1))
    return lax.dot_general(ablk, dblk, ((dims, dims), ((), ())))


def _bidir_sources(n: int):
    """Hop schedule of a bidirectional ring gather: at hop t this rank
    receives the block of rank ``me - t`` over the +1 direction and (for
    the first ``floor((n-1)/2)`` hops) rank ``me + t`` over -1."""
    down = (n - 1 + 1) // 2   # blocks arriving from below (me-1, me-2, ..)
    up = (n - 1) // 2         # blocks arriving from above (me+1, me+2, ..)
    return down, up


# --------------------------------------------------------------------- #
# all_gather_matmul
# --------------------------------------------------------------------- #
def _ag_matmul_impl(x, w, axis_name, gather_axis, chunks, fk_compute,
                    fk_comm, pet):
    n = _axis_size(axis_name)
    if n == 1:
        return _block_mm(x, w, chunks, gather_axis, pet, fk_compute)
    me = lax.axis_index(axis_name)
    s_loc = x.shape[gather_axis]
    dt = pet or jnp.result_type(x.dtype, w.dtype)
    out_shape = (x.shape[:gather_axis] + (n * s_loc,)
                 + x.shape[gather_axis + 1:-1] + (w.shape[-1],))
    out = jnp.zeros(out_shape, dt)

    def put(buf, blk, src):
        return lax.dynamic_update_slice_in_dim(buf, blk, src * s_loc,
                                               axis=gather_axis)

    # own block first: compute starts before any wire traffic
    out = put(out, _block_mm(x, w, chunks, gather_axis, pet, fk_compute),
              me)
    down, up = _bidir_sources(n)
    below = above = x
    for t in range(1, max(down, up) + 1):
        # issue both hops BEFORE this round's matmuls: the permutes
        # depend only on the previous hop, so XLA overlaps them with the
        # block matmuls below
        if t <= down:
            below = _shift(below, axis_name, +1, fk_comm)
        if t <= up:
            above = _shift(above, axis_name, -1, fk_comm)
        if t <= down:
            out = put(out, _block_mm(below, w, chunks, gather_axis, pet,
                                     fk_compute), (me - t) % n)
        if t <= up:
            out = put(out, _block_mm(above, w, chunks, gather_axis, pet,
                                     fk_compute), (me + t) % n)
    return out


def _ring_dw(x_like, other, axis_name, gather_axis, fk_compute, fk_comm,
             rotate_first):
    """Bidirectional-ring dw accumulation.

    ``rotate_first`` rotates ``x_like`` blocks around the ring and
    contracts each against the matching *local slice* of ``other``
    (all_gather_matmul's dw: x rotates, dout is full).  With
    ``rotate_first=False`` the roles flip (matmul_reduce_scatter's dw:
    dout rotates, a is full)."""
    n = _axis_size(axis_name)
    me = lax.axis_index(axis_name) if n > 1 else 0
    s_loc = x_like.shape[gather_axis]

    def contrib(blk, src):
        sel = lax.dynamic_slice_in_dim(other, src * s_loc, s_loc,
                                       gather_axis)
        return (_contract_dw(blk, sel, fk_compute) if rotate_first
                else _contract_dw(sel, blk, fk_compute))

    acc = contrib(x_like, me)
    if n == 1:
        return acc
    down, up = _bidir_sources(n)
    below = above = x_like
    for t in range(1, max(down, up) + 1):
        if t <= down:
            below = _shift(below, axis_name, +1, fk_comm)
        if t <= up:
            above = _shift(above, axis_name, -1, fk_comm)
        if t <= down:
            acc = acc + contrib(below, (me - t) % n)
        if t <= up:
            acc = acc + contrib(above, (me + t) % n)
    return acc


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _ag_matmul(x, w, axis_name, gather_axis, chunks, fk_compute, fk_comm,
               pet):
    return _ag_matmul_impl(x, w, axis_name, gather_axis, chunks,
                           fk_compute, fk_comm, pet)


def _ag_matmul_fwd(x, w, axis_name, gather_axis, chunks, fk_compute,
                   fk_comm, pet):
    return (_ag_matmul_impl(x, w, axis_name, gather_axis, chunks,
                            fk_compute, fk_comm, pet), (x, w))


def _ag_matmul_bwd(axis_name, gather_axis, chunks, fk_compute, fk_comm,
                   pet, res, dout):
    x, w = res
    # transpose of tiled all_gather is psum_scatter: dx decomposes into
    # the sibling op, so the backward overlaps the same way
    dx = _mm_rs_impl(dout, w.T, axis_name, gather_axis, chunks,
                     fk_compute, fk_comm, None)
    dw = _ring_dw(x, dout, axis_name, gather_axis, fk_compute, fk_comm,
                  rotate_first=True)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_ag_matmul.defvjp(_ag_matmul_fwd, _ag_matmul_bwd)


# --------------------------------------------------------------------- #
# matmul_reduce_scatter
# --------------------------------------------------------------------- #
def _mm_rs_impl(a, w, axis_name, scatter_axis, chunks, fk_compute,
                fk_comm, pet):
    n = _axis_size(axis_name)
    if n == 1:
        return _block_mm(a, w, chunks, scatter_axis, pet, fk_compute)
    me = lax.axis_index(axis_name)
    s_loc = a.shape[scatter_axis] // n

    def blk(b, wpart):
        ab = lax.dynamic_slice_in_dim(a, b * s_loc, s_loc, scatter_axis)
        return _block_mm(ab, wpart, chunks, scatter_axis, pet, fk_compute)

    kh = w.shape[-1] // 2
    halves = ([(w, +1)] if kh == 0
              else [(w[:, :kh], +1), (w[:, kh:], -1)])
    accs = []
    for wpart, direction in halves:
        # ring reduce-scatter: block b starts at rank b+direction and
        # picks up each rank's partial on the way to rank b; at hop t
        # this rank's partial is for block me - direction*(1+t)
        acc = blk((me - direction) % n, wpart)
        for t in range(1, n):
            acc = (_shift(acc, axis_name, direction, fk_comm)
                   + blk((me - direction * (1 + t)) % n, wpart))
        accs.append(acc)
    return accs[0] if len(accs) == 1 else jnp.concatenate(accs, axis=-1)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5, 6, 7))
def _mm_rs(a, w, axis_name, scatter_axis, chunks, fk_compute, fk_comm,
           pet):
    return _mm_rs_impl(a, w, axis_name, scatter_axis, chunks, fk_compute,
                       fk_comm, pet)


def _mm_rs_fwd(a, w, axis_name, scatter_axis, chunks, fk_compute,
               fk_comm, pet):
    return (_mm_rs_impl(a, w, axis_name, scatter_axis, chunks, fk_compute,
                        fk_comm, pet), (a, w))


def _mm_rs_bwd(axis_name, scatter_axis, chunks, fk_compute, fk_comm, pet,
               res, dout):
    a, w = res
    # transpose of tiled psum_scatter is all_gather: da decomposes into
    # the sibling op
    da = _ag_matmul_impl(dout, w.T, axis_name, scatter_axis, chunks,
                         fk_compute, fk_comm, None)
    dw = _ring_dw(dout, a, axis_name, scatter_axis, fk_compute, fk_comm,
                  rotate_first=False)
    return da.astype(a.dtype), dw.astype(w.dtype)


_mm_rs.defvjp(_mm_rs_fwd, _mm_rs_bwd)


# --------------------------------------------------------------------- #
# Public API
# --------------------------------------------------------------------- #
def all_gather_matmul(x, w, axis_name: str, *, gather_axis: int = 1,
                      chunks: int = 1, fake_compute: bool = False,
                      fake_comm: bool = False,
                      preferred_element_type=None):
    """``dot(all_gather(x, axis, tiled=True), w)`` as a ppermute-pipelined
    bidirectional-ring chunk loop (call inside ``shard_map``).

    ``x``: this rank's shard, gathered over ``gather_axis``; ``w``: 2-D,
    contracted with ``x``'s last dim.  ``chunks`` splits each block's
    matmul into row slices (overlap grain).  Backward overlaps too
    (custom VJP).
    """
    assert w.ndim == 2, f"w must be 2-D, got {w.shape}"
    pet = (None if preferred_element_type is None
           else jnp.dtype(preferred_element_type))
    return _ag_matmul(x, w, axis_name, int(gather_axis), int(chunks),
                      bool(fake_compute), bool(fake_comm), pet)


def matmul_reduce_scatter(a, w, axis_name: str, *, scatter_axis: int = 1,
                          chunks: int = 1, fake_compute: bool = False,
                          fake_comm: bool = False,
                          preferred_element_type=None):
    """``psum_scatter(dot(a, w), axis, scatter_dimension=scatter_axis,
    tiled=True)`` as a bidirectional ring reduce-scatter whose hops
    overlap the next block's partial matmul (call inside ``shard_map``).

    Ring accumulation order differs from the fused psum_scatter's, so
    equality with the baseline path is to f32 reduction tolerance
    (tests/test_collective_matmul.py pins it).  Backward overlaps too
    (custom VJP).
    """
    assert w.ndim == 2, f"w must be 2-D, got {w.shape}"
    pet = (None if preferred_element_type is None
           else jnp.dtype(preferred_element_type))
    return _mm_rs(a, w, axis_name, int(scatter_axis), int(chunks),
                  bool(fake_compute), bool(fake_comm), pet)
