"""Shared shims for every Pallas TPU kernel in ops/.

Before this module existed, ``_interpret()``, ``_compiler_params`` and
the fp32 constant were copy-pasted per kernel file (``mlp_backward.py``,
``flash_attention.py``); a fix to any of them (e.g. the interpret-mode
gate growing a force-override for debugging) had to be applied N times.
Everything here is the single definition the kernel files import.

The reference has no kernels at all (its compute tier is roofline
``usleep``); this module exists because the rebuild's real-compute tier
keeps growing Pallas kernels and they must all make the same
backend/VMEM decisions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams after 0.4.x; the
# fields are identical.  Resolving it HERE (the one shim module) is
# what turned the seed's 16 "Pallas-on-CPU" tier-1 failures — every
# kernel file AttributeError-ing on the new name under jax 0.4.37 —
# into passes (same spirit as utils/jax_compat.py for shard_map).
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams

# fp32: the accumulation / epilogue dtype of every kernel (MXU
# accumulators, online-softmax state, quantization scales)
F32 = jnp.float32

# Default Mosaic VMEM cap for the matmul-family kernels: raised above
# the 16 MiB default so 1-2k-wide blocks keep double-buffering headroom
# on v5e/v5p (128 MiB physical VMEM).  flash_attention uses a tighter
# 64 MiB cap (its three kernels hold more live blocks per lane).
DEFAULT_VMEM_LIMIT_MB = 100


def interpret_mode() -> bool:
    """True when Pallas kernels must run under ``interpret=True`` — any
    non-TPU backend, which is how the CPU-mesh tier-1 lane unit-tests
    every kernel without hardware."""
    return jax.default_backend() != "tpu"


def compiler_params(dimension_semantics,
                    vmem_limit_mb: int = DEFAULT_VMEM_LIMIT_MB):
    """Mosaic params shared by the kernels: per-kernel dimension
    semantics (``"parallel"`` outer axes let Mosaic pipeline DMA across
    grid rows; accumulator-carrying minor axes must be
    ``"arbitrary"``), VMEM cap in MiB."""
    return _CompilerParams(
        dimension_semantics=tuple(dimension_semantics),
        vmem_limit_bytes=vmem_limit_mb * 1024 * 1024)


# At and beyond this size a degenerate block choice stops being a perf
# wrinkle and becomes a pathology: a 64k+ dim tiled below one lane width
# means a >= 512-program grid of sub-MXU blocks (or, for the attention
# dispatcher, a silent fall-through to an S^2 dense path).  Mirrors
# flash_attention.LONG_SEQ — ISSUE 10 satellite.
LONG_DIM = 64 * 1024

# TPU lane width: the smallest block that still fills an MXU/VPU lane
# tile (flash_attention._LANES is this same constant)
LANES = 128


def fit_block(dim: int, block: int) -> int:
    """Largest power-of-two-halving of ``block`` that divides ``dim`` —
    the block-shrinking idiom every matmul-family wrapper used inline
    (``while dim % block: block //= 2``).  Raises if even block=1 does
    not divide (dim <= 0), and refuses a long dim (>= 64k) whose only
    fitting blocks are sub-lane-width: at that size the degenerate grid
    is always a config bug, not a fallback (ISSUE 10 satellite — name
    the dim instead of silently degrading)."""
    if dim <= 0:
        raise ValueError(f"fit_block: non-positive dim {dim}")
    while dim % block:
        block //= 2
    if dim >= LONG_DIM and block < LANES:
        raise ValueError(
            f"fit_block: dim {dim} >= {LONG_DIM} admits no block "
            f">= the {LANES}-wide lane tile (best fit {block}) — a "
            f"sub-lane grid at this size is a config bug; pad the dim "
            f"to a multiple of {LANES}")
    return block
