"""float8 (e4m3) matmul with per-tensor dynamic scales — the fp8 MLP
compute path.

The stat files model a ``float8`` dtype and v5e-class chips run fp8 at
2x the bf16 MXU rate (core/hardware.py peak tables; the reference's
compile-time ``PROXY_FLOAT8`` buffer selection, data_types.hpp:36-79,
covers only communication buffers — it has no fp8 COMPUTE path at all).
This module supplies the compute path TPU-style:

  * bf16 master weights and activations; each operand is scaled by
    max-abs / 448 (the e4m3 finite max) per tensor, cast to
    ``float8_e4m3fn``, multiplied with f32 accumulation on the MXU, and
    the product of the two scales is applied to the result.
  * the backward pass is straight-through: quantization is treated as
    identity and the gradient matmuls run in the master dtype (the
    standard transformer-engine-style recipe for fp8 forward without
    fp8 gradient plumbing).

``fp8_dot`` is jit/vmap-compatible (shapes static, scales dynamic) and
runs everywhere jax does (unit-testable on CPU).  Measured on v5e (r5,
docs/PERF.md): e4m3 dots execute NATIVELY on the MXU at up to 0.70 of
the fp8 peak — 274 TF/s, above the bf16 peak, killing the r3/r4
"upcast" theory, which turned out to be an HBM-residency measurement
artifact; the remaining gap to peak is quantization overhead.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from dlnetbench_tpu.ops import quantized_matmul as qmm

_F32 = jnp.float32
_E4M3_MAX = 448.0      # float8_e4m3fn finite max


def _quantize(x):
    """Per-tensor dynamic scaling to e4m3: returns (x_q, scale) with
    x ~= x_q * scale.  Delegates to the ONE definition in
    ops/quantized_matmul.py (shared with the fused Pallas kernels, so
    the fused-vs-composed A/B compares recipes, not scale formulas)."""
    return qmm.quantize_tensor(x, "float8")


@jax.custom_vjp
def fp8_dot(x, w):
    """[..., K] x [K, N] -> [..., N]: e4m3 operands, f32 accumulation,
    result in x.dtype.  Backward is straight-through in the master
    dtype."""
    out, _ = _fp8_dot_fwd(x, w)
    return out


def _fp8_dot_fwd(x, w):
    xq, sx = _quantize(x)
    wq, sw = _quantize(w)
    out = jnp.dot(xq, wq, preferred_element_type=_F32) * (sx * sw)
    return out.astype(x.dtype), (x, w)


# master-dtype backward shared by every quantized dot (fp8, int8 —
# ops/int8.py imports this name); the definition lives beside the
# fused kernels in ops/quantized_matmul.py
straight_through_dot_bwd = qmm.straight_through_dot_bwd

_fp8_dot_bwd = straight_through_dot_bwd


fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def swiglu_fp8(x, w_gate, w_up, w_down):
    """SwiGLU with all three matmuls in e4m3 (layers.swiglu's fp8
    sibling — same bf16-rounding discipline for saved residuals)."""
    g = fp8_dot(x, w_gate)      # already x.dtype (fp8_dot's contract)
    u = fp8_dot(x, w_up)
    h = (jax.nn.silu(g.astype(_F32)) * u.astype(_F32)).astype(g.dtype)
    return fp8_dot(h, w_down)


@jax.custom_vjp
def swiglu_fp8_fused(x, w_gate, w_up, w_down):
    """SwiGLU with all three matmuls through the fused-quantization
    Pallas kernel (ops/quantized_matmul.py): per-tensor e4m3 scales
    applied in the kernel prologue/epilogue instead of as separate XLA
    passes — the attack on the fp8 chain's 0.56-of-peak quantization
    overhead (docs/PERF.md r5/r6).  Whole-op custom VJP so the backward
    recomputes ``h`` instead of saving it (the same residual contract
    as swiglu_int8); backward is straight-through in the master
    dtype."""
    out, _ = qmm.swiglu_fused_fwd_res(x, w_gate, w_up, w_down, "float8")
    return out


def _swiglu_fp8_fused_fwd(x, w_gate, w_up, w_down):
    return qmm.swiglu_fused_fwd_res(x, w_gate, w_up, w_down, "float8")


swiglu_fp8_fused.defvjp(_swiglu_fp8_fused_fwd, qmm.swiglu_master_bwd)


@jax.custom_vjp
def swiglu_fp8_fused_delayed(x, w_gate, w_up, w_down, qs):
    """Delayed-scaling fused-SwiGLU (e4m3): ``qs`` is this layer's
    carried ``[amax_x, amax_h]`` f32 state from the PREVIOUS step —
    the scales come from it, so no fresh-amax HBM reduction runs on
    the hot path; the kernel emits this step's amaxes as the state for
    the next step (FP8-recipe delayed scaling, arXiv:2209.05433).
    Returns ``(y, new_qs)``; the state carries no gradient."""
    (out, new_qs), _ = qmm.swiglu_fused_delayed_fwd_res(
        x, w_gate, w_up, w_down, qs, "float8")
    return out, new_qs


def _swiglu_fp8_fused_delayed_fwd(x, w_gate, w_up, w_down, qs):
    return qmm.swiglu_fused_delayed_fwd_res(
        x, w_gate, w_up, w_down, qs, "float8")


swiglu_fp8_fused_delayed.defvjp(_swiglu_fp8_fused_delayed_fwd,
                                qmm.swiglu_delayed_master_bwd)
