"""float8 (e4m3) matmul with per-tensor dynamic scales — the fp8 MLP
compute path.

The stat files model a ``float8`` dtype and v5e-class chips run fp8 at
2x the bf16 MXU rate (core/hardware.py peak tables; the reference's
compile-time ``PROXY_FLOAT8`` buffer selection, data_types.hpp:36-79,
covers only communication buffers — it has no fp8 COMPUTE path at all).
This module supplies the compute path TPU-style:

  * bf16 master weights and activations; each operand is scaled by
    max-abs / 448 (the e4m3 finite max) per tensor, cast to
    ``float8_e4m3fn``, multiplied with f32 accumulation on the MXU, and
    the product of the two scales is applied to the result.
  * the backward pass is straight-through: quantization is treated as
    identity and the gradient matmuls run in the master dtype (the
    standard transformer-engine-style recipe for fp8 forward without
    fp8 gradient plumbing).

``fp8_dot`` is jit/vmap-compatible (shapes static, scales dynamic) and
runs everywhere jax does (unit-testable on CPU).  Measured on v5e (r5,
docs/PERF.md): e4m3 dots execute NATIVELY on the MXU at up to 0.70 of
the fp8 peak — 274 TF/s, above the bf16 peak, killing the r3/r4
"upcast" theory, which turned out to be an HBM-residency measurement
artifact; the remaining gap to peak is quantization overhead.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_F32 = jnp.float32
_E4M3_MAX = 448.0      # float8_e4m3fn finite max


def _quantize(x):
    """Per-tensor dynamic scaling to e4m3: returns (x_q, scale) with
    x ~= x_q * scale.  The scale is clamped away from zero so an
    all-zero tensor stays representable."""
    amax = jnp.max(jnp.abs(x.astype(_F32)))
    scale = jnp.maximum(amax, 1e-12) / _E4M3_MAX
    xq = (x.astype(_F32) / scale).astype(jnp.float8_e4m3fn)
    return xq, scale


@jax.custom_vjp
def fp8_dot(x, w):
    """[..., K] x [K, N] -> [..., N]: e4m3 operands, f32 accumulation,
    result in x.dtype.  Backward is straight-through in the master
    dtype."""
    out, _ = _fp8_dot_fwd(x, w)
    return out


def _fp8_dot_fwd(x, w):
    xq, sx = _quantize(x)
    wq, sw = _quantize(w)
    out = jnp.dot(xq, wq, preferred_element_type=_F32) * (sx * sw)
    return out.astype(x.dtype), (x, w)


def straight_through_dot_bwd(res, g):
    """Master-dtype backward shared by every quantized dot (fp8, int8 —
    ops/int8.py imports this): quantization treated as identity, so the
    gradient matmuls are the plain bf16/f32 ones."""
    x, w = res
    gf = g.astype(_F32)
    dx = jnp.dot(gf, w.astype(_F32).T).astype(x.dtype)
    # contract all leading (batch) axes of x against g: dw [K, N]
    lead = tuple(range(x.ndim - 1))
    dw = jax.lax.dot_general(
        x.astype(_F32), gf, ((lead, lead), ((), ()))).astype(w.dtype)
    return dx, dw


_fp8_dot_bwd = straight_through_dot_bwd


fp8_dot.defvjp(_fp8_dot_fwd, _fp8_dot_bwd)


def swiglu_fp8(x, w_gate, w_up, w_down):
    """SwiGLU with all three matmuls in e4m3 (layers.swiglu's fp8
    sibling — same bf16-rounding discipline for saved residuals)."""
    g = fp8_dot(x, w_gate)      # already x.dtype (fp8_dot's contract)
    u = fp8_dot(x, w_up)
    h = (jax.nn.silu(g.astype(_F32)) * u.astype(_F32)).astype(g.dtype)
    return fp8_dot(h, w_down)
